"""Fused Pallas pull-BFS megakernel + AOT compile cache.

Differential contract: the fused kernel (``ops/pallas_bfs``, run through
the Pallas interpreter on CPU — same grid/DMA/semaphore program, real
Mosaic needs a TPU) must equal the unfused ``ellbfs.bfs_pull`` chain and
the dense ``bfs_serve_batch`` sweep bit for bit: visited sets, reach
counts, truncation prefixes, pad-lane garbage included. Plus the AOT
cache lifecycle: cold miss → persist → warm hit → fingerprint/version
mismatch → quiet rebuild, corrupt file → warning + rebuild.
"""

from __future__ import annotations

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from hypergraphdb_tpu.ops import pallas_bfs as pb
from hypergraphdb_tpu.ops.ellbfs import bfs_pull, visited_rows
from tests.conftest import make_random_hypergraph


def _fused_pull(snap, seeds, hops, count_edges=True):
    """bfs_pull_fused with bfs_pull's seed padding applied."""
    seeds = np.asarray(seeds, dtype=np.int32)
    K = len(seeds)
    K_pad = -(-max(K, 32) // 32) * 32
    if K_pad != K:
        seeds = np.concatenate(
            [seeds, np.full(K_pad - K, snap.num_atoms, np.int32)]
        )
    vt, s_ins, reach = pb.bfs_pull_fused(snap, seeds, hops,
                                         count_edges=count_edges,
                                         interpret=True)
    return vt, s_ins, np.asarray(reach)[:K], K


# ------------------------------------------------------- fused vs unfused


@pytest.mark.parametrize("hops", [1, 3])
@pytest.mark.parametrize("k", [40, 64])
def test_fused_matches_unfused_chain(graph, hops, k):
    make_random_hypergraph(graph, n_nodes=150, n_links=300, seed=7)
    snap = graph.snapshot()
    r = np.random.default_rng(3)
    seeds = r.integers(0, snap.num_atoms, size=k).astype(np.int32)

    ref = bfs_pull(snap, seeds, hops, k_block=64)
    vt, s_ins, reach, K = _fused_pull(snap, seeds, hops)

    rvt = np.asarray(ref.visited_t)
    assert np.array_equal(np.asarray(vt)[: rvt.shape[0], : rvt.shape[1]],
                          rvt)
    assert np.array_equal(
        np.asarray(s_ins[-1]).astype(np.int64)[:k], ref.edges_touched
    )
    assert np.array_equal(reach[:k], np.asarray(ref.reach_counts))
    # per-seed reachable sets decode identically
    for a, b in zip(visited_rows(ref, snap.num_atoms)[:8],
                    _rows_of(vt, snap.num_atoms)[:8]):
        assert np.array_equal(a, b)


def _rows_of(vt, n_atoms):
    from hypergraphdb_tpu.ops.ellbfs import PullBFSResult

    return visited_rows(
        PullBFSResult(vt, np.zeros(1, np.int64), None), n_atoms
    )


def test_fused_duplicate_and_pad_seeds(graph):
    """Duplicate seeds OR into the same lanes' bits independently; pad
    seeds (dummy row) reach nothing and count zero — bfs_pull contract."""
    make_random_hypergraph(graph, n_nodes=80, n_links=160, seed=1)
    snap = graph.snapshot()
    seeds = np.asarray([5, 5, 5, 17], dtype=np.int32)
    ref = bfs_pull(snap, seeds, 2, k_block=32)
    vt, s_ins, reach, _ = _fused_pull(snap, seeds, 2)
    assert np.array_equal(reach[:4], np.asarray(ref.reach_counts))
    assert reach[0] == reach[1] == reach[2]
    # the pad lanes past K are all-zero
    assert int(np.asarray(reach)[4:].sum()) == 0 if len(reach) > 4 else True


def test_fused_empty_frontier(graph):
    """Every seed = the dummy row: zero reach, zero edges, empty bitmap."""
    make_random_hypergraph(graph, n_nodes=60, n_links=120, seed=2)
    snap = graph.snapshot()
    seeds = np.full(32, snap.num_atoms, np.int32)
    ref = bfs_pull(snap, seeds, 2, k_block=32)
    vt, s_ins, reach, _ = _fused_pull(snap, seeds, 2)
    assert int(np.asarray(vt).sum()) == 0
    assert np.array_equal(reach, np.asarray(ref.reach_counts))
    assert int(np.asarray(s_ins[-1]).sum()) == 0


def test_fused_multi_segment_scan(graph, monkeypatch):
    """Shrink SEG_BLOCKS so the per-hop lax.scan over segment
    pallas_calls runs in-test (big graphs hit this path for real)."""
    monkeypatch.setattr(pb, "SEG_BLOCKS", 4)
    make_random_hypergraph(graph, n_nodes=120, n_links=240, seed=4)
    snap = graph.snapshot()
    plan = pb.fused_plans_for(snap)
    assert plan.geom.n_seg > 1
    r = np.random.default_rng(0)
    seeds = r.integers(0, snap.num_atoms, size=32).astype(np.int32)
    ref = bfs_pull(snap, seeds, 3, k_block=32)
    vt, _, reach, _ = _fused_pull(snap, seeds, 3)
    rvt = np.asarray(ref.visited_t)
    assert np.array_equal(np.asarray(vt)[: rvt.shape[0], : rvt.shape[1]],
                          rvt)
    assert np.array_equal(reach[:32], np.asarray(ref.reach_counts))


def test_fused_count_edges_off(graph):
    make_random_hypergraph(graph, n_nodes=50, n_links=100, seed=6)
    snap = graph.snapshot()
    seeds = np.arange(32, dtype=np.int32)
    vt, s_ins, reach, _ = _fused_pull(snap, seeds, 2, count_edges=False)
    assert s_ins == [] or len(s_ins) == 0
    ref = bfs_pull(snap, seeds, 2, k_block=32, count_edges=False)
    assert np.array_equal(reach[:32], np.asarray(ref.reach_counts))


# ------------------------------------------------------- serve differential


def _serve_fused(base, delta, seeds_d, hops, top_r, bucket):
    from hypergraphdb_tpu.ops.serving import bfs_serve_batch_fused

    kw = pb.serve_fused_kwargs(base, delta, bucket)
    assert kw is not None
    return bfs_serve_batch_fused(
        kw["fused"], seeds_d, kw["n_atoms"], geom=kw["geom"],
        kwp=kw["kwp"], max_hops=hops, top_r=top_r,
        overlay=kw["overlay"], widths1=kw["widths1"],
        widths2=kw["widths2"], interpret=True,
    )


@pytest.mark.parametrize("bucket", [64, 256])
def test_serve_fused_matches_dense_bucket_shapes(graph, bucket):
    """Whole-batch parity, pad lanes included (the runtime's
    well-defined-garbage contract), across serve bucket widths."""
    from hypergraphdb_tpu.ops.serving import bfs_serve_batch

    make_random_hypergraph(graph, n_nodes=90, n_links=180, seed=8)
    mgr = graph.enable_incremental()
    dev, delta = mgr.device()
    n = mgr.base.num_atoms
    r = np.random.default_rng(5)
    seeds = np.full(bucket, n, np.int32)
    live = min(bucket - 3, 50)
    seeds[:live] = r.integers(0, 90, size=live)
    seeds_d = jnp.asarray(seeds)
    top_r = 9

    c_ref, f_ref = bfs_serve_batch(dev, delta, seeds_d, 2, top_r)
    c_f, f_f = _serve_fused(mgr.base, delta, seeds_d, 2, top_r, bucket)
    assert np.array_equal(np.asarray(c_ref), np.asarray(c_f))
    assert np.array_equal(np.asarray(f_ref), np.asarray(f_f))
    # truncation prefixes: some live seed must have count > top_r for the
    # prefix contract to be exercised at all
    assert (np.asarray(c_ref)[:live] > top_r).any()


def test_first_r_top_r_beyond_row_block():
    """``top_r`` wider than the 4096-row streaming block (a config the
    dense path serves fine) must not over-ask the per-block top_k — the
    block contributes at most its own row count of candidates, and the
    merge still yields the global ``top_r`` prefix."""
    from hypergraphdb_tpu.ops.setops import SENTINEL

    R, K, top_r, n1 = 8200, 32, 4100, 8000
    r = np.random.default_rng(2)
    vis = np.zeros((R, 1), np.uint32)
    rows0 = np.unique(r.integers(0, n1, size=7000))  # > top_r reached
    assert len(rows0) > top_r
    vis[rows0, 0] |= 1
    vis[[5, 4097, 8100], 0] |= 2  # seed 1: one row past n1 (masked)
    out = np.asarray(pb.first_r_from_bitmap(
        jnp.asarray(vis), jnp.int32(n1), top_r, K
    ))
    assert out.shape == (K, top_r)
    assert np.array_equal(out[0], rows0[:top_r])  # truncated prefix
    assert np.array_equal(out[1][:2], [5, 4097])
    assert (out[1][2:] == SENTINEL).all()         # 8100 >= n1 masked out
    assert (out[2:] == SENTINEL).all()


def test_serve_fused_delta_overlay_path(graph):
    """The delta-overlay path used by ``bfs_serve_batch``: fresh links in
    the memtable must flow through the fused kernel's overlay plan with
    exact parity against the dense base∪delta sweep."""
    from hypergraphdb_tpu.ops.serving import bfs_serve_batch

    make_random_hypergraph(graph, n_nodes=100, n_links=150, seed=12)
    mgr = graph.enable_incremental()
    r = np.random.default_rng(9)
    # delta: new links bridging previously-unlinked node pairs
    for i in range(40):
        a, b = int(r.integers(0, 50)), int(r.integers(50, 100))
        graph.add_link([a, b], value=f"delta{i}")
    dev, delta = mgr.device()
    assert int(np.asarray(delta.inc_links).min()) < mgr.base.num_atoms

    seeds = np.full(64, mgr.base.num_atoms, np.int32)
    seeds[:48] = r.integers(0, 100, size=48)
    seeds_d = jnp.asarray(seeds)
    for hops in (1, 3):
        c_ref, f_ref = bfs_serve_batch(dev, delta, seeds_d, hops, 7)
        c_f, f_f = _serve_fused(mgr.base, delta, seeds_d, hops, 7, 64)
        assert np.array_equal(np.asarray(c_ref), np.asarray(c_f)), hops
        assert np.array_equal(np.asarray(f_ref), np.asarray(f_f)), hops


def test_serve_fused_declines_without_breaking(graph):
    """Gate behavior the runtime relies on: CPU backend preflight is
    False (fallback exercised by the whole serve suite), and a pinned
    view with tombstones is refused by the executor gate."""
    from hypergraphdb_tpu.serve import ServeConfig
    from hypergraphdb_tpu.serve.runtime import DeviceExecutor

    assert jax.default_backend() == "cpu"
    assert pb.pallas_bfs_ok() is False

    make_random_hypergraph(graph, n_nodes=40, n_links=80, seed=3)
    ex = DeviceExecutor(graph, ServeConfig(manual=True))
    view = ex.mgr.pinned_view()
    assert ex._fused_bfs_kwargs(view, 64) is None  # backend gate
    # force the backend gate open; the tombstone gate must still decline
    pb._PREFLIGHT["cpu"] = True
    try:
        view2 = view._replace(dead={5})
        assert ex._fused_bfs_kwargs(view2, 64) is None
        # and with the gates open the kwargs bundle materializes
        assert ex._fused_bfs_kwargs(view, 64) is not None
    finally:
        pb._PREFLIGHT["cpu"] = False


def test_plan_supported_reports_budget_overflow(graph, monkeypatch):
    """A hub row too wide for the SMEM window declines with a reason —
    the window math hglint HG5xx models, enforced at runtime."""
    make_random_hypergraph(graph, n_nodes=60, n_links=120, seed=10)
    snap = graph.snapshot()
    assert pb.plan_supported(snap, 64) is None
    monkeypatch.setattr(pb, "SMEM_BUDGET", 64)  # absurdly small
    assert "SMEM" in pb.plan_supported(snap, 64)
    assert pb.fused_ready(snap, 64) is False


def test_hub_decline_skips_adjacency_materialization(graph):
    """A hub whose composed adjacency blows the SMEM window declines
    BEFORE the O(composition) flat index array is built (review fix:
    a 40 GB np.full on a hub-heavy graph would be a regression vs the
    staged chain), and bfs_pull still serves via the fallback."""
    nodes = list(graph.add_nodes_bulk([f"h{i}" for i in range(520)]))
    # one 500-ary link: every target's fused row is 500 wide → the
    # segment chunk cap overflows half the 1 MB SMEM budget
    graph.add_link([int(n) for n in nodes[:500]], value="hub")
    snap = graph.snapshot()
    plan = pb.fused_plans_for(snap)
    assert plan.blk_off.shape[0] == 0 and plan.idx.size == 0  # no build
    assert not plan.smem_ok
    assert "SMEM" in pb.plan_supported(snap, 64)
    assert pb.fused_ready(snap, 64) is False
    with pytest.raises(ValueError, match="declined"):
        pb.device_fused_plan(snap)
    res = bfs_pull(snap, np.asarray([int(nodes[0])], np.int32), 2)
    assert int(np.asarray(res.reach_counts)[0]) >= 500


def test_fused_traffic_model_counts_real_entries(graph):
    make_random_hypergraph(graph, n_nodes=50, n_links=100, seed=0)
    snap = graph.snapshot()
    geom = pb.fused_plans_for(snap).geom
    per_hop = pb.fused_bytes_per_hop(geom, 4096)
    assert per_hop > geom.total_entries * 512  # gathered 512-byte rows
    assert geom.total_entries > 0


# ----------------------------------------------------------- aot lifecycle


@pytest.fixture
def jit_fn():
    return jax.jit(lambda x, n: x * n + 1, static_argnames=("n",))


def test_aot_cache_lifecycle(tmp_path, jit_fn):
    """cold miss → persist → warm hit → fingerprint mismatch → quiet
    rebuild → version mismatch → quiet rebuild → corrupt → warn+rebuild."""
    from hypergraphdb_tpu.ops import aot_cache as ac

    args = (jnp.zeros((16,), jnp.float32),)
    statics = {"n": 2}

    c1 = ac.AOTCache(root=str(tmp_path), content_key="fp-a")
    comp = c1.get_or_compile("t.mul", jit_fn, args, statics)
    assert float(comp(jnp.ones((16,), jnp.float32))[0]) == 3.0
    assert c1.stats.misses == 1 and c1.stats.puts == 1

    # same process: memory hit; fresh cache object: disk hit (no compile)
    c1.get_or_compile("t.mul", jit_fn, args, statics)
    assert c1.stats.mem_hits == 1
    c2 = ac.AOTCache(root=str(tmp_path), content_key="fp-a")
    comp2 = c2.get_or_compile("t.mul", jit_fn, args, statics)
    assert c2.stats.disk_hits == 1 and c2.stats.misses == 0
    assert float(comp2(jnp.full((16,), 2.0))[0]) == 5.0

    # fingerprint mismatch at the SAME file path → StaleEntry → quiet
    # rebuild (simulated by planting fp-b's blob under fp-a's key)
    cb = ac.AOTCache(root=str(tmp_path), content_key="fp-b")
    cb.get_or_compile("t.mul", jit_fn, args, statics)
    import os

    key_a = c2.key_for("t.mul", args, statics)
    key_b = cb.key_for("t.mul", args, statics)
    os.replace(cb._path(key_b), c2._path(key_a))
    c3 = ac.AOTCache(root=str(tmp_path), content_key="fp-a")
    c3.get_or_compile("t.mul", jit_fn, args, statics)
    assert c3.stats.stale == 1 and c3.stats.misses == 1

    # format-version mismatch is stale too
    import json as _json

    path = c3._path(key_a)
    with open(path, "rb") as f:
        magic = f.read(len(ac._MAGIC))
        header = _json.loads(f.readline())
        rest = f.read()
    header["format"] = ac.FORMAT + 1
    with open(path, "wb") as f:
        f.write(magic + (_json.dumps(header) + "\n").encode() + rest)
    c4 = ac.AOTCache(root=str(tmp_path), content_key="fp-a")
    c4.get_or_compile("t.mul", jit_fn, args, statics)
    assert c4.stats.stale == 1

    # corrupt file → warning + rebuild; next cache instance hits again
    with open(path, "wb") as f:
        f.write(b"\x00 not an aot entry")
    c5 = ac.AOTCache(root=str(tmp_path), content_key="fp-a")
    c5.get_or_compile("t.mul", jit_fn, args, statics)
    assert c5.stats.corrupt == 1 and c5.stats.puts == 1
    c6 = ac.AOTCache(root=str(tmp_path), content_key="fp-a")
    c6.get_or_compile("t.mul", jit_fn, args, statics)
    assert c6.stats.hits == 1 and c6.stats.misses == 0


def test_aot_cache_corrupt_logs_warning(tmp_path, jit_fn, caplog):
    import logging

    from hypergraphdb_tpu.ops import aot_cache as ac

    args = (jnp.zeros((4,), jnp.float32),)
    c = ac.AOTCache(root=str(tmp_path))
    c.get_or_compile("t.x", jit_fn, args, {"n": 1})
    path = c._path(c.key_for("t.x", args, {"n": 1}))
    with open(path, "wb") as f:
        f.write(b"junk")
    with caplog.at_level(logging.WARNING, "hypergraphdb_tpu.aot"):
        ac.AOTCache(root=str(tmp_path)).get_or_compile(
            "t.x", jit_fn, args, {"n": 1}
        )
    assert any("rebuilding" in r.message for r in caplog.records)


def test_aot_gc_sweeps_superseded_generations(tmp_path, jit_fn):
    """ROADMAP 4f: the open-time sweep deletes entries whose header
    content_key is a SUPERSEDED generation once past the age bound; the
    current generation is never touched (the prewarm relies on it)."""
    import os
    import time as _time

    from hypergraphdb_tpu.ops import aot_cache as ac

    args = (jnp.zeros((16,), jnp.float32),)
    old = ac.AOTCache(root=str(tmp_path), content_key="gen-old")
    old.get_or_compile("t.mul", jit_fn, args, {"n": 2})
    old.get_or_compile("t.mul", jit_fn, args, {"n": 3})
    cur = ac.AOTCache(root=str(tmp_path), content_key="gen-new",
                      gc_max_age_s=None)          # no sweep at open
    cur.get_or_compile("t.mul", jit_fn, args, {"n": 2})

    def aot_files():
        return [f for f in os.listdir(cur.dir) if f.endswith(".aot")]

    assert len(aot_files()) == 3
    # young superseded entries survive a lenient sweep...
    cur.gc_max_age_s = 3600.0
    assert cur.gc(now=_time.time() + 1.0) == 0
    # ...and go once older than the bound — current generation stays
    assert cur.gc(now=_time.time() + 2 * 3600.0) == 2
    assert cur.stats.gc_removed == 2
    assert len(aot_files()) == 1
    # the survivor really is the current generation: a fresh open (the
    # default sweep runs) still disk-hits without a compile
    c2 = ac.AOTCache(root=str(tmp_path), content_key="gen-new")
    c2.get_or_compile("t.mul", jit_fn, args, {"n": 2})
    assert c2.stats.disk_hits == 1 and c2.stats.misses == 0


def test_aot_gc_size_bound_and_tmp_leftovers(tmp_path, jit_fn):
    """The size bound deletes oldest-superseded-first even when young,
    never the current generation; abandoned ``*.tmp.*`` writer leftovers
    go once past the age bound."""
    import os
    import time as _time

    from hypergraphdb_tpu.ops import aot_cache as ac

    args = (jnp.zeros((16,), jnp.float32),)
    old = ac.AOTCache(root=str(tmp_path), content_key="gen-old")
    for n in (2, 3, 4):
        old.get_or_compile("t.mul", jit_fn, args, {"n": n})
    cur = ac.AOTCache(root=str(tmp_path), content_key="gen-new",
                      gc_max_age_s=None)
    cur.get_or_compile("t.mul", jit_fn, args, {"n": 2})
    leftover = os.path.join(cur.dir, "deadbeef.aot.tmp.123")
    with open(leftover, "wb") as f:
        f.write(b"crashed writer leftover")

    cur.gc_max_age_s = 3600.0
    cur.gc_max_bytes = 1                    # force over-budget
    assert cur.gc(now=_time.time() + 1.0) == 3   # young, but over budget
    survivors = [f for f in os.listdir(cur.dir) if f.endswith(".aot")]
    assert survivors and all(
        cur._entry_content_key(os.path.join(cur.dir, f)) == "gen-new"
        for f in survivors
    )
    # the young tmp leftover survived; past the age bound it goes too
    assert os.path.exists(leftover)
    assert cur.gc(now=_time.time() + 2 * 3600.0) == 1
    assert not os.path.exists(leftover)


def test_aot_key_separates_shapes_and_statics(tmp_path, jit_fn):
    from hypergraphdb_tpu.ops import aot_cache as ac

    c = ac.AOTCache(root=str(tmp_path))
    k1 = c.key_for("e", (jnp.zeros((4,), jnp.float32),), {"n": 2})
    k2 = c.key_for("e", (jnp.zeros((8,), jnp.float32),), {"n": 2})
    k3 = c.key_for("e", (jnp.zeros((4,), jnp.float32),), {"n": 3})
    assert len({k1, k2, k3}) == 3


def test_serve_runtime_warm_start_skips_compiles(graph, tmp_path):
    """Acceptance: a fresh ServeRuntime over a populated AOT cache
    reaches first dispatch without recompiling the warmed buckets —
    asserted via the cache-hit counters."""
    from hypergraphdb_tpu.serve import ServeConfig, ServeRuntime

    make_random_hypergraph(graph, n_nodes=60, n_links=120, seed=5)
    cfg = dict(buckets=(4, 8), max_linger_s=0.001, top_r=8,
               aot_cache_dir=str(tmp_path), prewarm_hops=(2, 3),
               prewarm_pattern_arities=(1, 2))
    rt1 = ServeRuntime(graph, ServeConfig(**cfg))
    r1 = rt1.submit_bfs(3, max_hops=2).result(timeout=60)
    p1 = rt1.submit_pattern([3]).result(timeout=60)
    cold = rt1.stats_snapshot()["aot"]
    rt1.close()
    # 2 buckets x (2 hops + 2 pattern arities)
    assert cold["misses"] >= 8 and cold["puts"] >= 8

    rt2 = ServeRuntime(graph, ServeConfig(**cfg))
    r2 = rt2.submit_bfs(3, max_hops=2).result(timeout=60)
    # a NON-default hops the config declared must be warm too — the
    # dispatch thread never compiles for any (bucket, hops) in the plan
    rt2.submit_bfs(3, max_hops=3).result(timeout=60)
    # the pattern lane (ROADMAP 4d): first dispatch of BOTH warmed
    # anchor arities must be compile-free too
    p2 = rt2.submit_pattern([3]).result(timeout=60)
    rt2.submit_pattern([3, 5]).result(timeout=60)
    warm = rt2.stats_snapshot()["aot"]
    rt2.close()
    assert warm["misses"] == 0, warm
    assert warm["disk_hits"] >= 8 and warm["hits"] >= 8, warm
    assert r1.count == r2.count and np.array_equal(r1.matches, r2.matches)
    assert p1.count == p2.count and np.array_equal(p1.matches, p2.matches)


def test_aot_dispatch_results_match_plain_jit(graph, tmp_path):
    """The compiled-executable dispatch path returns exactly what the
    plain jitted call returns (same kernels, same pinned view)."""
    from hypergraphdb_tpu.serve import ServeConfig, ServeRuntime

    make_random_hypergraph(graph, n_nodes=70, n_links=140, seed=6)
    res = {}
    for dir_ in (str(tmp_path), None):
        cfg = ServeConfig(buckets=(4,), max_linger_s=0.001, top_r=8,
                          aot_cache_dir=dir_, prewarm_aot=dir_ is not None)
        rt = ServeRuntime(graph, cfg)
        res[dir_] = rt.submit_bfs(7, max_hops=2).result(timeout=60)
        rt.close()
    a, b = res.values()
    assert a.count == b.count and np.array_equal(a.matches, b.matches)


def test_aot_gc_disabled_by_none_is_inert(tmp_path, jit_fn):
    """``gc_max_age_s=None`` is the documented off switch: a MANUAL
    ``gc()`` must be a no-op too — reading None as age 0 would delete
    every superseded entry and any tmp a concurrent writer is
    mid-writing."""
    import os

    from hypergraphdb_tpu.ops import aot_cache as ac

    args = (jnp.zeros((16,), jnp.float32),)
    old = ac.AOTCache(root=str(tmp_path), content_key="gen-old")
    old.get_or_compile("t.mul", jit_fn, args, {"n": 2})
    cur = ac.AOTCache(root=str(tmp_path), content_key="gen-new",
                      gc_max_age_s=None)
    with open(os.path.join(cur.dir, "w.tmp.123"), "wb") as f:
        f.write(b"half-written")
    assert cur.gc() == 0
    names = set(os.listdir(cur.dir))
    assert "w.tmp.123" in names
    assert any(n.endswith(".aot") for n in names)
