"""Subsumption as data (VERDICT r2 item 9): ``Subsumes``/``Subsumed``
conditions mirror ``SubsumesCondition.java``/``SubsumedCondition.java`` —
declared ``HGSubsumes`` links first, then same-type value subsumption —
and the type hierarchy feeding TypePlus is graph-resident."""

import pytest

from hypergraphdb_tpu import HyperGraph
from hypergraphdb_tpu.atom.utilities import (
    SubsumesValue,
    declare_subsumes,
    subsumes_declared,
)
from hypergraphdb_tpu.query import dsl as q


@pytest.fixture()
def g():
    graph = HyperGraph()
    yield graph
    graph.close()


def test_declared_subsumption_link(g):
    gen = g.add("general-concept")
    spec = g.add("specific-concept")
    # declare at the atom level: a SubsumesValue-typed ordered link
    g.add_link((gen, spec), value=SubsumesValue())
    assert subsumes_declared(g, int(gen), int(spec))
    assert not subsumes_declared(g, int(spec), int(gen))  # directional

    assert q.find_all(g, q.and_(q.is_(gen), q.subsumes(spec))) == [int(gen)]
    assert q.find_all(g, q.and_(q.is_(spec), q.subsumed(gen))) == [int(spec)]
    # and not the other way around
    assert q.find_all(g, q.and_(q.is_(spec), q.subsumes(gen))) == []


def test_value_level_subsumption_same_type(g):
    """Without a declared link, same-type atoms subsume iff the type's
    value relation accepts them (default: equality)."""
    a1 = g.add("same")
    a2 = g.add("same")
    b = g.add("different")
    res = q.find_all(g, q.subsumes(a2))
    assert int(a1) in res and int(a2) in res
    assert int(b) not in res


def test_subsumption_rejects_cross_type(g):
    n_int = g.add(42)
    n_str = g.add("42")
    assert q.find_all(g, q.and_(q.is_(n_int), q.subsumes(n_str))) == []


def test_custom_type_subsumption(g):
    """A type overriding ``subsumes`` drives the relation (the reference's
    HGAtomType.subsumes contract)."""
    from hypergraphdb_tpu.types.primitive import StringType

    class PrefixType(StringType):
        name = "prefix-str"

        def subsumes(self, general, specific):
            return specific is not None and general is not None \
                and str(specific).startswith(str(general))

    g.typesystem.register(PrefixType())
    a = g.add_node("ab", type="prefix-str")
    abc = g.add_node("abcde", type="prefix-str")
    res = q.find_all(g, q.and_(q.is_(a), q.subsumes(abc)))
    assert res == [int(a)]
    assert q.find_all(g, q.and_(q.is_(abc), q.subsumes(a))) == []


def test_type_hierarchy_via_links_feeds_typeplus(g):
    from hypergraphdb_tpu.types.primitive import StringType

    class T(StringType):
        pass

    for name in ("vehicle", "car"):
        t = T()
        t.name = name
        g.typesystem.register(t)
    declare_subsumes(g, "vehicle", "car")
    c1 = g.add_node("beetle", type="car")
    v1 = g.add_node("boat", type="vehicle")
    res = q.find_all(g, q.type_plus("vehicle"))
    assert int(c1) in res and int(v1) in res
    # the hierarchy is graph-resident: a subsumes link atom exists
    th = g.typesystem.handle_of("vehicle")
    sh = g.typesystem.handle_of("car")
    assert subsumes_declared(g, int(th), int(sh))
