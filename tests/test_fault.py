"""hgfault unit tests: registry schedules, determinism, classification,
and the circuit-breaker state machine.

Everything here is single-threaded and clock-injected — the registry's
reproducibility properties (same seed → same fire sequence; per-point
decisions independent of cross-point interleaving) are asserted directly,
because they are what make the chaos soaks replayable.
"""

from __future__ import annotations

import pytest

from hypergraphdb_tpu.fault import (
    CLOSED,
    HALF_OPEN,
    OPEN,
    CircuitBreaker,
    FaultError,
    FaultRegistry,
    InjectedCrash,
    PermanentFault,
    TransientFault,
    is_transient,
)


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# ------------------------------------------------------------- registry


def test_disabled_registry_never_fires_or_counts():
    f = FaultRegistry()
    f.arm("p", times=100)
    f.check("p")                      # enabled is False: pure no-op
    assert f.hits("p") == 0
    assert f.fired("p") == 0


def test_times_schedule_fails_first_n_hits():
    f = FaultRegistry().enable(seed=0)
    f.arm("p", times=2)
    for _ in range(2):
        with pytest.raises(TransientFault):
            f.check("p")
    f.check("p")                      # third hit passes
    f.check("p")
    assert f.hits("p") == 4
    assert f.fired("p") == 2
    assert f.journal == [("p", 1), ("p", 2)]


def test_at_schedule_fires_exact_hit_indices():
    f = FaultRegistry().enable(seed=0)
    f.arm("p", at={2, 4}, error=PermanentFault)
    outcomes = []
    for _ in range(5):
        try:
            f.check("p")
            outcomes.append("ok")
        except PermanentFault:
            outcomes.append("boom")
    assert outcomes == ["ok", "boom", "ok", "boom", "ok"]


def test_prob_schedule_same_seed_same_sequence():
    def fired_pattern(seed):
        f = FaultRegistry().enable(seed=seed)
        f.arm("p", prob=0.5)
        out = []
        for _ in range(64):
            try:
                f.check("p")
                out.append(0)
            except TransientFault:
                out.append(1)
        return out

    a = fired_pattern(7)
    assert a == fired_pattern(7)      # reproducible by construction
    assert a != fired_pattern(8)      # and the seed actually matters
    assert 0 < sum(a) < 64            # a real mix at p=0.5 over 64 draws


def test_per_point_decisions_independent_of_interleaving():
    """Point p1's fire/pass pattern depends only on ITS OWN hit index —
    thread interleaving across points cannot change the fault sequence."""
    def run(order):
        f = FaultRegistry().enable(seed=3)
        f.arm("p1", prob=0.4)
        f.arm("p2", prob=0.4)
        fired = {"p1": [], "p2": []}
        for name in order:
            try:
                f.check(name)
            except TransientFault:
                fired[name].append(f.hits(name))
        return fired

    interleaved = run(["p1", "p2"] * 32)
    sequential = run(["p1"] * 32 + ["p2"] * 32)
    assert interleaved == sequential


def test_when_predicate_filters_by_ctx():
    f = FaultRegistry().enable(seed=0)
    f.arm("p", times=10, when=lambda ctx: ctx.get("target") == "b")
    f.check("p", target="a")          # filtered: no fire
    with pytest.raises(TransientFault):
        f.check("p", target="b")
    assert f.fired("p") == 1


def test_unarmed_point_counts_hits_only():
    f = FaultRegistry().enable(seed=0)
    f.check("never.armed", extra="ctx")
    assert f.hits("never.armed") == 1
    assert f.fired("never.armed") == 0


def test_injected_crash_is_base_exception():
    f = FaultRegistry().enable(seed=0)
    f.arm("kill", at={1}, error=InjectedCrash)
    try:
        f.check("kill")
        raise AssertionError("crash point did not fire")
    except Exception:  # noqa: BLE001 - the point of the test
        raise AssertionError(
            "InjectedCrash was caught by `except Exception` — recovery "
            "code could swallow a simulated kill"
        )
    except InjectedCrash:
        pass


def test_arm_validation_and_disarm():
    f = FaultRegistry().enable(seed=0)
    with pytest.raises(ValueError):
        f.arm("p")                    # no schedule
    with pytest.raises(ValueError):
        f.arm("p", prob=1.5)
    f.arm("p", times=5)
    assert f.armed() == ["p"]
    f.disarm("p")
    f.check("p")                      # disarmed: passes
    f.reset()
    assert f.hits("p") == 0 and f.journal == []


def test_fire_increments_fault_injected_counter():
    from hypergraphdb_tpu.utils.metrics import global_metrics

    c = global_metrics.registry.counter("fault.injected")
    before = c.value
    f = FaultRegistry().enable(seed=0)
    f.arm("p", times=1)
    with pytest.raises(TransientFault):
        f.check("p")
    assert c.value == before + 1


# ------------------------------------------------------------- classification


def test_is_transient_classification():
    assert is_transient(TransientFault("x"))
    assert is_transient(TimeoutError("x"))
    assert is_transient(ConnectionError("x"))
    assert not is_transient(PermanentFault("x"))
    assert not is_transient(RuntimeError("x"))
    assert is_transient(RuntimeError("x"), extra=(RuntimeError,))

    class MarkedTransient(Exception):
        transient = True

    class MarkedPermanent(TimeoutError):
        transient = False          # explicit attribute beats isinstance

    assert is_transient(MarkedTransient())
    assert not is_transient(MarkedPermanent())
    assert isinstance(TransientFault("x"), FaultError)


# ------------------------------------------------------------- breaker


def make_breaker(threshold=3, cooldown=1.0):
    clock = FakeClock()
    states, trips = [], []
    b = CircuitBreaker(threshold=threshold, cooldown_s=cooldown,
                       clock=clock, on_state=states.append,
                       on_trip=lambda: trips.append(1))
    return b, clock, states, trips


def test_breaker_trips_after_threshold_consecutive_failures():
    b, clock, states, trips = make_breaker(threshold=3)
    key = ("bfs", 2)
    assert b.allow(key)
    b.record_failure(key)
    b.record_failure(key)
    assert b.state_of(key) == CLOSED and b.allow(key)
    b.record_failure(key)
    assert b.state_of(key) == OPEN
    assert not b.allow(key)           # open: host fallback
    assert trips == [1] and states[-1] == 2


def test_breaker_success_resets_failure_streak():
    b, clock, states, trips = make_breaker(threshold=2)
    key = "k"
    b.record_failure(key)
    b.record_success(key)             # streak broken
    b.record_failure(key)
    assert b.state_of(key) == CLOSED  # 1 < threshold again
    assert trips == []


def test_breaker_half_open_probe_success_closes():
    b, clock, states, trips = make_breaker(threshold=1, cooldown=1.0)
    key = "k"
    b.record_failure(key)
    assert not b.allow(key)
    clock.advance(1.5)
    assert b.allow(key)               # the probe
    assert b.state_of(key) == HALF_OPEN
    assert not b.allow(key)           # one probe per cooldown window
    b.record_success(key)
    assert b.state_of(key) == CLOSED
    assert b.allow(key)
    assert states[-1] == 0


def test_breaker_half_open_probe_failure_reopens():
    b, clock, states, trips = make_breaker(threshold=1, cooldown=1.0)
    key = "k"
    b.record_failure(key)
    clock.advance(1.5)
    assert b.allow(key)
    b.record_failure(key)             # the probe failed
    assert b.state_of(key) == OPEN
    assert not b.allow(key)
    assert b.trips == 2               # initial trip + probe re-trip


def test_breaker_lost_probe_does_not_wedge_the_gate():
    b, clock, *_ = make_breaker(threshold=1, cooldown=1.0)
    key = "k"
    b.record_failure(key)
    clock.advance(1.5)
    assert b.allow(key)               # probe released... and lost
    clock.advance(1.5)
    assert b.allow(key)               # a fresh probe after another cooldown


def test_breaker_gates_are_per_key():
    b, clock, *_ = make_breaker(threshold=1)
    b.record_failure("bad")
    assert not b.allow("bad")
    assert b.allow("good")            # other keys unaffected
    assert b.worst_code() == 2
