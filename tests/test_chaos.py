"""Seeded chaos soaks: the full ServeRuntime + concurrent ingest (+
replication) under randomized-but-reproducible fault schedules.

The acceptance contract per seed:

- **stats identity**: submitted == completed + shed + cancelled + errors
  (+ 0 in flight after close) — no double counting under any failure
  interleaving;
- **no stranded tickets**: every future is done after close;
- **correct or typed**: every response is either exactly the precomputed
  ground truth (the fault schedule may reroute it through retries, host
  fallback, or a breaker-degraded batch — never change the answer) or a
  typed ``ServeError``/``FaultError``;
- **reproducible by construction**: the schedule is RANDOMIZED by
  pre-drawing fire indices from the seed, and the journal must equal
  that draw's offline replay — thread interleaving cannot change which
  hit indices fire.

Ground truth stays valid under concurrent ingest because the ingest
thread only creates atoms/links in a FRESH disconnected cluster: old
seeds reach nothing new, old anchors gain no incident links.

The short multi-seed soak is tier-1 (tools/chaos.sh gates on it); the
big combined soak is ``slow``, mirroring the PR-4 convention.
"""

from __future__ import annotations

import random
import threading
import time

import pytest

import hypergraphdb_tpu as hg
from hypergraphdb_tpu.algorithms.traversals import HGBreadthFirstTraversal
from hypergraphdb_tpu.fault import FaultError, FaultRegistry, global_faults
from hypergraphdb_tpu.peer.peer import HyperGraphPeer
from hypergraphdb_tpu.peer.transport import LoopbackNetwork
from hypergraphdb_tpu.query import conditions as c
from hypergraphdb_tpu.query import dsl as q
from hypergraphdb_tpu.serve import ServeConfig, ServeError, ServeRuntime

def draw_schedule(seed):
    """The randomized-but-reproducible schedule: fire indices pre-drawn
    from the seed (launch faults bursty on purpose — consecutive indices
    exercise the breaker trip)."""
    rng = random.Random(f"schedule:{seed}")
    launch_at = set(rng.sample(range(1, 10), 4))
    collect_at = set(rng.sample(range(1, 6), 2))
    return launch_at, collect_at


def build_graph(n_nodes=60, n_links=90):
    g = hg.HyperGraph()
    rng = random.Random(42)
    nodes = [int(g.add(f"s{i}")) for i in range(n_nodes)]
    for j in range(n_links):
        a, b = rng.sample(nodes, 2)
        g.add_link((a, b), value=f"e{j}")
    return g, nodes


def bfs_truth(g, seed, hops):
    reached = {
        int(a) for _, a in HGBreadthFirstTraversal(g, seed,
                                                   max_distance=hops)
    }
    reached.add(int(seed))  # include_seed=True (the submit default)
    return reached


def pattern_truth(g, anchor):
    return sorted(int(h) for h in g.find_all(c.Incident(anchor)))


def make_requests(g, nodes, seed, n=40):
    rng = random.Random(seed)
    reqs = []
    for _ in range(n):
        if rng.random() < 0.6:
            s = rng.choice(nodes)
            reqs.append(("bfs", s, bfs_truth(g, s, 2)))
        else:
            a = rng.choice(nodes)
            reqs.append(("pattern", a, pattern_truth(g, a)))
    return reqs


def start_ingest(g, seed, stop):
    """Mutations in a DISCONNECTED fresh cluster: real compaction/delta
    pressure, zero effect on the precomputed truths."""
    def work():
        irng = random.Random(seed + 1)
        fresh = []
        i = 0
        while not stop.is_set():
            fresh.append(int(g.add(f"x{seed}-{i}")))
            if len(fresh) >= 2 and irng.random() < 0.3:
                a, b = irng.sample(fresh, 2)
                g.add_link((a, b), value=f"xl{seed}-{i}")
            i += 1
            time.sleep(0.001)

    t = threading.Thread(target=work, name="chaos-ingest", daemon=True)
    t.start()
    return t


def check_outcome(kind, truth, fut):
    """correct-or-typed: returns 'ok' | 'typed'."""
    try:
        res = fut.result(timeout=60)
    except (ServeError, FaultError):
        return "typed"
    if kind == "bfs":
        assert res.count == len(truth)
        got = set(res.matches.tolist())
        if res.truncated:
            assert got <= truth
        else:
            assert got == truth
    else:
        got = res.matches.tolist()
        if res.truncated:
            assert got == truth[: len(got)]
        else:
            assert got == truth
    return "ok"


def assert_fault_sequence_reproducible(faults, point, at):
    """The journal must equal the armed draw's offline replay: every
    reached index fired, in ascending order, nothing else — thread
    interleaving cannot perturb it (per-point schedule indexing)."""
    hits = faults.hits(point)
    expected = sorted(i for i in at if i <= hits)
    got = [idx for (name, idx) in faults.journal if name == point]
    assert got == expected


def run_serve_soak(seed, n_requests=45, n_nodes=60, n_links=90):
    launch_at, collect_at = draw_schedule(seed)
    faults = FaultRegistry().enable(seed=seed)
    faults.arm("serve.launch", at=launch_at)
    faults.arm("serve.collect", at=collect_at)
    g, nodes = build_graph(n_nodes, n_links)
    reqs = make_requests(g, nodes, seed, n_requests)
    cfg = ServeConfig(
        buckets=(64,), max_linger_s=0.001, default_deadline_s=10.0,
        max_retries=2, retry_base_s=0.0005, retry_max_s=0.005,
        retry_seed=seed, breaker_threshold=3, breaker_cooldown_s=0.01,
        max_lag_edges=100_000, faults=faults,
    )
    rt = ServeRuntime(g, cfg)
    stop = threading.Event()
    ingester = start_ingest(g, seed, stop)
    try:
        # waves of 3: enough dispatches that every armed index is
        # reached, while requests still coalesce into real micro-batches
        outcomes = []
        futs = []
        for w in range(0, len(reqs), 3):
            wave = []
            for kind, arg, truth in reqs[w:w + 3]:
                if kind == "bfs":
                    wave.append((kind, truth,
                                 rt.submit_bfs(arg, max_hops=2)))
                else:
                    wave.append((kind, truth, rt.submit_pattern([arg])))
            futs.extend(wave)
            outcomes.extend(check_outcome(k, t, f) for k, t, f in wave)
    finally:
        stop.set()
        ingester.join(timeout=10)
        rt.close(drain=True)

    # no stranded tickets: every future reached a terminal state
    assert all(f.done() for _, _, f in futs)
    # the stats identity, post-drain (in-flight == 0)
    s = rt.stats
    assert s.submitted == (
        s.completed + s.shed_deadline + s.cancelled + s.errors
    ), s.snapshot()
    assert s.submitted == len(reqs)
    assert rt.queue.depth() == 0
    # every armed index was reached: the schedule REALLY injected
    assert faults.hits("serve.launch") >= max(launch_at)
    assert faults.fired("serve.launch") == len(launch_at)
    assert faults.fired("serve.collect") == len(
        [i for i in collect_at if i <= faults.hits("serve.collect")]
    )
    assert faults.fired("serve.collect") >= 1
    assert outcomes.count("ok") > 0
    # reproducible by construction: offline replay == journal
    assert_fault_sequence_reproducible(faults, "serve.launch", launch_at)
    assert_fault_sequence_reproducible(faults, "serve.collect",
                                       collect_at)
    g.close()
    return outcomes, s


@pytest.mark.parametrize("seed", [1, 2, 3, 4, 5])
def test_chaos_serve_ingest_soak(seed):
    run_serve_soak(seed)


def test_chaos_replication_converges():
    """Lossy-wire replication: pre-drawn deterministic drops on the
    transport; redelivery + catch-up converge the replica exactly."""
    faults = global_faults()
    faults.reset()
    seed = 11
    rng = random.Random(seed)
    drops = set(rng.sample(range(1, 60), 10))

    net = LoopbackNetwork()
    ga, gb = hg.HyperGraph(), hg.HyperGraph()
    pa = HyperGraphPeer.loopback(ga, net, identity="chaos-a")
    pb = HyperGraphPeer.loopback(gb, net, identity="chaos-b")
    for p in (pa, pb):
        p.replication.send_backoff_s = 0.001
        p.replication.send_backoff_max_s = 0.005
        p.replication.debounce_s = 0.005
    pa.start()
    pb.start()
    try:
        pb.replication.publish_interest(None)
        deadline = time.monotonic() + 10
        while "chaos-b" not in pa.replication.peer_interests:
            assert time.monotonic() < deadline
            time.sleep(0.01)
        # arm AFTER the interest handshake: only replication pushes/acks
        # ride the lossy wire
        faults.enable(seed=seed)
        faults.arm(
            "peer.transport.send", at=drops,
            when=lambda ctx: ctx.get("activity") == "replication",
        )
        markers = []
        hs = []
        for i in range(30):
            h = ga.add(f"c{i}")
            hs.append(h)
            markers.append(f"c{i}")
            if i % 5 == 4:
                lm = f"cl{i}"
                ga.add_link((hs[i - 1], h), value=lm)
                markers.append(lm)
        assert pa.replication.flush(timeout=30)
        n_dropped = faults.fired("peer.transport.send")
        # heal the tail: disarm, catch up, drain both pipelines
        faults.disarm("peer.transport.send")
        pb.replication.catch_up("chaos-a")
        assert pb.replication.flush(timeout=30)
        deadline = time.monotonic() + 20
        missing = list(markers)
        while missing and time.monotonic() < deadline:
            missing = [m for m in missing if not q.find_all(gb, q.value(m))]
            time.sleep(0.02)
        assert not missing, f"replica missing {missing[:5]}..."
        # no duplicates despite redelivery
        for m in markers:
            assert len(q.find_all(gb, q.value(m))) == 1
        # the wire really dropped, deterministically: the journal is the
        # ascending subset of the pre-drawn indices that were reached
        assert n_dropped > 0
        dropped = [idx for (name, idx) in faults.journal
                   if name == "peer.transport.send"]
        assert dropped == sorted(dropped) and set(dropped) <= drops
    finally:
        pa.stop()
        pb.stop()
        faults.reset()
        faults.disable()


@pytest.mark.slow
def test_chaos_full_stack_soak_long():
    """The combined long soak: serving + ingest chaos across more seeds
    and a larger graph, with the replication leg riding the same run."""
    for seed in (21, 22, 23):
        outcomes, stats = run_serve_soak(seed, n_requests=120,
                                         n_nodes=120, n_links=200)
        assert outcomes.count("ok") >= len(outcomes) * 0.5
    test_chaos_replication_converges()


def test_chaos_tcp_replication_soak():
    """The TCP-backed replication soak (ROADMAP follow-up: the soaks
    drove loopback, TCP had unit coverage only): seeded deterministic
    drops on the REAL socket transport plus forced mid-soak socket
    deaths (reconnect path), then heal + catch-up → the replica
    converges exactly, no duplicates."""
    faults = global_faults()
    faults.reset()
    seed = 29
    rng = random.Random(seed)
    drops = set(rng.sample(range(1, 40), 8))

    from hypergraphdb_tpu.peer.transport import TCPPeerInterface

    ga, gb = hg.HyperGraph(), hg.HyperGraph()
    pa = HyperGraphPeer(ga, TCPPeerInterface("tcp-chaos-a",
                                             connect_timeout=2.0),
                        identity="tcp-chaos-a")
    pb = HyperGraphPeer(gb, TCPPeerInterface("tcp-chaos-b",
                                             connect_timeout=2.0),
                        identity="tcp-chaos-b")
    for p in (pa, pb):
        p.interface.peer_id = p.identity
        p.replication.send_backoff_s = 0.001
        p.replication.send_backoff_max_s = 0.005
        p.replication.debounce_s = 0.005
        p.replication.redelivery_interval_s = 0.02
    pa.start()
    pb.start()
    try:
        pa.interface.connect("tcp-chaos-b", pb.interface.addr)
        pb.interface.connect("tcp-chaos-a", pa.interface.addr)
        pb.replication.publish_interest(None)
        deadline = time.monotonic() + 10
        while "tcp-chaos-b" not in pa.replication.peer_interests:
            assert time.monotonic() < deadline
            time.sleep(0.01)
        faults.enable(seed=seed)
        faults.arm(
            "peer.transport.send", at=drops,
            when=lambda ctx: ctx.get("activity") == "replication",
        )
        markers = []
        hs = []
        for i in range(24):
            h = ga.add(f"tcp-c{i}")
            hs.append(h)
            markers.append(f"tcp-c{i}")
            if i % 6 == 5:
                lm = f"tcp-cl{i}"
                ga.add_link((hs[i - 1], h), value=lm)
                markers.append(lm)
            if i == 12:
                # mid-soak socket death: close A's cached outbound
                # sockets WITHOUT forgetting them — the next send hits a
                # dead socket and must reconnect (counted)
                with pa.interface._lock:
                    conns = list(pa.interface._conns.values())
                for s in conns:
                    s.close()
        assert pa.replication.flush(timeout=30)
        n_dropped = faults.fired("peer.transport.send")
        # heal the tail: disarm, catch up, drain both pipelines
        faults.disarm("peer.transport.send")
        pb.replication.catch_up("tcp-chaos-a")
        assert pb.replication.flush(timeout=30)
        deadline = time.monotonic() + 20
        missing = list(markers)
        while missing and time.monotonic() < deadline:
            missing = [m for m in missing if not q.find_all(gb, q.value(m))]
            time.sleep(0.02)
        assert not missing, f"TCP replica missing {missing[:5]}..."
        for m in markers:
            assert len(q.find_all(gb, q.value(m))) == 1   # no duplicates
        c = ga.metrics.counters
        assert n_dropped > 0                      # the wire really lost
        assert c.get("peer.transport_sends", 0) > 0
        # the socket deaths forced real reconnects on the TCP transport
        assert c.get("peer.transport_reconnects", 0) >= 1
        # deterministic: the journal is the ascending reached subset of
        # the pre-drawn drop indices
        fired = [idx for (name, idx) in faults.journal
                 if name == "peer.transport.send"]
        assert fired == sorted(fired) and set(fired) <= drops
    finally:
        pa.stop()
        pb.stop()
        faults.reset()
        faults.disable()
