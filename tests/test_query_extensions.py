"""Round-4 query-surface breadth: LinkIndexer, regex predicates, result
mappings (``ResultMapQuery`` + ``DerefMapping``/``LinkProjectionMapping``)
and ``PipeQuery`` — plus the partitioned (hazelstore-role) backend behind a
full HyperGraph."""

import numpy as np
import pytest

import hypergraphdb_tpu as hg
from hypergraphdb_tpu.query import dsl as q


@pytest.fixture()
def g():
    graph = hg.HyperGraph()
    yield graph
    graph.close()


def test_link_indexer_exact_tuple_lookup(g):
    from hypergraphdb_tpu.indexing.manager import (
        LinkIndexer,
        get_index,
        register,
    )

    nodes = [g.add(f"n{i}") for i in range(6)]
    th = int(g.typesystem.handle_of("string"))
    links = [
        g.add_link((nodes[i], nodes[(i + 1) % 6]), value=f"l{i}")
        for i in range(6)
    ]
    register(g, LinkIndexer("by-tuple", th))
    key = LinkIndexer.tuple_key((int(nodes[2]), int(nodes[3])))
    hits = get_index(g, "by-tuple").find(key).array()
    assert hits.tolist() == [int(links[2])]
    # ordered: the reversed tuple is a different key
    rkey = LinkIndexer.tuple_key((int(nodes[3]), int(nodes[2])))
    assert get_index(g, "by-tuple").find(rkey).array().tolist() == []


def test_value_regex_predicate(g):
    a = g.add("alpha-1")
    b = g.add("beta-2")
    n = g.add(42)  # non-string: never matches
    got = sorted(q.find_all(g, q.and_(q.type_("string"),
                                      q.value_regex(r"^alpha"))))
    assert got == [int(a)]
    got2 = sorted(q.find_all(g, q.and_(q.type_("string"),
                                       q.value_regex(r"-\d$"))))
    assert got2 == sorted([int(a), int(b)])


def test_part_regex_predicate(g):
    from dataclasses import dataclass

    @dataclass(frozen=True)
    class City:
        name: str = ""
        country: str = ""

    ams = g.add(City("Amsterdam", "NL"))
    ber = g.add(City("Berlin", "DE"))
    tname = g.typesystem.infer(City()).name
    got = q.find_all(g, q.and_(q.type_(tname), q.part_regex("name", r"^Ber")))
    assert got == [int(ber)]
    assert ams not in got


def test_link_projection_mapping(g):
    nodes = [g.add(f"n{i}") for i in range(5)]
    rels = [g.add_link((nodes[i], nodes[4]), value=i) for i in range(4)]
    # all links incident to nodes[4]; project target 0 → the sources
    got = q.target_at(g, q.incident(nodes[4]), 0)
    assert sorted(got.tolist()) == sorted(int(n) for n in nodes[:4])


def test_deref_mapping(g):
    xs = [g.add(f"v{i}") for i in range(3)]
    vals = q.deref(g, q.type_("string"))
    assert set(vals) >= {"v0", "v1", "v2"}


def test_pipe_query(g):
    """links-of-links: producer finds links incident to a node; the pipe
    keys each produced link into an incident() query (PipeQuery.java)."""
    n = g.add("root")
    l1 = g.add_link((n,), value="inner")
    l2 = g.add_link((l1,), value="outer")  # link pointing at a link
    got = q.pipe(g, q.incident(n), lambda k: q.incident(k))
    assert got.tolist() == [int(l2)]


def test_graph_over_partitioned_backend(tmp_path):
    """Full stack over the hazelstore-role backend, with durable children
    and reopen."""
    pytest.importorskip("hypergraphdb_tpu.storage.native")
    loc = str(tmp_path / "grid")
    cfg = hg.HGConfiguration(store_backend="partitioned", location=loc,
                             n_partitions=3)
    graph = hg.HyperGraph(cfg)
    a, b = graph.add("a"), graph.add("b")
    l = graph.add_link((a, b), value="edge")
    assert sorted(graph.find_all(q.incident(a))) == [int(l)]
    graph.close()

    g2 = hg.HyperGraph(hg.HGConfiguration(
        store_backend="partitioned", location=loc, n_partitions=3))
    assert g2.get(l).targets == (a, b)
    assert g2.get(a) == "a"
    assert sorted(g2.find_all(q.value("edge"))) == [int(l)]
    snap = g2.snapshot()
    assert snap.incidence_row(int(a)).tolist() == [int(l)]
    g2.close()


# --------------------------------------------------------------------------
# MapCondition — first-class, composable result mapping (VERDICT r4
# missing #7; ref query/MapCondition.java)
# --------------------------------------------------------------------------


def test_map_condition_composes_inside_and(graph):
    """and_(mapped(...), type_(...)): the projected target set intersects
    like any other set — impossible with the top-level result_map API."""
    from hypergraphdb_tpu.query import dsl as hg

    a = graph.add("a")
    n1 = graph.add(1)
    s1 = graph.add("s1")
    graph.add_link((a, n1), value="to-int")
    graph.add_link((a, s1), value="to-str")

    # targets-at-1 of links incident to a, restricted to ints
    cond = hg.and_(hg.mapped(hg.incident(a), position=1), hg.type_("int"))
    got = sorted(hg.find_all(graph, cond))
    assert got == [int(n1)]


def test_map_condition_inside_or(graph):
    from hypergraphdb_tpu.query import dsl as hg

    a = graph.add("a")
    b = graph.add("b")
    x = graph.add(10)
    y = graph.add(20)
    graph.add_link((a, x))
    graph.add_link((b, y))

    cond = hg.or_(
        hg.mapped(hg.incident(a), position=1),
        hg.mapped(hg.incident(b), position=1),
    )
    got = sorted(hg.find_all(graph, cond))
    assert got == sorted([int(x), int(y)])


def test_map_condition_standalone_matches_result_map(graph):
    from hypergraphdb_tpu.query import dsl as hg

    a = graph.add("a")
    outs = [graph.add(f"t{i}") for i in range(4)]
    for o in outs:
        graph.add_link((a, o))
    got = sorted(hg.find_all(graph, hg.mapped(hg.incident(a), position=1)))
    want = sorted(int(x) for x in hg.target_at(graph, hg.incident(a), 1))
    assert got == want == sorted(int(o) for o in outs)


def test_map_condition_has_no_satisfies(graph):
    from hypergraphdb_tpu.core.errors import QueryError
    from hypergraphdb_tpu.query import conditions as c
    from hypergraphdb_tpu.query.compiler import LinkProjectionMapping

    mc = c.MapCondition(LinkProjectionMapping(0), c.AnyAtom())
    with pytest.raises(QueryError):
        mc.satisfies(graph, 0)


def test_map_condition_rejects_value_mappings(graph):
    """Review r5 finding 6: Deref inside MapCondition fails at compile
    time, not deep inside set algebra."""
    from hypergraphdb_tpu.core.errors import QueryError
    from hypergraphdb_tpu.query import conditions as c
    from hypergraphdb_tpu.query import dsl as hg
    from hypergraphdb_tpu.query.compiler import DerefMapping, compile_query

    graph.add("x")
    with pytest.raises(QueryError, match="handles"):
        compile_query(
            graph,
            hg.and_(
                c.MapCondition(DerefMapping(), c.AnyAtom()),
                hg.type_("string"),
            ),
        )
