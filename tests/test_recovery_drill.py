"""The ROADMAP recovery drill: kill ingest at every registered crash
point, reopen, replay, assert differential-equal against an uninterrupted
run.

A child process ingests a deterministic op stream over the native
(WAL-backed) backend and arms an ``InjectedCrash`` at a registered
``tx.commit.*`` crash point for the k-th op — the crash escapes every
``except Exception`` recovery layer (it is a BaseException) and the child
``os._exit``\\ s like a real kill, mid-commit. The parent then reopens the
store, replays exactly the ops whose markers are missing, and compares
the CANONICAL graph content (values + link targets by value) against a
never-crashed run of the same stream.
"""

from __future__ import annotations

import os
import subprocess
import sys
import textwrap

import pytest

pytest.importorskip("hypergraphdb_tpu.storage.native")

import hypergraphdb_tpu as hg
from hypergraphdb_tpu.query import dsl as q

N_OPS = 30


def build_ops():
    """Deterministic op stream: nodes n0..; every third op links two
    earlier nodes (targets always exist by construction)."""
    ops = []
    nodes = 0
    for i in range(N_OPS):
        if i % 3 == 2 and nodes >= 2:
            ops.append(("link", f"l{i}", f"n{i - 2}", f"n{i - 1}"))
        else:
            ops.append(("node", f"n{i}", None, None))
            nodes += 1
    return ops


def apply_op(g, handles, op):
    kind, marker, ta, tb = op
    if kind == "node":
        handles[marker] = int(g.add(marker))
    else:
        handles[marker] = int(
            g.add_link((handles[ta], handles[tb]), value=marker)
        )


def lookup(g, marker):
    found = q.find_all(g, q.value(marker))
    return int(found[0]) if found else None


def replay_missing(g, ops):
    """Idempotent replay: apply exactly the ops whose marker is absent —
    the recovery contract (the op stream is the retained source)."""
    handles = {}
    replayed = 0
    for op in ops:
        kind, marker, ta, tb = op
        h = lookup(g, marker)
        if h is not None:
            handles[marker] = h
            continue
        apply_op(g, handles, op)
        replayed += 1
    return replayed


def canonical(g):
    """Graph content as structure-by-value: handle-free, so a crashed+
    replayed store and a pristine one compare exactly."""
    out = set()
    for op in build_ops():
        kind, marker, ta, tb = op
        h = lookup(g, marker)
        assert h is not None, f"marker {marker} missing"
        if kind == "node":
            out.add(("node", marker))
        else:
            tgt_vals = tuple(g.get(t) for t in g.get(h).targets)
            out.add(("link", marker, tgt_vals))
    return out


CHILD = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, {repo!r})
    from hypergraphdb_tpu.fault import InjectedCrash, global_faults
    import hypergraphdb_tpu as hg
    sys.path.insert(0, {testdir!r})
    from test_recovery_drill import apply_op, build_ops

    g = hg.HyperGraph(hg.HGConfiguration(store_backend="native",
                                         location={loc!r}))
    f = global_faults()
    handles = {{}}
    try:
        for i, op in enumerate(build_ops()):
            if i == {k}:
                # arm the registered crash point: the NEXT write commit
                # dies exactly like a kill -9 mid-commit
                f.enable(seed=0)
                f.arm({point!r}, at={{1}}, error=InjectedCrash)
            apply_op(g, handles, op)
        os._exit(7)   # survived: the drill expected a crash
    except InjectedCrash:
        os._exit(9)   # no shutdown, no flush — abrupt death
""")


@pytest.fixture(scope="module")
def reference(tmp_path_factory):
    """The uninterrupted run's canonical content (computed once)."""
    loc = str(tmp_path_factory.mktemp("ref") / "db")
    g = hg.HyperGraph(hg.HGConfiguration(store_backend="native",
                                         location=loc))
    handles = {}
    for op in build_ops():
        apply_op(g, handles, op)
    ref = canonical(g)
    g.close()
    return ref


@pytest.mark.parametrize("point", ["tx.commit.pre", "tx.commit.apply"])
@pytest.mark.parametrize("k", [3, 17])
def test_kill_reopen_replay_differential_equal(tmp_path, reference,
                                               point, k):
    loc = str(tmp_path / "db")
    code = CHILD.format(repo=os.getcwd(),
                        testdir=os.path.join(os.getcwd(), "tests"),
                        loc=loc, k=k, point=point)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", code], cwd=os.getcwd(),
                          env=env, timeout=240)
    assert proc.returncode == 9, "child did not die at the crash point"

    # reopen: WAL replay restores exactly the committed prefix — the
    # crashed op's batch (begun or not) must be invisible
    g = hg.HyperGraph(hg.HGConfiguration(store_backend="native",
                                         location=loc))
    ops = build_ops()
    assert lookup(g, ops[k][1]) is None      # the killed op never landed
    for op in ops[:k]:
        assert lookup(g, op[1]) is not None  # every earlier op survived

    replayed = replay_missing(g, ops)
    assert replayed == N_OPS - k
    assert canonical(g) == reference         # differential-equal
    g.close()

    # and the replayed store REOPENS equal too (replay itself durable)
    g2 = hg.HyperGraph(hg.HGConfiguration(store_backend="native",
                                          location=loc))
    assert canonical(g2) == reference
    g2.close()
