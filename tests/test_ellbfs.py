"""Differential tests: pull-mode ELL BFS vs the r2 push-scan kernel and a
pure-numpy host BFS, on random hypergraphs (the correctness oracle pattern
from SURVEY §7 M4)."""

import numpy as np
import pytest

from hypergraphdb_tpu.ops.snapshot import CSRSnapshot
from hypergraphdb_tpu.ops.ellbfs import (
    bfs_pull,
    build_reduce_plan,
    plans_for,
    visited_rows,
)


def random_snapshot(n_nodes, n_links, max_arity, seed, zipf=False):
    r = np.random.default_rng(seed)
    N = n_nodes + n_links
    type_of = np.zeros(N, dtype=np.int32)
    is_link = np.zeros(N, dtype=bool)
    is_link[n_nodes:] = True
    arities = r.integers(2, max_arity + 1, size=n_links)
    offsets = np.zeros(N + 1, dtype=np.int64)
    offsets[n_nodes + 1 :] = np.cumsum(arities)
    if zipf:
        flat = (r.zipf(1.3, size=int(arities.sum())) % n_nodes).astype(np.int64)
    else:
        flat = r.integers(0, n_nodes, size=int(arities.sum()))
    return CSRSnapshot.from_tables(type_of, is_link, offsets, flat)


def host_bfs(snap, seed_atom, hops):
    """Reference semantics: atom → incident links → targets."""
    visited = {int(seed_atom)}
    frontier = {int(seed_atom)}
    edges = 0
    for _ in range(hops):
        nxt = set()
        for a in frontier:
            row = snap.incidence_row(a)
            edges += len(row)
            for l in row.tolist():
                for t in snap.targets_row(int(l)).tolist():
                    if t not in visited:
                        nxt.add(int(t))
        visited |= nxt
        frontier = nxt
    return visited, edges


@pytest.mark.parametrize("zipf", [False, True])
@pytest.mark.parametrize("hops", [1, 2, 3])
def test_pull_matches_host(zipf, hops):
    snap = random_snapshot(400, 300, 4, seed=11 + hops, zipf=zipf)
    r = np.random.default_rng(5)
    seeds = r.integers(0, 400, size=48).astype(np.int32)
    res = bfs_pull(snap, seeds, hops)
    rows = visited_rows(res, snap.num_atoms)
    counts = np.asarray(res.edges_touched)
    reach = np.asarray(res.reach_counts)
    for k, s in enumerate(seeds.tolist()):
        want, edges = host_bfs(snap, s, hops)
        got = set(rows[k].tolist())
        assert got == want, f"seed {s}: {got ^ want}"
        assert counts[k] == edges
        assert reach[k] == len(want)


def test_pull_matches_bitfrontier():
    from hypergraphdb_tpu.ops.bitfrontier import bfs_packed, unpack_visited

    snap = random_snapshot(600, 500, 5, seed=3)
    seeds = np.arange(0, 64, dtype=np.int32) * 7 % 600
    res = bfs_pull(snap, seeds, 2)
    vis_old, cnt_old, _ = bfs_packed(snap, seeds, 2, k_block=64)
    old_bool = unpack_visited(vis_old, snap.num_atoms)
    rows = visited_rows(res, snap.num_atoms)
    for k in range(len(seeds)):
        assert set(rows[k].tolist()) == set(np.nonzero(old_bool[k])[0].tolist())
    assert np.array_equal(np.asarray(res.edges_touched), cnt_old.astype(np.int32))


def test_duplicate_and_padded_seeds():
    snap = random_snapshot(100, 80, 3, seed=9)
    seeds = np.asarray([5, 5, 17], dtype=np.int32)  # dupes + K%32 != 0
    res = bfs_pull(snap, seeds, 2)
    rows = visited_rows(res, snap.num_atoms)
    assert set(rows[0].tolist()) == set(rows[1].tolist())
    w0, _ = host_bfs(snap, 5, 2)
    assert set(rows[0].tolist()) == w0
    assert res.edges_touched.shape == (3,)


def test_chunked_scan_and_multiblock():
    """Exercise the chunk-streamed _reduce_level scan path (E > chunk*w) and
    the multi-block k_block driver — the two paths that otherwise only
    activate at benchmark scale."""
    snap = random_snapshot(500, 400, 5, seed=21, zipf=True)
    r = np.random.default_rng(17)
    seeds = r.integers(0, 500, size=96).astype(np.int32)
    res = bfs_pull(snap, seeds, 2, chunk=4, k_block=32)
    rows = visited_rows(res, snap.num_atoms)
    counts = np.asarray(res.edges_touched)
    assert counts.dtype == np.int64
    for k in (0, 31, 32, 63, 64, 95):  # spans all three k-blocks
        want, edges = host_bfs(snap, int(seeds[k]), 2)
        assert set(rows[k].tolist()) == want
        assert counts[k] == edges


def test_k_block_validation():
    snap = random_snapshot(50, 40, 3, seed=2)
    with pytest.raises(ValueError, match="k_block"):
        bfs_pull(snap, np.arange(8, dtype=np.int32), 1, k_block=48)
    with pytest.raises(ValueError, match="k_block"):
        bfs_pull(snap, np.arange(8, dtype=np.int32), 1, k_block=0)


def test_reduce_plan_shapes():
    offsets = np.asarray([0, 0, 3, 3, 20])  # empty, 3-row, empty, 17-row
    flat = np.arange(20, dtype=np.int64) % 7
    plan = build_reduce_plan(offsets, flat, 4, zero_row=7, w=4, w_upper=4)
    # empty rows address the global zero row at concat_size
    assert plan.out_map[0] == plan.concat_size
    assert plan.out_map[2] == plan.concat_size
    assert all(len(l) % w == 0 for l, w in zip(plan.levels, plan.widths))
    # row 3 has 17 entries → 5 chunks at w=4 → needs 2 levels above level 0
    assert len(plan.levels) >= 3


def test_plans_cached():
    snap = random_snapshot(50, 40, 3, seed=1)
    assert plans_for(snap) is plans_for(snap)
