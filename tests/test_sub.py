"""hgsub unit coverage: the SubscriptionManager's envelopes, dirty
tracking, delivery semantics, and wire decoding.

The chaos-style acceptance soak (multi-seed differential equality,
1k-subscription coalescing, door resume across a replica kill) lives in
tests/test_sub_soak.py; this file pins the per-component contracts:

- subscribe/unsubscribe envelopes and the initial-snapshot seq anchor;
- incremental deltas: adds, removals, range window movement, BFS
  pre-commit target capture — each chained (``seq_from`` == previous
  ``seq_to``) and digest-audited;
- backpressure: window overflow sheds the WHOLE queue and resyncs
  (shed-not-hang, counted ``sub.shed``) while an independent fast
  consumer stays current;
- long-poll park/wake, close-wakes-pollers, typed refusals;
- the ``sub.*`` metric namespace drift gate and the perf-sentinel
  ``sub`` lane feed.
"""

from __future__ import annotations

import threading
import time

import pytest

import hypergraphdb_tpu as hg
from hypergraphdb_tpu.query import conditions as c
from hypergraphdb_tpu.serve import ServeConfig, ServeRuntime
from hypergraphdb_tpu.serve.types import QueueFull, RuntimeClosed, \
    Unservable
from hypergraphdb_tpu.sub import SubConfig, SubscriptionManager
from hypergraphdb_tpu.sub import wire as sub_wire
from hypergraphdb_tpu.sub.registry import match_digest
from hypergraphdb_tpu.sub.stats import DOTTED_NAMES, SubStats


def serve_cfg(**kw):
    kw.setdefault("buckets", (4,))
    kw.setdefault("max_linger_s", 0.001)
    kw.setdefault("prewarm_aot", False)
    return ServeConfig(**kw)


@pytest.fixture
def rig():
    """A small live graph + serving runtime + attached manager."""
    g = hg.HyperGraph()
    nodes = [int(g.add(i)) for i in range(8)]
    links = [int(g.add_link((nodes[0], nodes[k]), value=100 + k))
             for k in (1, 2, 3)]
    rt = ServeRuntime(g, serve_cfg())
    mgr = SubscriptionManager(g, rt)
    rt.attach_subscriptions(mgr)
    try:
        yield g, rt, mgr, nodes, links
    finally:
        mgr.close()
        rt.close(drain=False)
        g.close()


def settle(mgr, timeout=30.0):
    """Drive the evaluator until nothing is dirty or in flight."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        mgr.pump()
        with mgr._lock:
            busy = any(s.dirty or s.inflight is not None
                       for s in mgr.subs.all())
        if not busy:
            return
        time.sleep(0.005)
    raise AssertionError("subscriptions never settled")


def fold(matches, notes):
    """Client-side delta fold, asserting the chain + digest audit."""
    out = set(matches)
    for n in notes:
        assert n["what"] == "notification"
        out.difference_update(int(h) for h in n["removed"])
        out.update(int(h) for h in n["added"])
        assert n["digest"] == match_digest(out)
    return out


# --------------------------------------------------------------- envelopes


def test_subscribe_envelope_is_the_exact_initial_snapshot(rig):
    g, rt, mgr, nodes, links = rig
    resp = mgr.subscribe("pattern", {"anchors": [nodes[0]]})
    assert resp["what"] == "subscribed" and resp["kind"] == "pattern"
    assert resp["id"].startswith("sub-")
    want = {int(h) for h in g.find_all(c.Incident(nodes[0]))}
    assert set(resp["matches"]) == want == set(links)
    assert resp["digest"] == match_digest(want)
    assert resp["window"] == SubConfig().default_window
    out = mgr.unsubscribe(resp["id"])
    assert out == {"what": "unsubscribed", "id": resp["id"]}
    with pytest.raises(Unservable):
        mgr.poll(resp["id"], timeout_s=0.0)


def test_typed_refusals(rig):
    g, rt, mgr, nodes, links = rig
    with pytest.raises(Unservable):
        mgr.subscribe("tensor", {})                   # unknown kind
    with pytest.raises(Unservable):
        mgr.subscribe("pattern", {"anchors": [nodes[0]]}, window=0)
    with pytest.raises(Unservable):
        # top-k has no incremental delta semantics
        mgr.subscribe("range", {"lo": 1, "hi": 9, "limit": 4})
    with pytest.raises(Unservable):
        mgr.subscribe("range", {"lo": 1, "hi": 9, "desc": True})
    with pytest.raises(Unservable):
        mgr.poll("sub-999", timeout_s=0.0)
    with pytest.raises(Unservable):
        mgr.unsubscribe("sub-999")


def test_capacity_is_queue_full(rig):
    g, rt, mgr, nodes, links = rig
    mgr.config.max_subscriptions = 1
    mgr.subscribe("pattern", {"anchors": [nodes[0]]})
    with pytest.raises(QueueFull):
        mgr.subscribe("pattern", {"anchors": [nodes[1]]})


def test_closed_manager_refuses_subscribe(rig):
    g, rt, mgr, nodes, links = rig
    mgr.close()
    with pytest.raises(RuntimeClosed):
        mgr.subscribe("pattern", {"anchors": [nodes[0]]})


# ------------------------------------------------------ incremental deltas


def test_pattern_delta_chains_adds_and_removals(rig):
    g, rt, mgr, nodes, links = rig
    resp = mgr.subscribe("pattern", {"anchors": [nodes[0]]})
    sid = resp["id"]
    fresh = int(g.add_link((nodes[0], nodes[4]), value=999))
    settle(mgr)
    env = mgr.poll(sid, timeout_s=0.0)
    assert env["what"] == "notifications" and not env["more"]
    (note,) = env["notes"]
    assert note["seq_from"] == resp["seq"]          # chains off subscribe
    assert note["added"] == [fresh] and note["removed"] == []
    folded = fold(resp["matches"], [note])

    g.remove(fresh)
    settle(mgr)
    (note2,) = mgr.poll(sid, timeout_s=0.0)["notes"]
    assert note2["seq_from"] == note["seq_to"]      # consecutive chain
    assert note2["removed"] == [fresh] and note2["added"] == []
    folded = fold(folded, [note2])
    assert folded == {int(h) for h in g.find_all(c.Incident(nodes[0]))}


def test_irrelevant_ingest_never_fires(rig):
    g, rt, mgr, nodes, links = rig
    sid = mgr.subscribe("pattern", {"anchors": [nodes[0]]})["id"]
    evals_before = mgr.stats.evals
    g.add_link((nodes[5], nodes[6]), value=777)     # misses the anchor
    settle(mgr)
    env = mgr.poll(sid, timeout_s=0.0)
    assert env["notes"] == [] and not env["more"]
    # the incremental tier's whole point: no re-evaluation happened
    assert mgr.stats.evals == evals_before


def test_range_window_movement(rig):
    g, rt, mgr, nodes, links = rig
    resp = mgr.subscribe("range", {"lo": 100, "hi": 150})
    sid = resp["id"]
    assert set(resp["matches"]) == set(links)       # values 101..103
    inside = int(g.add(120))
    g.add(4242)                                     # outside the window
    settle(mgr)
    notes = mgr.poll(sid, timeout_s=0.0)["notes"]
    assert [n["added"] for n in notes] == [[inside]]
    # value moves OUT of the window via replace -> removal delta
    g.replace(inside, 9999)
    settle(mgr)
    (note,) = mgr.poll(sid, timeout_s=0.0)["notes"]
    assert note["removed"] == [inside]


def test_bfs_removal_uses_precommit_targets(rig):
    g, rt, mgr, nodes, links = rig
    resp = mgr.subscribe("bfs", {"seed": nodes[0], "max_hops": 1})
    sid = resp["id"]
    assert nodes[1] in set(resp["matches"])
    # removing the link makes nodes[1] unreachable; its targets are only
    # readable BEFORE the commit (the HGAtomRemoveRequestEvent capture)
    g.remove(links[0])
    settle(mgr)
    folded = fold(resp["matches"], mgr.poll(sid, timeout_s=0.0)["notes"])
    want = resp_matches_now = {
        int(nbr) for _, nbr in __import__(
            "hypergraphdb_tpu.algorithms.traversals",
            fromlist=["HGBreadthFirstTraversal"],
        ).HGBreadthFirstTraversal(g, nodes[0], max_distance=1)
    }
    assert folded == want
    assert nodes[1] not in folded


# ------------------------------------------------- backpressure / delivery


def test_slow_consumer_sheds_to_resync_fast_stays_current(rig):
    g, rt, mgr, nodes, links = rig
    slow = mgr.subscribe("pattern", {"anchors": [nodes[0]]}, window=1)
    fast = mgr.subscribe("pattern", {"anchors": [nodes[0]]}, window=64)
    folded = set(fast["matches"])
    for k in range(3):                 # 3 deltas > the slow window of 1
        g.add_link((nodes[0], nodes[4 + k]), value=500 + k)
        settle(mgr)
        # the fast consumer drains every round and stays current
        folded = fold(folded, mgr.poll(fast["id"], timeout_s=0.0)["notes"])
    want = {int(h) for h in g.find_all(c.Incident(nodes[0]))}
    assert folded == want
    # the slow consumer overflowed: typed resync with the EXACT set,
    # never a silent gap
    env = mgr.poll(slow["id"], timeout_s=0.0)
    assert env["what"] == "resync"
    assert set(env["matches"]) == want
    assert env["digest"] == match_digest(want)
    assert mgr.stats.shed > 0
    snap = mgr.stats.snapshot()
    assert snap["sub.resyncs"] == 1
    # after the resync the queue chain restarts cleanly
    g.add_link((nodes[0], nodes[7]), value=909)
    settle(mgr)
    env2 = mgr.poll(slow["id"], timeout_s=0.0)
    assert env2["what"] == "notifications"
    assert env2["notes"][0]["seq_from"] >= env["seq"]


def test_long_poll_parks_until_a_delta_arrives(rig):
    g, rt, mgr, nodes, links = rig
    sid = mgr.subscribe("pattern", {"anchors": [nodes[0]]})["id"]
    out = {}

    def park():
        out["env"] = mgr.poll(sid, timeout_s=10.0)

    t = threading.Thread(target=park)
    t.start()
    time.sleep(0.05)
    g.add_link((nodes[0], nodes[5]), value=321)
    settle(mgr)
    t.join(timeout=10)
    assert not t.is_alive()
    assert out["env"]["notes"], "parked poll never woke on the delta"


def test_close_wakes_parked_pollers(rig):
    g, rt, mgr, nodes, links = rig
    sid = mgr.subscribe("pattern", {"anchors": [nodes[0]]})["id"]
    out = {}

    def park():
        try:
            mgr.poll(sid, timeout_s=30.0)
        except Unservable as e:
            out["err"] = e

    t = threading.Thread(target=park)
    t.start()
    time.sleep(0.05)
    mgr.close()
    t.join(timeout=10)
    assert not t.is_alive() and "err" in out


def test_poll_batches_and_reports_more(rig):
    g, rt, mgr, nodes, links = rig
    sid = mgr.subscribe("pattern", {"anchors": [nodes[0]]},
                        window=16)["id"]
    for k in range(3):
        g.add_link((nodes[0], nodes[4 + k]), value=600 + k)
        settle(mgr)                    # one delta per settled round
    env = mgr.poll(sid, max_notes=2, timeout_s=0.0)
    assert len(env["notes"]) == 2 and env["more"]
    env2 = mgr.poll(sid, max_notes=2, timeout_s=0.0)
    assert len(env2["notes"]) == 1 and not env2["more"]
    assert env2["notes"][0]["seq_from"] == env["notes"][-1]["seq_to"]


# ----------------------------------------------------- seq / health / perf


def test_seq_source_anchors_notifications(rig):
    g, rt, mgr, nodes, links = rig
    ext = {"seq": 41}
    mgr._seq_source = lambda: ext["seq"]
    resp = mgr.subscribe("pattern", {"anchors": [nodes[0]]})
    assert resp["seq"] >= 41           # anchored at the external clock
    ext["seq"] = 57
    g.add_link((nodes[0], nodes[6]), value=808)
    settle(mgr)
    (note,) = mgr.poll(resp["id"], timeout_s=0.0)["notes"]
    assert note["seq_to"] >= 57
    assert note["seq_from"] == resp["seq"]


def test_health_section_shape(rig):
    g, rt, mgr, nodes, links = rig
    mgr.subscribe("pattern", {"anchors": [nodes[0]]})
    h = mgr.health_section()
    assert h["active"] == 1 and h["violating"] is False
    assert h["bound_s"] == mgr.config.staleness_bound_s
    assert {"dirty", "inflight", "staleness_s", "notified_total",
            "shed_total"} <= set(h)


def test_manager_feeds_the_perf_sentinel_sub_lane(rig):
    g, rt, mgr, nodes, links = rig
    samples = []

    class Tap:
        def observe(self, kind, latency_s, path="device", t=None):
            samples.append((kind, latency_s))

    rt.perf = Tap()
    sid = mgr.subscribe("pattern", {"anchors": [nodes[0]]})["id"]
    g.add_link((nodes[0], nodes[4]), value=111)
    settle(mgr)
    assert mgr.poll(sid, timeout_s=0.0)["notes"]
    subs = [(k, lat) for k, lat in samples if k == "sub"]
    assert len(subs) == 1 and subs[0][1] >= 0.0


def test_metrics_namespace_no_drift():
    assert set(SubStats().snapshot()) == set(DOTTED_NAMES)


# ------------------------------------------------------------ wire decoding


def test_wire_subscribe_and_poll_payloads(rig):
    g, rt, mgr, nodes, links = rig
    resp = sub_wire.subscribe_payload(mgr, {
        "what": "subscribe", "kind": "pattern", "anchors": [nodes[0]],
        "window": 8,
    })
    assert resp["what"] == "subscribed" and resp["window"] == 8
    g.add_link((nodes[0], nodes[5]), value=222)
    settle(mgr)
    env = sub_wire.poll_payload(mgr, {"id": resp["id"],
                                      "timeout_s": "0", "max": "16"})
    assert env["what"] == "notifications" and env["notes"]
    out = sub_wire.subscribe_payload(mgr, {"what": "unsubscribe",
                                           "id": resp["id"]})
    assert out["what"] == "unsubscribed"


def test_wire_refusals_are_typed(rig):
    g, rt, mgr, nodes, links = rig
    with pytest.raises(Unservable):
        sub_wire.subscribe_payload(mgr, {"what": "subscribe"})
    with pytest.raises(Unservable):
        sub_wire.subscribe_payload(mgr, {"what": "subscribe",
                                         "kind": "pattern"})
    with pytest.raises(Unservable):
        sub_wire.subscribe_payload(mgr, {"what": "subscribe",
                                         "kind": "bfs"})
    with pytest.raises(Unservable):
        sub_wire.subscribe_payload(mgr, {"what": "frobnicate"})
    with pytest.raises(Unservable):
        sub_wire.poll_payload(mgr, {})
    with pytest.raises(Unservable):
        sub_wire.poll_payload(mgr, {"id": "sub-1", "timeout_s": "soon"})


def test_wire_poll_timeout_is_clamped(rig):
    g, rt, mgr, nodes, links = rig
    sid = sub_wire.subscribe_payload(mgr, {
        "what": "subscribe", "kind": "pattern", "anchors": [nodes[0]],
    })["id"]
    t0 = time.monotonic()
    env = sub_wire.poll_payload(mgr, {"id": sid, "timeout_s": 9999},
                                max_timeout_s=0.05)
    assert time.monotonic() - t0 < 5.0
    assert env["notes"] == []
