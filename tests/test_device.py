"""Device-plane tests: CSR snapshot correctness + differential kernel checks.

The M4 gate from SURVEY §7: device BFS and conjunctive-pattern kernels must
match the host engine bit-for-bit on random hypergraphs.
"""

import numpy as np
import pytest

from hypergraphdb_tpu import HyperGraph
from hypergraphdb_tpu.algorithms.traversals import HGBreadthFirstTraversal
from hypergraphdb_tpu.ops import frontier as F
from hypergraphdb_tpu.ops import setops as S
from hypergraphdb_tpu.query import dsl as hg

from conftest import make_random_hypergraph


@pytest.fixture(scope="module")
def random_db():
    g = HyperGraph()
    nodes, links = make_random_hypergraph(g, n_nodes=150, n_links=400, max_arity=4,
                                          seed=3)
    snap = g.snapshot()
    yield g, nodes, links, snap
    g.close()


# ---------------------------------------------------------------- snapshot


def test_snapshot_incidence_matches_store(random_db):
    g, nodes, links, snap = random_db
    for a in nodes[:20]:
        expected = g.get_incidence_set(a).array()
        got = snap.incidence_row(a)
        assert got.tolist() == expected.tolist()


def test_snapshot_targets_match_store(random_db):
    g, nodes, links, snap = random_db
    for l in links[:20]:
        assert snap.targets_row(l).tolist() == list(g.get_targets(l))
        assert snap.arity[l] == g.arity(l)
        assert bool(snap.is_link[l])


def test_snapshot_types_match(random_db):
    g, nodes, links, snap = random_db
    for h in (*nodes[:10], *links[:10]):
        assert snap.type_of[h] == g.get_type_handle_of(h)


def test_snapshot_by_type_index(random_db):
    g, nodes, links, snap = random_db
    th = g.typesystem.handle_of("string")
    expected = sorted(g.find_all(hg.type_("string")))
    assert snap.type_set(th).tolist() == expected


def test_snapshot_version_caching(graph):
    graph.add("x")
    s1 = graph.snapshot()
    s2 = graph.snapshot()
    assert s1 is s2  # fresh → cached
    graph.add("y")
    s3 = graph.snapshot()
    assert s3 is not s1


# ---------------------------------------------------------------- BFS kernel


def _host_bfs_set(g, seed, hops):
    return sorted(
        a for _, a in HGBreadthFirstTraversal(g, seed, max_distance=hops)
    )


@pytest.mark.parametrize("hops", [1, 2, 3])
def test_device_bfs_matches_host(random_db, hops):
    g, nodes, links, snap = random_db
    seeds = np.asarray(nodes[:32], dtype=np.int32)
    device_results = F.bfs_reachable_host(snap, seeds, hops)
    for s, dev_set in zip(seeds.tolist(), device_results):
        assert dev_set.tolist() == _host_bfs_set(g, s, hops), f"seed {s} hops {hops}"


def test_device_bfs_levels(random_db):
    g, nodes, links, snap = random_db
    import jax.numpy as jnp

    seed = nodes[0]
    levels, visited = F.bfs_levels(snap.device, jnp.asarray([seed]), 3)
    levels = np.asarray(levels)[0]
    # distance-1 atoms = host BFS with max_distance 1
    d1 = set(_host_bfs_set(g, seed, 1))
    got_d1 = set(np.nonzero(levels == 1)[0].tolist())
    assert got_d1 == d1
    assert levels[seed] == 0


def test_frontier_edge_counts_positive(random_db):
    g, nodes, links, snap = random_db
    import jax.numpy as jnp

    n = F.frontier_edge_counts(snap.device, jnp.asarray(nodes[:8], dtype=jnp.int32), 2)
    assert np.asarray(n).sum() > 0


# ---------------------------------------------------------------- set kernels


def test_device_intersect_matches_numpy(rng):
    for _ in range(5):
        a = np.unique(rng.integers(0, 500, size=rng.integers(1, 200)))
        b = np.unique(rng.integers(0, 500, size=rng.integers(1, 200)))
        c = np.unique(rng.integers(0, 500, size=rng.integers(1, 200)))
        got = S.device_intersect_sorted([a, b, c])
        expected = np.intersect1d(np.intersect1d(a, b), c)
        assert got.tolist() == expected.tolist()


def test_and_incident_pattern_matches_query(random_db):
    g, nodes, links, snap = random_db
    # pick anchor pairs that share at least one link where possible
    pairs = []
    for l in links[:40]:
        ts = g.get_targets(l)
        if len(ts) >= 2:
            pairs.append((int(ts[0]), int(ts[1])))
        if len(pairs) == 16:
            break
    results = S.and_incident_pattern(snap, pairs)
    for (a, b), got in zip(pairs, results):
        expected = sorted(g.find_all(hg.and_(hg.incident(a), hg.incident(b))))
        assert got.tolist() == expected


def test_and_incident_pattern_with_type(random_db):
    g, nodes, links, snap = random_db
    th = int(g.typesystem.handle_of("int"))
    pairs = []
    for l in links[:20]:
        ts = g.get_targets(l)
        if len(ts) >= 2:
            pairs.append((int(ts[0]), int(ts[1])))
    results = S.and_incident_pattern(snap, pairs, type_handle=th)
    for (a, b), got in zip(pairs, results):
        expected = sorted(
            g.find_all(
                hg.and_(hg.type_("int"), hg.incident(a), hg.incident(b))
            )
        )
        assert got.tolist() == expected


def test_pattern_plan_execute_collect_roundtrip(random_db):
    """plan/execute/collect (the steady-state serving path) must agree with
    the one-shot wrapper, including when top_r=1 forces the overflow
    re-materialization branch in collect_pattern."""
    g, nodes, links, snap = random_db
    pairs = []
    for l in links[:40]:
        ts = g.get_targets(l)
        if len(ts) >= 2:
            pairs.append((int(ts[0]), int(ts[1])))
    want = S.and_incident_pattern(snap, pairs)
    plan = S.plan_pattern(snap, pairs)
    got = S.collect_pattern(plan, S.execute_pattern(plan))
    got_overflow = S.collect_pattern(plan, S.execute_pattern(plan, top_r=1))
    for w, a, b in zip(want, got, got_overflow):
        assert a.tolist() == w.tolist()
        assert b.tolist() == w.tolist()


def test_ell_targets_width_cap(graph):
    """Snapshots with a link wider than the ELL cap fall back (None) and
    the pattern kernel still answers via the zigzag path."""
    g = graph
    ids = [g.add(i) for i in range(80)]
    wide = g.add_link(tuple(ids), value="wide")  # arity 80 > default cap 64
    l1 = g.add_link((ids[0], ids[1]), value="x")
    snap = g.snapshot()
    assert S.ell_targets(snap) is None
    out = S.and_incident_pattern(snap, [(int(ids[0]), int(ids[1]))])
    assert out[0].tolist() == sorted([int(wide), int(l1)])


def test_member_mask_edges():
    import jax.numpy as jnp

    ref = jnp.asarray(S.pad_sorted(np.asarray([2, 5, 9], dtype=np.int32), 8))
    q = jnp.asarray(S.pad_sorted(np.asarray([1, 2, 9, 10], dtype=np.int32), 8))
    got = np.asarray(S.member_mask(ref, q))
    assert got[:4].tolist() == [False, True, True, False]
    assert not got[4:].any()  # padding never matches
