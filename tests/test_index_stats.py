"""Index statistics → planner (VERDICT r4 missing #3): range-scan and
user-index estimates come from cost-capped real counts (and persisted
whole-index stats when capped), not hardcoded 1e6/1e12 constants — the
reference's ``HGIndexStats.java:37`` feeding ``ResultSizeEstimation``."""

import numpy as np
import pytest

from hypergraphdb_tpu import HyperGraph
from hypergraphdb_tpu.indexing import manager as ixm
from hypergraphdb_tpu.query import dsl as hg
from hypergraphdb_tpu.query.compiler import (
    AllAtomsPlan,
    IntersectPlan,
    TypeSetPlan,
    ValueSetPlan,
    compile_query,
)


@pytest.fixture
def valued_graph():
    g = HyperGraph()
    for i in range(500):
        g.add(i)  # ints 0..499
    yield g
    g.close()


def test_range_estimate_is_real_count(valued_graph):
    g = valued_graph
    q = compile_query(g, hg.value(495, "gt"))
    assert isinstance(q.plan, ValueSetPlan)
    # 496..499 → 4 atoms; the old constant was 1e6
    assert q.plan.estimate(g) == 4.0
    got = q.plan.run(g)
    assert len(got) == 4


def test_range_plus_type_conjunction_orders_narrow_range_first(valued_graph):
    """The plan-shape regression VERDICT asked for: a NARROW range against
    a WIDE type set must run range-first (with the old 1e6 constant the
    wide type set always ordered first — silently wrong)."""
    g = valued_graph
    cond = hg.and_(hg.type_("int"), hg.value(495, "gt"))
    q = compile_query(g, cond)
    assert isinstance(q.plan, IntersectPlan), q.analyze()
    ests = {
        type(ch).__name__: ch.estimate(g) for ch in q.plan.children
    }
    assert ests["ValueSetPlan"] < ests["TypeSetPlan"], ests
    assert sorted(
        q.plan.children, key=lambda p: p.estimate(g)
    )[0].__class__ is ValueSetPlan
    # and the results are still exact
    assert sorted(g.get(h) for h in g.find_all(cond)) == [496, 497, 498, 499]


def test_wide_range_estimate_caps_not_constant(valued_graph):
    g = valued_graph
    g.config.query.range_estimate_cap = 64
    q = compile_query(g, hg.value(-1, "gt"))  # all 500 atoms
    est = q.plan.estimate(g)
    assert 64 <= est < 1e6  # capped fallback, never the old constant


def test_all_atoms_estimate_tracks_id_highwater(valued_graph):
    g = valued_graph
    est = AllAtomsPlan().estimate(g)
    assert 500 <= est <= 10_000  # dense-id high-water, not 1e12


def test_user_index_range_estimate(valued_graph):
    g = valued_graph
    from hypergraphdb_tpu.indexing.manager import DirectValueIndexer, register

    th = g.typesystem.handle_of("int")
    register(g, DirectValueIndexer("by-int", th))
    idx = ixm.get_index(g, "by-int")
    assert idx.key_count() > 0
    stats = ixm.index_stats(g, "by-int")
    assert stats["entries"] == 500 and stats["keys"] == 500
    # second call reuses the persisted record (no drift)
    again = ixm.index_stats(g, "by-int")
    assert again == stats


def test_index_stats_persist_across_reopen(tmp_path):
    pytest.importorskip("hypergraphdb_tpu.storage.native")
    import hypergraphdb_tpu as hgm

    loc = str(tmp_path / "db")
    g = HyperGraph(hgm.HGConfiguration(store_backend="native", location=loc))
    for i in range(50):
        g.add(i)
    from hypergraphdb_tpu.core.graph import IDX_BY_VALUE

    s1 = ixm.index_stats(g, IDX_BY_VALUE)
    assert s1["entries"] >= 50
    g.close()

    g2 = HyperGraph(hgm.HGConfiguration(store_backend="native", location=loc))
    # restored from the persisted record: same counts, same version marker
    s2 = ixm.index_stats(g2, IDX_BY_VALUE)
    assert s2["entries"] == s1["entries"]
    assert s2["version"] == s1["version"]
    # a forced refresh recounts live
    s3 = ixm.index_stats(g2, IDX_BY_VALUE, refresh=True)
    assert s3["entries"] >= 50
    g2.close()


def test_stats_recount_when_index_changed_across_reopen(tmp_path):
    """Review r5 finding 3: the session mutation counter resets at reopen,
    so a negative drift must not validate a stale record — the live key
    count is the cross-session authority."""
    pytest.importorskip("hypergraphdb_tpu.storage.native")
    import hypergraphdb_tpu as hgm
    from hypergraphdb_tpu.core.graph import IDX_BY_VALUE

    loc = str(tmp_path / "db")
    g = HyperGraph(hgm.HGConfiguration(store_backend="native", location=loc))
    for i in range(40):
        g.add(i)
    s1 = ixm.index_stats(g, IDX_BY_VALUE)
    g.close()

    g2 = HyperGraph(hgm.HGConfiguration(store_backend="native", location=loc))
    # grow the index far past the 25% key-drift window, with FEWER session
    # mutations than the recorded version
    for i in range(4000):
        g2.add(10_000 + i)
    s2 = ixm.index_stats(g2, IDX_BY_VALUE)
    assert s2["entries"] > s1["entries"], (s1, s2)
    g2.close()
