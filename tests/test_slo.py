"""hgslo: sliding-window error budgets + multi-window burn-rate alerts.

Everything runs on fake clocks; the acceptance contract is the chaos
smoke at the bottom: a serving runtime shedding past its deadline SLO
fires a burn-rate incident THROUGH the flight recorder, window dump on
disk.
"""

from __future__ import annotations

import os

import pytest

from hypergraphdb_tpu.obs.flight import FlightRecorder, parse_flight_jsonl
from hypergraphdb_tpu.obs.fleet import FleetCollector, LocalNodeSource
from hypergraphdb_tpu.obs.slo import (
    Objective,
    SLOMonitor,
    fleet_objectives,
)
from tests.test_serve_runtime import FakeClock, FakeExecutor, make_runtime


def make_monitor(windows=((10.0, 2.0), (60.0, 1.0)), target=0.99,
                 incident_dir=None):
    clock = FakeClock()
    flight = FlightRecorder(clock=clock, incident_dir=incident_dir,
                            min_dump_interval_s=0.0)
    mon = SLOMonitor(clock=clock, flight=flight)
    mon.add(Objective("obj", target, windows=windows))
    return mon, clock, flight


# ---------------------------------------------------------------- windows


def test_objective_validation():
    with pytest.raises(ValueError):
        Objective("x", 1.5)
    with pytest.raises(ValueError):
        Objective("x", 0.99, windows=())
    with pytest.raises(ValueError):
        Objective("x", 0.99, windows=((60.0, 1.0), (10.0, 2.0)))  # order


def test_burn_rate_math_over_sliding_window():
    mon, clock, _ = make_monitor()
    good = bad = 0
    for _ in range(60):
        clock.advance(1.0)
        good += 95
        bad += 5                     # 5% errors against a 1% budget
        mon.record("obj", good, bad)
    snap = mon.tick()["obj"]
    fast = snap["windows"][0]
    assert fast["error_ratio"] == pytest.approx(0.05)
    assert fast["burn_rate"] == pytest.approx(5.0)
    assert snap["budget_remaining"] == pytest.approx(1.0 - 5.0, rel=1e-3)


def test_alert_needs_every_window_burning():
    # fast window burns, the long window has already recovered: no alert
    mon, clock, flight = make_monitor(windows=((10.0, 2.0), (60.0, 4.0)))
    good = bad = 0
    for i in range(60):
        clock.advance(1.0)
        good += 99
        bad += 3 if i >= 50 else 0   # errors only in the last 10 s
        mon.record("obj", good, bad)
        snap = mon.tick()["obj"]
    fast, slow = snap["windows"]
    assert fast["burning"] is True
    assert slow["burning"] is False
    assert snap["alerting"] is False
    assert flight.incidents == 0


def test_idle_windows_never_alert():
    mon, clock, flight = make_monitor()
    for _ in range(100):
        clock.advance(1.0)
        mon.record("obj", 0, 0)      # an idle fleet must not page
        snap = mon.tick()["obj"]
    assert snap["alerting"] is False
    assert all(w["burn_rate"] is None for w in snap["windows"])
    assert flight.incidents == 0


def test_alert_fires_once_and_rearms_after_recovery():
    mon, clock, flight = make_monitor()
    good = bad = 0
    for _ in range(100):             # sustained 50% errors
        clock.advance(1.0)
        good += 5
        bad += 5
        mon.record("obj", good, bad)
        mon.tick()
    assert flight.incidents == 1     # edge-triggered, not per-eval
    snap = mon.snapshot()["obj"]
    assert snap["alerting"] is True and snap["alerts_total"] == 1
    for _ in range(200):             # clean recovery
        clock.advance(1.0)
        good += 10
        mon.record("obj", good, bad)
        mon.tick()
    assert mon.snapshot()["obj"]["alerting"] is False
    good += 0
    for _ in range(100):             # burn again → second incident
        clock.advance(1.0)
        bad += 5
        good += 5
        mon.record("obj", good, bad)
        mon.tick()
    assert flight.incidents == 2


def test_flapping_short_window_stays_one_alert():
    """Hysteresis re-arms only once EVERY window recovers: a sustained
    long-window burn whose short window dips clean for a tick must not
    fire one incident per oscillation."""
    mon, clock, flight = make_monitor(windows=((10.0, 2.0), (60.0, 1.0)))
    good = bad = 0
    for _ in range(60):              # sustained burn: both windows hot
        clock.advance(1.0)
        good += 5
        bad += 5
        mon.record("obj", good, bad)
        mon.tick()
    assert flight.incidents == 1
    for i in range(60):              # short window flaps, long stays hot
        clock.advance(1.0)
        if i % 12 < 6:
            good += 10               # clean burst: fast window recovers
        else:
            good += 5
            bad += 5                 # ...then burns again
        mon.record("obj", good, bad)
        snap = mon.tick()["obj"]
    assert snap["windows"][1]["burning"] is True   # the outage persists
    assert flight.incidents == 1                   # still ONE alert
    assert snap["alerts_total"] == 1


def test_snapshot_is_a_pure_read():
    mon, clock, flight = make_monitor()
    good = bad = 0
    for _ in range(100):
        clock.advance(1.0)
        good += 5
        bad += 5
        mon.record("obj", good, bad)
        snap = mon.snapshot()["obj"]     # reads only: no alert edges
    assert snap["alerting"] is False
    assert flight.incidents == 0
    mon.tick()                            # the tick owns the edge
    assert flight.incidents == 1


def test_incident_dump_written_with_window(tmp_path):
    mon, clock, flight = make_monitor(incident_dir=str(tmp_path))
    flight.record("serve.retry", attempt=1)   # context BEFORE the burn
    good = bad = 0
    for _ in range(100):
        clock.advance(1.0)
        good += 1
        bad += 9
        mon.record("obj", good, bad)
        mon.tick()
    snap = mon.snapshot()["obj"]
    path = snap["last_incident"]
    assert path is not None and os.path.exists(path)
    assert os.path.basename(path).startswith("flight_")
    recs = parse_flight_jsonl(open(path).read())
    kinds = [r["kind"] for r in recs]
    assert "serve.retry" in kinds             # the window leading in
    incident = next(r for r in recs if r["kind"] == "incident")
    assert incident["reason"] == "slo_burn_obj"
    assert incident["objective"] == "obj"


def test_unknown_objective_records_are_ignored():
    mon, clock, _ = make_monitor()
    mon.record("nope", 1, 1)          # a foreign node's objective
    assert "nope" not in mon.snapshot()


# --------------------------------------------------- fleet standard trio


def replica_source(node_id, lag, bound=4, healthy=True):
    def health():
        return healthy, {"role": "replica", "replication_lag": lag,
                         "lag_bound": bound, "breaker_worst": 0}

    return LocalNodeSource(node_id, health=health, role="replica")


def test_fleet_objectives_lag_and_availability_sources():
    clock = FakeClock()
    col = FleetCollector(
        [replica_source("fresh", lag=0),
         replica_source("stale", lag=9),        # past its bound
         replica_source("down", lag=0, healthy=False)],
        clock=clock, flight=FlightRecorder(clock=clock),
        poll_interval_s=0,
    )
    mon = fleet_objectives(col, windows=((10.0, 1.5), (30.0, 1.0)))
    col.slo = mon
    for _ in range(40):
        clock.advance(1.0)
        col.poll()
    snap = mon.snapshot()
    # replication_lag: 1 of 3 replicas over bound → ratio 1/3
    lag = snap["replication_lag"]["windows"][-1]
    assert lag["error_ratio"] == pytest.approx(1 / 3, rel=1e-3)
    # availability: the unhealthy node is the bad third
    avail = snap["availability"]["windows"][-1]
    assert avail["error_ratio"] == pytest.approx(1 / 3, rel=1e-3)
    assert snap["replication_lag"]["alerting"] is True


# ------------------------------------------------------- chaos smoke


def test_chaos_shed_past_deadline_slo_fires_burn_incident(tmp_path):
    """The acceptance smoke: a runtime shedding past its deadline SLO
    fires a burn-rate incident WITH a flight dump — serve terminals →
    collector scrape → SLO windows → flight incident machinery,
    end to end on fake clocks."""
    clock = FakeClock()
    rt, ex, _ = make_runtime(clock=clock)
    flight = FlightRecorder(clock=clock, incident_dir=str(tmp_path),
                            min_dump_interval_s=0.0)
    col = FleetCollector(
        [LocalNodeSource("primary", registries=[rt.stats.registry],
                         health=lambda: (True, {"role": "primary"}))],
        clock=clock, flight=flight, poll_interval_s=0,
    )
    col.slo = fleet_objectives(col, deadline_target=0.9,
                               windows=((10.0, 2.0), (30.0, 1.5)))
    # chaos: every request's deadline expires in the queue — 100% shed
    # against a 10% error budget
    for _ in range(40):
        clock.advance(1.0)
        fut = rt.submit_bfs(1, deadline_s=0.25)
        clock.advance(0.5)            # expire before dispatch
        rt.step(drain=True)           # shed in the admission queue
        assert fut.done()
        col.poll()                    # scrape + SLO tick
    assert rt.stats.shed_deadline == 40
    snap = col.slo.snapshot()["serve_deadline"]
    assert snap["alerting"] is True
    path = snap["last_incident"]
    assert path is not None and os.path.exists(path)
    recs = parse_flight_jsonl(open(path).read())
    incident = next(r for r in recs if r["kind"] == "incident")
    assert incident["reason"] == "slo_burn_serve_deadline"
    rt.close()
