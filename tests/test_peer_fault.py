"""Peer-plane self-healing under injected wire loss: reliable sends with
retry/backoff, the redelivery queue, idempotent re-application, resumable
snapshot transfer, and the TCP transport's bounded connect.

The lossy network is the ``peer.transport.send`` fault point on the
loopback transport — a fired fault IS a dropped message — armed with
deterministic ``at=``/``when=`` schedules so every test replays exactly.
"""

from __future__ import annotations

import time

import pytest

import hypergraphdb_tpu as hg
from hypergraphdb_tpu.fault import global_faults
from hypergraphdb_tpu.peer.peer import HyperGraphPeer
from hypergraphdb_tpu.peer.transport import LoopbackNetwork, TCPPeerInterface
from hypergraphdb_tpu.query import dsl as q


@pytest.fixture
def faults():
    f = global_faults()
    f.reset()
    yield f
    f.reset()
    f.disable()


def make_pair(tmp_path=None):
    net = LoopbackNetwork()
    ga = hg.HyperGraph()
    gb = hg.HyperGraph()
    pa = HyperGraphPeer.loopback(ga, net, identity="peer-a")
    pb = HyperGraphPeer.loopback(gb, net, identity="peer-b")
    for p in (pa, pb):
        # tight knobs: retries settle in milliseconds, not test-minutes
        p.replication.send_backoff_s = 0.001
        p.replication.send_backoff_max_s = 0.005
        p.replication.debounce_s = 0.005
    pa.start()
    pb.start()
    return pa, pb


def stop_pair(pa, pb):
    pa.stop()
    pb.stop()


def wait_for(cond, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.01)
    return False


def replication_push_only(ctx):
    """Fault filter: eat replication INFORMs (pushes/acks), never the
    interest/identity bootstrap."""
    return ctx.get("activity") == "replication"


# ------------------------------------------------------- reliable send


def test_dropped_sends_retry_and_converge(faults):
    pa, pb = make_pair()
    try:
        pb.replication.publish_interest(None)      # everything, please
        assert wait_for(lambda: "peer-b" in pa.replication.peer_interests)
        # drop the first 2 replication sends from A: the reliable-send
        # ladder (3 attempts) delivers on the third
        faults.enable(seed=0)
        faults.arm("peer.transport.send", at={1, 2},
                   when=replication_push_only)
        h = pa.graph.add("retry-me")
        assert pa.replication.flush()
        assert wait_for(
            lambda: q.find_all(pb.graph, q.value("retry-me")) != [])
        m = pa.graph.metrics.counters
        assert m.get("peer.send_retries", 0) >= 2
        assert m.get("peer.send_failures", 0) == 0
        assert int(h) >= 0
    finally:
        stop_pair(pa, pb)


def test_exhausted_sends_redeliver_next_cycle(faults):
    pa, pb = make_pair()
    try:
        pb.replication.publish_interest(None)
        assert wait_for(lambda: "peer-b" in pa.replication.peer_interests)
        # eat the first 4 replication sends: the in-line ladder (3
        # attempts) fails the message into the redelivery queue; the
        # redelivery pass's first attempt (hit 4) also drops, its retry
        # succeeds — converged with no catch-up needed
        faults.enable(seed=0)
        faults.arm("peer.transport.send", at={1, 2, 3, 4},
                   when=replication_push_only)
        pa.graph.add("redeliver-me")
        assert pa.replication.flush()
        assert wait_for(
            lambda: q.find_all(pb.graph, q.value("redeliver-me")) != [])
        m = pa.graph.metrics.counters
        assert m.get("peer.send_failures", 0) >= 1
        assert m.get("peer.redeliveries", 0) >= 1
    finally:
        stop_pair(pa, pb)


def test_duplicate_push_applies_idempotently(faults):
    """Redelivery means a receiver CAN see the same push twice: the
    gid-keyed write-through + SeenMap max-ack make the double apply a
    no-op instead of a duplicate atom."""
    pa, pb = make_pair()
    try:
        pb.replication.publish_interest(None)
        assert wait_for(lambda: "peer-b" in pa.replication.peer_interests)
        pa.graph.add("dup-me")
        assert pa.replication.flush()
        assert wait_for(
            lambda: q.find_all(pb.graph, q.value("dup-me")) != [])
        seen = pb.replication.last_seen.get("peer-a")
        # hand-replay the same logical push (same seq) straight into B
        entry_seq = seen
        raw = pa.replication.log.since(entry_seq - 1, limit=1)
        assert raw
        seq, kind, entry = raw[0]
        from hypergraphdb_tpu.peer import messages as M

        pb.replication.handle("peer-a", M.make_message(
            M.INFORM, "replication",
            {"what": "push", "kind": kind,
             "entry": pa.replication._expand_for_wire(kind, entry),
             "seq": seq},
        ))
        assert pb.replication.flush()
        assert len(q.find_all(pb.graph, q.value("dup-me"))) == 1
        assert pb.replication.last_seen.get("peer-a") == seen
    finally:
        stop_pair(pa, pb)


def test_dropped_catchup_converges_after_retry(faults):
    """An offline-ish peer whose catch-up request hits a lossy wire still
    converges: catch_up() itself rides the reliable-send ladder."""
    pa, pb = make_pair()
    try:
        # no interest: mutations land in A's log only
        pa.graph.add("log-entry-1")
        pa.graph.add("log-entry-2")
        assert pa.replication.flush()
        faults.enable(seed=0)
        faults.arm("peer.transport.send", at={1},
                   when=lambda ctx: ctx.get("activity") == "replication")
        pb.replication.catch_up("peer-a")
        assert wait_for(
            lambda: q.find_all(pb.graph, q.value("log-entry-2")) != [])
        assert pb.graph.metrics.counters.get("peer.catchups", 0) >= 1
    finally:
        stop_pair(pa, pb)


def test_redelivery_preserves_per_peer_order(faults):
    """Once a push to a peer fails its ladder, later pushes line up
    BEHIND it in the per-peer redelivery queue — a redelivered remove
    can never be overtaken by (and then clobber) a newer re-add."""
    pa, pb = make_pair()
    try:
        pb.replication.publish_interest(None)
        assert wait_for(lambda: "peer-b" in pa.replication.peer_interests)
        faults.enable(seed=0)
        # eat EVERY replication send: both pushes must end up queued
        faults.arm("peer.transport.send", prob=1.0,
                   when=replication_push_only)
        pa.replication.redelivery_interval_s = 0.01
        pa.graph.add("ordered-1")
        pa.graph.add("ordered-2")
        # the wire is fully down: flush settles with both messages in
        # ONE per-peer queue, in submission order (or already dropped
        # past the bounded budget — then the queue is empty)
        pa.replication.flush(timeout=30)
        q_ = pa.replication._redelivery.get("peer-b")
        if q_:
            seqs = [m["content"]["seq"] for m, _ in q_]
            assert seqs == sorted(seqs)
        # heal the wire: everything still queued delivers, in order
        faults.disarm("peer.transport.send")
        pa.replication.flush(timeout=30)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            got1 = q.find_all(pb.graph, q.value("ordered-1"))
            got2 = q.find_all(pb.graph, q.value("ordered-2"))
            # order invariant observable from outside: never 2-without-1
            assert not (got2 and not got1)
            if got1 and got2:
                break
            time.sleep(0.01)
        else:
            # both may have been dropped past the budget (full outage):
            # that is the documented gap — catch-up/bootstrap territory
            pb.replication.catch_up("peer-a")
            assert wait_for(
                lambda: q.find_all(pb.graph, q.value("ordered-2")))
    finally:
        stop_pair(pa, pb)


# ------------------------------------------------------- transfer resume


def test_transfer_resumes_after_dropped_chunk(faults):
    pa, pb = make_pair()
    try:
        handles = [pa.graph.add(f"atom-{i}") for i in range(40)]
        pa.graph.add_link(handles[:2], value="a-link")
        # drop the SECOND transfer chunk (page size 8 → several pages);
        # the client watchdog re-requests it and the stream completes
        faults.enable(seed=0)
        faults.arm(
            "peer.transport.send", at={2},
            when=lambda ctx: (ctx.get("activity") == "cact-transfer"
                              and ctx.get("performative") == "inform"),
        )
        n = pb.transfer_graph_from("peer-a", page=8, timeout=30.0,
                                   retry_after_s=0.1)
        assert n >= 41
        for i in range(40):
            assert q.find_all(pb.graph, q.value(f"atom-{i}")) != []
        assert len(q.find_all(pb.graph, q.value("a-link"))) == 1
        assert pb.graph.metrics.counters.get("peer.transfer_resumes",
                                             0) >= 1
        assert pa.graph.metrics.counters.get("peer.transfer_chunks",
                                             0) >= 5
    finally:
        stop_pair(pa, pb)


def test_transfer_resumes_after_dropped_eof_chunk(faults):
    """The nastiest drop: the server sent eof and completed, the client
    never saw it — the resume pull reaches a FRESH server activity, which
    re-snapshots and serves the tail from the requested position."""
    pa, pb = make_pair()
    try:
        for i in range(20):
            pa.graph.add(f"eof-{i}")
        faults.enable(seed=0)
        # with page 64 the whole graph is ONE chunk: dropping inform #1
        # drops the eof itself
        faults.arm(
            "peer.transport.send", at={1},
            when=lambda ctx: (ctx.get("activity") == "cact-transfer"
                              and ctx.get("performative") == "inform"),
        )
        n = pb.transfer_graph_from("peer-a", page=64, timeout=30.0,
                                   retry_after_s=0.1)
        assert n >= 20
        assert q.find_all(pb.graph, q.value("eof-19")) != []
    finally:
        stop_pair(pa, pb)


def test_transfer_stall_fails_typed_after_max_resumes(faults):
    from hypergraphdb_tpu.fault import TransientFault

    pa, pb = make_pair()
    try:
        pa.graph.add("unreachable")
        faults.enable(seed=0)
        faults.arm(  # eat EVERY transfer message, both directions
            "peer.transport.send", prob=1.0,
            when=lambda ctx: ctx.get("activity") == "cact-transfer",
        )
        with pytest.raises(TransientFault):
            pb.transfer_graph_from("peer-a", page=8, timeout=30.0,
                                   retry_after_s=0.05, max_resumes=3)
    finally:
        stop_pair(pa, pb)


# ------------------------------------------------------- TCP transport


def test_tcp_send_to_dead_peer_bounded_and_counted():
    import socket

    iface = TCPPeerInterface("tcp-a", connect_timeout=0.5,
                             send_attempts=2, retry_backoff_s=0.01)
    from hypergraphdb_tpu.utils.metrics import Metrics

    iface.metrics = Metrics()
    iface.start()
    try:
        # reserve a port, then close it: connect gets a fast refusal
        probe = socket.socket()
        probe.bind(("127.0.0.1", 0))
        dead = probe.getsockname()
        probe.close()
        iface._learn("ghost", dead)
        t0 = time.monotonic()
        assert iface.send("ghost", {"x": 1}) is False
        assert time.monotonic() - t0 < 5.0   # bounded, never a hang
        c = iface.metrics.counters
        assert c.get("peer.transport_drops", 0) == 1
        assert c.get("peer.transport_reconnects", 0) == 1
        assert iface.send("nobody", {"x": 1}) is False  # unknown target
    finally:
        iface.stop()
