"""P2P layer tests: two in-process peers, each with its own graph.

The reference's p2p tests need a live XMPP server (``TestCACT.java:17-40``
— SURVEY §4 flags this); here the loopback fabric runs the same scenarios
hermetically, plus one TCP transport smoke test."""

import threading
import time

import pytest

import hypergraphdb_tpu as hg
from hypergraphdb_tpu.peer import HyperGraphPeer, LoopbackNetwork
from hypergraphdb_tpu.peer import transfer
from hypergraphdb_tpu.query import dsl as q
from hypergraphdb_tpu.query import serialize as qser


@pytest.fixture
def two_peers():
    net = LoopbackNetwork()
    g1, g2 = hg.HyperGraph(), hg.HyperGraph()
    p1 = HyperGraphPeer.loopback(g1, net, identity="peer-1")
    p2 = HyperGraphPeer.loopback(g2, net, identity="peer-2")
    p1.start()
    p2.start()
    yield p1, p2
    p1.stop()
    p2.stop()
    g1.close()
    g2.close()


def _wait(cond, timeout=5.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if cond():
            return True
        time.sleep(0.01)
    return False


# ---------------------------------------------------------------- serialization


def test_condition_json_roundtrip():
    cond = q.and_(q.type_("string"), q.or_(q.incident(3), q.arity(2, "gte")),
                  q.not_(q.value("x")))
    js = qser.to_json(cond)
    import json

    js = json.loads(json.dumps(js))  # wire round-trip
    back = qser.from_json(js)
    assert back == cond


def test_predicate_not_serializable():
    from hypergraphdb_tpu.core.errors import QueryError

    with pytest.raises(QueryError):
        qser.to_json(q.predicate(lambda g, h: True))


# ---------------------------------------------------------------- CACT ops


def test_define_and_get_atom(two_peers):
    p1, p2 = two_peers
    a = p1.graph.add("hello")
    b = p1.graph.add("world")
    l = p1.graph.add_link((a, b), value=7)

    handles = p1.define_remote("peer-2", l)
    assert len(handles) == 3
    # remote now answers queries over the transferred closure
    assert p2.graph.get(handles[-1]).targets == tuple(handles[:2])
    assert p2.graph.get(handles[0]) == "hello"

    # get_remote round-trips the atom back by global id
    gid = transfer.global_id("peer-1", int(a))
    local = transfer.lookup_local(p2.graph, gid)
    assert local is not None and p2.graph.get(int(local)) == "hello"


def test_remote_query_streams_pages(two_peers):
    p1, p2 = two_peers
    vals = [f"item-{i}" for i in range(157)]
    p2.graph.add_nodes_bulk(vals)

    rows = p1.run_remote_query("peer-2", q.type_("string"), page=16)
    assert len(rows) == 157
    got = sorted(p2.graph.get(h) for h in rows)
    assert got == sorted(vals)


def test_remote_count_and_incidence(two_peers):
    p1, p2 = two_peers
    x = p2.graph.add("x")
    y = p2.graph.add("y")
    l = p2.graph.add_link((x, y))
    assert p1.count_remote("peer-2", q.type_("string")) == 2
    assert p1.remote_incidence_set("peer-2", int(x)) == [int(l)]


def test_remote_remove(two_peers):
    p1, p2 = two_peers
    a = p2.graph.add("doomed")
    gid = transfer.global_id("peer-2", int(a))
    transfer._atom_map(p2.graph).add_entry(gid.encode(), int(a))
    assert p1.remove_remote("peer-2", gid)
    assert not p2.graph.contains(int(a))


def test_remote_op_failure_surfaces(two_peers):
    p1, _ = two_peers
    # fetching a nonexistent remote atom fails the activity, and the
    # client future surfaces the server's FAILURE reply
    with pytest.raises(Exception, match="not found"):
        p1.get_remote("peer-2", "peer-2:999999")


# ---------------------------------------------------------------- replication


def test_interest_based_replication(two_peers):
    p1, p2 = two_peers
    # peer-2 wants every string atom from peer-1
    p2.replication.publish_interest(q.type_("string"))
    assert _wait(lambda: "peer-2" in p1.replication.peer_interests)

    h = p1.graph.add("replicate-me")
    gid = transfer.global_id("peer-1", int(h))
    assert _wait(lambda: transfer.lookup_local(p2.graph, gid) is not None)
    local = transfer.lookup_local(p2.graph, gid)
    assert p2.graph.get(int(local)) == "replicate-me"

    # non-matching atoms are NOT pushed
    p1.graph.add(12345)
    time.sleep(0.1)
    gid2 = transfer.global_id("peer-1", int(h) + 1)
    assert transfer.lookup_local(p2.graph, gid2) is None


def test_replicated_remove(two_peers):
    p1, p2 = two_peers
    p2.replication.publish_interest(q.type_("string"))
    assert _wait(lambda: "peer-2" in p1.replication.peer_interests)

    h = p1.graph.add("to-be-removed")
    gid = transfer.global_id("peer-1", int(h))
    assert _wait(lambda: transfer.lookup_local(p2.graph, gid) is not None)
    p1.graph.remove(int(h))
    assert _wait(lambda: (
        (lh := transfer.lookup_local(p2.graph, gid)) is None
        or not p2.graph.contains(int(lh))
    ))


def test_offline_catchup(two_peers):
    p1, p2 = two_peers
    # peer-1 writes while peer-2 is "offline" (no interest yet → no push)
    h1 = p1.graph.add("missed-1")
    h2 = p1.graph.add("missed-2")
    assert p1.replication.flush()  # pushes are async off the mutation path
    assert p1.replication.log.head >= 2

    # peer-2 comes online and catches up from peer-1's op log
    p2.replication.catch_up("peer-1")
    gid1 = transfer.global_id("peer-1", int(h1))
    gid2 = transfer.global_id("peer-1", int(h2))
    assert _wait(lambda: transfer.lookup_local(p2.graph, gid1) is not None)
    assert _wait(lambda: transfer.lookup_local(p2.graph, gid2) is not None)
    assert p2.replication.last_seen.get("peer-1") >= 2

    # a second catch-up is a no-op (vector clock advanced)
    before = p2.graph.atom_count()
    p2.replication.catch_up("peer-1")
    time.sleep(0.15)
    assert p2.graph.atom_count() == before


def test_no_echo_loop(two_peers):
    """Mutual interest must not ping-pong atoms forever."""
    p1, p2 = two_peers
    p1.replication.publish_interest(q.type_("string"))
    p2.replication.publish_interest(q.type_("string"))
    assert _wait(lambda: "peer-2" in p1.replication.peer_interests)
    assert _wait(lambda: "peer-1" in p2.replication.peer_interests)

    h = p1.graph.add("ping")
    gid = transfer.global_id("peer-1", int(h))
    assert _wait(lambda: transfer.lookup_local(p2.graph, gid) is not None)
    time.sleep(0.2)  # give any echo time to happen
    # peer-1's log has exactly the one local add; no replicated echoes
    adds = [e for e in p1.replication.log.since(0) if e[1] == "add"]
    assert len(adds) == 1
    # and peer-2 holds exactly one copy
    assert len(q.find_all(p2.graph, q.value("ping"))) == 1


# ---------------------------------------------------------------- TCP transport


def test_tcp_transport_remote_query():
    g1, g2 = hg.HyperGraph(), hg.HyperGraph()
    p1 = HyperGraphPeer.tcp(g1, identity="tcp-1")
    p2 = HyperGraphPeer.tcp(g2, identity="tcp-2")
    p1.start()
    p2.start()
    try:
        p1.interface.connect("tcp-2", p2.interface.addr)
        assert _wait(lambda: "tcp-1" in p2.interface.peers())
        g2.add_nodes_bulk(["a", "b", "c"])
        rows = p1.run_remote_query("tcp-2", q.type_("string"))
        assert len(rows) == 3
    finally:
        p1.stop()
        p2.stop()
        g1.close()
        g2.close()


def test_no_duplicate_on_round_trip(two_peers):
    """An atom pushed A→B and then back B→A must keep ONE identity — the
    return push must update A's original, not mint a duplicate."""
    p1, p2 = two_peers
    a = p1.graph.add("orig")
    p1.define_remote("peer-2", a)
    twin = transfer.lookup_local(
        p2.graph, transfer.global_id("peer-1", int(a))
    )
    assert twin is not None
    p2.define_remote("peer-1", int(twin))
    assert len(q.find_all(p1.graph, q.value("orig"))) == 1


def test_affirm_identity_handshake(two_peers):
    """Peers exchange identities at start (AffirmIdentityBootstrap)."""
    p1, p2 = two_peers
    assert _wait(lambda: "peer-2" in p1.known_peers)
    assert _wait(lambda: "peer-1" in p2.known_peers)
    assert p1.known_peers["peer-2"]["identity"] == "peer-2"


def test_replication_off_mutation_path(two_peers):
    """The event listener must only enqueue: no serialization, log append,
    or network send happens on the mutating thread (VERDICT r2 item 10)."""
    from hypergraphdb_tpu.peer import transfer as tr

    p1, p2 = two_peers
    calls = []
    orig = tr.serialize_atom

    def spy(*a, **k):
        calls.append(threading.current_thread().name)
        return orig(*a, **k)

    tr.serialize_atom = spy
    try:
        p1.graph.add("tracked")
        assert p1.replication.flush()
    finally:
        tr.serialize_atom = orig
    assert calls, "nothing was serialized at all"
    assert all(n == "replication-push" for n in calls), calls


def test_replication_ingest_overhead_bounded():
    """Ingest with replication attached must not collapse: the listener
    only enqueues (lock-free deque append) and the debounced worker defers
    serialization/logging to quiet gaps. The old synchronous push path
    measured 3-4x; the bound below catches a regression to it while
    staying robust to CI timing noise (the event-dispatch machinery itself
    costs ~10-20% under the GIL — the <10%-class target properly belongs
    to the native runtime, where the worker runs on its own core)."""
    def ingest(g, n=1500):
        t0 = time.perf_counter()
        nodes = [g.add(i) for i in range(n)]
        for i in range(0, n - 1, 2):
            g.add_link((nodes[i], nodes[i + 1]), value=i)
        return time.perf_counter() - t0

    ratios = []
    for _ in range(3):
        g_plain = hg.HyperGraph()
        t_plain = ingest(g_plain)
        g_plain.close()
        net = LoopbackNetwork()
        g_repl = hg.HyperGraph()
        p = HyperGraphPeer.loopback(g_repl, net, identity="solo")
        p.start()
        t_repl = ingest(g_repl)
        assert p.replication.flush()
        p.stop()
        g_repl.close()
        ratios.append(t_repl / t_plain)
    assert min(ratios) < 2.0, ratios


def test_contract_net_conversation():
    """FIPA contract-net (ProposalConversation analogue): CFP → bids →
    accept lowest → perform → result; losers are rejected cleanly."""
    from hypergraphdb_tpu.peer.contractnet import ContractNet, TaskParticipant

    class Worker(TaskParticipant):
        COSTS = {"w1": 5, "w2": 2, "w3": 9}

        def bid(self, task):
            me = self.peer.identity
            if task.get("kind") != "count":
                return None
            return {"cost": self.COSTS[me]}

        def perform(self, task):
            return {"by": self.peer.identity,
                    "n": self.peer.graph.atom_count()}

    net = LoopbackNetwork()
    peers = []
    for pid in ("boss", "w1", "w2", "w3"):
        g = hg.HyperGraph()
        p = HyperGraphPeer.loopback(g, net, identity=pid)
        if pid != "boss":
            p.activities.register_type(
                ContractNet.TYPE,
                lambda peer, activity_id=None: Worker(
                    peer, activity_id=activity_id),
            )
        p.start()
        peers.append((p, g))
    boss = peers[0][0]
    try:
        act = boss.activities.initiate(ContractNet(
            boss, task={"kind": "count"},
            participants=["w1", "w2", "w3"],
        ))
        winner, result = act.future.result(timeout=10)
        assert winner == "w2"  # lowest cost bid
        assert result["by"] == "w2"
        assert isinstance(result["n"], int)
    finally:
        for p, g in peers:
            p.stop()
            g.close()


def test_contract_net_all_refuse():
    from hypergraphdb_tpu.peer.contractnet import ContractNet, TaskParticipant

    class Refuser(TaskParticipant):
        def bid(self, task):
            return None

    net = LoopbackNetwork()
    g1, g2 = hg.HyperGraph(), hg.HyperGraph()
    boss = HyperGraphPeer.loopback(g1, net, identity="boss")
    w = HyperGraphPeer.loopback(g2, net, identity="w")
    w.activities.register_type(
        ContractNet.TYPE,
        lambda peer, activity_id=None: Refuser(peer, activity_id=activity_id),
    )
    boss.start()
    w.start()
    try:
        act = boss.activities.initiate(ContractNet(
            boss, task={"kind": "anything"}, participants=["w"]))
        with pytest.raises(Exception, match="refused"):
            act.future.result(timeout=10)
    finally:
        boss.stop()
        w.stop()
        g1.close()
        g2.close()


# ------------------------------------------------------- op-log lifecycle (r5)


def test_oplog_cursor_and_reopen_flat(tmp_path):
    """A durable log with thousands of entries opens by reading only the
    head/floor markers (no payload materialization) and serves `since` by
    index cursor."""
    from hypergraphdb_tpu.peer.replication import OpLog

    g = hg.HyperGraph()
    log = OpLog(g)
    batch = [(log.append_mem("add", {"i": i}), "add", {"i": i})
             for i in range(2000)]
    log.persist_many(batch)
    assert log.head == 2000

    # reopen: head restored from the meta marker, no in-RAM entry list
    log2 = OpLog(g)
    assert log2.head == 2000
    assert log2._mem == []  # durable mode never materializes entries
    tail = log2.since(1995)
    assert [s for s, _, _ in tail] == [1996, 1997, 1998, 1999, 2000]
    assert log2.since(1990, limit=3)[0][0] == 1991

    # truncation drops entries + data records and persists the floor
    dropped = log2.truncate_below(1900)
    assert dropped == 1900
    assert log2.floor == 1900
    assert log2.since(0)[0][0] == 1901
    log3 = OpLog(g)
    assert (log3.head, log3.floor) == (2000, 1900)
    g.close()


def test_ack_driven_truncation(two_peers):
    p1, p2 = two_peers
    p2.replication.publish_interest(None)  # interested in everything
    assert _wait(lambda: "peer-2" in p1.replication.peer_interests)
    p1.replication.truncate_batch = 8
    for i in range(40):
        p1.graph.add(f"t{i}")
    # generous timeouts: under full-suite CPU contention the push→apply→
    # ack round trips legitimately take longer than the defaults
    assert p1.replication.flush(30)
    assert p2.replication.flush(30)
    # p2's acks flowed back and let p1 reclaim acknowledged entries
    assert _wait(
        lambda: p1.replication.peer_acks.get("peer-2", 0) >= 30, timeout=15
    )
    assert _wait(lambda: p1.replication.log.floor > 0, timeout=15)
    # a catch-up from before the floor flags the full-sync path. The
    # rewind must cover BOTH SeenMap views (contiguous map + applied
    # ranges) and is re-applied each poll: a catch-up continuation or
    # gap-repair page still in flight (sent before flush, applied
    # after) can restore the clock via record_applied and turn one
    # rewound catch-up into a no-op — re-rewinding wins once the
    # stragglers run dry, since nothing new is being pushed.
    def rewound_catchup_flags_full_sync():
        seen = p2.replication.last_seen
        with seen._lock:
            seen._map["peer-1"] = 0
            seen._ranges["peer-1"] = [[0, 0]]
        p2.replication.catch_up("peer-1")
        return "peer-1" in p2.replication.needs_full_sync

    assert _wait(rewound_catchup_flags_full_sync, timeout=15)


def test_slow_apply_does_not_stall_dispatch(two_peers):
    """VERDICT r4 weak #7: a slow closure store on the apply path must not
    block unrelated peer messages (applies run off the dispatch thread)."""
    p1, p2 = two_peers
    p2.replication.publish_interest(None)
    assert _wait(lambda: "peer-2" in p1.replication.peer_interests)

    from hypergraphdb_tpu.peer import replication as R

    gate = threading.Event()
    entered = threading.Event()
    orig = R.transfer.store_closure

    def slow_store(g, atoms):
        entered.set()
        gate.wait(5.0)
        return orig(g, atoms)

    try:
        R.transfer.store_closure = slow_store
        p1.graph.add("slow-one")
        assert p1.replication.flush()
        assert _wait(entered.is_set)  # p2's apply worker is stuck in store
        # dispatch thread must still serve other traffic: an interest
        # published by p1 lands in p2 while the apply is blocked
        p1.replication.publish_interest(q.type_("string"))
        assert _wait(lambda: "peer-1" in p2.replication.peer_interests)
    finally:
        gate.set()
        R.transfer.store_closure = orig
    assert p2.replication.flush()
    assert len(q.find_all(p2.graph, q.value("slow-one"))) == 1


def test_catchup_pages_through_large_log(two_peers):
    """Catch-up is served in pages (review r5 finding 4): a rejoining peer
    pulls the whole log through repeated page requests, transparently."""
    p1, p2 = two_peers
    p1.replication.catchup_page = 7  # force many pages for 30 entries
    handles = [p1.graph.add(f"paged-{i}") for i in range(30)]
    assert p1.replication.flush()
    assert p1.replication.log.head >= 30

    p2.replication.catch_up("peer-1")
    gids = [transfer.global_id("peer-1", int(h)) for h in handles]
    assert _wait(
        lambda: all(
            transfer.lookup_local(p2.graph, g) is not None for g in gids
        ),
        timeout=10.0,
    )
    assert p2.replication.last_seen.get("peer-1") >= 30
    assert "peer-1" not in p2.replication.needs_full_sync


# --------------------------------------------------------------------------
# CACT breadth: SyncTypes / ReplaceAtom / GetAtomType / TransferGraph
# (VERDICT r4 missing #2 — ref peer/cact/SyncTypes.java, ReplaceAtom.java,
# GetAtomType.java, TransferGraph.java)
# --------------------------------------------------------------------------

from dataclasses import dataclass as _dataclass


@_dataclass(frozen=True)
class _Person:
    name: str = ""
    age: int = 0


def test_sync_types_installs_record_schema(two_peers):
    p1, p2 = two_peers
    p1.graph.add(_Person("ada", 36))  # auto-binds the record type on A
    tname = p1.graph.typesystem.infer(_Person()).name
    assert tname not in p2.graph.typesystem._by_name

    installed = p1.sync_types_to("peer-2")
    assert tname in installed
    t2 = p2.graph.typesystem.get_type(tname)
    assert tuple(t2.fields) == ("name", "age")


def test_record_atom_pushes_with_schema(two_peers):
    """A record atom defined only on A transfers to B: the wire schema
    installs the type, the value revives as a field dict."""
    p1, p2 = two_peers
    h = p1.graph.add(_Person("grace", 47))
    handles = p1.define_remote("peer-2", h)
    got = p2.graph.get(handles[-1])
    assert got == {"name": "grace", "age": 47} or getattr(
        got, "name", None
    ) == "grace"
    # and B can query it by type
    tname = p1.graph.typesystem.infer(_Person()).name
    th2 = p2.graph.typesystem.handle_of(tname)
    assert handles[-1] in {int(x) for x in q.find_all(
        p2.graph, q.type_(int(th2))
    )}


def test_replace_remote_and_get_type(two_peers):
    p1, p2 = two_peers
    a = p2.graph.add("before")
    gid = transfer.global_id("peer-2", int(a))
    transfer._atom_map(p2.graph).add_entry(gid.encode(), int(a))

    info = p1.get_remote_type("peer-2", gid)
    assert info["type"] == "string"
    assert p1.replace_remote("peer-2", gid, "after")
    assert p2.graph.get(int(a)) == "after"
    # missing gid → replaced False
    assert not p1.replace_remote("peer-2", "peer-2:999999", "x")


def test_transfer_graph_bootstraps_empty_peer(two_peers):
    """The VERDICT done-criterion: B starts empty, TransferGraph +
    catch-up converge it to A's graph INCLUDING a dataclass record type
    defined only on A."""
    p1, p2 = two_peers
    g1 = p1.graph
    nodes = [g1.add(f"n{i}") for i in range(12)]
    links = [
        g1.add_link((nodes[i], nodes[(i + 1) % 12]), value=i)
        for i in range(12)
    ]
    person = g1.add(_Person("ada", 36))
    g1.add_link((person, nodes[0]), value="author-of")
    assert p1.replication.flush()

    before = p2.graph.atom_count()
    stored = p2.transfer_graph_from("peer-1", page=7)
    assert stored >= 12 + 12 + 2

    # structure converged: every A-atom resolves by gid with same topology
    for l in links:
        gid = transfer.gid_of(g1, int(l), "peer-1")
        lb = transfer.lookup_local(p2.graph, gid)
        assert lb is not None
        ta = [transfer.gid_of(g1, t, "peer-1") for t in g1.get_targets(int(l))]
        tb = [
            transfer.gid_of(p2.graph, t, "peer-2")
            for t in p2.graph.get_targets(int(lb))
        ]
        assert ta == tb
    # the record atom arrived with its type installed
    pgid = transfer.gid_of(g1, int(person), "peer-1")
    pb = transfer.lookup_local(p2.graph, pgid)
    got = p2.graph.get(int(pb))
    assert got == {"name": "ada", "age": 36} or getattr(
        got, "name", None
    ) == "ada"

    # post-transfer mutations converge via CATCH-UP ONLY (clock jumped to
    # the server's log head at snapshot time — no full replay)
    seen_at_transfer = p2.replication.last_seen.get("peer-1")
    assert seen_at_transfer >= p1.replication.log.head - 1
    extra = g1.add("late-arrival")
    assert p1.replication.flush()
    p2.replication.catch_up("peer-1")
    egid = transfer.global_id("peer-1", int(extra))
    assert _wait(lambda: transfer.lookup_local(p2.graph, egid) is not None)
    assert "peer-1" not in p2.replication.needs_full_sync


def test_transfer_graph_maps_type_atoms_not_duplicates(two_peers):
    """Transferred TYPE atoms map onto the receiver's own type atoms:
    no duplicate 'string' type atom after a full bootstrap."""
    p1, p2 = two_peers
    p1.graph.add("x")
    p2.transfer_graph_from("peer-1")

    def type_atoms(g, name):
        ts = g.typesystem
        return [
            h for h in g.atoms()
            if ts._type_atom_name(int(h)) == name
        ]

    assert len(type_atoms(p2.graph, "string")) == 1


def test_replace_remote_keeps_record_type_on_schemaless_peer(two_peers):
    """Review r5 finding 1: replacing a record atom on a peer that holds
    only the wire schema must NOT retype it to 'dict'."""
    p1, p2 = two_peers
    h = p1.graph.add(_Person("ada", 36))
    handles = p1.define_remote("peer-2", h)
    tname = p1.graph.typesystem.infer(_Person()).name
    hb = handles[-1]
    assert p2.graph.typesystem.name_of(
        p2.graph.get_type_handle_of(hb)
    ) == tname

    gid = transfer.gid_of(p1.graph, int(h), "peer-1")
    assert p1.replace_remote("peer-2", gid, _Person("ada", 37))
    # still the record type, still findable by it, new value visible
    assert p2.graph.typesystem.name_of(
        p2.graph.get_type_handle_of(hb)
    ) == tname
    th2 = p2.graph.typesystem.handle_of(tname)
    assert int(hb) in {int(x) for x in q.find_all(
        p2.graph, q.type_(int(th2))
    )}
    got = p2.graph.get(int(hb))
    age = got["age"] if isinstance(got, dict) else got.age
    assert age == 37


def test_remote_graph_view(two_peers):
    """RemoteGraphView (PeerHyperNode analogue): CRUD + queries execute on
    the remote peer; nothing is replicated into the local graph."""
    from hypergraphdb_tpu.peer.remote_view import remote_view

    p1, p2 = two_peers
    view = remote_view(p1, "peer-2")

    # create remote nodes + a link between them
    ga = view.add("alpha")
    gb = view.add("beta")
    gl = view.add("bond", targets=(ga, gb))
    assert view.get(ga) == "alpha"
    assert view.get_targets(gl) == [ga, gb]
    assert view.get_type_name(ga) == "string"

    # the atoms live ONLY on peer-2
    before = p1.graph.atom_count()
    assert transfer.lookup_local(p1.graph, ga) is None
    assert p1.graph.atom_count() == before
    assert len(q.find_all(p2.graph, q.value("alpha"))) == 1

    # remote query through the view sees them
    rows = view.find_all(q.value("alpha"))
    assert len(rows) == 1
    assert view.count(q.type_("string")) >= 3
    lb = transfer.lookup_local(p2.graph, gl)
    ab = transfer.lookup_local(p2.graph, ga)
    assert view.incidence(int(ab)) == [int(lb)]

    # replace + remove round-trip
    assert view.replace(ga, "alpha2")
    assert view.get(ga) == "alpha2"
    assert view.remove(gl)
    assert view.incidence(int(ab)) == []

    # a record value defined only locally still round-trips (schema rides)
    view2 = remote_view(p2, "peer-1")
    gp = view2.add(_Person("lin", 29))
    got = view2.get(gp)
    name = got["name"] if isinstance(got, dict) else got.name
    assert name == "lin"
