"""hgplan cardinality-estimator oracle suite.

The estimator's contract splits by honesty bit:

- estimates flagged ``exact=True`` must EQUAL the brute-force oracle
  (``graph.find_all`` counts) — range-window widths under 128-bit
  searchsorted, incidence-set sizes, type counts;
- model estimates (``exact=False``) must stay within a BOUNDED relative
  error of the oracle on both graph families the planner meets: uniform
  (no skew) and hub-heavy (the degree distribution the join engine's
  hub split exists for).

Randomized over seeded rngs — the corpus is reproducible, not
hand-picked. Device-free: the estimator reads host numpy only.
"""

from __future__ import annotations

import numpy as np
import pytest

from hypergraphdb_tpu.plan import CardinalityEstimator
from hypergraphdb_tpu.query import conditions as c


def _uniform_graph(g, rng, n=80):
    """Nodes with int values 0..n-1, links with int values 1000+, arity
    2 spread uniformly — no hubs by construction."""
    nodes = [int(g.add(i)) for i in range(n)]
    links = []
    for i in range(n):
        a, b = rng.choice(n, size=2, replace=False)
        links.append(int(g.add_link([nodes[a], nodes[b]],
                                    value=1000 + i)))
    return nodes, links


def _hub_heavy_graph(g, rng, n=80, n_hubs=3):
    """Same vocabulary, but a few hub nodes soak most of the incidence:
    the degree distribution the mean-based model must not be fooled by."""
    nodes = [int(g.add(i)) for i in range(n)]
    hubs = nodes[:n_hubs]
    links = []
    for i in range(4 * n):
        hub = hubs[int(rng.integers(n_hubs))]
        other = nodes[int(rng.integers(n_hubs, n))]
        links.append(int(g.add_link([hub, other], value=1000 + i)))
    return nodes, links


@pytest.fixture(params=["uniform", "hub_heavy"])
def family(request, graph, rng):
    build = _uniform_graph if request.param == "uniform" else _hub_heavy_graph
    nodes, links = build(graph, rng)
    return request.param, graph, nodes, links


def _oracle_count(g, cond) -> int:
    return sum(1 for _ in g.find_all(cond))


def test_range_window_widths_are_exact(family, rng):
    """Exactness for range windows: every randomized [lo, hi] window's
    estimated width EQUALS the brute-force count, and says so."""
    _, g, _, _ = family
    est = CardinalityEstimator(g)
    for _ in range(25):
        lo, hi = sorted(int(v) for v in rng.integers(-5, 90, size=2))
        for lo_op, hi_op in (("gte", "lte"), ("gt", "lt"),
                             ("gte", "lt"), ("gt", "lte")):
            e = est.range_window(lo=lo, hi=hi, lo_op=lo_op, hi_op=hi_op)
            truth = _oracle_count(g, c.And(c.AtomValue(lo, lo_op),
                                           c.AtomValue(hi, hi_op)))
            assert e.exact, (lo, hi, lo_op, hi_op)
            assert e.rows == truth, (lo, hi, lo_op, hi_op)


def test_range_window_open_bounds_exact(family):
    """Half-open windows (one bound) stay exact too."""
    _, g, _, _ = family
    est = CardinalityEstimator(g)
    for bound, kw in ((20, dict(lo=20)), (20, dict(hi=20)),
                      (1005, dict(lo=1005, lo_op="gt"))):
        e = est.range_window(**kw)
        lo = kw.get("lo")
        hi = kw.get("hi")
        clauses = []
        if lo is not None:
            clauses.append(c.AtomValue(lo, kw.get("lo_op", "gte")))
        if hi is not None:
            clauses.append(c.AtomValue(hi, kw.get("hi_op", "lte")))
        cond = clauses[0] if len(clauses) == 1 else c.And(*clauses)
        assert e.exact
        assert e.rows == _oracle_count(g, cond)


def test_str_windows_exact_only_when_clean(graph):
    """Variable-width kinds: clean keys (≤16 payload bytes, NUL-free)
    keep the exactness claim; an ambiguous column entry drops it — the
    honesty bit is what routes the planner to host."""
    for s in ("ant", "bee", "cat", "dog", "elk"):
        graph.add(s)
    est = CardinalityEstimator(graph)
    e = est.range_window(lo="b", hi="d")
    assert e.exact
    assert e.rows == _oracle_count(
        graph, c.And(c.AtomValue("b", "gte"), c.AtomValue("d", "lte")))

    graph.add("a string well past the sixteen-byte rank prefix")
    est2 = CardinalityEstimator(graph)
    assert not est2.range_window(lo="b", hi="d").exact


def test_incident_counts_are_exact(family):
    """Incidence-set sizes are counts, not estimates."""
    _, g, nodes, _ = family
    est = CardinalityEstimator(g)
    for h in nodes[:10]:
        e = est.incident_count(h)
        assert e.exact
        assert e.rows == _oracle_count(g, c.Incident(h))


def test_type_counts_are_exact(family):
    _, g, nodes, links = family
    est = CardinalityEstimator(g)
    for h in (nodes[0], links[0]):
        th = int(g.get_type_handle_of(h))
        assert est.type_count(th) == _oracle_count(g, c.AtomType(th))


def test_degree_stats_bounded_relative_error(family):
    """Degree stats vs a numpy oracle over the live incidence rows:
    mean within 1% (it is computed, not modelled — the bound guards the
    selection logic), max exact, and the hub count separates the two
    families: zero on uniform, ≥ the planted hubs on hub_heavy."""
    name, g, nodes, links = family
    est = CardinalityEstimator(g)
    stats = est.degree_stats()
    truth = np.asarray([_oracle_count(g, c.Incident(int(h)))
                        for h in g.atoms()], dtype=np.int64)
    assert stats.n == len(truth)
    assert stats.max == truth.max()
    assert abs(stats.mean - truth.mean()) <= 0.01 * max(1.0, truth.mean())
    if name == "uniform":
        assert stats.hubs == 0
    else:
        assert stats.hubs >= 3


def test_coincident_estimate_bounded(family):
    """CoIncident is a model (Σ arity−1 over incident links): an upper
    bound on the truth, within a 4× relative error on both families."""
    _, g, nodes, _ = family
    est = CardinalityEstimator(g)
    for h in nodes[:6]:
        truth = _oracle_count(g, c.CoIncident(h))
        e = est.coincident_count(h)
        assert not e.exact or e.rows == truth
        assert e.rows >= truth
        if truth:
            assert e.rows <= 4.0 * truth


def test_bfs_frontier_model_is_capped_and_inexact(family):
    _, g, nodes, _ = family
    est = CardinalityEstimator(g)
    for hops in (1, 2, 3):
        e = est.bfs_frontier(nodes[0], hops)
        assert not e.exact
        assert 0.0 <= e.rows <= est.n_atoms()


def test_refresh_tracks_mutations(graph):
    """The standalone estimator re-reads the base when the graph's
    mutation counter moves — estimates never describe a stale world."""
    graph.add(1)
    est = CardinalityEstimator(graph)
    assert est.range_window(lo=0, hi=10).rows == 1
    graph.add(2)
    graph.add(3)
    assert est.range_window(lo=0, hi=10).rows == 3
