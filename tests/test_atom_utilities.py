"""Atom utilities (refs, Berge links, relations, subsumption) and
resumable maintenance operations."""

import pytest

import hypergraphdb_tpu as hg
from hypergraphdb_tpu.atom.utilities import (
    HARD,
    SYMBOLIC,
    BergeValue,
    HGAtomRef,
    HGBergeLink,
    RelTypeValue,
    add_rel,
    declare_subsumes,
    define_rel_type,
    install_ref_maintenance,
    load_subsumptions,
)
from hypergraphdb_tpu.query import dsl as q


# ---------------------------------------------------------------- atom refs


def test_hard_ref_pins_referent(graph):
    install_ref_maintenance(graph)
    target = graph.add("pinned")
    ref_holder = graph.add(HGAtomRef(int(target), HARD))
    # removal vetoed while a hard ref exists
    assert graph.remove(int(target)) is False
    assert graph.contains(int(target))
    # dropping the referrer releases the pin
    assert graph.remove(int(ref_holder))
    assert graph.remove(int(target))


def test_symbolic_ref_dangles(graph):
    install_ref_maintenance(graph)
    target = graph.add("temp")
    holder = graph.add(HGAtomRef(int(target), SYMBOLIC))
    assert graph.remove(int(target))  # not pinned
    ref = graph.get(int(holder))
    assert ref.deref(graph) is None  # dangling resolves to None


def test_hard_ref_to_missing_atom_rejected(graph):
    install_ref_maintenance(graph)
    with pytest.raises(hg.HGException):
        graph.add(HGAtomRef(999_999, HARD))


# ---------------------------------------------------------------- berge links


def test_berge_link_head_tail(graph):
    a, b, c, d = (graph.add(x) for x in "abcd")
    bl = HGBergeLink.add(graph, head=[a, b], tail=[c, d], payload="flow")
    assert bl.head == (int(a), int(b))
    assert bl.tail == (int(c), int(d))
    assert bl.payload == "flow"
    # it is an ordinary link to the device plane
    assert graph.arity(bl.handle) == 4
    assert int(bl.handle) in graph.get_incidence_set(a).array().tolist()


# ---------------------------------------------------------------- relations


def test_rel_type_and_instances(graph):
    works_at = define_rel_type(graph, "works-at", 2)
    alice = graph.add("alice")
    acme = graph.add("acme")
    r = add_rel(graph, works_at, int(alice), int(acme))
    assert graph.get_targets(r) == (int(alice), int(acme))
    # arity enforced
    with pytest.raises(hg.HGException):
        add_rel(graph, works_at, int(alice))
    # rel type is found, not duplicated
    assert int(define_rel_type(graph, "works-at", 2)) == int(works_at)


# ---------------------------------------------------------------- subsumption


def test_subsumes_persisted_and_reloaded(graph):
    from dataclasses import dataclass

    @dataclass(frozen=True)
    class Animal:
        name: str = ""

    @dataclass(frozen=True)
    class Dog:
        name: str = ""

    graph.add(Animal("generic"))
    rex = graph.add(Dog("rex"))
    at = graph.typesystem.infer(Animal("x")).name
    dt = graph.typesystem.infer(Dog("x")).name
    declare_subsumes(graph, at, dt)

    # TypePlus(Animal) now reaches Dog atoms
    res = q.find_all(graph, q.type_plus(at)) if hasattr(q, "type_plus") else None
    if res is not None:
        assert int(rex) in res

    # wipe the in-memory subsumption map, reload from persisted links
    graph.typesystem._supertypes.clear()
    assert load_subsumptions(graph) == 1
    assert dt in graph.typesystem.subtypes_closure(at)


# ---------------------------------------------------------------- maintenance


def test_apply_new_indexer_resumable(graph):
    from dataclasses import dataclass

    from hypergraphdb_tpu.indexing.manager import ByPartIndexer, get_index, register
    from hypergraphdb_tpu.maintenance import ApplyNewIndexer, run_pending, schedule

    @dataclass(frozen=True)
    class Person:
        name: str = ""
        age: int = 0

    people = [graph.add(Person(f"p{i}", i)) for i in range(25)]
    th = graph.typesystem.handle_of(graph.typesystem.infer(Person("x")).name)

    # register WITHOUT populating; schedule the offline batch build
    ix = ByPartIndexer("person-by-name", int(th), "name")
    register(graph, ix, populate=False)
    op = ApplyNewIndexer(indexer_name="person-by-name", type_handle=int(th),
                         batch_size=7)
    oph = schedule(graph, op)

    # run TWO batches (bound capture + one real batch), then "crash": the
    # cursor is persisted in the op atom
    cur = graph.get(oph)
    cur = getattr(cur, "value", cur)
    nxt = cur.execute_batch(graph)       # captures the frozen scan bound
    assert nxt.end_bound > 0
    nxt = nxt.execute_batch(graph)       # first real batch
    graph.replace(oph, nxt)
    assert nxt.last_processed >= 0

    # resume to completion
    assert run_pending(graph) == 1
    idx = get_index(graph, "person-by-name")
    pt = graph.typesystem.infer("p3")
    assert int(people[3]) in idx.find(pt.to_key("p3")).array().tolist()
    # and no duplicate entries for already-processed prefix atoms
    assert len(idx.find(pt.to_key("p3"))) == 1


# ---------------------------------------------------------------- metrics


def test_metrics_surface(graph):
    graph.add("m1")
    graph.add("m2")
    graph.snapshot()
    graph.find_all(q.value("m1"))
    snap = graph.metrics.snapshot()
    assert snap["counters"]["graph.mutations"] >= 2
    assert snap["counters"]["query.executed"] >= 1
    assert snap["timings"]["snapshot.pack"]["count"] >= 1
    assert snap["gauges"]["snapshot.num_atoms"] > 0


def test_query_analyze_plan_dump(graph):
    graph.add("x")
    from hypergraphdb_tpu.query.compiler import compile_query

    cq = compile_query(graph, q.and_(q.type_("string"), q.incident(0)))
    text = cq.analyze()
    assert "condition:" in text and "plan:" in text


# ------------------------------------------- review regressions (round 3)


def test_invalid_hard_ref_not_persisted(graph):
    """Validation runs pre-write: a rejected add leaves nothing behind."""
    install_ref_maintenance(graph)
    before = graph.atom_count()
    with pytest.raises(hg.HGException):
        graph.add(HGAtomRef(999_999, HARD))
    assert graph.atom_count() == before


def test_cascade_remove_respects_pin(graph):
    """Cascade removal must not delete a pinned incident link."""
    install_ref_maintenance(graph)
    n = graph.add("node")
    other = graph.add("other")
    l = graph.add_link((n, other), value="pinned-link")
    graph.add(HGAtomRef(int(l), HARD))
    # removing n would cascade to l, which is pinned → whole remove aborts
    with pytest.raises(hg.HGException):
        graph.remove(int(n))
    assert graph.contains(int(l)) and graph.contains(int(n))


def test_replace_maintains_pins(graph):
    install_ref_maintenance(graph)
    t1 = graph.add("t1")
    t2 = graph.add("t2")
    holder = graph.add(HGAtomRef(int(t1), HARD))
    graph.replace(int(holder), HGAtomRef(int(t2), HARD))
    # old pin released, new pin active
    assert graph.remove(int(t1)) is True
    assert graph.remove(int(t2)) is False
    # replacing away the ref releases the pin entirely
    graph.replace(int(holder), "plain")
    assert graph.remove(int(t2)) is True


def test_offline_indexer_covers_subtypes(graph):
    from dataclasses import dataclass

    from hypergraphdb_tpu.indexing.manager import ByPartIndexer, get_index, register
    from hypergraphdb_tpu.maintenance import ApplyNewIndexer, run_pending, schedule

    @dataclass(frozen=True)
    class Animal2:
        name: str = ""

    @dataclass(frozen=True)
    class Dog2:
        name: str = ""

    graph.add(Animal2("generic"))
    rex = graph.add(Dog2("rex"))
    at = graph.typesystem.infer(Animal2("x")).name
    dt = graph.typesystem.infer(Dog2("x")).name
    declare_subsumes(graph, at, dt)

    th = graph.typesystem.handle_of(at)
    register(graph, ByPartIndexer("animal-by-name", int(th), "name"),
             populate=False)
    schedule(graph, ApplyNewIndexer(indexer_name="animal-by-name",
                                    type_handle=int(th), batch_size=50))
    assert run_pending(graph) == 1
    idx = get_index(graph, "animal-by-name")
    kt = graph.typesystem.infer("rex")
    assert int(rex) in idx.find(kt.to_key("rex")).array().tolist()


def test_rel_type_not_confused_by_lookalike(graph):
    from dataclasses import dataclass

    @dataclass(frozen=True)
    class WidgetSpec:
        name: str = ""
        arity: int = 0

    graph.add(WidgetSpec(name="ships-to", arity=2))
    rt = define_rel_type(graph, "ships-to", 2)
    v = graph.get(int(rt))
    v = getattr(v, "value", v)
    assert isinstance(v, RelTypeValue)


def test_run_pending_skips_unregistered_indexer(graph):
    """A pending op whose indexer isn't registered this session defers,
    without aborting other pending operations."""
    from hypergraphdb_tpu.maintenance import ApplyNewIndexer, run_pending, schedule

    schedule(graph, ApplyNewIndexer(indexer_name="ghost-indexer",
                                    type_handle=1, batch_size=10))
    assert run_pending(graph) == 0  # deferred, not crashed
