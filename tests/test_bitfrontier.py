"""Bit-packed frontier engine tests (VERDICT r1 #1: the 10M-atom design).

The packed kernels must agree bit-for-bit with the dense ``ops.frontier``
kernels (which are differential-tested against the host traversal engine),
and the memory plan must prove BASELINE config-4 scale fits a v5e chip.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hypergraphdb_tpu.ops.bitfrontier import (
    bfs_memory_bytes,
    bfs_packed,
    bfs_packed_block,
    pack_bits,
    test_bits as _test_bits,
    unpack_bits,
    unpack_visited,
    valid_word_mask,
)
from hypergraphdb_tpu.ops.frontier import bfs_levels, frontier_edge_counts
from hypergraphdb_tpu.ops.snapshot import CSRSnapshot
from hypergraphdb_tpu.parallel import (
    ShardedSnapshot,
    bfs_packed_sharded,
    make_mesh,
)

from conftest import make_random_hypergraph


def test_pack_unpack_roundtrip():
    r = np.random.default_rng(0)
    bits = r.random((5, 256)) < 0.3
    packed = pack_bits(jnp.asarray(bits))
    assert packed.dtype == jnp.uint32 and packed.shape == (5, 8)
    np.testing.assert_array_equal(np.asarray(unpack_bits(packed)), bits)


def test_test_bits_gather():
    r = np.random.default_rng(1)
    bits = r.random(320) < 0.5
    packed = pack_bits(jnp.asarray(bits[None, :]))
    idx = jnp.asarray(r.integers(0, 320, size=64), dtype=jnp.int32)
    got = np.asarray(_test_bits(packed, idx))[0]
    np.testing.assert_array_equal(got, bits[np.asarray(idx)])


def test_valid_word_mask_clears_tail():
    m = valid_word_mask(70, 3)  # bits 0..69 set, 70..95 clear
    bits = np.asarray(unpack_bits(jnp.asarray(m[None, :])))[0]
    assert bits[:70].all() and not bits[70:].any()


def test_packed_bfs_matches_dense(graph):
    nodes, _ = make_random_hypergraph(graph, n_nodes=200, n_links=600, seed=7)
    snap = CSRSnapshot.pack(graph)
    r = np.random.default_rng(7)
    seeds = np.asarray(
        [int(nodes[i]) for i in r.integers(0, 200, size=33)], dtype=np.int32
    )
    lv_d, vis_d = bfs_levels(snap.device, jnp.asarray(seeds), 3)
    cnt_d = frontier_edge_counts(snap.device, jnp.asarray(seeds), 3)

    # odd K forces block padding; small edge_chunk forces multi-chunk scans
    vis_p, cnt_p, lv_p = bfs_packed(
        snap, seeds, 3, k_block=8, edge_chunk=256, with_levels=True
    )
    np.testing.assert_array_equal(
        unpack_visited(vis_p, snap.num_atoms + 1), np.asarray(vis_d)
    )
    np.testing.assert_array_equal(lv_p.astype(np.int32), np.asarray(lv_d))
    np.testing.assert_array_equal(cnt_p, np.asarray(cnt_d, dtype=np.int64))


def test_packed_bfs_isolated_seed(graph):
    h = graph.add("loner")
    graph.add("other")
    snap = CSRSnapshot.pack(graph)
    vis, cnt, _ = bfs_packed(snap, np.asarray([int(h)]), 4)
    dense = unpack_visited(vis, snap.num_atoms + 1)[0]
    assert dense.sum() == 1 and dense[int(h)]
    assert cnt[0] == 0


def test_packed_sharded_counts_match(graph):
    assert len(jax.devices()) == 8
    nodes, _ = make_random_hypergraph(graph, n_nodes=150, n_links=500, seed=9)
    snap = CSRSnapshot.pack(graph)
    sdev = ShardedSnapshot.from_host(snap, make_mesh(), edge_chunk=512)
    seeds = jnp.asarray([int(nodes[i]) for i in (0, 3, 77)], dtype=jnp.int32)
    vis_p, cnt_p, _ = bfs_packed_sharded(sdev, seeds, 3)
    cnt_d = frontier_edge_counts(snap.device, seeds, 3)
    np.testing.assert_array_equal(np.asarray(cnt_p), np.asarray(cnt_d))
    # packed visited in the row-sharded layout matches the dense reference
    _, vis_d = bfs_levels(snap.device, seeds, 3)
    got = unpack_visited(np.asarray(vis_p), snap.num_atoms + 1)
    np.testing.assert_array_equal(got, np.asarray(vis_d))


def test_config4_memory_fits_v5e_hbm():
    """BASELINE config 4: K=1024 seeds (256-blocks), N=10M, E=50M, v5e-4.

    Round 1's dense design needed >60 GB/device; the packed plan must fit
    comfortably under a v5e chip's 16 GB HBM."""
    plan = bfs_memory_bytes(
        n_atoms=10_000_000, e_inc=50_000_000, e_tgt=50_000_000,
        k_block=256, n_dev=4,
    )
    assert plan["total"] < 6 * 2**30, plan
    # single-chip config 3 scale must also fit
    plan1 = bfs_memory_bytes(
        n_atoms=10_000_000, e_inc=50_000_000, e_tgt=50_000_000,
        k_block=128, n_dev=1,
    )
    assert plan1["total"] < 8 * 2**30, plan1
