"""A durable database must not forget its self-knowledge on reopen
(VERDICT r2 item 4): indexer registrations, the subtype hierarchy, and the
replication op log all restore from the store at open — mirroring the
reference's ``HGIndexManager.loadIndexers`` (``HGIndexManager.java:62-215``),
class↔type index recovery (``HGTypeSystem.java:97-98``), and persisted
versioned log (``peer/log/Log.java:34``)."""

import pytest

import hypergraphdb_tpu as hg

pytest.importorskip("hypergraphdb_tpu.storage.native")


def _open(loc):
    return hg.HyperGraph(hg.HGConfiguration(store_backend="native",
                                            location=loc))


def test_indexer_registration_survives_reopen(tmp_path):
    from dataclasses import dataclass

    from hypergraphdb_tpu.indexing.manager import (
        ByPartIndexer,
        get_index,
        indexers_of,
        register,
    )

    @dataclass(frozen=True)
    class Person:
        name: str = ""
        age: int = 0

    loc = str(tmp_path / "db")
    g = _open(loc)
    th = int(g.typesystem.handle_of(g.typesystem.infer(Person()).name))
    register(g, ByPartIndexer("person-by-name", th, "name"))
    g.add(Person("ada", 36))
    g.close()

    g2 = _open(loc)
    # session 2 restored the registration at open...
    restored = indexers_of(g2, th)
    assert [ix.name for ix in restored] == ["person-by-name"]
    # ...the index answers queries...
    pt = g2.typesystem.infer("ada")
    hits = get_index(g2, "person-by-name").find(pt.to_key("ada")).array()
    assert len(hits) == 1
    # binding the class (first use, as any app does) makes values load
    g2.typesystem.infer(Person())
    assert g2.get(int(hits[0])).name == "ada"
    # ...and NEW atoms keep being indexed without re-registration
    g2.add(Person("bob", 9))
    hits_bob = get_index(g2, "person-by-name").find(pt.to_key("bob")).array()
    assert len(hits_bob) == 1
    g2.close()


def test_unregister_survives_reopen(tmp_path):
    from dataclasses import dataclass

    from hypergraphdb_tpu.indexing.manager import (
        ByPartIndexer,
        indexers_of,
        register,
        unregister,
    )

    @dataclass(frozen=True)
    class Thing:
        tag: str = ""

    loc = str(tmp_path / "db")
    g = _open(loc)
    th = int(g.typesystem.handle_of(g.typesystem.infer(Thing()).name))
    register(g, ByPartIndexer("thing-by-tag", th, "tag"))
    unregister(g, "thing-by-tag")
    g.close()

    g2 = _open(loc)
    assert indexers_of(g2, th) == []
    g2.close()


def test_subtype_hierarchy_survives_reopen(tmp_path):
    from hypergraphdb_tpu.atom.utilities import declare_subsumes
    from hypergraphdb_tpu.query import dsl as q

    loc = str(tmp_path / "db")
    g = _open(loc)
    # animal subsumes dog; both are plain (string-named primitive) types
    # pre-registered as type atoms here
    g.typesystem.register(_named_type("animal"))
    g.typesystem.register(_named_type("dog"))
    declare_subsumes(g, "animal", "dog")
    d = g.add_node("rex", type="dog")
    g.close()

    g2 = _open(loc)
    assert "dog" in g2.typesystem.subtypes_closure("animal")
    # TypePlus closure intact: the subtype's atoms answer
    res = q.find_all(g2, q.type_plus("animal"))
    assert int(d) in res
    g2.close()


def _named_type(name):
    from hypergraphdb_tpu.types.primitive import StringType

    class T(StringType):
        pass

    t = T()
    t.name = name
    return t


def test_oplog_and_vector_clock_survive_reopen(tmp_path):
    """Catch-up must work after the SERVING peer restarts: its op log (and
    the client's vector clock) restore from the store."""
    import time

    from hypergraphdb_tpu.peer import HyperGraphPeer, LoopbackNetwork

    loc1 = str(tmp_path / "p1")
    loc2 = str(tmp_path / "p2")

    net = LoopbackNetwork()
    g1 = _open(loc1)
    p1 = HyperGraphPeer.loopback(g1, net, identity="peer-1")
    p1.start()
    a = g1.add("replicated-1")
    b = g1.add("replicated-2")
    assert p1.replication.flush()  # pushes are async off the mutation path
    head_before = p1.replication.log.head
    assert head_before >= 2
    p1.stop()
    g1.close()

    # restart peer-1 on the same store: the log must still be there
    net2 = LoopbackNetwork()
    g1b = _open(loc1)
    p1b = HyperGraphPeer.loopback(g1b, net2, identity="peer-1")
    p1b.start()
    assert p1b.replication.log.head == head_before

    # a fresh peer-2 catches up from the RESTARTED peer-1
    g2 = _open(loc2)
    p2 = HyperGraphPeer.loopback(g2, net2, identity="peer-2")
    p2.start()
    p2.replication.catch_up("peer-1")
    t0 = time.monotonic()
    while time.monotonic() - t0 < 5.0:
        if p2.replication.last_seen.get("peer-1") >= head_before:
            break
        time.sleep(0.01)
    assert p2.replication.last_seen.get("peer-1") >= head_before
    from hypergraphdb_tpu.query import dsl as q

    assert q.find_all(g2, q.value("replicated-1"))
    p2.stop()
    g2.close()

    # restart peer-2: its vector clock survived, so a new catch-up asks
    # only for entries beyond what it already applied
    g2b = _open(loc2)
    p2b = HyperGraphPeer.loopback(g2b, net2, identity="peer-2b")
    assert p2b.replication.last_seen.get("peer-1") >= head_before
    g2b.close()
    p1b.stop()
    g1b.close()
