"""Regression tests for code-review findings (round 1)."""

from dataclasses import dataclass

import numpy as np

from hypergraphdb_tpu.query import dsl as q


@dataclass
class MutablePerson:  # eq=True, frozen=False → __hash__ is None
    name: str = ""
    age: int = 0


def test_query_on_unhashable_record_value(graph):
    """simplify() must dedupe conditions whose payload is unhashable."""
    h = graph.add(MutablePerson("ada", 36))
    graph.add(MutablePerson("bob", 9))
    # duplicate clause forces the dedupe path in And
    res = q.find_all(
        graph, q.and_(q.eq(MutablePerson("ada", 36)), q.eq(MutablePerson("ada", 36)))
    )
    assert res == [int(h)]
    # Or branch too
    res = q.find_all(
        graph, q.or_(q.eq(MutablePerson("ada", 36)), q.eq(MutablePerson("ada", 36)))
    )
    assert res == [int(h)]


def test_parallel_union_sees_tx_writes(graph):
    """Parallel Or-branches must observe the calling tx's uncommitted writes."""
    graph.config.query.parallel_or = True
    pre = graph.add("pre-existing")

    def inside():
        fresh = graph.add("fresh-in-tx")
        res = q.find_all(graph, q.or_(q.value("pre-existing"),
                                      q.value("fresh-in-tx")))
        assert int(pre) in res
        assert int(fresh) in res, "parallel union lost the caller's tx context"
        return fresh

    graph.txman.transact(inside)


def test_device_value_rank_not_truncated(graph):
    """value_rank must survive device transfer with its HIGH 32 bits intact."""
    a = graph.add("aaaa-low")
    b = graph.add("zzzz-high")
    snap = graph.snapshot()
    dev = snap.device
    hi = np.asarray(dev.value_rank_hi)
    lo = np.asarray(dev.value_rank_lo)
    full = (hi.astype(np.uint64) << np.uint64(32)) | lo.astype(np.uint64)
    np.testing.assert_array_equal(full, snap.value_rank)
    # ordering is dominated by the high word for string keys
    ra, rb = snap.value_rank[int(a)], snap.value_rank[int(b)]
    assert (ra < rb) == (
        (hi[int(a)], lo[int(a)]) < (hi[int(b)], lo[int(b)])
    )


# -------------------------------------------------- round-2 ADVICE findings


def test_remove_veto_runs_inside_tx(graph):
    """The remove-request veto must execute inside the removal transaction
    (a listener guarding pinned atoms needs transactional state)."""
    from hypergraphdb_tpu.core import events as ev

    a = graph.add("pinned")
    seen_in_tx = []

    def veto(g, event):
        seen_in_tx.append(g.txman.current() is not None)
        return ev.HGListener.CANCEL

    graph.events.add_listener(ev.HGAtomRemoveRequestEvent, veto)
    assert graph.remove(a) is False
    assert graph.contains(a)
    assert seen_in_tx == [True]


def test_bulk_import_invalidates_readers(graph):
    """A transaction that read 'value absent' before a bulk_import of that
    value must FAIL validation, not commit on stale reads."""
    from hypergraphdb_tpu.query import dsl as hg

    import threading

    tx = graph.txman.begin()
    assert hg.find_all(graph, hg.value(123456)) == []  # read: absent
    # the bulk load happens on ANOTHER thread (same-thread bulk_import
    # correctly joins the open transaction instead)
    t = threading.Thread(
        target=lambda: graph.bulk_import(values=[123456, 123457])
    )
    t.start()
    t.join()
    graph.add("marker")  # a write so commit validation runs

    import pytest as _pytest
    from hypergraphdb_tpu.core.errors import TransactionConflict
    with _pytest.raises(TransactionConflict):
        graph.txman.commit(tx)


def test_import_graph_rolls_back_on_failure(graph, tmp_path):
    """A corrupt record mid-file must leave the graph unchanged."""
    import json

    from hypergraphdb_tpu.ops.checkpoint import export_graph, import_graph

    src_atoms = [graph.add(f"v{i}") for i in range(5)]
    path = str(tmp_path / "dump.jsonl")
    export_graph(graph, path)
    # corrupt the last record: link referencing an unknown original handle
    with open(path, "a", encoding="utf-8") as f:
        f.write(json.dumps({
            "h": 999999, "type": "int", "v": None, "link": True,
            "t": [424242],
        }) + "\n")

    from hypergraphdb_tpu import HyperGraph
    dst = HyperGraph()
    before = sorted(dst.atoms())
    import pytest as _pytest
    with _pytest.raises(Exception):
        import_graph(dst, path)
    assert sorted(dst.atoms()) == before  # nothing leaked
    dst.close()


def test_removed_unreplicated_atom_mints_no_gid():
    """Removing an atom that never crossed the replication boundary must
    not mint a gid nor push a retraction."""
    import hypergraphdb_tpu as hgdb
    from hypergraphdb_tpu.peer import HyperGraphPeer, LoopbackNetwork, transfer

    net = LoopbackNetwork()
    g = hgdb.HyperGraph()
    # the atom predates the peer: it never crossed the replication boundary
    a = g.add("local-only")
    p1 = HyperGraphPeer.loopback(g, net, identity="p1")
    p1.start()
    try:
        rep = p1.replication
        assert transfer.existing_gid(g, int(a)) is None
        n_log = rep.log.head
        g.remove(a)
        assert rep.flush()  # drain the async push worker before asserting
        assert transfer.existing_gid(g, int(a)) is None  # no mint
        removes = [
            e for e in rep.log.since(n_log) if e[1] == "remove"
        ]
        assert removes == []
    finally:
        p1.stop()
        g.close()


def test_keep_incident_links_rewrite_fires_replaced_event(graph):
    """remove(keep_incident_links=True) rewrites incident links' target
    tuples in place; snapshot overlays must be told (via replaced events)
    or columnar Arity/PositionedIncident filters serve stale answers."""
    import numpy as np

    from hypergraphdb_tpu.query import conditions as c
    from hypergraphdb_tpu.query.compiler import filter_predicates

    a, b, x = graph.add("a"), graph.add("b"), graph.add("x")
    l = graph.add_link((a, b, x), value="rel")
    graph.enable_incremental(headroom=10.0, background=False)
    graph.snapshot()
    graph.remove(x, keep_incident_links=True)  # l becomes (a, b)

    arr = np.asarray([int(l)], dtype=np.int64)
    got3 = filter_predicates(graph, arr, [c.Arity(3, "eq")])
    got2 = filter_predicates(graph, arr, [c.Arity(2, "eq")])
    assert got3.tolist() == []          # stale column answer would keep l
    assert got2.tolist() == [int(l)]


def test_bulk_import_invalidates_user_index_readers(graph):
    """The user-index version bump must use the STORAGE cell name readers
    note — a raw-name bump is a no-op (review r4)."""
    import threading

    from hypergraphdb_tpu.core.errors import TransactionConflict
    from hypergraphdb_tpu.indexing.manager import (
        DirectValueIndexer,
        get_index,
        register,
    )

    th = int(graph.typesystem.handle_of("int"))
    register(graph, DirectValueIndexer("myidx", th))
    tx = graph.txman.begin()
    key = graph.typesystem.infer(777).to_key(777)
    assert get_index(graph, "myidx").find(key).array().tolist() == []
    t = threading.Thread(target=lambda: graph.bulk_import(values=[777]))
    t.start()
    t.join()
    graph.add("marker")
    import pytest as _pytest
    with _pytest.raises(TransactionConflict):
        graph.txman.commit(tx)
