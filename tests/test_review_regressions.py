"""Regression tests for code-review findings (round 1)."""

from dataclasses import dataclass

import numpy as np

from hypergraphdb_tpu.query import dsl as q


@dataclass
class MutablePerson:  # eq=True, frozen=False → __hash__ is None
    name: str = ""
    age: int = 0


def test_query_on_unhashable_record_value(graph):
    """simplify() must dedupe conditions whose payload is unhashable."""
    h = graph.add(MutablePerson("ada", 36))
    graph.add(MutablePerson("bob", 9))
    # duplicate clause forces the dedupe path in And
    res = q.find_all(
        graph, q.and_(q.eq(MutablePerson("ada", 36)), q.eq(MutablePerson("ada", 36)))
    )
    assert res == [int(h)]
    # Or branch too
    res = q.find_all(
        graph, q.or_(q.eq(MutablePerson("ada", 36)), q.eq(MutablePerson("ada", 36)))
    )
    assert res == [int(h)]


def test_parallel_union_sees_tx_writes(graph):
    """Parallel Or-branches must observe the calling tx's uncommitted writes."""
    graph.config.query.parallel_or = True
    pre = graph.add("pre-existing")

    def inside():
        fresh = graph.add("fresh-in-tx")
        res = q.find_all(graph, q.or_(q.value("pre-existing"),
                                      q.value("fresh-in-tx")))
        assert int(pre) in res
        assert int(fresh) in res, "parallel union lost the caller's tx context"
        return fresh

    graph.txman.transact(inside)


def test_device_value_rank_not_truncated(graph):
    """value_rank must survive device transfer with its HIGH 32 bits intact."""
    a = graph.add("aaaa-low")
    b = graph.add("zzzz-high")
    snap = graph.snapshot()
    dev = snap.device
    hi = np.asarray(dev.value_rank_hi)
    lo = np.asarray(dev.value_rank_lo)
    full = (hi.astype(np.uint64) << np.uint64(32)) | lo.astype(np.uint64)
    np.testing.assert_array_equal(full, snap.value_rank)
    # ordering is dominated by the high word for string keys
    ra, rb = snap.value_rank[int(a)], snap.value_rank[int(b)]
    assert (ra < rb) == (
        (hi[int(a)], lo[int(a)]) < (hi[int(b)], lo[int(b)])
    )
