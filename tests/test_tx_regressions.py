"""Regression tests for transaction/cache interaction bugs found in review."""

import pytest

from hypergraphdb_tpu import HyperGraph, NotFoundError
from hypergraphdb_tpu.core import events as ev


def test_aborted_tx_does_not_pollute_atom_cache(graph: HyperGraph):
    holder = {}

    def work():
        h = holder["h"] = graph.add("hello")
        assert graph.get(h) == "hello"  # must not land in shared cache
        raise RuntimeError("abort")

    with pytest.raises(RuntimeError):
        graph.txman.transact(work)
    h = holder["h"]
    assert not graph.contains(h)
    with pytest.raises(NotFoundError):
        graph.get(h)


def test_keep_incident_links_invalidates_link_cache(graph: HyperGraph):
    a, b = graph.add("a"), graph.add("b")
    l = graph.add_link((a, b))
    assert graph.get(l).targets == (a, b)  # warm the cache
    graph.remove(a, keep_incident_links=True)
    assert graph.get(l).targets == (b,)


def test_events_deferred_until_commit(graph: HyperGraph):
    seen = []
    graph.events.add_listener(
        ev.HGAtomAddedEvent, lambda g, e: seen.append(e.handle) or 0
    )

    def work():
        graph.add("ghost")
        assert seen == []  # not yet committed
        raise RuntimeError("abort")

    with pytest.raises(RuntimeError):
        graph.txman.transact(work)
    assert seen == []  # aborted adds never reach listeners

    h = graph.txman.transact(lambda: graph.add("real"))
    assert seen == [h]


def test_mutation_counter_not_bumped_on_abort(graph: HyperGraph):
    before = graph._mutations

    def work():
        graph.add("ghost")
        raise RuntimeError("abort")

    with pytest.raises(RuntimeError):
        graph.txman.transact(work)
    assert graph._mutations == before


def test_atoms_sees_parent_tx_writes(graph: HyperGraph):
    outer = graph.txman.begin()
    h = graph.add("outer-atom")
    inner = graph.txman.begin()
    assert h in set(graph.atoms())  # read-your-writes through the chain
    graph.txman.abort(inner)
    graph.txman.abort(outer)


def test_scan_keys_consistent_after_tx_removal(graph: HyperGraph):
    idx = graph.store.get_index("sk")
    idx.add_entry(b"only", 7)
    tx = graph.txman.begin()
    idx2 = graph.store.get_index("sk")
    idx2.remove_entry(b"only", 7)
    assert len(idx2.find(b"only")) == 0
    assert b"only" not in list(idx2.scan_keys())
    graph.txman.abort(tx)
    assert b"only" in list(graph.store.get_index("sk").scan_keys())


def test_environment_does_not_mutate_caller_config(tmp_path):
    from hypergraphdb_tpu import HGConfiguration
    from hypergraphdb_tpu.core import environment

    cfg = HGConfiguration()
    g = environment.get(str(tmp_path / "db"), cfg)
    assert cfg.location is None
    assert cfg.store_backend == "memory"
    environment.close(str(tmp_path / "db"))


def test_bulk_import_preserves_open_snapshots(graph: HyperGraph):
    """A transaction begun BEFORE a concurrent bulk_import keeps its
    begin-time view of every cell the load touches (ADVICE r4: bulk_import
    bumped versions but captured no MVCC pre-images)."""
    import threading

    target = graph.add("target")
    l0 = graph.add_link((target,), value="pre")
    from hypergraphdb_tpu.core.graph import IDX_BY_VALUE

    tx = graph.txman.begin()
    # warm the read-set/snapshot on the cells the bulk load will touch
    pre_inc = graph.get_incidence_set(target).array().tolist()
    pre_vals = graph.store.get_index(IDX_BY_VALUE, create=True)

    done = threading.Event()

    def load():
        graph.bulk_import(
            values=[f"bulk{i}" for i in range(8)],
            target_lists=[[int(target)]] * 8,
        )
        done.set()

    t = threading.Thread(target=load)
    t.start()
    t.join()
    assert done.is_set()
    # snapshot reads must still see the pre-load state
    assert graph.get_incidence_set(target).array().tolist() == pre_inc == [int(l0)]
    th = graph._resolve_type_handle("bulk0", None)
    key = graph.typesystem.get_type(int(th)).to_key("bulk0")
    assert len(pre_vals.find(key)) == 0  # bulk value keys invisible in-tx
    graph.txman.abort(tx)
    # outside the snapshot the bulk atoms are visible
    assert len(graph.get_incidence_set(target)) == 9


def test_bulk_import_abort_keeps_snapshot_isolation(graph: HyperGraph):
    """A bulk_import that fails mid-batch must still serve open snapshots
    their begin-time view of the half-applied cells AND doom transactions
    that read them (the error path keeps pre-images and bumps versions)."""
    import threading

    target = graph.add("t")
    l0 = graph.add_link((target,), value="pre")
    tx = graph.txman.begin()
    pre = graph.get_incidence_set(target).array().tolist()

    err = {}

    def load():
        try:
            # an unparseable target mid-batch raises after some direct
            # backend writes already landed
            graph.bulk_import(
                values=["a", "b", "c", "d"],
                target_lists=[[int(target)], [int(target)],
                              ["not-a-handle"], [int(target)]],
            )
        except Exception as e:  # noqa: BLE001
            err["e"] = e

    t = threading.Thread(target=load)
    t.start()
    t.join()
    assert "e" in err  # the batch did fail
    # snapshot still sees the begin-time incidence
    assert graph.get_incidence_set(target).array().tolist() == pre == [int(l0)]
    # and committing on top of that read must conflict, not succeed
    graph.add("unrelated-write")
    import pytest

    from hypergraphdb_tpu import TransactionConflict

    with pytest.raises(TransactionConflict):
        graph.txman.commit(tx)
