"""Behavior pins for the concurrency fixes the hgconc sweep forced.

Every HG7xx/HG8xx finding on the real tree was FIXED (not baselined) —
mostly by restructuring hot read paths to snapshot-under-lock /
sort-outside, and by guarding the memory-watch worker loop. These tests
pin the observable contracts of the restructured code so a future edit
can't quietly revert a fix while the analyzer happens to stay green.
"""

import threading
import time

from hypergraphdb_tpu.fault.registry import FaultRegistry
from hypergraphdb_tpu.obs.registry import Histogram, Registry
from hypergraphdb_tpu.utils.cache import MemoryWarningSystem


# ------------------------------------------------- snapshot-then-sort reads


def test_histogram_windowed_percentiles_stay_consistent_under_writes():
    """percentiles() snapshots the window under the lock and sorts
    OUTSIDE it — the result must still be one consistent cut (monotone
    across the requested ps) even while another thread observes."""
    h = Histogram("lat", window=512)
    for v in (5.0, 1.0, 3.0, 2.0, 4.0):
        h.observe(v)
    stop = threading.Event()

    def writer():
        v = 0.0
        while not stop.is_set():
            v += 1.0
            h.observe(v % 100.0)

    t = threading.Thread(target=writer, daemon=True)
    t.start()
    try:
        for _ in range(200):
            p25, p50, p99 = h.percentiles((0.25, 0.5, 0.99))
            assert p25 is not None
            assert p25 <= p50 <= p99, "percentile cut tore across updates"
    finally:
        stop.set()
        t.join(timeout=5)


def test_histogram_windowed_percentiles_match_oracle():
    h = Histogram("lat", window=128)
    vals = [float(v) for v in (9, 1, 8, 2, 7, 3, 6, 4, 5)]
    for v in vals:
        h.observe(v)
    lat = sorted(vals)
    got = h.percentiles((0.0, 0.5, 1.0))
    assert got == [lat[0], lat[len(lat) // 2], lat[-1]]


def test_registry_names_and_instruments_sorted_and_aligned():
    reg = Registry("t")
    reg.counter("zeta")
    reg.gauge("alpha")
    reg.histogram("mid")
    assert reg.names() == ["alpha", "mid", "zeta"]
    assert [m.name for m in reg.instruments()] == ["alpha", "mid", "zeta"]


def test_fault_registry_armed_is_sorted():
    f = FaultRegistry()
    f.arm("z.point", times=1)
    f.arm("a.point", times=1)
    f.arm("m.point", times=1)
    assert f.armed() == ["a.point", "m.point", "z.point"]


def test_perf_sentinel_health_summary_is_a_pure_sorted_read():
    from hypergraphdb_tpu.obs.perf import PerfSentinel

    sen = PerfSentinel(baseline={"lanes": {"write": {}, "read": {}}})
    out = sen.health_summary()
    assert set(out) == {"violating", "watched", "alerts_total", "skew",
                        "profile_open"}
    assert out["violating"] == []
    assert out["watched"] == sorted(out["watched"])
    assert out["alerts_total"] == 0
    # a pure read: calling it again changes nothing
    assert sen.health_summary() == out


# ------------------------------------------------- guarded worker loop


def test_memwatch_thread_survives_a_raising_sweep():
    """The memwatch loop guards check_now(): one bad sweep must not kill
    the watcher (the HG805 fix in utils/cache.py)."""
    ws = MemoryWarningSystem(threshold_bytes=1, interval_s=0.01)
    sweeps = []
    twice = threading.Event()

    def boom():
        sweeps.append(1)
        if len(sweeps) >= 2:
            twice.set()
        raise RuntimeError("sweep bug")

    ws.check_now = boom
    ws.start()
    try:
        assert twice.wait(5.0), "watch thread died after the first raise"
        assert ws._thread.is_alive()
    finally:
        ws.stop()
    assert len(sweeps) >= 2


def test_memwatch_stop_joins_the_thread():
    ws = MemoryWarningSystem(threshold_bytes=0, interval_s=0.01)
    ws.start()
    t = ws._thread
    time.sleep(0.03)
    ws.stop()
    assert ws._thread is None
    assert not t.is_alive()
