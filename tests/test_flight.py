"""Flight recorder: bounded healthy-path ring, incident dumps, and the
acceptance sequence — an injected serve fault tripping the breaker dumps
a window containing the fault firings, the retry ladder, and the breaker
transition, in order."""

from __future__ import annotations

import pytest

from hypergraphdb_tpu.fault import global_faults
from hypergraphdb_tpu.obs.flight import (
    FlightRecorder,
    global_flight,
    parse_flight_jsonl,
)
from hypergraphdb_tpu.serve import ServeConfig, ServeRuntime
from tests.test_serve_runtime import FakeClock, FakeExecutor


@pytest.fixture
def flight(tmp_path):
    """The process recorder, pointed at a tmp incident dir and restored
    clean (the global instance is what the wired sites bind)."""
    fl = global_flight()
    fl.reset()
    prev_dir, prev_interval = fl.incident_dir, fl.min_dump_interval_s
    fl.configure(incident_dir=str(tmp_path), min_dump_interval_s=0.0)
    try:
        yield fl
    finally:
        fl.reset()
        fl.configure(incident_dir=prev_dir,
                     min_dump_interval_s=prev_interval)
        fl.incident_dir = prev_dir  # configure(None) means "keep"


@pytest.fixture
def faults():
    f = global_faults()
    f.reset()
    yield f
    f.reset()
    f.disable()


# ------------------------------------------------------------- the ring


def test_ring_is_bounded_and_ordered():
    fl = FlightRecorder(capacity=16, clock=iter(range(10_000)).__next__)
    for i in range(100):
        fl.record("tick", i=i)
    recs = fl.records()
    assert len(recs) == 16 == fl.capacity
    # oldest evicted, order preserved
    assert [f["i"] for _, _, f in recs] == list(range(84, 100))
    # a soak does not grow the ring (bounded allocation: the window is
    # the only retained state)
    for i in range(1000):
        fl.record("tick", i=i)
    assert len(fl.records()) == 16


def test_disabled_recorder_records_nothing():
    fl = FlightRecorder(capacity=8)
    fl.enabled = False
    fl.record("x")
    assert fl.records() == []
    fl.enabled = True
    fl.record("y")
    assert len(fl.records()) == 1


def test_dump_and_parse_roundtrip(tmp_path):
    fl = FlightRecorder(capacity=8, clock=iter(range(100)).__next__)
    fl.record("a", n=1, ok=True, label="x")
    fl.record("b", obj=object())     # non-scalar → stringified, not fatal
    path = fl.dump(str(tmp_path / "w.jsonl"))
    recs = parse_flight_jsonl(open(path).read())
    assert [r["kind"] for r in recs] == ["a", "b"]
    assert recs[0]["n"] == 1 and recs[0]["ok"] is True
    assert isinstance(recs[1]["obj"], str)
    with pytest.raises(ValueError):
        parse_flight_jsonl('{"kind": "missing-t"}')


def test_incident_counts_and_rate_limits(tmp_path):
    clk = [0.0]
    fl = FlightRecorder(capacity=8, clock=lambda: clk[0],
                        incident_dir=str(tmp_path),
                        min_dump_interval_s=10.0)
    p1 = fl.incident("boom")
    assert p1 and fl.dumps == 1 and fl.incidents == 1
    assert fl.incident("boom") is None          # rate-limited
    assert fl.incidents == 2                     # still counted
    clk[0] = 11.0
    p2 = fl.incident("boom")
    assert p2 and p2 != p1 and fl.dumps == 2
    assert fl.last_dump_path == p2


def test_incident_without_dir_counts_only():
    fl = FlightRecorder(capacity=8)
    assert fl.incident("quiet") is None
    assert fl.incidents == 1
    assert fl.records()[-1][1] == "incident"


# --------------------------------------- acceptance: serve fault → dump


class _FaultSiteExecutor(FakeExecutor):
    """A fake executor carrying the REAL ``serve.launch`` fault site (the
    one-gate-read discipline of ``DeviceExecutor.launch``)."""

    def launch(self, batch):
        f = global_faults()
        if f.enabled:
            f.check("serve.launch", kind=batch.key[0])
        return super().launch(batch)


def test_breaker_trip_dumps_fault_retries_and_transition(flight, faults,
                                                         tmp_path):
    """Injected serve fault → retry ladder → breaker trip: the incident
    dump contains the fault firings, the retries, and the OPEN
    transition, in that order — and the request still completes via the
    host-degraded path."""
    faults.enable(seed=0)
    faults.arm("serve.launch", times=3)
    clock = FakeClock()
    cfg = ServeConfig(buckets=(4,), manual=True, max_linger_s=0.0,
                      clock=clock, breaker_threshold=3, max_retries=3,
                      retry_base_s=0.0, sleep=lambda s: None)
    rt = ServeRuntime(graph=None, config=cfg,
                      executor=_FaultSiteExecutor())
    fut = rt.submit_bfs(1)
    assert rt.step(drain=True)
    assert fut.result(timeout=0).kind == "bfs"   # degraded, not an error
    rt.close(drain=True)

    assert flight.incidents == 1
    path = flight.last_dump_path
    assert path is not None and path.startswith(str(tmp_path))
    recs = parse_flight_jsonl(open(path).read())
    kinds = [r["kind"] for r in recs]

    fires = [i for i, r in enumerate(recs)
             if r["kind"] == "fault.fired" and r["point"] == "serve.launch"]
    retries = [i for i, r in enumerate(recs) if r["kind"] == "serve.retry"]
    trips = [i for i, r in enumerate(recs)
             if r["kind"] == "breaker.transition" and r["state"] == "open"]
    assert len(fires) == 3, kinds
    assert len(retries) == 2, kinds             # the 3rd failure trips
    assert len(trips) == 1, kinds
    # in order: fire → retry → fire → retry → fire → OPEN → incident
    assert fires[0] < retries[0] < fires[1] < retries[1] < fires[2] \
        < trips[0] < kinds.index("incident")
    assert recs[kinds.index("incident")]["reason"] == "breaker_trip"


def test_serve_error_incident_on_permanent_failure(flight):
    """A typed (permanent) batch failure is an incident too."""
    from tests.test_serve_runtime import ExplodingExecutor

    clock = FakeClock()
    cfg = ServeConfig(buckets=(4,), manual=True, max_linger_s=0.0,
                      clock=clock)
    rt = ServeRuntime(graph=None, config=cfg, executor=ExplodingExecutor())
    fut = rt.submit_bfs(1)
    rt.step(drain=True)
    with pytest.raises(RuntimeError):
        fut.result(timeout=0)
    rt.close(drain=True)
    assert flight.incidents >= 1
    recs = parse_flight_jsonl(open(flight.last_dump_path).read())
    inc = [r for r in recs if r["kind"] == "incident"][-1]
    assert inc["reason"] == "serve_error"
    assert inc["error"] == "RuntimeError"


def test_healthy_path_is_silent(flight):
    """A clean serving run leaves no incidents and no dump files —
    the recorder's healthy-path footprint is the bounded ring alone."""
    clock = FakeClock()
    cfg = ServeConfig(buckets=(4,), manual=True, max_linger_s=0.0,
                      clock=clock)
    rt = ServeRuntime(graph=None, config=cfg, executor=FakeExecutor())
    for i in range(8):
        rt.submit_bfs(i)
        rt.step(drain=True)
    rt.close(drain=True)
    assert flight.incidents == 0
    assert flight.last_dump_path is None
    assert len(flight.records()) <= flight.capacity


# ------------------------------------------------------------- sigterm hook


def test_sigterm_dump_via_subprocess(tmp_path):
    """The opt-in SIGTERM hook (PR 7 follow-up): an orderly kill dumps
    the flight window before the process dies with the conventional
    -SIGTERM status — exercised in a REAL subprocess because signal
    disposition is process-global state a test must not repurpose."""
    import signal
    import subprocess
    import sys

    code = f"""
import os, signal
from hypergraphdb_tpu.obs.flight import FlightRecorder, install_sigterm_dump

rec = FlightRecorder(incident_dir={str(tmp_path)!r}, min_dump_interval_s=0.0)
rec.record("serve.retry", attempt=1)
rec.record("breaker.transition", state="open")
install_sigterm_dump(rec)
os.kill(os.getpid(), signal.SIGTERM)
raise SystemExit("unreachable: the re-delivered SIGTERM must kill us")
"""
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == -signal.SIGTERM, proc.stderr
    dumps = sorted(tmp_path.glob("flight_*_sigterm.jsonl"))
    assert len(dumps) == 1, list(tmp_path.iterdir())
    recs = parse_flight_jsonl(dumps[0].read_text())
    kinds = [r["kind"] for r in recs]
    assert kinds == ["serve.retry", "breaker.transition", "incident"]
    inc = recs[-1]
    assert inc["reason"] == "sigterm" and inc["signal"] == int(
        signal.SIGTERM
    )


def test_sigterm_hook_chains_and_uninstalls(tmp_path):
    """In-process: a prior Python handler is invoked after the dump, and
    uninstall restores it — the library never owns the signal outright."""
    import os
    import signal

    from hypergraphdb_tpu.obs.flight import install_sigterm_dump

    rec = FlightRecorder(incident_dir=str(tmp_path),
                         min_dump_interval_s=0.0)
    seen = []
    prev = signal.signal(signal.SIGTERM, lambda n, f: seen.append(n))
    try:
        uninstall = install_sigterm_dump(rec)
        os.kill(os.getpid(), signal.SIGTERM)
        assert seen == [signal.SIGTERM]       # chained, process alive
        assert rec.incidents == 1 and rec.last_dump_path is not None
        uninstall()
        os.kill(os.getpid(), signal.SIGTERM)
        assert seen == [signal.SIGTERM, signal.SIGTERM]
        assert rec.incidents == 1             # hook really removed
    finally:
        signal.signal(signal.SIGTERM, prev)
