"""Device value-predicate pushdown (VERDICT r2 item 3): conjunctions of
``Incident + AtomValue[range] (+ AtomType)`` must run on the device value
ranks, never through per-handle host ``satisfies`` for fixed-width kinds
(the reference's value-indexed conjunctions, ``cond2qry/AndToQuery.java:
102-306``)."""

import numpy as np
import pytest

from hypergraphdb_tpu import HyperGraph
from hypergraphdb_tpu.query import dsl as hg
from hypergraphdb_tpu.query import conditions as c
from hypergraphdb_tpu.query.compiler import (
    DeviceValueConjPlan,
    compile_query,
)


@pytest.fixture()
def valued_db():
    g = HyperGraph()
    g.config.query.device_min_batch = 0  # force the device path at test scale
    nodes = [g.add(f"n{i}") for i in range(24)]
    rels = []
    rng = np.random.default_rng(5)
    for i in range(200):
        a, b = rng.choice(24, size=2, replace=False)
        rels.append(
            g.add_link((nodes[a], nodes[b]), value=int(rng.integers(0, 50)))
        )
    yield g, nodes, rels
    g.close()


def _brute(g, rels, anchor, pred):
    out = []
    for l in rels:
        atom = g.get(l)
        if int(anchor) in [int(t) for t in atom.targets] and pred(atom.value):
            out.append(int(l))
    return sorted(out)


OPS = {
    "eq": lambda v, k: v == k,
    "lt": lambda v, k: v < k,
    "lte": lambda v, k: v <= k,
    "gt": lambda v, k: v > k,
    "gte": lambda v, k: v >= k,
}


@pytest.mark.parametrize("op", list(OPS))
def test_int_value_pushdown_differential(valued_db, op):
    g, nodes, rels = valued_db
    for anchor in nodes[:6]:
        cond = hg.and_(
            hg.type_("int"), hg.value(25, op), hg.incident(anchor)
        )
        q = compile_query(g, cond)
        assert isinstance(q.plan, DeviceValueConjPlan), q.analyze()
        got = sorted(g.find_all(cond))
        want = _brute(g, rels, anchor, lambda v: OPS[op](v, 25))
        assert got == want, (op, int(anchor))


def test_int_pushdown_never_calls_satisfies(valued_db, monkeypatch):
    """Fixed-width kinds are tie-free on device: zero host satisfies()."""
    g, nodes, rels = valued_db
    calls = []
    orig = c.AtomValue.satisfies
    monkeypatch.setattr(
        c.AtomValue, "satisfies",
        lambda self, graph, h: calls.append(h) or orig(self, graph, h),
    )
    cond = hg.and_(hg.value(25, "lt"), hg.incident(nodes[0]))
    got = sorted(g.find_all(cond))
    assert calls == []
    want = _brute(g, rels, nodes[0], lambda v: v < 25)
    assert got == want


def test_string_value_ties_verified_host_side():
    """Variable-width kinds: rank ties (shared 8-byte prefix) must be
    resolved exactly by host verification."""
    g = HyperGraph()
    g.config.query.device_min_batch = 0
    n = g.add("anchor")
    # all values share an 8-byte prefix → every rank comparison ties
    vals = ["prefix__a", "prefix__b", "prefix__c", "prefix__"]
    links = {v: g.add_link((n,), value=v) for v in vals}
    got = sorted(g.find_all(hg.and_(hg.value("prefix__b", "lte"), hg.incident(n))))
    want = sorted(int(links[v]) for v in vals if v <= "prefix__b")
    assert got == want
    got_eq = sorted(g.find_all(hg.and_(hg.value("prefix__b", "eq"), hg.incident(n))))
    assert got_eq == [int(links["prefix__b"])]
    g.close()


def test_pushdown_shape_rejected_with_extra_clauses(valued_db):
    """A conjunction with clauses outside the pushdown shape must take the
    generic planner (correctness first)."""
    g, nodes, rels = valued_db
    cond = hg.and_(
        hg.value(25, "lt"), hg.incident(nodes[0]), c.Arity(2, "eq")
    )
    q = compile_query(g, cond)
    assert not isinstance(q.plan, DeviceValueConjPlan)
    got = sorted(g.find_all(cond))
    want = _brute(g, rels, nodes[0], lambda v: v < 25)  # all rels arity 2
    assert got == want


def test_typed_value_expands_into_pushdown(valued_db):
    g, nodes, rels = valued_db
    cond = hg.and_(
        c.TypedValue(25, "int", "gte"), hg.incident(nodes[1])
    )
    q = compile_query(g, cond)
    assert isinstance(q.plan, DeviceValueConjPlan), q.analyze()
    got = sorted(g.find_all(cond))
    want = _brute(g, rels, nodes[1], lambda v: v >= 25)
    assert got == want
