"""Device value-predicate pushdown (VERDICT r2 item 3): conjunctions of
``Incident + AtomValue[range] (+ AtomType)`` must run on the device value
ranks, never through per-handle host ``satisfies`` for fixed-width kinds
(the reference's value-indexed conjunctions, ``cond2qry/AndToQuery.java:
102-306``)."""

import numpy as np
import pytest

from hypergraphdb_tpu import HyperGraph
from hypergraphdb_tpu.query import dsl as hg
from hypergraphdb_tpu.query import conditions as c
from hypergraphdb_tpu.query.compiler import (
    DeviceValueConjPlan,
    compile_query,
)


@pytest.fixture()
def valued_db():
    g = HyperGraph()
    g.config.query.device_min_batch = 0  # force the device path at test scale
    nodes = [g.add(f"n{i}") for i in range(24)]
    rels = []
    rng = np.random.default_rng(5)
    for i in range(200):
        a, b = rng.choice(24, size=2, replace=False)
        rels.append(
            g.add_link((nodes[a], nodes[b]), value=int(rng.integers(0, 50)))
        )
    yield g, nodes, rels
    g.close()


def _brute(g, rels, anchor, pred):
    out = []
    for l in rels:
        atom = g.get(l)
        if int(anchor) in [int(t) for t in atom.targets] and pred(atom.value):
            out.append(int(l))
    return sorted(out)


OPS = {
    "eq": lambda v, k: v == k,
    "lt": lambda v, k: v < k,
    "lte": lambda v, k: v <= k,
    "gt": lambda v, k: v > k,
    "gte": lambda v, k: v >= k,
}


@pytest.mark.parametrize("op", list(OPS))
def test_int_value_pushdown_differential(valued_db, op):
    g, nodes, rels = valued_db
    for anchor in nodes[:6]:
        cond = hg.and_(
            hg.type_("int"), hg.value(25, op), hg.incident(anchor)
        )
        q = compile_query(g, cond)
        assert isinstance(q.plan, DeviceValueConjPlan), q.analyze()
        got = sorted(g.find_all(cond))
        want = _brute(g, rels, anchor, lambda v: OPS[op](v, 25))
        assert got == want, (op, int(anchor))


def test_int_pushdown_never_calls_satisfies(valued_db, monkeypatch):
    """Fixed-width kinds are tie-free on device: zero host satisfies()."""
    g, nodes, rels = valued_db
    calls = []
    orig = c.AtomValue.satisfies
    monkeypatch.setattr(
        c.AtomValue, "satisfies",
        lambda self, graph, h: calls.append(h) or orig(self, graph, h),
    )
    cond = hg.and_(hg.value(25, "lt"), hg.incident(nodes[0]))
    got = sorted(g.find_all(cond))
    assert calls == []
    want = _brute(g, rels, nodes[0], lambda v: v < 25)
    assert got == want


def test_string_value_ties_verified_host_side():
    """Variable-width kinds: rank ties (shared 8-byte prefix) must be
    resolved exactly by host verification."""
    g = HyperGraph()
    g.config.query.device_min_batch = 0
    n = g.add("anchor")
    # all values share an 8-byte prefix → every rank comparison ties
    vals = ["prefix__a", "prefix__b", "prefix__c", "prefix__"]
    links = {v: g.add_link((n,), value=v) for v in vals}
    got = sorted(g.find_all(hg.and_(hg.value("prefix__b", "lte"), hg.incident(n))))
    want = sorted(int(links[v]) for v in vals if v <= "prefix__b")
    assert got == want
    got_eq = sorted(g.find_all(hg.and_(hg.value("prefix__b", "eq"), hg.incident(n))))
    assert got_eq == [int(links["prefix__b"])]
    g.close()


def test_pushdown_shape_rejected_with_extra_clauses(valued_db):
    """A conjunction with clauses outside the pushdown shape must take the
    generic planner (correctness first)."""
    g, nodes, rels = valued_db
    cond = hg.and_(
        hg.value(25, "lt"), hg.incident(nodes[0]), c.Arity(2, "eq")
    )
    q = compile_query(g, cond)
    assert not isinstance(q.plan, DeviceValueConjPlan)
    got = sorted(g.find_all(cond))
    want = _brute(g, rels, nodes[0], lambda v: v < 25)  # all rels arity 2
    assert got == want


def test_typed_value_expands_into_pushdown(valued_db):
    g, nodes, rels = valued_db
    cond = hg.and_(
        c.TypedValue(25, "int", "gte"), hg.incident(nodes[1])
    )
    q = compile_query(g, cond)
    assert isinstance(q.plan, DeviceValueConjPlan), q.analyze()
    got = sorted(g.find_all(cond))
    want = _brute(g, rels, nodes[1], lambda v: v >= 25)
    assert got == want


# --------------------------------------------------------------------------
# fused range windows — VERDICT r4 item 4
# --------------------------------------------------------------------------


def test_range_window_fuses_to_one_plan(valued_db):
    """And(incident, gte lo, lt hi) compiles to ONE DeviceValueConjPlan with
    both bounds (a single fused launch), not a generic intersection."""
    g, nodes, rels = valued_db
    cond = hg.and_(
        hg.value(10, "gte"), hg.value(30, "lt"), hg.incident(nodes[0])
    )
    q = compile_query(g, cond)
    assert isinstance(q.plan, DeviceValueConjPlan), q.analyze()
    assert q.plan.op2 is not None
    assert ".." in q.plan.describe()


@pytest.mark.parametrize("lo_op,hi_op", [
    ("gte", "lt"), ("gt", "lte"), ("gte", "lte"), ("gt", "lt"),
])
def test_range_window_differential(valued_db, lo_op, hi_op):
    g, nodes, rels = valued_db
    lo, hi = 10, 30
    for anchor in nodes[:6]:
        cond = hg.and_(
            hg.value(lo, lo_op), hg.value(hi, hi_op), hg.incident(anchor)
        )
        got = sorted(g.find_all(cond))
        want = _brute(
            g, rels, anchor,
            lambda v: OPS[lo_op](v, lo) and OPS[hi_op](v, hi),
        )
        assert got == want, (lo_op, hi_op, int(anchor))


def test_range_kernel_matches_two_single_probes(valued_db):
    """incident_value_range must agree bit-for-bit with the AND of two
    incident_value_pattern launches over the same window."""
    import jax.numpy as jnp

    from hypergraphdb_tpu.ops.setops import (
        _bucket,
        ell_targets,
        incident_value_pattern,
        incident_value_range,
    )
    from hypergraphdb_tpu.utils.ordered_bytes import rank64

    g, nodes, rels = valued_db
    snap = g.snapshot()
    ell = ell_targets(snap)
    vt = g.typesystem.infer(11)
    key_lo, key_hi = vt.to_key(11), vt.to_key(37)
    r_lo, r_hi = rank64(key_lo[1:]), rank64(key_hi[1:])
    kind = key_lo[0]

    anchors = np.asarray([[int(nodes[0])], [int(nodes[3])]], dtype=np.int32)
    lens = snap.inc_offsets[anchors[:, 0] + 1] - snap.inc_offsets[anchors[:, 0]]
    pad = _bucket(int(lens.max()))
    args = (snap.device, ell, jnp.asarray(anchors), pad, jnp.uint8(kind))

    _, keep_lo, _ = incident_value_pattern(
        *args, jnp.uint32(r_lo >> 32), jnp.uint32(r_lo & 0xFFFFFFFF),
        "gte", True, None,
    )
    _, keep_hi, _ = incident_value_pattern(
        *args, jnp.uint32(r_hi >> 32), jnp.uint32(r_hi & 0xFFFFFFFF),
        "lt", True, None,
    )
    rows, keep, tie, counts = incident_value_range(
        *args,
        jnp.uint32(r_lo >> 32), jnp.uint32(r_lo & 0xFFFFFFFF),
        jnp.uint32(r_hi >> 32), jnp.uint32(r_hi & 0xFFFFFFFF),
        "gte", "lt", True, None,
    )
    np.testing.assert_array_equal(
        np.asarray(keep), np.asarray(keep_lo & keep_hi)
    )
    np.testing.assert_array_equal(
        np.asarray(counts), np.asarray((keep_lo & keep_hi).sum(axis=1))
    )
    assert not np.asarray(tie).any()


def test_string_range_ties_verified_host_side():
    """Variable-width kinds: survivors strictly inside the window are
    definite; bound ties go through host verification — results must still
    be exact."""
    g = HyperGraph()
    g.config.query.device_min_batch = 0
    a = g.add("anchor")
    words = ["apple", "banana", "cherry", "damson", "elder", "fig"]
    links = {w: g.add_link((a,), value=w) for w in words}
    cond = hg.and_(
        hg.value("banana", "gte"), hg.value("elder", "lt"), hg.incident(a)
    )
    q = compile_query(g, cond)
    assert isinstance(q.plan, DeviceValueConjPlan) and q.plan.op2 is not None
    got = sorted(g.find_all(cond))
    want = sorted(
        int(links[w]) for w in words if "banana" <= w < "elder"
    )
    assert got == want
    g.close()


def test_value_columns_row_pack_matches_default(valued_db):
    """The optional (N+1, 4) row-packed rank layout (CALIBRATION.md §4)
    must agree bit-for-bit with the default column gathers."""
    import jax.numpy as jnp

    from hypergraphdb_tpu.ops.setops import (
        _bucket,
        ell_targets,
        incident_value_range,
        value_columns,
    )
    from hypergraphdb_tpu.utils.ordered_bytes import rank64

    g, nodes, rels = valued_db
    snap = g.snapshot()
    ell = ell_targets(snap)
    vcols = value_columns(snap)
    vt = g.typesystem.infer(11)
    r_lo = rank64(vt.to_key(11)[1:])
    r_hi = rank64(vt.to_key(37)[1:])
    kind = vt.to_key(11)[0]
    anchors = np.asarray([[int(nodes[0])], [int(nodes[4])]], dtype=np.int32)
    lens = snap.inc_offsets[anchors[:, 0] + 1] - snap.inc_offsets[anchors[:, 0]]
    pad = _bucket(int(lens.max()))
    args = (
        snap.device, ell, jnp.asarray(anchors), pad, jnp.uint8(kind),
        jnp.uint32(r_lo >> 32), jnp.uint32(r_lo & 0xFFFFFFFF),
        jnp.uint32(r_hi >> 32), jnp.uint32(r_hi & 0xFFFFFFFF),
        "gte", "lt", True, None,
    )
    _, keep0, _, counts0 = incident_value_range(*args)
    _, keep1, _, counts1 = incident_value_range(*args, vcols)
    np.testing.assert_array_equal(np.asarray(keep0), np.asarray(keep1))
    np.testing.assert_array_equal(np.asarray(counts0), np.asarray(counts1))
