"""Tier-1 gate + precision pins for the hglint static analyzer.

Two jobs:

1. pin analyzer precision against the checked-in fixture sets —
   ``hglint_fixtures/bad_pkg`` (every seeded hazard must be flagged) and
   ``hglint_fixtures/clean_pkg`` (zero findings allowed);
2. act as the repo gate: ``hypergraphdb_tpu`` linted against
   ``tools/hglint/baseline.json`` must produce no NEW findings, so a PR
   that introduces a fresh host-sync/retrace/Pallas/lock hazard fails
   tier-1.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from tools.hglint import (  # noqa: E402
    RULES,
    apply_baseline,
    baseline_counts,
    load_baseline,
    run_lint,
    write_baseline,
)

FIXTURES = Path(__file__).parent / "hglint_fixtures"
BASELINE = REPO / "tools" / "hglint" / "baseline.json"


def _rules(findings):
    return {f.rule for f in findings}


# ------------------------------------------------------------ bad fixtures


def test_bad_fixture_flags_every_family():
    findings = run_lint([str(FIXTURES / "bad_pkg")])
    rules = _rules(findings)
    # family 1: host-sync-in-traced-code, every spelling, + donation (106)
    # and host-numpy upload (107)
    assert {"HG101", "HG102", "HG103", "HG104", "HG105",
            "HG106", "HG107"} <= rules
    # family 2: retrace hazards
    assert {"HG201", "HG202", "HG203", "HG204"} <= rules
    # family 3: Pallas contracts
    assert {"HG301", "HG302", "HG303", "HG304"} <= rules
    # family 4: lock order + contract discipline
    assert {"HG401", "HG402", "HG403"} <= rules
    # family 5: VMEM budgets (incl. scalar-prefetch SMEM)
    assert {"HG501", "HG502", "HG503"} <= rules
    # family 6: shard_map collective consistency (incl. cond branches)
    assert {"HG601", "HG602", "HG603", "HG604"} <= rules
    assert len(findings) >= 8  # acceptance floor; actual seed is larger


def test_taint_flows_through_call_graph():
    """block_until_ready lives in an UNdecorated helper; it must be flagged
    because a jit root calls the helper."""
    findings = run_lint([str(FIXTURES / "bad_pkg" / "hostsync.py")])
    hits = [f for f in findings if f.rule == "HG105"]
    assert len(hits) == 1
    assert hits[0].scope == "_helper_sync"
    assert "bad_transitive" in hits[0].message


def test_pallas_out_of_bounds_and_arity():
    findings = run_lint([str(FIXTURES / "bad_pkg" / "pallas_bad.py")])
    msgs = [f.message for f in findings if f.rule == "HG302"]
    assert any("out of bounds" in m for m in msgs)
    assert any("grid has rank 2" in m for m in msgs)


# ------------------------------------------------------------ vmem fixtures


def test_vmem_overflow_and_unresolvable_are_distinct():
    findings = run_lint([str(FIXTURES / "bad_pkg" / "vmem_bad.py")])
    by_rule = {f.rule: f for f in findings if f.rule.startswith("HG5")}
    assert set(by_rule) == {"HG501", "HG502"}
    assert "exceeds" in by_rule["HG501"].message
    assert by_rule["HG501"].scope == "overflow"
    assert "not statically resolvable" in by_rule["HG502"].message
    assert by_rule["HG502"].scope == "unresolvable"


def test_vmem_budget_is_configurable():
    # the 32 MiB fixture passes under a 64 MiB budget; the resolvable-but-
    # small spec never flags
    findings = run_lint(
        [str(FIXTURES / "bad_pkg" / "vmem_bad.py")], vmem_budget=64 << 20
    )
    assert [f for f in findings if f.rule == "HG501"] == []


def test_vmem_pragma_suppresses_hg502():
    # clean_pkg/vmem_ok.py contains a genuinely unresolvable pallas_call
    # annotated with `# hglint: disable=HG502` — covered by the clean
    # sweep, pinned here so the pragma path has a dedicated failure mode
    findings = run_lint([str(FIXTURES / "clean_pkg" / "vmem_ok.py")])
    assert findings == [], "\n".join(f.render() for f in findings)


def test_smem_scalar_prefetch_budget():
    findings = run_lint([str(FIXTURES / "bad_pkg" / "smem_bad.py")])
    hits = [f for f in findings if f.rule == "HG503"]
    assert len(hits) == 1
    assert "SMEM" in hits[0].message and hits[0].scope == "smem_overflow"
    # the fitting twin (the pallas_gather SEG contract) stays silent
    ok = run_lint([str(FIXTURES / "clean_pkg" / "smem_ok.py")])
    assert [f for f in ok if f.rule == "HG503"] == []


def test_fused_bfs_kernel_window_fixtures():
    """The fused pull-BFS hop kernel's window math (ops/pallas_bfs): the
    scalar-prefetched chunk plan overflowing SMEM and the scratch+window
    set overflowing VMEM are both caught; the committed real geometry
    folds clean."""
    findings = run_lint([str(FIXTURES / "bad_pkg" / "fusedbfs_bad.py")])
    by_rule = {f.rule: f for f in findings}
    assert set(by_rule) == {"HG501", "HG503"}
    assert by_rule["HG503"].scope == "fused_hop_smem_overflow"
    assert by_rule["HG501"].scope == "fused_hop_vmem_overflow"
    ok = run_lint([str(FIXTURES / "clean_pkg" / "fusedbfs_ok.py")])
    assert ok == [], "\n".join(f.render() for f in ok)


def test_shapes_fold_through_scan_and_vmap():
    """ShapeDtype propagates through lax.scan carries and jax.vmap
    results: the wrapshape fixtures' None block dims fold, so overflows
    surface as HG501 (not the weaker HG502), and the fitting twins fold
    clean (no HG502 either)."""
    findings = run_lint([str(FIXTURES / "bad_pkg" / "wrapshape_bad.py")])
    by_scope = {f.scope: f.rule for f in findings
                if f.rule.startswith("HG5")}
    assert by_scope == {"scan_carried_overflow": "HG501",
                        "vmap_result_overflow": "HG501"}
    ok = run_lint([str(FIXTURES / "clean_pkg" / "wrapshape_ok.py")])
    assert [f for f in ok if f.rule.startswith("HG5")] == []


# ------------------------------------------------------ collective fixtures


def test_collective_axis_and_divergence_flagged():
    findings = run_lint([str(FIXTURES / "bad_pkg" / "collectives_bad.py")])
    rules = {f.rule: f for f in findings}
    assert {"HG601", "HG602", "HG603"} <= set(rules)
    assert "'ghost'" in rules["HG601"].message
    assert "deadlock" in rules["HG602"].message
    assert rules["HG602"].scope == "_diverging_body"
    assert "'model'" in rules["HG603"].message
    assert rules["HG603"].scope == "_mismatch_helper"


def test_collectives_clean_region_is_silent():
    findings = run_lint([str(FIXTURES / "clean_pkg" / "collectives_ok.py")])
    assert [f for f in findings if f.rule.startswith("HG6")] == []


def test_cond_branch_collective_mismatch_flagged():
    findings = run_lint([str(FIXTURES / "bad_pkg" / "condcoll_bad.py")])
    hits = [f for f in findings if f.rule == "HG604"]
    by_scope = {f.scope: f for f in hits}
    # _helper_body: the mismatched psum hides one call deep — the branch
    # scan must follow resolvable helpers in both directions
    assert set(by_scope) == {"_cond_body", "_switch_body", "_helper_body"}
    assert "mismatched collectives" in by_scope["_cond_body"].message
    # identical-psum branches must stay silent — including a branch that
    # routes the SAME psum through a helper
    ok = run_lint([str(FIXTURES / "clean_pkg" / "condcoll_ok.py")])
    assert [f for f in ok if f.rule == "HG604"] == []


def test_decorator_args_are_host_scope(tmp_path):
    """Decorator expressions of a module-level jitted function execute at
    import (host) — numpy work there must NOT be flagged as traced; the
    same hazard on a def NESTED inside a jit root executes under tracing
    and must be flagged."""
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "m.py").write_text(
        "import jax\n"
        "import numpy as np\n\n\n"
        "def _register(table):\n"
        "    def deco(fn):\n"
        "        return fn\n"
        "    return deco\n\n\n"
        "@_register(table=np.arange(8))\n"
        "@jax.jit\n"
        "def host_decorated(x):\n"
        "    return x * 2\n"
    )
    assert run_lint([str(pkg)]) == [], "host-side decorator arg flagged"
    (pkg / "m.py").write_text(
        "import jax\n"
        "import numpy as np\n\n\n"
        "def _register(table):\n"
        "    def deco(fn):\n"
        "        return fn\n"
        "    return deco\n\n\n"
        "@jax.jit\n"
        "def traced(x):\n"
        "    @_register(table=np.arange(8))\n"
        "    def inner(y):\n"
        "        return y\n"
        "    return inner(x)\n"
    )
    rules = {f.rule for f in run_lint([str(pkg)])}
    assert "HG103" in rules, "traced nested-def decorator arg missed"


# -------------------------------------------------------- donation fixtures


def test_donated_buffer_reuse_flagged():
    findings = run_lint([str(FIXTURES / "bad_pkg" / "donation_bad.py")])
    hits = [f for f in findings if f.rule == "HG106"]
    by_scope = {f.scope: f for f in hits}
    assert set(by_scope) == {"read_after_donate", "loop_donate",
                             "branch_test_read", "iter_read"}
    assert len(hits) == 4
    assert "donated to `_update`" in by_scope["read_after_donate"].message
    assert "next loop iteration" in by_scope["loop_donate"].message
    # reads hiding in a branch condition / loop iterator are still reads
    assert "donated to `_update`" in by_scope["branch_test_read"].message
    assert "donated to `_update`" in by_scope["iter_read"].message


def test_donation_rebind_idiom_is_silent():
    findings = run_lint([str(FIXTURES / "clean_pkg" / "donation_ok.py")])
    assert [f for f in findings if f.rule == "HG106"] == []


# --------------------------------------------------------- asarray fixtures


def test_host_numpy_upload_flagged():
    findings = run_lint([str(FIXTURES / "bad_pkg" / "asarray_bad.py")])
    hits = [f for f in findings if f.rule == "HG107"]
    assert len(hits) == 2
    assert any("_TABLE" in f.message for f in hits)
    assert any("mask" in f.message for f in hits)


# ------------------------------------------------------------ lock fixtures


def test_lock_cycle_flagged():
    findings = run_lint([str(FIXTURES / "bad_pkg" / "locks_cycle.py")])
    cycles = [f for f in findings if f.rule == "HG401"]
    assert len(cycles) == 1
    assert "lock_a" in cycles[0].message and "lock_b" in cycles[0].message


def test_clean_two_lock_module_not_flagged():
    findings = run_lint([str(FIXTURES / "clean_pkg" / "locks_ok.py")])
    assert [f for f in findings if f.rule.startswith("HG4")] == []


def test_locked_contract_violation_flagged():
    # inverse *_locked contract: a `_locked` leaf invoked from a caller
    # that provably holds NO registered lock
    findings = run_lint([str(FIXTURES / "bad_pkg" / "locks_cycle.py")])
    (hit,) = [f for f in findings if f.rule == "HG403"]
    assert hit.line == 49 and hit.scope == "Journal.drain_fast"
    assert "_append_locked" in hit.message
    assert "holding no registered lock" in hit.message


# ------------------------------------------------------------ clean fixtures


def test_clean_fixture_is_silent():
    findings = run_lint([str(FIXTURES / "clean_pkg")])
    assert findings == [], "\n".join(f.render() for f in findings)


# ------------------------------------------------------------- repo gate


def test_repo_gate_passes_with_baseline(monkeypatch):
    """The tier-1 contract: hypergraphdb_tpu linted against the checked-in
    baseline reports zero NEW findings."""
    monkeypatch.chdir(REPO)  # baseline keys are repo-root-relative
    findings = run_lint(["hypergraphdb_tpu"])
    baseline = load_baseline(str(BASELINE))
    fresh = apply_baseline(findings, baseline)
    assert fresh == [], (
        "new hglint findings (fix them or regenerate the baseline via "
        "`python -m tools.hglint hypergraphdb_tpu --write-baseline "
        "tools/hglint/baseline.json`):\n"
        + "\n".join(f.render() for f in fresh)
    )


def test_repo_baseline_is_not_stale(monkeypatch):
    """Every baseline entry must still correspond to a live finding —
    otherwise fixed hazards stay suppressed forever."""
    monkeypatch.chdir(REPO)
    live = baseline_counts(run_lint(["hypergraphdb_tpu"]))
    baseline = load_baseline(str(BASELINE))
    stale = {
        k: (v, live.get(k, 0))
        for k, v in baseline.items()
        if live.get(k, 0) < v
    }
    assert stale == {}, f"baseline entries with no live finding: {stale}"


# ------------------------------------------------------------- baseline io


def test_baseline_roundtrip(tmp_path):
    findings = run_lint([str(FIXTURES / "bad_pkg")])
    path = tmp_path / "baseline.json"
    write_baseline(findings, str(path))
    loaded = load_baseline(str(path))
    assert loaded == baseline_counts(findings)
    # everything baselined -> nothing new
    assert apply_baseline(findings, loaded) == []
    # dropping one entry resurfaces exactly that finding count
    key, n = next(iter(sorted(loaded.items())))
    partial = dict(loaded)
    partial[key] = n - 1
    fresh = apply_baseline(findings, partial)
    assert len(fresh) == 1 and fresh[0].baseline_key == key


def test_rule_registry_consistency():
    findings = run_lint([str(FIXTURES / "bad_pkg")])
    assert _rules(findings) <= set(RULES), "finding with unregistered rule id"


_BAD_SNIPPET = '''\
import jax


@jax.jit
def f(x):
    return x.item()
'''

_FIXED_SNIPPET = '''\
import jax


@jax.jit
def f(x):
    return x
'''


def test_baseline_lifecycle_staleness_forces_removal(tmp_path):
    """The full suppression lifecycle: a finding appears, gets baselined
    (gate passes), the hazard is FIXED — and the staleness check must then
    reject the baseline entry so the suppression cannot outlive the bug."""
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    mod = pkg / "hot.py"
    bl = tmp_path / "baseline.json"

    # 1. the hazard appears
    mod.write_text(_BAD_SNIPPET)
    findings = run_lint([str(pkg)])
    assert [f.rule for f in findings] == ["HG101"]

    # 2. it is baselined: the gate goes quiet
    write_baseline(findings, str(bl))
    loaded = load_baseline(str(bl))
    assert apply_baseline(run_lint([str(pkg)]), loaded) == []

    # 3. the hazard is fixed but the baseline still carries the entry:
    #    the staleness check (mirrors test_repo_baseline_is_not_stale)
    #    must flag it for removal
    mod.write_text(_FIXED_SNIPPET)
    live = baseline_counts(run_lint([str(pkg)]))
    stale = {k: v for k, v in loaded.items() if live.get(k, 0) < v}
    assert stale, "fixed hazard left no stale baseline entry to remove"

    # 4. removing the stale entry closes the loop: gate still clean
    pruned = {k: v for k, v in loaded.items() if k not in stale}
    assert apply_baseline(run_lint([str(pkg)]), pruned) == []


# ---------------------------------------------------------------- filters


def test_only_family_filter():
    all_f = run_lint([str(FIXTURES / "bad_pkg")])
    vmem_only = run_lint([str(FIXTURES / "bad_pkg")], only="HG5")
    assert vmem_only and all(f.rule.startswith("HG5") for f in vmem_only)
    assert len(vmem_only) < len(all_f)
    multi = run_lint([str(FIXTURES / "bad_pkg")], only="HG5,HG601")
    assert {f.rule for f in multi} <= {"HG501", "HG502", "HG503", "HG601"}
    assert any(f.rule == "HG601" for f in multi)


def test_only_typo_refuses_silent_green():
    # a prefix matching no rule must raise, not skip every runner and
    # report a clean run
    with pytest.raises(ValueError, match="matches no known rule"):
        run_lint([str(FIXTURES / "bad_pkg")], only="HG0")
    with pytest.raises(ValueError, match="matches no known rule"):
        run_lint([str(FIXTURES / "bad_pkg")], only="hg5")  # case-sensitive


def test_pragma_disables_named_rule_only(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "m.py").write_text(
        "import jax\n\n\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return x.item()  # hglint: disable=HG101\n"
    )
    assert run_lint([str(pkg)]) == []
    # a pragma for a DIFFERENT rule must not suppress the finding
    (pkg / "m.py").write_text(
        "import jax\n\n\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return x.item()  # hglint: disable=HG999\n"
    )
    assert [f.rule for f in run_lint([str(pkg)])] == ["HG101"]


# ------------------------------------------------------------------- CLI


def test_cli_exit_codes(tmp_path):
    env = dict(os.environ)
    env.setdefault("PYTHONPATH", str(REPO))
    bad = subprocess.run(
        [sys.executable, "-m", "tools.hglint",
         str(FIXTURES / "bad_pkg")],
        cwd=REPO, capture_output=True, text=True, env=env,
    )
    assert bad.returncode == 1
    assert "HG101" in bad.stdout
    clean = subprocess.run(
        [sys.executable, "-m", "tools.hglint",
         str(FIXTURES / "clean_pkg")],
        cwd=REPO, capture_output=True, text=True, env=env,
    )
    assert clean.returncode == 0

    out = subprocess.run(
        [sys.executable, "-m", "tools.hglint", str(FIXTURES / "bad_pkg"),
         "--json"],
        cwd=REPO, capture_output=True, text=True, env=env,
    )
    data = json.loads(out.stdout)
    assert isinstance(data, list) and len(data) >= 8
    assert {"rule", "severity", "path", "line", "scope", "message"} <= set(
        data[0]
    )
