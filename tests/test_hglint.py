"""Tier-1 gate + precision pins for the hglint static analyzer.

Two jobs:

1. pin analyzer precision against the checked-in fixture sets —
   ``hglint_fixtures/bad_pkg`` (every seeded hazard must be flagged) and
   ``hglint_fixtures/clean_pkg`` (zero findings allowed);
2. act as the repo gate: ``hypergraphdb_tpu`` linted against
   ``tools/hglint/baseline.json`` must produce no NEW findings, so a PR
   that introduces a fresh host-sync/retrace/Pallas/lock hazard fails
   tier-1.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from tools.hglint import (  # noqa: E402
    RULES,
    apply_baseline,
    baseline_counts,
    load_baseline,
    run_lint,
    write_baseline,
)

FIXTURES = Path(__file__).parent / "hglint_fixtures"
BASELINE = REPO / "tools" / "hglint" / "baseline.json"


def _rules(findings):
    return {f.rule for f in findings}


# ------------------------------------------------------------ bad fixtures


def test_bad_fixture_flags_every_family():
    findings = run_lint([str(FIXTURES / "bad_pkg")])
    rules = _rules(findings)
    # family 1: host-sync-in-traced-code, every spelling
    assert {"HG101", "HG102", "HG103", "HG104", "HG105"} <= rules
    # family 2: retrace hazards
    assert {"HG201", "HG202", "HG203", "HG204"} <= rules
    # family 3: Pallas contracts
    assert {"HG301", "HG302", "HG303", "HG304"} <= rules
    # family 4: lock order
    assert {"HG401", "HG402"} <= rules
    assert len(findings) >= 8  # acceptance floor; actual seed is larger


def test_taint_flows_through_call_graph():
    """block_until_ready lives in an UNdecorated helper; it must be flagged
    because a jit root calls the helper."""
    findings = run_lint([str(FIXTURES / "bad_pkg" / "hostsync.py")])
    hits = [f for f in findings if f.rule == "HG105"]
    assert len(hits) == 1
    assert hits[0].scope == "_helper_sync"
    assert "bad_transitive" in hits[0].message


def test_pallas_out_of_bounds_and_arity():
    findings = run_lint([str(FIXTURES / "bad_pkg" / "pallas_bad.py")])
    msgs = [f.message for f in findings if f.rule == "HG302"]
    assert any("out of bounds" in m for m in msgs)
    assert any("grid has rank 2" in m for m in msgs)


# ------------------------------------------------------------ lock fixtures


def test_lock_cycle_flagged():
    findings = run_lint([str(FIXTURES / "bad_pkg" / "locks_cycle.py")])
    cycles = [f for f in findings if f.rule == "HG401"]
    assert len(cycles) == 1
    assert "lock_a" in cycles[0].message and "lock_b" in cycles[0].message


def test_clean_two_lock_module_not_flagged():
    findings = run_lint([str(FIXTURES / "clean_pkg" / "locks_ok.py")])
    assert [f for f in findings if f.rule.startswith("HG4")] == []


# ------------------------------------------------------------ clean fixtures


def test_clean_fixture_is_silent():
    findings = run_lint([str(FIXTURES / "clean_pkg")])
    assert findings == [], "\n".join(f.render() for f in findings)


# ------------------------------------------------------------- repo gate


def test_repo_gate_passes_with_baseline(monkeypatch):
    """The tier-1 contract: hypergraphdb_tpu linted against the checked-in
    baseline reports zero NEW findings."""
    monkeypatch.chdir(REPO)  # baseline keys are repo-root-relative
    findings = run_lint(["hypergraphdb_tpu"])
    baseline = load_baseline(str(BASELINE))
    fresh = apply_baseline(findings, baseline)
    assert fresh == [], (
        "new hglint findings (fix them or regenerate the baseline via "
        "`python -m tools.hglint hypergraphdb_tpu --write-baseline "
        "tools/hglint/baseline.json`):\n"
        + "\n".join(f.render() for f in fresh)
    )


def test_repo_baseline_is_not_stale(monkeypatch):
    """Every baseline entry must still correspond to a live finding —
    otherwise fixed hazards stay suppressed forever."""
    monkeypatch.chdir(REPO)
    live = baseline_counts(run_lint(["hypergraphdb_tpu"]))
    baseline = load_baseline(str(BASELINE))
    stale = {
        k: (v, live.get(k, 0))
        for k, v in baseline.items()
        if live.get(k, 0) < v
    }
    assert stale == {}, f"baseline entries with no live finding: {stale}"


# ------------------------------------------------------------- baseline io


def test_baseline_roundtrip(tmp_path):
    findings = run_lint([str(FIXTURES / "bad_pkg")])
    path = tmp_path / "baseline.json"
    write_baseline(findings, str(path))
    loaded = load_baseline(str(path))
    assert loaded == baseline_counts(findings)
    # everything baselined -> nothing new
    assert apply_baseline(findings, loaded) == []
    # dropping one entry resurfaces exactly that finding count
    key, n = next(iter(sorted(loaded.items())))
    partial = dict(loaded)
    partial[key] = n - 1
    fresh = apply_baseline(findings, partial)
    assert len(fresh) == 1 and fresh[0].baseline_key == key


def test_rule_registry_consistency():
    findings = run_lint([str(FIXTURES / "bad_pkg")])
    assert _rules(findings) <= set(RULES), "finding with unregistered rule id"


# ------------------------------------------------------------------- CLI


def test_cli_exit_codes(tmp_path):
    env = dict(os.environ)
    env.setdefault("PYTHONPATH", str(REPO))
    bad = subprocess.run(
        [sys.executable, "-m", "tools.hglint",
         str(FIXTURES / "bad_pkg")],
        cwd=REPO, capture_output=True, text=True, env=env,
    )
    assert bad.returncode == 1
    assert "HG101" in bad.stdout
    clean = subprocess.run(
        [sys.executable, "-m", "tools.hglint",
         str(FIXTURES / "clean_pkg")],
        cwd=REPO, capture_output=True, text=True, env=env,
    )
    assert clean.returncode == 0

    out = subprocess.run(
        [sys.executable, "-m", "tools.hglint", str(FIXTURES / "bad_pkg"),
         "--json"],
        cwd=REPO, capture_output=True, text=True, env=env,
    )
    data = json.loads(out.stdout)
    assert isinstance(data, list) and len(data) >= 8
    assert {"rule", "severity", "path", "line", "scope", "message"} <= set(
        data[0]
    )
