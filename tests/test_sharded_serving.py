"""Multi-chip sharded serving: differential exactness + routing.

The acceptance contract: sharded BFS/pattern/join serve results ==
single-chip results == host ground truth for every bucket shape,
including delta/tombstone visibility mid-ingest and truncation prefixes
— on the virtual 8-device CPU mesh the conftest forces.
"""

import numpy as np
import pytest

from hypergraphdb_tpu import HyperGraph
from hypergraphdb_tpu.query import conditions as c
from hypergraphdb_tpu.query.variables import var
from hypergraphdb_tpu.serve import (
    DeviceExecutor,
    ServeConfig,
    ServeRuntime,
    ShardedExecutor,
)

from conftest import make_random_hypergraph

#: small buckets keep the per-test compile count bounded; 16 and 64 are
#: both divisible by the 8-device mesh (the join lane split needs that)
BUCKETS = (16, 64)


def _cfg(**kw):
    base = dict(buckets=BUCKETS, max_linger_s=0.001, top_r=16,
                use_pallas_bfs=False, prewarm_aot=False)
    base.update(kw)
    return ServeConfig(**base)


def _pair(graph_builder):
    """Two graphs with identical content; a sharded runtime on one, a
    single-chip runtime on the other."""
    g1, aux1 = graph_builder()
    g2, aux2 = graph_builder()
    rt_sh = ServeRuntime(g1, _cfg(sharded=True))
    rt_one = ServeRuntime(g2, _cfg(sharded=False))
    assert isinstance(rt_sh.executor, ShardedExecutor)
    assert type(rt_one.executor) is DeviceExecutor
    return (g1, aux1, rt_sh), (g2, aux2, rt_one)


def _build(seed=3, n_nodes=150, n_links=300):
    def build():
        g = HyperGraph()
        aux = make_random_hypergraph(g, n_nodes=n_nodes, n_links=n_links,
                                     seed=seed)
        return g, aux
    return build


def _assert_same(r1, r2):
    assert r1.count == r2.count
    assert r1.truncated == r2.truncated
    np.testing.assert_array_equal(np.asarray(r1.matches),
                                  np.asarray(r2.matches))


# ---------------------------------------------------------------- BFS


def test_sharded_bfs_matches_single_chip_and_host():
    (g1, (nodes1, _), rt1), (g2, (nodes2, _), rt2) = _pair(_build())
    try:
        futs1 = [rt1.submit_bfs(int(nodes1[i]), max_hops=3)
                 for i in range(24)]
        futs2 = [rt2.submit_bfs(int(nodes2[i]), max_hops=3)
                 for i in range(24)]
        for i, (f1, f2) in enumerate(zip(futs1, futs2)):
            r1, r2 = f1.result(timeout=120), f2.result(timeout=120)
            assert r1.served_by == "device"
            _assert_same(r1, r2)
            truth = sorted(
                int(h) for h in g1.find_all(
                    c.BFS(int(nodes1[i]), max_distance=3))
            ) + [int(nodes1[i])]
            assert r1.count == len(set(truth))
        assert rt1.stats.sharded_dispatches > 0
    finally:
        rt1.close()
        rt2.close()
        g1.close()
        g2.close()


def test_sharded_bfs_sees_delta_and_tombstones_mid_ingest():
    """The pinned sharded (base ∪ delta) twins: post-compaction adds are
    visible through the sharded kernel, removals tombstone out — equal
    to the single-chip delta path lane for lane."""
    (g1, (nodes1, links1), rt1), (g2, (nodes2, links2), rt2) = \
        _pair(_build(seed=5))
    try:
        # mutate BOTH graphs identically AFTER the runtimes pinned once
        for g, nodes, links in ((g1, nodes1, links1),
                                (g2, nodes2, links2)):
            for i in range(6):
                g.add_link([nodes[i], nodes[i + 40]])
            g.remove(links[7])
            g.remove(links[9])
        for i in list(range(8)) + [40, 41]:
            r1 = rt1.submit_bfs(int(nodes1[i]), max_hops=2).result(
                timeout=120)
            r2 = rt2.submit_bfs(int(nodes2[i]), max_hops=2).result(
                timeout=120)
            _assert_same(r1, r2)
            truth = set(
                int(h) for h in g1.find_all(
                    c.BFS(int(nodes1[i]), max_distance=2))
            ) | {int(nodes1[i])}
            assert r1.count == len(truth)
    finally:
        rt1.close()
        rt2.close()
        g1.close()
        g2.close()


def test_sharded_bfs_truncation_prefix_exact():
    (g1, (nodes1, _), rt1), (g2, (nodes2, _), rt2) = _pair(_build(seed=9))
    rt1.config.top_r = rt2.config.top_r = 4  # shrink the compact window
    try:
        r1 = rt1.submit_bfs(int(nodes1[0]), max_hops=3).result(timeout=120)
        r2 = rt2.submit_bfs(int(nodes2[0]), max_hops=3).result(timeout=120)
        assert r1.truncated and r1.count > 4 and len(r1.matches) == 4
        _assert_same(r1, r2)
        truth = sorted(set(
            int(h) for h in g1.find_all(
                c.BFS(int(nodes1[0]), max_distance=3))
        ) | {int(nodes1[0])})
        assert list(r1.matches) == truth[:4]   # ascending prefix
    finally:
        rt1.close()
        rt2.close()
        g1.close()
        g2.close()


# ---------------------------------------------------------------- patterns


def test_sharded_pattern_matches_single_chip_and_host():
    (g1, (nodes1, links1), rt1), (g2, (nodes2, links2), rt2) = \
        _pair(_build(seed=7, n_links=400))
    try:
        lt = int(g1.get_type_handle_of(links1[0]))
        pairs = []
        for lk in links1[:24]:
            ts = [int(t) for t in g1.get_targets(lk)]
            if len(ts) >= 2 and ts[0] != ts[1]:
                pairs.append((ts[0], ts[1]))
        assert len(pairs) >= 4
        for th in (None, lt):
            for a, b in pairs[:6]:
                r1 = rt1.submit_pattern([a, b], type_handle=th).result(
                    timeout=120)
                r2 = rt2.submit_pattern([a, b], type_handle=th).result(
                    timeout=120)
                _assert_same(r1, r2)
                clauses = [c.Incident(a), c.Incident(b)]
                if th is not None:
                    clauses.append(c.AtomType(th))
                truth = sorted(int(h) for h in g1.find_all(c.And(*clauses)))
                assert r1.count == len(truth)
                if not r1.truncated:
                    assert sorted(int(m) for m in r1.matches) == truth
        assert rt1.stats.sharded_dispatches > 0
    finally:
        rt1.close()
        rt2.close()
        g1.close()
        g2.close()


def test_sharded_pattern_memtable_correction_mid_ingest():
    """Pattern lanes run on the BASE; the host memtable merge at collect
    must make fresh links visible and tombstoned ones invisible —
    exactly the single-chip LSM correction, through the sharded path."""
    (g1, (nodes1, links1), rt1), (g2, (nodes2, links2), rt2) = \
        _pair(_build(seed=11))
    try:
        a, b = int(nodes1[2]), int(nodes1[3])
        a2, b2 = int(nodes2[2]), int(nodes2[3])
        fresh1 = [int(g1.add_link([a, b])) for _ in range(3)]
        [int(g2.add_link([a2, b2])) for _ in range(3)]
        g1.remove(fresh1[0])
        g2.remove(int(fresh1[0]))  # same handle space by construction
        r1 = rt1.submit_pattern([a, b]).result(timeout=120)
        r2 = rt2.submit_pattern([a2, b2]).result(timeout=120)
        _assert_same(r1, r2)
        truth = sorted(int(h) for h in g1.find_all(
            c.And(c.Incident(a), c.Incident(b))))
        assert r1.count == len(truth)
        assert sorted(int(m) for m in r1.matches) == truth[:16]
    finally:
        rt1.close()
        rt2.close()
        g1.close()
        g2.close()


# ---------------------------------------------------------------- joins


def test_sharded_join_matches_single_chip_and_host():
    from hypergraphdb_tpu.join.host import host_join
    from hypergraphdb_tpu.join.ir import extract_pattern

    (g1, (nodes1, _), rt1), (g2, (nodes2, _), rt2) = \
        _pair(_build(seed=13, n_links=400))
    try:
        spec = lambda a: {"y": c.CoIncident(a), "z": c.CoIncident(var("y"))}
        for i in range(6):
            a1, a2 = int(nodes1[i]), int(nodes2[i])
            r1 = rt1.submit_join(spec(a1)).result(timeout=300)
            r2 = rt2.submit_join(spec(a2)).result(timeout=300)
            assert r1.count == r2.count
            assert r1.truncated == r2.truncated
            np.testing.assert_array_equal(r1.tuples, r2.tuples)
            truth = host_join(g1, extract_pattern(g1, spec(a1)))
            assert r1.count == len(truth)
            got = [tuple(int(v) for v in row) for row in r1.tuples]
            assert got == truth[:16]
        assert rt1.stats.sharded_dispatches > 0
    finally:
        rt1.close()
        rt2.close()
        g1.close()
        g2.close()


# ---------------------------------------------------------------- routing


def test_executor_pick_forced_and_auto():
    g = HyperGraph()
    make_random_hypergraph(g, n_nodes=40, n_links=60, seed=1)
    try:
        rt = ServeRuntime(g, _cfg(sharded=False))
        assert type(rt.executor) is DeviceExecutor
        rt.close()
        # AUTO: a 1-byte budget means any snapshot overflows one chip
        rt = ServeRuntime(g, _cfg(sharded=None, hbm_budget_bytes=1))
        assert isinstance(rt.executor, ShardedExecutor)
        rt.close()
        # AUTO with a huge budget stays single-chip
        rt = ServeRuntime(g, _cfg(sharded=None,
                                  hbm_budget_bytes=1 << 40))
        assert type(rt.executor) is DeviceExecutor
        rt.close()
    finally:
        g.close()


def test_sharded_prewarm_hits_aot_cache(tmp_path):
    """Satellite: a fresh pod over a populated cache reaches first
    sharded dispatch with ZERO compiles — every prewarmed sharded bucket
    program loads from disk."""
    def build():
        g = HyperGraph()
        make_random_hypergraph(g, n_nodes=80, n_links=160, seed=2)
        return g

    cfg = _cfg(sharded=True, buckets=(16,), prewarm_aot=True,
               aot_cache_dir=str(tmp_path), prewarm_pattern_arities=(2,))
    g = build()
    rt = ServeRuntime(g, cfg)
    first = rt.stats_snapshot()["aot"]
    assert first["puts"] >= 2          # bfs + pattern sharded programs
    rt.close()
    g.close()

    g = build()
    rt = ServeRuntime(g, cfg)
    warm = rt.stats_snapshot()["aot"]
    assert warm["misses"] == 0, warm
    assert warm["disk_hits"] >= 2, warm
    rt.close()
    g.close()


def test_healthz_advertises_mesh_and_partition_map():
    from hypergraphdb_tpu.obs.http import runtime_health

    g = HyperGraph()
    make_random_hypergraph(g, n_nodes=60, n_links=100, seed=4)
    rt = ServeRuntime(g, _cfg(sharded=True))
    try:
        rt.submit_bfs(3, max_hops=1).result(timeout=120)  # builds the shard
        healthy, payload = runtime_health(rt)()
        assert healthy
        mesh = payload["mesh"]
        assert mesh["devices"] == 8
        assert mesh["axis"] == "shard"
        pm = mesh["partition_map"]
        assert pm["n_parts"] == 8
        assert len(pm["ranges"]) == 8
        assert len(mesh["shards"]) == 8
        assert mesh["shards"][0]["gid_lo"] == 0
    finally:
        rt.close()
        g.close()


def test_front_door_places_by_shard_ownership():
    """A backend whose advertised partition map covers the request's ids
    wins placement over a fresher one that does not."""
    from hypergraphdb_tpu.replica.router import FrontDoor, RouterConfig

    class FakeBackend:
        def __init__(self, bid, capacity, lag):
            self.id = bid
            self.capacity = capacity
            self.lag = lag
            self.served = 0

        def submit(self, payload, timeout):
            self.served += 1
            return {"kind": payload["kind"], "count": 0, "matches": [],
                    "truncated": False, "epoch": 0, "served_by": "device"}

        def health(self):
            return True, {
                "replication_lag": self.lag, "queue_depth": 0,
                "breaker_worst": 0,
                "mesh": {"partition_map": {"capacity": self.capacity}},
            }

    small = FakeBackend("small-pod", capacity=100, lag=0)   # fresher
    big = FakeBackend("big-pod", capacity=10_000, lag=5)    # covers more
    primary = FakeBackend("primary", capacity=None, lag=0)
    door = FrontDoor(primary, [small, big],
                     RouterConfig(poll_interval_s=0))
    # seed beyond the small pod's coverage → the big pod owns it,
    # despite its worse lag
    res = door.submit({"kind": "bfs", "seed": 5000, "max_hops": 1})
    assert res["routed_to"] == "big-pod"
    # seed INSIDE both coverages → freshness wins again
    res = door.submit({"kind": "bfs", "seed": 7, "max_hops": 1})
    assert res["routed_to"] == "small-pod"
    # the router's own healthz surfaces the advertised coverage
    _, payload = door.health_probe()()
    assert payload["backends"]["small-pod"]["gid_capacity"] == 100
    assert payload["backends"]["big-pod"]["gid_capacity"] == 10_000
    door.stop()


def test_sharded_view_refreshes_across_compaction():
    """A compaction swap re-shards the base; the sharded pinned view
    must keep answering exactly (epoch re-check loop)."""
    g = HyperGraph()
    nodes, links = make_random_hypergraph(g, n_nodes=80, n_links=150,
                                          seed=6)
    rt = ServeRuntime(g, _cfg(sharded=True))
    try:
        r_before = rt.submit_bfs(int(nodes[1]), max_hops=2).result(
            timeout=120)
        epoch_before = r_before.epoch
        mgr = rt.executor.mgr
        # force a compaction by flooding the memtable past the ratio
        for i in range(40):
            g.add_link([nodes[i % 20], nodes[(i + 1) % 20]])
        mgr._request_compact()
        mgr.wait_compacted(timeout=30)
        r_after = rt.submit_bfs(int(nodes[1]), max_hops=2).result(
            timeout=120)
        assert r_after.epoch > epoch_before
        truth = set(int(h) for h in g.find_all(
            c.BFS(int(nodes[1]), max_distance=2))) | {int(nodes[1])}
        assert r_after.count == len(truth)
        assert r_after.served_by == "device"
    finally:
        rt.close()
        g.close()
