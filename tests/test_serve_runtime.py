"""Deterministic serving-runtime tests: injectable clock + fake executor.

Every test here drives the admission/batching/dispatch machinery with
``ServeConfig(manual=True)`` (no thread), a :class:`FakeClock`, and a
:class:`FakeExecutor` — deadline shedding, backpressure, flush policy,
double-buffer ordering, and drains are exactly reproducible with zero
device work. The real device path is covered by
``test_serve_differential.py``; the threaded soak runs under ``slow``.
"""

from __future__ import annotations

import threading

import numpy as np
import pytest

from hypergraphdb_tpu.serve import (
    Batcher,
    DeadlineExceeded,
    QueueFull,
    RuntimeClosed,
    ServeConfig,
    ServeResult,
    ServeRuntime,
    bucket_for,
)
from hypergraphdb_tpu.serve.types import BFSRequest, PatternRequest, Ticket


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


class FakeExecutor:
    """Records launch/collect ordering; completes every ticket with a
    stub result."""

    def __init__(self):
        self.events: list[tuple] = []
        self.batches: list = []

    def launch(self, batch):
        self.events.append(("launch", len(self.batches)))
        self.batches.append(batch)
        return (len(self.batches) - 1, batch)

    def collect(self, token):
        idx, batch = token
        self.events.append(("collect", idx))
        return [
            (t, ServeResult(t.request.kind, 0,
                            np.empty(0, dtype=np.int64), False, 0, "fake"))
            for t in batch.tickets
        ]


def make_runtime(clock=None, buckets=(4, 16), max_queue=64,
                 policy="block", linger=0.010, **kw):
    cfg = ServeConfig(buckets=buckets, max_queue=max_queue, policy=policy,
                      max_linger_s=linger, clock=clock or FakeClock(),
                      manual=True, **kw)
    ex = FakeExecutor()
    return ServeRuntime(graph=None, config=cfg, executor=ex), ex, cfg.clock


# ---------------------------------------------------------------- buckets


def test_bucket_for_picks_smallest_fitting():
    assert bucket_for(1, (64, 256, 1024)) == 64
    assert bucket_for(64, (64, 256, 1024)) == 64
    assert bucket_for(65, (64, 256, 1024)) == 256
    assert bucket_for(1024, (64, 256, 1024)) == 1024
    with pytest.raises(ValueError):
        bucket_for(1025, (64, 256, 1024))


# ---------------------------------------------------------------- deadlines


def test_deadline_expiry_sheds_before_dispatch():
    rt, ex, clock = make_runtime()
    fut = rt.submit_bfs(1, max_hops=2, deadline_s=0.5)
    clock.advance(1.0)  # expire in the queue
    assert rt.step(drain=True) is False  # shed, nothing left to dispatch
    with pytest.raises(DeadlineExceeded):
        fut.result(timeout=0)
    assert ex.batches == []  # the dead request never cost a dispatch
    assert rt.stats.shed_deadline == 1
    assert rt.stats.batches == 0


def test_expired_requests_shed_live_ones_dispatch():
    rt, ex, clock = make_runtime()
    dead = rt.submit_bfs(1, deadline_s=0.5)
    live = rt.submit_bfs(2, deadline_s=10.0)
    clock.advance(1.0)
    assert rt.step(drain=True) is True
    with pytest.raises(DeadlineExceeded):
        dead.result(timeout=0)
    assert live.result(timeout=0).kind == "bfs"
    (batch,) = ex.batches
    assert [t.request.seed for t in batch.tickets] == [2]


def test_already_expired_submit_sheds_immediately():
    rt, ex, clock = make_runtime(policy="block", max_queue=1)
    rt.submit_bfs(1)  # fill the queue
    fut = rt.submit_bfs(2, deadline_s=0.0)  # would block; already expired
    with pytest.raises(DeadlineExceeded):
        fut.result(timeout=0)
    assert rt.queue.depth() == 1  # the shed request never entered
    # accounting identity: submitted == completed + shed + cancelled + live
    assert rt.stats.submitted == 2
    assert rt.stats.shed_deadline == 1


def test_serve_result_eq_and_hash_do_not_raise():
    r1 = ServeResult("bfs", 2, np.asarray([1, 2]), False, 0)
    r2 = ServeResult("bfs", 2, np.asarray([1, 2]), False, 0)
    assert (r1 == r2) is False      # identity eq — never elementwise
    assert r1 == r1
    assert isinstance(hash(r1), int)
    assert len({r1, r2}) == 2


# ---------------------------------------------------------------- backpressure


def test_fail_fast_policy_raises_queue_full():
    rt, ex, _ = make_runtime(policy="fail", max_queue=2)
    rt.submit_bfs(1)
    rt.submit_bfs(2)
    with pytest.raises(QueueFull):
        rt.submit_bfs(3)
    assert rt.stats.rejected_queue_full == 1
    assert rt.stats.submitted == 2


def test_block_policy_blocks_until_space():
    rt, ex, clock = make_runtime(policy="block", max_queue=1, linger=0.0)
    rt.submit_bfs(1)
    admitted = threading.Event()

    def submit_second():
        rt.submit_bfs(2)
        admitted.set()

    t = threading.Thread(target=submit_second, daemon=True)
    t.start()
    assert not admitted.wait(0.15)  # genuinely blocked on the full queue
    assert rt.step(drain=True)      # drain frees a slot
    assert admitted.wait(2.0)       # blocked submit completes
    t.join(2.0)
    assert rt.queue.depth() == 1


# ---------------------------------------------------------------- flush policy


def test_flush_on_batch_full_ignores_linger():
    rt, ex, clock = make_runtime(linger=1e9)  # linger can never expire
    futs = [rt.submit_bfs(i) for i in range(16)]  # == largest bucket
    assert rt.step() is True
    (batch,) = ex.batches
    assert batch.bucket == 16 and len(batch.tickets) == 16
    assert all(f.result(timeout=0).kind == "bfs" for f in futs)
    assert rt.stats.batches == 1


def test_no_flush_before_linger_then_flush_after():
    rt, ex, clock = make_runtime(linger=0.010)
    fut = rt.submit_bfs(7)
    assert rt.step() is False           # neither full nor lingered
    assert ex.batches == []
    clock.advance(0.011)
    assert rt.step() is True            # linger expired → flush partial
    (batch,) = ex.batches
    assert batch.bucket == 4            # padded to the SMALLEST fitting bucket
    assert len(batch.tickets) == 1
    assert fut.result(timeout=0).served_by == "fake"
    assert rt.stats.snapshot()["batch_occupancy"] == pytest.approx(0.25)


def test_batches_group_by_key_oldest_first():
    rt, ex, clock = make_runtime(linger=0.0)
    b1 = rt.submit_bfs(1, max_hops=2)
    p1 = rt.submit_pattern([1, 2])
    b2 = rt.submit_bfs(2, max_hops=2)
    b3 = rt.submit_bfs(3, max_hops=3)   # different statics → different key
    assert rt.step() is True
    assert rt.step() is True
    assert rt.step() is True
    assert rt.step() is False
    keys = [b.key for b in ex.batches]
    # oldest ticket defines each flushed group; FIFO across keys
    assert keys == [("bfs", 2), ("pattern", 2), ("bfs", 3)]
    assert [t.request.seed for t in ex.batches[0].tickets] == [1, 2]
    for f in (b1, p1, b2, b3):
        assert f.result(timeout=0) is not None


# ---------------------------------------------------------------- pipelining


def test_pump_launches_next_before_collecting_previous():
    rt, ex, clock = make_runtime(linger=0.0)
    rt.submit_bfs(1)
    assert rt.pump() is True            # launch B0, nothing to collect yet
    rt.submit_bfs(2)
    assert rt.pump() is True            # launch B1 THEN collect B0
    rt.pump()                           # nothing new: collect B1
    assert ex.events == [
        ("launch", 0), ("launch", 1), ("collect", 0), ("collect", 1),
    ]


# ---------------------------------------------------------------- shutdown


def test_close_drains_queued_and_inflight():
    rt, ex, clock = make_runtime(linger=1e9)
    futs = [rt.submit_bfs(i) for i in range(6)]
    rt.submit_pattern([1, 2])
    rt.pump(drain=True)                 # leave one batch in flight
    rt.close(drain=True)
    for f in futs:
        assert f.result(timeout=0).served_by == "fake"
    assert rt.stats.completed == 7
    with pytest.raises(RuntimeClosed):
        rt.submit_bfs(99)


def test_close_without_drain_cancels_queued():
    rt, ex, clock = make_runtime(linger=1e9)
    futs = [rt.submit_bfs(i) for i in range(3)]
    rt.close(drain=False)
    for f in futs:
        with pytest.raises(RuntimeClosed):
            f.result(timeout=0)
    assert rt.stats.cancelled == 3
    assert ex.batches == []


def test_context_manager_drains():
    clock = FakeClock()
    cfg = ServeConfig(buckets=(4,), clock=clock, manual=True,
                      max_linger_s=1e9)
    ex = FakeExecutor()
    with ServeRuntime(graph=None, config=cfg, executor=ex) as rt:
        fut = rt.submit_bfs(1)
    assert fut.result(timeout=0).kind == "bfs"


# ---------------------------------------------------------------- stats


def test_stats_surface_shape():
    rt, ex, clock = make_runtime(linger=0.0)
    rt.submit_bfs(1)
    clock.advance(0.004)
    rt.step(drain=True)
    snap = rt.stats_snapshot()
    assert snap["submitted"] == 1 and snap["completed"] == 1
    assert snap["queue_depth"] == 0
    assert snap["batches"] == 1
    assert snap["latency_ms"]["p50"] == pytest.approx(4.0)
    assert snap["latency_ms"]["p99"] == pytest.approx(4.0)
    assert snap["batch_occupancy"] == pytest.approx(0.25)


# ---------------------------------------------------------------- requests


def test_pattern_request_validation():
    from hypergraphdb_tpu.serve.types import Unservable

    with pytest.raises(Unservable):
        PatternRequest(())
    assert PatternRequest((np.int64(3), 4)).anchors == (3, 4)
    assert BFSRequest(1, 2).batch_key != BFSRequest(1, 3).batch_key
    assert PatternRequest((1, 2)).batch_key == PatternRequest((9, 8)).batch_key
    assert PatternRequest((1, 2)).batch_key != PatternRequest((1, 2, 3)).batch_key


def test_batcher_rejects_bad_buckets():
    from hypergraphdb_tpu.serve import AdmissionQueue

    q = AdmissionQueue(4)
    with pytest.raises(ValueError):
        Batcher(q, buckets=(16, 4))  # unsorted
    with pytest.raises(ValueError):
        AdmissionQueue(4, policy="bogus")


# ------------------------------------------------------- review regressions


def test_cancelled_future_does_not_poison_dispatch():
    """A caller cancel()ing a pending future must not raise out of the
    dispatch path (InvalidStateError) or count as a completion."""
    rt, ex, clock = make_runtime(linger=0.0)
    f1 = rt.submit_bfs(1)
    f2 = rt.submit_bfs(2)
    assert f1.cancel()
    assert rt.step(drain=True) is True   # no exception escapes
    assert f2.result(timeout=0).kind == "bfs"
    assert rt.stats.completed == 1       # the cancelled one is not counted
    f3 = rt.submit_bfs(3)                # runtime still serves
    rt.step(drain=True)
    assert f3.result(timeout=0).kind == "bfs"


class ExplodingExecutor(FakeExecutor):
    """Fails the FIRST launch, then behaves."""

    def __init__(self):
        super().__init__()
        self.exploded = False

    def launch(self, batch):
        if not self.exploded:
            self.exploded = True
            raise RuntimeError("device fell over")
        return super().launch(batch)


def test_executor_launch_error_fails_tickets_not_runtime():
    clock = FakeClock()
    cfg = ServeConfig(buckets=(4,), clock=clock, manual=True,
                      max_linger_s=0.0)
    ex = ExplodingExecutor()
    rt = ServeRuntime(graph=None, config=cfg, executor=ex)
    f1 = rt.submit_bfs(1)
    assert rt.step(drain=True) is True
    with pytest.raises(RuntimeError, match="device fell over"):
        f1.result(timeout=0)
    f2 = rt.submit_bfs(2)                # the next batch serves normally
    rt.step(drain=True)
    assert f2.result(timeout=0).kind == "bfs"
    rt.close()
