"""Tier-1 gate + precision pins for the hgverify jaxpr-level verifier.

Three jobs:

1. precision against the fixture registries — every seedable HV rule
   fires on ``hgverify_fixtures.entries.build_bad_registry()`` and the
   clean twins stay silent (HV104 needs the removed legacy host_callback
   staging and is pinned by rule-table presence only);
2. the ``costs.json`` lifecycle: uncovered -> HV402, ``--update-costs``
   covers, drift -> HV401, stale -> HV403;
3. the repo gate: every registered production entry traces, the
   committed budgets cover all of them, and the full verify + concordance
   run clean — a PR that sneaks a callback into a jitted hot path or
   doubles an op's footprint fails tier-1.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from hgverify_fixtures.entries import (  # noqa: E402
    build_bad_registry,
    build_clean_registry,
)
from tools.hgverify import (  # noqa: E402
    RULES,
    load_costs,
    parse_only,
    run_verify,
)
from tools.hgverify import concord as concord_mod  # noqa: E402
from tools.hgverify.engine import build_report  # noqa: E402
from tools.hgverify.harvest import COST_METRICS  # noqa: E402

COSTS = REPO / "tools" / "hgverify" / "costs.json"


def _rules(findings):
    return {f.rule for f in findings}


# ------------------------------------------------------------ fixture gate


@pytest.fixture(scope="module")
def bad_run(tmp_path_factory):
    costs = tmp_path_factory.mktemp("hv") / "costs.json"
    return run_verify(registry=build_bad_registry(), costs_path=str(costs))


def test_bad_registry_fires_every_family(bad_run):
    findings, meta = bad_run
    rules = _rules(findings)
    # family 1: trace failure + every constructible callback flavor
    # (HV104's legacy host_callback staging cannot be built on this jax)
    assert {"HV100", "HV101", "HV102", "HV103"} <= rules
    # family 2: declared-mesh ghost axis, cond divergence, missing mesh
    assert {"HV201", "HV202", "HV203"} <= rules
    # family 3: unusable donation, double-aliased donation, lost donation
    assert {"HV301", "HV302", "HV303"} <= rules
    # family 4: a fresh costs file leaves every fixture entry uncovered
    assert "HV402" in rules


def test_bad_findings_anchor_to_entries(bad_run):
    findings, _ = bad_run
    by_scope = {f.scope for f in findings}
    assert "fix.pure_cb" in by_scope and "fix.donate_twice" in by_scope
    for f in findings:
        if f.rule != "HV403":
            assert f.path.endswith("entries.py")
            assert f.line > 0


def test_clean_registry_is_silent_once_covered(tmp_path):
    costs = tmp_path / "costs.json"
    _, _ = run_verify(registry=build_clean_registry(),
                      costs_path=str(costs), update_costs=True)
    findings, meta = run_verify(registry=build_clean_registry(),
                                costs_path=str(costs))
    assert findings == [], "\n".join(f.render() for f in findings)
    assert meta["traced"] == meta["registered"]


# ------------------------------------------------------- costs lifecycle


def test_costs_lifecycle(tmp_path):
    costs = tmp_path / "costs.json"
    reg = build_clean_registry

    # 1. fresh entries have no budget -> HV402 uncovered warnings
    findings, _ = run_verify(registry=reg(), costs_path=str(costs))
    assert _rules(findings) == {"HV402"}

    # 2. --update-costs writes budgets; the gate goes quiet
    run_verify(registry=reg(), costs_path=str(costs), update_costs=True)
    budgets = load_costs(str(costs))
    assert set(budgets) == {e.name for e in reg()}
    assert all(set(b) == set(COST_METRICS) for b in budgets.values())
    findings, _ = run_verify(registry=reg(), costs_path=str(costs))
    assert findings == []

    # 3. drift beyond tolerance -> HV401 names the metric and direction
    data = json.loads(costs.read_text())
    data["entries"]["fix.cost_probe"]["flops"] *= 3
    costs.write_text(json.dumps(data))
    findings, _ = run_verify(registry=reg(), costs_path=str(costs))
    hits = [f for f in findings if f.rule == "HV401"]
    assert len(hits) == 1 and hits[0].scope == "fix.cost_probe"
    assert "flops" in hits[0].message and "shrank" in hits[0].message

    # 4. a generous tolerance accepts the same drift
    findings, _ = run_verify(registry=reg(), costs_path=str(costs),
                             tolerance=5.0)
    assert [f for f in findings if f.rule == "HV401"] == []

    # 5. stale budget (no live entry) fails the gate like hglint
    #    baseline staleness
    data["entries"]["fix.cost_probe"]["flops"] //= 3
    data["entries"]["fix.removed_entry"] = {
        "flops": 1, "bytes_accessed": 1, "temp_bytes": 0
    }
    costs.write_text(json.dumps(data))
    findings, _ = run_verify(registry=reg(), costs_path=str(costs))
    stale = [f for f in findings if f.rule == "HV403"]
    assert len(stale) == 1 and stale[0].scope == "fix.removed_entry"
    assert stale[0].severity == "error"

    # 6. --update-costs prunes the stale entry: the loop closes
    run_verify(registry=reg(), costs_path=str(costs), update_costs=True)
    assert "fix.removed_entry" not in load_costs(str(costs))


def test_costs_file_tolerance_is_honored(tmp_path):
    """The tolerance committed IN costs.json is the default gate width;
    an explicit --tolerance still wins."""
    costs = tmp_path / "costs.json"
    reg = build_clean_registry
    run_verify(registry=reg(), costs_path=str(costs), update_costs=True)
    data = json.loads(costs.read_text())
    data["entries"]["fix.cost_probe"]["flops"] *= 2
    data["tolerance"] = 5.0
    costs.write_text(json.dumps(data))
    findings, meta = run_verify(registry=reg(), costs_path=str(costs))
    assert [f for f in findings if f.rule == "HV401"] == []
    assert meta["tolerance"] == 5.0
    findings, _ = run_verify(registry=reg(), costs_path=str(costs),
                             tolerance=0.15)
    assert [f for f in findings if f.rule == "HV401"]


def test_family_filter_never_corrupts_concordance(tmp_path):
    """--only narrows the REPORT; meta['all_findings'] (what --concord
    cross-tabulates) keeps the full ground truth."""
    costs = tmp_path / "costs.json"
    findings, meta = run_verify(registry=build_bad_registry(),
                                costs_path=str(costs), only="HV4")
    visible = {f.rule for f in findings}
    full = {f.rule for f in meta["all_findings"]}
    assert "HV101" not in visible
    assert {"HV101", "HV201", "HV302"} <= full


# ------------------------------------------------------------- repo gate


@pytest.fixture(scope="module")
def production_run(tmp_path_factory):
    os.chdir(REPO)   # finding paths and costs default are repo-relative
    return run_verify()


def test_production_entries_all_trace(production_run):
    findings, meta = production_run
    assert meta["registered"] >= 10, "entry registry shrank below floor"
    assert meta["traced"] == meta["registered"], (
        "entries failed to trace:\n"
        + "\n".join(f.render() for f in findings if f.rule == "HV100")
    )


def test_production_gate_is_clean(production_run):
    findings, _ = production_run
    assert findings == [], (
        "hgverify findings on the production entries (fix them or, for "
        "accepted cost changes, regenerate budgets via `python -m "
        "tools.hgverify --update-costs`):\n"
        + "\n".join(f.render() for f in findings)
    )


def test_costs_json_covers_every_entry(production_run):
    _, meta = production_run
    budgets = load_costs(str(COSTS))
    live = {t.entry.name for t in meta["traces"]}
    assert budgets, "committed costs.json is missing or empty"
    assert live - set(budgets) == set(), "uncovered entries"
    assert set(budgets) - live == set(), "stale budget entries"
    donated = [t.entry.name for t in meta["traces"] if t.entry.donate]
    assert "ops.ellbfs._visited_update" in donated


def test_concordance_runs_cleanly(production_run):
    findings, meta = production_run
    table = concord_mod.concord(meta["traces"], findings,
                                ["hypergraphdb_tpu"])
    assert table["rows"], "concordance produced no (entry, family) rows"
    verdicts = {r["verdict"] for r in table["rows"]}
    assert verdicts <= {"agree_clean", "agree_flagged",
                        "hglint_false_negative", "hglint_only"}
    assert concord_mod.render(table).startswith("hgverify concordance")


def test_committed_concord_record_is_not_stale(production_run):
    """The ROADMAP maintenance invariant: the committed concord record
    (``tools/hgverify/concord.json``) must match a live re-mine — a PR
    that adds a kernel with callbacks/collectives/donation has to re-run
    ``python -m tools.hgverify --concord`` and commit the new record."""
    findings, meta = production_run
    record = json.loads(
        (REPO / "tools" / "hgverify" / "concord.json").read_text()
    )
    live = concord_mod.concord(meta["traces"], findings,
                               ["hypergraphdb_tpu"])
    assert record["concordance"]["summary"] == live["summary"], (
        "committed concord record is stale — re-run "
        "`python -m tools.hgverify --concord --output json` and refresh "
        "tools/hgverify/concord.json"
    )
    assert record["entries"]["traced"] == len(meta["traces"])
    # zero AST-layer blind spots on the committed kernel surface
    assert "hglint_false_negative" not in record["concordance"]["summary"]


def test_report_shape_matches_hglint_envelope(production_run):
    findings, meta = production_run
    report = build_report(findings, meta)
    assert report["tool"] == "hgverify"
    assert report["report_version"] == 2
    # the keys CI consumers share with hglint's report
    assert {"counts", "findings", "only"} <= set(report)
    assert set(report["counts"]) == {"total", "by_rule", "by_severity"}
    bad, _ = run_verify(registry=build_bad_registry(),
                        costs_path=str(COSTS))
    rep2 = build_report(bad, meta)
    assert all({"rule", "severity", "path", "line", "scope", "message",
                "doc"} <= set(f) for f in rep2["findings"])
    assert any(f["doc"].startswith("README.md#hv") for f in rep2["findings"])


# ---------------------------------------------------------------- filters


def test_only_family_filter(tmp_path):
    costs = tmp_path / "costs.json"
    findings, _ = run_verify(registry=build_bad_registry(),
                             costs_path=str(costs), only="HV3")
    rules = _rules(findings)
    assert {"HV301", "HV302", "HV303"} <= rules
    # HV100 always surfaces (broken ground truth must never hide) but the
    # other families are filtered out
    assert rules - {"HV301", "HV302", "HV303", "HV100"} == set()


def test_only_typo_refuses_silent_green():
    with pytest.raises(ValueError, match="matches no known rule"):
        parse_only("HV9")
    with pytest.raises(ValueError, match="matches no known rule"):
        parse_only("hv4")   # case-sensitive


def test_rule_registry_consistency(bad_run):
    findings, _ = bad_run
    assert _rules(findings) <= set(RULES)


# ------------------------------------------------------------------- CLI


def test_cli_crash_is_exit_3_not_a_finding(monkeypatch, capsys):
    """The lint.sh/verify.sh contract: an analyzer bug exits 3 with a
    traceback, never masquerading as '1 finding'."""
    from tools.hgverify import __main__ as cli
    from tools.hgverify import engine

    def boom(**kw):
        raise RuntimeError("injected analyzer bug")

    monkeypatch.setattr(engine, "run_verify", boom)
    rc = cli.main([])
    assert rc == 3
    err = capsys.readouterr().err
    assert "injected analyzer bug" in err
    assert "internal analyzer crash" in err


def test_cli_usage_error_exit_2():
    env = dict(os.environ)
    env.setdefault("PYTHONPATH", str(REPO))
    out = subprocess.run(
        [sys.executable, "-m", "tools.hgverify", "--only", "HV9"],
        cwd=REPO, capture_output=True, text=True, env=env,
    )
    assert out.returncode == 2
    assert "matches no known rule" in out.stderr


@pytest.mark.slow
def test_cli_end_to_end_json():
    env = dict(os.environ)
    env.setdefault("PYTHONPATH", str(REPO))
    out = subprocess.run(
        [sys.executable, "-m", "tools.hgverify", "--output", "json"],
        cwd=REPO, capture_output=True, text=True, env=env,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    report = json.loads(out.stdout)
    assert report["tool"] == "hgverify"
    assert report["entries"]["traced"] >= 10
    assert report["counts"]["total"] == 0
