"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Multi-chip hardware is not available in CI; sharding correctness is tested
on ``xla_force_host_platform_device_count=8`` CPU devices (the driver
separately dry-runs the multi-chip path via ``__graft_entry__.dryrun_multichip``).
Must run before the first ``import jax`` anywhere in the test process.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The image's sitecustomize imports jax (registering the TPU backend) before
# this conftest runs, so the env vars above are too late for jax.config —
# override the already-imported config directly. Backend init is lazy, so
# this still takes effect as long as no test touched jax.devices() yet.
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: excluded from the tier-1 gate (`-m 'not slow'`); "
        "full-fidelity end-to-end runs",
    )


@pytest.fixture
def graph():
    from hypergraphdb_tpu import HyperGraph

    g = HyperGraph()
    yield g
    g.close()


@pytest.fixture
def rng():
    return np.random.default_rng(42)


def make_random_hypergraph(g, n_nodes=200, n_links=400, max_arity=4, seed=0,
                           n_types=3):
    """Shared fixture-builder: random nodes + random typed links; returns
    (node_handles, link_handles)."""
    r = np.random.default_rng(seed)
    nodes = list(g.add_nodes_bulk([f"n{i}" for i in range(n_nodes)]))
    links = []
    for i in range(n_links):
        arity = int(r.integers(1, max_arity + 1))
        ts = r.choice(nodes, size=arity, replace=False)
        links.append(g.add_link([int(t) for t in ts], value=i))
    return nodes, links
