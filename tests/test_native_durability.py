"""Durability tests for the native C++ backend.

Models the reference's crash-recovery coverage (``testcore`` ``AbruptExit``
kill-process test + BDB log replay on open, SURVEY §4/§5): state written
before an abrupt process death must be fully visible after reopen; a torn
WAL tail must be truncated, not poison the store.
"""

import os
import subprocess
import sys
import textwrap

import pytest

pytest.importorskip("hypergraphdb_tpu.storage.native")

from hypergraphdb_tpu.storage.native import NativeStorage


def _parse_wal_v2(raw):
    """Parse v2 WAL frames: yields (offset, seq, op, payload)."""
    pos = 4  # skip magic
    while pos + 13 <= len(raw):
        ln = int.from_bytes(raw[pos:pos + 4], "little")
        seq = int.from_bytes(raw[pos + 8:pos + 12], "little")
        op = raw[pos + 12]
        payload = raw[pos + 13:pos + 12 + ln]
        yield pos, seq, op, payload
        pos += 12 + ln


def test_reopen_sees_committed_state(tmp_path):
    loc = str(tmp_path / "db")
    s = NativeStorage(loc)
    s.startup()
    s.store_link(1, (10, 20))
    s.store_data(2, b"payload")
    s.add_incidence_link(10, 1)
    s.get_index("by-name").add_entry(b"k", 7)
    s.shutdown()

    s2 = NativeStorage(loc)
    s2.startup()
    assert s2.get_link(1) == (10, 20)
    assert s2.get_data(2) == b"payload"
    assert s2.get_incidence_set(10).array().tolist() == [1]
    assert s2.get_index("by-name").find(b"k").array().tolist() == [7]
    assert s2.max_handle() >= 21
    s2.shutdown()


def test_abrupt_exit_recovery(tmp_path):
    """Write in a subprocess that dies via os._exit (no shutdown/flush of
    Python state); everything written must survive."""
    loc = str(tmp_path / "db")
    code = textwrap.dedent(f"""
        import os, sys
        sys.path.insert(0, {str(os.getcwd())!r})
        from hypergraphdb_tpu.storage.native import NativeStorage
        s = NativeStorage({loc!r})
        s.startup()
        for i in range(500):
            s.store_link(i, (i + 1000, i + 2000))
            s.add_incidence_link(i + 1000, i)
        s.get_index("idx").add_entry(b"key", 42)
        os._exit(9)  # abrupt: no shutdown, no atexit
    """)
    proc = subprocess.run([sys.executable, "-c", code], cwd=os.getcwd())
    assert proc.returncode == 9

    s = NativeStorage(loc)
    s.startup()
    assert s.get_link(499) == (1499, 2499)
    assert s.get_incidence_set(1499).array().tolist() == [499]
    assert s.get_index("idx").find(b"key").array().tolist() == [42]
    s.shutdown()


def test_checkpoint_compacts_and_survives(tmp_path):
    loc = str(tmp_path / "db")
    s = NativeStorage(loc)
    s.startup()
    for i in range(100):
        s.store_link(i, (i + 100,))
    s.checkpoint()
    # truncated to just the 4-byte v2 magic
    assert os.path.getsize(os.path.join(loc, "wal.log")) == 4
    s.store_link(777, (1, 2, 3))  # post-checkpoint delta goes to fresh WAL
    s.shutdown()

    s2 = NativeStorage(loc)
    s2.startup()
    assert s2.get_link(50) == (150,)
    assert s2.get_link(777) == (1, 2, 3)
    s2.shutdown()


def test_torn_wal_tail_truncated(tmp_path):
    loc = str(tmp_path / "db")
    s = NativeStorage(loc)
    s.startup()
    s.store_link(1, (2, 3))
    s.shutdown()

    # simulate a torn write: garbage partial record at the tail
    wal = os.path.join(loc, "wal.log")
    with open(wal, "ab") as f:
        f.write(b"\xff\xff\xff\x7f\x01partial")

    s2 = NativeStorage(loc)
    s2.startup()
    assert s2.get_link(1) == (2, 3)
    # and the tail was cleaned: store accepts and persists new writes
    s2.store_link(9, (8,))
    s2.shutdown()
    s3 = NativeStorage(loc)
    s3.startup()
    assert s3.get_link(9) == (8,)
    s3.shutdown()


def test_graph_over_native_backend(tmp_path):
    """Full HyperGraph stack over the native backend, reopened."""
    import hypergraphdb_tpu as hg

    loc = str(tmp_path / "gdb")
    cfg = hg.HGConfiguration(store_backend="native", location=loc)
    g = hg.HyperGraph(cfg)
    a = g.add("alpha")
    b = g.add("beta")
    l = g.add_link((a, b), value="rel")
    g.close()

    g2 = hg.HyperGraph(hg.HGConfiguration(store_backend="native", location=loc))
    assert g2.get(a) == "alpha"
    assert g2.get(l).targets == (a, b)
    assert g2.get_incidence_set(a).array().tolist() == [int(l)]
    from hypergraphdb_tpu.query import dsl as q

    assert q.find_all(g2, q.value("beta")) == [int(b)]
    g2.close()


def test_mid_commit_crash_is_atomic(tmp_path):
    """A process dying mid-commit-batch must leave NO partial state: records
    between batch_begin and batch_commit replay all-or-nothing."""
    loc = str(tmp_path / "db")
    code = textwrap.dedent(f"""
        import os, sys
        sys.path.insert(0, {str(os.getcwd())!r})
        from hypergraphdb_tpu.storage.native import NativeStorage
        s = NativeStorage({loc!r})
        s.startup()
        # one complete commit
        s.commit_batch_begin()
        s.store_link(1, (10,))
        s.add_incidence_link(10, 1)
        s.commit_batch_end()
        # one commit cut off mid-flight: link written, incidence NOT
        s.commit_batch_begin()
        s.store_link(2, (20,))
        os._exit(9)
    """)
    proc = subprocess.run([sys.executable, "-c", code], cwd=os.getcwd())
    assert proc.returncode == 9

    s = NativeStorage(loc)
    s.startup()
    assert s.get_link(1) == (10,)
    assert s.get_incidence_set(10).array().tolist() == [1]
    # the unterminated batch must have been discarded entirely
    assert s.get_link(2) is None
    s.shutdown()


def test_graph_commit_is_batched(tmp_path):
    """HyperGraph.add over the native backend groups its writes into one
    WAL commit batch (link + data + incidence + index entries atomic)."""
    import hypergraphdb_tpu as hg

    loc = str(tmp_path / "gdb")
    g = hg.HyperGraph(hg.HGConfiguration(store_backend="native", location=loc))
    a = g.add("x")
    wal = os.path.join(loc, "wal.log")
    raw = open(wal, "rb").read()
    # batch markers present: op 13 (begin) and 14 (commit)
    assert raw[:4] == b"HGW2"
    ops = [op for _, _, op, _ in _parse_wal_v2(raw)]
    assert 13 in ops and 14 in ops
    g.close()


def test_type_atom_protected_across_sessions(tmp_path):
    """A persisted type atom must be unremovable even in a session that
    never (re-)registered its type."""
    import hypergraphdb_tpu as hg
    from dataclasses import dataclass

    @dataclass(frozen=True)
    class Marker:
        tag: str = ""

    loc = str(tmp_path / "gdb")
    g = hg.HyperGraph(hg.HGConfiguration(store_backend="native", location=loc))
    g.add(Marker("m1"))  # auto-registers the record type, creating its atom
    th = int(g.typesystem.handle_of(g.typesystem.infer(Marker("m1")).name))
    g.close()

    g2 = hg.HyperGraph(hg.HGConfiguration(store_backend="native", location=loc))
    # session 2 never touches Marker; the guard must still refuse
    import pytest as _pytest
    with _pytest.raises(hg.HGException):
        g2.remove(th)
    g2.close()


def test_aborted_batch_discarded_on_replay(tmp_path):
    """commit_batch_abort must make the batch invisible after reopen while
    later writes still apply."""
    loc = str(tmp_path / "db")
    s = NativeStorage(loc)
    s.startup()
    s.commit_batch_begin()
    s.store_link(1, (10,))
    s.commit_batch_abort()
    s.store_link(2, (20,))  # standalone write after the abort
    s.shutdown()

    s2 = NativeStorage(loc)
    s2.startup()
    assert s2.get_link(1) is None, "aborted batch leaked into replay"
    assert s2.get_link(2) == (20,)
    s2.shutdown()


def test_wal_crc_detects_bitrot(tmp_path):
    """A flipped byte INSIDE a record body (length still valid) must be
    caught by the per-record CRC32 and the tail truncated at the last good
    record — length-only framing would replay the corrupt record
    (VERDICT r2 / ADVICE: reference's BDB log is checksummed)."""
    loc = str(tmp_path / "db")
    s = NativeStorage(loc)
    s.startup()
    s.store_link(1, (2, 3))
    s.store_link(4, (5, 6))
    s.shutdown()

    wal = os.path.join(loc, "wal.log")
    raw = bytearray(open(wal, "rb").read())
    frames = list(_parse_wal_v2(bytes(raw)))
    assert len(frames) == 2
    # flip one payload byte of the SECOND record
    off = frames[1][0]
    raw[off + 14] ^= 0xFF
    open(wal, "wb").write(bytes(raw))

    s2 = NativeStorage(loc)
    s2.startup()
    assert s2.get_link(1) == (2, 3)   # good prefix survives
    assert s2.get_link(4) is None     # corrupt record NOT replayed
    # the tail was truncated: new writes go through and persist
    s2.store_link(9, (8,))
    s2.shutdown()
    s3 = NativeStorage(loc)
    s3.startup()
    assert s3.get_link(9) == (8,)
    assert s3.get_link(4) is None
    s3.shutdown()


def test_wal_sequence_gap_truncates(tmp_path):
    """A record whose sequence number skips ahead (lost/reordered write)
    ends the valid prefix even if its CRC is self-consistent."""
    loc = str(tmp_path / "db")
    s = NativeStorage(loc)
    s.startup()
    s.store_link(1, (2,))
    s.store_link(3, (4,))
    s.shutdown()

    wal = os.path.join(loc, "wal.log")
    raw = bytearray(open(wal, "rb").read())
    frames = list(_parse_wal_v2(bytes(raw)))
    # drop the FIRST record wholesale: second record's seq=1 arrives when
    # seq=0 is expected
    first_off = frames[0][0]
    second_off = frames[1][0]
    fixed = raw[:first_off] + raw[second_off:]
    open(wal, "wb").write(bytes(fixed))

    s2 = NativeStorage(loc)
    s2.startup()
    assert s2.get_link(1) is None
    assert s2.get_link(3) is None  # seq gap: record not trusted
    s2.shutdown()


def test_wal_seq_continues_after_reopen(tmp_path):
    """Sequence numbers must continue across close/open cycles (a reset
    would make every reopened log look corrupt)."""
    loc = str(tmp_path / "db")
    s = NativeStorage(loc)
    s.startup()
    s.store_link(1, (2,))
    s.shutdown()
    s = NativeStorage(loc)
    s.startup()
    s.store_link(3, (4,))
    s.shutdown()
    raw = open(os.path.join(loc, "wal.log"), "rb").read()
    seqs = [seq for _, seq, _, _ in _parse_wal_v2(raw)]
    assert seqs == list(range(len(seqs)))
    s = NativeStorage(loc)
    s.startup()
    assert s.get_link(1) == (2,) and s.get_link(3) == (4,)
    s.shutdown()
