"""MVCC transaction tests.

Covers the intent of the reference's ``testcore/test/java/hgtest/tx/`` suite:
``BasicTxTests``, ``NestedTxTests``, ``DataTxTests``, ``LinkTxTests``,
``WriteTxTests`` (conflict/retry), ``NoTxTests`` (disabled mode) — SURVEY §4.
"""

import threading

import pytest

from hypergraphdb_tpu import HGConfiguration, HyperGraph, TransactionConflict
from hypergraphdb_tpu.core.errors import TransactionAborted


def test_transact_commits(graph: HyperGraph):
    h = graph.txman.transact(lambda: graph.add("v"))
    assert graph.get(h) == "v"


def test_abort_discards_writes(graph: HyperGraph):
    tx = graph.txman.begin()
    h = graph.add("temp")
    assert graph.get(h) == "temp"  # read-your-writes
    graph.txman.abort(tx)
    graph._atom_cache.clear()
    assert not graph.contains(h)


def test_explicit_exception_rolls_back(graph: HyperGraph):
    before = graph.atom_count()

    def work():
        graph.add("doomed")
        raise RuntimeError("boom")

    with pytest.raises(RuntimeError):
        graph.txman.transact(work)
    graph._atom_cache.clear()
    assert graph.atom_count() == before


def test_nested_commit_merges_into_parent(graph: HyperGraph):
    outer = graph.txman.begin()
    h1 = graph.add("outer")
    inner = graph.txman.begin()
    h2 = graph.add("inner")
    graph.txman.commit(inner)
    assert graph.get(h2) == "inner"  # visible in parent
    graph.txman.commit(outer)
    assert graph.get(h1) == "outer"
    assert graph.get(h2) == "inner"


def test_nested_abort_discards_only_inner(graph: HyperGraph):
    outer = graph.txman.begin()
    h1 = graph.add("outer")
    inner = graph.txman.begin()
    h2 = graph.add("inner")
    graph.txman.abort(inner)
    graph.txman.commit(outer)
    graph._atom_cache.clear()
    assert graph.contains(h1)
    assert not graph.contains(h2)


def test_commit_wrong_order_raises(graph: HyperGraph):
    outer = graph.txman.begin()
    graph.txman.begin()
    with pytest.raises(TransactionAborted):
        graph.txman.commit(outer)
    # clean up
    graph.txman.abort(graph.txman.current())
    graph.txman.abort(outer)


def test_conflict_detected(graph: HyperGraph):
    """Two transactions read the same cell; first commit wins, second
    conflicts (HGTransaction.java:96-108 semantics)."""
    h = graph.add("initial")
    tman = graph.txman

    t1 = tman.begin()
    _ = graph.store.get_link(h)  # read the cell
    graph.replace(h, "t1")

    # a competing commit from another "thread" (simulated inline):
    done = threading.Event()

    def competitor():
        tman.transact(lambda: graph.replace(h, "other"))
        done.set()

    t = threading.Thread(target=competitor)
    t.start()
    t.join()
    assert done.is_set()

    with pytest.raises(TransactionConflict):
        tman.commit(t1)


def test_transact_retries_on_conflict(graph: HyperGraph):
    h = graph.add(0)
    attempts = []

    def bump():
        attempts.append(1)
        v = graph.get(h)
        if len(attempts) == 1:
            # sneak in a competing committed write on first attempt
            def competing():
                graph.txman.transact(lambda: graph.replace(h, 100))

            t = threading.Thread(target=competing)
            t.start()
            t.join()
            graph._atom_cache.clear()
        graph.replace(h, v + 1)

    graph.txman.transact(bump)
    graph._atom_cache.clear()
    assert len(attempts) == 2
    assert graph.get(h) == 101


def test_concurrent_increments_all_land(graph: HyperGraph):
    h = graph.add(0)
    n_threads, per_thread = 8, 10

    def worker():
        for _ in range(per_thread):

            def inc():
                graph._atom_cache.clear()
                v = graph.get(h)
                graph.replace(h, v + 1)

            graph.txman.transact(inc, retries=200)

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    graph._atom_cache.clear()
    assert graph.get(h) == n_threads * per_thread


def test_tx_incidence_overlay(graph: HyperGraph):
    a = graph.add("a")
    tx = graph.txman.begin()
    l = graph.add_link((a,))
    assert l in graph.get_incidence_set(a)  # visible inside tx
    graph.txman.abort(tx)
    assert l not in graph.get_incidence_set(a)


def test_tx_index_overlay(graph: HyperGraph):
    idx = graph.store.get_index("t")
    tx = graph.txman.begin()
    idx.add_entry(b"k", 5)
    assert idx.find(b"k").array().tolist() == [5]
    graph.txman.abort(tx)
    assert len(graph.store.get_index("t").find(b"k")) == 0


def test_non_transactional_mode():
    g = HyperGraph(HGConfiguration(transactional=False))
    h = g.add("direct")
    assert g.get(h) == "direct"
    assert g.txman.transact(lambda: 42) == 42  # passthrough
    g.close()


def test_readonly_tx_records_no_reads(graph: HyperGraph):
    h = graph.add("x")
    tx = graph.txman.begin(readonly=True)
    _ = graph.store.get_link(h)
    assert not tx.read_set
    graph.txman.commit(tx)


def test_stats_counters(graph: HyperGraph):
    before = graph.txman.committed
    graph.txman.transact(lambda: graph.add("x"))
    assert graph.txman.committed == before + 1


# ---------------------------------------------------------------- MVCC snapshots


def test_snapshot_read_sees_begin_time_state(graph):
    """VERDICT r2 item 6 (VBox.java:28 semantics): a writer committing
    mid-transaction must be invisible to an open reader's reads."""
    import threading

    a = graph.add("original")
    l = graph.add_link((a,), value="before")

    tx = graph.txman.begin(readonly=True)
    assert graph.get(l).value == "before"
    inc_before = graph.get_incidence_set(a).array().tolist()

    def writer():
        graph.replace(l, "after")
        graph.add_link((a,), value="late-link")

    t = threading.Thread(target=writer)
    t.start()
    t.join()

    # reads inside the open tx still see the begin-time state
    assert graph.get(l).value == "before"
    assert graph.get_incidence_set(a).array().tolist() == inc_before
    graph.txman.commit(tx)

    # after the tx, the new state is visible
    assert graph.get(l).value == "after"
    assert len(graph.get_incidence_set(a)) == len(inc_before) + 1


def test_snapshot_read_index_and_value_queries(graph):
    import threading

    from hypergraphdb_tpu.query import dsl as q

    graph.add(111)
    tx = graph.txman.begin(readonly=True)
    assert q.find_all(graph, q.value(111)) != []
    assert q.find_all(graph, q.value(222)) == []

    t = threading.Thread(target=lambda: graph.add(222))
    t.start()
    t.join()

    # the by-value index read reconstructs the begin-time membership
    assert q.find_all(graph, q.value(222)) == []
    graph.txman.commit(tx)
    assert q.find_all(graph, q.value(222)) != []


def test_stale_snapshot_write_tx_conflicts(graph):
    """A WRITE tx whose read raced past its snapshot must fail validation
    (it acted on begin-time data that is no longer current)."""
    import threading

    import pytest as _pytest

    from hypergraphdb_tpu.core.errors import TransactionConflict

    a = graph.add("cell")
    tx = graph.txman.begin()
    t = threading.Thread(target=lambda: graph.replace(a, "moved"))
    t.start()
    t.join()
    # this read returns the begin-time value ("cell") — and dooms the tx
    assert graph.get(a) == "cell"
    graph.add("marker")
    with _pytest.raises(TransactionConflict):
        graph.txman.commit(tx)


def test_history_gc(graph):
    """Pre-image chains must drain once no live snapshot needs them."""
    a = graph.add("x")
    tx = graph.txman.begin(readonly=True)
    import threading

    t = threading.Thread(target=lambda: graph.replace(a, "y"))
    t.start()
    t.join()
    assert graph.txman._history  # captured for the open snapshot
    graph.txman.commit(tx)
    # next commit GCs chains below the (now empty) active floor
    graph.add("tick")
    assert graph.txman._history == {}
