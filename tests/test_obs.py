"""hgobs unit tests: span trees, the registry, and the export formats.

Everything here is deterministic — injected fake clocks for traces,
synthetic samples for histograms, and pure-text assertions for the
Prometheus / JSONL wire formats (parsed line-by-line / round-tripped, per
the committed schema).
"""

from __future__ import annotations

import math
import re

import pytest

from hypergraphdb_tpu import obs
from hypergraphdb_tpu.obs.registry import (
    DEFAULT_BOUNDS,
    Histogram,
    Registry,
)
from hypergraphdb_tpu.obs.trace import Tracer


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# ------------------------------------------------------------------ tracing


def make_tracer(**kw):
    clock = FakeClock()
    tr = Tracer(clock=clock, **kw)
    tr.enable()
    return tr, clock


def test_span_tree_parenting_and_durations():
    tracer, clock = make_tracer()
    tr = tracer.start_trace("serve.request", kind="bfs")
    root = tr.start_span("request")
    clock.advance(1.0)
    child = tr.start_span("queue_wait", parent=root)
    clock.advance(2.0)
    child.end()
    grand = tr.start_span("collect", parent=child)
    clock.advance(0.5)
    grand.end()
    root.end()
    tr.finish()

    assert tr.attrs == {"kind": "bfs"}
    assert child.parent_id == root.span_id
    assert grand.parent_id == child.span_id
    assert root.parent_id is None
    assert child.duration == pytest.approx(2.0)
    assert root.duration == pytest.approx(3.5)
    assert tr.children_of(root) == [child]
    assert tr.children_of(None) == [root]
    # nested: every child's window sits inside its parent's
    assert root.t0 <= child.t0 <= child.t1 <= root.t1


def test_span_attributes_typed():
    tracer, _ = make_tracer()
    tr = tracer.start_trace("t")
    sp = tr.start_span("s", bucket=64, n_real=3)
    sp.set(occupancy=0.25, key="bfs", flag=True, nothing=None)
    assert sp.attrs["bucket"] == 64
    assert sp.attrs["occupancy"] == 0.25
    with pytest.raises(TypeError):
        sp.set(bad=[1, 2, 3])  # non-scalar attrs are not exportable


def test_span_budget_overflow_counts_drops():
    tracer, _ = make_tracer(max_spans=4)
    tr = tracer.start_trace("t")
    spans = [tr.start_span(f"s{i}") for i in range(10)]
    assert len(tr.spans()) == 4
    assert tr.dropped == 6
    # overflow spans are real objects — call sites never branch
    spans[-1].end()
    tr.finish()
    assert tr.dropped == 6


def test_off_gate_allocates_nothing():
    tracer = Tracer(clock=FakeClock())
    assert tracer.enabled is False
    assert tracer.start_trace("t") is None
    assert tracer.traces_started == 0
    with tracer.trace_ctx("t") as tr:
        assert tr is None
        with tracer.span("child") as sp:
            assert sp is None
    assert tracer.traces_started == 0
    assert tracer.drain() == []


def test_finish_idempotent_and_retains_once():
    tracer, clock = make_tracer()
    tr = tracer.start_trace("t")
    sp = tr.start_span("open")  # left open: finish closes it
    clock.advance(1.0)
    assert tr.finish() is True
    assert tr.finish() is False
    tracer.finish_trace(tr)  # tolerant second path
    assert sp.t1 == pytest.approx(1.0)
    assert tracer.finished_count() == 1
    assert [t.name for t in tracer.drain()] == ["t"]
    assert tracer.drain() == []  # drain consumes


def test_span_after_finish_is_detached():
    """Cross-thread race hardening: a span started after finish() must
    never mutate the already-retained trace (no forever-open spans in the
    export)."""
    tracer, clock = make_tracer()
    tr = tracer.start_trace("t")
    tr.start_span("before")
    tr.finish()
    late = tr.start_span("late")          # loser of a finish race
    late.end()                            # harmless on the detached span
    assert [s.name for s in tr.spans()] == ["before"]
    assert all(s.t1 is not None for s in tr.spans())
    (done,) = tracer.drain()
    assert done is tr


def test_trace_ctx_implicit_nesting():
    tracer, clock = make_tracer()
    with tracer.trace_ctx("query") as tr:
        with tracer.span("compile"):
            clock.advance(1.0)
            with tracer.span("inner"):
                clock.advance(0.5)
        with tracer.span("plan", plan="IntersectPlan"):
            clock.advance(0.25)
    (done,) = tracer.drain()
    assert done is tr
    names = [s.name for s in done.spans()]
    assert names == ["query", "compile", "inner", "plan"]
    root = done.find("query")
    inner = done.find("inner")
    assert done.find("compile").parent_id == root.span_id
    assert inner.parent_id == done.find("compile").span_id
    assert done.find("plan").attrs == {"plan": "IntersectPlan"}
    assert tracer.current_trace() is None


def test_finished_buffer_is_bounded():
    tracer, _ = make_tracer(max_finished=3)
    for i in range(10):
        tracer.finish_trace(tracer.start_trace(f"t{i}"))
    assert tracer.finished_count() == 3
    assert [t.name for t in tracer.drain()] == ["t7", "t8", "t9"]


# ---------------------------------------------------------------- registry


def test_registry_get_or_create_and_kind_conflict():
    r = Registry()
    c1 = r.counter("serve.submitted")
    c1.inc()
    c1.inc(4)
    assert r.counter("serve.submitted") is c1
    assert c1.value == 5
    r.gauge("serve.queue_depth").set(7)
    with pytest.raises(ValueError):
        r.gauge("serve.submitted")  # same name, different kind
    with pytest.raises(ValueError):
        r.counter("")
    assert r.names() == ["serve.queue_depth", "serve.submitted"]
    r.reset()
    assert c1.value == 0


def test_histogram_param_drift_guard():
    """Explicit non-default bounds/window must match the existing
    instrument — a requested exact-percentile window cannot silently
    degrade to bucket estimates (default-arg calls are pure gets)."""
    r = Registry()
    h = r.histogram("lat", window=16)
    assert r.histogram("lat") is h              # default args: pure get
    assert r.histogram("lat", window=16) is h   # matching params fine
    with pytest.raises(ValueError, match="window"):
        r.histogram("lat", window=32)
    r2 = Registry()
    r2.histogram("b", bounds=(1.0, 2.0))
    with pytest.raises(ValueError, match="bounds"):
        r2.histogram("b", bounds=(1.0, 4.0))
    with pytest.raises(ValueError, match="window"):
        r2.histogram("b", window=8)  # windowless registered first


def test_histogram_bucket_boundaries():
    h = Histogram("h", bounds=(1.0, 2.0, 4.0))
    for v in (0.5, 1.0, 1.5, 4.0, 100.0):  # edges land in their own bucket
        h.observe(v)
    buckets = h.bucket_counts()
    assert [b for b, _ in buckets] == [1.0, 2.0, 4.0, math.inf]
    assert [c for _, c in buckets] == [2, 3, 4, 5]  # cumulative
    assert h.count == 5
    assert h.max == 100.0
    assert h.total == pytest.approx(107.0)
    assert h.mean == pytest.approx(107.0 / 5)
    with pytest.raises(ValueError):
        Histogram("bad", bounds=(2.0, 1.0))


def test_histogram_percentiles_exact_window_vs_oracle():
    import numpy as np

    rng = np.random.default_rng(7)
    samples = rng.exponential(0.01, size=500).tolist()
    h = Histogram("h", window=1024)
    for s in samples:
        h.observe(s)
    lat = sorted(samples)

    def oracle(p):
        return lat[min(len(lat) - 1, int(round(p * (len(lat) - 1))))]

    for p in (0.5, 0.95, 0.99):
        assert h.percentile(p) == pytest.approx(oracle(p))
    # the one-locked-read triple matches and is monotone by construction
    p50, p95, p99 = h.percentiles((0.5, 0.95, 0.99))
    assert p50 == pytest.approx(oracle(0.5))
    assert p50 <= p95 <= p99


def test_histogram_percentiles_bucketed_within_one_ratio():
    import numpy as np

    rng = np.random.default_rng(11)
    samples = rng.exponential(0.01, size=2000).tolist()
    h = Histogram("h")  # no window: log-bucket estimate, DEFAULT_BOUNDS ×2
    for s in samples:
        h.observe(s)
    lat = sorted(samples)
    for p in (0.5, 0.95, 0.99):
        est = h.percentile(p)
        truth = lat[min(len(lat) - 1, int(round(p * (len(lat) - 1))))]
        assert truth <= est <= truth * 2.0  # upper edge, one ×2 bucket off
    assert Histogram("e").percentile(0.5) is None
    with pytest.raises(ValueError):
        h.percentile(1.5)


def test_default_bounds_are_log_spaced():
    ratios = [b / a for a, b in zip(DEFAULT_BOUNDS, DEFAULT_BOUNDS[1:])]
    assert all(r == pytest.approx(2.0) for r in ratios)


# ---------------------------------------------------------------- exports

PROM_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{le=\"[^\"]+\"\})? -?[0-9.eE+-]+$"
    r"|^[a-zA-Z_:][a-zA-Z0-9_:]*(\{le=\"\+Inf\"\})? [0-9]+$"
)


def _sample_registry():
    r = Registry()
    r.counter("serve.submitted").inc(3)
    r.gauge("serve.queue_depth").set(2.5)
    h = r.histogram("serve.latency_seconds", bounds=(0.001, 0.01, 0.1))
    for v in (0.0005, 0.02, 0.5):
        h.observe(v)
    return r


def test_prometheus_text_parses_line_by_line():
    text = obs.prometheus_text(_sample_registry())
    lines = text.strip().splitlines()
    assert lines, "empty exposition"
    for ln in lines:
        if ln.startswith("# TYPE "):
            assert re.match(r"^# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* "
                            r"(counter|gauge|histogram)$", ln)
        else:
            assert PROM_LINE.match(ln), f"unparseable line: {ln!r}"
    assert "serve_submitted_total 3" in lines
    assert "serve_queue_depth 2.5" in lines
    # histogram: cumulative buckets, +Inf == count, sum present
    buckets = [ln for ln in lines if ln.startswith("serve_latency_seconds_bucket")]
    counts = [int(ln.rsplit(" ", 1)[1]) for ln in buckets]
    assert counts == sorted(counts)
    assert buckets[-1] == 'serve_latency_seconds_bucket{le="+Inf"} 3'
    assert "serve_latency_seconds_count 3" in lines


def test_prometheus_merged_registries_dedupe():
    a, b = _sample_registry(), _sample_registry()
    b.counter("other.thing").inc()
    text = obs.prometheus_text(a, b)
    samples = [ln for ln in text.splitlines()
               if ln.startswith("serve_submitted_total ")]
    assert samples == ["serve_submitted_total 3"]  # first registry wins
    assert "other_thing_total 1" in text


def test_traces_jsonl_round_trip():
    tracer, clock = make_tracer()
    tr = tracer.start_trace("serve.request", kind="bfs")
    root = tr.start_span("request")
    clock.advance(1.0)
    tr.start_span("submit", parent=root, bucket=64).end()
    tr.finish()
    text = obs.traces_to_jsonl(tracer.drain())
    recs = obs.parse_traces_jsonl(text)
    assert len(recs) == 1
    rec = recs[0]
    assert rec["schema_version"] == obs.TRACE_SCHEMA_VERSION
    assert rec["name"] == "serve.request"
    assert rec["attrs"] == {"kind": "bfs"}
    names = [s["name"] for s in rec["spans"]]
    assert names == ["request", "submit"]
    by_name = {s["name"]: s for s in rec["spans"]}
    assert by_name["submit"]["parent_id"] == by_name["request"]["span_id"]
    assert by_name["submit"]["attrs"] == {"bucket": 64}
    assert rec["t1"] >= rec["t0"]


def test_traces_jsonl_rejects_wrong_schema():
    tracer, _ = make_tracer()
    tracer.finish_trace(tracer.start_trace("t"))
    text = obs.traces_to_jsonl(tracer.drain())
    bumped = text.replace(f'"schema_version": {obs.TRACE_SCHEMA_VERSION}',
                          '"schema_version": 99')
    with pytest.raises(ValueError, match="schema_version"):
        obs.parse_traces_jsonl(bumped)
    with pytest.raises(ValueError, match="missing"):
        obs.parse_traces_jsonl(
            '{"schema_version": %d}\n' % obs.TRACE_SCHEMA_VERSION)
    assert obs.parse_traces_jsonl("") == []


def test_traces_jsonl_rejects_v1_records():
    """Schema v2 (128-bit trace/span ids) must REJECT v1 files: the two
    id spaces are not comparable, and silently mixing them would corrupt
    cross-process joins in a multi-pod collector."""
    v1 = ('{"schema_version": 1, "trace_id": 4611686018427387905, '
          '"name": "serve.request", "t0": 0.0, "t1": 1.0, '
          '"dropped_spans": 0, "attrs": {}, "spans": []}\n')
    with pytest.raises(ValueError, match="schema_version 1"):
        obs.parse_traces_jsonl(v1)


def test_trace_ids_are_128_bit():
    """The per-process id base carries 86 random high bits over the
    42-bit counter — ids occupy the full 128-bit space (the schema-v2
    collision-resistance contract for multi-process pods)."""
    from hypergraphdb_tpu.obs import trace as trace_mod

    base = trace_mod._TRACE_ID_BASE
    assert base < (1 << 128)
    assert base % (1 << 42) == 0       # counter bits stay clear
    tracer, _ = make_tracer()
    tr = tracer.start_trace("t")
    sp = tr.start_span("s")
    assert 0 < tr.trace_id < (1 << 128)
    assert 0 < sp.span_id < (1 << 128)
    tr.finish()


def test_write_telemetry_files(tmp_path):
    tracer, _ = make_tracer()
    tracer.finish_trace(tracer.start_trace("t"))
    out = obs.write_telemetry(str(tmp_path / "tele"),
                              registries=[_sample_registry()],
                              tracer=tracer)
    assert out["n_traces"] == 1
    prom = open(out["prometheus"]).read()
    assert "serve_submitted_total 3" in prom
    recs = obs.parse_traces_jsonl(open(out["traces"]).read())
    assert [r["name"] for r in recs] == ["t"]


def test_profile_noop_without_logdir():
    with obs.profile(None) as active:
        assert active is False
    with obs.profile("") as active:
        assert active is False


# ------------------------------------------------------------ the façades


def test_metrics_facade_shapes_unchanged():
    from hypergraphdb_tpu.utils.metrics import Metrics

    m = Metrics()
    m.incr("graph.mutations", 2)
    m.gauge("snapshot.num_atoms", 10)
    m.observe("snapshot.pack", 0.25)
    with m.timer("query.execute"):
        pass
    snap = m.snapshot()
    assert snap["counters"]["graph.mutations"] == 2
    assert snap["gauges"]["snapshot.num_atoms"] == 10.0
    assert snap["timings"]["snapshot.pack"]["count"] == 1
    assert snap["timings"]["snapshot.pack"]["max_s"] == pytest.approx(0.25)
    assert snap["timings"]["query.execute"]["count"] == 1
    # legacy attribute views still read
    assert m.counters == {"graph.mutations": 2}
    assert m.timings["snapshot.pack"][0] == 1
    # and the whole surface is one registry — renderable as Prometheus
    assert "graph_mutations_total 2" in obs.prometheus_text(m.registry)
    m.reset()
    assert m.snapshot()["counters"] == {"graph.mutations": 0}


def test_serve_stats_namespace_no_drift():
    """The metric-name-drift gate: ServeStats registers EXACTLY the
    committed dotted names, every legacy snapshot key maps to a live
    instrument, and nothing in the registry is orphaned."""
    from hypergraphdb_tpu.serve.stats import (
        DOTTED_NAMES,
        LEGACY_TO_DOTTED,
        ServeStats,
    )

    s = ServeStats(latency_window=16)
    assert s.registry.names() == sorted(DOTTED_NAMES)      # no orphans
    assert len(set(DOTTED_NAMES)) == len(DOTTED_NAMES)     # no duplicates
    for legacy, dotted in LEGACY_TO_DOTTED.items():
        assert s.registry.get(dotted) is not None, (legacy, dotted)
    # every snapshot key is covered by the shim
    snap = s.snapshot(queue_depth=0)
    assert set(snap) == set(LEGACY_TO_DOTTED)
    # namespaced view mirrors the legacy one
    s.record_submit()
    s.record_batch(n_real=1, bucket=4)
    ns = s.snapshot_namespaced(queue_depth=3)
    assert ns["serve.submitted"] == 1
    assert ns["serve.queue_depth"] == 3
    assert ns["serve.batch_occupancy"] == pytest.approx(0.25)
    assert s.registry.get("serve.queue_depth").value == 3.0


def test_serve_lane_counter_family_no_drift():
    """The lane drift gate: every serve lane (request kind × executor
    path) has its dispatch counter registered the moment a ServeStats
    exists, LANE_KINDS matches the runtime's actual request vocabulary,
    and the executors' path labels stay inside LANE_PATHS — a PR adding
    a lane (PR 10 join, PR 12 range, PR 11 sharded) that forgets the
    counter family fails here, not in a dashboard."""
    from types import SimpleNamespace

    from hypergraphdb_tpu.serve.runtime import DeviceExecutor
    from hypergraphdb_tpu.serve.sharded import ShardedExecutor
    from hypergraphdb_tpu.serve.stats import (
        LANE_KINDS,
        LANE_PATHS,
        ServeStats,
    )
    from hypergraphdb_tpu.serve.types import (
        BFSRequest,
        JoinRequest,
        PatternRequest,
        RangeRequest,
    )

    # the registered vocabulary IS the request vocabulary
    kinds = {
        BFSRequest(1, 1).kind,
        PatternRequest((1,)).kind,
        JoinRequest(SimpleNamespace(n_consts=0, vars=()), ()).kind,
        RangeRequest(105, None, None).kind,
    }
    assert kinds == set(LANE_KINDS)
    assert set(LANE_PATHS) == {"device", "sharded", "host"}
    # every executor's device-lane label is a registered path
    assert DeviceExecutor.device_lane in LANE_PATHS
    assert ShardedExecutor.device_lane in LANE_PATHS
    s = ServeStats(latency_window=8)
    for kind in LANE_KINDS:
        for path in LANE_PATHS:
            m = s.registry.get(f"serve.lane.{kind}.{path}")
            assert m is not None, (kind, path)
            assert m.kind == "counter"
    # recording drops unknown combinations instead of raising
    s.record_lane("bfs", "device")
    s.record_lane("future-kind", "device")
    assert s.lane_counts()[("bfs", "device")] == 1
    # reset covers the family (the bench's post-warmup cut)
    s.reset()
    assert all(v == 0 for v in s.lane_counts().values())


def test_serve_stats_shared_namespace_with_graph_metrics():
    """ServeStats and Metrics can share ONE process registry without
    name collisions — the unified-surface claim."""
    from hypergraphdb_tpu.serve.stats import ServeStats
    from hypergraphdb_tpu.utils.metrics import Metrics

    reg = Registry()
    m = Metrics(registry=reg)
    s = ServeStats(latency_window=8, registry=reg)
    m.incr("graph.mutations")
    s.record_submit()
    names = set(reg.names())
    assert "graph.mutations" in names and "serve.submitted" in names
    text = obs.prometheus_text(reg)
    assert "graph_mutations_total 1" in text
    assert "serve_submitted_total 1" in text
    # reset scope: each façade zeroes only ITS instruments — a serving
    # post-warmup cut must not wipe graph/tx counters sharing the registry
    s.reset()
    assert reg.get("serve.submitted").value == 0
    assert reg.get("graph.mutations").value == 1
    m.incr("graph.mutations")
    m.reset()
    assert reg.get("graph.mutations").value == 0
    s.record_submit()
    assert reg.get("serve.submitted").value == 1  # untouched by m.reset


# ------------------------------------------------------------- sampling


def test_sample_rate_zero_drops_and_one_keeps():
    tracer, clock = make_tracer()
    tracer.set_sample_rate("noisy", 0.0)
    tracer.start_trace("noisy").finish()
    tracer.start_trace("other").finish()     # default rate 1.0
    kept = tracer.drain()
    assert [t.name for t in kept] == ["other"]
    assert tracer.traces_dropped == 1
    assert tracer.sample_rate_of("noisy") == 0.0
    assert tracer.sample_rate_of("other") == 1.0


def test_error_and_shed_terminals_always_sampled():
    """Head-based sampling with the always-capture override: an
    unsampled trace that ends in error/shed is upgraded and retained."""
    tracer, clock = make_tracer()
    tracer.set_sample_rate("serve.request", 0.0)
    ok = tracer.start_trace("serve.request")
    ok.finish_terminal("resolve")            # healthy → dropped
    bad = tracer.start_trace("serve.request")
    bad.finish_error(RuntimeError("x"))      # error → kept
    shed = tracer.start_trace("serve.request")
    shed.finish_terminal("shed", waited_s=1.0)
    kept = tracer.drain()
    assert len(kept) == 2
    assert {t.spans()[-1].name for t in kept} == {"error", "shed"}
    assert tracer.traces_dropped == 1


def test_force_sample_retains_breaker_trip_trace():
    tracer, clock = make_tracer()
    tracer.set_sample_rate("serve.request", 0.0)
    tr = tracer.start_trace("serve.request")
    tr.force_sample()                        # the breaker-trip path
    tr.finish_terminal("resolve")
    assert [t.trace_id for t in tracer.drain()] == [tr.trace_id]


def test_sampling_deterministic_under_seed():
    a = Tracer(clock=FakeClock(), seed=42).enable()
    b = Tracer(clock=FakeClock(), seed=42).enable()
    for t in (a, b):
        t.set_sample_rate("x", 0.5)
    pattern_a = [a.start_trace("x").sampled for _ in range(64)]
    pattern_b = [b.start_trace("x").sampled for _ in range(64)]
    assert pattern_a == pattern_b
    assert 0 < sum(pattern_a) < 64           # a real 50% stream


def test_finished_buffer_eviction_counted():
    tracer, clock = make_tracer(max_finished=4)
    for _ in range(6):
        tracer.start_trace("t").finish()
    assert tracer.finished_count() == 4
    assert tracer.traces_evicted == 2
    snap = tracer.sampling_snapshot()
    assert snap["traces_evicted"] == 2
    assert snap["finished_fill"] == 4 and snap["finished_capacity"] == 4


def test_adaptive_controller_scales_down_and_recovers():
    tracer, clock = make_tracer(max_finished=10)
    tracer.enable_adaptive(target_fill=0.5, floor=0.05)
    assert tracer.sample_rate_of("t") == 1.0
    for _ in range(5):                       # fill to the target
        tracer.start_trace("t").finish()
    assert tracer.sample_rate_of("t") == 0.5  # halved at the watermark
    for _ in range(4):                       # keep pressing
        tr = tracer.start_trace("t")
        tr.force_sample()
        tr.finish()
    assert tracer.sample_rate_of("t") == 0.05   # floor under pressure
    tracer.drain()       # pressure cleared (this drain still saw fill)
    for _ in range(3):   # idle drains: controller doubles back up
        tracer.drain()
    assert tracer.sample_rate_of("t") == pytest.approx(0.4)
    # floor respected under sustained overload
    for _ in range(100):
        tr = tracer.start_trace("t")
        tr.force_sample()
        tr.finish()
    assert tracer.sample_rate_of("t") >= 0.05


def test_adaptive_per_kind_isolates_hot_root_kind():
    """One hot root kind (peer.push at replication qps) fills the buffer:
    ITS scale halves to the floor while a cold kind keeps its whole
    budget; only once the hot kind is floored and pressure persists does
    the GLOBAL outer clamp engage (and idle drains recover both)."""
    tracer, clock = make_tracer(max_finished=10)
    tracer.enable_adaptive(target_fill=0.5, floor=0.05)
    for _ in range(5):                       # flood to the watermark
        tracer.start_trace("peer.push").finish()
    assert tracer.sample_rate_of("peer.push") == 0.5
    assert tracer.sample_rate_of("serve.request") == 1.0  # untouched
    for _ in range(4):                       # press the hot kind to floor
        tr = tracer.start_trace("peer.push")
        tr.force_sample()
        tr.finish()
    assert tracer.sample_rate_of("peer.push") == 0.05
    assert tracer.sample_rate_of("serve.request") == 1.0  # STILL whole
    snap = tracer.sampling_snapshot()
    assert snap["adaptive_kind_scales"]["peer.push"] == 0.05
    assert snap["adaptive_scale"] == 1.0
    # hot kind floored + sustained pressure → the global clamp engages
    tr = tracer.start_trace("peer.push")
    tr.force_sample()
    tr.finish()
    assert tracer.sampling_snapshot()["adaptive_scale"] == 0.5
    assert tracer.sample_rate_of("serve.request") == 0.5
    assert tracer.sample_rate_of("peer.push") == 0.05  # floor-clamped
    # recovery: idle drains double kind scales and the global scale back
    tracer.drain()                           # this drain still saw fill
    for _ in range(6):
        tracer.drain()
    assert tracer.sample_rate_of("peer.push") == 1.0
    assert tracer.sample_rate_of("serve.request") == 1.0
    assert tracer.sampling_snapshot()["adaptive_kind_scales"] == {}


def test_peek_does_not_consume():
    tracer, clock = make_tracer()
    tracer.start_trace("a").finish()
    tracer.start_trace("b").finish()
    assert [t.name for t in tracer.peek()] == ["a", "b"]
    assert [t.name for t in tracer.peek(1)] == ["b"]
    assert len(tracer.drain()) == 2          # peek left them in place


def test_breaker_key_family_rides_the_registry():
    """The dynamic serve.breaker.* family: labelled per-key gauges and
    trip counters beside the committed fixed names."""
    from hypergraphdb_tpu.serve.stats import (
        BREAKER_KEY_PREFIX,
        DOTTED_NAMES,
        ServeStats,
    )

    s = ServeStats(latency_window=8)
    s.set_breaker_key_state(("bfs", 2), 2)
    s.record_breaker_key_trip(("bfs", 2))
    s.set_breaker_key_state(("pattern", 3), 0)
    extras = sorted(set(s.registry.names()) - set(DOTTED_NAMES))
    assert extras == [
        "serve.breaker.state.bfs_2",
        "serve.breaker.state.pattern_3",
        "serve.breaker.trips.bfs_2",
    ]
    assert all(n.startswith(BREAKER_KEY_PREFIX) for n in extras)
    assert s.breaker_key_states() == {"bfs_2": 2.0, "pattern_3": 0.0}
    text = obs.prometheus_text(s.registry)
    assert "serve_breaker_state_bfs_2 2.0" in text
    assert "serve_breaker_trips_bfs_2_total 1" in text
    s.reset()                                # the family resets too
    assert s.registry.get("serve.breaker.state.bfs_2").value == 0.0
    assert s.registry.get("serve.breaker.trips.bfs_2").value == 0
