"""hgplan feedback-loop tests: the drift digest learns, bounded, gated.

Three claims:

- **it helps** — replaying a trace of systematically-biased estimates
  through the digest demonstrably SHRINKS the median est-vs-actual
  relative error once corrections warm up (measured prequentially: each
  pair is scored with the correction learned from pairs BEFORE it);
- **it is bounded and gated** — LRU shape eviction, ratio clamping,
  warm-up identity, enabled=False identity;
- **it cannot steer into a fire** — a correction that flips the argmin
  onto a lane the perf sentinel flags is vetoed (``plan.guard_vetoes``),
  the uncorrected choice dispatches instead.
"""

from __future__ import annotations

import numpy as np
import pytest

from hypergraphdb_tpu.plan import PlanFeedback, QueryPlanner
from hypergraphdb_tpu.query import conditions as c


# ---------------------------------------------------------------- digest
def test_replayed_trace_shrinks_median_relative_error(rng):
    """The acceptance claim: on a trace whose actuals run ~3.3× below
    the estimates (the coincident-overcount signature), prequential
    corrected estimates beat raw ones on median relative error."""
    fb = PlanFeedback(min_samples=8)
    raw_err, corr_err = [], []
    for _ in range(120):
        est = float(rng.uniform(50, 5000))
        actual = est * 0.3 * float(rng.uniform(0.8, 1.2))
        corrected = est * fb.correction("join")  # learned from the PAST
        raw_err.append(abs(est - actual) / actual)
        corr_err.append(abs(corrected - actual) / actual)
        fb.observe("join", est, actual)
    assert np.median(corr_err) < 0.5 * np.median(raw_err)
    snap = fb.snapshot()
    assert snap["shapes"]["join"]["samples"] == min(120, fb.window)
    assert 0.25 <= snap["shapes"]["join"]["correction"] <= 0.4


def test_warmup_and_disabled_serve_identity():
    fb = PlanFeedback(min_samples=4)
    assert fb.correction("join") == 1.0
    for _ in range(3):
        fb.observe("join", 100.0, 50.0)
    assert fb.correction("join") == 1.0  # still warming up
    fb.observe("join", 100.0, 50.0)
    assert fb.correction("join") == 0.5
    fb.enabled = False
    assert fb.correction("join") == 1.0
    assert fb.corrections_active() == 0


def test_ratios_clamp_and_count():
    fb = PlanFeedback(min_samples=1, clamp=(0.25, 4.0))
    assert fb.observe("s", 1000.0, 1.0) == 0.25       # floor
    assert fb.observe("s", 1.0, 1000.0) == 4.0        # ceiling
    assert fb.observe("s", 10.0, 20.0) == 2.0         # pass-through
    assert fb.snapshot()["clamped"] == 2
    # unusable pairs never enter the window
    assert fb.observe("s", 0.0, 5.0) is None
    assert fb.observe("s", float("nan"), 5.0) is None
    assert fb.snapshot()["shapes"]["s"]["samples"] == 3


def test_shape_store_is_lru_bounded():
    fb = PlanFeedback(max_shapes=3, min_samples=1)
    for name in ("a", "b", "c"):
        fb.observe(name, 10.0, 20.0)
    fb.observe("a", 10.0, 20.0)       # refresh a: b is now staletest
    fb.observe("d", 10.0, 20.0)       # evicts b
    shapes = set(fb.snapshot()["shapes"])
    assert shapes == {"a", "c", "d"}
    assert fb.correction("b") == 1.0  # evicted = back to identity


def test_bad_clamp_rejected():
    with pytest.raises(ValueError):
        PlanFeedback(clamp=(1.5, 4.0))
    with pytest.raises(ValueError):
        PlanFeedback(clamp=(0.5, 0.9))
    with pytest.raises(ValueError):
        PlanFeedback(max_shapes=0)


# ---------------------------------------------------------------- guard
def _overcount_graph(g, n_links=1500):
    """An anchor with many arity-3 multi-links over ten satellites: the
    CoIncident estimate (Σ arity−1 ≈ 2×links) overcounts the true
    co-neighbour set (~10) by orders of magnitude — exactly the bias the
    feedback loop learns away, and enough atoms that the host scan is
    genuinely expensive."""
    sats = [int(g.add(1000 + i)) for i in range(10)]
    anchor = int(g.add(999))
    for i in range(n_links):
        a, b = sats[i % 10], sats[(i + 1) % 10]
        g.add_link([anchor, a, b], value=i)
    return anchor


def test_correction_flips_argmin_and_sentinel_guard_vetoes(graph):
    """End to end on a real graph: raw costing picks host (the join
    estimate is wildly high), the warmed correction flips the argmin to
    the join lane — unless the sentinel flags the join lane, in which
    case the flip is vetoed and counted."""
    anchor = _overcount_graph(graph)
    cond = c.And(c.CoIncident(anchor), c.AtomValue(0, "gte"))
    truth = sorted(int(h) for h in graph.find_all(cond))
    assert len(truth) == 10

    planner = QueryPlanner(graph, feedback=PlanFeedback(min_samples=8))
    raw = planner.plan(cond)
    assert raw.shape == "host"  # the overcounted join estimate loses

    # replay: the join shape's actuals keep undershooting the estimate
    for _ in range(10):
        forced = planner.plan(cond, force_shape="join")
        assert not forced.exact_est
        planner.observe(forced, len(truth))
    assert planner.feedback.correction("join") == 0.25  # clamped floor

    corrected = planner.plan(cond)
    assert corrected.shape == "join"
    assert corrected.correction == 0.25
    assert not corrected.guard_vetoed

    # same planner, sentinel now flags the join lane: veto the flip
    planner.lane_degraded = lambda kind: kind == "join"
    vetoed = planner.plan(cond)
    assert vetoed.shape == "host"
    assert vetoed.guard_vetoed
    assert planner.health_summary()["guard_vetoes"] == 1
    # a degraded lane the correction did NOT flip onto is not vetoed
    planner.lane_degraded = lambda kind: kind == "bfs"
    assert not planner.plan(cond).guard_vetoed


def test_planner_health_summary_shape(graph):
    planner = QueryPlanner(graph)
    graph.add(1)
    h = planner.health_summary()
    assert set(h) == {"enabled", "corrections_active", "guard_vetoes",
                      "shapes", "updates"}
    assert h["enabled"] is True
    assert h["guard_vetoes"] == 0
