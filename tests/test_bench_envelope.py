"""Bench record envelope + ``--diff`` regression verdicts.

One ``_record_bench`` envelope for every ``BENCH_C*_<tag>.json`` writer
(schema v2 adds ``git_rev``; the committed v1 smokes stay readable), the
version-checking reader, and the per-metric diff tool the real-TPU sweep
answers the "is the CPU smoke lying" question with.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

import bench

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
FIXTURES = os.path.join(HERE, "perf_fixtures")
BASE = os.path.join(FIXTURES, "BENCH_C6_base.json")
REGRESSED = os.path.join(FIXTURES, "BENCH_C6_regressed.json")


# ---------------------------------------------------------------- envelope


def test_record_bench_envelope_shape(tmp_path, monkeypatch):
    monkeypatch.setenv("BENCH_RECORD_DIR", str(tmp_path))
    monkeypatch.setenv("BENCH_C6_TAG", "unit")
    name = bench._record_bench("c6_serving", {
        "served_qps": 10.0, "telemetry": {"x": 1}, "recorded_to": "old",
    })
    assert name == "BENCH_C6_unit.json"
    rec = bench.read_bench(str(tmp_path / name))
    assert rec["schema_version"] == bench.BENCH_SCHEMA_VERSION
    assert rec["tag"] == "unit"
    assert "backend" in rec and "recorded_unix" in rec
    assert "git_rev" in rec           # provenance (None off-git is fine)
    # envelope-internal keys never leak into the payload
    assert rec["c6_serving"] == {"served_qps": 10.0}
    key, payload = bench.bench_payload(rec)
    assert key == "c6_serving" and payload["served_qps"] == 10.0


def test_every_recorded_config_shares_the_envelope(tmp_path, monkeypatch):
    monkeypatch.setenv("BENCH_RECORD_DIR", str(tmp_path))
    for config_key, (tag_env, prefix) in bench.BENCH_RECORDED.items():
        monkeypatch.setenv(tag_env, "unit")
        name = bench._record_bench(config_key, {"m": 1.0})
        assert name == f"{prefix}_unit.json"
        rec = bench.read_bench(str(tmp_path / name))
        assert set(rec) == {"schema_version", "recorded_unix", "tag",
                            "backend", "git_rev", config_key}


def test_reader_accepts_committed_v1_smokes():
    for name in ("BENCH_C7_smoke.json", "BENCH_C8_smoke.json",
                 "BENCH_C9_smoke.json", "BENCH_C6_local.json"):
        rec = bench.read_bench(os.path.join(REPO, name))
        assert rec["schema_version"] in bench.BENCH_SCHEMA_ACCEPTED


def test_reader_rejects_bad_records(tmp_path):
    p = tmp_path / "x.json"
    p.write_text(json.dumps({"schema_version": 99, "tag": "t",
                             "backend": "cpu", "recorded_unix": 1,
                             "c6_serving": {}}))
    with pytest.raises(ValueError, match="schema"):
        bench.read_bench(str(p))
    p.write_text(json.dumps({"schema_version": 2, "backend": "cpu",
                             "recorded_unix": 1, "c6_serving": {}}))
    with pytest.raises(ValueError, match="tag"):
        bench.read_bench(str(p))
    p.write_text(json.dumps({"schema_version": 2, "tag": "t",
                             "backend": "cpu", "recorded_unix": 1}))
    with pytest.raises(ValueError, match="config payload"):
        bench.read_bench(str(p))
    p.write_text(json.dumps({"schema_version": 2, "tag": "t",
                             "backend": "cpu", "recorded_unix": 1,
                             "c6_serving": {}, "c8_sharded": {}}))
    with pytest.raises(ValueError, match="config payload"):
        bench.read_bench(str(p))


# -------------------------------------------------------------------- diff


def test_diff_identical_files_verdict_ok():
    report = bench.bench_diff(BASE, BASE)
    assert report["verdict"] == "ok"
    assert report["regressed"] == [] and report["improved"] == []
    assert report["context_mismatch"] == []
    assert report["backend_differs"] is False


def test_diff_injected_regression_fixture_pair():
    report = bench.bench_diff(BASE, REGRESSED)
    assert report["verdict"] == "regressed"
    assert "latency_ms_p50" in report["regressed"]
    assert "served_qps" in report["regressed"]
    assert "batched_vs_unbatched" in report["regressed"]
    m = report["metrics"]["latency_ms_p50"]
    assert m["direction"] == "lower" and m["verdict"] == "regressed"
    # scale knobs matched, so the comparison context is clean
    assert report["context_mismatch"] == []


def test_diff_improvement_is_not_regression():
    # reversed direction: B is the FASTER file → improved, exit-0 class
    report = bench.bench_diff(REGRESSED, BASE)
    assert report["verdict"] == "ok"
    assert "latency_ms_p50" in report["improved"]
    assert report["regressed"] == []


def test_diff_tolerance_is_honored():
    strict = bench.bench_diff(BASE, REGRESSED, tolerance=0.01)
    loose = bench.bench_diff(BASE, REGRESSED, tolerance=10.0)
    assert strict["verdict"] == "regressed"
    assert loose["verdict"] == "ok"


def test_diff_context_mismatch_flagged_not_fatal(tmp_path):
    rec = json.load(open(BASE))
    rec["c6_serving"]["entities"] = 9999          # different scale
    other = tmp_path / "BENCH_C6_other.json"
    other.write_text(json.dumps(rec))
    report = bench.bench_diff(BASE, str(other))
    assert "entities" in report["context_mismatch"]
    assert report["verdict"] == "ok"


def test_diff_config_mismatch_raises():
    with pytest.raises(ValueError, match="config mismatch"):
        bench.bench_diff(BASE, os.path.join(REPO, "BENCH_C8_smoke.json"))


def test_metric_direction_classification():
    d = bench._metric_direction
    assert d("served_qps") == "higher"
    assert d("edges_per_sec") == "higher"
    assert d("batched_vs_unbatched") == "higher"
    assert d("batch_occupancy") == "higher"
    assert d("served_qps_per_device_count.8") == "higher"
    # matched per SEGMENT: the nested vs_host ratios c7 records gate too
    assert d("triangle.vs_host") == "higher"
    assert d("hub_heavy.device_anchors_per_sec") == "higher"
    assert d("latency_ms_p99") == "lower"
    assert d("fact_build_s") == "lower"
    assert d("cold_start_s.cache_absent_s") == "lower"
    # the seconds suffix applies to the FINAL segment only
    assert d("cold_start_s.entities") == "info"
    # config knobs never read as perf regressions
    assert d("deadline_s") == "info"
    assert d("offered_qps") == "info"      # the INPUT rate, not served
    assert d("requests") == "info"
    assert d("entities") == "info"
    assert d("devices.0") == "info"


def test_diff_gates_nested_vs_ratios():
    """A c7-style nested vs_host collapse must exit nonzero (the
    full-path classifier once read `triangle.vs_host` as info)."""
    rec = {"schema_version": 2, "recorded_unix": 1, "tag": "a",
           "backend": "cpu", "git_rev": None,
           "c7_pattern_join": {"triangle": {"vs_host": 8.0}}}
    import copy
    worse = copy.deepcopy(rec)
    worse["c7_pattern_join"]["triangle"]["vs_host"] = 0.5
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        a, b = os.path.join(td, "a.json"), os.path.join(td, "b.json")
        json.dump(rec, open(a, "w"))
        json.dump(worse, open(b, "w"))
        report = bench.bench_diff(a, b)
    assert report["regressed"] == ["triangle.vs_host"]


# --------------------------------------------------------------------- CLI


def run_cli(*args):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), *args],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )


def test_cli_exit_codes():
    ok = run_cli("--diff", BASE, BASE)
    assert ok.returncode == 0, ok.stderr
    assert json.loads(ok.stdout)["verdict"] == "ok"
    bad = run_cli("--diff", BASE, REGRESSED)
    assert bad.returncode == 1, bad.stderr
    assert json.loads(bad.stdout)["verdict"] == "regressed"
    loose = run_cli("--diff", BASE, REGRESSED, "--diff-tolerance", "10")
    assert loose.returncode == 0, loose.stderr
    usage = run_cli("--diff", BASE)
    assert usage.returncode == 2
    missing = run_cli("--diff", BASE, "/nonexistent.json")
    assert missing.returncode == 2
    # a mistyped flag must not silently gate at the default tolerance
    typo = run_cli("--diff", BASE, REGRESSED, "--tolerance", "10")
    assert typo.returncode == 2 and "unknown flag" in typo.stderr


def test_cli_seed_baseline(tmp_path):
    out = str(tmp_path / "PERF_BASELINE.json")
    proc = run_cli("--seed-baseline", out)
    assert proc.returncode == 0, proc.stderr
    from hypergraphdb_tpu.obs.perf import load_baseline

    rec = load_baseline(out)
    assert rec["lanes"]                     # seeded from committed smokes
    assert json.loads(proc.stdout)["wrote"] == out


def test_committed_perf_baseline_loads():
    """The committed PERF_BASELINE.json is readable and names real
    serve lanes — the file the sentinel drill loads."""
    from hypergraphdb_tpu.obs.perf import load_baseline
    from hypergraphdb_tpu.serve.stats import LANE_KINDS

    rec = load_baseline(os.path.join(REPO, "PERF_BASELINE.json"))
    assert rec["lanes"]
    # sentinel lanes = the serve executor lanes + the standing tier's
    # "sub" notification lane (fed by SubscriptionManager, seeded from
    # the c10 record)
    assert set(rec["lanes"]) <= set(LANE_KINDS) | {"sub"}
    for lane in rec["lanes"].values():
        assert lane.get("p50_s") or lane.get("p99_s")
