"""Owner-map semantics of ``storage/partitioned.py``: the gid-range
:class:`PartitionMap` (the ONE map the storage grid and the device mesh
share) and record migration under repartitioning — gid ranges move,
``find``/``count`` stay exact.
"""

import numpy as np
import pytest

from hypergraphdb_tpu.storage.memstore import MemStorage
from hypergraphdb_tpu.storage.partitioned import (
    PartitionedStorage,
    PartitionMap,
)


# ---------------------------------------------------------------- the map


def test_partition_map_ranges_cover_and_align():
    pm = PartitionMap.for_mesh(1000, 4)
    assert pm.part_size % PartitionMap.ALIGN == 0
    ranges = pm.ranges()
    assert ranges[0][0] == 0
    for (lo, hi), (lo2, _) in zip(ranges, ranges[1:]):
        assert hi == lo2          # contiguous, no gaps
    assert ranges[-1][1] >= pm.capacity


def test_partition_map_matches_sharded_snapshot_layout():
    """The storage map IS the mesh's row split: for_mesh must reproduce
    ShardedSnapshot.from_host's n_loc arithmetic exactly."""
    for n_dev in (1, 2, 4, 8):
        for n_atoms in (7, 127, 128, 1000, 99_999):
            pm = PartitionMap.for_mesh(n_atoms + 1, n_dev)
            n_loc = -(-(n_atoms + 1) // (n_dev * 128)) * 128
            assert pm.part_size == n_loc, (n_dev, n_atoms)


def test_partition_map_owner_total_and_clamped():
    pm = PartitionMap.for_mesh(512, 4)
    for gid in range(0, 2 * pm.n_parts * pm.part_size, 37):
        own = pm.owner_of(gid)
        assert 0 <= own < pm.n_parts
        lo, hi = pm.range_of(own)
        if own < pm.n_parts - 1:
            assert lo <= gid < hi
        else:
            assert gid >= lo      # overflow ids clamp into the last range
    with pytest.raises(ValueError):
        pm.owner_of(-1)


def test_partition_map_owner_np_agrees_with_scalar():
    pm = PartitionMap.for_mesh(777, 3)
    gids = np.arange(0, 3000, 13)
    vec = pm.owner_np(gids)
    assert list(vec) == [pm.owner_of(int(g)) for g in gids]


def test_partition_map_to_dict_wire_shape():
    d = PartitionMap.for_mesh(400, 2).to_dict()
    assert set(d) == {"n_parts", "part_size", "capacity", "ranges"}
    assert len(d["ranges"]) == 2
    assert d["ranges"][0][0] == 0


# ---------------------------------------------------------------- routing


def _seed(store, n=300, cap=4096):
    """Links with spread-out handles + a bidirectional index."""
    rng = np.random.default_rng(7)
    handles = sorted(rng.choice(cap, size=n, replace=False).tolist())
    for h in handles:
        store.store_link(int(h), (int(h) % 17, int(h) % 5))
        store.store_data(int(h), f"payload-{h}".encode())
        store.add_incidence_link(int(h), int(h) % 29)
    idx = store.get_index("by-mod")
    for h in handles:
        idx.add_entry(str(int(h) % 13).encode(), int(h))
    return handles


def _snapshot(store, handles):
    idx = store.get_index("by-mod", create=False)
    return {
        "links": {h: store.get_link(h) for h in handles},
        "data": {h: store.get_data(h) for h in handles},
        "inc": {h: list(store.get_incidence_set(h)) for h in handles},
        "finds": {m: list(idx.find(str(m).encode()))
                  for m in range(13)},
        "counts": {m: idx.count(str(m).encode()) for m in range(13)},
        "key_count": idx.key_count(),
    }


def test_gid_range_routing_places_by_owner():
    pm = PartitionMap.for_mesh(4096, 4)
    store = PartitionedStorage(partition_map=pm)
    handles = _seed(store)
    for h in handles:
        part = pm.owner_of(h)
        assert store._parts[part].get_link(h) is not None
        for other in range(4):
            if other != part:
                assert store._parts[other].get_link(h) is None


def test_repartition_moves_ranges_and_stays_exact():
    """The satellite contract: re-cut the map for a grown id space —
    records migrate to their new range owners, and every SPI read
    (links, payloads, incidence, index find/count) answers identically
    before and after."""
    pm = PartitionMap.for_mesh(1024, 4)
    store = PartitionedStorage(partition_map=pm)
    handles = _seed(store, cap=4000)      # many ids clamp into range 3
    before = _snapshot(store, handles)

    new_map = pm.repartitioned(4096)      # the grown id space's cut
    assert new_map.part_size != pm.part_size
    moved = store.repartition(new_map)
    assert moved > 0                      # ranges really moved

    assert _snapshot(store, handles) == before
    for h in handles:                     # and placement follows the NEW map
        assert store._parts[new_map.owner_of(h)].get_link(h) is not None

    # idempotent: re-running the same repartition moves nothing
    assert store.repartition(new_map) == 0
    assert _snapshot(store, handles) == before


def test_repartition_requires_range_routing_and_same_owner_count():
    legacy = PartitionedStorage(n_partitions=3)
    with pytest.raises(ValueError, match="modulo"):
        legacy.repartition(PartitionMap.for_mesh(100, 3))
    pm = PartitionMap.for_mesh(100, 3)
    ranged = PartitionedStorage(partition_map=pm)
    with pytest.raises(ValueError, match="partition count"):
        ranged.repartition(PartitionMap.for_mesh(100, 4))


def test_partition_map_mismatched_children_rejected():
    with pytest.raises(ValueError, match="owners"):
        PartitionedStorage(
            partitions=[MemStorage(), MemStorage()],
            partition_map=PartitionMap.for_mesh(100, 3),
        )


def test_iter_record_handles_enumerates_every_record_kind():
    m = MemStorage()
    m.store_link(1, (2,))
    m.store_data(9, b"x")
    m.add_incidence_link(5, 1)
    assert m.iter_record_handles() == {1, 9, 5}
