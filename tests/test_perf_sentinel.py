"""hgperf: runtime perf baselines, drift sentinel, skew attribution,
incident profiles.

Everything runs on fake clocks and an injectable profiler (jax-free);
the acceptance contract is the end-to-end drill at the bottom: a seeded
slowdown on one serve lane fires exactly ONE flight incident whose dump
dir holds both the flight window and a profiler capture, ``/fleet/perf``
names that lane (and only that lane), and the undisturbed soak fires
zero.
"""

from __future__ import annotations

import json
import os
from contextlib import contextmanager

import pytest

from hypergraphdb_tpu.obs.flight import FlightRecorder, parse_flight_jsonl
from hypergraphdb_tpu.obs.fleet import FleetCollector, LocalNodeSource
from hypergraphdb_tpu.obs.http import runtime_health
from hypergraphdb_tpu.obs.perf import (
    BASELINE_SCHEMA_VERSION,
    PerfSentinel,
    load_baseline,
    save_baseline,
    seed_baseline,
    shard_skew,
)
from hypergraphdb_tpu.obs.slo import fleet_objectives
from hypergraphdb_tpu.serve import ServeConfig, ServeRuntime
from tests.test_serve_runtime import FakeClock, FakeExecutor

BASELINE = {
    "schema_version": BASELINE_SCHEMA_VERSION,
    "backend": "fake",
    "lanes": {"bfs": {"p50_s": 0.01, "p99_s": 0.02, "qps": 100.0}},
    "factors": {"p50_s": 3.0, "p99_s": 3.0, "device_s_per_req": 3.0},
}


class FakeProfiler:
    """Injectable ``obs.profile`` stand-in: records open/close edges and
    drops a trace marker file in the session dir (what the real
    profiler's trace files assert as)."""

    def __init__(self):
        self.events: list = []

    def __call__(self, logdir):
        @contextmanager
        def session():
            self.events.append(("open", logdir))
            with open(os.path.join(logdir, "trace.marker"), "w") as f:
                f.write("profiler trace\n")
            yield True
            self.events.append(("close", logdir))

        return session()


def make_sentinel(tmp_path=None, baseline=BASELINE, windows=(5.0, 20.0),
                  **kw):
    clock = FakeClock()
    incident_dir = str(tmp_path) if tmp_path is not None else None
    flight = FlightRecorder(clock=clock, incident_dir=incident_dir,
                            min_dump_interval_s=0.0)
    profiler = FakeProfiler()
    kw.setdefault("min_samples", 4)
    kw.setdefault("eval_interval_s", 0.0)
    kw.setdefault("profile_s", 2.0)
    sen = PerfSentinel(baseline=baseline, clock=clock, flight=flight,
                       windows=windows, profiler=profiler, **kw)
    return sen, clock, flight, profiler


def feed(sen, clock, latency, n, dt=1.0, kind="bfs", tick=True):
    for _ in range(n):
        clock.advance(dt)
        sen.observe(kind, latency)
        if tick:
            sen.tick()


# ---------------------------------------------------------------- baseline


def test_baseline_round_trip_and_version_check(tmp_path):
    path = str(tmp_path / "PERF_BASELINE.json")
    save_baseline(BASELINE, path)
    assert load_baseline(path)["lanes"]["bfs"]["p99_s"] == 0.02
    bad = dict(BASELINE, schema_version=BASELINE_SCHEMA_VERSION + 1)
    save_baseline(bad, path)
    with pytest.raises(ValueError):
        load_baseline(path)
    save_baseline({"schema_version": BASELINE_SCHEMA_VERSION}, path)
    with pytest.raises(ValueError):  # no lanes mapping
        load_baseline(path)


def test_seed_baseline_from_bench_records(tmp_path):
    (tmp_path / "BENCH_C6_smoke.json").write_text(json.dumps({
        "schema_version": 1, "tag": "smoke", "backend": "cpu",
        "recorded_unix": 1,
        "c6_serving": {"batched_vs_unbatched": 2.0, "latency_ms_p50": 20.0,
                       "latency_ms_p99": 30.0, "served_qps": 120.0},
    }))
    (tmp_path / "BENCH_C9_local.json").write_text(json.dumps({
        "schema_version": 2, "tag": "local", "backend": "cpu",
        "recorded_unix": 1, "git_rev": "abc",
        "c9_value_index": {"latency_ms_p50": 5.0, "latency_ms_p99": 8.0,
                           "served_qps": 900.0},
    }))
    (tmp_path / "BENCH_C7_smoke.json").write_text(json.dumps({
        "schema_version": 1, "tag": "smoke", "backend": "cpu",
        "recorded_unix": 1,
        "c7_pattern_join": {"triangle": {"device_anchors_per_sec": 50.0}},
    }))
    out = str(tmp_path / "PERF_BASELINE.json")
    rec = seed_baseline(str(tmp_path), out_path=out)
    assert sorted(rec["lanes"]) == ["bfs", "join", "range"]
    assert rec["lanes"]["bfs"]["p50_s"] == pytest.approx(0.02)
    assert rec["lanes"]["range"]["p99_s"] == pytest.approx(0.008)
    assert rec["lanes"]["join"]["p50_s"] == pytest.approx(0.02)
    assert rec["backend"] == "cpu"
    # the written file round-trips through the version-checking reader
    assert load_baseline(out)["source"] == rec["source"]
    # pattern has no bench record: not seeded, so never gated
    assert "pattern" not in rec["lanes"]
    # the c7 throughput proxy says so in its note
    assert "proxy" in rec["lanes"]["join"]["note"]


def test_seed_baseline_c11_beats_the_c7_join_proxy(tmp_path):
    """Both join sources present: the c11 open-loop percentiles win the
    ``join`` lane over c7's closed-loop throughput proxy, whatever the
    records' relative ages."""
    (tmp_path / "BENCH_C7_smoke.json").write_text(json.dumps({
        "schema_version": 1, "tag": "smoke", "backend": "cpu",
        "recorded_unix": 999,
        "c7_pattern_join": {"triangle": {"device_anchors_per_sec": 50.0}},
    }))
    (tmp_path / "BENCH_C11_smoke.json").write_text(json.dumps({
        "schema_version": 2, "tag": "smoke", "backend": "cpu",
        "recorded_unix": 1,
        "c11_join": {"latency_ms_p50": 40.0, "latency_ms_p99": 90.0,
                     "served_qps": 77.0},
    }))
    rec = seed_baseline(str(tmp_path))
    join = rec["lanes"]["join"]
    assert join["p50_s"] == pytest.approx(0.04)
    assert join["p99_s"] == pytest.approx(0.09)
    assert join["qps"] == 77.0
    assert "open-loop" in join["note"]


# ---------------------------------------------------------------- windows


def test_window_digest_math():
    sen, clock, _, _ = make_sentinel(windows=(10.0, 40.0))
    for lat in (0.01, 0.02, 0.03, 0.04):
        clock.advance(1.0)
        sen.observe("bfs", lat)
    sen.observe("bfs", 0.05, path="host")
    sen.observe_batch("bfs", 0.008, n_real=2, n_total=4)
    snap = sen.tick()
    short = snap["lanes"]["bfs"]["windows"][0]
    assert short["n"] == 5
    assert short["qps"] == pytest.approx(0.5)
    assert short["p50_s"] == pytest.approx(0.03)
    assert short["p99_s"] == pytest.approx(0.05)
    assert short["host_fraction"] == pytest.approx(0.2)
    assert short["device_s_per_req"] == pytest.approx(0.004)
    assert short["occupancy"] == pytest.approx(0.5)


def test_healthy_soak_fires_zero_incidents(tmp_path):
    sen, clock, flight, profiler = make_sentinel(tmp_path)
    feed(sen, clock, 0.01, 60)       # exactly at baseline p50
    assert flight.incidents == 0
    assert profiler.events == []
    lane = sen.snapshot()["lanes"]["bfs"]
    assert lane["violating"] is False
    assert all(w["degraded"] is False for w in lane["windows"])


def test_sustained_slowdown_exactly_one_incident_with_profile(tmp_path):
    sen, clock, flight, profiler = make_sentinel(tmp_path)
    feed(sen, clock, 0.01, 30)                  # healthy history
    feed(sen, clock, 0.2, 40)                   # sustained 20× slowdown
    assert flight.incidents == 1                # edge-triggered: ONE
    lane = sen.snapshot()["lanes"]["bfs"]
    assert lane["violating"] is True
    assert lane["alerts_total"] == 1
    # the flight window dump landed beside a profiler capture
    dump = lane["last_incident"]
    assert dump is not None and os.path.exists(dump)
    records = parse_flight_jsonl(open(dump).read())
    assert any(r["kind"] == "incident"
               and r["reason"] == "perf_drift_bfs" for r in records)
    profile_dir = lane["last_profile"]
    assert profile_dir is not None and os.path.isdir(profile_dir)
    assert os.path.dirname(profile_dir) == os.path.dirname(dump)
    assert os.path.exists(os.path.join(profile_dir, "trace.marker"))
    manifest = json.load(open(os.path.join(profile_dir, "PROFILE.json")))
    assert manifest["lane"] == "bfs"
    assert manifest["profiler_active"] is True
    # the session is BOUNDED: it closed profile_s after opening, and the
    # manifest records both edges
    assert profiler.events[0][0] == "open"
    assert ("close", profile_dir) in profiler.events
    assert manifest["t1"] >= manifest["t0"]


def test_short_blip_does_not_alert(tmp_path):
    sen, clock, flight, _ = make_sentinel(tmp_path, windows=(5.0, 60.0))
    feed(sen, clock, 0.01, 60)
    feed(sen, clock, 0.2, 2)         # 2-sample blip: 2/60 < 5% long-window
    feed(sen, clock, 0.01, 10)
    assert flight.incidents == 0


def test_rearm_only_after_every_window_clears(tmp_path):
    sen, clock, flight, _ = make_sentinel(tmp_path, windows=(5.0, 20.0))
    feed(sen, clock, 0.01, 25)
    feed(sen, clock, 0.2, 20)        # sustained → one incident
    assert flight.incidents == 1
    # recover just past the SHORT window: the long window still holds
    # the degraded period, so the lane stays armed-off — a fresh burst
    # must NOT fire a second incident
    feed(sen, clock, 0.01, 7)
    lane = sen.snapshot()["lanes"]["bfs"]
    assert lane["windows"][0]["degraded"] is False
    assert lane["windows"][1]["degraded"] is True
    assert lane["violating"] is True              # not yet re-armed
    feed(sen, clock, 0.2, 6)
    assert flight.incidents == 1
    # clear EVERY window, then a new sustained degradation fires again
    feed(sen, clock, 0.01, 25)
    assert sen.snapshot()["lanes"]["bfs"]["violating"] is False
    feed(sen, clock, 0.2, 20)
    assert flight.incidents == 2


def test_unwatched_lane_never_gates(tmp_path):
    sen, clock, flight, _ = make_sentinel(tmp_path)
    feed(sen, clock, 9.9, 50, kind="pattern")     # no baseline entry
    assert flight.incidents == 0
    lane = sen.snapshot()["lanes"]["pattern"]
    assert lane["watched"] is False
    assert lane["violating"] is False


def test_snapshot_is_a_pure_read(tmp_path):
    sen, clock, flight, profiler = make_sentinel(tmp_path)
    feed(sen, clock, 0.01, 25, tick=False)
    feed(sen, clock, 0.2, 20, tick=False)
    for _ in range(5):
        assert sen.snapshot()["lanes"]["bfs"]["violating"] is False
    assert flight.incidents == 0
    assert profiler.events == []
    sen.tick()                        # the mutating edge
    assert flight.incidents == 1


def test_incidents_rate_limited_by_flight_recorder(tmp_path):
    """The dump machinery is the flight recorder's own rate limit: two
    lanes firing inside min_dump_interval_s cost two COUNTED incidents
    but one dump file."""
    baseline = dict(BASELINE, lanes={
        "bfs": {"p50_s": 0.01, "p99_s": 0.02},
        "range": {"p50_s": 0.01, "p99_s": 0.02},
    })
    sen, clock, flight, _ = make_sentinel(tmp_path, baseline=baseline)
    flight.min_dump_interval_s = 3600.0
    for _ in range(25):
        clock.advance(1.0)
        sen.observe("bfs", 0.01)
        sen.observe("range", 0.01)
        sen.tick()
    for _ in range(20):
        clock.advance(1.0)
        sen.observe("bfs", 0.2)
        sen.observe("range", 0.2)
        sen.tick()
    assert flight.incidents == 2
    assert flight.dumps == 1


# -------------------------------------------------------------------- skew


def test_shard_skew_math_names_the_straggler():
    skew = shard_skew({"shards": [
        {"device": 0, "gid_lo": 0, "gid_hi": 100, "hbm_bytes_in_use": 100},
        {"device": 1, "gid_lo": 100, "gid_hi": 200, "hbm_bytes_in_use": 300},
    ]})
    assert skew["hbm_bytes_in_use"]["ratio"] == pytest.approx(1.5)
    assert skew["hbm_bytes_in_use"]["straggler"] == 1
    assert skew["gid_span"]["ratio"] == pytest.approx(1.0)
    assert shard_skew({}) == {}
    # a CPU mesh (no allocator stats) still reports the structural span
    cpu = shard_skew({"shards": [{"device": 0, "gid_lo": 0,
                                  "gid_hi": 128}]})
    assert "hbm_bytes_in_use" not in cpu


def test_skew_violation_is_edge_triggered(tmp_path):
    report = {"shards": [
        {"device": 0, "hbm_bytes_in_use": 100},
        {"device": 1, "hbm_bytes_in_use": 100},
    ]}
    sen, clock, flight, _ = make_sentinel(
        tmp_path, mesh_source=lambda: report, skew_ratio_max=1.5,
    )
    for _ in range(3):
        clock.advance(1.0)
        sen.tick()
    assert flight.incidents == 0
    report["shards"][1]["hbm_bytes_in_use"] = 1000   # 1.82× mean
    for _ in range(5):
        clock.advance(1.0)
        sen.tick()
    assert flight.incidents == 1                      # edge, not level
    assert sen.health_summary()["violating"] == ["skew"]
    window = parse_flight_jsonl(open(flight.last_dump_path).read())
    inc = [r for r in window if r["kind"] == "incident"][-1]
    assert inc["reason"] == "perf_skew_hbm_bytes_in_use"
    assert inc["straggler"] == 1
    report["shards"][1]["hbm_bytes_in_use"] = 100     # recover → re-arm
    sen.tick()
    assert sen.health_summary()["violating"] == []
    report["shards"][1]["hbm_bytes_in_use"] = 1000
    sen.tick()
    assert flight.incidents == 2


# -------------------------------------------------- end-to-end runtime drill


class SlowFakeExecutor(FakeExecutor):
    """FakeExecutor whose collect costs ``delay`` seconds on the shared
    fake clock — the seeded per-lane slowdown injection."""

    def __init__(self, clock):
        super().__init__()
        self.clock = clock
        self.delay = 0.0

    def collect(self, token):
        self.clock.advance(self.delay)
        return super().collect(token)


def drill_runtime(tmp_path, inject: bool):
    clock = FakeClock()
    flight = FlightRecorder(clock=clock, incident_dir=str(tmp_path),
                            min_dump_interval_s=0.0)
    profiler = FakeProfiler()
    sen = PerfSentinel(baseline=BASELINE, clock=clock, flight=flight,
                       windows=(5.0, 20.0), min_samples=4,
                       eval_interval_s=0.0, profiler=profiler)
    ex = SlowFakeExecutor(clock)
    cfg = ServeConfig(buckets=(4,), max_linger_s=0.0, clock=clock,
                      manual=True, perf=sen)
    rt = ServeRuntime(graph=None, config=cfg, executor=ex)

    def soak(n, delay):
        ex.delay = delay
        for _ in range(n):
            clock.advance(1.0)
            rt.submit_bfs(1)
            rt.step(drain=True)

    soak(25, 0.005)                       # healthy: inside baseline
    soak(25, 0.2 if inject else 0.005)    # the seeded lane slowdown
    return rt, sen, flight, profiler


def test_e2e_drill_injected_slowdown(tmp_path):
    """The acceptance drill: one seeded slow lane → exactly one
    rate-limited incident, flight window + profiler capture in the dump
    dir, ``/fleet/perf`` showing that lane and ONLY that lane."""
    rt, sen, flight, profiler = drill_runtime(tmp_path, inject=True)
    try:
        assert flight.incidents == 1
        lane = sen.snapshot()["lanes"]["bfs"]
        assert lane["violating"] is True
        dump, profile_dir = lane["last_incident"], lane["last_profile"]
        assert dump and os.path.exists(dump)
        window = parse_flight_jsonl(open(dump).read())
        assert [r["reason"] for r in window
                if r["kind"] == "incident"] == ["perf_drift_bfs"]
        assert profile_dir and os.path.exists(
            os.path.join(profile_dir, "trace.marker"))

        # the fleet view: the door names the lane, and only the lane
        collector = FleetCollector(
            [LocalNodeSource("n1", registries=[sen.registry],
                             health=runtime_health(rt))],
            clock=rt.clock, poll_interval_s=0,
        )
        collector.poll()
        fp = collector.fleet_perf()
        assert fp["violating"] == {"n1": ["bfs"]}
        assert fp["nodes"]["n1"]["watched"] == ["bfs"]
        assert fp["alerts_total"] == 1
        assert fp["nodes_reporting"] == 1

        # the perf-drift error-budget objective sees the violating node
        mon = fleet_objectives(collector)
        collector.slo = mon
        for _ in range(3):
            rt.clock.advance(1.0)
            collector.poll()
        snap = mon.snapshot()["perf_drift"]
        assert snap["windows"][0]["events"] >= 2
        assert snap["windows"][0]["error_ratio"] == pytest.approx(1.0)
    finally:
        rt.close()
        sen.close()


def test_e2e_drill_undisturbed_soak_is_silent(tmp_path):
    rt, sen, flight, profiler = drill_runtime(tmp_path, inject=False)
    try:
        assert flight.incidents == 0
        assert profiler.events == []
        assert sen.health_summary()["violating"] == []
        assert not [p for p in os.listdir(tmp_path)]
    finally:
        rt.close()
        sen.close()


def test_runtime_device_batches_feed_the_sentinel():
    clock = FakeClock()
    sen = PerfSentinel(baseline=BASELINE, clock=clock, windows=(5.0,),
                       eval_interval_s=0.0)
    cfg = ServeConfig(buckets=(4,), max_linger_s=0.0, clock=clock,
                      manual=True, perf=sen)
    rt = ServeRuntime(graph=None, config=cfg, executor=FakeExecutor())
    rt.submit_bfs(1)
    clock.advance(0.25)
    rt.step(drain=True)
    rt.close()
    lane = sen.snapshot()["lanes"]["bfs"]
    assert lane["windows"][0]["n"] == 1
    assert lane["windows"][0]["p50_s"] == pytest.approx(0.25)


def test_outrun_sample_ring_is_unknown_not_degraded(tmp_path):
    """A burst that fills the WHOLE bounded ring must not impersonate a
    degraded long window: with history evicted younger than the window
    start, the window is span-truncated → unknown → no page (size
    max_samples ≥ qps × longest window to keep long windows live)."""
    sen, clock, flight, _ = make_sentinel(tmp_path, windows=(5.0, 60.0),
                                          max_samples=8)
    feed(sen, clock, 0.01, 100)      # healthy history (long since evicted)
    # sub-second burst: 8 slow samples fill the ring inside 0.8 s
    for _ in range(8):
        clock.advance(0.1)
        sen.observe("bfs", 0.2)
    snap = sen.tick()
    lane = snap["lanes"]["bfs"]
    assert all(w["span_truncated"] for w in lane["windows"])
    assert all(w["status"] == "unknown" for w in lane["windows"])
    assert flight.incidents == 0
    # a ring that DOES cover the span keeps its verdict power
    sen2, clock2, flight2, _ = make_sentinel(tmp_path)
    feed(sen2, clock2, 0.01, 25)
    feed(sen2, clock2, 0.2, 20)
    assert flight2.incidents == 1


def test_seed_baseline_newest_record_wins(tmp_path):
    """The documented re-seed flow: a fresh real-hardware sweep under a
    NEW tag must beat the committed smokes, whatever the tag — and
    records under a second dir (BENCH_RECORD_DIR) are scanned too."""
    (tmp_path / "BENCH_C6_smoke.json").write_text(json.dumps({
        "schema_version": 1, "tag": "smoke", "backend": "cpu",
        "recorded_unix": 100,
        "c6_serving": {"batched_vs_unbatched": 2.0,
                       "latency_ms_p50": 1000.0, "latency_ms_p99": 2000.0,
                       "served_qps": 1.0},
    }))
    rec_dir = tmp_path / "records"
    rec_dir.mkdir()
    (rec_dir / "BENCH_C6_tpu.json").write_text(json.dumps({
        "schema_version": 2, "tag": "tpu", "backend": "tpu",
        "recorded_unix": 200, "git_rev": "abc",
        "c6_serving": {"batched_vs_unbatched": 9.0, "latency_ms_p50": 2.0,
                       "latency_ms_p99": 4.0, "served_qps": 50000.0},
    }))
    rec = seed_baseline((str(tmp_path), str(rec_dir)))
    assert rec["source"] == ["BENCH_C6_tpu.json"]
    assert rec["backend"] == "tpu"
    assert rec["lanes"]["bfs"]["p50_s"] == pytest.approx(0.002)
    # SAME BASENAME in the record dir still competes (dedup is by real
    # path): a read-only-checkout rerun under the default tag must beat
    # the committed smoke it shadows by name
    (rec_dir / "BENCH_C6_smoke.json").write_text(json.dumps({
        "schema_version": 2, "tag": "smoke", "backend": "tpu",
        "recorded_unix": 300, "git_rev": "abc",
        "c6_serving": {"batched_vs_unbatched": 9.0, "latency_ms_p50": 1.0,
                       "latency_ms_p99": 2.0, "served_qps": 90000.0},
    }))
    rec = seed_baseline((str(tmp_path), str(rec_dir)))
    assert rec["source"] == ["BENCH_C6_smoke.json"]
    assert rec["lanes"]["bfs"]["p50_s"] == pytest.approx(0.001)


def test_min_samples_zero_is_clamped_not_a_crash():
    sen, clock, flight, _ = make_sentinel(min_samples=0)
    assert sen.min_samples == 1
    sen.tick()                      # zero samples: unknown, no division
    assert flight.incidents == 0


def test_undersized_ring_warns_at_construction(caplog):
    import logging

    with caplog.at_level(logging.WARNING, "hypergraphdb_tpu.obs"):
        PerfSentinel(baseline={"lanes": {"range": {"p50_s": 0.01,
                                                   "qps": 12568.0}}},
                     windows=(30.0, 120.0), max_samples=4096)
    assert any("span_truncated" in r.message for r in caplog.records)
    caplog.clear()
    with caplog.at_level(logging.WARNING, "hypergraphdb_tpu.obs"):
        PerfSentinel(baseline={"lanes": {"bfs": {"p50_s": 0.01,
                                                 "qps": 10.0}}},
                     windows=(30.0, 120.0), max_samples=4096)
    assert not caplog.records       # ring covers the window: silent


def test_broken_sentinel_never_strands_a_batch():
    """The runtime's perf hooks are guarded: an evaluation bug degrades
    observability, never a request."""
    class ExplodingSentinel:
        def observe(self, *a, **k):
            raise RuntimeError("boom")

        def observe_batch(self, *a, **k):
            raise RuntimeError("boom")

        def maybe_tick(self):
            raise RuntimeError("boom")

    clock = FakeClock()
    cfg = ServeConfig(buckets=(4,), max_linger_s=0.0, clock=clock,
                      manual=True, perf=ExplodingSentinel())
    rt = ServeRuntime(graph=None, config=cfg, executor=FakeExecutor())
    fut = rt.submit_bfs(1)
    rt.step(drain=True)
    assert fut.result(timeout=0).kind == "bfs"   # resolved, not stranded
    rt.close()


def test_seed_baseline_flags_mixed_backends(tmp_path):
    (tmp_path / "BENCH_C6_tpu.json").write_text(json.dumps({
        "schema_version": 2, "tag": "tpu", "backend": "tpu",
        "recorded_unix": 200, "git_rev": "x",
        "c6_serving": {"batched_vs_unbatched": 9.0, "latency_ms_p50": 2.0,
                       "latency_ms_p99": 4.0, "served_qps": 50000.0},
    }))
    (tmp_path / "BENCH_C9_smoke.json").write_text(json.dumps({
        "schema_version": 1, "tag": "smoke", "backend": "cpu",
        "recorded_unix": 100,
        "c9_value_index": {"latency_ms_p50": 5.0, "latency_ms_p99": 8.0,
                           "served_qps": 900.0},
    }))
    rec = seed_baseline(str(tmp_path))
    assert rec["backend"] == "mixed"            # loud, not masqueraded
    assert rec["lanes"]["bfs"]["backend"] == "tpu"
    assert rec["lanes"]["range"]["backend"] == "cpu"


def test_concurrent_alert_edges_open_one_profile_session(tmp_path):
    """Two lanes firing in the same evaluation reserve ONE bounded
    session (check-and-reserve is atomic; a racing loser must not leak
    an unclosed profiler)."""
    baseline = dict(BASELINE, lanes={
        "bfs": {"p50_s": 0.01, "p99_s": 0.02},
        "range": {"p50_s": 0.01, "p99_s": 0.02},
    })
    sen, clock, flight, profiler = make_sentinel(tmp_path,
                                                 baseline=baseline)
    for _ in range(25):
        clock.advance(1.0)
        sen.observe("bfs", 0.01)
        sen.observe("range", 0.01)
        sen.tick()
    for _ in range(20):
        clock.advance(1.0)
        sen.observe("bfs", 0.2)
        sen.observe("range", 0.2)
        sen.tick()
    assert flight.incidents == 2                 # both lanes fired...
    opens = [e for e in profiler.events if e[0] == "open"]
    closes = [e for e in profiler.events if e[0] == "close"]
    assert len(opens) == 1                       # ...one session opened
    assert len(closes) == 1                      # ...and it was closed
    sen.close()
