"""hgplan planner differential suite: every candidate plan, same answer.

The planner's core safety claim is that plan choice can only change COST,
never RESULTS: for every condition in a seeded corpus, every enumerable
candidate shape (forced via ``submit_planned(force_shape=...)``) must
return exactly ``graph.find_all``'s match set — device lanes, host
residual filters, truncation fallbacks and all. Runs the real
DeviceExecutor under ``JAX_PLATFORMS=cpu`` with manual stepping.
"""

from __future__ import annotations

import numpy as np
import pytest

from hypergraphdb_tpu.plan import PlanFeedback, QueryPlanner
from hypergraphdb_tpu.query import conditions as c
from hypergraphdb_tpu.serve import ServeConfig, ServeRuntime
from hypergraphdb_tpu.serve.types import Unservable


def _runtime(g, **kw):
    kw.setdefault("top_r", 256)
    cfg = ServeConfig(buckets=(64,), manual=True, max_linger_s=0.0, **kw)
    rt = ServeRuntime(g, cfg)
    rt.attach_planner(QueryPlanner(g))
    return rt


def _drain(rt):
    while rt.step(drain=True):
        pass


def _skewed_graph(g, rng, n=40):
    """The planner's home turf: a hub node soaking most links, typed
    links with int values, a couple of sparse satellites — so different
    clauses of one conjunction have wildly different cardinalities."""
    nodes = [int(g.add(i)) for i in range(n)]
    hub = nodes[0]
    links = []
    for i in range(3 * n):
        other = nodes[1 + int(rng.integers(n - 1))]
        links.append(int(g.add_link([hub, other], value=100 + i)))
    # sparse corner: one atom with exactly two incident links
    rare = nodes[-1]
    links.append(int(g.add_link([rare, nodes[1]], value=500)))
    links.append(int(g.add_link([rare, nodes[2]], value=501)))
    return nodes, links, hub, rare


def _corpus(g, nodes, links, hub, rare):
    lt = int(g.get_type_handle_of(links[0]))
    return [
        c.And(c.AtomValue(105, "gte"), c.AtomValue(130, "lte")),
        c.And(c.AtomValue(105, "gte"), c.AtomValue(130, "lte"),
              c.AtomType(lt)),
        c.And(c.AtomValue(100, "gte"), c.AtomValue(520, "lte"),
              c.Incident(rare)),
        c.And(c.Incident(hub), c.AtomType(lt)),
        c.And(c.Incident(rare), c.Incident(nodes[1])),
        c.And(c.CoIncident(rare)),
        c.And(c.CoIncident(rare), c.AtomValue(0, "gte")),
        c.And(c.BFS(rare, 2), c.AtomType(lt)),
        c.AtomValue(110, "eq"),
    ]


def test_every_candidate_shape_is_result_identical(graph, rng):
    nodes, links, hub, rare = _skewed_graph(graph, rng)
    rt = _runtime(graph)
    conds = _corpus(graph, nodes, links, hub, rare)
    futs = []
    for cond in conds:
        truth = sorted(int(h) for h in graph.find_all(cond))
        shapes = rt.planner.shapes_for(cond)
        assert "host" in shapes  # the oracle shape is always enumerable
        for shape in shapes:
            futs.append((cond, shape, truth,
                         rt.submit_planned(cond, force_shape=shape)))
    _drain(rt)
    rt.close()
    for cond, shape, truth, fut in futs:
        res = fut.result(timeout=0)
        assert list(res.matches) == truth, (cond, shape)
        assert res.count == len(truth)
        assert not res.truncated
        assert res.plan["shape"] == shape


def test_planner_default_choice_matches_oracle(graph, rng):
    """The unforced (cheapest) choice is just as exact — and the plan
    record carries est/actual for the feedback loop."""
    nodes, links, hub, rare = _skewed_graph(graph, rng)
    rt = _runtime(graph)
    conds = _corpus(graph, nodes, links, hub, rare)
    futs = [(cond, sorted(int(h) for h in graph.find_all(cond)),
             rt.submit_planned(cond, explain=True)) for cond in conds]
    _drain(rt)
    rt.close()
    for cond, truth, fut in futs:
        res = fut.result(timeout=0)
        assert list(res.matches) == truth, cond
        assert "est_rows" in res.plan and "actual_rows" in res.plan
        assert res.plan["actual_rows"] >= 0
        ex = getattr(fut, "explain", None)
        assert ex is not None and ex["plan"]["shape"] == res.plan["shape"]
    assert rt.stats.plan_requests == len(conds)
    assert sum(rt.stats.plan_choice_counts().values()) == len(conds)


def test_planner_prefers_cheap_anchor_on_skewed_graph(graph, rng):
    """On the skewed graph, a conjunction anchored at BOTH the hub and
    the rare atom must plan through the rare end: the chosen candidate's
    estimate reflects the sparse anchor, not the hub."""
    nodes, links, hub, rare = _skewed_graph(graph, rng)
    rt = _runtime(graph)
    cond = c.And(c.Incident(hub), c.Incident(rare))
    choice = rt.planner.plan(cond)
    est = rt.planner.estimator
    assert choice.est_rows <= est.degree(rare)
    assert choice.est_rows < est.degree(hub)
    fut = rt.submit_planned(cond)
    _drain(rt)
    rt.close()
    truth = sorted(int(h) for h in graph.find_all(cond))
    assert list(fut.result(timeout=0).matches) == truth


def test_truncated_lane_windows_reserve_exactly(graph, rng):
    """A range window wider than the lane's top-k truncates on device;
    the planned result must re-serve brute-force and stay exact."""
    nodes, links, hub, rare = _skewed_graph(graph, rng)
    rt = _runtime(graph, top_r=4)
    cond = c.And(c.AtomValue(100, "gte"), c.AtomValue(400, "lte"))
    truth = sorted(int(h) for h in graph.find_all(cond))
    assert len(truth) > 4
    fut = rt.submit_planned(cond, force_shape="range_first")
    _drain(rt)
    rt.close()
    res = fut.result(timeout=0)
    assert list(res.matches) == truth
    assert res.served_by == "host"  # truncation fallback
    assert not res.truncated


def test_submit_planned_requires_attached_planner(graph):
    rt = ServeRuntime(graph, ServeConfig(buckets=(64,), manual=True,
                                         max_linger_s=0.0))
    with pytest.raises(Unservable):
        rt.submit_planned(c.AtomValue(1, "eq"))
    rt.close()


def test_planner_priors_read_the_committed_baseline(graph, tmp_path,
                                                    monkeypatch):
    """``from_committed_baseline`` prices lanes from the SAME record
    ``bench.py --seed-baseline`` writes (``HG_PERF_BASELINE`` points at
    it), and degrades to the default prior table when the file is
    missing — never fails."""
    import json

    from hypergraphdb_tpu.plan.planner import DEFAULT_LANE_PRIOR_S

    path = tmp_path / "PERF_BASELINE.json"
    path.write_text(json.dumps({
        "schema_version": 1,
        "lanes": {"join": {"p50_s": 0.123, "qps": 10.0},
                  "range": {"p50_s": 0.004}},
    }))
    monkeypatch.setenv("HG_PERF_BASELINE", str(path))
    p = QueryPlanner.from_committed_baseline(graph)
    assert p._priors["join"] == 0.123
    assert p._priors["range"] == 0.004
    assert p._priors["pattern"] == DEFAULT_LANE_PRIOR_S["pattern"]

    monkeypatch.setenv("HG_PERF_BASELINE", str(tmp_path / "absent.json"))
    p2 = QueryPlanner.from_committed_baseline(graph)
    assert p2._priors == DEFAULT_LANE_PRIOR_S


def test_force_shape_rejects_non_candidates(graph):
    graph.add(1)
    rt = _runtime(graph)
    with pytest.raises(ValueError):
        rt.planner.plan(c.AtomValue(1, "eq"), force_shape="bfs")
    rt.close()


def test_plan_metrics_reach_the_registry(graph, rng):
    """plan.* instruments move with planned traffic and ride the same
    governed registry the drift gate audits."""
    nodes, links, hub, rare = _skewed_graph(graph, rng)
    rt = _runtime(graph)
    futs = [rt.submit_planned(cond)
            for cond in _corpus(graph, nodes, links, hub, rare)]
    _drain(rt)
    for f in futs:
        f.result(timeout=0)
    names = rt.stats.registry.names()
    for name in ("plan.requests", "plan.est_rows", "plan.actual_rows",
                 "plan.abs_rel_error", "plan.guard_vetoes"):
        assert name in names
    assert rt.stats.plan_requests == len(futs)
    rt.close()
