"""Sharded (multi-chip) execution tests on the virtual 8-device CPU mesh.

Differential tests: the mesh-sharded kernels must agree bit-for-bit with the
single-device kernels in ``ops.frontier`` / ``ops.setops`` (which are
themselves differential-tested against the host query engine).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hypergraphdb_tpu.ops.frontier import bfs_levels
from hypergraphdb_tpu.ops.snapshot import CSRSnapshot
from hypergraphdb_tpu.parallel import (
    ShardedSnapshot,
    and_incident_pattern_sharded,
    bfs_levels_sharded,
    make_mesh,
)
from hypergraphdb_tpu.query import dsl as q

from conftest import make_random_hypergraph


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "conftest must force 8 CPU devices"
    return make_mesh()


def test_sharded_bfs_matches_single_device(graph, mesh):
    nodes, links = make_random_hypergraph(graph, n_nodes=150, n_links=300, seed=3)
    snap = CSRSnapshot.pack(graph)
    sdev = ShardedSnapshot.from_host(snap, mesh)

    seeds = jnp.asarray([int(nodes[i]) for i in (0, 7, 42, 99)], dtype=jnp.int32)
    lv_ref, vis_ref = bfs_levels(snap.device, seeds, max_hops=3)
    lv_sh, vis_sh = bfs_levels_sharded(sdev, seeds, max_hops=3)

    np.testing.assert_array_equal(np.asarray(vis_ref), np.asarray(vis_sh))
    np.testing.assert_array_equal(np.asarray(lv_ref), np.asarray(lv_sh))


def test_sharded_pattern_matches_host_query(graph, mesh):
    nodes, links = make_random_hypergraph(graph, n_nodes=120, n_links=400, seed=5)
    snap = CSRSnapshot.pack(graph)
    sdev = ShardedSnapshot.from_host(snap, mesh)

    # pick two anchors that share at least one incident link
    a1 = int(nodes[0])
    row = snap.incidence_row(a1)
    assert len(row), "node 0 should have incident links"
    lk = int(row[0])
    others = [int(t) for t in graph.get_targets(lk) if int(t) != a1]
    a2 = others[0] if others else int(nodes[1])

    link_type = graph.get_type_handle_of(links[0])
    got = and_incident_pattern_sharded(snap, sdev, int(link_type), [a1, a2])

    want = sorted(
        q.find_all(graph, q.and_(q.type_(int(link_type)),
                                 q.incident(a1), q.incident(a2)))
    )
    assert sorted(got.tolist()) == want


def test_sharded_bfs_empty_frontier_stops(graph, mesh):
    # isolated node: BFS finds nothing beyond the seed at any hop count
    h = graph.add("loner")
    graph.add("other")
    snap = CSRSnapshot.pack(graph)
    sdev = ShardedSnapshot.from_host(snap, mesh)
    lv, vis = bfs_levels_sharded(
        sdev, jnp.asarray([int(h)], dtype=jnp.int32), max_hops=4
    )
    vis = np.asarray(vis)[0]
    assert vis.sum() == 1 and vis[int(h)]


def test_blocked_sharded_bfs_matches_unblocked(graph, mesh):
    """The seed-blocked driver (VERDICT r2 item 8) must agree with one big
    launch and report measured per-device memory stats."""
    from hypergraphdb_tpu.parallel.sharded import (
        bfs_packed_sharded,
        bfs_packed_sharded_blocked,
    )

    nodes, links = make_random_hypergraph(graph, n_nodes=200, n_links=350, seed=8)
    snap = CSRSnapshot.pack(graph)
    sdev = ShardedSnapshot.from_host(snap, mesh)
    rng = np.random.default_rng(4)
    seeds = np.asarray(
        [int(nodes[i]) for i in rng.integers(0, len(nodes), size=96)],
        dtype=np.int32,
    )
    vis_all, cnt_all, _ = bfs_packed_sharded(
        sdev, jnp.asarray(seeds), max_hops=3
    )
    vis_blk, cnt_blk, peaks = bfs_packed_sharded_blocked(
        sdev, seeds, max_hops=3, k_block=32
    )
    np.testing.assert_array_equal(np.asarray(vis_all), np.asarray(vis_blk))
    np.testing.assert_array_equal(
        np.asarray(cnt_all).astype(np.int64), cnt_blk
    )
    assert isinstance(peaks, dict)  # CPU backends may report no stats


def test_blocked_sharded_bfs_validates_k_block(graph, mesh):
    from hypergraphdb_tpu.parallel.sharded import bfs_packed_sharded_blocked

    nodes, _ = make_random_hypergraph(graph, n_nodes=40, n_links=60, seed=2)
    snap = CSRSnapshot.pack(graph)
    sdev = ShardedSnapshot.from_host(snap, mesh)
    with pytest.raises(ValueError, match="k_block"):
        bfs_packed_sharded_blocked(
            sdev, np.asarray([int(nodes[0])]), 2, k_block=48
        )


# --------------------------------------------------------------------------
# sharded (base, delta) overlay — VERDICT r4 item 3
# --------------------------------------------------------------------------


def _make_mgr_with_delta(graph, seed=11):
    """Base-packed manager + post-base mutations living only in the delta."""
    from hypergraphdb_tpu.ops.incremental import SnapshotManager

    nodes, links = make_random_hypergraph(
        graph, n_nodes=120, n_links=200, seed=seed
    )
    mgr = SnapshotManager(graph, headroom=2.0, compact_ratio=50.0)
    base_epoch = mgr.compactions
    # post-base: new links between existing nodes, one new node + link,
    # one removal — all must be visible through the sharded overlay
    r = np.random.default_rng(seed + 1)
    for _ in range(40):
        a, b = (int(x) for x in r.choice(nodes, size=2, replace=False))
        graph.add_link([a, b], value="post-base")
    nn = graph.add("post-base-node")
    graph.add_link([int(nn), int(nodes[0])], value="post-base-bridge")
    graph.remove(int(links[3]))
    assert mgr.compactions == base_epoch, "delta must not have compacted"
    assert mgr.delta_edges > 0
    return mgr, nodes, nn


def test_sharded_delta_bfs_matches_host_oracle(graph, mesh):
    """Sharded (base, delta) BFS must agree bit-for-bit with the
    single-device bfs_levels_delta oracle, including post-base links."""
    from hypergraphdb_tpu.ops.incremental import bfs_levels_delta
    from hypergraphdb_tpu.parallel import (
        bfs_levels_sharded_delta,
        shard_host_delta,
    )

    mgr, nodes, nn = _make_mgr_with_delta(graph)
    dev, delta = mgr.device()
    sdev = ShardedSnapshot.from_host(mgr.base, mesh)
    sdelta = shard_host_delta(sdev, mgr.host_delta())

    seeds = jnp.asarray(
        [int(nodes[0]), int(nodes[7]), int(nn)], dtype=jnp.int32
    )
    lv_ref, vis_ref = bfs_levels_delta(dev, delta, seeds, max_hops=3)
    lv_sh, vis_sh = bfs_levels_sharded_delta(sdev, sdelta, seeds, max_hops=3)

    np.testing.assert_array_equal(np.asarray(vis_ref), np.asarray(vis_sh))
    np.testing.assert_array_equal(np.asarray(lv_ref), np.asarray(lv_sh))
    # the post-base node is reachable from nodes[0] through the bridge link
    assert bool(np.asarray(vis_sh)[0, int(nn)])
    mgr.close()


def test_sharded_delta_sees_post_base_links(graph, mesh):
    """A link added after the base pack must connect components through the
    sharded overlay (the read-freshness contract of BASELINE config 5)."""
    from hypergraphdb_tpu.ops.incremental import SnapshotManager
    from hypergraphdb_tpu.parallel import (
        bfs_packed_sharded_delta,
        shard_host_delta,
    )
    from hypergraphdb_tpu.ops.bitfrontier import unpack_visited

    a = graph.add("a")
    b = graph.add("b")
    mgr = SnapshotManager(graph, headroom=4.0, compact_ratio=50.0)
    sdev = ShardedSnapshot.from_host(mgr.base, mesh)

    # before: a and b are disconnected
    sd0 = shard_host_delta(sdev, mgr.host_delta())
    vis0, _, _ = bfs_packed_sharded_delta(
        sdev, sd0, jnp.asarray([int(a)], dtype=jnp.int32), 2
    )
    assert not unpack_visited(np.asarray(vis0), sdev.num_atoms)[0][int(b)]

    graph.add_link([int(a), int(b)], value="bridge")
    sd1 = shard_host_delta(sdev, mgr.host_delta())
    vis1, counts, _ = bfs_packed_sharded_delta(
        sdev, sd1, jnp.asarray([int(a)], dtype=jnp.int32), 2
    )
    assert unpack_visited(np.asarray(vis1), sdev.num_atoms)[0][int(b)]
    assert int(np.asarray(counts)[0]) >= 1
    mgr.close()


def test_sharded_delta_tombstones_and_epoch_guard(graph, mesh):
    """Removed atoms must be invisible through the overlay; a stale delta
    (capacity from another epoch) must be rejected loudly."""
    from hypergraphdb_tpu.ops.incremental import SnapshotManager
    from hypergraphdb_tpu.parallel import (
        bfs_packed_sharded_delta,
        shard_host_delta,
    )
    from hypergraphdb_tpu.ops.bitfrontier import unpack_visited

    a = graph.add("a")
    b = graph.add("b")
    c = graph.add("c")
    graph.add_link([int(a), int(b)], value=1)
    lk = graph.add_link([int(b), int(c)], value=2)
    mgr = SnapshotManager(graph, headroom=4.0, compact_ratio=50.0)
    sdev = ShardedSnapshot.from_host(mgr.base, mesh)

    graph.remove(int(lk))  # tombstone the b—c link post-base
    sd = shard_host_delta(sdev, mgr.host_delta())
    vis, _, _ = bfs_packed_sharded_delta(
        sdev, sd, jnp.asarray([int(a)], dtype=jnp.int32), 4
    )
    row = unpack_visited(np.asarray(vis), sdev.num_atoms)[0]
    assert row[int(b)] and not row[int(c)]

    hd = mgr.host_delta()
    hd["capacity"] = hd["capacity"] + 128  # simulate post-compaction epoch
    with pytest.raises(ValueError, match="epoch"):
        shard_host_delta(sdev, hd)
    mgr.close()


def test_sharded_delta_pattern_merges_memtable(graph, mesh):
    """(base, delta)-aware sharded pattern: post-base links of the right
    type appear, tombstoned ones vanish — results equal the live host
    query engine's answer (VERDICT r4 item 3, pattern half)."""
    from hypergraphdb_tpu.ops.incremental import SnapshotManager
    from hypergraphdb_tpu.parallel import and_incident_pattern_sharded_delta

    nodes, links = make_random_hypergraph(
        graph, n_nodes=80, n_links=150, seed=13
    )
    mgr = SnapshotManager(graph, headroom=2.0, compact_ratio=50.0)
    sdev = ShardedSnapshot.from_host(mgr.base, mesh)

    a1, a2 = int(nodes[0]), int(nodes[1])
    link_type = int(graph.get_type_handle_of(links[0]))
    # post-base: one matching link, and remove any pre-existing match
    fresh = graph.add_link([a1, a2], value=999_999)
    pre = q.find_all(graph, q.and_(
        q.type_(link_type), q.incident(a1), q.incident(a2)
    ))
    doomed = next((int(h) for h in pre if int(h) != int(fresh)), None)
    if doomed is not None:
        graph.remove(doomed)

    got = sorted(
        int(x) for x in and_incident_pattern_sharded_delta(
            mgr, sdev, link_type, [a1, a2]
        )
    )
    want = sorted(q.find_all(graph, q.and_(
        q.type_(link_type), q.incident(a1), q.incident(a2)
    )))
    assert got == want
    assert int(fresh) in got
    if doomed is not None:
        assert doomed not in got
    mgr.close()


def test_sharded_delta_pattern_handles_revalued_and_anchorless(graph, mesh):
    """Review r5 finding: a replace() that changes an atom's TYPE must
    drop it from (or surface it into) the sharded delta pattern result;
    anchorless calls are rejected loudly."""
    from hypergraphdb_tpu.ops.incremental import SnapshotManager
    from hypergraphdb_tpu.parallel import and_incident_pattern_sharded_delta

    a = graph.add("a")
    b = graph.add("b")
    l_int = graph.add_link((a, b), value=7)
    l_str = graph.add_link((a, b), value="s")
    mgr = SnapshotManager(graph, headroom=3.0, compact_ratio=50.0)
    sdev = ShardedSnapshot.from_host(mgr.base, mesh)
    th_int = int(graph.get_type_handle_of(l_int))

    graph.replace(int(l_int), "now-a-string")   # int → string post-base
    graph.replace(int(l_str), 42)               # string → int post-base
    got = sorted(int(x) for x in and_incident_pattern_sharded_delta(
        mgr, sdev, th_int, [int(a), int(b)]
    ))
    want = sorted(q.find_all(graph, q.and_(
        q.type_(th_int), q.incident(int(a)), q.incident(int(b))
    )))
    assert got == want == [int(l_str)]

    with pytest.raises(ValueError, match="anchor"):
        and_incident_pattern_sharded_delta(mgr, sdev, th_int, [])
    mgr.close()
