"""Sharded (multi-chip) execution tests on the virtual 8-device CPU mesh.

Differential tests: the mesh-sharded kernels must agree bit-for-bit with the
single-device kernels in ``ops.frontier`` / ``ops.setops`` (which are
themselves differential-tested against the host query engine).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hypergraphdb_tpu.ops.frontier import bfs_levels
from hypergraphdb_tpu.ops.snapshot import CSRSnapshot
from hypergraphdb_tpu.parallel import (
    ShardedSnapshot,
    and_incident_pattern_sharded,
    bfs_levels_sharded,
    make_mesh,
)
from hypergraphdb_tpu.query import dsl as q

from conftest import make_random_hypergraph


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "conftest must force 8 CPU devices"
    return make_mesh()


def test_sharded_bfs_matches_single_device(graph, mesh):
    nodes, links = make_random_hypergraph(graph, n_nodes=150, n_links=300, seed=3)
    snap = CSRSnapshot.pack(graph)
    sdev = ShardedSnapshot.from_host(snap, mesh)

    seeds = jnp.asarray([int(nodes[i]) for i in (0, 7, 42, 99)], dtype=jnp.int32)
    lv_ref, vis_ref = bfs_levels(snap.device, seeds, max_hops=3)
    lv_sh, vis_sh = bfs_levels_sharded(sdev, seeds, max_hops=3)

    np.testing.assert_array_equal(np.asarray(vis_ref), np.asarray(vis_sh))
    np.testing.assert_array_equal(np.asarray(lv_ref), np.asarray(lv_sh))


def test_sharded_pattern_matches_host_query(graph, mesh):
    nodes, links = make_random_hypergraph(graph, n_nodes=120, n_links=400, seed=5)
    snap = CSRSnapshot.pack(graph)
    sdev = ShardedSnapshot.from_host(snap, mesh)

    # pick two anchors that share at least one incident link
    a1 = int(nodes[0])
    row = snap.incidence_row(a1)
    assert len(row), "node 0 should have incident links"
    lk = int(row[0])
    others = [int(t) for t in graph.get_targets(lk) if int(t) != a1]
    a2 = others[0] if others else int(nodes[1])

    link_type = graph.get_type_handle_of(links[0])
    got = and_incident_pattern_sharded(snap, sdev, int(link_type), [a1, a2])

    want = sorted(
        q.find_all(graph, q.and_(q.type_(int(link_type)),
                                 q.incident(a1), q.incident(a2)))
    )
    assert sorted(got.tolist()) == want


def test_sharded_bfs_empty_frontier_stops(graph, mesh):
    # isolated node: BFS finds nothing beyond the seed at any hop count
    h = graph.add("loner")
    graph.add("other")
    snap = CSRSnapshot.pack(graph)
    sdev = ShardedSnapshot.from_host(snap, mesh)
    lv, vis = bfs_levels_sharded(
        sdev, jnp.asarray([int(h)], dtype=jnp.int32), max_hops=4
    )
    vis = np.asarray(vis)[0]
    assert vis.sum() == 1 and vis[int(h)]


def test_blocked_sharded_bfs_matches_unblocked(graph, mesh):
    """The seed-blocked driver (VERDICT r2 item 8) must agree with one big
    launch and report measured per-device memory stats."""
    from hypergraphdb_tpu.parallel.sharded import (
        bfs_packed_sharded,
        bfs_packed_sharded_blocked,
    )

    nodes, links = make_random_hypergraph(graph, n_nodes=200, n_links=350, seed=8)
    snap = CSRSnapshot.pack(graph)
    sdev = ShardedSnapshot.from_host(snap, mesh)
    rng = np.random.default_rng(4)
    seeds = np.asarray(
        [int(nodes[i]) for i in rng.integers(0, len(nodes), size=96)],
        dtype=np.int32,
    )
    vis_all, cnt_all, _ = bfs_packed_sharded(
        sdev, jnp.asarray(seeds), max_hops=3
    )
    vis_blk, cnt_blk, peaks = bfs_packed_sharded_blocked(
        sdev, seeds, max_hops=3, k_block=32
    )
    np.testing.assert_array_equal(np.asarray(vis_all), np.asarray(vis_blk))
    np.testing.assert_array_equal(
        np.asarray(cnt_all).astype(np.int64), cnt_blk
    )
    assert isinstance(peaks, dict)  # CPU backends may report no stats


def test_blocked_sharded_bfs_validates_k_block(graph, mesh):
    from hypergraphdb_tpu.parallel.sharded import bfs_packed_sharded_blocked

    nodes, _ = make_random_hypergraph(graph, n_nodes=40, n_links=60, seed=2)
    snap = CSRSnapshot.pack(graph)
    sdev = ShardedSnapshot.from_host(snap, mesh)
    with pytest.raises(ValueError, match="k_block"):
        bfs_packed_sharded_blocked(
            sdev, np.asarray([int(nodes[0])]), 2, k_block=48
        )
