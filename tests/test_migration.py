"""On-disk format versioning + migration chain (VERDICT r4 missing #6 —
the reference's maintenance upgrades; the WAL magic alone cannot
distinguish new layout from corruption)."""

import pytest

import hypergraphdb_tpu as hg
from hypergraphdb_tpu.maintenance import migration as mig


@pytest.fixture(autouse=True)
def _clean_registry():
    saved = dict(mig._MIGRATIONS)
    yield
    mig._MIGRATIONS.clear()
    mig._MIGRATIONS.update(saved)


def test_fresh_db_stamped_current():
    g = hg.HyperGraph()
    assert mig.stored_format_version(g) == mig.FORMAT_VERSION
    g.close()


def test_migration_chain_runs_and_stamps(tmp_path, monkeypatch):
    pytest.importorskip("hypergraphdb_tpu.storage.native")
    loc = str(tmp_path / "db")
    g = hg.HyperGraph(hg.HGConfiguration(store_backend="native", location=loc))
    g.add("survivor")
    assert mig.stored_format_version(g) == mig.FORMAT_VERSION
    g.close()

    ran = []
    mig.register_migration(mig.FORMAT_VERSION, lambda graph: ran.append(1))
    mig.register_migration(mig.FORMAT_VERSION + 1, lambda graph: ran.append(2))
    monkeypatch.setattr(mig, "FORMAT_VERSION", mig.FORMAT_VERSION + 2)

    g2 = hg.HyperGraph(hg.HGConfiguration(store_backend="native", location=loc))
    assert ran == [1, 2]  # both steps, in order
    assert mig.stored_format_version(g2) == mig.FORMAT_VERSION
    assert len([h for h in g2.atoms() if g2.get(h) == "survivor"]) == 1
    g2.close()


def test_newer_db_refuses_to_open(tmp_path, monkeypatch):
    pytest.importorskip("hypergraphdb_tpu.storage.native")
    loc = str(tmp_path / "db")
    g = hg.HyperGraph(hg.HGConfiguration(store_backend="native", location=loc))
    mig.stamp_format_version(g, mig.FORMAT_VERSION + 5)
    g.close()
    with pytest.raises(mig.MigrationError, match="newer"):
        hg.HyperGraph(hg.HGConfiguration(store_backend="native", location=loc))


def test_missing_migration_step_raises(monkeypatch):
    g = hg.HyperGraph()
    monkeypatch.setattr(mig, "FORMAT_VERSION", mig.FORMAT_VERSION + 1)
    mig.stamp_format_version(g, mig.FORMAT_VERSION - 1)
    with pytest.raises(mig.MigrationError, match="no migration"):
        mig.migrate(g)
    g.close()


def test_crash_mid_chain_resumes(tmp_path, monkeypatch):
    """Each completed step stamps: a failure in step 2 leaves step 1's
    stamp, so the next open reruns only step 2."""
    pytest.importorskip("hypergraphdb_tpu.storage.native")
    loc = str(tmp_path / "db")
    g = hg.HyperGraph(hg.HGConfiguration(store_backend="native", location=loc))
    g.close()

    ran = []

    def boom(graph):
        ran.append("step2-fail")
        raise RuntimeError("mid-chain crash")

    mig.register_migration(mig.FORMAT_VERSION, lambda graph: ran.append(1))
    mig.register_migration(mig.FORMAT_VERSION + 1, boom)
    monkeypatch.setattr(mig, "FORMAT_VERSION", mig.FORMAT_VERSION + 2)
    with pytest.raises(RuntimeError):
        hg.HyperGraph(
            hg.HGConfiguration(store_backend="native", location=loc)
        )
    # step 1 completed and stamped; resume runs ONLY step 2
    mig.register_migration(mig.FORMAT_VERSION - 1, lambda graph: ran.append(2))
    g3 = hg.HyperGraph(hg.HGConfiguration(store_backend="native", location=loc))
    assert ran == [1, "step2-fail", 2]
    assert mig.stored_format_version(g3) == mig.FORMAT_VERSION
    g3.close()
