"""hgjoin differential suite: device joins == host ``find_all`` truth.

The worst-case-optimal executor (``ops/join``) and the GHD-lite planner
(``join/planner``) are held to the exact host enumerator
(``join/host.host_join`` — find_all + satisfies, a deliberately separate
implementation path) on seeded random graphs across every supported
shape: triangles, paths, stars, typed variants, link-variable patterns,
empty results, duplicate-target links, pad-lane garbage, truncation
prefixes, and mid-ingest memtable visibility through the serving lane.
"""

from __future__ import annotations

import numpy as np
import pytest

from hypergraphdb_tpu import join
from hypergraphdb_tpu.join.ir import (
    ConjunctivePattern,
    JoinAtom,
    JoinUnsupported,
)
from hypergraphdb_tpu.ops.join import execute_join, neighbor_csr
from hypergraphdb_tpu.query import conditions as c
from hypergraphdb_tpu.query import dsl as q
from hypergraphdb_tpu.query.variables import var
from tests.conftest import make_random_hypergraph


def _build(g, seed=0, n_nodes=80, n_links=160):
    nodes, links = make_random_hypergraph(
        g, n_nodes=n_nodes, n_links=n_links, max_arity=4, seed=seed
    )
    return [int(n) for n in nodes], [int(x) for x in links]


def _device_rows(g, pattern, **kw):
    """Full device binding rows in the REQUEST's variable order.
    Exact-count shape policy by default — the truncation contract has
    its own test (:func:`test_truncation_honest_prefix`)."""
    kw.setdefault("var_pad_max", True)
    snap = g.snapshot()
    sig, consts = join.split_constants(pattern)
    plan = join.plan_join(snap, pattern, sig, consts)
    out = execute_join(snap, plan, np.asarray([consts], dtype=np.int32),
                       top_r=0, full=True, **kw)
    rows = out.full_bindings(0)
    perm = [plan.order.index(v) for v in pattern.vars]
    dev = sorted(tuple(int(x) for x in row[perm]) for row in rows)
    trunc = bool(np.asarray(out.trunc)[0])
    count = int(np.asarray(out.counts)[0])
    return dev, count, trunc


def _check(g, spec, distinct=True, **kw):
    p = join.extract_pattern(g, spec, distinct=distinct)
    truth = join.host_join(g, p)
    dev, count, trunc = _device_rows(g, p, **kw)
    assert not trunc
    assert dev == truth
    assert count == len(truth)
    return truth


# ---------------------------------------------------------------- shapes


SHAPES = {
    "triangle": lambda a: {
        "y": c.And(c.CoIncident(a), c.CoIncident(var("z"))),
        "z": c.CoIncident(a),
    },
    "path2": lambda a: {
        "y": c.CoIncident(a),
        "z": c.CoIncident(var("y")),
    },
    "star3": lambda a: {
        "y": c.CoIncident(a),
        "z": c.CoIncident(a),
        "w": c.CoIncident(a),
    },
    "link_var": lambda a: {
        "l": c.Incident(a),
        "y": c.Target(var("l")),
    },
}


@pytest.mark.parametrize("shape", sorted(SHAPES))
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_device_join_matches_host_truth(graph, shape, seed):
    nodes, _ = _build(graph, seed=seed)
    _check(graph, SHAPES[shape](nodes[3 + seed]))


def test_host_join_reorders_spec_declaration_order(graph):
    """The spec declares y BEFORE its generator z is bound — the host
    enumerator must find a feasible binding order (the device planner
    reorders freely; the exact fallback has to keep up), and tuples
    still read in spec-declared variable order."""
    nodes, _ = _build(graph, seed=3)
    a = nodes[6]
    fwd = {"z": c.CoIncident(a), "y": c.CoIncident(var("z"))}
    rev = {"y": c.CoIncident(var("z")), "z": c.CoIncident(a)}
    t_fwd = join.host_join(graph, join.extract_pattern(graph, fwd))
    t_rev = join.host_join(graph, join.extract_pattern(graph, rev))
    assert t_fwd and {(y, z) for z, y in t_fwd} == set(t_rev)
    _check(graph, rev)  # device agrees on the awkward declaration too


def test_typed_variant_matches(graph):
    nodes, _ = _build(graph, seed=4)
    a = nodes[2]
    th = int(graph.get_type_handle_of(
        graph.add_link([a, nodes[9]], value="typed-probe")
    ))
    _check(graph, {"y": c.And(c.CoIncident(a), c.AtomType(th))})
    # typed on the non-anchor variable of a 2-path
    _check(graph, {
        "y": c.CoIncident(a),
        "z": c.And(c.CoIncident(var("y")), c.AtomType(th)),
    })


def test_empty_result_and_out_of_pattern_anchor(graph):
    _build(graph, seed=5)
    lone = int(graph.add_node("lonely"))
    truth = _check(graph, {"y": c.CoIncident(lone)})
    assert truth == []
    truth = _check(graph, {
        "y": c.CoIncident(lone), "z": c.CoIncident(var("y"))
    })
    assert truth == []


def test_duplicate_targets_dedupe(graph):
    """A link whose target tuple repeats an atom must not mint duplicate
    binding rows through the tgt-expansion path."""
    a, b = int(graph.add_node("a")), int(graph.add_node("b"))
    dup = int(graph.add_link([a, b, a], value="dup"))
    _check(graph, {"y": c.Target(dup)})                      # tgt const
    _check(graph, {"l": c.Incident(a), "y": c.Target(var("l"))})


def test_distinctness_is_enforced(graph):
    """distinct=True: no variable repeats another variable's binding or
    a pattern constant anywhere in a result tuple."""
    nodes, _ = _build(graph, seed=6)
    a = nodes[4]
    truth = _check(graph, SHAPES["star3"](a))
    for t in truth:
        assert len(set(t)) == len(t)
        assert a not in t


def test_pad_lane_garbage_is_inert(graph):
    """Bucket-padded lanes (n_real < K) must contribute nothing: zero
    counts, no truncation, and real lanes unchanged."""
    nodes, _ = _build(graph, seed=7)
    p = join.extract_pattern(graph, SHAPES["triangle"](nodes[5]))
    sig, consts = join.split_constants(p)
    snap = graph.snapshot()
    plan = join.plan_join(snap, p, sig, consts)
    K = 8
    cv = np.zeros((K, sig.n_consts), dtype=np.int32)
    cv[0] = consts
    # pad lanes deliberately carry garbage constants (stale anchors)
    cv[1:] = snap.num_atoms - 1
    out = execute_join(snap, plan, cv, top_r=16, n_real=1)
    counts = np.asarray(out.counts)
    trunc = np.asarray(out.trunc)
    truth = join.host_join(graph, p)
    assert int(counts[0]) == len(truth)
    assert (counts[1:] == 0).all()
    assert not trunc.any()


def test_truncation_honest_prefix(graph):
    """Caps small enough to overflow flag ``trunc`` and leave counts a
    LOWER bound whose downloaded rows are a subset of the truth — never
    fabricated rows, never a silent drop."""
    nodes, _ = _build(graph, seed=8)
    p = join.extract_pattern(graph, SHAPES["star3"](nodes[2]))
    truth = set(join.host_join(graph, p))
    assert truth  # the shape must actually overflow to test anything
    sig, consts = join.split_constants(p)
    snap = graph.snapshot()
    plan = join.plan_join(snap, p, sig, consts)
    out = execute_join(snap, plan, np.asarray([consts], dtype=np.int32),
                       top_r=0, full=True, row_cap=16, pad_cap=8)
    assert bool(np.asarray(out.trunc)[0])
    count = int(np.asarray(out.counts)[0])
    assert count <= len(truth)
    perm = [plan.order.index(v) for v in p.vars]
    rows = {tuple(int(x) for x in r[perm]) for r in out.full_bindings(0)}
    assert rows <= truth


def test_seeds_mode_global_count(graph):
    """Unanchored (whole-graph) triangle counting via seeds mode equals
    the numpy enumeration over the co-incidence CSR."""
    _build(graph, seed=9, n_nodes=50, n_links=110)
    p = join.extract_pattern(graph, {
        "x": c.CoIncident(var("y")),
        "y": c.And(c.CoIncident(var("x")), c.CoIncident(var("z"))),
        "z": c.CoIncident(var("x")),
    })
    snap = graph.snapshot()
    plan = join.plan_join(snap, p, seed_var="x")
    out = execute_join(
        snap, plan, np.zeros((1, 0), dtype=np.int32), top_r=0,
        count_only=True, seeds=np.arange(snap.num_atoms, dtype=np.int32),
        row_cap=1 << 18, var_pad_max=True,
    )
    assert not bool(np.asarray(out.trunc)[0])
    off, flat = neighbor_csr(snap)
    tri = sum(
        len(np.intersect1d(flat[off[int(y)]: off[int(y) + 1]],
                           flat[off[x]: off[x + 1]]))
        for x in range(snap.num_atoms)
        for y in flat[off[x]: off[x + 1]]
    )
    assert int(np.asarray(out.counts)[0]) == tri
    assert tri % 6 == 0  # every triangle appears once per ordering


def test_neighbor_csr_matches_satisfies(graph):
    """The materialized co-incidence CSR agrees with the CoIncident
    condition's own satisfies() on every pair of a small graph."""
    nodes, _ = _build(graph, seed=10, n_nodes=30, n_links=60)
    snap = graph.snapshot()
    off, flat = neighbor_csr(snap)
    for u in nodes[:12]:
        row = set(int(x) for x in flat[off[u]: off[u + 1]])
        assert u not in row  # irreflexive
        for v in nodes[:12]:
            expect = c.CoIncident(v).satisfies(graph, u)
            assert (v in row) == expect, (u, v)


# ---------------------------------------------------------------- planner


def test_planner_rejects_unanchored_and_disconnected(graph):
    _build(graph, seed=11)
    snap = graph.snapshot()
    floating = ConjunctivePattern(
        vars=("x", "y"), atoms=(JoinAtom("co", "x", "y"),)
    )
    with pytest.raises(JoinUnsupported):
        join.plan_join(snap, floating)  # no constant anchor
    disconnected = ConjunctivePattern(
        vars=("x", "y"), atoms=(JoinAtom("co", "x", 3),)
    )
    with pytest.raises(JoinUnsupported):
        join.plan_join(snap, disconnected)  # y unreachable


def test_extraction_rejects_out_of_vocabulary(graph):
    _build(graph, seed=12)
    with pytest.raises(JoinUnsupported):
        join.extract_pattern(graph, {"x": c.Or(c.CoIncident(3),
                                               c.CoIncident(4))})
    with pytest.raises(JoinUnsupported):
        join.extract_pattern(graph, {"x": c.BFS(3, max_distance=2)})


def test_extraction_dedupes_mirrored_atoms(graph):
    _build(graph, seed=13)
    p = join.extract_pattern(graph, {
        "x": c.CoIncident(var("y")),
        "y": c.And(c.CoIncident(var("x")), c.CoIncident(7)),
    })
    # co(x,y) and co(y,x) are ONE constraint
    assert len([a for a in p.atoms if a.key_is_var]) == 1


# ---------------------------------------------------------------- compiler


def test_single_var_pushdown_equals_host(graph, monkeypatch):
    """find_all(And(CoIncident, CoIncident)) — common neighbours — must
    answer identically with the join pushdown forced onto the device arm
    (at toy scale the cost model rightly prefers host, so both gates are
    pinned open) and with it off."""
    from hypergraphdb_tpu.join import planner as jp

    nodes, _ = _build(graph, seed=14)
    a, b = nodes[3], nodes[8]
    cond = q.and_(q.co_incident(a), q.co_incident(b))
    host = sorted(int(h) for h in graph.find_all(cond))
    monkeypatch.setattr(graph.config.query, "device_min_batch", 0)
    monkeypatch.setattr(jp, "host_cost_bytes",
                        lambda *_: float("inf"))
    dev = sorted(int(h) for h in graph.find_all(cond))
    assert dev == host
    assert graph.metrics.counters.get("query.join.device", 0) >= 1


def test_pushdown_with_memtable_falls_back_exact(graph):
    nodes, _ = _build(graph, seed=15)
    a, b = nodes[2], nodes[6]
    graph.snapshot()  # pin a base, then mutate past it
    fresh = int(graph.add_link([a, b], value="fresh"))
    cond = q.and_(q.co_incident(a), q.co_incident(b))
    old = graph.config.query.device_min_batch
    try:
        graph.config.query.device_min_batch = 0
        got = sorted(int(h) for h in graph.find_all(cond))
    finally:
        graph.config.query.device_min_batch = old
    # ground truth by direct satisfies() over every atom — the device
    # base predates the fresh link, so agreement here proves the
    # memtable correction (or exact fallback) engaged
    expect = sorted(
        int(h) for h in graph.atoms()
        if c.CoIncident(a).satisfies(graph, h)
        and c.CoIncident(b).satisfies(graph, h)
    )
    assert got == expect
    assert fresh not in got  # the link shares no LINK with a (it IS one)


# ---------------------------------------------------------------- serving


def _serve(g, **kw):
    from hypergraphdb_tpu.serve import ServeConfig, ServeRuntime

    kw.setdefault("buckets", (4, 16))
    kw.setdefault("max_linger_s", 0.001)
    kw.setdefault("top_r", 128)
    return ServeRuntime(g, ServeConfig(**kw))


def test_serve_join_batch_differential(graph):
    """A same-signature batch of anchored triangles through the serving
    lane: every lane equals its host truth, device-served."""
    nodes, _ = _build(graph, seed=16)
    rt = _serve(graph)
    try:
        futs = [(x, rt.submit_join(SHAPES["triangle"](x)))
                for x in nodes[:8]]
        saw_device = False
        for x, f in futs:
            res = f.result(timeout=60)
            truth = join.host_join(
                graph, join.extract_pattern(graph, SHAPES["triangle"](x))
            )
            assert res.count == len(truth)
            got = sorted(tuple(int(v) for v in row) for row in res.tuples)
            assert got == (truth[:128] if res.truncated else truth)
            saw_device = saw_device or res.served_by == "device"
        assert saw_device
    finally:
        rt.close()


def test_serve_join_mid_ingest_partial_correction(graph):
    """A link added after the base pack must be visible. Join engine v2
    (ROADMAP 2d): a SMALL pure-add dirty set no longer re-routes the
    batch to host — the lane stays device-served and collect merges the
    host-enumerated tuples touching the dirty atoms, counted in
    ``serve.join.partial_corrections``."""
    nodes, _ = _build(graph, seed=17)
    a = nodes[5]
    rt = _serve(graph)
    try:
        rt.submit_join(SHAPES["path2"](a)).result(timeout=60)  # pin base
        far = int(graph.add_node("far"))
        graph.add_link([a, far], value="mid-ingest")
        res = rt.submit_join({"y": c.CoIncident(a)}).result(timeout=60)
        assert res.served_by == "device"
        got = {int(r[0]) for r in res.tuples}
        assert far in got
        truth = join.host_join(
            graph, join.extract_pattern(graph, {"y": c.CoIncident(a)})
        )
        assert res.count == len(truth)
        assert rt.stats.join_partial_corrections >= 1
    finally:
        rt.close()


def test_serve_join_mid_ingest_big_dirty_set_serves_host(graph):
    """Past ``join_dirty_max`` touched atoms (here: 0 — the partial
    path disabled) the lane keeps PR 10's exact-at-collect rule: the
    whole batch re-routes to host while the memtable is dirty."""
    nodes, _ = _build(graph, seed=17)
    a = nodes[5]
    rt = _serve(graph, join_dirty_max=0)
    try:
        rt.submit_join(SHAPES["path2"](a)).result(timeout=60)  # pin base
        far = int(graph.add_node("far"))
        graph.add_link([a, far], value="mid-ingest")
        res = rt.submit_join({"y": c.CoIncident(a)}).result(timeout=60)
        assert res.served_by == "host"
        assert far in {int(r[0]) for r in res.tuples}
        assert rt.stats.join_partial_corrections == 0
    finally:
        rt.close()


def test_serve_join_mid_ingest_tombstone_serves_host(graph):
    """Tombstones are never partially correctable (a vanished link may
    have been a result's only witness): the batch takes the exact host
    path even under a tiny dirty set."""
    nodes, links = _build(graph, seed=22)
    a = nodes[4]
    rt = _serve(graph)
    try:
        rt.submit_join(SHAPES["path2"](a)).result(timeout=60)  # pin base
        graph.remove(links[0])
        res = rt.submit_join({"y": c.CoIncident(a)}).result(timeout=60)
        assert res.served_by == "host"
        truth = join.host_join(
            graph, join.extract_pattern(graph, {"y": c.CoIncident(a)})
        )
        assert res.count == len(truth)
    finally:
        rt.close()


def test_serve_join_result_window_truncation(graph):
    """count exact + ascending prefix when the binding set outgrows
    top_r — the compact-window contract, join edition."""
    nodes, _ = _build(graph, seed=18)
    a = nodes[1]
    truth = join.host_join(
        graph, join.extract_pattern(graph, SHAPES["star3"](a))
    )
    assert len(truth) > 4
    rt = _serve(graph, top_r=4)
    try:
        res = rt.submit_join(SHAPES["star3"](a)).result(timeout=60)
        assert res.truncated and res.count == len(truth)
        got = [tuple(int(v) for v in row) for row in res.tuples]
        assert got == truth[:4]
    finally:
        rt.close()


def test_serve_join_stale_anchor_exact(graph):
    """An anchor newer than the pinned base must still answer exactly.
    v2: within the base's padded id space the anchor's BASE rows are
    empty and the per-lane correction supplies every memtable tuple —
    device-served, exact; with the partial path disabled it keeps PR
    10's exact host route."""
    nodes, _ = _build(graph, seed=19)
    for dirty_max, path in ((16, "device"), (0, "host")):
        rt = _serve(graph, join_dirty_max=dirty_max)
        try:
            rt.submit_join(SHAPES["path2"](nodes[0])).result(timeout=60)
            fresh_n = int(graph.add_node(f"fresh-anchor-{dirty_max}"))
            graph.add_link([fresh_n, nodes[2]], value="fresh-link")
            res = rt.submit_join({"y": c.CoIncident(fresh_n)}).result(
                timeout=60
            )
            truth = join.host_join(
                graph,
                join.extract_pattern(graph, {"y": c.CoIncident(fresh_n)}),
            )
            assert res.count == len(truth) > 0
            got = sorted(int(r[0]) for r in res.tuples)
            assert got == [t[0] for t in truth]
            if rt.executor.mgr.compactions == 1:
                # no compaction raced the submit: the routing verdict is
                # deterministic and pinned per config
                assert res.served_by == path
        finally:
            rt.close()


def test_factorize_failure_never_poisons_plan_cache(graph, monkeypatch):
    """An over-budget co relation makes the factorized build raise —
    that must NOT demote a co-FREE signature (which the pair-budget
    guard rightly let through) to the host path: the plan survives and
    the lane serves device over the flat CSRs (review regression)."""
    from hypergraphdb_tpu.ops import join as oj

    nodes, _ = _build(graph, seed=40)
    a = nodes[2]
    monkeypatch.setattr(oj, "NBR_MAX_PAIRS", 1)
    spec = {"l": c.Incident(a), "y": c.Target(var("l"))}  # no co atoms
    truth = join.host_join(graph, join.extract_pattern(graph, spec))
    assert truth
    rt = _serve(graph)   # join_factorized defaults on
    try:
        res = rt.submit_join(spec).result(timeout=60)
        assert res.served_by == "device"
        assert res.count == len(truth)
    finally:
        rt.close()


def test_nbr_pair_budget_declines_to_host(graph, monkeypatch):
    """A snapshot whose co-incidence relation would blow the pair
    budget never builds it: the serve lane declines BEFORE launch and
    the one-shot pushdown falls back — both still exact via host."""
    from hypergraphdb_tpu.join import planner as jp
    from hypergraphdb_tpu.ops import join as oj

    nodes, _ = _build(graph, seed=21)
    monkeypatch.setattr(oj, "NBR_MAX_PAIRS", 1)
    a = nodes[3]
    spec = {"y": c.CoIncident(a)}
    truth = join.host_join(graph, join.extract_pattern(graph, spec))
    rt = _serve(graph)
    try:
        res = rt.submit_join(spec).result(timeout=60)
        assert res.served_by == "host"
        assert res.count == len(truth)
    finally:
        rt.close()
    # one-shot: the executor raises JoinUnsupported inside run(), the
    # classic host plan answers (And pushdown — a bare CoIncident is a
    # NeighborsPlan leaf and never reaches the device arm)
    monkeypatch.setattr(graph.config.query, "device_min_batch", 0)
    monkeypatch.setattr(jp, "host_cost_bytes", lambda *_: float("inf"))
    b = nodes[8]
    cond = q.and_(q.co_incident(a), q.co_incident(b))
    got = sorted(int(h) for h in graph.find_all(cond))
    expect = sorted(
        int(h) for h in graph.atoms()
        if c.CoIncident(a).satisfies(graph, h)
        and c.CoIncident(b).satisfies(graph, h)
    )
    assert got == expect
    assert graph.metrics.counters.get("query.join.host", 0) >= 1


# ------------------------------------------------- join engine v2 suites


def _build_hub(g, seed=0, hub_links=70):
    """A random graph plus one deliberate HUB: a node sharing a link
    with most of the population, so its co row (~70 distinct
    neighbours) dwarfs every tail row (base-graph co rows stay ≤ ~30)."""
    nodes, links = _build(g, seed=seed)
    hub = nodes[0]
    for i in range(hub_links):
        g.add_link([hub, nodes[1 + i % (len(nodes) - 1)]],
                   value=f"hub-{i}")
    return hub, nodes


@pytest.mark.parametrize("shape", ["path2", "triangle"])
def test_degree_split_hub_anchor_matches_host(graph, shape):
    """Hub-anchored patterns through the degree-split executor: the
    dense-frontier chain serves the hub exactly (no width truncation)
    where the PR-10 padded path would truncate under the same pad cap."""
    hub, _ = _build_hub(graph, seed=30)
    p = join.extract_pattern(graph, SHAPES[shape](hub))
    truth = join.host_join(graph, p)
    assert truth
    snap = graph.snapshot()
    sig, consts = join.split_constants(p)
    plan = join.plan_join(snap, p, sig, consts)
    # pad_cap sits BETWEEN the tail row widths (base-graph co rows stay
    # under it) and the hub row width (well over it): the flat executor
    # must truncate the hub expansion, the split must not
    kw = dict(top_r=0, full=True, pad_cap=40, row_cap=1 << 16)
    out = execute_join(snap, plan, np.asarray([consts], dtype=np.int32),
                       hub_threshold=40, **kw)
    assert out.hub_lanes == 1
    assert not bool(np.asarray(out.trunc)[0])
    perm = [plan.order.index(v) for v in p.vars]
    dev = sorted(tuple(int(x) for x in r[perm])
                 for r in out.full_bindings(0))
    assert dev == truth
    assert int(np.asarray(out.counts)[0]) == len(truth)
    # the PR-10 executor under the same caps: the hub row overflows the
    # pad and the lane truncates (host re-route in production)
    old = execute_join(snap, plan, np.asarray([consts], dtype=np.int32),
                       hub_split=False, **kw)
    assert old.hub_lanes == 0
    assert bool(np.asarray(old.trunc)[0])


def test_degree_split_mixed_batch(graph):
    """One batch mixing hub and tail anchors: tail lanes keep the
    padded fast path (pads priced from tail widths only), the hub lane
    rides the dense-frontier chain, and every lane equals host truth."""
    from hypergraphdb_tpu.ops.join import neighbor_csr

    hub, nodes = _build_hub(graph, seed=31)
    snap = graph.snapshot()
    off, _ = neighbor_csr(snap)
    w = np.diff(off.astype(np.int64))[: snap.num_atoms]
    tails = [n for n in nodes[1:] if 2 <= w[n] <= 8][:7]
    assert tails
    anchors = [hub] + tails
    p0 = join.extract_pattern(graph, SHAPES["path2"](anchors[0]))
    sig, _ = join.split_constants(p0)
    plan = join.plan_join(snap, p0, sig,
                          join.split_constants(p0)[1])
    consts = np.asarray([[a] for a in anchors], dtype=np.int32)
    mask = join.hub_lane_mask(snap, plan.steps, consts, threshold=8)
    assert mask[0] and not mask[1:].any()
    out = execute_join(snap, plan, consts, top_r=0, count_only=True,
                       hub_threshold=8, var_pad_max=True,
                       row_cap=1 << 16)
    assert out.hub_lanes == 1
    counts = np.asarray(out.counts)
    assert not np.asarray(out.trunc).any()
    for i, a in enumerate(anchors):
        truth = join.host_join(
            graph, join.extract_pattern(graph, SHAPES["path2"](a))
        )
        assert int(counts[i]) == len(truth), (i, a)


def test_bushy_star_of_stars_matches_host(graph):
    """Star-of-stars (two independently-anchored 2-var components):
    auto planning goes bushy, and bushy == forced-left-deep == host
    truth, including cross-component distinctness."""
    from hypergraphdb_tpu.join.planner import BushyJoinPlan

    nodes, _ = _build(graph, seed=32)
    a, b = nodes[3], nodes[8]
    spec = {
        "y": c.CoIncident(a), "z": c.CoIncident(var("y")),
        "u": c.CoIncident(b), "w": c.CoIncident(var("u")),
    }
    p = join.extract_pattern(graph, spec)
    truth = join.host_join(graph, p)
    snap = graph.snapshot()
    sig, consts = join.split_constants(p)
    plan = join.plan_join(snap, p, sig, consts)        # auto
    assert isinstance(plan, BushyJoinPlan)
    assert "bushy[" in plan.describe()
    cv = np.asarray([consts], dtype=np.int32)
    out = execute_join(snap, plan, cv, top_r=0, full=True,
                       var_pad_max=True, row_cap=1 << 18)
    assert not bool(np.asarray(out.trunc)[0])
    perm = [plan.order.index(v) for v in p.vars]
    dev = sorted(tuple(int(x) for x in r[perm])
                 for r in out.full_bindings(0))
    assert dev == truth
    assert int(np.asarray(out.counts)[0]) == len(truth)
    # forced left-deep agrees
    flat = join.plan_join(snap, p, sig, consts, bushy=False)
    assert not isinstance(flat, BushyJoinPlan)
    out2 = execute_join(snap, flat, cv, top_r=0, count_only=True,
                        var_pad_max=True, row_cap=1 << 18)
    assert not bool(np.asarray(out2.trunc)[0])
    assert int(np.asarray(out2.counts)[0]) == len(truth)
    for t in truth:
        assert len(set(t)) == len(t)  # cross-bag distinctness held


def test_bushy_auto_policy(graph):
    """Auto stays left-deep when every component is a singleton (plain
    star3 — a bag would buy nothing) and for single-component shapes;
    ``bushy=True`` forces the split."""
    from hypergraphdb_tpu.join.planner import BushyJoinPlan

    nodes, _ = _build(graph, seed=33)
    a = nodes[2]
    snap = graph.snapshot()
    star = join.extract_pattern(graph, SHAPES["star3"](a))
    assert not isinstance(join.plan_join(snap, star), BushyJoinPlan)
    assert isinstance(join.plan_join(snap, star, bushy=True),
                      BushyJoinPlan)
    tri = join.extract_pattern(graph, SHAPES["triangle"](a))
    assert not isinstance(join.plan_join(snap, tri, bushy=True),
                          BushyJoinPlan)  # one component: nothing to bag


def test_bushy_forced_star3_matches_host(graph):
    """Bushy with singleton bags (forced on star3) still answers
    exactly — the fold enforces the pairwise distinctness the left-deep
    chain got from its step masks."""
    nodes, _ = _build(graph, seed=34)
    a = nodes[5]
    p = join.extract_pattern(graph, SHAPES["star3"](a))
    truth = join.host_join(graph, p)
    snap = graph.snapshot()
    sig, consts = join.split_constants(p)
    plan = join.plan_join(snap, p, sig, consts, bushy=True)
    out = execute_join(snap, plan, np.asarray([consts], dtype=np.int32),
                       top_r=0, full=True, var_pad_max=True,
                       row_cap=1 << 18)
    assert not bool(np.asarray(out.trunc)[0])
    perm = [plan.order.index(v) for v in p.vars]
    dev = sorted(tuple(int(x) for x in r[perm])
                 for r in out.full_bindings(0))
    assert dev == truth


def test_bushy_truncation_honest(graph):
    """Bushy chains and folds under tiny caps flag ``trunc`` with a
    count that stays a lower bound and rows a subset of truth — the
    PR-10 honesty contract, bag edition."""
    nodes, _ = _build(graph, seed=35)
    a, b = nodes[1], nodes[6]
    spec = {
        "y": c.CoIncident(a), "z": c.CoIncident(var("y")),
        "u": c.CoIncident(b), "w": c.CoIncident(var("u")),
    }
    p = join.extract_pattern(graph, spec)
    truth = set(join.host_join(graph, p))
    assert truth
    snap = graph.snapshot()
    sig, consts = join.split_constants(p)
    plan = join.plan_join(snap, p, sig, consts, bushy=True)
    out = execute_join(snap, plan, np.asarray([consts], dtype=np.int32),
                       top_r=0, full=True, row_cap=32, pad_cap=8)
    assert bool(np.asarray(out.trunc)[0])
    assert int(np.asarray(out.counts)[0]) <= len(truth)
    perm = [plan.order.index(v) for v in p.vars]
    rows = {tuple(int(x) for x in r[perm])
            for r in out.full_bindings(0)}
    assert rows <= truth


@pytest.mark.parametrize("shape", sorted(SHAPES))
def test_factorized_matches_flat(graph, shape):
    """The prefix-grouped (trie) relation encoding answers every shape
    identically to the flat CSRs — closed co rows re-irreflexed, tgt
    tuples grouped exactly."""
    nodes, _ = _build(graph, seed=36)
    a = nodes[4]
    p = join.extract_pattern(graph, SHAPES[shape](a))
    truth = join.host_join(graph, p)
    dev, count, trunc = _device_rows(graph, p, factorized=True)
    assert not trunc
    assert dev == truth
    assert count == len(truth)


def test_factorized_grouping_shares_link_rows(graph):
    """Members of a single shared link carry IDENTICAL closed co rows —
    one stored group; the encoding's saving is measurable and the
    grouped payload is never larger than the flat one."""
    from hypergraphdb_tpu.ops.join import factorized_relations

    a = int(graph.add_node("a"))
    b = int(graph.add_node("b"))
    d = int(graph.add_node("d"))
    graph.add_link([a, b, d], value="triple")
    fr = factorized_relations(graph.snapshot())["co"]
    ga, gb, gd = fr.group_of[a], fr.group_of[b], fr.group_of[d]
    assert ga == gb == gd != 0
    row = fr.flat[fr.offsets[ga]: fr.offsets[ga + 1]]
    assert sorted(int(x) for x in row) == sorted([a, b, d])
    assert fr.entries <= fr.entries_flat
    assert fr.closed


def test_host_join_touching_equivalence(graph):
    """``host_join_touching`` with the full atom set reproduces
    ``host_join`` exactly, and with a restricted set returns precisely
    the truth tuples intersecting it — the per-lane correction's
    soundness contract."""
    nodes, _ = _build(graph, seed=37)
    a, b = nodes[2], nodes[9]
    spec = {
        "y": c.CoIncident(a), "z": c.CoIncident(var("y")),
        "u": c.CoIncident(b), "w": c.CoIncident(var("u")),
    }
    p = join.extract_pattern(graph, spec)
    truth = join.host_join(graph, p)
    everything = [int(h) for h in graph.atoms()]
    assert join.host_join_touching(graph, p, everything) == truth
    if truth:
        probe = set(truth[0][:1])
        got = join.host_join_touching(graph, p, probe)
        expect = sorted(t for t in truth if probe & set(t))
        assert got == expect


def test_serve_join_hub_dispatch_counter(graph):
    """A hub-anchored join through the serving lane dispatches the hub
    lane on DEVICE (``serve.join.hub_dispatches`` moves) and equals the
    host truth — the lane PR 10 re-routed to host."""
    hub, _ = _build_hub(graph, seed=38)
    rt = _serve(graph, join_hub_threshold=8)
    try:
        res = rt.submit_join(SHAPES["path2"](hub)).result(timeout=60)
        truth = join.host_join(
            graph, join.extract_pattern(graph, SHAPES["path2"](hub))
        )
        assert res.served_by == "device"
        assert res.count == len(truth)
        got = sorted(tuple(int(v) for v in row) for row in res.tuples)
        assert got == (truth[:128] if res.truncated else truth)
        assert rt.stats.join_hub_dispatches > 0
    finally:
        rt.close()


def test_serve_join_bushy_signature_batch(graph):
    """A same-signature batch of star-of-stars requests through the
    serving lane (bushy plans under the hood): every lane equals its
    host truth."""
    nodes, _ = _build(graph, seed=39)
    rt = _serve(graph)
    try:
        spec_of = lambda x, y: {             # noqa: E731 - test-local
            "p": c.CoIncident(x), "q": c.CoIncident(var("p")),
            "r": c.CoIncident(y), "s": c.CoIncident(var("r")),
        }
        pairs = [(nodes[i], nodes[i + 4]) for i in range(4)]
        futs = [(x, y, rt.submit_join(spec_of(x, y)))
                for x, y in pairs]
        for x, y, f in futs:
            res = f.result(timeout=60)
            truth = join.host_join(
                graph, join.extract_pattern(graph, spec_of(x, y))
            )
            assert res.count == len(truth), (x, y)
            got = sorted(tuple(int(v) for v in row)
                         for row in res.tuples)
            assert got == (truth[:128] if res.truncated else truth)
    finally:
        rt.close()


def test_bridge_routes_coincident_conditions_to_join(graph):
    from hypergraphdb_tpu.query.bridge import to_join_request, to_request
    from hypergraphdb_tpu.serve.types import JoinRequest, Unservable

    nodes, _ = _build(graph, seed=20)
    a, b = nodes[0], nodes[1]
    req = to_request(graph, q.and_(q.co_incident(a), q.co_incident(b)))
    assert isinstance(req, JoinRequest)
    assert req.consts == (a, b)
    # single-variable CONDITIONS carry find_all semantics: no implicit
    # distinct-from-anchors (Incident(a) admits a self-targeting a)
    assert req.sig.distinct is False
    req2 = to_request(graph, q.co_incident(a))
    assert isinstance(req2, JoinRequest)
    # same shape, different anchors → same signature (one batch key)
    assert to_request(graph, q.co_incident(b)).batch_key == req2.batch_key
    with pytest.raises(Unservable):
        to_join_request(graph, {
            "x": c.CoIncident(var("y")), "y": c.CoIncident(var("x")),
        })  # no constant anchor
