"""hgjoin differential suite: device joins == host ``find_all`` truth.

The worst-case-optimal executor (``ops/join``) and the GHD-lite planner
(``join/planner``) are held to the exact host enumerator
(``join/host.host_join`` — find_all + satisfies, a deliberately separate
implementation path) on seeded random graphs across every supported
shape: triangles, paths, stars, typed variants, link-variable patterns,
empty results, duplicate-target links, pad-lane garbage, truncation
prefixes, and mid-ingest memtable visibility through the serving lane.
"""

from __future__ import annotations

import numpy as np
import pytest

from hypergraphdb_tpu import join
from hypergraphdb_tpu.join.ir import (
    ConjunctivePattern,
    JoinAtom,
    JoinUnsupported,
)
from hypergraphdb_tpu.ops.join import execute_join, neighbor_csr
from hypergraphdb_tpu.query import conditions as c
from hypergraphdb_tpu.query import dsl as q
from hypergraphdb_tpu.query.variables import var
from tests.conftest import make_random_hypergraph


def _build(g, seed=0, n_nodes=80, n_links=160):
    nodes, links = make_random_hypergraph(
        g, n_nodes=n_nodes, n_links=n_links, max_arity=4, seed=seed
    )
    return [int(n) for n in nodes], [int(x) for x in links]


def _device_rows(g, pattern, **kw):
    """Full device binding rows in the REQUEST's variable order.
    Exact-count shape policy by default — the truncation contract has
    its own test (:func:`test_truncation_honest_prefix`)."""
    kw.setdefault("var_pad_max", True)
    snap = g.snapshot()
    sig, consts = join.split_constants(pattern)
    plan = join.plan_join(snap, pattern, sig, consts)
    out = execute_join(snap, plan, np.asarray([consts], dtype=np.int32),
                       top_r=0, full=True, **kw)
    rows = out.full_bindings(0)
    perm = [plan.order.index(v) for v in pattern.vars]
    dev = sorted(tuple(int(x) for x in row[perm]) for row in rows)
    trunc = bool(np.asarray(out.trunc)[0])
    count = int(np.asarray(out.counts)[0])
    return dev, count, trunc


def _check(g, spec, distinct=True, **kw):
    p = join.extract_pattern(g, spec, distinct=distinct)
    truth = join.host_join(g, p)
    dev, count, trunc = _device_rows(g, p, **kw)
    assert not trunc
    assert dev == truth
    assert count == len(truth)
    return truth


# ---------------------------------------------------------------- shapes


SHAPES = {
    "triangle": lambda a: {
        "y": c.And(c.CoIncident(a), c.CoIncident(var("z"))),
        "z": c.CoIncident(a),
    },
    "path2": lambda a: {
        "y": c.CoIncident(a),
        "z": c.CoIncident(var("y")),
    },
    "star3": lambda a: {
        "y": c.CoIncident(a),
        "z": c.CoIncident(a),
        "w": c.CoIncident(a),
    },
    "link_var": lambda a: {
        "l": c.Incident(a),
        "y": c.Target(var("l")),
    },
}


@pytest.mark.parametrize("shape", sorted(SHAPES))
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_device_join_matches_host_truth(graph, shape, seed):
    nodes, _ = _build(graph, seed=seed)
    _check(graph, SHAPES[shape](nodes[3 + seed]))


def test_host_join_reorders_spec_declaration_order(graph):
    """The spec declares y BEFORE its generator z is bound — the host
    enumerator must find a feasible binding order (the device planner
    reorders freely; the exact fallback has to keep up), and tuples
    still read in spec-declared variable order."""
    nodes, _ = _build(graph, seed=3)
    a = nodes[6]
    fwd = {"z": c.CoIncident(a), "y": c.CoIncident(var("z"))}
    rev = {"y": c.CoIncident(var("z")), "z": c.CoIncident(a)}
    t_fwd = join.host_join(graph, join.extract_pattern(graph, fwd))
    t_rev = join.host_join(graph, join.extract_pattern(graph, rev))
    assert t_fwd and {(y, z) for z, y in t_fwd} == set(t_rev)
    _check(graph, rev)  # device agrees on the awkward declaration too


def test_typed_variant_matches(graph):
    nodes, _ = _build(graph, seed=4)
    a = nodes[2]
    th = int(graph.get_type_handle_of(
        graph.add_link([a, nodes[9]], value="typed-probe")
    ))
    _check(graph, {"y": c.And(c.CoIncident(a), c.AtomType(th))})
    # typed on the non-anchor variable of a 2-path
    _check(graph, {
        "y": c.CoIncident(a),
        "z": c.And(c.CoIncident(var("y")), c.AtomType(th)),
    })


def test_empty_result_and_out_of_pattern_anchor(graph):
    _build(graph, seed=5)
    lone = int(graph.add_node("lonely"))
    truth = _check(graph, {"y": c.CoIncident(lone)})
    assert truth == []
    truth = _check(graph, {
        "y": c.CoIncident(lone), "z": c.CoIncident(var("y"))
    })
    assert truth == []


def test_duplicate_targets_dedupe(graph):
    """A link whose target tuple repeats an atom must not mint duplicate
    binding rows through the tgt-expansion path."""
    a, b = int(graph.add_node("a")), int(graph.add_node("b"))
    dup = int(graph.add_link([a, b, a], value="dup"))
    _check(graph, {"y": c.Target(dup)})                      # tgt const
    _check(graph, {"l": c.Incident(a), "y": c.Target(var("l"))})


def test_distinctness_is_enforced(graph):
    """distinct=True: no variable repeats another variable's binding or
    a pattern constant anywhere in a result tuple."""
    nodes, _ = _build(graph, seed=6)
    a = nodes[4]
    truth = _check(graph, SHAPES["star3"](a))
    for t in truth:
        assert len(set(t)) == len(t)
        assert a not in t


def test_pad_lane_garbage_is_inert(graph):
    """Bucket-padded lanes (n_real < K) must contribute nothing: zero
    counts, no truncation, and real lanes unchanged."""
    nodes, _ = _build(graph, seed=7)
    p = join.extract_pattern(graph, SHAPES["triangle"](nodes[5]))
    sig, consts = join.split_constants(p)
    snap = graph.snapshot()
    plan = join.plan_join(snap, p, sig, consts)
    K = 8
    cv = np.zeros((K, sig.n_consts), dtype=np.int32)
    cv[0] = consts
    # pad lanes deliberately carry garbage constants (stale anchors)
    cv[1:] = snap.num_atoms - 1
    out = execute_join(snap, plan, cv, top_r=16, n_real=1)
    counts = np.asarray(out.counts)
    trunc = np.asarray(out.trunc)
    truth = join.host_join(graph, p)
    assert int(counts[0]) == len(truth)
    assert (counts[1:] == 0).all()
    assert not trunc.any()


def test_truncation_honest_prefix(graph):
    """Caps small enough to overflow flag ``trunc`` and leave counts a
    LOWER bound whose downloaded rows are a subset of the truth — never
    fabricated rows, never a silent drop."""
    nodes, _ = _build(graph, seed=8)
    p = join.extract_pattern(graph, SHAPES["star3"](nodes[2]))
    truth = set(join.host_join(graph, p))
    assert truth  # the shape must actually overflow to test anything
    sig, consts = join.split_constants(p)
    snap = graph.snapshot()
    plan = join.plan_join(snap, p, sig, consts)
    out = execute_join(snap, plan, np.asarray([consts], dtype=np.int32),
                       top_r=0, full=True, row_cap=16, pad_cap=8)
    assert bool(np.asarray(out.trunc)[0])
    count = int(np.asarray(out.counts)[0])
    assert count <= len(truth)
    perm = [plan.order.index(v) for v in p.vars]
    rows = {tuple(int(x) for x in r[perm]) for r in out.full_bindings(0)}
    assert rows <= truth


def test_seeds_mode_global_count(graph):
    """Unanchored (whole-graph) triangle counting via seeds mode equals
    the numpy enumeration over the co-incidence CSR."""
    _build(graph, seed=9, n_nodes=50, n_links=110)
    p = join.extract_pattern(graph, {
        "x": c.CoIncident(var("y")),
        "y": c.And(c.CoIncident(var("x")), c.CoIncident(var("z"))),
        "z": c.CoIncident(var("x")),
    })
    snap = graph.snapshot()
    plan = join.plan_join(snap, p, seed_var="x")
    out = execute_join(
        snap, plan, np.zeros((1, 0), dtype=np.int32), top_r=0,
        count_only=True, seeds=np.arange(snap.num_atoms, dtype=np.int32),
        row_cap=1 << 18, var_pad_max=True,
    )
    assert not bool(np.asarray(out.trunc)[0])
    off, flat = neighbor_csr(snap)
    tri = sum(
        len(np.intersect1d(flat[off[int(y)]: off[int(y) + 1]],
                           flat[off[x]: off[x + 1]]))
        for x in range(snap.num_atoms)
        for y in flat[off[x]: off[x + 1]]
    )
    assert int(np.asarray(out.counts)[0]) == tri
    assert tri % 6 == 0  # every triangle appears once per ordering


def test_neighbor_csr_matches_satisfies(graph):
    """The materialized co-incidence CSR agrees with the CoIncident
    condition's own satisfies() on every pair of a small graph."""
    nodes, _ = _build(graph, seed=10, n_nodes=30, n_links=60)
    snap = graph.snapshot()
    off, flat = neighbor_csr(snap)
    for u in nodes[:12]:
        row = set(int(x) for x in flat[off[u]: off[u + 1]])
        assert u not in row  # irreflexive
        for v in nodes[:12]:
            expect = c.CoIncident(v).satisfies(graph, u)
            assert (v in row) == expect, (u, v)


# ---------------------------------------------------------------- planner


def test_planner_rejects_unanchored_and_disconnected(graph):
    _build(graph, seed=11)
    snap = graph.snapshot()
    floating = ConjunctivePattern(
        vars=("x", "y"), atoms=(JoinAtom("co", "x", "y"),)
    )
    with pytest.raises(JoinUnsupported):
        join.plan_join(snap, floating)  # no constant anchor
    disconnected = ConjunctivePattern(
        vars=("x", "y"), atoms=(JoinAtom("co", "x", 3),)
    )
    with pytest.raises(JoinUnsupported):
        join.plan_join(snap, disconnected)  # y unreachable


def test_extraction_rejects_out_of_vocabulary(graph):
    _build(graph, seed=12)
    with pytest.raises(JoinUnsupported):
        join.extract_pattern(graph, {"x": c.Or(c.CoIncident(3),
                                               c.CoIncident(4))})
    with pytest.raises(JoinUnsupported):
        join.extract_pattern(graph, {"x": c.BFS(3, max_distance=2)})


def test_extraction_dedupes_mirrored_atoms(graph):
    _build(graph, seed=13)
    p = join.extract_pattern(graph, {
        "x": c.CoIncident(var("y")),
        "y": c.And(c.CoIncident(var("x")), c.CoIncident(7)),
    })
    # co(x,y) and co(y,x) are ONE constraint
    assert len([a for a in p.atoms if a.key_is_var]) == 1


# ---------------------------------------------------------------- compiler


def test_single_var_pushdown_equals_host(graph, monkeypatch):
    """find_all(And(CoIncident, CoIncident)) — common neighbours — must
    answer identically with the join pushdown forced onto the device arm
    (at toy scale the cost model rightly prefers host, so both gates are
    pinned open) and with it off."""
    from hypergraphdb_tpu.join import planner as jp

    nodes, _ = _build(graph, seed=14)
    a, b = nodes[3], nodes[8]
    cond = q.and_(q.co_incident(a), q.co_incident(b))
    host = sorted(int(h) for h in graph.find_all(cond))
    monkeypatch.setattr(graph.config.query, "device_min_batch", 0)
    monkeypatch.setattr(jp, "host_cost_bytes",
                        lambda *_: float("inf"))
    dev = sorted(int(h) for h in graph.find_all(cond))
    assert dev == host
    assert graph.metrics.counters.get("query.join.device", 0) >= 1


def test_pushdown_with_memtable_falls_back_exact(graph):
    nodes, _ = _build(graph, seed=15)
    a, b = nodes[2], nodes[6]
    graph.snapshot()  # pin a base, then mutate past it
    fresh = int(graph.add_link([a, b], value="fresh"))
    cond = q.and_(q.co_incident(a), q.co_incident(b))
    old = graph.config.query.device_min_batch
    try:
        graph.config.query.device_min_batch = 0
        got = sorted(int(h) for h in graph.find_all(cond))
    finally:
        graph.config.query.device_min_batch = old
    # ground truth by direct satisfies() over every atom — the device
    # base predates the fresh link, so agreement here proves the
    # memtable correction (or exact fallback) engaged
    expect = sorted(
        int(h) for h in graph.atoms()
        if c.CoIncident(a).satisfies(graph, h)
        and c.CoIncident(b).satisfies(graph, h)
    )
    assert got == expect
    assert fresh not in got  # the link shares no LINK with a (it IS one)


# ---------------------------------------------------------------- serving


def _serve(g, **kw):
    from hypergraphdb_tpu.serve import ServeConfig, ServeRuntime

    kw.setdefault("buckets", (4, 16))
    kw.setdefault("max_linger_s", 0.001)
    kw.setdefault("top_r", 128)
    return ServeRuntime(g, ServeConfig(**kw))


def test_serve_join_batch_differential(graph):
    """A same-signature batch of anchored triangles through the serving
    lane: every lane equals its host truth, device-served."""
    nodes, _ = _build(graph, seed=16)
    rt = _serve(graph)
    try:
        futs = [(x, rt.submit_join(SHAPES["triangle"](x)))
                for x in nodes[:8]]
        saw_device = False
        for x, f in futs:
            res = f.result(timeout=60)
            truth = join.host_join(
                graph, join.extract_pattern(graph, SHAPES["triangle"](x))
            )
            assert res.count == len(truth)
            got = sorted(tuple(int(v) for v in row) for row in res.tuples)
            assert got == (truth[:128] if res.truncated else truth)
            saw_device = saw_device or res.served_by == "device"
        assert saw_device
    finally:
        rt.close()


def test_serve_join_mid_ingest_memtable_visible(graph):
    """A link added after the base pack must be visible: the lane goes
    exact-at-collect (host) while the memtable is dirty."""
    nodes, _ = _build(graph, seed=17)
    a = nodes[5]
    rt = _serve(graph)
    try:
        rt.submit_join(SHAPES["path2"](a)).result(timeout=60)  # pin base
        far = int(graph.add_node("far"))
        graph.add_link([a, far], value="mid-ingest")
        res = rt.submit_join({"y": c.CoIncident(a)}).result(timeout=60)
        assert res.served_by == "host"
        got = {int(r[0]) for r in res.tuples}
        assert far in got
        truth = join.host_join(
            graph, join.extract_pattern(graph, {"y": c.CoIncident(a)})
        )
        assert res.count == len(truth)
    finally:
        rt.close()


def test_serve_join_result_window_truncation(graph):
    """count exact + ascending prefix when the binding set outgrows
    top_r — the compact-window contract, join edition."""
    nodes, _ = _build(graph, seed=18)
    a = nodes[1]
    truth = join.host_join(
        graph, join.extract_pattern(graph, SHAPES["star3"](a))
    )
    assert len(truth) > 4
    rt = _serve(graph, top_r=4)
    try:
        res = rt.submit_join(SHAPES["star3"](a)).result(timeout=60)
        assert res.truncated and res.count == len(truth)
        got = [tuple(int(v) for v in row) for row in res.tuples]
        assert got == truth[:4]
    finally:
        rt.close()


def test_serve_join_stale_anchor_serves_host(graph):
    """An anchor newer than the pinned base routes to the exact host
    lane — never a device answer over ids the base cannot address."""
    nodes, _ = _build(graph, seed=19)
    rt = _serve(graph)
    try:
        rt.submit_join(SHAPES["path2"](nodes[0])).result(timeout=60)
        fresh_n = int(graph.add_node("fresh-anchor"))
        graph.add_link([fresh_n, nodes[2]], value="fresh-link")
        res = rt.submit_join({"y": c.CoIncident(fresh_n)}).result(
            timeout=60
        )
        assert res.served_by == "host"
        truth = join.host_join(
            graph,
            join.extract_pattern(graph, {"y": c.CoIncident(fresh_n)}),
        )
        assert res.count == len(truth)
    finally:
        rt.close()


def test_nbr_pair_budget_declines_to_host(graph, monkeypatch):
    """A snapshot whose co-incidence relation would blow the pair
    budget never builds it: the serve lane declines BEFORE launch and
    the one-shot pushdown falls back — both still exact via host."""
    from hypergraphdb_tpu.join import planner as jp
    from hypergraphdb_tpu.ops import join as oj

    nodes, _ = _build(graph, seed=21)
    monkeypatch.setattr(oj, "NBR_MAX_PAIRS", 1)
    a = nodes[3]
    spec = {"y": c.CoIncident(a)}
    truth = join.host_join(graph, join.extract_pattern(graph, spec))
    rt = _serve(graph)
    try:
        res = rt.submit_join(spec).result(timeout=60)
        assert res.served_by == "host"
        assert res.count == len(truth)
    finally:
        rt.close()
    # one-shot: the executor raises JoinUnsupported inside run(), the
    # classic host plan answers (And pushdown — a bare CoIncident is a
    # NeighborsPlan leaf and never reaches the device arm)
    monkeypatch.setattr(graph.config.query, "device_min_batch", 0)
    monkeypatch.setattr(jp, "host_cost_bytes", lambda *_: float("inf"))
    b = nodes[8]
    cond = q.and_(q.co_incident(a), q.co_incident(b))
    got = sorted(int(h) for h in graph.find_all(cond))
    expect = sorted(
        int(h) for h in graph.atoms()
        if c.CoIncident(a).satisfies(graph, h)
        and c.CoIncident(b).satisfies(graph, h)
    )
    assert got == expect
    assert graph.metrics.counters.get("query.join.host", 0) >= 1


def test_bridge_routes_coincident_conditions_to_join(graph):
    from hypergraphdb_tpu.query.bridge import to_join_request, to_request
    from hypergraphdb_tpu.serve.types import JoinRequest, Unservable

    nodes, _ = _build(graph, seed=20)
    a, b = nodes[0], nodes[1]
    req = to_request(graph, q.and_(q.co_incident(a), q.co_incident(b)))
    assert isinstance(req, JoinRequest)
    assert req.consts == (a, b)
    # single-variable CONDITIONS carry find_all semantics: no implicit
    # distinct-from-anchors (Incident(a) admits a self-targeting a)
    assert req.sig.distinct is False
    req2 = to_request(graph, q.co_incident(a))
    assert isinstance(req2, JoinRequest)
    # same shape, different anchors → same signature (one batch key)
    assert to_request(graph, q.co_incident(b)).batch_key == req2.batch_key
    with pytest.raises(Unservable):
        to_join_request(graph, {
            "x": c.CoIncident(var("y")), "y": c.CoIncident(var("x")),
        })  # no constant anchor
