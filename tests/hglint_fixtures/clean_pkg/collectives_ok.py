"""Consistent shard_map collectives — HG6xx must stay silent."""

import jax
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

AXIS = "rows"


def _sum_helper(x, axis):
    # axis constant-propagates to 'rows' from the single call site — in
    # the region's mesh, so no HG603
    return jax.lax.psum(x, axis)


def _body(x, flag):
    d = jax.lax.axis_index(AXIS)
    shifted = x + d
    total = _sum_helper(shifted, AXIS)
    if flag:
        # branch on a traced value is legal as long as NO collective is
        # issued inside it — every device still runs the same sequence;
        # axis_index is device-local (no communication), so divergent
        # execution of it cannot deadlock either
        shifted = shifted * jax.lax.axis_index(AXIS)
    return total + shifted


def run(x):
    mesh = Mesh(np.array(jax.devices()), (AXIS,))
    fn = shard_map(
        _body, mesh=mesh, in_specs=(P(AXIS), P()), out_specs=P(AXIS)
    )
    return fn(x, 2)
