"""Clean twin of wrapshape_bad — scan/vmap-folded shapes fit the budget.

The point: these operands fold ONLY through the scan-carry / vmap-result
propagation. If that propagation regressed, these sites would degrade to
HG502 (unresolvable) and fail the clean sweep — the fixture pins the
fold, not just the absence of an overflow.
"""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _copy(x_ref, o_ref):
    o_ref[:] = x_ref[:]


def scan_carried_fits(xs):
    small = jnp.zeros((64, 256), jnp.float32)
    small, _ = jax.lax.scan(lambda c, x: (c, x), small, xs)
    return pl.pallas_call(
        _copy,
        grid=(4,),
        in_specs=[pl.BlockSpec((None, None), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((32, 128), jnp.float32),
    )(small)


def _tile(row):
    return jnp.zeros((64, 256), jnp.float32)


def vmap_result_fits():
    rows = jnp.zeros((4, 16), jnp.float32)
    tiles = jax.vmap(_tile)(rows)   # (4, 64, 256) via the fold
    return pl.pallas_call(
        _copy,
        grid=(4,),
        in_specs=[pl.BlockSpec((1, None, None), lambda i: (i, 0, 0))],
        out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((32, 128), jnp.float32),
    )(tiles)
