"""Lifecycle shapes hglint must NOT flag: double-checked locking, daemon
and joined threads, a cancelled timer, finally/with-managed resources,
timed parks, predicate-loop waits, guarded worker loops, and threads
that escape to a caller who owns the join."""

import socket
import threading


def _noop():
    return None


class Launcher:
    def __init__(self):
        self._lock = threading.Lock()
        self._thread = None

    def start(self):
        if self._thread is None:  # benign: the ACT is under the lock
            with self._lock:
                if self._thread is None:
                    self._thread = threading.Thread(
                        target=_noop, daemon=True
                    )
                    self._thread.start()

    def stop(self):
        if self._thread is not None:  # check-then-JOIN races harmlessly
            self._thread.join()


class Pump:
    def __init__(self):
        self._cv = threading.Condition()
        self._queue = []
        self._running = True
        self._thread = None

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(target=self._run)
            self._thread.start()

    def stop(self):
        with self._cv:
            self._running = False
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join()  # join-reachable from the stop path

    def submit(self, item):
        with self._cv:
            self._queue.append(item)
            self._cv.notify()

    def park(self, timeout):
        with self._cv:
            self._cv.wait(timeout)  # timed park: the caller re-checks

    def _run(self):
        while True:
            with self._cv:
                while self._running and not self._queue:
                    self._cv.wait()  # predicate re-check loop
                if not self._running:
                    return
                item = self._queue.pop(0)
            try:
                _handle(item)
            except Exception:  # a bad item must not kill the pump
                continue


def _handle(item):
    return item


class Ticker:
    def __init__(self):
        self._timer = None

    def arm(self, cb):
        self._timer = threading.Timer(1.0, cb)
        self._timer.start()

    def disarm(self):
        if self._timer is not None:
            self._timer.cancel()  # cancel-reachable: no leak


def fetch(host):
    sock = socket.create_connection((host, 80))
    try:
        return sock.recv(64)
    finally:
        sock.close()  # closed on the exception edge


def fetch_managed(host):
    with socket.create_connection((host, 80)) as sock:
        return sock.recv(64)


def ping(host):
    sock = socket.create_connection((host, 80))
    sock.close()  # nothing risky in between: straight-line close is fine
    return True


def spawn_daemon():
    t = threading.Thread(target=_handle, daemon=True)
    t.start()


def spawn_tracked(registry):
    t = threading.Thread(target=_handle)
    t.start()
    registry.append(t)  # escapes: the registry's owner joins it
    return t


def accept_once(server):
    conn, addr = server.accept()
    try:
        return conn.recv(64), addr
    finally:
        conn.close()  # exception-edge close for the unpacked conn


class Channel:
    def __init__(self):
        self._sock = None

    def handshake(self, host):
        self._sock = socket.create_connection((host, 80))
        try:
            self._sock.sendall(b"HELLO\n")
        finally:
            self._sock.close()
