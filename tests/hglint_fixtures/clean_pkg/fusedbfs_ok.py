"""Clean twin of fusedbfs_bad — the REAL fused hop geometry
(``ops/pallas_bfs``: B=8 rows × 128 lanes, D*W=64-row DMA scratch,
chunk plan inside half the SMEM budget). Zero findings allowed."""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _hop_kernel(blk_off, chunk_rows, idx, visited, vis_blk, out_ref,
                rows, sems):
    out_ref[...] = vis_blk[...]


def fused_hop_in_budget(visited):
    # chunk plan: 16K chunks × (8 idx + 1 row) int32 = 578 KB of the
    # 1 MB SMEM; windows: 2×2×(8,128) u32 tiles + (64,128) scratch =
    # 48 KB of the 16 MiB VMEM — the committed real-kernel geometry
    blk_off = jnp.zeros((257,), jnp.int32)
    chunk_rows = jnp.zeros((1 << 14,), jnp.int32)
    idx = jnp.zeros((1 << 17,), jnp.int32)
    return pl.pallas_call(
        functools.partial(_hop_kernel),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(256,),
            in_specs=[
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec((8, 128), lambda i, s0, s1, s2: (i, 0)),
            ],
            out_specs=pl.BlockSpec((8, 128), lambda i, s0, s1, s2: (i, 0)),
            scratch_shapes=[pltpu.VMEM((64, 128), jnp.uint32),
                            pltpu.SemaphoreType.DMA((8,))],
        ),
        out_shape=jax.ShapeDtypeStruct((2048, 128), jnp.uint32),
    )(blk_off, chunk_rows, idx, visited, visited[:2048])
