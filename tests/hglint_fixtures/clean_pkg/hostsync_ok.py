"""Known-good traced code + host wrappers — hglint must stay silent.

Host-side syncs (np.asarray, block_until_ready) are DELIBERATE here: they
live in plain host functions, where they belong.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, static_argnames=("n",))
def scale(x, n):
    if n > 2:  # branch on a STATIC param: fine
        return x * n
    return x + n


@jax.jit
def device_sum(x):
    k = int(x.shape[0])  # shape access is concrete under trace: fine
    return jnp.sum(x) * k


@jax.jit
def masked(x):
    return jnp.where(x > 0, x, 0)  # data-dependent select, no Python branch


def host_wrapper(xs):
    arr = np.asarray(xs)  # host side: allowed
    out = device_sum(jnp.asarray(arr))
    jax.block_until_ready(out)  # host side: allowed
    return float(np.asarray(out).sum())
