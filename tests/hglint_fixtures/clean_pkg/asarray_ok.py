"""jnp.asarray on traced/literal values — HG107 must stay silent."""

import jax
import jax.numpy as jnp
import numpy as np

_HOST_TABLE = np.arange(64)


@jax.jit
def traced_asarray(x):
    y = jnp.asarray(x)          # a traced value: legitimate no-op
    z = jnp.asarray([1, 2, 3])  # a literal constant: fine
    return y + z


def host_upload():
    # outside traced code a host->device transfer is exactly where it
    # belongs
    return jnp.asarray(_HOST_TABLE)


@jax.jit
def shadowed_param(_HOST_TABLE):
    # the PARAMETER shadows the module-level numpy global: this is a
    # traced array, not a host upload
    return jnp.asarray(_HOST_TABLE) * 2
