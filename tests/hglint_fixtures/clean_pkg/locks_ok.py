"""Two locks always taken in one consistent order, and a lock-owning class
whose mutations all happen under the lock — hglint must stay silent."""

import threading

outer = threading.Lock()
inner = threading.Lock()


def update_both(items, extra):
    with outer:
        with inner:  # consistent order: outer -> inner, everywhere
            items.extend(extra)


def read_both(items):
    with outer:
        with inner:
            return list(items)


class Store:
    def __init__(self):
        self._lock = threading.Lock()
        self._rev = 0

    def bump(self):
        with self._lock:
            self._rev = self._rev + 1

    def reset_locked(self):
        # *_locked suffix documents the caller-holds-the-lock contract
        self._rev = 0

    def reset(self):
        with self._lock:
            self.reset_locked()  # the hold satisfies the *_locked contract

    def clear_locked(self):
        self.reset_locked()  # *_locked -> *_locked: the contract chains
