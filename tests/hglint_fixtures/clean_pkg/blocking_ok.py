"""Blocking-adjacent shapes hglint must NOT flag: snapshot-then-sort, a
condition wait over its own lock, an audited ``*_locked`` leaf, a
non-blocking queue op, a blocking target merely PASSED under a lock, and
a pragma'd deliberate hold (the pragma is exercised, so HG901 stays
quiet)."""

import queue
import threading
import time

lock = threading.Lock()
events = queue.Queue()


def digest(items):
    with lock:
        snap = list(items)
    return sorted(snap)  # the sort runs OUTSIDE the lock


def poll():
    with lock:
        return events.get(block=False)  # non-blocking get is fine


def deliberate_pause():
    with lock:
        time.sleep(0.01)  # hglint: disable=HG701


class Batcher:
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._items = []
        self._worker = None

    def wait_items(self):
        with self._lock:
            while not self._items:
                self._cv.wait()  # releases its OWN lock while waiting
            return self._items.pop(0)

    def _write_metric_locked(self, value):
        self._items.append(value)  # audited caller-holds-the-lock leaf

    def record(self, value):
        with self._lock:
            self._write_metric_locked(value)

    def spawn(self):
        with self._lock:
            self._worker = threading.Thread(target=time.sleep, daemon=True)
        # a blocking TARGET handed to a thread does not run under the
        # caller's hold — only the ctor call happened there
        self._worker.start()
        self._worker.join()


# -- arg-flow shapes that must stay silent -------------------------------


def _count(items):
    return len(items)


SAFE_OPS = {"count": _count}


def apply_op(kind, items):
    with lock:
        return SAFE_OPS[kind](items)  # every table member is non-blocking


def enqueue_probe(registry):
    with lock:
        registry.apply(_count)  # a NON-blocking callable smuggled in
