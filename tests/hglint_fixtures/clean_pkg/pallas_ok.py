"""A correctly-tiled pallas_call — hglint must stay silent."""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _copy_kernel(x_ref, o_ref):
    o_ref[:] = x_ref[:].astype(jnp.float32)


def tiled_copy(x):
    return pl.pallas_call(
        _copy_kernel,
        grid=(2, 1),
        in_specs=[pl.BlockSpec((8, 128), lambda i, j: (i, j))],
        out_specs=pl.BlockSpec((8, 128), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((16, 128), jnp.float32),
    )(x)
