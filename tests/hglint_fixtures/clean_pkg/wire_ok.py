"""Wire-contract near-misses that must stay silent (HG11xx family).

Mirror of bad_pkg/wire_bad.py: the same shapes with the contracts kept —
matched arities (including a tolerant starred unpack), consumed envelope
keys, a stamped and version-checked artifact, a covering error table
with a faithful round-trip, and registry-vocabulary metric names.
"""
import json

LEDGER_SCHEMA_VERSION = 1

DOTTED_NAMES = ("wireok.sent", "wireok.acked")
WIREOK_LANE_PREFIX = "wireok.lane."


# -- HG1101 twin: matched arity + a starred (tolerant) consumer ----------


class Redelivery:
    def __init__(self):
        self._q = []
        self._wide = []

    def enqueue(self, message, attempt):
        self._q.append((message, attempt))
        self._wide.append((message, attempt, 0.0))

    def drain(self):
        out = []
        for message, attempt in self._q:
            out.append(message)
        for message, *rest in self._wide:
            out.append(message)
        return out


# -- HG1102 twin: every hard-read key is produced ------------------------


def ping(link, seq):
    link.send({"what": "wireok-ping", "seq": seq, "note": "n"})


def on_message(content):
    if content.get("what") == "wireok-ping":
        return content["seq"], content.get("note")
    return None


# -- HG1102 twin at two forwarding hops: the decoder two callees deep
# reads EVERY produced key, so neither the hard-read check nor the
# dead-field warning may fire --------------------------------------------


def pong(link, seq):
    link.send({"what": "wireok-pong", "seq": seq, "echo": "e"})


def on_pong(content):
    if content.get("what") == "wireok-pong":
        return _relay_pong(content)
    return None


def _relay_pong(payload):
    return _decode_pong(payload)


def _decode_pong(payload):
    return payload["seq"], payload.get("echo")


# -- HG1103 twin: stamped writer, version-checked reader -----------------


def save_ledger(path, entries):
    rec = {"schema_version": LEDGER_SCHEMA_VERSION, "entries": entries}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(rec, f)


def load_ledger(path):
    with open(path, encoding="utf-8") as f:
        rec = json.load(f)
    if rec.get("schema_version") != LEDGER_SCHEMA_VERSION:
        return None
    return rec["entries"]


# -- HG1104 twin: covering table + faithful round-trip -------------------


class WireOkErr(Exception):
    pass


class WireOkTimeout(WireOkErr):
    pass


class WireOkRefused(WireOkErr):
    pass


_WIREOK_STATUS = (
    (WireOkTimeout, 504),
    (WireOkRefused, 503),
)


def rehydrate(body):
    kind = body.get("error")
    if kind == "WireOkTimeout":
        raise WireOkTimeout(body)
    if kind == "WireOkRefused":
        raise WireOkRefused(body)
    return None


# -- HG1105 twin: registry names and a registered dynamic prefix ---------


def bump(metrics, lane):
    metrics.incr("wireok.sent")
    metrics.incr("wireok.lane.push")
    metrics.gauge(WIREOK_LANE_PREFIX + lane, 1)
