"""Well-budgeted / hand-verified pallas_call shapes — HG5xx must stay
silent."""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

TILE_R = 8
LANES = 128


def _kernel(x_ref, o_ref, acc_ref):
    acc_ref[:] = x_ref[:]
    o_ref[:] = acc_ref[:]


def within_budget(x):
    # (8, 128) f32 blocks double-buffered + one scratch tile: ~20 KiB
    return pl.pallas_call(
        _kernel,
        grid=(4,),
        in_specs=[pl.BlockSpec((TILE_R, LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((TILE_R, LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((32, LANES), jnp.float32),
        scratch_shapes=[pltpu.VMEM((TILE_R, LANES), jnp.float32)],
    )(x)


def _copy(x_ref, o_ref):
    o_ref[:] = x_ref[:]


def verified_by_hand(x, rows):
    # runtime-shaped block: unresolvable statically, but verified by hand
    # and guarded at runtime by the caller — the pragma records that
    return pl.pallas_call(  # hglint: disable=HG502
        _copy,
        grid=(2,),
        in_specs=[pl.BlockSpec((rows, LANES), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((8, LANES), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((16, LANES), jnp.float32),
    )(x)
