"""Disciplined twins of exceptions_bad.py — every HG10xx rule must stay
silent on this module (and so must every other family)."""
import threading

from hypergraphdb_tpu.fault.errors import TransientFault, is_transient
from hypergraphdb_tpu.fault.registry import FaultRegistry

FAULTS = FaultRegistry()


# -- kill-transparent broad handler (HG1001 silent: re-raises kills) -----


def _arm_fault_point(batch):
    FAULTS.check("ingest.pump", size=len(batch))
    return batch


def pump_once(batch, stats):
    try:
        return _arm_fault_point(batch)
    except BaseException as err:
        if not isinstance(err, Exception):
            raise   # InjectedCrash / KeyboardInterrupt pass through
        stats.incr("pump.errors")
        return None


# -- live typed fault handler (HG1002 silent: TransientFault arrives) ----


def parse_frame(blob):
    try:
        return _arm_fault_point(blob)
    except TransientFault:
        return None


# -- transient-only retry (HG1003 silent) --------------------------------


def drain(inbox):
    while True:
        try:
            return inbox.get_nowait()
        except TransientFault:
            continue


# -- broad retry with a transience guard (HG1003 silent) -----------------


def _submit_once(router, req):
    if router is None:
        raise TransientFault("route table still warming")
    return router.dispatch(req)


def submit_with_retry(router, req):
    for _ in range(3):
        try:
            return _submit_once(router, req)
        except Exception as err:
            if not is_transient(err):
                raise
    return None


# -- guarded thread targets (HG1004 silent) ------------------------------


def _ingest(batch):
    if not batch:
        raise ValueError("empty ingest batch")
    batch.clear()


def guarded_worker(batch, stats):
    try:
        _ingest(batch)
    except Exception:
        stats.incr("ingest.errors")


def drill_worker(stats):
    # only InjectedCrash escapes the guard — by design, a simulated kill
    # MUST take the thread down, so HG1004 exempts base-only escapes
    try:
        FAULTS.check("ingest.drill")
    except Exception:
        stats.incr("drill.faults")


def spawn_ingest(batch, stats):
    return threading.Thread(target=guarded_worker, args=(batch, stats),
                            daemon=True)


def spawn_drill(stats):
    return threading.Thread(target=drill_worker, args=(stats,),
                            daemon=True)


# -- swallow with evidence (HG1005 silent) -------------------------------


def best_effort_flush(sink, log):
    try:
        sink.flush()
    except Exception:
        log.warning("flush failed; next flush retries", exc_info=True)
