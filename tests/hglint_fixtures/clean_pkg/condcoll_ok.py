"""Clean twin of condcoll_bad — branches issue IDENTICAL collectives."""

import jax
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

AXIS = "data"


def _scaled_psum(x):
    return jax.lax.psum(x * 2, AXIS)


def _plain_psum(x):
    return jax.lax.psum(x, AXIS)


def _cond_body(x, flag):
    # both branches run one psum over the same axis: every device issues
    # the same collective sequence regardless of its flag — no finding
    return jax.lax.cond(flag, _scaled_psum, _plain_psum, x)


def run_cond_matched(x, flag):
    mesh = Mesh(np.array(jax.devices()), (AXIS,))
    return shard_map(
        _cond_body, mesh=mesh, in_specs=(P(AXIS), P()), out_specs=P(AXIS)
    )(x, flag)


def _via_helper(x):
    return _plain_psum(x)   # identical collective, one call deep


def _helper_body(x, flag):
    # one branch psums directly, the other routes the SAME psum through a
    # helper — the branch comparison must follow the call and stay silent
    return jax.lax.cond(flag, _plain_psum, _via_helper, x)


def run_helper_matched(x, flag):
    mesh = Mesh(np.array(jax.devices()), (AXIS,))
    return shard_map(
        _helper_body, mesh=mesh, in_specs=(P(AXIS), P()),
        out_specs=P(AXIS),
    )(x, flag)


_REDUCERS = (_plain_psum,)


def _opaque_body(x, flag):
    # one branch psums directly, the other dispatches the SAME psum
    # through a tuple subscript — an opaque callable the scan cannot
    # resolve, so the comparison must be VOIDED (silence over guessing),
    # not reported as a mismatch against an empty branch
    return jax.lax.cond(
        flag, _plain_psum, lambda v: _REDUCERS[0](v), x
    )


def run_opaque_matched(x, flag):
    mesh = Mesh(np.array(jax.devices()), (AXIS,))
    return shard_map(
        _opaque_body, mesh=mesh, in_specs=(P(AXIS), P()),
        out_specs=P(AXIS),
    )(x, flag)


def _helper_with_opaque(x):
    # the helper ALSO psums, but routes part of its work through an
    # opaque subscript call — the scan cannot prove this helper's
    # collective multiset, so the whole comparison must void, not read
    # the helper as an empty arm against the direct psum
    y = _REDUCERS[0](x)
    return jax.lax.psum(y, AXIS)


def _opaque_in_helper_body(x, flag):
    return jax.lax.cond(flag, _plain_psum, _helper_with_opaque, x)


def run_opaque_in_helper(x, flag):
    mesh = Mesh(np.array(jax.devices()), (AXIS,))
    return shard_map(
        _opaque_in_helper_body, mesh=mesh, in_specs=(P(AXIS), P()),
        out_specs=P(AXIS),
    )(x, flag)
