"""Donation used correctly (rebind idiom) — HG106 must stay silent."""

from functools import partial

import jax


@partial(jax.jit, donate_argnums=(0,))
def _update(state, x):
    return state + x


def rebind(state, xs):
    for x in xs:
        state = _update(state, x)   # rebound every iteration: safe
    return state


def branch_rebind(state, x, cold):
    if cold:
        state = _update(state, x)
    else:
        state = _update(state, x * 2)
    return state
