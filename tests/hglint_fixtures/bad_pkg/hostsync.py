"""Seeded HG1xx hazards — host syncs inside traced code.

NEVER imported by tests; hglint analyzes the AST only.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def bad_item(x):
    return x.sum().item()  # HG101: .item() under trace


@partial(jax.jit, static_argnames=("n",))
def bad_float(x, n):
    s = float(x[0])  # HG102: float() on a traced element
    return x * s + n


@jax.jit
def bad_numpy(x):
    return jnp.asarray(np.asarray(x) + 1)  # HG103: numpy under trace


@jax.jit
def bad_device_get(x):
    host = jax.device_get(x)  # HG104: blocking transfer under trace
    return x + host


def _helper_sync(x):
    # HG105, but only because bad_transitive below jits a caller — the
    # taint must flow through the call graph, not the decorator list
    jax.block_until_ready(x)
    return x


@jax.jit
def bad_transitive(x):
    return _helper_sync(x) * 2
