"""Wire-contract violations (HG11xx family), one per rule.

Each section below breaks exactly one cross-boundary contract the hgwire
producer/consumer model can prove wrong. Expected findings are pinned by
line in tests/test_hglint_wire.py; the clean twin is
clean_pkg/wire_ok.py.
"""
import json


# -- HG1101: payload arity drift on a queue channel ----------------------


class Redelivery:
    def __init__(self):
        self._q = []

    def enqueue(self, message, attempt):
        self._q.append((message, attempt))

    def drain(self):
        out = []
        # HG1101: unpacks 3 values from a channel packed with 2-tuples
        for message, attempt, deadline in self._q:
            out.append(message)
        return out


# -- HG1102: consumer hard-reads a key no producer writes ----------------


def ping(link, seq):
    link.send({"what": "wire-ping", "seq": seq, "host": "a"})


def on_message(content):
    if content.get("what") == "wire-ping":
        host = content.get("host")
        deadline = content["deadline"]  # HG1102: never produced
        return content["seq"], host, deadline
    return None


# -- HG1102 at two forwarding hops: the handler delegates to a helper
# that delegates to the decoder; the decoder's hard-read of a key no
# producer writes must still be charged to the consumer ------------------


def pong(link, seq):
    link.send({"what": "wire-pong", "seq": seq})


def on_pong(content):
    if content.get("what") == "wire-pong":
        return _relay_pong(content)
    return None


def _relay_pong(payload):
    return _decode_pong(payload)


def _decode_pong(payload):
    return payload["seq"], payload["ttl"]  # HG1102: never produced


# -- HG1103: persisted JSON record with no schema-version stamp ----------


def save_ledger(path, entries):
    rec = {"entries": entries, "source": "wire"}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(rec, f)  # HG1103: no schema_version stamp


# -- HG1104: wire-table misses a member of the mapped error family -------


class WireErr(Exception):
    pass


class WireTimeout(WireErr):
    pass


class WireRefused(WireErr):
    pass


_WIRE_STATUS = (  # HG1104: WireRefused falls through to the generic 500
    (WireTimeout, 504),
)


# -- HG1105: metric site absent from the governing registry --------------


DOTTED_NAMES = ("wire.sent", "wire.acked")


def bump(metrics):
    metrics.incr("wire.sentt")  # HG1105: typo, not in DOTTED_NAMES
