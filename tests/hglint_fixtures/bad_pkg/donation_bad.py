"""Seeded HG106 hazards — donated-buffer reuse after donate_argnums."""

from functools import partial

import jax


@partial(jax.jit, donate_argnums=(0,))
def _update(state, x):
    return state + x


def read_after_donate(state, x):
    new = _update(state, x)
    # HG106: state's buffer aliased into `new`; this read hits a deleted
    # array on hardware
    return new + state


def _step(state, x):
    return state * x


apply_step = jax.jit(_step, donate_argnums=(0,))


def loop_donate(state, xs):
    out = None
    for x in xs:
        # HG106: `state` is donated on iteration 0 and re-read (re-donated)
        # on iteration 1 — never rebound inside the loop
        out = apply_step(state, x)
    return out


def branch_test_read(state, x):
    new = _update(state, x)
    # HG106: the branch CONDITION reads the donated buffer
    if state.sum() > 0:
        return new
    return new * 2


def iter_read(state, x):
    new = _update(state, x)
    acc = 0.0
    # HG106: the loop ITERATOR reads the donated buffer
    for row in state:
        acc = acc + row
    return new, acc
