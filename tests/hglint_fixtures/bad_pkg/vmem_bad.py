"""Seeded HG5xx hazards — VMEM budget violations."""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _big_kernel(x_ref, o_ref):
    o_ref[:] = x_ref[:]


def overflow(x):
    # HG501: (2048, 1024) f32 blocks are 8 MiB each; double-buffered in +
    # out windows total 32 MiB against the 16 MiB per-core budget
    return pl.pallas_call(
        _big_kernel,
        grid=(4,),
        in_specs=[pl.BlockSpec((2048, 1024), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((2048, 1024), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((8192, 1024), jnp.float32),
    )(x)


def _copy(x_ref, o_ref):
    o_ref[:] = x_ref[:]


def unresolvable(x, rows):
    # HG502: the block row count is a runtime argument — the budget cannot
    # be folded and there is no pragma vouching for it
    return pl.pallas_call(
        _copy,
        grid=(2,),
        in_specs=[pl.BlockSpec((rows, 128), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((16, 128), jnp.float32),
    )(x)
