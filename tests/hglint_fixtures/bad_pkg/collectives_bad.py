"""Seeded HG6xx hazards — shard_map collective inconsistencies."""

import jax
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

AXIS = "data"


def _ghost_body(x):
    # HG601: axis 'ghost' does not exist in the ('data',) mesh
    return jax.lax.psum(x, "ghost")


def run_ghost(x):
    mesh = Mesh(np.array(jax.devices()), (AXIS,))
    return shard_map(
        _ghost_body, mesh=mesh, in_specs=(P(AXIS),), out_specs=P(AXIS)
    )(x)


def _diverging_body(x):
    d = jax.lax.axis_index(AXIS)
    if d == 0:
        # HG602: psum under a branch on a device value — devices taking
        # different paths issue different collective sequences
        x = jax.lax.psum(x, AXIS)
    return x


def run_diverging(x):
    mesh = Mesh(np.array(jax.devices()), (AXIS,))
    return shard_map(
        _diverging_body, mesh=mesh, in_specs=(P(AXIS),), out_specs=P(AXIS)
    )(x)


def _mismatch_helper(x, axis):
    # HG603: every call site passes axis='model', but the only region
    # reaching this helper runs on a ('data',) mesh
    return jax.lax.psum(x, axis)


def _mismatch_body(x):
    return _mismatch_helper(x, "model")


def run_mismatch(x):
    mesh = Mesh(np.array(jax.devices()), (AXIS,))
    return shard_map(
        _mismatch_body, mesh=mesh, in_specs=(P(AXIS),), out_specs=P(AXIS)
    )(x)
