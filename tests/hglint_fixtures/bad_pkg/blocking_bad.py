"""Seeded HG7xx hazards — blocking primitives, transitive blocking, and
O(n) sorts, all while holding a registered lock."""

import queue
import threading
import time

lock = threading.Lock()
state_lock = threading.Lock()
cv = threading.Condition()
jobs = queue.Queue()


def heartbeat():
    with lock:
        time.sleep(0.5)  # HG701: sleep under the module lock


def flush(sock, payload):
    with lock:
        sock.sendall(payload)  # HG701: socket send under the lock


def drain_one():
    with lock:
        return jobs.get()  # HG701: bounded-queue get blocks under the lock


def wait_holding_other():
    with state_lock:
        with cv:
            cv.wait(1.0)  # HG701: state_lock stays held across the wait


def _slow_helper():
    time.sleep(0.1)


def tick():
    with lock:
        _slow_helper()  # HG702: transitively reaches time.sleep


class Ring:
    def __init__(self):
        self._lock = threading.Lock()
        self._members = []
        self._worker = threading.Thread(target=heartbeat, daemon=True)

    def digest(self):
        with self._lock:
            return sorted(self._members)  # HG703: whole-ring sort held

    def stop(self):
        with self._lock:
            self._worker.join()  # HG701: thread join under the lock


# -- blocking taint smuggled through arguments and dispatch tables -------


def run_probe(probe):
    probe()


def prober():
    run_probe(_slow_helper)  # taint follows the smuggled argument


def audit_all():
    with lock:
        prober()  # HG702: reaches time.sleep through an arg-passed edge


def smuggle(registry):
    with lock:
        registry.apply(_slow_helper)  # HG702: blocking callable passed
        # into an unresolvable receiver that runs it under this hold


OPS = {"tick": _slow_helper, "noop": run_probe}


def dispatch(kind):
    with lock:
        OPS[kind]()  # HG702: a table member reaches time.sleep
