"""Seeded HG3xx hazards — Pallas kernel contract violations."""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _cast_kernel(x_ref, o_ref):
    o_ref[:] = x_ref[:].astype(jnp.float16)  # HG304: out_shape says float32


def misaligned(x):
    return pl.pallas_call(
        _cast_kernel,
        grid=(4,),
        in_specs=[pl.BlockSpec((8, 100), lambda i: (i, 0))],  # HG301: lane 100
        out_specs=pl.BlockSpec((5, 128), lambda i: (i, 0)),   # HG301: sublane 5
        out_shape=jax.ShapeDtypeStruct((20, 128), jnp.float32),
    )(x)


def _copy2(x_ref, o_ref):
    o_ref[:] = x_ref[:]


def bad_index_map(x):
    return pl.pallas_call(
        _copy2,
        grid=(4, 2),
        # HG302: index_map takes 1 arg, grid has rank 2
        in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
        # HG302: block index i+1 reaches 4 -> rows up to 40 > 16
        out_specs=pl.BlockSpec((8, 128), lambda i, j: (i + 1, 0)),
        out_shape=jax.ShapeDtypeStruct((16, 128), jnp.float32),
    )(x)


def _copy3(x_ref, o_ref):
    o_ref[:] = x_ref[:]


def bad_dtype_tile(x):
    return pl.pallas_call(
        _copy3,
        grid=(2,),
        in_specs=[pl.BlockSpec((16, 128), lambda i: (i, 0))],
        # HG303: bfloat16 needs sublane % 16, block says 8
        out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((16, 128), jnp.bfloat16),
    )(x)
