"""Seeded HG8xx hazards — leaked threads/timers, an exception-edge
resource leak, a racy check-then-act, an unsafe condition wait, an
unguarded worker loop — plus a stale suppression for HG901."""

import socket
import threading

_LIMIT = 8  # hglint: disable=HG402  <- stale: HG402 never fired here


class Pumper:
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._queue = []
        self._running = True
        self._thread = None

    def start(self):
        if self._thread is None:  # HG803: check-then-act without the lock
            self._thread = threading.Thread(target=self._pump)  # HG801
            self._thread.start()  # never joined, not daemon

    def push(self, item, handler):
        with self._cv:
            self._queue.append((item, handler))
            self._cv.notify()

    def take(self):
        with self._cv:
            if not self._queue:
                self._cv.wait()  # HG804: untimed wait outside a loop
            return self._queue.pop(0)

    def _pump(self):
        while self._running:
            item, handler = self.take()
            handler(item)  # HG805: a raising handler strands the queue


def probe(host):
    sock = socket.create_connection((host, 80))
    banner = sock.recv(64)  # HG802: a raising recv leaks the socket
    sock.close()
    return banner


def fire_and_forget(fn):
    t = threading.Thread(target=fn)  # HG801: local thread, never joined
    t.start()


def schedule(cb):
    t = threading.Timer(5.0, cb)  # HG801: timer never cancelled/joined
    t.start()


def accept_once(server):
    conn, addr = server.accept()
    banner = conn.recv(64)  # HG802: a raising recv leaks the accepted conn
    conn.close()
    return banner, addr


class Channel:
    def handshake(self, host):
        self._sock = socket.create_connection((host, 80))
        self._sock.sendall(b"HELLO\n")  # HG802: a raising send leaks it
        self._sock.close()
        self._sock = None
