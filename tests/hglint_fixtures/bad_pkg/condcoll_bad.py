"""Seeded HG604 hazard — lax.cond branches with mismatched collectives."""

import jax
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

AXIS = "data"


def _with_psum(x):
    return jax.lax.psum(x, AXIS)


def _without_psum(x):
    return x * 2


def _cond_body(x, flag):
    # HG604: the true branch issues a psum, the false branch none — the
    # cond traces fine, but devices whose flags disagree deadlock
    return jax.lax.cond(flag, _with_psum, _without_psum, x)


def run_cond_mismatch(x, flag):
    mesh = Mesh(np.array(jax.devices()), (AXIS,))
    return shard_map(
        _cond_body, mesh=mesh, in_specs=(P(AXIS), P()), out_specs=P(AXIS)
    )(x, flag)


def _hidden_psum(x):
    return _with_psum(x)   # the collective hides one call deep


def _helper_body(x, flag):
    # HG604 through a helper: the true branch's psum is routed through
    # `_hidden_psum`; the false branch issues none — the one-level-deep
    # scan must still see the mismatch
    return jax.lax.cond(flag, _hidden_psum, _without_psum, x)


def run_helper_mismatch(x, flag):
    mesh = Mesh(np.array(jax.devices()), (AXIS,))
    return shard_map(
        _helper_body, mesh=mesh, in_specs=(P(AXIS), P()),
        out_specs=P(AXIS),
    )(x, flag)


def _switch_body(x, which):
    # HG604 via switch: branch collectives disagree on axis spelling
    return jax.lax.switch(
        which,
        [lambda v: jax.lax.psum(v, AXIS), lambda v: jax.lax.pmax(v, AXIS)],
        x,
    )


def run_switch_mismatch(x, which):
    mesh = Mesh(np.array(jax.devices()), (AXIS,))
    return shard_map(
        _switch_body, mesh=mesh, in_specs=(P(AXIS), P()),
        out_specs=P(AXIS),
    )(x, which)
