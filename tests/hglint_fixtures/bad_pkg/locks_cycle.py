"""Seeded HG4xx hazards — a deliberate A->B / B->A lock-order cycle plus an
unlocked shared-state mutation."""

import threading

lock_a = threading.Lock()
lock_b = threading.Lock()


def transfer_ab(src, dst):
    with lock_a:
        with lock_b:  # order: a -> b
            dst.append(src.pop())


def transfer_ba(src, dst):
    with lock_b:
        with lock_a:  # HG401: order b -> a closes the cycle
            dst.append(src.pop())


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0

    def bump_unsafe(self):
        self.value = self.value + 1  # HG402: mutation outside self._lock

    def bump(self):
        with self._lock:
            self.value = self.value + 1


class Journal:
    def __init__(self):
        self._lock = threading.Lock()
        self._entries = []

    def _append_locked(self, entry):
        self._entries.append(entry)

    def append(self, entry):
        with self._lock:
            self._append_locked(entry)

    def drain_fast(self):
        out = list(self._entries)
        self._append_locked(("drained", len(out)))  # HG403: no lock held
        return out
