"""Seeded HG107 hazards — host numpy silently uploaded in traced code."""

import jax
import jax.numpy as jnp
import numpy as np

_TABLE = np.arange(1024)


@jax.jit
def uses_global_table(x):
    # HG107: a module-level host numpy array baked into the trace — a
    # silent host->device transfer on every retrace
    t = jnp.asarray(_TABLE)
    return x + t


@jax.jit
def uses_local_numpy(x):
    mask = np.zeros(8)       # HG103: numpy call in traced code
    m = jnp.asarray(mask)    # HG107: ...and its upload
    return x * m
