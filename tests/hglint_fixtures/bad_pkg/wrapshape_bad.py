"""Seeded HG501 hazards only foldable THROUGH scan/vmap wrappers.

Both pallas_call sites use ``None`` block dims, so the budget needs the
operand's shape — which only exists if the interpreter propagates
``ShapeDtype`` through the ``lax.scan`` carry / ``jax.vmap`` result.
Before that propagation these sites degraded to HG502 (unresolvable);
now they fold and the overflow is caught as the error it is.
"""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _copy(x_ref, o_ref):
    o_ref[:] = x_ref[:]


def scan_carried_overflow(xs):
    # carry keeps the init's (4096, 2048) f32 shape through the scan; the
    # None block dims then fold to 32 MiB double-buffered in-window alone
    big = jnp.zeros((4096, 2048), jnp.float32)
    big, _ = jax.lax.scan(lambda c, x: (c, x), big, xs)
    return pl.pallas_call(
        _copy,
        grid=(4,),
        in_specs=[pl.BlockSpec((None, None), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((32, 128), jnp.float32),
    )(big)


def _tile(row):
    return jnp.zeros((4096, 2048), jnp.float32)


def vmap_result_overflow():
    rows = jnp.zeros((4, 16), jnp.float32)
    tiles = jax.vmap(_tile)(rows)   # (4, 4096, 2048) via the fold
    return pl.pallas_call(
        _copy,
        grid=(4,),
        in_specs=[pl.BlockSpec((1, None, None), lambda i: (i, 0, 0))],
        out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((32, 128), jnp.float32),
    )(tiles)
