"""Seeded HG501 + HG503 hazards shaped like the fused pull-BFS hop
kernel (``ops/pallas_bfs._hop_call``): the scalar-prefetched chunk plan
overflowing SMEM, and DMA row scratch + double-buffered visited windows
overflowing VMEM — the exact window math the real kernel guards with
``_smem_bytes``/``_vmem_bytes`` at runtime."""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _hop_kernel(blk_off, chunk_rows, idx, visited, vis_blk, out_ref,
                rows, sems):
    out_ref[...] = vis_blk[...]


def fused_hop_smem_overflow(visited):
    # HG503: the fused chunk plan — (1 << 17,) chunk_rows + (1 << 20,)
    # idx int32 — is 4.5 MB of scalar prefetch against the 1 MB SMEM;
    # Mosaic allocation dies on hardware only
    blk_off = jnp.zeros((257,), jnp.int32)
    chunk_rows = jnp.zeros((1 << 17,), jnp.int32)
    idx = jnp.zeros((1 << 20,), jnp.int32)
    return pl.pallas_call(
        functools.partial(_hop_kernel),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(256,),
            in_specs=[
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec((8, 128), lambda i, s0, s1, s2: (i, 0)),
            ],
            out_specs=pl.BlockSpec((8, 128), lambda i, s0, s1, s2: (i, 0)),
            scratch_shapes=[pltpu.VMEM((64, 128), jnp.uint32),
                            pltpu.SemaphoreType.DMA((8,))],
        ),
        out_shape=jax.ShapeDtypeStruct((2048, 128), jnp.uint32),
    )(blk_off, chunk_rows, idx, visited, visited[:2048])


def fused_hop_vmem_overflow(visited):
    # HG501: a 16K-lane visited row blows the window model — the
    # double-buffered (8, 16384) uint32 in/out blocks plus the
    # (64, 16384) DMA row scratch total ~6 MiB... widened further by a
    # (2048, 16384) scratch that alone is 128 MiB
    blk_off = jnp.zeros((257,), jnp.int32)
    return pl.pallas_call(
        functools.partial(_hop_kernel),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(256,),
            in_specs=[
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec((8, 16384), lambda i, s0: (i, 0)),
            ],
            out_specs=pl.BlockSpec((8, 16384), lambda i, s0: (i, 0)),
            scratch_shapes=[pltpu.VMEM((2048, 16384), jnp.uint32),
                            pltpu.SemaphoreType.DMA((8,))],
        ),
        out_shape=jax.ShapeDtypeStruct((2048, 16384), jnp.uint32),
    )(blk_off, visited, visited[:2048])
