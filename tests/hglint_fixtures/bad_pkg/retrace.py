"""Seeded HG2xx hazards — retrace/recompile traps."""

from functools import partial

import jax

_REGISTRY = {}  # mutable module global


def retrace_loop(fns, xs):
    outs = []
    for f in fns:
        jf = jax.jit(f)  # HG201: fresh jit per iteration
        outs.append(jf(xs))
    return outs


@jax.jit
def branch_on_traced(x, flag):
    if flag:  # HG202: Python branch on traced param
        return x + 1
    return x - 1


@jax.jit
def global_capture(x):
    scale = len(_REGISTRY)  # HG203: mutable global baked in at trace time
    return x * scale


def make_jitted(fn):
    return jax.jit(fn, static_argnums={"n": 1})  # HG204: dict is unhashable


make_partial = partial(jax.jit, static_argnames={"mode"})  # HG204 via partial
