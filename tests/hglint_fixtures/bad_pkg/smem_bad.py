"""Seeded HG503 hazard — scalar-prefetch operands overflow SMEM."""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _gather_kernel(idx_ref, x_ref, o_ref):
    o_ref[:] = x_ref[:]


def smem_overflow(x):
    # HG503: the scalar-prefetched index array is (1 << 19,) int32 = 2 MB,
    # double the 1 MB SMEM — Mosaic allocation dies on hardware only
    idx = jnp.zeros((1 << 19,), jnp.int32)
    return pl.pallas_call(
        _gather_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(4,),
            in_specs=[pl.BlockSpec((8, 128), lambda i, s: (i, 0))],
            out_specs=pl.BlockSpec((8, 128), lambda i, s: (i, 0)),
        ),
        out_shape=jax.ShapeDtypeStruct((32, 128), jnp.float32),
    )(idx, x)
