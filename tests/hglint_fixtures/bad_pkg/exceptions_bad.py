"""Exception-flow discipline violations (HG10xx family).

Each function below swallows, misdirects, or retries a failure in a way
the interprocedural raise-set model can prove wrong. Expected findings
are pinned by line in tests/test_hglint_exc.py.
"""
import threading

from hypergraphdb_tpu.fault.errors import PermanentFault, TransientFault
from hypergraphdb_tpu.fault.registry import FaultRegistry

FAULTS = FaultRegistry()


# -- HG1001: a broad handler that eats the drill's simulated kill --------


def _arm_fault_point(batch):
    FAULTS.check("ingest.pump", size=len(batch))
    return batch


def pump_once(batch, stats):
    try:
        return _arm_fault_point(batch)
    except BaseException:   # HG1001: swallows InjectedCrash
        stats.incr("pump.errors")
        return None


# -- HG1002: a typed fault handler over a body that cannot raise it ------


def _decode(blob):
    if not blob:
        raise ValueError("empty frame")
    return blob


def parse_frame(blob):
    try:
        return _decode(blob)
    except TransientFault:   # HG1002: _decode only raises ValueError
        return None


# -- HG1003 (explicit): retry loop that re-attempts a permanent fault ----


def drain(inbox):
    while True:
        try:
            return inbox.get_nowait()
        except PermanentFault:   # HG1003: permanent -> retrying is futile
            continue


# -- HG1003 (inferred): broad retry over a provably-permanent raise ------


def _submit_once(router, req):
    if router is None:
        raise PermanentFault("no route for shard")
    return router.dispatch(req)


def submit_with_retry(router, req):
    for _ in range(3):
        try:
            return _submit_once(router, req)
        except Exception:   # HG1003: PermanentFault arrives here
            req.attempts += 1
    return None


# -- HG1004: a thread target whose body can raise straight through -------


def crashy_worker(batch):
    if not batch:
        raise ValueError("empty ingest batch")
    batch.clear()


def spawn_ingest(batch):
    return threading.Thread(target=crashy_worker, args=(batch,),
                            daemon=True)


# -- HG1005: swallow with no evidence at all -----------------------------


def best_effort_flush(sink):
    try:
        sink.flush()
    except Exception:   # HG1005: no re-raise, log, counter, or fallback
        pass
