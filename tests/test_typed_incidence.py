"""Typed-incidence annotation (VERDICT r4 missing #4): And(Incident,
AtomType) answered from the incidence set + the hot host type column —
no store record read per candidate link (ref
``storage/bdb-native/.../TypeAndPositionIncidenceAnnotator.java``)."""

import numpy as np
import pytest

from hypergraphdb_tpu import HyperGraph
from hypergraphdb_tpu.query import dsl as hg
from hypergraphdb_tpu.query.compiler import (
    TypedIncidencePlan,
    compile_query,
)


@pytest.fixture
def tdb():
    g = HyperGraph()
    anchor = g.add("anchor")
    others = [g.add(f"o{i}") for i in range(6)]
    links = []
    for i, o in enumerate(others):
        # alternate int-valued and string-valued links → two link types
        v = i if i % 2 == 0 else f"s{i}"
        links.append(g.add_link((anchor, o), value=v))
    yield g, anchor, others, links
    g.close()


def test_plan_shape_fuses_type_into_incidence(tdb):
    g, anchor, *_ = tdb
    q = compile_query(g, hg.and_(hg.type_("int"), hg.incident(anchor)))
    assert isinstance(q.plan, TypedIncidencePlan), q.analyze()


def test_typed_incidence_differential(tdb):
    g, anchor, others, links = tdb
    got = sorted(g.find_all(hg.and_(hg.type_("int"), hg.incident(anchor))))
    want = sorted(
        int(l) for i, l in enumerate(links) if i % 2 == 0
    )
    assert got == want


def test_no_store_reads_per_candidate(tdb, monkeypatch):
    """The annotation's whole point: once the column is hot, candidate
    links are classified WITHOUT loading their records."""
    g, anchor, *_ = tdb
    g.type_column()  # build while get_link is unpatched
    calls = []
    orig = g.store.get_link
    monkeypatch.setattr(
        g.store, "get_link", lambda h: (calls.append(h), orig(h))[1]
    )
    got = g.find_all(hg.and_(hg.type_("int"), hg.incident(anchor)))
    assert len(got) == 3
    assert not calls, f"candidate links were loaded: {calls}"


def test_column_tracks_add_remove_replace(tdb):
    g, anchor, others, links = tdb
    cond = hg.and_(hg.type_("int"), hg.incident(anchor))
    before = set(g.find_all(cond))

    nl = g.add_link((anchor, others[0]), value=99)       # new int link
    g.remove(int(links[0]))                              # drop an int link
    g.replace(int(links[2]), "now-a-string")             # int → string
    got = set(g.find_all(cond))
    assert int(nl) in got
    assert int(links[0]) not in got
    assert int(links[2]) not in got
    assert got == (before | {int(nl)}) - {int(links[0]), int(links[2])}


def test_column_cold_start_falls_back_to_store(tdb):
    """Handles beyond the built column (or unknown) re-check the store —
    staleness costs time, never correctness."""
    g, anchor, others, _ = tdb
    tc = g.type_column()
    # shrink the column artificially: everything is "unknown"
    tc._col = np.full(2, -1, dtype=np.int32)
    got = sorted(g.find_all(hg.and_(hg.type_("int"), hg.incident(anchor))))
    want = sorted(
        int(h) for h in g.get_incidence_set(int(anchor)).array()
        if isinstance(g.get(int(h)).value, int)
    )
    assert got == want


def test_three_way_conjunction_still_exact(tdb):
    g, anchor, others, links = tdb
    got = sorted(g.find_all(hg.and_(
        hg.type_("int"), hg.incident(anchor), hg.incident(others[0])
    )))
    assert got == [int(links[0])]


def test_first_class_typed_incident_condition(tdb):
    """TypedIncident (bdb-native TypedIncidentCondition parity): compiles
    to the fused plan, matches the And form, survives the wire."""
    from hypergraphdb_tpu.query import serialize as qser

    g, anchor, others, links = tdb
    cond = hg.typed_incident(anchor, "int")
    q = compile_query(g, cond)
    assert isinstance(q.plan, TypedIncidencePlan), q.analyze()
    got = sorted(g.find_all(cond))
    want = sorted(g.find_all(hg.and_(hg.type_("int"), hg.incident(anchor))))
    assert got == want and len(got) == 3

    # per-atom predicate form agrees
    assert all(cond.satisfies(g, h) for h in got)
    assert not cond.satisfies(g, int(links[1]))  # string-valued link

    # remote-query serialization round-trips
    back = qser.from_json(qser.to_json(cond))
    assert back == cond
