"""hgsub chaos-style acceptance soak.

Three standing contracts, each end-to-end:

1. **Differential soak** (3 seeds): N standing patterns + ranges under
   seeded concurrent ingest receive EXACTLY the incremental match
   deltas — at every checkpoint the client-side fold of the pushed
   deltas equals a full re-evaluation against the live graph, every
   note chains ``seq_from == previous seq_to``, every digest audits,
   no duplicate adds, no phantom removals, zero sheds.
2. **Coalescing**: a 1000-subscription dirty burst batches into the
   SAME bucketed device programs as ad-hoc lanes — the device dispatch
   count stays sublinear in the eval count (serve stats evidence).
3. **Door resume**: a killed replica's subscription resumes through the
   front door without loss or duplicates — the failover synthesizes ONE
   chained notification diffing the door mirror against the adopted
   snapshot, and the subscription stays live on the survivor.
"""

from __future__ import annotations

import random
import threading
import time

import pytest

import hypergraphdb_tpu as hg
from hypergraphdb_tpu.obs.http import runtime_health
from hypergraphdb_tpu.peer import transfer
from hypergraphdb_tpu.peer.peer import HyperGraphPeer
from hypergraphdb_tpu.peer.transport import LoopbackNetwork
from hypergraphdb_tpu.query import conditions as c
from hypergraphdb_tpu.replica import (
    FrontDoor,
    LocalBackend,
    ReplicaConfig,
    ReplicaNode,
    RouterConfig,
    submit_payload,
)
from hypergraphdb_tpu.serve import ServeConfig, ServeRuntime
from hypergraphdb_tpu.serve.types import Unservable
from hypergraphdb_tpu.sub import SubscriptionManager
from hypergraphdb_tpu.sub import wire as sub_wire
from hypergraphdb_tpu.sub.registry import match_digest


def serve_cfg(**kw):
    kw.setdefault("max_linger_s", 0.001)
    kw.setdefault("prewarm_aot", False)
    return ServeConfig(**kw)


def settle(mgr, timeout=60.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        mgr.pump()
        with mgr._lock:
            busy = any(s.dirty or s.inflight is not None
                       for s in mgr.subs.all())
        if not busy:
            return
        time.sleep(0.005)
    raise AssertionError("subscriptions never settled")


def wait_for(cond, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.02)
    return False


class Folder:
    """A consumer's fold of the pushed deltas, enforcing the delivery
    contract on every note: chained seqs, no duplicate adds, no phantom
    removals, a digest that audits the folded set."""

    def __init__(self, subscribed: dict):
        assert subscribed["what"] == "subscribed"
        self.matches = {int(m) for m in subscribed["matches"]}
        self.seq = subscribed["seq"]
        assert subscribed["digest"] == match_digest(self.matches)

    def fold_env(self, env: dict) -> None:
        assert env["what"] == "notifications", env
        for n in env["notes"]:
            assert n["what"] == "notification"
            # empty-diff evals advance the anchor WITHOUT a note (the
            # freshness contract), so a chain may skip forward — but it
            # must never regress or overlap the folded prefix
            assert self.seq <= n["seq_from"] <= n["seq_to"], \
                f"chain regressed: {n['seq_from']}..{n['seq_to']} " \
                f"after {self.seq}"
            added = {int(x) for x in n["added"]}
            removed = {int(x) for x in n["removed"]}
            assert added.isdisjoint(self.matches), "duplicate delivery"
            assert removed <= self.matches, "phantom removal"
            self.matches -= removed
            self.matches |= added
            self.seq = n["seq_to"]
            assert n["digest"] == match_digest(self.matches)

    def drain(self, poll) -> None:
        """Poll-fold until the queue reads empty."""
        while True:
            env = poll()
            self.fold_env(env)
            if not env["notes"] and not env["more"]:
                return


# ------------------------------------------------- 1. differential soak


@pytest.mark.parametrize("seed", [3, 11, 29])
def test_differential_soak_incremental_equals_full_eval(seed):
    rng = random.Random(seed)
    g = hg.HyperGraph()
    hubs = [int(g.add(f"hub{i}")) for i in range(6)]
    pool = [int(g.add(f"n{i}")) for i in range(30)]
    links = [int(g.add_link((rng.choice(hubs), rng.choice(pool)),
                            value=5000 + rng.randrange(180)))
             for _ in range(40)]
    vatoms = [int(g.add(5000 + rng.randrange(180))) for _ in range(20)]

    rt = ServeRuntime(g, serve_cfg(buckets=(4,)))
    mgr = SubscriptionManager(g, rt)
    rt.attach_subscriptions(mgr)
    try:
        folders = {}
        for h in hubs:
            r = mgr.subscribe("pattern", {"anchors": [h]}, window=512)
            folders[r["id"]] = Folder(r)
        for k in range(4):
            lo = 5000 + k * 40
            r = mgr.subscribe("range", {"lo": lo, "hi": lo + 60},
                              window=512)
            folders[r["id"]] = Folder(r)

        CHECKPOINTS = 3
        barrier = threading.Barrier(2, timeout=120)
        failures = []

        def writer():
            w = random.Random(seed * 7 + 1)
            try:
                for _ in range(CHECKPOINTS):
                    for _ in range(25):
                        p = w.random()
                        if p < 0.45:
                            links.append(int(g.add_link(
                                (w.choice(hubs), w.choice(pool)),
                                value=5000 + w.randrange(180))))
                        elif p < 0.65:
                            vatoms.append(int(
                                g.add(5000 + w.randrange(180))))
                        elif p < 0.80 and vatoms:
                            # a value MOVE across the range windows
                            g.replace(w.choice(vatoms),
                                      5000 + w.randrange(180))
                        elif p < 0.92 and links:
                            g.remove(links.pop(
                                w.randrange(len(links))))
                        elif vatoms:
                            g.remove(vatoms.pop(
                                w.randrange(len(vatoms))))
                    barrier.wait()   # checkpoint: graph now stable
                    barrier.wait()   # verified — resume writing
            except Exception as e:  # surface, don't deadlock the barrier
                failures.append(e)
                barrier.abort()

        t = threading.Thread(target=writer)
        t.start()
        for ck in range(CHECKPOINTS):
            barrier.wait()
            settle(mgr)
            for sid, f in folders.items():
                f.drain(lambda s=sid: mgr.poll(s, max_notes=64,
                                               timeout_s=0.0))
                sub = mgr.subs.get(sid)
                full = mgr._full_eval(sub)
                assert f.matches == full, (
                    f"seed {seed} checkpoint {ck}: {sub.kind} fold "
                    f"diverged from full re-evaluation")
            barrier.wait()
        t.join(timeout=60)
        assert not t.is_alive() and not failures

        # in-window consumers: the whole soak was DELTAS, never a resync
        assert mgr.stats.shed == 0
        snap = mgr.stats.snapshot()
        assert snap["sub.resyncs"] == 0
        assert snap["sub.notified"] > 0
        assert snap["sub.eval_errors"] == 0
    finally:
        mgr.close()
        rt.close(drain=False)
        g.close()


# ------------------------------------------------------- 2. coalescing


def test_thousand_subscription_burst_coalesces_into_buckets():
    """1000 dirty standing patterns re-fire through the SAME bucketed
    batcher as ad-hoc lanes: device dispatches stay sublinear in evals
    (the acceptance bound; a per-subscription dispatch would be 1:1)."""
    rng = random.Random(5)
    g = hg.HyperGraph()
    hubs = [int(g.add(f"hub{i}")) for i in range(8)]
    pool = [int(g.add(i)) for i in range(64)]
    for j in range(256):
        g.add_link((hubs[j % 8], rng.choice(pool)), value=j)

    rt = ServeRuntime(g, serve_cfg(buckets=(64,), max_linger_s=0.005))
    mgr = SubscriptionManager(g, rt)
    mgr.config.max_subscriptions = 2048
    rt.attach_subscriptions(mgr)
    try:
        sids = [mgr.subscribe("pattern", {"anchors": [hubs[i % 8]]},
                              window=64)["id"]
                for i in range(1000)]
        settle(mgr, timeout=120)

        before = rt.stats_snapshot()["device_dispatches"]
        evals_before = mgr.stats.evals
        for h in hubs:                 # one mutation per hub dirties all
            g.add_link((h, pool[0]), value=9999)
        settle(mgr, timeout=300)

        evals = mgr.stats.evals - evals_before
        dispatches = rt.stats_snapshot()["device_dispatches"] - before
        assert evals >= 1000           # every subscription re-evaluated
        assert 0 < dispatches <= evals // 4, (
            f"{dispatches} dispatches for {evals} evals — the burst "
            "did not coalesce")

        # spot-check delivery: folds equal full re-evaluation
        for sid in rng.sample(sids, 12):
            sub = mgr.subs.get(sid)
            f = Folder({"what": "subscribed", "matches": [],
                        "seq": 0, "digest": match_digest(set())})
            f.matches = set(sub.matches)  # resynced view is fine here;
            # the soak above already proved the chain — this checks the
            # settled STATE against the oracle
            assert f.matches == mgr._full_eval(sub)
    finally:
        mgr.close()
        rt.close(drain=False)
        g.close()


# ------------------------------------------------------- 3. door resume


class SubNodeBackend:
    """A replaceable-node backend that also speaks the subscription
    verbs (the shape ``LocalBackend`` exposes for a primary)."""

    def __init__(self, backend_id, get_node):
        self.id = backend_id
        self._get = get_node

    def _mgr(self):
        m = getattr(self._get().runtime, "subscriptions", None)
        if m is None:
            raise Unservable(f"{self.id} has no subscription tier")
        return m

    def submit(self, payload, timeout):
        return submit_payload(self._get().runtime, payload, timeout)

    def subscribe(self, payload, timeout):
        return sub_wire.subscribe_payload(self._mgr(), payload)

    def poll(self, params, timeout):
        return sub_wire.poll_payload(self._mgr(), params)

    def health(self):
        return self._get().health_probe()()


def test_replica_kill_resumes_subscription_through_door(tmp_path):
    rng = random.Random(17)
    net = LoopbackNetwork()

    gp = hg.HyperGraph()
    pp = HyperGraphPeer.loopback(gp, net, identity="primary")
    pp.replication.debounce_s = 0.005
    pp.replication.send_backoff_s = 0.001
    pp.replication.redelivery_interval_s = 0.01
    pp.replication.max_redeliveries = 2
    pp.replication.max_redelivery_backlog = 500
    pp.replication.journal_path = str(tmp_path / "primary.jsonl")
    pp.start()
    hubs = [int(gp.add(f"hub{i}")) for i in range(4)]
    pool = [int(gp.add(f"p{i}")) for i in range(16)]
    for j in range(24):
        gp.add_link((rng.choice(hubs), rng.choice(pool)), value=100 + j)
    doomed = int(gp.add_link((hubs[0], pool[3]), value=999))

    def new_replica(ident):
        gr = hg.HyperGraph()
        pr = HyperGraphPeer.loopback(gr, net, identity=ident)
        pr.replication.debounce_s = 0.005
        node = ReplicaNode(gr, pr, ReplicaConfig(
            primary="primary", anti_entropy_interval_s=0.1,
            serve=serve_cfg()))
        node.start()
        return node

    n1, n2 = new_replica("r1"), new_replica("r2")
    current = {"r1": n1, "r2": n2}
    assert pp.replication.flush()
    assert n1.wait_converged(timeout=30) and n2.wait_converged(timeout=30)
    for n in (n1, n2):
        assert wait_for(lambda n=n: transfer.content_digest(gp)
                        == transfer.content_digest(n.graph))

    # both replicas built identically from empty via the same stream →
    # identical replica-LOCAL handles; the wire payload carries raw
    # handles, so that determinism is what makes re-placement coherent
    def resolve(graph, value):
        hs = [int(h) for h in graph.find_all(c.AtomValue(value))]
        assert len(hs) == 1
        return hs[0]

    anchor = resolve(n1.graph, "hub0")
    assert anchor == resolve(n2.graph, "hub0")

    def truth(graph):
        return {int(h) for h in
                graph.find_all(c.Incident(resolve(graph, "hub0")))}

    # primary deliberately WITHOUT a subscription tier: the failover
    # below must adopt on the surviving replica, not fall back
    prt = ServeRuntime(gp, serve_cfg())
    fd = FrontDoor(
        LocalBackend("primary", prt, runtime_health(prt), role="primary"),
        [SubNodeBackend("r1", lambda: current["r1"]),
         SubNodeBackend("r2", lambda: current["r2"])],
        RouterConfig(breaker_threshold=2, breaker_cooldown_s=3600.0,
                     poll_interval_s=0, health_refresh_s=3600.0),
    ).start()
    try:
        fd.refresh_health()
        resp = fd.subscribe({"what": "subscribe", "kind": "pattern",
                             "anchors": [anchor], "window": 64})
        assert resp["what"] == "subscribed"
        dsid = resp["id"]
        assert dsid.startswith("dsub-")
        owner = resp["routed_to"]
        assert owner in ("r1", "r2")
        folder = Folder(resp)
        assert folder.matches == truth(n1.graph)

        def drained_to(want_graph):
            folder.drain(lambda: fd.poll(
                {"id": dsid, "timeout_s": 0.2, "max": 32}))
            return folder.matches == truth(want_graph)

        # a live delta BEFORE the kill flows through the owner
        gp.add_link((hubs[0], pool[0]), value=201)
        assert pp.replication.flush()
        assert wait_for(lambda: drained_to(current[owner].graph))

        # kill the owning replica, then land ingest it will never see:
        # one add and one removal, so the resume diff has BOTH edges
        survivor = "r2" if owner == "r1" else "r1"
        current[owner].stop(drain=False)
        gp.add_link((hubs[0], pool[1]), value=202)
        gp.remove(doomed)
        surv = current[survivor]
        assert wait_for(lambda: transfer.content_digest(gp)
                        == transfer.content_digest(surv.graph))

        # the poll crosses the kill: the door re-places the ORIGINAL
        # payload on the survivor and answers with one synthesized
        # chained note (Folder enforces chain/no-dup/no-loss/digest)
        assert wait_for(lambda: drained_to(surv.graph), timeout=30)
        assert fd.metrics.counters.get("router.sub_failovers", 0) == 1
        assert fd.metrics.counters.get("router.sub_chain_gaps", 0) == 0
        with fd._lock:
            assert fd._subs[dsid]["backend"] == survivor

        # still live AFTER the failover: deltas flow from the survivor
        gp.add_link((hubs[0], pool[2]), value=203)
        assert pp.replication.flush()
        assert wait_for(lambda: drained_to(surv.graph))
        assert fd.metrics.counters.get("router.sub_failovers", 0) == 1

        # unsubscribe tears the mirror down
        out = fd.subscribe({"what": "unsubscribe", "id": dsid})
        assert out == {"what": "unsubscribed", "id": dsid}
        with pytest.raises(Unservable):
            fd.poll({"id": dsid, "timeout_s": 0.0})
    finally:
        fd.stop()
        prt.close()
        for node in set(current.values()):
            node.stop(drain=False)
        pp.stop()
        gp.close()
