"""Precision pins for the hgwire rule family (HG11xx wire-contract
analysis).

Four jobs, mirroring tests/test_hglint_exc.py:

1. pin the seeded wire fixtures exactly — rule AND line — so a
   precision regression in either direction (missed drift, new false
   positive) fails loudly;
2. pin the diagnostics' CONTENT: channel names, producer witnesses,
   and remediation hints a reviewer needs to judge the finding;
3. prove HG1105 agrees with the runtime metric-drift gate: the
   AST-evaluated registry vocabulary equals the imported
   ``DOTTED_NAMES``, so the static rule and the runtime test can never
   disagree about what "registered" means;
4. act as the zero-baseline gate: ``hypergraphdb_tpu`` must carry NO
   HG11xx findings — wire drift gets fixed (or pragma-audited), never
   baselined.
"""

import sys
import textwrap
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from tools.hglint import run_lint  # noqa: E402
from tools.hglint.loader import discover_modules  # noqa: E402
from tools.hglint.model import rule_matches  # noqa: E402
from tools.hglint.rules_wire import collect_registries  # noqa: E402

FIXTURES = Path(__file__).parent / "hglint_fixtures"
BAD = FIXTURES / "bad_pkg" / "wire_bad.py"
OK = FIXTURES / "clean_pkg" / "wire_ok.py"


def _pins(findings):
    return sorted((f.rule, f.line) for f in findings)


# ------------------------------------------------------------- exact pins


def test_wire_bad_exact_rule_and_line():
    findings = run_lint([str(BAD)], only="HG11")
    assert _pins(findings) == [
        ("HG1101", 24),   # 3-unpack of a channel packed with 2-tuples
        ("HG1102", 37),   # hard-read of a key no producer writes
        ("HG1102", 54),   # the same drift TWO forwarding hops deep
        ("HG1103", 73),   # persisted record with no schema-version stamp
        ("HG1104", 91),   # WireRefused missing from the status table
        ("HG1105", 103),  # metric name absent from DOTTED_NAMES
    ], "\n".join(f.render() for f in findings)


def test_each_rule_fires_exactly_as_seeded():
    findings = run_lint([str(BAD)], only="HG11")
    rules = sorted(f.rule for f in findings)
    # HG1102 is seeded twice: the direct consumer and the two-hop
    # forwarded one — everything else exactly once
    assert rules == ["HG1101", "HG1102", "HG1102", "HG1103", "HG1104",
                     "HG1105"]
    assert all(f.severity == "error" for f in findings)


def test_wire_clean_shapes_are_silent():
    # EVERY near-miss must stay silent: matched arity, a tolerant
    # starred unpack, produced keys, a stamped+checked artifact, a
    # covering table with a faithful round-trip, registry metric names
    # and a registered dynamic prefix
    findings = run_lint([str(OK)])
    assert findings == [], "\n".join(f.render() for f in findings)


# ----------------------------------------------------- diagnostic content


def test_arity_drift_names_channel_and_producer_witness():
    findings = run_lint([str(BAD)], only="HG1101")
    (hit,) = findings
    assert hit.scope == "Redelivery.drain"
    assert "wire_bad.Redelivery._q" in hit.message       # merged channel
    assert "needs exactly 3 values" in hit.message
    assert "`Redelivery.enqueue` packs 2-tuples" in hit.message
    assert "wire_bad.py:19" in hit.message               # pack-site witness


def test_envelope_drift_names_kind_and_key():
    findings = run_lint([str(BAD)], only="HG1102")
    hit = next(f for f in findings if "wire-ping" in f.message)
    assert "kind 'wire-ping'" in hit.message
    assert "'deadline'" in hit.message
    assert "KeyError in waiting" in hit.message
    assert "`.get()`" in hit.message                     # the tolerant out


def test_two_hop_forwarded_consumer_is_charged_the_read():
    # the handler delegates to a helper that delegates to the decoder;
    # the decoder's hard-read of an unproduced key anchors at the
    # CONSUMER's dispatch branch, not at the decoder
    findings = run_lint([str(BAD)], only="HG1102")
    hit = next(f for f in findings if "wire-pong" in f.message)
    assert hit.scope == "on_pong"
    assert "'ttl'" in hit.message
    assert "'seq'" not in hit.message        # the produced key is clean


def test_forwarded_walk_is_bounded_at_two_hops(tmp_path):
    # THREE forwarding hops exceed the budget: the decoder's read is
    # invisible, so neither the hard-read error nor a dead-field
    # warning may fire — the walk under-approximates, never guesses
    mod = tmp_path / "three_hops.py"
    mod.write_text(textwrap.dedent("""\
        def ping(link):
            link.send({"what": "hop3-ping", "seq": 1})


        def on_message(content):
            if content.get("what") == "hop3-ping":
                return hop_a(content)
            return None


        def hop_a(payload):
            return hop_b(payload)


        def hop_b(payload):
            return hop_c(payload)


        def hop_c(payload):
            return payload["never_produced"]
    """))
    findings = run_lint([str(mod)], only="HG1102")
    errors = [f for f in findings if f.severity == "error"]
    assert errors == [], "\n".join(f.render() for f in errors)


def test_dead_field_is_a_warning_not_an_error(tmp_path):
    # a produced-but-never-read key is drift evidence, not a crash:
    # severity must stay "warning" so it never trips the error gate
    mod = tmp_path / "dead_field.py"
    mod.write_text(textwrap.dedent("""\
        def ping(link):
            link.send({"what": "df-ping", "seq": 1, "orphan": 2})


        def on_message(content):
            if content.get("what") == "df-ping":
                return content["seq"]
            return None
    """))
    findings = run_lint([str(mod)], only="HG1102")
    (hit,) = findings
    assert hit.severity == "warning"
    assert "'orphan'" in hit.message


def test_unversioned_artifact_lists_record_keys():
    findings = run_lint([str(BAD)], only="HG1103")
    (hit,) = findings
    assert hit.scope == "save_ledger"
    assert "'entries'" in hit.message and "'source'" in hit.message
    assert "schema_version/version/format" in hit.message


def test_table_drift_names_the_uncovered_type_and_root():
    findings = run_lint([str(BAD)], only="HG1104")
    (hit,) = findings
    assert hit.scope == "<module>"                       # fires at the table
    assert "`WireRefused`" in hit.message
    assert "WireErr" in hit.message                      # the family root
    assert "wire_bad.py:87" in hit.message               # class-def witness


def test_metric_drift_names_registry_and_namespace():
    findings = run_lint([str(BAD)], only="HG1105")
    (hit,) = findings
    assert "'wire.sentt'" in hit.message
    assert "`DOTTED_NAMES`" in hit.message
    assert "'wire' namespace" in hit.message


# --------------------------------------------------------- family scoping


def test_only_hg11_selects_the_family_without_aliasing():
    # "HG11" must mean HG1101–HG1105 and nothing else: the bad_pkg dir
    # holds fixtures for ten other families, none of which may leak in
    findings = run_lint([str(FIXTURES / "bad_pkg")], only="HG11")
    assert findings and all(f.rule.startswith("HG11") for f in findings)
    assert sorted({f.rule for f in findings}) == [
        "HG1101", "HG1102", "HG1103", "HG1104", "HG1105",
    ]


def test_rule_matches_is_family_aware_for_hg11():
    assert rule_matches("HG1101", "HG11")
    assert rule_matches("HG1105", "HG11")
    assert not rule_matches("HG1101", "HG1")   # HG1 is exactly the HG1xx
    # family — a four-digit family never aliases into a three-digit one
    assert not rule_matches("HG101", "HG11")
    assert rule_matches("HG1103", "HG1103")
    assert not rule_matches("HG1103", "HG1101")


def test_single_rule_scoping():
    findings = run_lint([str(BAD)], only="HG1104")
    assert _pins(findings) == [("HG1104", 91)]


# --------------------------------- HG1105 vs the runtime metric-drift gate


def test_static_registry_agrees_with_runtime_dotted_names(monkeypatch):
    """HG1105's vocabulary is the SAME set the runtime drift gate
    (tests/test_obs.py::test_serve_stats_namespace_no_drift) checks
    against: the AST evaluation of ``DOTTED_NAMES`` — including the
    ``tuple(f"..." ...)`` lane comprehension — must equal the imported
    constant, or the static and runtime gates could disagree."""
    monkeypatch.chdir(REPO)
    mods = discover_modules("hypergraphdb_tpu")
    vocab, prefixes = collect_registries(mods)

    from hypergraphdb_tpu.serve import stats
    from hypergraphdb_tpu.sub import stats as sub_stats

    assert set(vocab) == set(stats.DOTTED_NAMES) | set(
        sub_stats.DOTTED_NAMES)
    # the one dynamic family (per-endpoint breaker gauges) is governed
    # by a registered prefix rather than enumerated names
    assert "serve.breaker." in prefixes


def test_seeded_registry_drift_fires_statically(tmp_path):
    # the same drift the runtime gate would catch at test time (a site
    # emitting an unregistered name) must fire at lint time
    mod = tmp_path / "drifted.py"
    mod.write_text(textwrap.dedent("""\
        DOTTED_NAMES = ("gate.sent",)


        def bump(metrics):
            metrics.incr("gate.sent")
            metrics.incr("gate.recv")
    """))
    findings = run_lint([str(mod)], only="HG1105")
    assert _pins(findings) == [("HG1105", 6)]
    assert "'gate.recv'" in findings[0].message


# ------------------------------------------------------ zero-baseline gate


def test_repo_carries_zero_wire_findings(monkeypatch):
    """The hgwire acceptance bar: HG11xx holds a ZERO baseline on the
    real tree — every unversioned artifact got a schema stamp (pinned in
    tests/test_wire_fixes.py) and every envelope/arity/table/metric
    contract holds."""
    monkeypatch.chdir(REPO)
    findings = run_lint(["hypergraphdb_tpu"], only="HG11")
    assert findings == [], (
        "wire-contract findings must be FIXED, not baselined:\n"
        + "\n".join(f.render() for f in findings)
    )
