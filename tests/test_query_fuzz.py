"""Randomized planner differential: arbitrary condition trees evaluated by
the full compile→plan→execute pipeline must agree with a brute-force
per-atom satisfies() scan. This sweeps every planner path at once —
typed-incidence fusion, value-range fusion, stats-ordered intersections,
unions, negation-in-DNF — the property-style complement to the per-feature
suites (the reference's querying tests enumerate shapes by hand;
randomization covers the combinations they miss)."""

import numpy as np
import pytest

from hypergraphdb_tpu import HyperGraph
from hypergraphdb_tpu.query import conditions as c
from hypergraphdb_tpu.query import dsl as hg

from conftest import make_random_hypergraph


@pytest.fixture(scope="module")
def fuzz_graph():
    g = HyperGraph()
    nodes, links = make_random_hypergraph(
        g, n_nodes=120, n_links=260, max_arity=3, seed=77
    )
    # widen the value space: ints, strings, and some removals
    extra = [g.add(int(i)) for i in range(40)]
    for i in range(0, 20, 3):
        g.remove(int(extra[i]))
    yield g, nodes, links
    g.close()


def _leaf_pool(g, nodes, links, r):
    anchors = [int(nodes[i]) for i in r.integers(0, len(nodes), size=4)]
    return [
        lambda: hg.type_("int"),
        lambda: hg.type_("string"),
        lambda: hg.value(int(r.integers(0, 260)), str(r.choice(
            ["eq", "lt", "lte", "gt", "gte"]
        ))),
        lambda: hg.incident(int(r.choice(anchors))),
        lambda: hg.typed_incident(int(r.choice(anchors)), "int"),
        lambda: hg.arity(int(r.integers(1, 4)), str(r.choice(["eq", "gte"]))),
        lambda: c.IsLink(),
        lambda: c.IsNode(),
        lambda: hg.is_(int(r.choice(anchors))),
    ]


def _random_condition(g, nodes, links, r, depth=2):
    leaves = _leaf_pool(g, nodes, links, r)
    if depth == 0 or r.random() < 0.35:
        return leaves[int(r.integers(0, len(leaves)))]()
    kind = r.random()
    n = int(r.integers(2, 4))
    subs = [_random_condition(g, nodes, links, r, depth - 1) for _ in range(n)]
    if kind < 0.45:
        return hg.and_(*subs)
    if kind < 0.9:
        return hg.or_(*subs)
    # Not over a LEAF only (Not(And/Or) explodes DNF at fuzz scale)
    return hg.not_(leaves[int(r.integers(0, len(leaves)))]())


def _brute(g, cond):
    out = []
    for h in g.atoms():
        try:
            if cond.satisfies(g, int(h)):
                out.append(int(h))
        except Exception:
            pass
    return sorted(out)


@pytest.mark.parametrize("seed", range(12))
def test_random_condition_trees_match_brute_force(fuzz_graph, seed):
    g, nodes, links = fuzz_graph
    r = np.random.default_rng(1000 + seed)
    for _ in range(6):
        cond = _random_condition(g, nodes, links, r)
        got = sorted(int(h) for h in g.find_all(cond))
        want = _brute(g, cond)
        assert got == want, f"divergence on {cond!r}"


@pytest.mark.parametrize("seed", range(4))
def test_random_trees_on_device_thresholds(fuzz_graph, seed):
    """Same sweep with the device gate forced OPEN (device_min_batch=0):
    planner duality must not change answers."""
    g, nodes, links = fuzz_graph
    old = g.config.query.device_min_batch
    g.config.query.device_min_batch = 0
    try:
        r = np.random.default_rng(2000 + seed)
        for _ in range(4):
            cond = _random_condition(g, nodes, links, r)
            got = sorted(int(h) for h in g.find_all(cond))
            want = _brute(g, cond)
            assert got == want, f"divergence on {cond!r}"
    finally:
        g.config.query.device_min_batch = old
