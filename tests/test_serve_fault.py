"""Serve-plane failure paths: retries, deadline-aware backoff, breaker
degradation/recovery, collect recovery, and the fault-off overhead gate.

Deterministic throughout: manual-mode runtimes, one FakeClock shared by
the runtime and an injected fake sleeper (sleeping ADVANCES the clock),
and a FlakyExecutor whose failures are scripted — no device, no threads.
The real-device fault story runs under the chaos soak
(``tests/test_chaos.py``).
"""

from __future__ import annotations

import numpy as np
import pytest

from hypergraphdb_tpu.fault import (
    CLOSED,
    HALF_OPEN,
    OPEN,
    FaultRegistry,
    PermanentFault,
    TransientFault,
)
from hypergraphdb_tpu.serve import (
    DeadlineExceeded,
    ServeConfig,
    ServeResult,
    ServeRuntime,
)
from tests.test_serve_runtime import FakeClock, FakeExecutor


class FlakyExecutor:
    """Scripted failures: the first ``fail_launches`` device launches and
    the first ``fail_collects`` device collects raise ``error``. Honors
    ``batch.force_host`` (serves "host" results without device work) and
    implements the ``collect_host`` recovery hook."""

    def __init__(self, fail_launches=0, fail_collects=0,
                 error=TransientFault):
        self.fail_launches = fail_launches
        self.fail_collects = fail_collects
        self.error = error
        self.events: list[tuple] = []
        self.batches: list = []

    def _results(self, batch, served_by):
        return [
            (t, ServeResult(t.request.kind, 0,
                            np.empty(0, dtype=np.int64), False, 0,
                            served_by))
            for t in batch.tickets
        ]

    def launch(self, batch):
        if batch.force_host:
            self.events.append(("host", len(self.batches)))
            self.batches.append(batch)
            return ("host", batch)
        if self.fail_launches > 0:
            self.fail_launches -= 1
            self.events.append(("launch_fail",))
            raise self.error("device fell over at launch")
        self.events.append(("launch", len(self.batches)))
        self.batches.append(batch)
        return ("device", batch)

    def collect(self, token):
        kind, batch = token
        if kind == "device" and self.fail_collects > 0:
            self.fail_collects -= 1
            self.events.append(("collect_fail",))
            raise self.error("device fell over at collect")
        self.events.append(("collect", kind))
        return self._results(batch, "fake" if kind == "device" else "host")

    def collect_host(self, token):
        _, batch = token
        self.events.append(("collect_host",))
        return self._results(batch, "host")


def make_runtime(ex=None, clock=None, linger=0.0, **kw):
    clock = clock or FakeClock()
    sleeps: list[float] = []

    def sleep(dt):
        sleeps.append(dt)
        clock.advance(dt)

    kw.setdefault("retry_base_s", 0.01)
    kw.setdefault("retry_max_s", 0.08)
    cfg = ServeConfig(buckets=(4, 16), max_linger_s=linger, clock=clock,
                      manual=True, sleep=sleep, **kw)
    ex = ex if ex is not None else FlakyExecutor()
    rt = ServeRuntime(graph=None, config=cfg, executor=ex)
    return rt, ex, clock, sleeps


def assert_identity(rt):
    """The accounting identity the chaos soak enforces, with the queue
    drained: submitted == completed + shed + cancelled + errors."""
    s = rt.stats
    assert s.submitted == (
        s.completed + s.shed_deadline + s.cancelled + s.errors
    )
    assert rt.queue.depth() == 0


# --------------------------------------------------------- transient retry


def test_transient_launch_failure_retries_to_success():
    ex = FlakyExecutor(fail_launches=1)
    rt, ex, clock, sleeps = make_runtime(ex)
    fut = rt.submit_bfs(1)
    assert rt.step(drain=True)
    assert fut.result(timeout=0).served_by == "fake"
    assert rt.stats.retries == 1
    assert len(sleeps) == 1
    # first backoff: base * (1 + U[0, jitter]) with jitter 0.5
    assert 0.01 <= sleeps[0] <= 0.015
    assert ex.events[0] == ("launch_fail",)
    assert ("launch", 0) in ex.events
    assert_identity(rt)


def test_backoff_is_exponential_and_capped():
    ex = FlakyExecutor(fail_launches=3)
    rt, ex, clock, sleeps = make_runtime(ex, max_retries=5,
                                         breaker_threshold=99)
    fut = rt.submit_bfs(1)
    rt.step(drain=True)
    assert fut.result(timeout=0).served_by == "fake"
    assert len(sleeps) == 3
    base = [0.01, 0.02, 0.04]
    for dt, b in zip(sleeps, base):
        assert b <= dt <= b * 1.5


def test_retry_jitter_is_seeded_deterministic():
    def sleeps_for(seed):
        ex = FlakyExecutor(fail_launches=2)
        rt, ex, clock, sleeps = make_runtime(
            ex, retry_seed=seed, max_retries=5, breaker_threshold=99)
        rt.submit_bfs(1)
        rt.step(drain=True)
        return sleeps

    assert sleeps_for(4) == sleeps_for(4)
    assert sleeps_for(4) != sleeps_for(5)


def test_permanent_failure_surfaces_typed_without_retry():
    ex = FlakyExecutor(fail_launches=5, error=PermanentFault)
    rt, ex, clock, sleeps = make_runtime(ex)
    fut = rt.submit_bfs(1)
    rt.step(drain=True)
    with pytest.raises(PermanentFault):
        fut.result(timeout=0)
    assert sleeps == []               # permanent: no backoff was paid
    assert rt.stats.retries == 0
    assert rt.stats.errors == 1
    assert_identity(rt)


def test_retry_budget_exhausted_surfaces_transient_error():
    ex = FlakyExecutor(fail_launches=10)
    rt, ex, clock, sleeps = make_runtime(ex, max_retries=2,
                                         breaker_threshold=99)
    fut = rt.submit_bfs(1)
    rt.step(drain=True)
    with pytest.raises(TransientFault):
        fut.result(timeout=0)
    assert rt.stats.retries == 2      # 2 re-attempts, 3 launches total
    assert rt.stats.errors == 1
    assert_identity(rt)


# --------------------------------------------------------- deadline respect


def test_backoff_never_sleeps_past_the_deadline_sheds_instead():
    """Retry budget exhausted BY DEADLINE → shed, not hang: a ticket
    whose deadline falls inside the next backoff is shed immediately."""
    ex = FlakyExecutor(fail_launches=10)
    rt, ex, clock, sleeps = make_runtime(
        ex, retry_base_s=1.0, retry_max_s=2.0, max_retries=5,
        breaker_threshold=99)
    fut = rt.submit_bfs(1, deadline_s=0.5)
    rt.step(drain=True)
    with pytest.raises(DeadlineExceeded):
        fut.result(timeout=0)
    assert sleeps == []               # the 1 s backoff was never paid
    assert rt.stats.shed_deadline == 1
    assert_identity(rt)


def test_backoff_sheds_doomed_tickets_keeps_live_ones():
    ex = FlakyExecutor(fail_launches=1)
    rt, ex, clock, sleeps = make_runtime(
        ex, retry_base_s=1.0, retry_max_s=2.0, retry_jitter=0.0,
        max_retries=5, breaker_threshold=99)
    doomed = rt.submit_bfs(1, deadline_s=0.5)
    live = rt.submit_bfs(2, deadline_s=10.0)
    rt.step(drain=True)
    with pytest.raises(DeadlineExceeded):
        doomed.result(timeout=0)
    assert live.result(timeout=0).served_by == "fake"
    assert sleeps == [1.0]            # the survivor paid the backoff
    (batch,) = ex.batches
    assert [t.request.seed for t in batch.tickets] == [2]
    assert rt.stats.snapshot()["batch_occupancy"] == pytest.approx(0.25)
    assert_identity(rt)


# --------------------------------------------------------- circuit breaker


def test_breaker_trips_to_host_and_recovers_via_probe():
    """The acceptance demo: a failing device schedule degrades EVERY
    in-deadline request to exact host fallback; once the schedule clears
    and the cooldown elapses, a half-open probe restores device serving."""
    ex = FlakyExecutor(fail_launches=2)
    rt, ex, clock, sleeps = make_runtime(
        ex, breaker_threshold=2, breaker_cooldown_s=1.0, max_retries=5)
    key = ("bfs", 2)

    # batch 1: two transient failures trip the breaker; the SAME batch
    # re-routes to host — the caller sees an answer, not an error
    f1 = rt.submit_bfs(1)
    rt.step(drain=True)
    assert f1.result(timeout=0).served_by == "host"
    assert rt.breaker.state_of(key) == OPEN
    assert rt.stats.breaker_trips == 1
    assert rt.stats.snapshot()["breaker_state"] == 2

    # while OPEN: straight to host, no device attempt at all
    f2 = rt.submit_bfs(2)
    rt.step(drain=True)
    assert f2.result(timeout=0).served_by == "host"
    assert ("launch_fail",) not in ex.events[-2:]

    # cooldown elapses → half-open probe; the schedule has cleared, so
    # the probe succeeds and the gate closes: device serving resumes
    clock.advance(1.5)
    f3 = rt.submit_bfs(3)
    rt.step(drain=True)
    assert f3.result(timeout=0).served_by == "fake"
    assert rt.breaker.state_of(key) == CLOSED
    assert rt.stats.snapshot()["breaker_state"] == 0

    f4 = rt.submit_bfs(4)
    rt.step(drain=True)
    assert f4.result(timeout=0).served_by == "fake"
    # every request was answered: 100% completion through the outage
    assert rt.stats.completed == 4 and rt.stats.errors == 0
    assert_identity(rt)


def test_breaker_probe_failure_reopens_and_host_serves():
    ex = FlakyExecutor(fail_launches=10)
    rt, ex, clock, sleeps = make_runtime(
        ex, breaker_threshold=1, breaker_cooldown_s=1.0, max_retries=0)
    f1 = rt.submit_bfs(1)
    rt.step(drain=True)               # failure trips immediately → host
    assert f1.result(timeout=0).served_by == "host"
    clock.advance(1.5)
    f2 = rt.submit_bfs(2)             # probe fails → re-open → host
    rt.step(drain=True)
    assert f2.result(timeout=0).served_by == "host"
    assert rt.breaker.state_of(("bfs", 2)) == OPEN
    assert rt.stats.breaker_trips == 2
    assert rt.stats.completed == 2 and rt.stats.errors == 0
    assert_identity(rt)


def test_breaker_gates_are_per_batch_key():
    ex = FlakyExecutor(fail_launches=1)
    rt, ex, clock, sleeps = make_runtime(
        ex, breaker_threshold=1, max_retries=0)
    fb = rt.submit_bfs(1)
    rt.step(drain=True)               # trips ("bfs", 2) → host
    assert fb.result(timeout=0).served_by == "host"
    fp = rt.submit_pattern([1, 2])    # different key: still device
    rt.step(drain=True)
    assert fp.result(timeout=0).served_by == "fake"
    assert rt.breaker.state_of(("pattern", 2)) == CLOSED


# --------------------------------------------------------- collect recovery


def test_collect_failure_recovers_on_host_same_epoch():
    ex = FlakyExecutor(fail_collects=1)
    rt, ex, clock, sleeps = make_runtime(ex)
    fut = rt.submit_bfs(1)
    rt.step(drain=True)
    assert fut.result(timeout=0).served_by == "host"
    assert ("collect_host",) in ex.events
    assert rt.stats.retries == 1
    assert rt.breaker.state_of(("bfs", 2)) != OPEN  # 1 < threshold
    assert_identity(rt)


def test_collect_failure_without_hook_fails_typed_runtime_survives():
    class NoHookExecutor(FakeExecutor):
        def __init__(self):
            super().__init__()
            self.boom = True

        def collect(self, token):
            if self.boom:
                self.boom = False
                raise TransientFault("collect fell over")
            return super().collect(token)

    ex = NoHookExecutor()
    rt, ex, clock, sleeps = make_runtime(ex)
    f1 = rt.submit_bfs(1)
    rt.step(drain=True)
    with pytest.raises(TransientFault):
        f1.result(timeout=0)
    f2 = rt.submit_bfs(2)             # the runtime keeps serving
    rt.step(drain=True)
    assert f2.result(timeout=0).kind == "bfs"
    assert rt.stats.errors == 1
    assert_identity(rt)


def test_permanent_collect_failure_skips_host_recovery():
    ex = FlakyExecutor(fail_collects=1, error=PermanentFault)
    rt, ex, clock, sleeps = make_runtime(ex)
    fut = rt.submit_bfs(1)
    rt.step(drain=True)
    with pytest.raises(PermanentFault):
        fut.result(timeout=0)
    assert ("collect_host",) not in ex.events
    assert_identity(rt)


# --------------------------------------------------------- off-gate contract


def run_workload(rt, clock):
    rt.submit_bfs(1)
    rt.submit_bfs(2)
    rt.pump(drain=True)
    rt.submit_pattern([1, 2])
    rt.submit_bfs(3, max_hops=5)
    clock.advance(0.02)
    while rt.pump(drain=True):
        pass
    rt.close(drain=True)


def test_faults_off_identical_dispatch_sequence_and_no_entry(monkeypatch):
    """The overhead contract: with the fault layer DISABLED (default)
    the dispatch event order is byte-identical to the committed pipeline
    contract, and the fault registry is never entered — ``check`` is
    poisoned, so one reached call would fail the test. The only cost left
    is the ``enabled`` attribute read per site."""
    def boom(self, name, **ctx):  # pragma: no cover - must not run
        raise AssertionError(f"fault check {name!r} reached while disabled")

    monkeypatch.setattr(FaultRegistry, "check", boom)
    clock = FakeClock()
    cfg = ServeConfig(buckets=(4, 16), max_linger_s=0.010, clock=clock,
                      manual=True, faults=FaultRegistry())
    ex = FakeExecutor()
    rt = ServeRuntime(graph=None, config=cfg, executor=ex)
    assert rt.faults.enabled is False
    run_workload(rt, clock)
    assert ex.events == [
        ("launch", 0), ("launch", 1), ("collect", 0),
        ("launch", 2), ("collect", 1), ("collect", 2),
    ]
    assert rt.stats.retries == 0 and rt.stats.errors == 0
    assert_identity(rt)


def test_injected_registry_drives_the_executor_sites():
    """A private armed registry injected via ServeConfig(faults=) reaches
    the runtime's ladder: one armed transient launch fault → one retry."""
    faults = FaultRegistry().enable(seed=0)
    faults.arm("serve.launch", times=1)

    class SiteExecutor(FakeExecutor):
        """Fake executor that honors the executor-site idiom."""

        def __init__(self, faults):
            super().__init__()
            self.faults = faults

        def launch(self, batch):
            if self.faults.enabled:
                self.faults.check("serve.launch", kind=batch.key[0])
            return super().launch(batch)

    clock = FakeClock()
    sleeps = []

    def sleep(dt):
        sleeps.append(dt)
        clock.advance(dt)

    cfg = ServeConfig(buckets=(4,), max_linger_s=0.0, clock=clock,
                      manual=True, faults=faults, sleep=sleep,
                      retry_base_s=0.001)
    rt = ServeRuntime(graph=None, config=cfg,
                      executor=SiteExecutor(faults))
    fut = rt.submit_bfs(1)
    rt.step(drain=True)
    assert fut.result(timeout=0).kind == "bfs"
    assert rt.stats.retries == 1
    assert faults.fired("serve.launch") == 1
    assert faults.journal == [("serve.launch", 1)]
