"""Benchmark: BASELINE configs 2-4 on real hardware, honest baselines.

Prints ONE JSON line. Headline metric = config 4 (3-hop, 4096-seed BFS over
the 10M-atom DBpedia-shaped hypergraph) in edges/s; ``vs_baseline`` compares
against the **vectorized numpy host engine** on the same CSR arrays — the
honest single-core "CPU database" stand-in (VERDICT r1 #2), NOT a per-atom
Python loop. The full per-config table rides in the same JSON object:

- ``c2_bfs_2hop_120k``  — WordNet-scale (BASELINE config 2), built through
  the full graph API, packed-BFS device kernel vs vectorized host BFS.
  ``vs_python_engine`` additionally records the ratio against the
  pointer-chasing per-atom engine (the reference's actual access pattern,
  ``HGBreadthFirstTraversal.java:49-66``) for context.
- ``c3_pattern_10m``    — And(type, incident, incident) conjunctive match,
  1024 queries over 10M atoms (config 3), degree-bucketed device kernel vs
  vectorized numpy intersect1d host engine.
- ``c4_bfs_3hop_10m``   — 4096-seed 3-hop BFS over 10M atoms / ~50M arity
  (config 4): pull-mode visited-transposed kernel (``ops/ellbfs.py``) with
  the Pallas row-gather (``ops/pallas_gather.py``) on 512-byte rows; reports
  bytes/s against the v5e HBM peak (819 GB/s) so single-chip efficiency is
  assessable. Reps adapt to a time budget so the bench always terminates.

Scale knobs: BENCH_ENTITIES / BENCH_LINKS / BENCH_SEEDS env vars (defaults
reproduce the 10M-atom configs).

Telemetry: ``python bench.py --telemetry [dir]`` enables hgobs tracing in
every config subprocess and dumps ``telemetry_<config>.prom`` +
``telemetry_<config>.trace.jsonl`` next to the results (see README
"Observability"). ``c6_serving`` always records its batched-vs-unbatched
ratio, occupancy, and percentiles to ``BENCH_C6_<tag>.json``
(``BENCH_C6_TAG``, default ``local``) — the ROADMAP asks for this number
to be recorded, not just printed.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

import numpy as np

V5E_HBM_PEAK = 819e9  # bytes/s, v5e per-chip HBM bandwidth

#: set by --telemetry (inherited by config subprocesses via env)
TELEMETRY_ENV = "BENCH_TELEMETRY_DIR"


def _telemetry_dir():
    return os.environ.get(TELEMETRY_ENV) or None


def _telemetry_begin() -> None:
    """Enable process-wide hgobs tracing when --telemetry is active. The
    process registry and trace buffer are RESET here so each config's
    dump reports only its own run — on the default isolated path the
    reset is a no-op (fresh subprocess); on BENCH_ISOLATE=0 it is what
    keeps telemetry_c4.prom from accumulating c3's counters.

    ``BENCH_TRACE_SAMPLE`` (a rate in [0, 1], default 1.0) sets the
    head sample rate for the run — how BENCH_C6 exercises the 1%-
    sampling production posture; errors/sheds stay always-sampled."""
    if _telemetry_dir():
        from hypergraphdb_tpu import obs
        from hypergraphdb_tpu.utils.metrics import global_metrics

        # registry-level reset: the facade's reset() covers only its own
        # memoized instruments, but anything registered directly on the
        # default registry must be cleared too
        global_metrics.registry.reset()
        tracer = obs.enable()
        tracer.drain()
        rate = os.environ.get("BENCH_TRACE_SAMPLE")
        if rate is not None:
            tracer.default_sample_rate = min(1.0, max(0.0, float(rate)))


def _telemetry_dump(name: str, registries=()) -> dict:
    """Write the registry + trace dumps for one config; no-op without
    --telemetry. Returns the paths plus the tracer's sampling/buffer
    counters (``sampling``) — the record of whether the finished-trace
    buffer ever saturated under this config's load."""
    out_dir = _telemetry_dir()
    if not out_dir:
        return {}
    from hypergraphdb_tpu import obs
    from hypergraphdb_tpu.utils.metrics import global_metrics

    regs = list(registries) + [global_metrics.registry]
    sampling = obs.tracer().sampling_snapshot()  # BEFORE drain empties it
    paths = obs.write_telemetry(
        os.path.join(out_dir, f"telemetry_{name}"),
        registries=regs, tracer=obs.tracer(),
    )
    return {"prometheus": paths["prometheus"], "traces": paths["traces"],
            "sampling": sampling}


def _compile_cache_dir() -> str:
    """THE resolution of the persistent XLA compile-cache path — used by
    both the jax config below and the cache-hit detector, so the two can
    never drift onto different directories."""
    return os.environ.get(
        "JAX_COMPILE_CACHE",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_cache"),
    )


def _enable_compile_cache() -> None:
    """Persistent XLA compilation cache: first-ever compile of the 10M-scale
    kernels costs minutes over the axon tunnel; every later bench run reuses
    the cached executables."""
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", _compile_cache_dir())
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass


def _bench_entry_env() -> None:
    """Bench ENTRY-point environment, called from ``main()`` and the
    per-config wrappers (the isolated-subprocess entries) — deliberately
    NOT at import time: importing bench as a library (the envelope/diff
    tests, ``--diff``, tooling) must not flip process-global jax config
    or seed cache env vars that every later ServeRuntime in the same
    process would silently open (a leaked ``HG_AOT_CACHE`` once handed
    stale sharded executables to an unrelated test's runtime).

    - persistent XLA compile cache (minutes of 10M-scale compiles);
    - pull-BFS plan pyramids keyed by snapshot content: warm bench runs
      skip the ~15 s 10M-scale host plan build (VERDICT r4 weak #2);
    - serving AOT executables (ops/aot_cache): ServeRuntime prewarm +
      the c6 cold-start probe read this root."""
    _enable_compile_cache()
    here = os.path.dirname(os.path.abspath(__file__))
    os.environ.setdefault("HG_PLAN_CACHE", os.path.join(here,
                                                        ".plan_cache"))
    os.environ.setdefault("HG_AOT_CACHE", os.path.join(here, ".aot_cache"))


def _xla_cache_files() -> int:
    """Entries in the persistent XLA compile cache — the honest (if
    coarse) cache-hit signal: a config whose warmup persisted NO new
    executable into a non-empty cache compiled nothing substantial."""
    try:
        return len(os.listdir(_compile_cache_dir()))
    except OSError:
        return 0


def _timed_warmup(fn) -> dict:
    """Run one config's compile/warmup phase, recording ``compile_s``
    (wall — includes trace+compile or cache load) and ``cache_hit``
    (no new persistent-cache entries were written and the cache was
    already populated). The ISSUE-8 trajectory fields."""
    files0 = _xla_cache_files()
    t0 = time.perf_counter()
    fn()
    dt = time.perf_counter() - t0
    return {
        "compile_s": round(dt, 3),
        "cache_hit": bool(_xla_cache_files() == files0 and files0 > 0),
    }


# ---------------------------------------------------------------- host engines


def gather_ragged(flat, starts, lens):
    """Vectorized ragged-row gather: concatenation of flat[s:s+l] rows."""
    total = int(lens.sum())
    if total == 0:
        return np.empty(0, dtype=flat.dtype)
    idx = np.repeat(
        starts - np.concatenate([[0], np.cumsum(lens)[:-1]]), lens
    ) + np.arange(total)
    return flat[idx]


def host_bfs_vectorized(snap, seeds, max_hops):
    """The honest CPU baseline: frontier BFS with numpy CSR ops (vectorized
    gather + unique per hop), one seed at a time — what a well-written
    single-core columnar engine does. Returns (edges_per_sec, edges)."""
    inc_off = snap.inc_offsets.astype(np.int64)
    inc = snap.inc_links
    tgt_off = snap.tgt_offsets.astype(np.int64)
    tgt = snap.tgt_flat
    edges = 0
    t0 = time.perf_counter()
    for s in seeds:
        visited = np.zeros(snap.num_atoms + 1, dtype=bool)
        visited[s] = True
        frontier = np.asarray([s], dtype=np.int64)
        for _ in range(max_hops):
            starts, lens = inc_off[frontier], (
                inc_off[frontier + 1] - inc_off[frontier]
            )
            edges += int(lens.sum())
            links = np.unique(gather_ragged(inc, starts, lens))
            ts = gather_ragged(
                tgt, tgt_off[links], tgt_off[links + 1] - tgt_off[links]
            )
            nxt = np.unique(ts.astype(np.int64))
            nxt = nxt[~visited[nxt]]
            visited[nxt] = True
            frontier = nxt
            if not len(frontier):
                break
    dt = time.perf_counter() - t0
    return edges / dt if dt else 0.0, edges


def host_bfs_python(g, seeds, max_hops):
    """The reference-shaped pointer-chasing engine (per-atom incidence fetch,
    per-link target iteration) — reported for context only."""
    t0 = time.perf_counter()
    edges = 0
    for s in seeds:
        visited = {s}
        frontier = [s]
        for _ in range(max_hops):
            nxt = []
            for a in frontier:
                inc = g.get_incidence_set(a).array()
                edges += len(inc)
                for lk in inc.tolist():
                    for t in g.get_targets(lk):
                        t = int(t)
                        if t not in visited:
                            visited.add(t)
                            nxt.append(t)
            frontier = nxt
    dt = time.perf_counter() - t0
    return edges / dt if dt else 0.0, edges


def host_value_pattern_vectorized(snap, queries, lo, hi):
    """Vectorized numpy host engine for And(incident(a), incident(b),
    value_rank in [lo, hi)): sorted intersection + rank-window filter per
    query — the same job as the device value-pushdown kernel. Returns q/s."""
    inc_off = snap.inc_offsets.astype(np.int64)
    inc = snap.inc_links
    rank = snap.value_rank
    t0 = time.perf_counter()
    for a, b in queries:
        ra = inc[inc_off[a] : inc_off[a + 1]]
        rb = inc[inc_off[b] : inc_off[b + 1]]
        common = np.intersect1d(ra, rb, assume_unique=True)
        r = rank[common]
        _ = common[(r >= lo) & (r < hi)]
    dt = time.perf_counter() - t0
    return len(queries) / dt if dt else 0.0


def best_of(fn, n=2):
    """Run ``fn`` n times, keep the FASTEST result (highest first element
    if a tuple, else highest value). This host and its chip tunnel are
    shared: single timing windows swing 2-4× run to run with ambient
    contention, so every throughput — device AND host baseline alike, for
    symmetry — reports best-of-n."""
    best = None
    best_key = None
    for _ in range(n):
        r = fn()
        key = r[0] if isinstance(r, tuple) else r
        if best_key is None or key > best_key:
            best, best_key = r, key
    return best


def host_pattern_vectorized(snap, queries, type_handle):
    """Vectorized numpy host engine for And(type, incident(a), incident(b)):
    sorted-array intersection + type filter per query. Returns queries/s."""
    inc_off = snap.inc_offsets.astype(np.int64)
    inc = snap.inc_links
    type_of = snap.type_of
    t0 = time.perf_counter()
    for a, b in queries:
        ra = inc[inc_off[a] : inc_off[a + 1]]
        rb = inc[inc_off[b] : inc_off[b + 1]]
        common = np.intersect1d(ra, rb, assume_unique=True)
        _ = common[type_of[common] == type_handle]
    dt = time.perf_counter() - t0
    return len(queries) / dt if dt else 0.0


# ---------------------------------------------------------------- configs


def bench_c2():
    import jax.numpy as jnp

    from hypergraphdb_tpu import HyperGraph
    from hypergraphdb_tpu.models import zipf_hypergraph
    from hypergraphdb_tpu.ops.bitfrontier import bfs_packed_block
    from hypergraphdb_tpu.ops.snapshot import CSRSnapshot

    g = HyperGraph()
    nodes, _ = zipf_hypergraph(
        g, n_nodes=80_000, n_links=40_000, max_arity=5, seed=7
    )
    snap = CSRSnapshot.pack(g)
    dev = snap.device

    K, HOPS = 1024, 2
    r = np.random.default_rng(123)
    seeds = (
        r.choice(len(nodes), size=K, replace=False) + int(nodes[0])
    ).astype(np.int32)
    seeds_dev = jnp.asarray(seeds)

    import jax

    chunk = int(os.environ.get("BENCH_EDGE_CHUNK", 1 << 17))
    compile_info = _timed_warmup(lambda: jax.block_until_ready(
        bfs_packed_block(dev, seeds_dev, HOPS, edge_chunk=chunk)
    ))
    rep_times = []
    for _ in range(3):
        t0 = time.perf_counter()
        res = bfs_packed_block(dev, seeds_dev, HOPS, edge_chunk=chunk)
        jax.block_until_ready(res)
        rep_times.append(time.perf_counter() - t0)
    dt = min(rep_times)  # best-of: see best_of()
    edges = int(np.asarray(res.edges_touched, dtype=np.int64).sum())
    device_eps = edges / dt

    host_eps, _ = best_of(
        lambda: host_bfs_vectorized(snap, seeds[:64].tolist(), HOPS)
    )
    py_eps, _ = best_of(lambda: host_bfs_python(g, seeds[:16].tolist(), HOPS))
    telemetry = _telemetry_dump("c2", registries=[g.metrics.registry])
    g.close()
    out = {
        "edges_per_sec": round(device_eps, 1),
        "vs_vectorized_host": round(device_eps / host_eps, 2) if host_eps else None,
        "vs_python_engine": round(device_eps / py_eps, 2) if py_eps else None,
        "edges_per_run": edges,
        "device_ms": round(dt * 1e3, 3),
        **compile_info,
    }
    if telemetry:
        out["telemetry"] = telemetry
    return out


def _build_10m():
    from hypergraphdb_tpu.models import dbpedia_snapshot

    n_entities = int(os.environ.get("BENCH_ENTITIES", 2_000_000))
    n_links = int(os.environ.get("BENCH_LINKS", 8_000_000))
    t0 = time.perf_counter()
    snap, info = dbpedia_snapshot(n_entities=n_entities, n_links=n_links)
    build_s = time.perf_counter() - t0
    return snap, info, build_s


def bench_c3(snap, info):
    import jax

    from hypergraphdb_tpu.ops.setops import (
        collect_pattern,
        execute_pattern,
        plan_pattern,
    )

    r = np.random.default_rng(42)
    K = int(os.environ.get("BENCH_SEEDS", 1024))
    # anchor pairs that co-occur in a link of the most common property type
    # → non-trivial intersections that actually pass the type filter
    th = int(max(
        info["property_types"], key=lambda t: len(snap.type_set(t))
    ))
    cands = snap.type_set(th)
    links = cands[r.integers(0, len(cands), size=K)].astype(np.int64)
    starts = snap.tgt_offsets[links].astype(np.int64)
    a = snap.tgt_flat[starts].astype(np.int64)
    b = snap.tgt_flat[starts + 1].astype(np.int64)
    pairs = np.stack([a, b], axis=1).astype(np.int32)

    # plan once (compile + anchor staging — the HGQuery.make analogue).
    # MEASUREMENT ORDER IS LOAD-BEARING: the first bulk device_get through
    # the axon tunnel degrades this process's launch path ~100× for good
    # (measured: the identical exec window runs at 10.1M q/s before any
    # download and 109K q/s after one serving window), so the DOWNLOADLESS
    # execution-mode windows run first, then the serving windows (which
    # measure the tunnel as much as the engine), then result collection
    # and host baselines.
    plan = plan_pattern(snap, pairs, th)
    reps = int(os.environ.get("BENCH_C3_REPS", 64))
    compile_info = _timed_warmup(lambda: jax.block_until_ready([
        x for _, c_, f in execute_pattern(plan, top_r=4) for x in (c_, f)
    ]))  # warmup, no download

    # execution mode: results stay in HBM (what the chip sustains when the
    # host link is not the bottleneck)
    def exec_window():
        t0 = time.perf_counter()
        last = None
        for _ in range(reps):
            last = execute_pattern(plan, top_r=4)
        jax.block_until_ready([x for _, c_, f in last for x in (c_, f)])
        return K / ((time.perf_counter() - t0) / reps)

    exec_qps = best_of(exec_window, n=3)

    # value-predicate pushdown leg (VERDICT r2 item 3): the SAME anchor
    # pairs constrained by property rank in [16, 48) — the device rank
    # window rides the plan's bucketing, vs the host doing intersection +
    # rank filter
    import jax.numpy as jnp

    from hypergraphdb_tpu.ops.setops import (
        ell_targets,
        incident_value_range,
    )

    ell = ell_targets(snap)
    lo, hi = 16, 48

    def value_exec():
        # [16, 48) == gte lo AND lt hi, fused: ONE launch per bucket does
        # the membership pass once and compares both bounds (the r4 form
        # paid two full incident_value_pattern passes per window — exactly
        # the 2× VERDICT item 4 pointed at)
        outs = []
        for _, anchors_dev, pad in plan.buckets:
            _, _, _, counts = incident_value_range(
                snap.device, ell, anchors_dev, pad,
                jnp.uint8(0),
                jnp.uint32(0), jnp.uint32(lo),
                jnp.uint32(0), jnp.uint32(hi),
                "gte", "lt", True, None,
            )
            outs.append(counts)  # per-query counts
        return outs

    jax.block_until_ready(value_exec())  # warmup, no download
    vreps = reps

    def value_exec_window():
        t0 = time.perf_counter()
        last = None
        for _ in range(vreps):
            last = value_exec()
        jax.block_until_ready(last)
        return K / ((time.perf_counter() - t0) / vreps)

    value_exec_qps = best_of(value_exec_window, n=3)

    # serving mode: per-rep result download (counts + top-4 matches, which
    # covers every real result set in this workload). These windows pay
    # the host link — on tunneled hardware that IS the bottleneck, which
    # is the point of reporting them separately from exec mode.
    def serving_window():
        t0 = time.perf_counter()
        all_pending = [execute_pattern(plan, top_r=4) for _ in range(reps)]
        jax.device_get([(c_, f) for p in all_pending for _, c_, f in p])
        return K / ((time.perf_counter() - t0) / reps)

    device_qps = best_of(serving_window, n=3)

    def value_window():
        t0 = time.perf_counter()
        pend = [value_exec() for _ in range(vreps)]
        jax.device_get(pend)
        return K / ((time.perf_counter() - t0) / vreps)

    value_qps = best_of(value_window, n=3)

    # result collection (downloads) + host baselines LAST
    out = collect_pattern(plan, execute_pattern(plan))
    host_n = min(256, K)
    host_qps = best_of(lambda: host_pattern_vectorized(
        snap, pairs[:host_n].tolist(), th
    ))
    host_value_qps = best_of(lambda: host_value_pattern_vectorized(
        snap, pairs[:host_n].tolist(), lo, hi
    ))

    return {
        "queries_per_sec": round(device_qps, 1),
        "vs_vectorized_host": round(device_qps / host_qps, 2) if host_qps else None,
        "exec_queries_per_sec": round(exec_qps, 1),
        "exec_vs_vectorized_host": (
            round(exec_qps / host_qps, 2) if host_qps else None
        ),
        "n_queries": K,
        "nonempty_results": int(sum(len(o) > 0 for o in out)),
        "device_ms_per_batch": round(K / device_qps * 1e3, 2),
        "pipelined_reps": reps,
        "value_queries_per_sec": round(value_qps, 1),
        "value_vs_vectorized_host": (
            round(value_qps / host_value_qps, 2) if host_value_qps else None
        ),
        "value_exec_queries_per_sec": round(value_exec_qps, 1),
        "value_exec_vs_vectorized_host": (
            round(value_exec_qps / host_value_qps, 2)
            if host_value_qps else None
        ),
        **compile_info,
    }


def pull_bytes_per_run(plans, K, hops):
    """HBM traffic model for the pull kernel, counting the K axis honestly
    (VERDICT r2 Weak #4): every gathered row is Kw uint32 words, every
    reduction level reads its int32 index array plus one row per index and
    writes one row per w indices, the out_map stage re-gathers n_pad rows,
    and the frontier/visited updates + degree bit-dot stream the (n_pad, Kw)
    state a few times per hop."""
    kw_bytes = (K // 32) * 4
    per_hop = 0
    for stage_levels, widths in (
        (plans.stage1.levels, plans.stage1.widths),
        (plans.stage2_levels, plans.stage2_widths),
    ):
        for lvl, w in zip(stage_levels, widths):
            n = len(lvl)
            per_hop += n * 4            # index reads
            per_hop += n * kw_bytes     # row gathers
            per_hop += (n // w) * kw_bytes  # chunk writes
    n_pad = plans.n_pad
    # visited-pull update: out_map read + reach gather + visited rd/wr
    per_hop += n_pad * (4 + kw_bytes * 3)
    per_hop += n_pad * (kw_bytes + 4)       # _bitdot degree pass (S_h)
    return per_hop * hops


def bench_c4(snap, info, budget_s=240.0):
    import jax

    from hypergraphdb_tpu.ops.ellbfs import bfs_pull, plans_for

    # 4096 seeds per block = 512-byte visited rows: the chip's row-gather
    # descriptor rate (~30M/s) is width-independent, so wider rows move 4×
    # the bytes and serve 4× the seeds per descriptor (and enable the
    # Pallas gather path, 128-lane rows). Fits v5e HBM at 10M atoms only
    # with the staged hop in ops/ellbfs.py.
    K = int(os.environ.get("BENCH_C4_SEEDS", 4096))
    HOPS = 3
    k_block = -(-int(os.environ.get("BENCH_K_BLOCK", K)) // 32) * 32
    chunk = int(os.environ.get("BENCH_PULL_CHUNK", 1 << 16))
    r = np.random.default_rng(7)
    e0, eN = info["entities"]
    seeds = r.integers(e0, eN, size=K).astype(np.int32)

    n_dev = len(jax.devices())
    t0 = time.perf_counter()
    plans = plans_for(snap)  # host index-pyramid build, reused across runs
    plan_s = time.perf_counter() - t0

    def run_once():
        res = bfs_pull(snap, seeds, HOPS, chunk=chunk, k_block=k_block)
        jax.block_until_ready(res.visited_t)
        return int(np.asarray(res.edges_touched).sum())

    compile_info = _timed_warmup(run_once)  # warmup/compile
    # adaptive reps: stay inside the time budget (r3's fixed 3-rep loop on a
    # 324 s/run kernel is what timed the whole bench out); best single rep
    # is reported (see best_of())
    deadline = time.perf_counter() + budget_s
    reps, rep_times = 0, []
    while reps < 3 and (reps == 0 or time.perf_counter() < deadline):
        t0 = time.perf_counter()
        edges = run_once()
        rep_times.append(time.perf_counter() - t0)
        reps += 1
    dt = min(rep_times)
    device_eps = edges / dt

    # charge each block its REAL width (the kernel's own layout rule) and
    # its REAL path: a block the fused megakernel served moves only the
    # gathered rows + one visited read/write per hop (ops/pallas_bfs
    # traffic model — no stage buffers, no out_map re-gather), so fused
    # and staged runs stay comparable on the same honest basis
    from hypergraphdb_tpu.ops import pallas_bfs as _pbfs
    from hypergraphdb_tpu.ops.ellbfs import block_layout

    widths = block_layout(K, k_block)
    fused_w = {w: _pbfs.fused_ready(snap, w) for w in set(widths)}

    def bytes_for(w: int) -> int:
        if fused_w[w]:
            return _pbfs.fused_bytes_per_hop(
                _pbfs.fused_plans_for(snap).geom, w
            ) * HOPS
        return pull_bytes_per_run(plans, w, HOPS)

    gbps = sum(bytes_for(w) for w in widths) / dt / 1e9

    host_n = min(8, K)
    host_eps, _ = best_of(
        lambda: host_bfs_vectorized(snap, seeds[:host_n].tolist(), HOPS)
    )

    return {
        "edges_per_sec": round(device_eps, 1),
        "vs_vectorized_host": round(device_eps / host_eps, 2) if host_eps else None,
        "effective_GBps": round(gbps, 2),
        "hbm_peak_frac": round(gbps * 1e9 / V5E_HBM_PEAK, 4),
        "edges_per_run": edges,
        "device_s": round(dt, 3),
        "plan_build_s": round(plan_s, 1),
        "fused_path": bool(any(fused_w.values())),
        "reps": reps,
        "n_devices": n_dev,
        **compile_info,
    }


def bench_c5():
    """BASELINE config 5: streaming ingest through the REAL store path
    (core/bulkload — not array synthesis) with CONCURRENT device traversal
    over the incremental (base, delta) pair. Reports ingest atoms/s while
    queries run, query batches/s, staleness (delta edges pending at query
    time), and proof of freshness (every probe batch must see a link added
    after the base pack)."""
    import threading

    import jax
    import jax.numpy as jnp

    from hypergraphdb_tpu import HyperGraph
    from hypergraphdb_tpu.ops.incremental import bfs_levels_delta

    n_entities = int(os.environ.get("BENCH_C5_ENTITIES", 200_000))
    n_links = int(os.environ.get("BENCH_C5_LINKS", 400_000))
    # 40 batches ≈ 34s of sustained ingest: long enough for ≥2 LIVE
    # compactions (each ~13s of background assembly) to complete inside
    # the timed window
    stream_batches = int(os.environ.get("BENCH_C5_BATCHES", 40))
    batch_links = int(os.environ.get("BENCH_C5_BATCH_LINKS", 10_000))

    g = HyperGraph()
    r = np.random.default_rng(11)
    t0 = time.perf_counter()
    entities = g.bulk_import(values=np.arange(n_entities).tolist())
    e0 = int(entities[0])
    for s in range(0, n_links, 100_000):
        m = min(100_000, n_links - s)
        subj = r.integers(0, n_entities, size=m)
        obj = r.integers(0, n_entities, size=m)
        g.bulk_import(
            values=[int(x) for x in range(s, s + m)],
            target_lists=[[e0 + int(a), e0 + int(b)]
                          for a, b in zip(subj, obj)],
        )
    build_s = time.perf_counter() - t0
    base_atoms = n_entities + n_links

    # compact_ratio sized so the stream crosses the threshold repeatedly:
    # ≥2 LIVE compactions must fire inside the timed window (VERDICT r4
    # item 5 — r4's stream never crossed 0.5×base, so "incremental re-pack
    # under load" was demonstrated only at toy scale in tests).
    # pack_pad_multiple 1<<19 keeps base device shapes identical across
    # MOST swaps (cached executable reuse); when the growing capacity
    # crosses a 512K bucket boundary mid-run — it does once at these
    # stream sizes — that swap pays one XLA recompile, and the reported
    # query_latency_ms_over_swap_max deliberately INCLUDES it: that is the
    # real worst-case serving cost of a base swap. (A coarser multiple
    # would avoid it but at 1<<21 the dense per-seed state overflowed the
    # 16 GB chip.)
    mgr = g.enable_incremental(
        headroom=1.8, background=True, delta_bucket_min=1 << 18,
        compact_ratio=float(os.environ.get("BENCH_C5_COMPACT_RATIO", "0.1")),
        pack_pad_multiple=1 << 19,
    )
    base_version = mgr.base.version
    compactions_at_start = mgr.compactions

    ingested = {"atoms": 0, "done": False, "s": 0.0}

    def writer():
        t0 = time.perf_counter()
        for b in range(stream_batches):
            subj = r.integers(0, n_entities, size=batch_links)
            obj = r.integers(0, n_entities, size=batch_links)
            g.bulk_import(
                values=[int(x) for x in range(batch_links)],
                target_lists=[[e0 + int(a), e0 + int(b)]
                              for a, b in zip(subj, obj)],
            )
            ingested["atoms"] += batch_links
        ingested["s"] = time.perf_counter() - t0
        ingested["done"] = True

    K, HOPS = 256, 2
    seeds = (e0 + r.integers(0, n_entities, size=K)).astype(np.int32)
    # warmup compile (kernel AND the scalar probe ops) before the clock
    dev, delta = mgr.device()
    _, vis_w = bfs_levels_delta(
        dev, delta, jnp.asarray(seeds), HOPS, with_levels=False
    )
    bool(jnp.take(vis_w[0], jnp.int32(0)))

    staleness = []
    fresh_seen = 0
    fresh_probes = 0
    qbatches = 0
    latencies: list[float] = []   # per-batch query wall (read path only)
    epochs: list[int] = []        # compaction epoch each batch ran under
    wt = threading.Thread(target=writer)
    t0 = time.perf_counter()
    wt.start()
    while not ingested["done"]:
        staleness.append(mgr.delta_edges)
        tq = time.perf_counter()
        dev, delta = mgr.device(max_lag_edges=batch_links)
        # freshness probe: seed the batch at one endpoint of a link added
        # AFTER the base pack; the other endpoint must come back visited —
        # i.e. the traversal really flows through the delta overlay. Probe
        # only atoms whose edges are inside the bounded-lag upload window
        # (newer ones are legitimately not device-visible yet).
        probe_target = None
        for h in mgr.device_visible_new_atoms():
            rec = g.store.get_link(h)
            if rec is not None and len(rec) >= 5:
                a, b = int(rec[3]), int(rec[4])
                if a != b and a < dev.num_atoms and b < dev.num_atoms:
                    seeds[0] = a
                    probe_target = b
                    break
        _, visited = bfs_levels_delta(
            dev, delta, jnp.asarray(seeds), HOPS, with_levels=False
        )
        # scalar download only — shipping the whole visited bitmap off the
        # device every batch would measure the transfer link, not the DB.
        # NB: the index must be a DEVICE value: a varying python int would
        # bake into the executable and recompile every batch
        hit = bool(jnp.take(visited[0], jnp.int32(probe_target or 0)))
        latencies.append(time.perf_counter() - tq)
        epochs.append(mgr.compactions)
        qbatches += 1
        if probe_target is not None:
            fresh_probes += 1
            if hit:
                fresh_seen += 1
    wt.join()
    wall = time.perf_counter() - t0
    compactions = mgr.compactions
    final_version = mgr.base.version
    # latency percentiles + the batches that STRADDLED a base swap (the
    # epoch moved between consecutive batches): proof queries keep flowing
    # through compactions, and at what cost
    lat_ms = np.asarray(latencies) * 1e3
    swap_idx = [i for i in range(1, len(epochs)) if epochs[i] != epochs[i - 1]]
    comp_stats = mgr.compaction_stats[1:]  # entry 0 is the init pack
    telemetry = _telemetry_dump("c5", registries=[g.metrics.registry])
    g.close()

    out = {
        "base_atoms": base_atoms,
        "build_through_store_s": round(build_s, 1),
        "build_atoms_per_sec": round(base_atoms / build_s, 1),
        "concurrent_ingest_atoms_per_sec": round(
            ingested["atoms"] / ingested["s"], 1
        ) if ingested["s"] else None,
        "query_batches_per_sec": round(qbatches / wall, 2),
        "query_K": K,
        "hops": HOPS,
        "staleness_delta_edges_mean": int(np.mean(staleness)) if staleness else 0,
        "staleness_delta_edges_max": int(np.max(staleness)) if staleness else 0,
        "fresh_probes_passed": fresh_seen,
        "fresh_probes": fresh_probes,
        "query_batches": qbatches,
        "compactions": compactions,
        "live_compactions": compactions - compactions_at_start,
        "base_advanced": final_version > base_version,
        "query_latency_ms_p50": round(float(np.percentile(lat_ms, 50)), 2)
        if len(lat_ms) else None,
        "query_latency_ms_p95": round(float(np.percentile(lat_ms, 95)), 2)
        if len(lat_ms) else None,
        "query_latency_ms_p99": round(float(np.percentile(lat_ms, 99)), 2)
        if len(lat_ms) else None,
        "swap_crossings": len(swap_idx),
        "query_latency_ms_over_swap_max": round(
            float(max(lat_ms[i] for i in swap_idx)), 2
        ) if swap_idx else None,
        "compaction_wall_s_mean": round(
            float(np.mean([c["total_s"] for c in comp_stats])), 2
        ) if comp_stats else None,
        "compaction_wall_s_max": round(
            float(np.max([c["total_s"] for c in comp_stats])), 2
        ) if comp_stats else None,
        "compaction_extract_s_max": round(
            float(np.max([c["extract_s"] for c in comp_stats])), 3
        ) if comp_stats else None,
    }
    if telemetry:
        out["telemetry"] = telemetry
    return out


#: sentinel: bench_c6() runs the cold-start probe itself unless main()'s
#: legacy in-process path already ran it before any config touched the
#: device
_PROBE = object()


def bench_c6(cold=_PROBE):
    """Serving runtime under open-loop load: Poisson arrivals against
    ``serve.ServeRuntime`` (micro-batched BFS dispatches over the
    incremental pair) while ingest runs concurrently — the c5 workload
    re-entered through the SERVICE front door instead of caller-owned
    one-shot dispatches. Open-loop means arrival times are drawn from the
    offered rate, NOT paced by completions, so queueing delay is measured
    honestly (a closed loop would hide it). Reports served throughput,
    batch occupancy, shed counts, and latency percentiles, plus a
    one-request-per-dispatch baseline at the SAME offered load — the
    number the ≥5× batched-serving claim is judged against."""
    _bench_entry_env()
    import threading

    from hypergraphdb_tpu import HyperGraph
    from hypergraphdb_tpu.serve import DeadlineExceeded, ServeConfig, \
        ServeRuntime

    # cold-start probe FIRST, before this process touches the device: the
    # probe's fresh subprocesses must each own the (single-client) TPU
    # for their lifetime — after the parent initializes jax they could
    # not, and the acceptance field would silently vanish exactly on the
    # hardware it exists to measure. main()'s legacy BENCH_ISOLATE=0 path
    # passes a pre-run result instead (there, c2-c5 run in-process first)
    if cold is _PROBE:
        cold = _cold_start_probe()
    _telemetry_begin()
    n_entities = int(os.environ.get("BENCH_C6_ENTITIES", 200_000))
    n_links = int(os.environ.get("BENCH_C6_LINKS", 400_000))
    n_requests = int(os.environ.get("BENCH_C6_REQUESTS", 4096))
    offered_qps = float(os.environ.get("BENCH_C6_OFFERED_QPS", 2000.0))
    deadline_s = float(os.environ.get("BENCH_C6_DEADLINE_S", 1.0))
    hops = int(os.environ.get("BENCH_C6_HOPS", 2))
    stream_batches = int(os.environ.get("BENCH_C6_INGEST_BATCHES", 20))
    batch_links = int(os.environ.get("BENCH_C6_BATCH_LINKS", 10_000))

    g = HyperGraph()
    r = np.random.default_rng(17)
    entities = g.bulk_import(values=np.arange(n_entities).tolist())
    e0 = int(entities[0])
    for s in range(0, n_links, 100_000):
        m = min(100_000, n_links - s)
        subj = r.integers(0, n_entities, size=m)
        obj = r.integers(0, n_entities, size=m)
        g.bulk_import(
            values=[int(x) for x in range(s, s + m)],
            target_lists=[[e0 + int(a), e0 + int(b)]
                          for a, b in zip(subj, obj)],
        )
    g.enable_incremental(
        headroom=1.8, background=True, delta_bucket_min=1 << 18,
        compact_ratio=0.25,
        # shape-stable swaps at streaming scale; reduced-scale CPU smoke
        # runs shrink it so the padded capacity tracks the real graph
        pack_pad_multiple=int(os.environ.get("BENCH_C6_PAD", 1 << 19)),
    )

    cfg = ServeConfig(
        buckets=(64, 256, 1024),
        max_queue=int(os.environ.get("BENCH_C6_QUEUE", 8192)),
        max_linger_s=float(os.environ.get("BENCH_C6_LINGER_S", 0.005)),
        max_lag_edges=batch_links,
        top_r=16,
    )
    seeds = (e0 + r.integers(0, n_entities, size=n_requests)).astype(np.int64)

    # -- baseline: the SAME requests, one device dispatch each (K=1
    # bucket through the identical runtime machinery) — what every caller
    # paid before the serving tier existed. Run FIRST on a quiet graph so
    # the baseline is not handicapped by ingest.
    base_n = min(int(os.environ.get("BENCH_C6_BASELINE_N", 256)), n_requests)
    rt1 = ServeRuntime(g, ServeConfig(buckets=(1,), max_linger_s=0.0,
                                      max_lag_edges=batch_links, top_r=16))
    rt1.submit_bfs(int(seeds[0]), max_hops=hops).result(timeout=120)  # warm
    t0 = time.perf_counter()
    futs = [rt1.submit_bfs(int(s), max_hops=hops) for s in seeds[:base_n]]
    for f in futs:
        f.result(timeout=300)
    unbatched_qps = base_n / (time.perf_counter() - t0)
    rt1.close()

    # -- batched serving under concurrent ingest, open-loop Poisson
    rt = ServeRuntime(g, cfg)
    # warm every bucket shape ahead of the clock — a steady-state server
    # compiles once per bucket at deploy time, not inside a deadline
    for b in cfg.buckets:
        warm = [rt.submit_bfs(int(seeds[j % len(seeds)]), max_hops=hops)
                for j in range(b)]
        for f in warm:
            f.result(timeout=600)
    rt.stats.reset()  # compile-time latencies stay out of the percentiles
    ingested = {"done": False, "atoms": 0, "s": 0.0}

    def writer():
        t0 = time.perf_counter()
        for _ in range(stream_batches):
            subj = r.integers(0, n_entities, size=batch_links)
            obj = r.integers(0, n_entities, size=batch_links)
            g.bulk_import(
                values=[int(x) for x in range(batch_links)],
                target_lists=[[e0 + int(a), e0 + int(b)]
                              for a, b in zip(subj, obj)],
            )
            ingested["atoms"] += batch_links
        ingested["s"] = time.perf_counter() - t0
        ingested["done"] = True

    wt = threading.Thread(target=writer)
    wt.start()
    gaps = r.exponential(1.0 / offered_qps, size=n_requests)
    futs = []
    # opt-in profiler session (BENCH_C6_PROFILE=<logdir>): every kernel
    # dispatch inside carries a TraceAnnotation naming its batch kind,
    # bucket, and double-buffer slot, so the captured device timeline is
    # attributable per batch (obs.device docs)
    from hypergraphdb_tpu import obs

    with obs.profile(os.environ.get("BENCH_C6_PROFILE")):
        t0 = time.perf_counter()
        next_t = t0
        for i in range(n_requests):
            next_t += gaps[i]
            pause = next_t - time.perf_counter()
            if pause > 0:
                time.sleep(pause)
            futs.append(rt.submit_bfs(int(seeds[i]), max_hops=hops,
                                      deadline_s=deadline_s))
        served = shed = 0
        for f in futs:
            try:
                res = f.result(timeout=300)
                assert res.count >= 0
                served += 1
            except DeadlineExceeded:
                shed += 1
        wall = time.perf_counter() - t0
    wt.join()
    rt.close(drain=True, timeout=120)
    s = rt.stats_snapshot()

    telemetry = _telemetry_dump(
        "c6", registries=[rt.stats.registry, g.metrics.registry]
    )
    g.close()
    batched_qps = served / wall if wall else 0.0
    out = {
        "offered_qps": round(offered_qps, 1),
        "served_qps": round(batched_qps, 1),
        "unbatched_baseline_qps": round(unbatched_qps, 1),
        "batched_vs_unbatched": (
            round(batched_qps / unbatched_qps, 2) if unbatched_qps else None
        ),
        "requests": n_requests,
        "served": served,
        "shed_deadline": shed,
        "deadline_s": deadline_s,
        "batches": s["batches"],
        "device_dispatches": s["device_dispatches"],
        "batch_occupancy": (
            round(s["batch_occupancy"], 3)
            if s["batch_occupancy"] is not None else None
        ),
        "latency_ms_p50": (
            round(s["latency_ms"]["p50"], 2)
            if s["latency_ms"]["p50"] is not None else None
        ),
        "latency_ms_p95": (
            round(s["latency_ms"]["p95"], 2)
            if s["latency_ms"]["p95"] is not None else None
        ),
        "latency_ms_p99": (
            round(s["latency_ms"]["p99"], 2)
            if s["latency_ms"]["p99"] is not None else None
        ),
        "host_fallbacks": s["host_fallbacks"],
        # the main runtime's AOT cache counters (env HG_AOT_CACHE is set
        # by this bench): cache_hit for the serving config is exact
        "aot": s.get("aot"),
        "cache_hit": bool(s.get("aot", {}) and
                          s["aot"].get("misses", 1) == 0),
        "concurrent_ingest_atoms_per_sec": round(
            ingested["atoms"] / ingested["s"], 1
        ) if ingested["s"] else None,
    }
    if cold is not None:
        out["cold_start_s"] = cold
    if telemetry:
        # the SAME sampling snapshot the telemetry sidecar carries also
        # rides the recorded result (telemetry itself is excluded from
        # BENCH_C6_<tag>.json) — one capture, so the two can't disagree
        out["tracing"] = telemetry["sampling"]
        out["telemetry"] = telemetry
    out["recorded_to"] = _record_bench("c6_serving", out)
    return out


def _cold_start_probe() -> Optional[dict]:
    """ISSUE-8 acceptance instrumentation: wall time from ServeRuntime
    construction (prewarm included) to the first served result in a
    FRESH python process, with the AOT cache absent vs present on the
    same graph content — the number that shows the compile-storm
    collapsing. Small fixed scale so the probe costs seconds; disable
    with BENCH_C6_COLD=0."""
    if os.environ.get("BENCH_C6_COLD", "1") == "0":
        return None
    import subprocess
    import sys
    import tempfile

    n = int(os.environ.get("BENCH_C6_COLD_ENTITIES", 20_000))
    cache_dir = tempfile.mkdtemp(prefix="hg_aot_coldstart_")
    code = f"""
import json, time
import numpy as np
from hypergraphdb_tpu import HyperGraph
from hypergraphdb_tpu.serve import ServeConfig, ServeRuntime

g = HyperGraph()
r = np.random.default_rng(3)
ents = g.bulk_import(values=np.arange({n}).tolist())
e0 = int(ents[0])
subj = r.integers(0, {n}, size={n})
obj = r.integers(0, {n}, size={n})
g.bulk_import(values=[int(x) for x in range({n})],
              target_lists=[[e0 + int(a), e0 + int(b)]
                            for a, b in zip(subj, obj)])
t0 = time.perf_counter()
rt = ServeRuntime(g, ServeConfig(buckets=(64, 256, 1024),
                                 max_linger_s=0.002, top_r=16,
                                 aot_cache_dir={cache_dir!r}))
rt.submit_bfs(e0, max_hops=2).result(timeout=600)
dt = time.perf_counter() - t0
s = rt.stats_snapshot()
print("COLD_RESULT " + json.dumps(
    {{"first_result_s": round(dt, 3), "aot": s.get("aot")}}), flush=True)
rt.close()
g.close()
"""

    def run_once() -> dict:
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            cwd=os.path.dirname(os.path.abspath(__file__)), timeout=900,
        )
        for line in proc.stdout.splitlines():
            if line.startswith("COLD_RESULT "):
                return json.loads(line[len("COLD_RESULT "):])
        raise RuntimeError(f"cold-start probe failed (rc="
                           f"{proc.returncode}):\n{proc.stderr[-2000:]}")

    import shutil

    try:
        absent = run_once()   # empty cache dir: pays the compiles
        present = run_once()  # same dir, same content: loads executables
    except Exception as e:  # noqa: BLE001 - a probe must not kill the run
        import sys as _sys

        print(f"bench: cold-start probe failed: {e}", file=_sys.stderr)
        return None
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)
    return {
        "cache_absent_s": absent["first_result_s"],
        "cache_present_s": present["first_result_s"],
        "warm_aot": present["aot"],
        "entities": n,
    }


def bench_c7(snap, info):
    """c7_pattern_join: worst-case-optimal conjunctive pattern joins —
    anchored triangle and 2-path COUNTING over the 10M-atom graph
    (hgjoin: GHD-planned multiway intersections, ``ops/join``), K
    anchors per batched dispatch, vs the vectorized numpy host engine on
    the same co-incidence CSR. Count-only mode: the device download is
    one (K,) int32 per window, so the number measures the join engine,
    not the host link. Exact-count shape policy (``var_pad_max``) — any
    lane the caps still truncate is excluded from the differential and
    reported.

    Join engine v2 adds the HUB-HEAVY configuration (``hub_heavy`` in
    the recorded result): a mixed batch of hub anchors (co-degree past
    ``BENCH_C7_HUB_THRESHOLD`` — the lanes PR 10's padded executor
    truncated onto the host path) and tail anchors, run three ways —
    the degree-split executor, the PR-10 flat executor
    (``hub_split=False``), and the degree-split executor over the
    factorized trie relations — recording the tail-vs-hub lane ratio,
    ``split_vs_pr10`` and ``factorized_vs_flat`` throughput ratios, and
    both differential verdicts.

    Env knobs: BENCH_SEEDS (anchors per window), BENCH_C7_MAX_DEG
    (tail-anchor co-degree bound — the device-servable tail population;
    hub anchors now serve through the degree-split path instead of
    routing to host), BENCH_C7_ROW_CAP / BENCH_C7_PAD_CAP (executor
    caps — smoke-tuned defaults; the CPU smoke cannot tune them for
    real HBM, see README), BENCH_C7_BASELINE_N (host-engine sample),
    BENCH_C7_REPS, BENCH_C7_HUB_THRESHOLD (hub split bound, default
    MAX_DEG), BENCH_C7_HUB_MAX (hub sample's width ceiling, default
    4×threshold — the fell-off-pad band, not the top-0.01% monsters),
    BENCH_C7_HUB_N (hub lanes per dispatch, default half)."""
    _bench_entry_env()
    import jax

    from hypergraphdb_tpu.join.ir import (
        ConjunctivePattern,
        JoinAtom,
        split_constants,
    )
    from hypergraphdb_tpu.join.planner import plan_join
    from hypergraphdb_tpu.ops.join import (
        execute_join,
        factorized_relations,
        neighbor_csr,
    )

    r = np.random.default_rng(43)
    K = int(os.environ.get("BENCH_SEEDS", 1024))
    # few lanes × big row bucket: a 2-path through a 512-wide anchor can
    # bind ~10^5 tuples, and the binding table pools all lanes — 16
    # lanes under a 2^20 bucket keeps dense anchors exact where 128
    # lanes would overflow (and truncate) on every dispatch
    lanes = int(os.environ.get("BENCH_C7_LANES", 16))
    reps = int(os.environ.get("BENCH_C7_REPS", 8))
    max_deg = int(os.environ.get("BENCH_C7_MAX_DEG", 512))
    row_cap = int(os.environ.get("BENCH_C7_ROW_CAP", 1 << 20))
    pad_cap = int(os.environ.get("BENCH_C7_PAD_CAP", 2048))
    base_n = min(int(os.environ.get("BENCH_C7_BASELINE_N", 128)), K)

    t0 = time.perf_counter()
    off, flat = neighbor_csr(snap)  # one-time per snapshot, like ELL
    nbr_build_s = time.perf_counter() - t0
    off64 = off.astype(np.int64)

    # anchors: entities with a non-trivial but bounded co-row whose
    # NEIGHBOURS' co-rows also fit the pad — a zipf hub's row can run
    # into the millions, and a production deployment routes hub-anchored
    # patterns to the serving tier's exact host lane (truncation-honest
    # executor + host re-serve); the bench measures the device-servable
    # population, same honesty
    e0, l0 = info["entities"]
    N = snap.num_atoms
    all_w = off64[1: N + 1] - off64[:N]
    widths = all_w[e0:l0]
    cand = np.flatnonzero((widths >= 2) & (widths <= max_deg)) + e0
    if len(cand):
        # subsample BEFORE the per-anchor neighbour scan: the scan is a
        # host loop, and 8×K candidates is plenty to fill K lanes
        cand = cand[r.integers(0, len(cand),
                               size=min(8 * K, len(cand)))]
        nbr_max = np.array([
            all_w[flat[off64[a]: off64[a + 1]]].max(initial=0)
            for a in cand
        ])
        cand = cand[nbr_max <= pad_cap]
    if not len(cand):
        raise RuntimeError("c7: no device-servable anchors at this "
                           "scale; lower BENCH_C7_MAX_DEG / raise "
                           "BENCH_C7_PAD_CAP")
    anchors = cand[r.integers(0, len(cand), size=K)].astype(np.int64)

    def pattern_of(shape: str, a0: int) -> ConjunctivePattern:
        if shape == "triangle":   # a–y, y–z, z–a
            return ConjunctivePattern(
                vars=("y", "z"),
                atoms=(JoinAtom("co", "y", int(a0)),
                       JoinAtom("co", "y", "z"),
                       JoinAtom("co", "z", int(a0))),
            )
        return ConjunctivePattern(   # 2-path: a–y, y–z
            vars=("y", "z"),
            atoms=(JoinAtom("co", "y", int(a0)),
                   JoinAtom("co", "z", "y")),
        )

    def host_counts(shape: str, aa: np.ndarray) -> np.ndarray:
        """The vectorized numpy host engine: per-anchor sorted-array
        intersections over the same co-incidence CSR rows."""
        out = np.zeros(len(aa), dtype=np.int64)
        for i, a in enumerate(aa):
            row = flat[off64[a]: off64[a + 1]].astype(np.int64)
            if shape == "triangle":
                out[i] = sum(
                    len(np.intersect1d(
                        flat[off64[y]: off64[y + 1]], row,
                        assume_unique=True,
                    )) for y in row
                )
            else:
                # enumerate (y, z) bindings the way a join engine must
                # (z ≠ a, z ≠ y by irreflexivity) — counting via degree
                # arithmetic would be the special-case shortcut, not
                # the conjunctive-pattern workload under test
                zs = flat[np.concatenate([
                    np.arange(off64[y], off64[y + 1]) for y in row
                ]) if len(row) else np.empty(0, dtype=np.int64)]
                out[i] = int((zs != a).sum())
        return out

    result: dict = {
        "anchors": K,
        "nbr_build_s": round(nbr_build_s, 2),
        "nbr_edges": int(off64[snap.num_atoms]),
    }
    for shape, n_consts in (("triangle", 2), ("path2", 1)):
        pat = pattern_of(shape, int(anchors[0]))
        sig, consts0 = split_constants(pat)
        plan = plan_join(snap, pat, sig, consts0)
        consts = np.repeat(anchors[:, None], n_consts, axis=1) \
            .astype(np.int32)
        # pad the anchor list to a lanes multiple so every dispatch
        # shares ONE compiled shape (counts are sliced back to K)
        if K % lanes:
            consts = np.concatenate(
                [consts, np.repeat(consts[:1], lanes - K % lanes, 0)]
            )

        def window(n_anchors=len(consts)):
            """n_anchors through ``lanes``-wide dispatches (bounding the
            pooled binding table) — returns the async handle list."""
            return [
                execute_join(
                    snap, plan, consts[i: i + lanes], top_r=0,
                    count_only=True, row_cap=row_cap, pad_cap=pad_cap,
                    var_pad_max=True,
                )
                for i in range(0, n_anchors, lanes)
            ]

        compile_info = _timed_warmup(lambda: jax.block_until_ready(
            [ex.counts for ex in window(min(lanes, K))]
        ))

        def timed():
            t0 = time.perf_counter()
            exs = window()
            jax.block_until_ready([ex.counts for ex in exs])
            return K / (time.perf_counter() - t0), exs

        dev_qps, exs = best_of(timed, n=reps)
        counts = np.concatenate(
            [np.asarray(ex.counts, dtype=np.int64) for ex in exs]
        )[:K]
        trunc = np.concatenate(
            [np.asarray(ex.trunc) for ex in exs]
        )[:K]

        def host_window():
            t0 = time.perf_counter()
            hc = host_counts(shape, anchors[:base_n])
            return base_n / (time.perf_counter() - t0), hc

        host_qps, hc = best_of(host_window, n=2)
        exact = ~trunc[:base_n]
        agree = bool(np.array_equal(counts[:base_n][exact], hc[exact]))
        result[shape] = {
            "device_anchors_per_sec": round(dev_qps, 1),
            "host_anchors_per_sec": round(host_qps, 1),
            "vs_host": (round(dev_qps / host_qps, 2)
                        if host_qps else None),
            "bindings_total": int(counts[~trunc].sum()),
            "n_truncated": int(trunc.sum()),
            "differential_equal": agree,
            "plan": plan.describe(),
            **compile_info,
        }
        if not agree:
            bad = np.flatnonzero(
                exact & (counts[:base_n] != hc)
            )[:5]
            result[shape]["differential_diff"] = [
                [int(anchors[i]), int(counts[i]), int(hc[i])]
                for i in bad
            ]
    # -- hub-heavy configuration (join engine v2) ----------------------------
    # TRIANGLES through anchors the PR-10 executor excluded: co-rows
    # past the hub threshold (triangle keeps every step const-keyed, so
    # the hub chain's chunked expansion serves the whole plan — the
    # pattern's multiway intersections probe the other relations by
    # binary search, width-free). Hub anchors sample just ABOVE the
    # threshold (bounded by BENCH_C7_HUB_MAX): the fell-off-pad
    # population the split reclaims, not the top-0.01% monsters whose
    # binding tables outgrow any row budget. Mixed with tails so ONE
    # dispatch exercises both chains; count-only, exact-count shape
    # policy (var_pad_max) for all three modes so the comparison is the
    # executor, not the pads.
    hub_thr = int(os.environ.get("BENCH_C7_HUB_THRESHOLD", max_deg))
    hub_cap = int(os.environ.get("BENCH_C7_HUB_MAX", 4 * hub_thr))
    n_hub = min(int(os.environ.get("BENCH_C7_HUB_N",
                                   max(lanes // 2, 1))), lanes)
    w_ent = all_w[e0:l0]
    hub_pool = np.flatnonzero((w_ent > hub_thr) & (w_ent <= hub_cap)) \
        + e0
    if not len(hub_pool):
        # no anchor in the band at this scale: take the widest rows and
        # drop the threshold just under them so the split still engages
        # (recorded — the smoke stays honest about it)
        hub_pool = np.argsort(w_ent)[-max(4 * n_hub, 8):] + e0
        hub_thr = max(int(all_w[hub_pool].min()) - 1, 2)
    hub_anchors = hub_pool[r.integers(0, len(hub_pool), size=n_hub)]
    tail_anchors = cand[r.integers(0, len(cand), size=lanes - n_hub)]
    anchors_h = np.concatenate([hub_anchors, tail_anchors]) \
        .astype(np.int64)
    pat_h = pattern_of("triangle", int(anchors_h[0]))
    sig_h, consts0_h = split_constants(pat_h)
    plan_h = plan_join(snap, pat_h, sig_h, consts0_h)
    consts_h = np.repeat(anchors_h[:, None], 2, axis=1) \
        .astype(np.int32)

    t0 = time.perf_counter()
    fact = factorized_relations(snap)
    fact_build_s = time.perf_counter() - t0

    def hub_run(mode: str):
        kw = dict(top_r=0, count_only=True, row_cap=row_cap,
                  pad_cap=pad_cap, var_pad_max=True)
        if mode == "split":
            kw.update(hub_threshold=hub_thr, factorized=False)
        elif mode == "fact":
            kw.update(hub_threshold=hub_thr, factorized=True)
        else:                                   # the PR-10 executor
            kw.update(hub_split=False, factorized=False)
        return execute_join(snap, plan_h, consts_h, **kw)

    hub_stats: dict = {
        "hub_threshold": hub_thr,
        "hub_lanes": n_hub,
        "tail_lanes": lanes - n_hub,
        "lane_ratio": round((lanes - n_hub) / max(n_hub, 1), 2),
        "max_hub_width": int(all_w[hub_anchors].max()),
        "fact_build_s": round(fact_build_s, 3),
        "fact_entries": fact["co"].entries,
        "fact_entries_flat": fact["co"].entries_flat,
        "fact_groups": fact["co"].n_groups,
    }
    # throughput metric: EXACTLY-SERVED anchors per second — a
    # truncated lane re-routes to the exact host path in production
    # (orders of magnitude slower), so it is not served by the device
    # path whatever the wall clock says. This is what makes the
    # split-vs-PR10 comparison honest: PR 10 truncates the hub lanes
    # (fast but unserved), the split serves them.
    mode_counts = {}
    for mode, key in (("split", "device_anchors_per_sec"),
                      ("pr10", "pr10_anchors_per_sec"),
                      ("fact", "fact_anchors_per_sec")):
        jax.block_until_ready(hub_run(mode).counts)   # compile warmup

        def timed_hub(mode=mode):
            t0 = time.perf_counter()
            ex = hub_run(mode)
            jax.block_until_ready(ex.counts)
            dt = time.perf_counter() - t0
            exact = lanes - int(np.asarray(ex.trunc).sum())
            return exact / dt, (ex, lanes / dt)

        qps, (ex, raw_qps) = best_of(timed_hub, n=reps)
        hub_stats[key] = round(qps, 1)
        hub_stats[key.replace("anchors_per_sec", "raw_per_sec")] = \
            round(raw_qps, 1)
        mode_counts[mode] = (np.asarray(ex.counts, dtype=np.int64),
                             np.asarray(ex.trunc))
        if mode == "split":
            hub_stats["hub_lanes_dispatched"] = ex.hub_lanes
    s_counts, s_trunc = mode_counts["split"]
    p_counts, p_trunc = mode_counts["pr10"]
    f_counts, f_trunc = mode_counts["fact"]
    hub_stats["n_truncated"] = int(s_trunc.sum())
    hub_stats["pr10_truncated"] = int(p_trunc.sum())
    hub_stats["split_vs_pr10"] = round(
        hub_stats["device_anchors_per_sec"]
        / max(hub_stats["pr10_anchors_per_sec"], 1e-9), 2)
    hub_stats["factorized_vs_flat"] = round(
        hub_stats["fact_anchors_per_sec"]
        / max(hub_stats["device_anchors_per_sec"], 1e-9), 2)
    ok = ~(s_trunc | f_trunc)
    hub_stats["factorized_equal"] = bool(
        np.array_equal(s_counts[ok], f_counts[ok])
    )
    hc_h = host_counts("triangle", anchors_h[:base_n])
    exact_h = ~s_trunc[:base_n]
    hub_stats["differential_equal"] = bool(
        np.array_equal(s_counts[:base_n][exact_h], hc_h[exact_h])
    ) and bool(exact_h.any())
    result["hub_heavy"] = hub_stats

    telemetry = _telemetry_dump("c7")
    if telemetry:
        # the SAME sampling snapshot the telemetry sidecar carries also
        # rides the recorded result (c6's discipline: one capture, the
        # two can't disagree; telemetry paths stay excluded)
        result["tracing"] = telemetry["sampling"]
        result["telemetry"] = telemetry
    result["recorded_to"] = _record_bench("c7_pattern_join", result)
    return result


def bench_c8():
    """c8_sharded: multi-chip sharded serving — per-device-count serve
    throughput over the SAME graph, batched BFS buckets routed through
    the mesh-sharded executor (``serve/sharded`` + ``ops/sharded_serving``)
    at 1/2/4/8 devices vs the single-chip ``DeviceExecutor`` path, plus
    a differential verdict (sharded results == single-chip results for a
    probe set). Closed-loop flood (submit everything, wait): the number
    under test is sustained batched throughput, and the scaling curve is
    what the real-TPU sweep validates (CPU devices share host cores, so
    virtual-mesh ratios UNDERSTATE real chips).

    Env knobs: BENCH_C8_ENTITIES / _LINKS (graph scale; the 10M shape on
    real hardware), BENCH_C8_REQUESTS, BENCH_C8_HOPS, BENCH_C8_DEVICES
    (comma list, default "1,2,4,8" clipped to visible), BENCH_C8_TAG."""
    _bench_entry_env()
    import jax

    from hypergraphdb_tpu import HyperGraph
    from hypergraphdb_tpu.serve import ServeConfig, ServeRuntime

    _telemetry_begin()
    n_entities = int(os.environ.get("BENCH_C8_ENTITIES", 200_000))
    n_links = int(os.environ.get("BENCH_C8_LINKS", 400_000))
    n_requests = int(os.environ.get("BENCH_C8_REQUESTS", 2048))
    hops = int(os.environ.get("BENCH_C8_HOPS", 2))
    n_vis = len(jax.devices())
    asked = [int(x) for x in os.environ.get(
        "BENCH_C8_DEVICES", "1,2,4,8").split(",")]
    # clamp (never silently drop) over-sized requests to the visible
    # device count, dedupe ascending; an all-oversized list degrades to
    # the honest [full mesh] instead of crashing after the single-chip
    # measurement already ran
    counts = sorted({min(x, n_vis) for x in asked if x >= 1}) or [n_vis]
    if counts != sorted(set(asked)):
        import sys

        print(f"bench c8: device counts {asked} clamped to {counts} "
              f"({n_vis} visible)", file=sys.stderr)

    g = HyperGraph()
    r = np.random.default_rng(23)
    entities = g.bulk_import(values=np.arange(n_entities).tolist())
    e0 = int(entities[0])
    for s in range(0, n_links, 100_000):
        m = min(100_000, n_links - s)
        subj = r.integers(0, n_entities, size=m)
        obj = r.integers(0, n_entities, size=m)
        g.bulk_import(
            values=[int(x) for x in range(s, s + m)],
            target_lists=[[e0 + int(a), e0 + int(b)]
                          for a, b in zip(subj, obj)],
        )
    g.enable_incremental(
        headroom=1.8, delta_bucket_min=1 << 14,
        pack_pad_multiple=int(os.environ.get("BENCH_C8_PAD", 1 << 17)),
    )
    seeds = (e0 + r.integers(0, n_entities, size=n_requests)).astype(
        np.int64)

    # ROADMAP 1(d): an env-gated c6-style OPEN-LOOP Poisson arrival mode,
    # so the multi-chip scaling claim can run under the same
    # shed/deadline contract as c6 (arrivals paced by the offered rate,
    # not by completions — queueing delay measured honestly). Closed-loop
    # flood stays the default: sustained-throughput scaling is the
    # primary number under test.
    open_loop = os.environ.get("BENCH_C8_OPEN_LOOP", "0") == "1"
    offered_qps = float(os.environ.get("BENCH_C8_OFFERED_QPS", 2000.0))
    deadline_s = float(os.environ.get("BENCH_C8_DEADLINE_S", 1.0))

    def run(cfg) -> tuple[float, list, int, Optional[dict]]:
        from hypergraphdb_tpu.serve import DeadlineExceeded

        rt = ServeRuntime(g, cfg)
        try:
            # warm each bucket shape off the clock
            for b in cfg.buckets:
                warm = [rt.submit_bfs(int(seeds[j % len(seeds)]),
                                      max_hops=hops) for j in range(b)]
                for f in warm:
                    f.result(timeout=600)
            rt.stats.reset()
            if not open_loop:
                t0 = time.perf_counter()
                futs = [rt.submit_bfs(int(s), max_hops=hops)
                        for s in seeds]
                results = [f.result(timeout=600) for f in futs]
                wall = time.perf_counter() - t0
                probe_out = [(int(res.count),
                              [int(m) for m in res.matches])
                             for res in results[:64]]
                return (len(results) / wall, probe_out,
                        rt.stats.sharded_dispatches, None)
            # open-loop window: Poisson gaps per the offered rate (its
            # own rng so the arrival stream is identical per device
            # count), expired requests shed with a typed deadline
            gaps = np.random.default_rng(31).exponential(
                1.0 / offered_qps, size=n_requests
            )
            t0 = time.perf_counter()
            next_t = t0
            futs = []
            for i in range(n_requests):
                next_t += gaps[i]
                pause = next_t - time.perf_counter()
                if pause > 0:
                    time.sleep(pause)
                futs.append(rt.submit_bfs(int(seeds[i]), max_hops=hops,
                                          deadline_s=deadline_s))
            served = shed = 0
            for f in futs:
                try:
                    res = f.result(timeout=600)
                    assert res.count >= 0
                    served += 1
                except DeadlineExceeded:
                    shed += 1
            wall = time.perf_counter() - t0
            # p99 read BEFORE the probe: the probe is an unpaced burst
            # whose queueing would otherwise own the recorded tail
            lat = rt.stats.latency_percentiles_ms()
            # the differential probe re-issues closed-loop so shed
            # requests never blind the verdict
            pf = [rt.submit_bfs(int(s), max_hops=hops)
                  for s in seeds[:64]]
            probe_out = [(int(res.count), [int(m) for m in res.matches])
                         for res in (f.result(timeout=600) for f in pf)]
            return (served / wall if wall else 0.0, probe_out,
                    rt.stats.sharded_dispatches,
                    {"served": served, "shed_deadline": shed,
                     "latency_ms_p99": (round(lat["p99"], 2)
                                        if lat["p99"] is not None
                                        else None)})
        finally:
            rt.close(drain=True, timeout=120)

    base_cfg = dict(
        buckets=(64, 256, 1024),
        max_linger_s=float(os.environ.get("BENCH_C8_LINGER_S", 0.002)),
        top_r=16, prewarm_aot=False,
    )
    single_qps, single_probe, _, single_ol = run(ServeConfig(
        sharded=False, **base_cfg))
    per_dev = {}
    open_stats = {}
    if single_ol is not None:
        open_stats["1"] = single_ol
    diff_equal = True
    sharded_dispatches = 0
    for d in counts:
        if d == 1:
            per_dev["1"] = round(single_qps, 1)
            continue
        qps, probe_out, n_sharded, ol = run(
            ServeConfig(sharded=True, mesh_devices=d, **base_cfg))
        per_dev[str(d)] = round(qps, 1)
        diff_equal = diff_equal and probe_out == single_probe
        sharded_dispatches += n_sharded
        if ol is not None:
            open_stats[str(d)] = ol
    g.close()
    top = str(max(int(k) for k in per_dev))
    out = {
        "entities": n_entities,
        "links": n_links,
        "requests": n_requests,
        "hops": hops,
        "devices": counts,
        "served_qps_per_device_count": per_dev,
        "single_chip_qps": round(single_qps, 1),
        "sharded_vs_single_chip": (
            round(per_dev[top] / single_qps, 2) if single_qps else None
        ),
        # proves the multi-device runs really took the mesh path (a
        # silently-single-chip "sharded" run would be trivially
        # differential-equal) — the shard.sh gate asserts it nonzero
        "sharded_dispatches": sharded_dispatches,
        "differential_equal": diff_equal,
        "arrival_mode": "open" if open_loop else "closed",
        "backend": _backend_name(),
    }
    if open_loop:
        out["open_loop"] = {
            "offered_qps": round(offered_qps, 1),
            "deadline_s": deadline_s,
            "per_device": open_stats,
        }
    telemetry = _telemetry_dump("c8")
    if telemetry:
        # sampling snapshot rides the recorded result (c6's discipline)
        out["tracing"] = telemetry["sampling"]
        out["telemetry"] = telemetry
    out["recorded_to"] = _record_bench("c8_sharded", out)
    return out


def bench_c9():
    """c9_value_index: device-side secondary value indexes (hgindex) —
    batched range / ordered / top-k serving over the per-kind sorted
    device columns (``storage/value_index`` + ``ops/value_index``) vs
    the HOST VALUE SCAN the serve tier answered with before (value
    predicates raised Unservable; callers ran ``graph.find_all``, a
    by-value B-tree walk — ROADMAP item 3's 43×-slower path). Built
    through the REAL store path so the whole pipeline is under test:
    by-value index → snapshot value ranks → sorted device column.
    Closed-loop flood through ``ServeRuntime.submit_range``; a probe
    subset is differentially verified against the exact host oracle
    (value-ordered, count-exact) and the verdict recorded.

    Env knobs: BENCH_C9_ENTITIES / _LINKS (graph scale), _REQUESTS,
    _WINDOW (value width of each range), _BASELINE_N, _TAG."""
    _bench_entry_env()
    from hypergraphdb_tpu import HyperGraph
    from hypergraphdb_tpu.query import conditions as qc
    from hypergraphdb_tpu.serve import ServeConfig, ServeRuntime

    _telemetry_begin()
    n_entities = int(os.environ.get("BENCH_C9_ENTITIES", 200_000))
    n_links = int(os.environ.get("BENCH_C9_LINKS", 400_000))
    n_requests = int(os.environ.get("BENCH_C9_REQUESTS", 4096))
    window = int(os.environ.get("BENCH_C9_WINDOW", 24))
    base_n = min(int(os.environ.get("BENCH_C9_BASELINE_N", 128)),
                 n_requests)
    probe_n = min(64, n_requests)

    g = HyperGraph()
    r = np.random.default_rng(29)
    entities = g.bulk_import(values=np.arange(n_entities).tolist())
    e0 = int(entities[0])
    for s in range(0, n_links, 100_000):
        m = min(100_000, n_links - s)
        subj = r.integers(0, n_entities, size=m)
        obj = r.integers(0, n_entities, size=m)
        g.bulk_import(
            # link values live in a disjoint int range so entity windows
            # and link windows exercise the SAME sorted column at
            # different densities
            values=[int(1_000_000 + s + x) for x in range(m)],
            target_lists=[[e0 + int(a), e0 + int(b)]
                          for a, b in zip(subj, obj)],
        )
    g.enable_incremental(
        headroom=1.8, delta_bucket_min=1 << 14,
        pack_pad_multiple=int(os.environ.get("BENCH_C9_PAD", 1 << 17)),
    )

    cfg = ServeConfig(
        buckets=(64, 256, 1024),
        max_linger_s=float(os.environ.get("BENCH_C9_LINGER_S", 0.002)),
        top_r=16, prewarm_aot=False,
    )
    los = r.integers(0, n_entities - window, size=n_requests)
    kinds = r.integers(0, 3, size=n_requests)  # range | top-k asc | desc
    topk_limit = 8  # the k of the top-k request classes

    def limit_of(i):
        return None if kinds[i] == 0 else topk_limit

    def submit(rt, i):
        lo = int(los[i])
        return rt.submit_range(lo=lo, hi=lo + window, limit=limit_of(i),
                               desc=bool(kinds[i] == 2))

    rt = ServeRuntime(g, cfg)
    # warm each bucket shape off the clock
    for b in cfg.buckets:
        warm = [submit(rt, j % n_requests) for j in range(b)]
        for f in warm:
            f.result(timeout=600)
    rt.stats.reset()
    t0 = time.perf_counter()
    futs = [submit(rt, i) for i in range(n_requests)]
    results = [f.result(timeout=600) for f in futs]
    wall = time.perf_counter() - t0
    device_qps = n_requests / wall if wall else 0.0
    s = rt.stats_snapshot()
    rt.close(drain=True, timeout=120)

    # -- the host-scan baseline: what every value query cost BEFORE the
    # range lane existed (bridge: Unservable → caller runs find_all's
    # by-value index walk). Same windows, exact results.
    def host_window():
        t0 = time.perf_counter()
        for i in range(base_n):
            lo = int(los[i])
            g.find_all(qc.And(qc.AtomValue(lo, "gte"),
                              qc.AtomValue(lo + window, "lte")))
        return base_n / (time.perf_counter() - t0)

    host_qps = best_of(host_window, n=2)

    # -- differential verdict: probe subset vs the exact host oracle
    # (order-, count-, and truncation-exact)
    from hypergraphdb_tpu.storage.value_index import value_key_of

    diff_equal = True
    diffs = []
    for i in range(probe_n):
        res = results[i]
        lo = int(los[i])
        hs = [int(h) for h in g.find_all(qc.And(
            qc.AtomValue(lo, "gte"), qc.AtomValue(lo + window, "lte")
        ))]
        keyed = sorted(((value_key_of(g, h)[1:], h) for h in hs),
                       key=lambda kv: (kv[0], kv[1]))
        ordered = [h for _, h in keyed]
        if kinds[i] == 2:
            ordered = [h for _, h in sorted(
                keyed, key=lambda kv: kv[0], reverse=True)]
        # the same window math the runtime applies (limit capped by the
        # config's top_r) — never a re-hardcoded literal
        lim = limit_of(i)
        upto = min(lim if lim is not None else cfg.top_r, cfg.top_r)
        want = ordered[:upto]
        got = [int(m) for m in res.matches]
        if res.count != len(ordered) or got != want:
            diff_equal = False
            if len(diffs) < 5:
                diffs.append([lo, res.count, len(ordered), got, want])
    g.close()

    out = {
        "entities": n_entities,
        "links": n_links,
        "requests": n_requests,
        "window": window,
        "served_qps": round(device_qps, 1),
        "host_scan_qps": round(host_qps, 1),
        "device_vs_host_scan": (
            round(device_qps / host_qps, 2) if host_qps else None
        ),
        "range_dispatches": s["range_dispatches"],
        "host_fallbacks": s["host_fallbacks"],
        "batch_occupancy": (
            round(s["batch_occupancy"], 3)
            if s["batch_occupancy"] is not None else None
        ),
        "latency_ms_p50": (
            round(s["latency_ms"]["p50"], 2)
            if s["latency_ms"]["p50"] is not None else None
        ),
        "latency_ms_p99": (
            round(s["latency_ms"]["p99"], 2)
            if s["latency_ms"]["p99"] is not None else None
        ),
        "differential_probes": probe_n,
        "differential_equal": diff_equal,
        "backend": _backend_name(),
    }
    if diffs:
        out["differential_diff"] = diffs
    telemetry = _telemetry_dump("c9")
    if telemetry:
        # sampling snapshot rides the recorded result (c6's discipline)
        out["tracing"] = telemetry["sampling"]
        out["telemetry"] = telemetry
    out["recorded_to"] = _record_bench("c9_value_index", out)
    return out


def bench_c10():
    """c10_pattern: OPEN-LOOP pattern serving + standing subscriptions
    (hgsub) — Poisson arrivals of ad-hoc ``submit_pattern`` requests
    against ``ServeRuntime`` while ingest streams concurrently and N
    standing pattern/range subscriptions ride the SAME bucketed device
    programs (``SubscriptionManager`` attached to the runtime's
    dispatch cycle). Open-loop means arrival times come from the
    offered rate, not from completions, so queueing delay under the
    standing-eval background load is measured honestly.

    Two lanes come out of one run: the ad-hoc ``pattern`` percentiles
    (runtime stats) and the ``sub`` notification-latency percentiles
    (ingest-dirty → delta-enqueued, via the manager's perf feed) — the
    pair ``--seed-baseline`` turns into the sentinel's ``pattern`` and
    ``sub`` contracts. A probe subset of subscriptions is differentially
    verified the wire way: initial snapshot + folded polled deltas must
    equal the exact host re-evaluation at settle.

    Env knobs: BENCH_C10_ENTITIES / _LINKS (graph scale), _REQUESTS,
    _OFFERED_QPS, _DEADLINE_S, _SUBS (standing queries), _HUBS (anchor
    pool the ingest keeps hitting), _INGEST_BATCHES / _BATCH_LINKS,
    _BASELINE_N, _TAG."""
    _bench_entry_env()
    import threading

    from hypergraphdb_tpu import HyperGraph
    from hypergraphdb_tpu.query import conditions as qc
    from hypergraphdb_tpu.serve import DeadlineExceeded, ServeConfig, \
        ServeRuntime
    from hypergraphdb_tpu.sub import SubscriptionManager

    _telemetry_begin()
    n_entities = int(os.environ.get("BENCH_C10_ENTITIES", 200_000))
    n_links = int(os.environ.get("BENCH_C10_LINKS", 400_000))
    n_requests = int(os.environ.get("BENCH_C10_REQUESTS", 4096))
    offered_qps = float(os.environ.get("BENCH_C10_OFFERED_QPS", 1000.0))
    deadline_s = float(os.environ.get("BENCH_C10_DEADLINE_S", 2.0))
    n_subs = int(os.environ.get("BENCH_C10_SUBS", 64))
    n_hubs = int(os.environ.get("BENCH_C10_HUBS", 16))
    stream_batches = int(os.environ.get("BENCH_C10_INGEST_BATCHES", 8))
    batch_links = int(os.environ.get("BENCH_C10_BATCH_LINKS", 5_000))
    base_n = min(int(os.environ.get("BENCH_C10_BASELINE_N", 128)),
                 n_requests)
    probe_n = min(16, n_subs)

    g = HyperGraph()
    r = np.random.default_rng(31)
    entities = g.bulk_import(values=np.arange(n_entities).tolist())
    e0 = int(entities[0])
    for s in range(0, n_links, 100_000):
        m = min(100_000, n_links - s)
        subj = r.integers(0, n_entities, size=m)
        obj = r.integers(0, n_entities, size=m)
        g.bulk_import(
            values=[int(1_000_000 + s + x) for x in range(m)],
            target_lists=[[e0 + int(a), e0 + int(b)]
                          for a, b in zip(subj, obj)],
        )
    g.enable_incremental(
        headroom=1.8, background=True, delta_bucket_min=1 << 14,
        pack_pad_multiple=int(os.environ.get("BENCH_C10_PAD", 1 << 17)),
    )

    # the manager feeds dirty→notified latency to ServeConfig.perf's
    # observe("sub", ...) — a recording tap keeps the bench independent
    # of sentinel window spans while exercising the REAL feed path
    class _PerfTap:
        def __init__(self):
            self.lanes: dict = {}
            self.lock = threading.Lock()

        def observe(self, kind, latency_s, path="device", t=None):
            with self.lock:
                self.lanes.setdefault(kind, []).append(float(latency_s))

        def observe_batch(self, *a, **k):
            pass

        def maybe_tick(self):
            return None

    tap = _PerfTap()
    cfg = ServeConfig(
        buckets=(64, 256, 1024),
        max_queue=int(os.environ.get("BENCH_C10_QUEUE", 8192)),
        max_linger_s=float(os.environ.get("BENCH_C10_LINGER_S", 0.002)),
        top_r=16, prewarm_aot=False, perf=tap,
    )
    rt = ServeRuntime(g, cfg)
    mgr = SubscriptionManager(g, rt)
    rt.attach_subscriptions(mgr)

    # standing queries: pattern subs anchored on a hub pool the ingest
    # keeps linking into, range subs whose value windows the ingest's
    # fresh link values land inside — both kinds receive real deltas
    hubs = [e0 + int(h) for h in
            r.integers(0, n_entities, size=n_hubs)]
    ingest_v0 = 10_000_000
    ingest_span = stream_batches * batch_links
    folded: list = []  # (sid, kind, anchor/None, client-folded set)
    for i in range(n_subs):
        if i % 2 == 0:
            anchor = hubs[i % n_hubs]
            resp = mgr.subscribe("pattern", {"anchors": [anchor]})
        else:
            lo = ingest_v0 + (i * ingest_span) // n_subs
            hi = ingest_v0 + ((i + 2) * ingest_span) // n_subs
            resp = mgr.subscribe("range", {"lo": lo, "hi": hi})
        folded.append((resp["id"], resp["kind"],
                       {int(h) for h in resp["matches"]}))

    seeds = [e0 + int(x) for x in r.integers(0, n_entities,
                                             size=n_requests)]

    # warm every bucket shape off the clock (compile at deploy time)
    for b in cfg.buckets:
        warm = [rt.submit_pattern([seeds[j % n_requests]])
                for j in range(b)]
        for f in warm:
            f.result(timeout=600)
    rt.stats.reset()
    ingested = {"done": False, "atoms": 0, "s": 0.0}

    def writer():
        t0 = time.perf_counter()
        v = ingest_v0
        for _ in range(stream_batches):
            obj = r.integers(0, n_entities, size=batch_links)
            g.bulk_import(
                values=[int(v + x) for x in range(batch_links)],
                target_lists=[[hubs[int(o) % n_hubs], e0 + int(o)]
                              for o in obj],
            )
            v += batch_links
            ingested["atoms"] += batch_links
        ingested["s"] = time.perf_counter() - t0
        ingested["done"] = True

    wt = threading.Thread(target=writer)
    wt.start()
    gaps = r.exponential(1.0 / offered_qps, size=n_requests)
    futs = []
    t0 = time.perf_counter()
    next_t = t0
    for i in range(n_requests):
        next_t += gaps[i]
        pause = next_t - time.perf_counter()
        if pause > 0:
            time.sleep(pause)
        futs.append(rt.submit_pattern([seeds[i]],
                                      deadline_s=deadline_s))
    served = shed = 0
    for f in futs:
        try:
            res = f.result(timeout=300)
            assert res.count >= 0
            served += 1
        except DeadlineExceeded:
            shed += 1
    wall = time.perf_counter() - t0
    wt.join()

    # settle the standing tier: keep the dispatch cycle turning until
    # every subscription is clean (bounded — staleness keeps score)
    settle_t0 = time.perf_counter()
    while time.perf_counter() - settle_t0 < 120:
        mgr.pump()
        with mgr._lock:
            busy = any(s.dirty or s.inflight is not None
                       for s in mgr.subs.all())
        if not busy:
            break
        time.sleep(0.01)
    settle_s = time.perf_counter() - settle_t0

    s = rt.stats_snapshot()
    sub_snap = mgr.stats.snapshot()

    # -- differential verdict, the WIRE way: initial snapshot + folded
    # polled deltas must equal the exact host oracle at settle
    diff_equal = True
    diffs = []
    for sid, kind, matches in folded[:probe_n]:
        while True:
            env = mgr.poll(sid, max_notes=64, timeout_s=0.0)
            if env["what"] == "resync":
                matches = {int(h) for h in env["matches"]}
                break
            for note in env["notes"]:
                matches.difference_update(
                    int(h) for h in note["removed"])
                matches.update(int(h) for h in note["added"])
            if not env["more"] and not env["notes"]:
                break
        sub = mgr.subs.get(sid)
        want = mgr._full_eval(sub)
        if matches != want:
            diff_equal = False
            if len(diffs) < 5:
                diffs.append([sid, kind, len(matches), len(want)])

    # -- host baseline: the same ad-hoc pattern answered by the by-target
    # host index walk (what a caller paid without the serving tier)
    def host_window():
        t0 = time.perf_counter()
        for i in range(base_n):
            g.find_all(qc.Incident(seeds[i]))
        return base_n / (time.perf_counter() - t0)

    host_qps = best_of(host_window, n=2)
    mgr.close()
    rt.close(drain=True, timeout=120)

    with tap.lock:
        notify_lat = sorted(tap.lanes.get("sub") or ())
    n_lat = len(notify_lat)

    def pct(q):
        if not n_lat:
            return None
        return round(notify_lat[min(n_lat - 1, (q * n_lat) // 100)]
                     * 1e3, 2)

    telemetry = _telemetry_dump(
        "c10", registries=[rt.stats.registry, mgr.stats.registry,
                           g.metrics.registry]
    )
    g.close()
    served_qps = served / wall if wall else 0.0
    out = {
        "entities": n_entities,
        "links": n_links,
        "requests": n_requests,
        "offered_qps": round(offered_qps, 1),
        "served_qps": round(served_qps, 1),
        "served": served,
        "shed_deadline": shed,
        "deadline_s": deadline_s,
        "host_pattern_qps": round(host_qps, 1),
        "device_vs_host": (
            round(served_qps / host_qps, 2) if host_qps else None
        ),
        "batches": s["batches"],
        "device_dispatches": s["device_dispatches"],
        "batch_occupancy": (
            round(s["batch_occupancy"], 3)
            if s["batch_occupancy"] is not None else None
        ),
        "latency_ms_p50": (
            round(s["latency_ms"]["p50"], 2)
            if s["latency_ms"]["p50"] is not None else None
        ),
        "latency_ms_p99": (
            round(s["latency_ms"]["p99"], 2)
            if s["latency_ms"]["p99"] is not None else None
        ),
        "host_fallbacks": s["host_fallbacks"],
        "concurrent_ingest_atoms_per_sec": round(
            ingested["atoms"] / ingested["s"], 1
        ) if ingested["s"] else None,
        "sub": {
            "subscriptions": n_subs,
            "eval_rounds": sub_snap["sub.eval_rounds"],
            "evals": sub_snap["sub.evals"],
            "dirty_skipped": sub_snap["sub.dirty_skipped"],
            "notified": sub_snap["sub.notified"],
            "shed": sub_snap["sub.shed"],
            "notify_samples": n_lat,
            "notify_ms_p50": pct(50),
            "notify_ms_p99": pct(99),
            "settle_s": round(settle_s, 3),
        },
        "differential_probes": probe_n,
        "differential_equal": diff_equal,
        "backend": _backend_name(),
    }
    if diffs:
        out["differential_diff"] = diffs
    if telemetry:
        out["tracing"] = telemetry["sampling"]
        out["telemetry"] = telemetry
    out["recorded_to"] = _record_bench("c10_pattern", out)
    return out


def bench_c11():
    """c11_join: OPEN-LOOP join serving — Poisson arrivals of anchored
    triangle ``submit_join`` requests against ``ServeRuntime`` while
    ingest streams concurrently. Where c7 measures the join EXECUTOR's
    closed-loop throughput (dispatch as fast as the last batch
    finishes), c11 measures the join LANE as a service: arrival times
    come from the offered rate, so the recorded latency percentiles
    include queueing delay under concurrent write load — the numbers a
    latency contract (and a cost model) can actually be built on.
    ``--seed-baseline`` turns this record into the sentinel's and the
    hgplan planner's ``join`` lane entry, replacing the c7 proxy
    (per-anchor mean with a 4× p99 heuristic).

    The graph is locality-clustered — every link lands within a small
    id window of its subject — so anchored triangles genuinely close;
    anchors are sampled from a bounded co-degree band (c7's honesty
    rule: the device-servable population, hub monsters route to host in
    production). A ``base_n`` subset is differentially verified against
    the exact host join engine (``join/host.host_join``).

    The write side is COMPACTION-PACED: the join lane's exact-at-collect
    discipline host-routes every batch while a non-trivial dirty
    memtable is outstanding (a memtable link can mint bindings anywhere
    in the tuple space — only a compaction swap makes the device base
    whole again), so the writer requests a compaction after each ingest
    batch and waits for the swap, the deployment posture a join-heavy
    service actually runs. The dirty windows still land inside the
    measured distribution — ``host_fallbacks`` in the record says how
    much of the load they carried.

    Env knobs: BENCH_C11_ENTITIES / _LINKS (graph scale), _REQUESTS,
    _OFFERED_QPS, _DEADLINE_S, _WINDOW (link locality), _MAX_DEG
    (anchor co-degree band), _INGEST_BATCHES / _BATCH_LINKS /
    _INGEST_GAP_S, _BASELINE_N, _QUEUE, _LINGER_S, _PAD, _TAG."""
    _bench_entry_env()
    import threading

    from hypergraphdb_tpu import HyperGraph, join
    from hypergraphdb_tpu.query import conditions as qc
    from hypergraphdb_tpu.query.variables import var
    from hypergraphdb_tpu.serve import DeadlineExceeded, ServeConfig, \
        ServeRuntime

    _telemetry_begin()
    n_entities = int(os.environ.get("BENCH_C11_ENTITIES", 100_000))
    n_links = int(os.environ.get("BENCH_C11_LINKS", 300_000))
    n_requests = int(os.environ.get("BENCH_C11_REQUESTS", 2048))
    offered_qps = float(os.environ.get("BENCH_C11_OFFERED_QPS", 200.0))
    deadline_s = float(os.environ.get("BENCH_C11_DEADLINE_S", 5.0))
    window = int(os.environ.get("BENCH_C11_WINDOW", 16))
    max_deg = int(os.environ.get("BENCH_C11_MAX_DEG", 64))
    stream_batches = int(os.environ.get("BENCH_C11_INGEST_BATCHES", 8))
    batch_links = int(os.environ.get("BENCH_C11_BATCH_LINKS", 2_000))
    ingest_gap_s = float(os.environ.get("BENCH_C11_INGEST_GAP_S", 0.2))
    base_n = min(int(os.environ.get("BENCH_C11_BASELINE_N", 64)),
                 n_requests)

    g = HyperGraph()
    r = np.random.default_rng(37)
    entities = g.bulk_import(values=np.arange(n_entities).tolist())
    e0 = int(entities[0])
    # locality-clustered links: objects within `window` ids of their
    # subject, so two co-neighbours of an anchor are themselves likely
    # linked — the triangle-closing structure a pure-uniform graph
    # (expected triangle count ~0 at this density) cannot provide
    deg = np.zeros(n_entities, dtype=np.int64)
    for s in range(0, n_links, 100_000):
        m = min(100_000, n_links - s)
        subj = r.integers(0, n_entities, size=m)
        obj = (subj + r.integers(1, window + 1, size=m)) % n_entities
        g.bulk_import(
            values=[int(1_000_000 + s + x) for x in range(m)],
            target_lists=[[e0 + int(a), e0 + int(b)]
                          for a, b in zip(subj, obj)],
        )
        np.add.at(deg, subj, 1)
        np.add.at(deg, obj, 1)
    mgr = g.enable_incremental(
        headroom=1.8, background=True, delta_bucket_min=1 << 14,
        pack_pad_multiple=int(os.environ.get("BENCH_C11_PAD", 1 << 16)),
    )

    # anchors: the bounded co-degree band (c7's device-servable rule) —
    # enough incidence that the triangle does real intersection work,
    # not so much that one hub row floods every dispatch
    cand = np.flatnonzero((deg >= 2) & (deg <= max_deg))
    if not len(cand):
        raise RuntimeError("c11: no anchor in the co-degree band; "
                           "raise BENCH_C11_MAX_DEG")
    anchors = [e0 + int(a)
               for a in cand[r.integers(0, len(cand), size=n_requests)]]

    def spec(a: int) -> dict:
        # anchored triangle, the SHAPES["triangle"] idiom: a–y, y–z, z–a
        return {"y": qc.And(qc.CoIncident(a), qc.CoIncident(var("z"))),
                "z": qc.CoIncident(a)}

    cfg = ServeConfig(
        buckets=(16, 64, 256),
        max_queue=int(os.environ.get("BENCH_C11_QUEUE", 8192)),
        max_linger_s=float(os.environ.get("BENCH_C11_LINGER_S", 0.002)),
        top_r=16, prewarm_aot=False,
    )
    rt = ServeRuntime(g, cfg)

    # warm every bucket shape off the clock (compile at deploy time)
    for b in cfg.buckets:
        warm = [rt.submit_join(spec(anchors[j % n_requests]))
                for j in range(b)]
        for f in warm:
            f.result(timeout=600)
    rt.stats.reset()
    ingested = {"done": False, "atoms": 0, "s": 0.0}

    def writer():
        t0 = time.perf_counter()
        v = 10_000_000
        for _ in range(stream_batches):
            subj = r.integers(0, n_entities, size=batch_links)
            obj = (subj + r.integers(1, window + 1, size=batch_links)) \
                % n_entities
            g.bulk_import(
                values=[int(v + x) for x in range(batch_links)],
                target_lists=[[e0 + int(a), e0 + int(b)]
                              for a, b in zip(subj, obj)],
            )
            v += batch_links
            ingested["atoms"] += batch_links
            # compaction-paced: swap the device base after every batch
            # so the join lane's dirty-memtable host window stays
            # bounded — the ratio-triggered path would leave the whole
            # run host-served at smoke scale (the +4096-edge floor)
            mgr._request_compact()
            mgr.wait_compacted(timeout=120)
            if ingest_gap_s > 0:
                time.sleep(ingest_gap_s)
        ingested["s"] = time.perf_counter() - t0
        ingested["done"] = True

    wt = threading.Thread(target=writer)
    wt.start()
    gaps = r.exponential(1.0 / offered_qps, size=n_requests)
    futs = []
    t0 = time.perf_counter()
    next_t = t0
    for i in range(n_requests):
        next_t += gaps[i]
        pause = next_t - time.perf_counter()
        if pause > 0:
            time.sleep(pause)
        futs.append(rt.submit_join(spec(anchors[i]),
                                   deadline_s=deadline_s))
    served = shed = 0
    counts = []
    for f in futs:
        try:
            res = f.result(timeout=300)
            counts.append(int(res.count))
            served += 1
        except DeadlineExceeded:
            counts.append(-1)
            shed += 1
    wall = time.perf_counter() - t0
    wt.join()
    s = rt.stats_snapshot()

    # -- differential verdict: a FRESH post-settle probe batch (the
    # open-loop counts were recorded mid-ingest; their truth moved under
    # them, so equality there would be luck, not a check). The lane's
    # exact binding COUNT (pre-truncation, so this holds whatever top_r
    # sliced) vs the host join engine, both on the settled graph.
    probe_futs = [(a, rt.submit_join(spec(a))) for a in anchors[:base_n]]
    diff_equal = True
    diffs = []
    checked = 0
    for a, f in probe_futs:
        res = f.result(timeout=300)
        truth = join.host_join(g, join.extract_pattern(g, spec(a)))
        if res.count != len(truth):
            diff_equal = False
            if len(diffs) < 5:
                diffs.append([int(a), int(res.count), len(truth)])
        checked += 1

    # -- host baseline: the same anchored triangle answered by the exact
    # host join engine (what a caller paid without the serving tier)
    def host_window():
        t0 = time.perf_counter()
        for i in range(base_n):
            join.host_join(g, join.extract_pattern(g, spec(anchors[i])))
        return base_n / (time.perf_counter() - t0)

    host_qps = best_of(host_window, n=2)
    rt.close(drain=True, timeout=120)
    telemetry = _telemetry_dump(
        "c11", registries=[rt.stats.registry, g.metrics.registry]
    )
    g.close()
    served_qps = served / wall if wall else 0.0
    out = {
        "entities": n_entities,
        "links": n_links,
        "requests": n_requests,
        "offered_qps": round(offered_qps, 1),
        "served_qps": round(served_qps, 1),
        "served": served,
        "shed_deadline": shed,
        "deadline_s": deadline_s,
        "host_join_qps": round(host_qps, 1),
        "device_vs_host": (
            round(served_qps / host_qps, 2) if host_qps else None
        ),
        "batches": s["batches"],
        "device_dispatches": s["device_dispatches"],
        "batch_occupancy": (
            round(s["batch_occupancy"], 3)
            if s["batch_occupancy"] is not None else None
        ),
        "latency_ms_p50": (
            round(s["latency_ms"]["p50"], 2)
            if s["latency_ms"]["p50"] is not None else None
        ),
        "latency_ms_p99": (
            round(s["latency_ms"]["p99"], 2)
            if s["latency_ms"]["p99"] is not None else None
        ),
        "host_fallbacks": s["host_fallbacks"],
        "concurrent_ingest_atoms_per_sec": round(
            ingested["atoms"] / ingested["s"], 1
        ) if ingested["s"] else None,
        "bindings_total": int(sum(x for x in counts if x > 0)),
        "differential_probes": checked,
        "differential_equal": diff_equal,
        "backend": _backend_name(),
    }
    if diffs:
        out["differential_diff"] = diffs
    if telemetry:
        out["tracing"] = telemetry["sampling"]
        out["telemetry"] = telemetry
    out["recorded_to"] = _record_bench("c11_join", out)
    return out


# ------------------------------------------------------------- bench records

#: committed envelope schema for every ``BENCH_C*_<tag>.json`` record.
#: One envelope — ``schema_version`` / ``tag`` / ``backend`` /
#: ``git_rev`` / ``recorded_unix`` wrapping a single ``<config_key>``
#: payload — shared by every writer (c6/c7/c8/c9 used to carry four
#: copy-pasted writers that could drift). v2 added ``git_rev`` so a
#: recorded curve names the code that produced it; the reader accepts
#: v1 too (the committed smokes stay readable).
BENCH_SCHEMA_VERSION = 2
BENCH_SCHEMA_ACCEPTED = (1, 2)

#: the recorded configs: payload key -> (tag env knob, file prefix)
BENCH_RECORDED = {
    "c6_serving": ("BENCH_C6_TAG", "BENCH_C6"),
    "c7_pattern_join": ("BENCH_C7_TAG", "BENCH_C7"),
    "c8_sharded": ("BENCH_C8_TAG", "BENCH_C8"),
    "c9_value_index": ("BENCH_C9_TAG", "BENCH_C9"),
    "c10_pattern": ("BENCH_C10_TAG", "BENCH_C10"),
    "c11_join": ("BENCH_C11_TAG", "BENCH_C11"),
}


def _git_rev() -> Optional[str]:
    """Short git revision of this checkout, or None (tarball installs,
    no git binary) — best-effort provenance, never a failure."""
    import subprocess

    try:
        proc = subprocess.run(
            ["git", "rev-parse", "--short=12", "HEAD"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=10,
        )
    except Exception:  # noqa: BLE001 - provenance is optional
        return None
    rev = proc.stdout.strip()
    return rev if proc.returncode == 0 and rev else None


def _record_dir() -> str:
    """Where records land: next to this file, or ``BENCH_RECORD_DIR``
    (tests and read-only-checkout CI point it at a scratch dir)."""
    return (os.environ.get("BENCH_RECORD_DIR")
            or os.path.dirname(os.path.abspath(__file__)))


def _record_bench(config_key: str, result: dict) -> Optional[str]:
    """Persist one config's numbers in the ONE committed envelope to
    ``<prefix>_<tag>.json`` (tag from the config's env knob, default
    ``local``). Best-effort: an unwritable checkout (read-only CI,
    site-packages) must not discard the minutes-long run it is trying
    to record. Returns the basename written, or None."""
    tag_env, prefix = BENCH_RECORDED[config_key]
    tag = os.environ.get(tag_env, "local")
    path = os.path.join(_record_dir(), f"{prefix}_{tag}.json")
    record = {
        "schema_version": BENCH_SCHEMA_VERSION,
        "recorded_unix": int(time.time()),
        "tag": tag,
        "backend": _backend_name(),
        "git_rev": _git_rev(),
        config_key: {k: v for k, v in result.items()
                     if k not in ("telemetry", "recorded_to")},
    }
    try:
        with open(path, "w") as f:
            json.dump(record, f, indent=2, sort_keys=True)
            f.write("\n")
    except OSError as e:
        import sys

        print(f"bench: could not write {path}: {e}", file=sys.stderr)
        return None
    return os.path.basename(path)


def read_bench(path: str) -> dict:
    """The version-checking reader for recorded bench files: rejects
    unknown schema versions and envelopes missing the committed keys or
    carrying anything but exactly one known config payload — ``--diff``
    must never compare shapes it merely guessed."""
    with open(path) as f:
        record = json.load(f)
    v = record.get("schema_version")
    if v not in BENCH_SCHEMA_ACCEPTED:
        raise ValueError(
            f"{path}: bench schema {v!r} not in {BENCH_SCHEMA_ACCEPTED}"
        )
    for key in ("tag", "backend", "recorded_unix"):
        if key not in record:
            raise ValueError(f"{path}: bench record missing {key!r}")
    keys = [k for k in record if k in BENCH_RECORDED]
    if len(keys) != 1:
        raise ValueError(
            f"{path}: expected exactly one config payload, found {keys}"
        )
    return record


def bench_payload(record: dict) -> tuple:
    """(config_key, payload) of a :func:`read_bench` record."""
    key = next(k for k in record if k in BENCH_RECORDED)
    return key, record[key]


# ------------------------------------------------------------- bench --diff

#: metric direction by dotted-name match: throughput/efficiency up is
#: good, time/lag up is bad; everything else (counts, scale knobs,
#: verdict booleans) is comparison CONTEXT, not a gated metric
_HIGHER_MARKS = ("per_sec", "qps", "ratio", "_vs_", "speedup", "gbps",
                 "occupancy", "edges_per")
_LOWER_MARKS = ("latency", "seconds", "_lag")
_LOWER_SUFFIXES = ("_s", "_ms")

#: config KNOBS that would otherwise match a direction rule — a
#: deliberately changed deadline or offered load must read as comparison
#: context, not a perf regression (offered_qps is the INPUT rate the
#: open-loop configs were driven at; served_qps is the measurement)
_INFO_SEGMENTS = ("deadline_s", "offered_qps")


def _metric_direction(name: str) -> str:
    """Direction of one flattened dotted path. Matched per SEGMENT:
    ``triangle.vs_host`` is a higher-is-better ratio (the full-path
    ``startswith("vs_")`` would never see past the dot), while the
    lower-is-better seconds suffix applies to the FINAL segment only
    (``cold_start_s.entities`` is a count under a timing dict, not a
    timing)."""
    segments = name.lower().split(".")
    if segments[-1] in _INFO_SEGMENTS:
        return "info"
    for seg in segments:
        if any(m in seg for m in _HIGHER_MARKS) or seg.startswith("vs_"):
            return "higher"
    last = segments[-1]
    if (any(m in last for m in _LOWER_MARKS)
            or last.endswith(_LOWER_SUFFIXES)):
        return "lower"
    return "info"


def _flatten_scalars(payload, prefix: str = "") -> dict:
    """{dotted path: scalar} over nested dicts/lists — the leaves
    ``--diff`` compares. Booleans ride along (context equality, never a
    direction-gated metric)."""
    out: dict = {}
    if isinstance(payload, dict):
        items = payload.items()
    elif isinstance(payload, (list, tuple)):
        items = ((str(i), v) for i, v in enumerate(payload))
    else:
        items = ()
    for k, v in items:
        name = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, (dict, list, tuple)):
            out.update(_flatten_scalars(v, name))
        elif isinstance(v, (bool, int, float)):
            out[name] = v
    return out


def bench_diff(path_a: str, path_b: str, tolerance: float = 0.25) -> dict:
    """Per-metric regression verdict between two recorded bench files
    (A = reference, B = candidate): every shared numeric leaf is
    classified by direction and compared under ``tolerance`` (relative;
    0.25 = B may be up to 25% worse before it counts as regressed —
    generous by default because the CPU smokes are noisy; a real-TPU
    sweep passes its own). Cross-backend diffs are allowed — comparing
    a TPU run against the committed CPU smoke is exactly the "is the
    CPU smoke lying" question — but flagged ``backend_differs`` so the
    verdict is read with that in mind. Info leaves (scale knobs,
    counts, verdict booleans) that differ are listed as
    ``context_mismatch``: the perf verdict still computes, the caller
    decides whether the runs were comparable."""
    a, b = read_bench(path_a), read_bench(path_b)
    key_a, pay_a = bench_payload(a)
    key_b, pay_b = bench_payload(b)
    if key_a != key_b:
        raise ValueError(
            f"config mismatch: {path_a} records {key_a}, "
            f"{path_b} records {key_b}"
        )
    flat_a = _flatten_scalars(pay_a)
    flat_b = _flatten_scalars(pay_b)
    metrics: dict = {}
    regressed: list = []
    improved: list = []
    context: list = []
    for name in sorted(set(flat_a) & set(flat_b)):
        va, vb = flat_a[name], flat_b[name]
        direction = _metric_direction(name)
        if (direction == "info" or isinstance(va, bool)
                or isinstance(vb, bool)):
            if va != vb:
                context.append(name)
            continue
        entry = {"a": va, "b": vb, "direction": direction}
        if va == 0:
            entry["verdict"] = "ok" if vb == 0 else "incomparable"
        else:
            change = (vb - va) / abs(va)
            entry["change"] = round(change, 4)
            if direction == "lower":
                verdict = ("regressed" if vb > va * (1 + tolerance)
                           else "improved" if vb < va * (1 - tolerance)
                           else "ok")
            else:
                verdict = ("regressed" if vb < va * (1 - tolerance)
                           else "improved" if vb > va * (1 + tolerance)
                           else "ok")
            entry["verdict"] = verdict
            if verdict == "regressed":
                regressed.append(name)
            elif verdict == "improved":
                improved.append(name)
        metrics[name] = entry
    return {
        "config": key_a,
        "a": {"path": path_a, "tag": a["tag"], "backend": a["backend"],
              "git_rev": a.get("git_rev")},
        "b": {"path": path_b, "tag": b["tag"], "backend": b["backend"],
              "git_rev": b.get("git_rev")},
        "tolerance": tolerance,
        "backend_differs": a["backend"] != b["backend"],
        "context_mismatch": context,
        "metrics": metrics,
        "regressed": regressed,
        "improved": improved,
        "verdict": "regressed" if regressed else "ok",
    }


def _diff_main(argv: list) -> int:
    """``bench.py --diff A.json B.json [--diff-tolerance 0.25]``:
    prints the verdict JSON; exit 0 clean, 1 on any regressed metric,
    2 on usage/unreadable/mismatched inputs — the CI gate contract
    (``tools/perf.sh``) and the real-TPU sweep's comparison tool."""
    import sys

    i = argv.index("--diff")
    paths = []
    tolerance = 0.25
    rest = argv[i + 1:]
    j = 0
    while j < len(rest):
        arg = rest[j]
        if arg == "--diff-tolerance":
            if j + 1 >= len(rest):
                print("bench --diff: --diff-tolerance needs a value",
                      file=sys.stderr)
                return 2
            try:
                tolerance = float(rest[j + 1])
            except ValueError:
                print(f"bench --diff: bad tolerance {rest[j + 1]!r}",
                      file=sys.stderr)
                return 2
            j += 2
            continue
        if arg.startswith("-"):
            # a mistyped flag must not silently gate at the defaults
            print(f"bench --diff: unknown flag {arg!r} "
                  "(did you mean --diff-tolerance?)", file=sys.stderr)
            return 2
        paths.append(arg)
        j += 1
    if len(paths) != 2:
        print("usage: bench.py --diff A.json B.json "
              "[--diff-tolerance 0.25]", file=sys.stderr)
        return 2
    try:
        report = bench_diff(paths[0], paths[1], tolerance)
    except (OSError, ValueError) as e:
        print(f"bench --diff: {e}", file=sys.stderr)
        return 2
    print(json.dumps(report, indent=2, sort_keys=True))
    return 1 if report["regressed"] else 0


def _seed_baseline_main(argv: list) -> int:
    """``bench.py --seed-baseline [out.json]``: seed the hgperf runtime
    baseline (``PERF_BASELINE.json``) from the recorded bench files —
    scanned next to this script AND under ``BENCH_RECORD_DIR`` (where a
    read-only-checkout run just recorded), newest record per config
    winning, so a fresh real-hardware sweep beats the committed
    smokes."""
    import sys

    from hypergraphdb_tpu.obs.perf import BASELINE_FILENAME, seed_baseline

    i = argv.index("--seed-baseline")
    flags = [a for a in argv[i + 1:] if a.startswith("-")]
    if flags:
        # same contract as --diff: a mistyped flag must not silently
        # seed with the defaults
        print(f"bench --seed-baseline: unknown flag {flags[0]!r}",
              file=sys.stderr)
        return 2
    rest = list(argv[i + 1:])
    here = os.path.dirname(os.path.abspath(__file__))
    out = rest[0] if rest else os.path.join(_record_dir(),
                                            BASELINE_FILENAME)
    record = seed_baseline((here, _record_dir()), out_path=out)
    print(json.dumps({"wrote": out, "lanes": sorted(record["lanes"]),
                      "source": record["source"]}, sort_keys=True))
    return 0 if record["lanes"] else 1


def _backend_name() -> str:
    try:
        import jax

        return jax.devices()[0].platform
    except Exception:
        return "unknown"


def _with_telemetry(name: str, fn) -> dict:
    """Run one config with hgobs tracing when --telemetry is active.
    Configs that own a graph or runtime dump their private registries
    from inside (c2/c5: `g.metrics.registry`; c6: runtime + graph); this
    wrapper's fallback dump covers the kernel-level global registry and
    the trace buffer for the snapshot-only configs (c3/c4)."""
    _telemetry_begin()
    out = fn()
    if "telemetry" not in out:
        # only when the config did NOT dump for itself — re-dumping here
        # would overwrite its files with the global-only view and an
        # already-drained (empty) trace buffer
        t = _telemetry_dump(name)
        if t:
            out["telemetry"] = t
    return out


def _config_c2() -> dict:
    _bench_entry_env()
    return _with_telemetry("c2", bench_c2)


def _config_c3() -> dict:
    _bench_entry_env()
    snap, info, _ = _build_10m()
    return _with_telemetry("c3", lambda: bench_c3(snap, info))


def _config_c4() -> dict:
    _bench_entry_env()
    snap, info, build_s = _build_10m()
    out = _with_telemetry("c4", lambda: bench_c4(snap, info))
    out["_graph"] = {
        "n_atoms": info["n_atoms"],
        "total_arity": info["total_arity"],
        "build_s": round(build_s, 1),
    }
    return out


def _config_c5() -> dict:
    _bench_entry_env()
    return _with_telemetry("c5", bench_c5)


def _config_c6() -> dict:
    _bench_entry_env()
    return bench_c6()


def _config_c7() -> dict:
    _bench_entry_env()
    snap, info, _ = _build_10m()
    return _with_telemetry("c7", lambda: bench_c7(snap, info))


def _config_c8() -> dict:
    _bench_entry_env()
    return _with_telemetry("c8", bench_c8)


def _config_c9() -> dict:
    _bench_entry_env()
    return _with_telemetry("c9", bench_c9)


def _config_c10() -> dict:
    _bench_entry_env()
    return _with_telemetry("c10", bench_c10)


def _config_c11() -> dict:
    _bench_entry_env()
    return _with_telemetry("c11", bench_c11)


def _run_isolated(name: str) -> dict:
    """Run one config in a FRESH python subprocess.

    Why process isolation: measured head-to-head, the identical exec
    window runs the c3 pattern kernel at ~11.2M q/s in a fresh process and
    ~95K q/s after EITHER c2's or c4's scan-heavy executables have been on
    the chip — small-kernel launch latency degrades ~100× for the rest of
    the process even with all buffers freed, and in-process ordering can
    only protect ONE config. Each config now gets pristine launch state;
    the duplicated 10M build is absorbed by the persistent XLA-compile and
    plan caches."""
    import subprocess
    import sys

    code = (
        "import json, bench\n"
        f"r = bench._config_{name}()\n"
        "print('BENCH_RESULT ' + json.dumps(r), flush=True)\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True,
        cwd=os.path.dirname(os.path.abspath(__file__)),
        timeout=int(os.environ.get("BENCH_CONFIG_TIMEOUT_S", 1800)),
    )
    for line in proc.stdout.splitlines():
        if line.startswith("BENCH_RESULT "):
            return json.loads(line[len("BENCH_RESULT "):])
    raise RuntimeError(
        f"config {name} subprocess failed (rc={proc.returncode}):\n"
        f"{proc.stderr[-4000:]}"
    )


def main() -> None:
    import sys

    if "--diff" in sys.argv:
        # comparison tool, not a run: never touches a device
        sys.exit(_diff_main(sys.argv[1:]))
    if "--seed-baseline" in sys.argv:
        sys.exit(_seed_baseline_main(sys.argv[1:]))
    _bench_entry_env()
    if "--telemetry" in sys.argv:
        # optional positional dir after the flag; default: next to results
        i = sys.argv.index("--telemetry")
        out_dir = (sys.argv[i + 1] if len(sys.argv) > i + 1
                   and not sys.argv[i + 1].startswith("-")
                   else os.path.dirname(os.path.abspath(__file__)))
        os.makedirs(out_dir, exist_ok=True)
        # env so the per-config subprocesses inherit the switch; absolute
        # because _run_isolated children run with cwd=bench.py's dir, not
        # the caller's
        os.environ[TELEMETRY_ENV] = os.path.abspath(out_dir)
    if os.environ.get("BENCH_ISOLATE", "1") != "0":
        c3 = _run_isolated("c3")
        c4 = _run_isolated("c4")
        c2 = _run_isolated("c2")
        c5 = _run_isolated("c5")
        c6 = _run_isolated("c6")
        c7 = _run_isolated("c7")
        c8 = _run_isolated("c8")
        c9 = _run_isolated("c9")
        c10 = _run_isolated("c10")
        c11 = _run_isolated("c11")
        graph = c4.pop("_graph")
    else:  # legacy in-process path (BENCH_ISOLATE=0): order still matters
        # c6's cold-start probe BEFORE any config initializes the device
        # in this process — its fresh subprocesses must own the
        # single-client TPU (same rule as the isolated path, where each
        # config's subprocess starts clean)
        cold = _cold_start_probe()
        snap, info, build_s = _build_10m()
        c3 = _with_telemetry("c3", lambda: bench_c3(snap, info))
        snap.__dict__.pop("device", None)  # cached_property storage
        for attr in ("_tgt_ell", "_value_cols"):
            if hasattr(snap, attr):
                object.__delattr__(snap, attr)
        c4 = _with_telemetry("c4", lambda: bench_c4(snap, info))
        c2 = _with_telemetry("c2", bench_c2)
        c5 = _with_telemetry("c5", bench_c5)
        c6 = bench_c6(cold=cold)
        c7 = _with_telemetry("c7", lambda: bench_c7(snap, info))
        c8 = _with_telemetry("c8", bench_c8)
        c9 = _with_telemetry("c9", bench_c9)
        c10 = _with_telemetry("c10", bench_c10)
        c11 = _with_telemetry("c11", bench_c11)
        graph = {
            "n_atoms": info["n_atoms"],
            "total_arity": info["total_arity"],
            "build_s": round(build_s, 1),
        }
    print(json.dumps({
        "metric": "bfs_3hop_4kseed_10m_edges_per_sec",
        "value": c4["edges_per_sec"],
        "unit": "edges/s",
        "vs_baseline": c4["vs_vectorized_host"],
        "configs": {
            "c2_bfs_2hop_120k": c2,
            "c3_pattern_10m": c3,
            "c4_bfs_3hop_10m": c4,
            "c5_streaming": c5,
            "c6_serving": c6,
            "c7_pattern_join": c7,
            "c8_sharded": c8,
            "c9_value_index": c9,
            "c10_pattern": c10,
            "c11_join": c11,
        },
        "graph": graph,
    }))


if __name__ == "__main__":
    main()
