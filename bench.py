"""Benchmark: batched 1K-seed 2-hop BFS frontier expansion on TPU.

BASELINE.md config 2 — WordNet-scale hypergraph (~120K atoms), 1024-seed
2-hop incident-atom BFS as CSR hyperedge message passing on one TPU core,
vs. the host pointer-chasing traversal engine (the stand-in for the
reference's bdb-je CPU backend, ``HGBreadthFirstTraversal.java:49-66``).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import time

import numpy as np


def build_graph(n_nodes: int = 80_000, n_links: int = 40_000, seed: int = 7):
    """Synthetic WordNet-shaped hypergraph: ~120K atoms, skewed-degree
    links of arity 2-5 (see ``models/generators.py``)."""
    from hypergraphdb_tpu import HyperGraph
    from hypergraphdb_tpu.models import zipf_hypergraph

    g = HyperGraph()
    nodes, _ = zipf_hypergraph(
        g, n_nodes=n_nodes, n_links=n_links, max_arity=5, seed=seed
    )
    return g, nodes


def host_edges_per_sec(g, seeds: list[int], max_hops: int) -> tuple[float, int]:
    """Host traversal engine baseline: drain BFS per seed, counting
    incidence edges examined (same workload measure as the device kernel)."""
    t0 = time.perf_counter()
    edges = 0
    for s in seeds:
        visited = {s}
        frontier = [s]
        for _ in range(max_hops):
            nxt = []
            for a in frontier:
                inc = g.get_incidence_set(a).array()
                edges += len(inc)
                for lk in inc.tolist():
                    for t in g.get_targets(lk):
                        t = int(t)
                        if t not in visited:
                            visited.add(t)
                            nxt.append(t)
            frontier = nxt
    dt = time.perf_counter() - t0
    return edges / dt, edges


def main() -> None:
    import jax
    import jax.numpy as jnp

    from hypergraphdb_tpu.ops.frontier import frontier_edge_counts
    from hypergraphdb_tpu.ops.snapshot import CSRSnapshot

    g, nodes = build_graph()
    snap = CSRSnapshot.pack(g)
    dev = snap.device

    K, HOPS = 1024, 2
    r = np.random.default_rng(123)
    seeds = r.choice(len(nodes), size=K, replace=False).astype(np.int32)
    seeds_dev = jnp.asarray(seeds + int(nodes[0]))

    # warmup/compile
    frontier_edge_counts(dev, seeds_dev, HOPS).block_until_ready()
    reps = 5
    t0 = time.perf_counter()
    for _ in range(reps):
        counts = frontier_edge_counts(dev, seeds_dev, HOPS)
    counts.block_until_ready()
    dt = (time.perf_counter() - t0) / reps
    total_edges = int(np.asarray(counts, dtype=np.int64).sum())
    device_eps = total_edges / dt

    # host baseline on a subsample, extrapolated per-edge
    host_seeds = [int(s) + int(nodes[0]) for s in seeds[:32]]
    host_eps, _ = host_edges_per_sec(g, host_seeds, HOPS)

    print(json.dumps({
        "metric": "bfs_2hop_1kseed_edges_per_sec",
        "value": round(device_eps, 1),
        "unit": "edges/s",
        "vs_baseline": round(device_eps / host_eps, 2) if host_eps else None,
    }))
    g.close()


if __name__ == "__main__":
    main()
