#!/usr/bin/env bash
# hgjoin gate: the conjunctive-pattern-join suite — the differential
# suite (device executor == host find_all truth across triangle / path /
# star / typed / link-variable shapes, truncation honesty, pad-lane
# garbage, seeds-mode global counting, mid-ingest memtable visibility
# through the serving lane), the query suites that own the compiler
# pushdown + bridge, then the c7 pattern-join bench in SMOKE mode
# (small graph, few anchors) proving the whole device pipeline runs
# green and records its device-vs-host ratio + differential verdict to
# BENCH_C7_smoke.json.
#
# Sits beside lint.sh (AST hazards), verify.sh (jaxpr ground truth +
# cost budgets — the two ops/join entries gate there), chaos.sh,
# obs.sh, perf.sh, and replica.sh: this one gates the join subsystem.
#
# Usage: tools/join.sh [extra pytest args]
#   tools/join.sh -k serve            # one area, fast local run
set -uo pipefail
cd "$(dirname "$0")/.."

JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest \
    tests/test_join.py \
    tests/test_query.py \
    tests/test_query_extensions.py \
    tests/test_serve_differential.py \
    -q -m 'not slow' -p no:cacheprovider "$@"
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "tools/join.sh: join tests failed (exit $rc)" >&2
    exit "$rc"
fi

# -- c7 smoke: the bench pipeline end to end at toy scale --------------------
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
BENCH_ENTITIES="${BENCH_ENTITIES:-30000}" \
BENCH_LINKS="${BENCH_LINKS:-120000}" \
BENCH_SEEDS="${BENCH_SEEDS:-64}" \
BENCH_C7_LANES="${BENCH_C7_LANES:-16}" \
BENCH_C7_REPS="${BENCH_C7_REPS:-2}" \
BENCH_C7_BASELINE_N="${BENCH_C7_BASELINE_N:-32}" \
BENCH_C7_TAG="${BENCH_C7_TAG:-smoke}" \
python - <<'PY'
import json

import bench

r = bench._config_c7()
for shape in ("triangle", "path2"):
    assert r[shape]["differential_equal"], (shape, r[shape])
    assert r[shape]["vs_host"] is not None, (shape, r[shape])
print("tools/join.sh c7 smoke:", json.dumps({
    s: {k: r[s][k] for k in ("vs_host", "bindings_total", "n_truncated",
                             "differential_equal")}
    for s in ("triangle", "path2")
}))
PY
smoke_rc=$?
if [ "$smoke_rc" -ne 0 ]; then
    echo "tools/join.sh: c7 smoke failed (exit $smoke_rc)" >&2
    exit "$smoke_rc"
fi
echo "tools/join.sh: join gate green"
exit 0
