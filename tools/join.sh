#!/usr/bin/env bash
# hgjoin gate: the conjunctive-pattern-join suite — the differential
# suite (device executor == host find_all truth across triangle / path /
# star / typed / link-variable shapes, the join-engine-v2 degree-split /
# bushy / factorized suites, truncation honesty, pad-lane garbage,
# seeds-mode global counting, mid-ingest memtable visibility incl. the
# per-lane partial correction), the query suites that own the compiler
# pushdown + bridge, a live serve smoke asserting hub-anchored joins
# dispatch on DEVICE (serve.join.hub_dispatches > 0) with exact results,
# then the c7 pattern-join bench in SMOKE mode (small graph, few
# anchors) proving the whole device pipeline — including the hub-heavy
# configuration's split-vs-PR10 and factorized-vs-flat differentials —
# runs green and records to BENCH_C7_smoke.json.
#
# Sits beside lint.sh (AST hazards), verify.sh (jaxpr ground truth +
# cost budgets — the four ops/join entries gate there), chaos.sh,
# obs.sh, perf.sh, and replica.sh: this one gates the join subsystem.
#
# Usage: tools/join.sh [extra pytest args]
#   tools/join.sh -k serve            # one area, fast local run
set -uo pipefail
cd "$(dirname "$0")/.."

JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest \
    tests/test_join.py \
    tests/test_query.py \
    tests/test_query_extensions.py \
    tests/test_serve_differential.py \
    -q -m 'not slow' -p no:cacheprovider "$@"
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "tools/join.sh: join tests failed (exit $rc)" >&2
    exit "$rc"
fi

# -- live hub smoke: degree-split lanes dispatch on device -------------------
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - <<'PY'
from tests.conftest import make_random_hypergraph
from hypergraphdb_tpu import HyperGraph, join
from hypergraphdb_tpu.query import conditions as c
from hypergraphdb_tpu.query.variables import var
from hypergraphdb_tpu.serve import ServeConfig, ServeRuntime

g = HyperGraph()
nodes, _ = make_random_hypergraph(g, n_nodes=80, n_links=160,
                                  max_arity=4, seed=7)
nodes = [int(n) for n in nodes]
hub = nodes[0]
for i in range(40):
    g.add_link([hub, nodes[1 + i % 70]], value=f"hub-{i}")
rt = ServeRuntime(g, ServeConfig(buckets=(4, 16), max_linger_s=0.001,
                                 top_r=512, join_hub_threshold=8))
try:
    spec = {"y": c.CoIncident(hub), "z": c.CoIncident(var("y"))}
    res = rt.submit_join(spec).result(timeout=120)
    truth = join.host_join(g, join.extract_pattern(g, spec))
    assert res.served_by == "device", res.served_by
    assert res.count == len(truth), (res.count, len(truth))
    got = sorted(tuple(int(v) for v in r) for r in res.tuples)
    assert got == (truth[:512] if res.truncated else truth)
    hub_lanes = rt.stats.join_hub_dispatches
    assert hub_lanes > 0, "hub lane did not dispatch on device"
finally:
    rt.close()
print("tools/join.sh hub smoke: serve.join.hub_dispatches =", hub_lanes,
      "differential_equal = True")
PY
hub_rc=$?
if [ "$hub_rc" -ne 0 ]; then
    echo "tools/join.sh: hub serve smoke failed (exit $hub_rc)" >&2
    exit "$hub_rc"
fi

# -- c7 smoke: the bench pipeline end to end at toy scale --------------------
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
BENCH_ENTITIES="${BENCH_ENTITIES:-30000}" \
BENCH_LINKS="${BENCH_LINKS:-120000}" \
BENCH_SEEDS="${BENCH_SEEDS:-64}" \
BENCH_C7_LANES="${BENCH_C7_LANES:-16}" \
BENCH_C7_REPS="${BENCH_C7_REPS:-2}" \
BENCH_C7_BASELINE_N="${BENCH_C7_BASELINE_N:-32}" \
BENCH_C7_TAG="${BENCH_C7_TAG:-smoke}" \
python - <<'PY'
import json

import bench

r = bench._config_c7()
for shape in ("triangle", "path2"):
    assert r[shape]["differential_equal"], (shape, r[shape])
    assert r[shape]["vs_host"] is not None, (shape, r[shape])
hub = r["hub_heavy"]
assert hub["differential_equal"], hub
assert hub["hub_lanes_dispatched"] > 0, hub
assert hub["factorized_equal"], hub
assert hub["split_vs_pr10"] >= 1.0, (
    "degree-split executor slower than the PR-10 path on the hub-heavy "
    "smoke", hub)
print("tools/join.sh c7 smoke:", json.dumps({
    **{s: {k: r[s][k] for k in ("vs_host", "bindings_total",
                                "n_truncated", "differential_equal")}
       for s in ("triangle", "path2")},
    "hub_heavy": {k: hub[k] for k in (
        "hub_lanes", "tail_lanes", "split_vs_pr10", "factorized_vs_flat",
        "factorized_equal", "differential_equal", "n_truncated")},
}))
PY
smoke_rc=$?
if [ "$smoke_rc" -ne 0 ]; then
    echo "tools/join.sh: c7 smoke failed (exit $smoke_rc)" >&2
    exit "$smoke_rc"
fi
echo "tools/join.sh: join gate green"
exit 0
