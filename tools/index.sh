#!/usr/bin/env bash
# hgindex gate: the device-side secondary value-index subsystem — the
# value-index differential suite (batched range/ordered/top-k == exact
# host oracle across pad-adjacent lanes, duplicate bounds, empty
# windows, mid-ingest delta/tombstone visibility, truncation prefixes,
# and the join value-window candidate filter), the query suites that own
# the bridge + compiler pushdown, and the serve differentials the range
# lane must not regress — then a LIVE smoke: the c9_value_index bench at
# toy scale asserting the device lane really dispatched, answered
# identically to the host oracle (differential_equal), ran at least as
# fast as the host value scan it replaces, and recorded its numbers to
# BENCH_C9_smoke.json (the shared _record_bench envelope, schema v2).
#
# Sits beside lint.sh, verify.sh (the two ops/value_index entries gate
# there), chaos.sh, obs.sh, perf.sh, replica.sh, join.sh, and shard.sh:
# this one gates the value-index subsystem.
#
# Usage: tools/index.sh [extra pytest args]
#   tools/index.sh -k topk            # one area, fast local run
set -uo pipefail
cd "$(dirname "$0")/.."

JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest \
    tests/test_value_index.py \
    tests/test_query.py \
    tests/test_value_pushdown.py \
    tests/test_serve_differential.py \
    -q -m 'not slow' -p no:cacheprovider "$@"
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "tools/index.sh: value-index tests failed (exit $rc)" >&2
    exit "$rc"
fi

# -- c9 smoke: the value-index serving pipeline end to end at toy scale ------
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
BENCH_C9_ENTITIES="${BENCH_C9_ENTITIES:-20000}" \
BENCH_C9_LINKS="${BENCH_C9_LINKS:-40000}" \
BENCH_C9_REQUESTS="${BENCH_C9_REQUESTS:-512}" \
BENCH_C9_BASELINE_N="${BENCH_C9_BASELINE_N:-64}" \
BENCH_C9_TAG="${BENCH_C9_TAG:-smoke}" \
python - <<'PY'
import json

import bench

r = bench.bench_c9()
assert r["differential_equal"], r
assert r["recorded_to"], r
# the device lane must have REALLY dispatched: a regression that routed
# every lane to the host fallback would be trivially differential-equal
assert r["range_dispatches"] > 0, r
ratio = r["device_vs_host_scan"]
assert ratio is not None, r
print("tools/index.sh c9 smoke:", json.dumps({
    k: r[k] for k in ("served_qps", "host_scan_qps",
                      "device_vs_host_scan", "range_dispatches",
                      "host_fallbacks", "differential_equal")
}))
if ratio < 1.0:
    # the acceptance target: the batched device lane >= the host value
    # scan it replaces, even on the CPU smoke (real chips only do
    # better)
    raise SystemExit(
        f"tools/index.sh: device/host-scan ratio {ratio} < 1.0")
PY
smoke_rc=$?
if [ "$smoke_rc" -ne 0 ]; then
    echo "tools/index.sh: c9 smoke failed (exit $smoke_rc)" >&2
    exit "$smoke_rc"
fi
echo "tools/index.sh: value-index gate green"
exit 0
