#!/usr/bin/env bash
# Multi-chip sharded-serving gate: the partitioned-storage owner-map
# suite (gid ranges move, find/count stay exact), the sharded-executor
# differential suite (sharded BFS/pattern/join == single-chip == host
# truth, incl. mid-ingest delta/tombstone visibility and truncation
# prefixes), the mesh kernel suite, and the single-chip serve
# differentials the sharded path must not regress — then a LIVE smoke:
# the c8_sharded bench on the forced 8-device CPU mesh, asserting the
# sharded path really dispatched, answered bit-identically to the
# single-chip path, and recorded its scaling curve to
# BENCH_C8_smoke.json (the shared _record_bench envelope, schema v2).
#
# Sits beside lint.sh, verify.sh (the two ops/sharded_serving entries
# gate there), chaos.sh, obs.sh, perf.sh, replica.sh, and join.sh: this
# one gates the multi-chip serving subsystem.
#
# Usage: tools/shard.sh [extra pytest args]
#   tools/shard.sh -k bfs             # one area, fast local run
set -uo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
case "${XLA_FLAGS:-}" in
  *xla_force_host_platform_device_count*) ;;
  *) export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8" ;;
esac

python -m pytest \
    tests/test_partitioned_storage.py \
    tests/test_sharded_serving.py \
    tests/test_parallel.py \
    tests/test_serve_differential.py \
    -q -m 'not slow' -p no:cacheprovider "$@"
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "tools/shard.sh: sharded-serving tests failed (exit $rc)" >&2
    exit "$rc"
fi

# -- c8 smoke: the sharded serving pipeline end to end at toy scale ----------
BENCH_C8_ENTITIES="${BENCH_C8_ENTITIES:-60000}" \
BENCH_C8_LINKS="${BENCH_C8_LINKS:-120000}" \
BENCH_C8_REQUESTS="${BENCH_C8_REQUESTS:-1024}" \
BENCH_C8_DEVICES="${BENCH_C8_DEVICES:-1,8}" \
BENCH_C8_TAG="${BENCH_C8_TAG:-smoke}" \
python - <<'PY'
import json

import bench

r = bench.bench_c8()
assert r["differential_equal"], r
assert r["recorded_to"], r
# the mesh path must have REALLY dispatched: a regression that silently
# routes "sharded" runs through the single-chip executor would be
# trivially differential-equal and could ride timing noise past the
# ratio check below
assert r["sharded_dispatches"] > 0, r
ratio = r["sharded_vs_single_chip"]
assert ratio is not None, r
print("tools/shard.sh c8 smoke:", json.dumps({
    k: r[k] for k in ("served_qps_per_device_count", "single_chip_qps",
                      "sharded_vs_single_chip", "sharded_dispatches",
                      "differential_equal")
}))
if ratio < 1.0:
    # the acceptance target: batched sharded serving >= the single-chip
    # path on the 8-virtual-device smoke (real chips only do better —
    # CPU "devices" share host cores)
    raise SystemExit(
        f"tools/shard.sh: sharded/single ratio {ratio} < 1.0")
PY
smoke_rc=$?
if [ "$smoke_rc" -ne 0 ]; then
    echo "tools/shard.sh: c8 smoke failed (exit $smoke_rc)" >&2
    exit "$smoke_rc"
fi
echo "tools/shard.sh: sharded-serving gate green"
exit 0
