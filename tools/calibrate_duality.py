"""Calibration sweep for the planner's duality constants (VERDICT r4
item 10): the zig-zag/merge size ratio in ``query/compiler.intersect_sorted``
and ``QueryConfig.device_min_batch`` gating host vs device intersections.

Run on the TPU host: ``python tools/calibrate_duality.py``. Prints a
machine-readable JSON block; the recorded run lives in ``CALIBRATION.md``
and the pinned constants cite it.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ID_SPACE = 10_000_000  # the 10M-atom graph's id space (BASELINE configs 3/4)


def _sorted_sample(rng, n: int) -> np.ndarray:
    return np.unique(rng.integers(0, ID_SPACE, size=int(n * 1.1)))[: n].astype(
        np.int64
    )


def _time(fn, reps: int = 5) -> float:
    fn()  # warm
    t0 = time.perf_counter()
    for _ in range(reps):
        fn()
    return (time.perf_counter() - t0) / reps


def sweep_zigzag() -> dict:
    """Crossover ratio where searchsorted probing beats np.intersect1d."""
    rng = np.random.default_rng(7)
    out = {}
    for small_n in (1_000, 10_000, 100_000):
        small = _sorted_sample(rng, small_n)
        rows = {}
        for ratio in (2, 4, 8, 16, 32, 64, 128, 256):
            large = _sorted_sample(rng, min(small_n * ratio, 9_000_000))
            if len(large) < small_n * ratio * 0.9:
                continue  # id space exhausted; ratio not reachable

            def probe():
                pos = np.minimum(
                    np.searchsorted(large, small), len(large) - 1
                )
                return small[large[pos] == small]

            def merge():
                return np.intersect1d(small, large, assume_unique=True)

            rows[ratio] = {
                "probe_ms": round(_time(probe) * 1e3, 3),
                "merge_ms": round(_time(merge) * 1e3, 3),
            }
        # first ratio where probing wins and keeps winning
        cross = None
        for r in sorted(rows):
            if rows[r]["probe_ms"] < rows[r]["merge_ms"]:
                if all(
                    rows[r2]["probe_ms"] <= rows[r2]["merge_ms"]
                    for r2 in rows if r2 >= r
                ):
                    cross = r
                    break
        out[small_n] = {"rows": rows, "crossover_ratio": cross}
    return out


def sweep_device_min_batch() -> dict:
    """Crossover size where the device intersection (incl. transfers)
    beats the host path, for a 2-way intersection with an 8× larger
    partner — the planner's gating shape (smallest child's estimate)."""
    import hypergraphdb_tpu.query.compiler as qc
    from hypergraphdb_tpu.ops.setops import device_intersect_sorted

    rng = np.random.default_rng(11)
    rows = {}
    for n in (64, 256, 1_024, 4_096, 16_384, 65_536, 262_144):
        a = _sorted_sample(rng, n)
        b = _sorted_sample(rng, min(n * 8, 8_000_000))

        host_ms = _time(lambda: qc.intersect_sorted(None, a, b)) * 1e3
        dev_ms = _time(lambda: device_intersect_sorted([a, b])) * 1e3
        rows[n] = {
            "host_ms": round(host_ms, 3),
            "device_ms": round(dev_ms, 3),
        }
    cross = None
    for n in sorted(rows):
        if rows[n]["device_ms"] < rows[n]["host_ms"]:
            cross = n
            break
    return {"rows": rows, "crossover_smallest_child": cross}


def sweep_value_conj() -> dict:
    """Crossover for the OTHER device_min_batch consumer: a single ad-hoc
    And(incident(hub), value) query through the snapshot-RESIDENT value
    kernel (DeviceValueConjPlan — no bulk upload per query, just a launch)
    vs the host fallback, at varying hub incidence size."""
    from hypergraphdb_tpu import HyperGraph
    from hypergraphdb_tpu.query import dsl as q
    from hypergraphdb_tpu.query.compiler import (
        DeviceValueConjPlan,
        compile_query,
    )

    g = HyperGraph()
    rng = np.random.default_rng(3)
    hubs = {}
    spokes = list(g.add_nodes_bulk([f"s{i}" for i in range(1024)]))
    for n in (1_024, 8_192, 65_536, 262_144):
        hub = g.add(f"hub{n}")
        g.bulk_import(
            values=[int(x) for x in rng.integers(0, 1000, size=n)],
            target_lists=[
                [int(hub), int(spokes[i % 1024])] for i in range(n)
            ],
        )
        hubs[n] = hub
    g.snapshot()  # resident base
    rows = {}
    cross = None
    for n, hub in hubs.items():
        cond = q.and_(q.incident(hub), q.value(500, "gt"))
        cq = compile_query(g, cond)
        assert isinstance(cq.plan, DeviceValueConjPlan)
        g.config.query.device_min_batch = 0        # force device
        dev_ms = _time(lambda: cq.plan.run(g), reps=3) * 1e3
        g.config.query.device_min_batch = 1 << 60  # force host fallback
        host_ms = _time(lambda: cq.plan.run(g), reps=3) * 1e3
        rows[n] = {
            "host_ms": round(host_ms, 3), "device_ms": round(dev_ms, 3),
        }
        if cross is None and dev_ms < host_ms:
            cross = n
    g.close()
    return {"rows": rows, "crossover_incidence": cross}


def sweep_parallel_or() -> dict:
    """Does the OrToParellelQuery-style thread pool actually buy anything
    for index-read children (VERDICT r4 weak #5: 'GIL mirage')? Or of 8
    by-value eq sets over a 400K-atom graph, parallel vs sequential."""
    from hypergraphdb_tpu import HyperGraph
    from hypergraphdb_tpu.query import dsl as q
    from hypergraphdb_tpu.query.compiler import compile_query

    g = HyperGraph()
    rng = np.random.default_rng(5)
    g.bulk_import(
        values=[int(x) for x in rng.integers(0, 8, size=400_000)]
    )
    cond = q.or_(*[q.eq(i) for i in range(8)])
    g.config.query.parallel_or = False
    seq = compile_query(g, cond)
    g.config.query.parallel_or = True
    par = compile_query(g, cond)
    seq_ms = _time(lambda: seq.plan.run(g), reps=3) * 1e3
    par_ms = _time(lambda: par.plan.run(g), reps=3) * 1e3
    g.close()
    return {
        "sequential_ms": round(seq_ms, 2),
        "parallel_ms": round(par_ms, 2),
        "parallel_speedup": round(seq_ms / par_ms, 2),
    }


def main() -> None:
    import jax

    report = {
        "platform": str(jax.devices()[0]),
        "zigzag": sweep_zigzag(),
        "device_min_batch": sweep_device_min_batch(),
        "value_conj": sweep_value_conj(),
        "parallel_or": sweep_parallel_or(),
    }
    print(json.dumps(report, indent=1))


if __name__ == "__main__":
    main()
