"""HG8xx — thread & resource lifecycle analysis.

The distributed runtime is thread-heavy (dispatch thread, apply worker,
activity ticker, router poll, perf sentinel) and review rounds kept
hand-finding the same lifecycle bug classes: a leaked profiler session
from a racing check-then-act, a `pump()` unwound by an unguarded hook
stranding its tickets, fire-and-forget threads nothing ever joins.  This
family checks the lifecycle contracts statically:

HG801  every started ``threading.Thread``/``Timer`` must be daemon or
       join/cancel-reachable (class slots: from *any* method — the
       stop()/close() path; locals: in the same function unless the
       thread object escapes).
HG802  a function-local closeable resource (``x = ctor()`` ...
       ``x.close()``) whose close is only on the straight-line path leaks
       on the exception edge — close in a ``finally``/``with``.
HG803  check-then-act on a lifecycle attribute (``if self._t is None:
       self._t = Thread(...); self._t.start()``) outside any lock in a
       lock-owning class — two racing starts leak a thread (the leaked
       profiler-session shape).
HG804  ``Condition.wait`` outside an enclosing loop — spurious wakeups
       and stolen predicates require the while-recheck idiom
       (``Event.wait`` is a latch and exempt).
HG805  a thread-target worker loop whose body can exit through an
       unguarded exception strands every in-flight future/ticket handed
       to it — guard the body (or the loop) with a broad handler that
       resolves them (the stranded-ticket shape).

Escape hatches: ``# hglint: disable=HG80x`` on the line (audited by
HG901), and the ``*_locked`` suffix exempts HG803 like every other
caller-holds-the-lock contract.
"""

from __future__ import annotations

import ast
from typing import Optional

from tools.hglint.callgraph import CallGraph
from tools.hglint.loader import resolve_fqn
from tools.hglint.model import Finding
from tools.hglint.rules_blocking import THREAD_CTORS, _SlotRegistry
from tools.hglint.rules_locks import (
    EXEMPT_METHODS,
    _collect_locks,
    _resolve_lock,
)

#: receiver methods that count as releasing/terminating a resource
CLOSE_METHODS = {"close", "stop", "shutdown", "cancel", "terminate"}

#: receiver methods that count as lifecycle transitions for HG803 —
#: ``join`` is deliberately absent: joining twice (or a dead thread) is
#: harmless, so check-then-join is not a race worth flagging
LIFECYCLE_ACTS = {"start", "stop", "close", "cancel", "shutdown"}

#: coordination calls a worker loop is EXPECTED to make between units of
#: work — waiting, queue/deque/heap shuffling, logging, introspection.
#: These don't raise in practice and flagging them would bury the real
#: signal (an unguarded handler/launch call) under noise.
_COORD_FUNCS = {
    "len", "list", "dict", "set", "tuple", "min", "max", "sorted",
    "int", "str", "float", "bool", "repr", "getattr", "hasattr",
    "isinstance", "enumerate", "zip", "range", "id", "hash", "print",
}
_COORD_METHODS = {
    "wait", "wait_for", "notify", "notify_all", "acquire", "release",
    "append", "appendleft", "pop", "popleft", "popitem", "add",
    "discard", "remove", "clear", "extend", "update", "setdefault",
    "get", "put", "get_nowait", "put_nowait", "items", "keys", "values",
    "heappush", "heappop", "is_set", "set", "is_alive",
    "monotonic", "time", "perf_counter", "sleep",
    "debug", "info", "warning", "error", "exception", "getLogger",
}


def check(cg: CallGraph, modules: list) -> list:
    slots = _SlotRegistry(cg, modules)
    reg = _collect_locks(modules)
    findings = []
    findings += _thread_lifecycle(cg)
    findings += _resource_exception_edges(cg)
    findings += _check_then_act(cg, reg)
    findings += _condition_wait_loops(cg, slots)
    findings += _worker_loops(cg)
    return findings


# ------------------------------------------------------------------- HG801


def _thread_lifecycle(cg: CallGraph) -> list:
    # class slots: (cls key, attr) -> state dict
    cls_slots: dict = {}
    for key, fi in cg.functions.items():
        if fi.cls_name is None:
            continue
        cls_key = f"{fi.mod.name}.{fi.cls_name}"
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    attr = _self_attr(tgt)
                    if attr is None:
                        continue
                    if isinstance(node.value, ast.Call):
                        kind = _thread_ctor_kind(node.value, fi.mod)
                        if kind is not None:
                            st = cls_slots.setdefault(
                                (cls_key, attr), _slot_state()
                            )
                            st["ctors"].append(
                                (fi, node.lineno, kind,
                                 _ctor_daemon(node.value))
                            )
                    # self.X.daemon = True
                    if isinstance(tgt, ast.Attribute) and \
                            tgt.attr == "daemon":
                        inner = _self_attr(tgt.value)
                        if inner is not None and not (
                            isinstance(node.value, ast.Constant)
                            and node.value.value is False
                        ):
                            cls_slots.setdefault(
                                (cls_key, inner), _slot_state()
                            )["daemon"] = True
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute):
                attr = _self_attr(node.func.value)
                if attr is None:
                    continue
                st = cls_slots.get((cls_key, attr))
                if st is None:
                    st = cls_slots.setdefault(
                        (cls_key, attr), _slot_state()
                    )
                if node.func.attr == "start":
                    st["started"] = True
                elif node.func.attr == "join":
                    st["joined"] = True
                elif node.func.attr == "cancel":
                    st["cancelled"] = True

    findings = []
    for (cls_key, attr), st in sorted(cls_slots.items()):
        if not st["ctors"] or not st["started"]:
            continue
        daemon = st["daemon"] or any(d for (_, _, _, d) in st["ctors"])
        kind = st["ctors"][0][2]
        ok = daemon or st["joined"] or \
            (kind == "timer" and st["cancelled"])
        if ok:
            continue
        fi, line, kind, _ = st["ctors"][0]
        fix = "cancel/join it" if kind == "timer" else "join it"
        findings.append(Finding(
            rule="HG801", path=fi.mod.path, line=line, scope=fi.qualpath,
            message=f"{kind} `self.{attr}` is started but neither daemon "
                    f"nor join/cancel-reachable from any method of "
                    f"`{cls_key.rsplit('.', 1)[-1]}` — a stop()/close() "
                    f"path must {fix} (or mark daemon=True)",
        ))

    # function-local fire-and-forget threads
    for key, fi in sorted(cg.functions.items()):
        findings += _local_threads(fi)
    return findings


def _slot_state() -> dict:
    return {"ctors": [], "started": False, "joined": False,
            "cancelled": False, "daemon": False}


def _local_threads(fi) -> list:
    locals_: dict = {}   # name -> (line, kind, daemon)
    state: dict = {}     # name -> {"started","joined","cancelled","escapes"}
    for node in _own_scope(fi.node):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                isinstance(node.value, ast.Call):
            kind = _thread_ctor_kind(node.value, fi.mod)
            if kind is not None:
                name = node.targets[0].id
                locals_[name] = (node.lineno, kind,
                                 _ctor_daemon(node.value))
                state[name] = {"started": False, "joined": False,
                               "cancelled": False, "escapes": False}
    if not locals_:
        return []
    safe_attrs = {"start", "join", "cancel", "daemon", "is_alive", "name",
                  "ident", "setDaemon"}
    parents = _parent_map(fi.node)
    for node in _own_scope(fi.node):
        if not isinstance(node, ast.Name) or node.id not in locals_:
            continue
        p = parents.get(id(node))
        if isinstance(p, ast.Attribute) and p.value is node:
            if p.attr not in safe_attrs:
                state[node.id]["escapes"] = True
            elif p.attr == "join":
                state[node.id]["joined"] = True
            elif p.attr == "cancel":
                state[node.id]["cancelled"] = True
            elif p.attr == "start":
                state[node.id]["started"] = True
        elif isinstance(p, ast.Assign) and node in p.targets:
            pass  # (re)binding, not a use
        elif isinstance(node.ctx, ast.Load):
            # any other load — argument, return, container, comparison —
            # lets the object escape this function's lifecycle view
            state[node.id]["escapes"] = True
    findings = []
    for name, (line, kind, daemon) in sorted(locals_.items()):
        st = state[name]
        if not st["started"] or st["escapes"] or daemon:
            continue
        if st["joined"] or (kind == "timer" and st["cancelled"]):
            continue
        findings.append(Finding(
            rule="HG801", path=fi.mod.path, line=line, scope=fi.qualpath,
            message=f"local {kind} `{name}` is started here but never "
                    f"joined (and not daemon) — a fire-and-forget "
                    f"{kind} outlives every shutdown path",
        ))
    return findings


def _thread_ctor_kind(call: ast.Call, mod) -> Optional[str]:
    fqn = resolve_fqn(call.func, mod)
    if fqn == "threading.Thread":
        return "thread"
    if fqn == "threading.Timer":
        return "timer"
    return None


def _ctor_daemon(call: ast.Call) -> bool:
    for k in call.keywords:
        if k.arg == "daemon":
            if isinstance(k.value, ast.Constant):
                return bool(k.value.value)
            return True   # computed daemon flag: assume the author chose
    return False


# ------------------------------------------------------------------- HG802


def _resource_exception_edges(cg: CallGraph) -> list:
    findings = []
    for key, fi in sorted(cg.functions.items()):
        acquires: dict = {}   # name -> (line, end_line, ctor spelling)
        closes: dict = {}     # name -> [close Call nodes]
        for node in _own_scope(fi.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.value, ast.Call):
                # single-name, tuple-unpack (``sock, addr = accept()``),
                # and attribute (``self._sock = socket(...)``) targets
                for name in _target_names(node.targets[0]):
                    if name not in acquires:
                        acquires[name] = (
                            node.lineno,
                            getattr(node, "end_lineno", node.lineno),
                            _spelling(node.value.func),
                        )
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in CLOSE_METHODS:
                recv = _receiver_name(node.func.value)
                if recv is not None:
                    closes.setdefault(recv, []).append(node)
        if not closes:
            continue
        protected_ids = _protected_node_ids(fi.node)
        with_ctx = _with_context_names(fi.node)
        for name, close_nodes in sorted(closes.items()):
            if name not in acquires or name in with_ctx:
                continue
            if any(id(c) in protected_ids for c in close_nodes):
                continue
            line, end_line, ctor = acquires[name]
            first_close = min(c.lineno for c in close_nodes)
            risky = any(
                isinstance(n, (ast.Call, ast.Raise, ast.Assert))
                and end_line < getattr(n, "lineno", 0) < first_close
                and not any(n is c or _contains(c, n)
                            for c in close_nodes)
                for n in _own_scope(fi.node)
            )
            if not risky:
                continue
            findings.append(Finding(
                rule="HG802", path=fi.mod.path, line=line,
                scope=fi.qualpath,
                message=f"resource `{name}` = `{ctor}(...)` is closed at "
                        f"line {first_close} only on the straight-line "
                        f"path — an exception in between leaks it; close "
                        f"in a finally (or use a with block)",
            ))
    return findings


def _target_names(tgt: ast.AST) -> list:
    """Assign target -> trackable resource names: ``s`` for a Name,
    each element of a tuple unpack, ``self._sock`` for an attribute."""
    if isinstance(tgt, ast.Name):
        return [tgt.id]
    if isinstance(tgt, (ast.Tuple, ast.List)):
        out = []
        for e in tgt.elts:
            out.extend(_target_names(e))
        return out
    recv = _receiver_name(tgt)
    return [recv] if recv is not None else []


def _receiver_name(expr: ast.AST) -> Optional[str]:
    """``s`` / ``self._sock`` -> a dotted tracking name (one attribute
    hop only: deeper chains are another object's lifecycle)."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name):
        return f"{expr.value.id}.{expr.attr}"
    return None


def _protected_node_ids(fn_node: ast.AST) -> set:
    """ids of nodes inside any try ``finally`` or ``except`` body — a
    close there runs on the exception edge."""
    ids: set = set()
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Try):
            for s in node.finalbody:
                ids.update(id(n) for n in ast.walk(s))
            for h in node.handlers:
                for s in h.body:
                    ids.update(id(n) for n in ast.walk(s))
    return ids


def _with_context_names(fn_node: ast.AST) -> set:
    names: set = set()
    for node in ast.walk(fn_node):
        if isinstance(node, ast.With):
            for item in node.items:
                ce = item.context_expr
                recv = _receiver_name(ce)
                if recv is not None:
                    names.add(recv)
                elif isinstance(ce, ast.Call):
                    for a in ce.args:
                        r = _receiver_name(a)
                        if r is not None:
                            names.add(r)   # closing(x) / ExitStack(x)
                if isinstance(item.optional_vars, ast.Name):
                    names.add(item.optional_vars.id)
    return names


def _contains(outer: ast.AST, inner: ast.AST) -> bool:
    return any(n is inner for n in ast.walk(outer))


# ------------------------------------------------------------------- HG803


def _check_then_act(cg: CallGraph, reg) -> list:
    # lifecycle attrs per class: assigned a Thread/Timer ctor anywhere, or
    # receiver of a .start() call
    lifecycle: dict = {}   # cls key -> {attr}
    for key, fi in cg.functions.items():
        if fi.cls_name is None:
            continue
        cls_key = f"{fi.mod.name}.{fi.cls_name}"
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                for tgt in node.targets:
                    attr = _self_attr(tgt)
                    if attr and _thread_ctor_kind(node.value, fi.mod):
                        lifecycle.setdefault(cls_key, set()).add(attr)
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "start":
                attr = _self_attr(node.func.value)
                if attr:
                    lifecycle.setdefault(cls_key, set()).add(attr)

    findings = []
    for key, fi in sorted(cg.functions.items()):
        if fi.cls_name is None:
            continue
        cls_key = f"{fi.mod.name}.{fi.cls_name}"
        if cls_key not in reg.class_attrs:
            continue   # no lifecycle lock exists; HG402 owns that story
        attrs = lifecycle.get(cls_key)
        if not attrs:
            continue
        method = fi.qualpath.rsplit(".", 1)[-1]
        if method in EXEMPT_METHODS or method.endswith("_locked"):
            continue
        hits: list = []
        _scan_cta(fi, fi.node, False, attrs, reg, hits)
        for attr, line in hits:
            findings.append(Finding(
                rule="HG803", path=fi.mod.path, line=line,
                scope=fi.qualpath,
                message=f"check-then-act on lifecycle attribute "
                        f"`self.{attr}` outside any lock — two racing "
                        f"callers both pass the check and double-start / "
                        f"double-stop; hold the lifecycle lock across "
                        f"check and act",
            ))
    return findings


def _scan_cta(fi, node, locked, attrs, reg, hits):
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef, ast.Lambda)) and node is not fi.node:
        return
    if isinstance(node, ast.With):
        now_locked = locked or any(
            _resolve_lock(item.context_expr, fi, reg) is not None
            for item in node.items
        )
        for stmt in node.body:
            _scan_cta(fi, stmt, now_locked, attrs, reg, hits)
        return
    if not locked and isinstance(node, ast.If):
        tested = {a for n in ast.walk(node.test)
                  if (a := _self_attr(n)) is not None} & attrs
        if tested:
            acted = _unlocked_acts(fi, node.body + node.orelse, attrs, reg)
            for attr in sorted(tested & acted):
                hits.append((attr, node.lineno))
    for child in ast.iter_child_nodes(node):
        _scan_cta(fi, child, locked, attrs, reg, hits)


def _unlocked_acts(fi, stmts, attrs, reg) -> set:
    """Lifecycle acts (start/stop/assign-thread) reached from ``stmts``
    WITHOUT passing a lock — an act under a nested ``with lock`` is the
    double-checked idiom and stays silent."""
    acted: set = set()

    def scan(node):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            return
        if isinstance(node, ast.With) and any(
            _resolve_lock(item.context_expr, fi, reg) is not None
            for item in node.items
        ):
            return   # locked region: safe by construction
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in LIFECYCLE_ACTS:
            attr = _self_attr(node.func.value)
            if attr in attrs:
                acted.add(attr)
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                attr = _self_attr(tgt)
                if attr in attrs and isinstance(node.value, ast.Call) and \
                        _thread_ctor_kind(node.value, fi.mod):
                    acted.add(attr)
        for child in ast.iter_child_nodes(node):
            scan(child)

    for s in stmts:
        scan(s)
    return acted


# ------------------------------------------------------------------- HG804


def _condition_wait_loops(cg: CallGraph, slots) -> list:
    findings = []
    for key, fi in sorted(cg.functions.items()):
        hits: list = []
        _scan_waits(fi, fi.node, False, slots, hits)
        for node in hits:
            findings.append(Finding(
                rule="HG804", path=fi.mod.path, line=node.lineno,
                scope=fi.qualpath,
                message=f"`{_spelling(node.func)}` outside a predicate "
                        f"re-check loop — Condition.wait can wake "
                        f"spuriously or lose the race for the predicate; "
                        f"wrap it in `while not <predicate>:`",
            ))
    return findings


def _scan_waits(fi, node, in_loop, slots, hits):
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef, ast.Lambda)) and node is not fi.node:
        return
    if isinstance(node, (ast.While, ast.For)):
        for child in ast.iter_child_nodes(node):
            _scan_waits(fi, child, True, slots, hits)
        return
    if isinstance(node, ast.Call) and \
            isinstance(node.func, ast.Attribute) and \
            node.func.attr == "wait" and not in_loop and \
            not node.args and not node.keywords:
        # only the UNTIMED wait: a timed `cv.wait(t)` outside a loop is a
        # bounded park (the caller re-checks on return by contract); an
        # untimed one outside a predicate loop hangs on a lost wakeup and
        # mis-runs on a spurious one
        if slots.receiver_kind(node.func.value, fi) == "condition":
            hits.append(node)
    for child in ast.iter_child_nodes(node):
        _scan_waits(fi, child, in_loop, slots, hits)


# ------------------------------------------------------------------- HG805


def _worker_loops(cg: CallGraph) -> list:
    targets = _thread_targets(cg)
    if not targets:
        return []
    # workers = targets plus everything they reach by direct call from an
    # UNGUARDED site (the loop often lives one helper down from the
    # target, but a helper only ever invoked from inside a broad
    # try/except can't kill the thread — its caller's guard absorbs it)
    edges = _unguarded_call_edges(cg)
    workers = set(targets)
    stack = list(targets)
    while stack:
        k = stack.pop()
        for c in edges.get(k, ()):
            if c not in workers:
                workers.add(c)
                stack.append(c)
    findings = []
    seen: set = set()
    for key in sorted(workers):
        fi = cg.functions.get(key)
        if fi is None:
            continue
        guarded = _broadly_guarded_ids(fi.node)
        for node in _own_scope(fi.node):
            if not isinstance(node, ast.While) or \
                    not _main_loop_shape(node):
                continue
            if id(node) in guarded:
                continue   # loop exit itself lands in a broad handler
            bad = _first_unguarded_call(node, guarded)
            if bad is None or (key, bad.lineno) in seen:
                continue
            seen.add((key, bad.lineno))
            findings.append(Finding(
                rule="HG805", path=fi.mod.path, line=bad.lineno,
                scope=fi.qualpath,
                message=f"worker loop in thread target `{fi.qualpath}` "
                        f"can exit through an unguarded exception from "
                        f"`{_spelling(bad.func)}` — in-flight "
                        f"futures/tickets handed to this loop are "
                        f"stranded; guard the loop body with a broad "
                        f"except that resolves them",
            ))
    return findings


def _unguarded_call_edges(cg: CallGraph) -> dict:
    """Direct call edges whose call SITE is outside every broad
    try/except of the caller — the edges an exception can actually
    travel back across to kill a worker thread."""
    guarded_by_fn: dict = {}
    edges: dict = {}
    for site in cg.calls:
        if site.fn_key is None:
            continue
        fi = cg.functions.get(site.fn_key)
        if fi is None:
            continue
        if site.fn_key not in guarded_by_fn:
            guarded_by_fn[site.fn_key] = _broadly_guarded_ids(fi.node)
        if id(site.node) in guarded_by_fn[site.fn_key]:
            continue
        callee = cg.resolve_callable(site.node.func, site)
        if callee is not None:
            edges.setdefault(site.fn_key, set()).add(callee)
    return edges


def _thread_targets(cg: CallGraph) -> set:
    targets: set = set()
    for site in cg.calls:
        fqn = resolve_fqn(site.node.func, site.mod)
        if fqn not in THREAD_CTORS:
            continue
        cands = [k.value for k in site.node.keywords
                 if k.arg in ("target", "function")]
        if fqn == "threading.Timer" and len(site.node.args) >= 2:
            cands.append(site.node.args[1])
        for c in cands:
            k = cg.resolve_callable(c, site)
            if k is not None:
                targets.add(k)
    return targets


def _main_loop_shape(node: ast.While) -> bool:
    """True for the service-loop shapes: ``while True`` and loops whose
    test reads instance state (``while not self._closed``) — data-drain
    loops (``while stack:``) are not lifecycle surfaces."""
    t = node.test
    if isinstance(t, ast.Constant) and t.value is True:
        return True
    if isinstance(t, ast.Compare):
        return False   # `while len(q) > cap:` — bounded drain, not a loop
    return any(_self_attr(n) is not None for n in ast.walk(t))


def _broadly_guarded_ids(fn_node: ast.AST) -> set:
    """ids of nodes inside a ``try`` body whose handlers include a broad
    (bare / Exception / BaseException) except."""
    ids: set = set()
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Try) and any(
            _is_broad_handler(h) for h in node.handlers
        ):
            # the handlers and finally are the recovery path itself — a
            # log call there re-raising is not the hazard this rule hunts
            for s in (node.body + node.finalbody
                      + [x for h in node.handlers for x in h.body]):
                ids.update(id(n) for n in ast.walk(s))
    return ids


def _is_broad_handler(h: ast.ExceptHandler) -> bool:
    if h.type is None:
        return True
    types = h.type.elts if isinstance(h.type, ast.Tuple) else [h.type]
    for t in types:
        name = t.attr if isinstance(t, ast.Attribute) else \
            (t.id if isinstance(t, ast.Name) else None)
        if name in ("Exception", "BaseException"):
            return True
    return False


def _first_unguarded_call(loop: ast.While, guarded: set):
    for stmt in loop.body:
        for n in ast.walk(stmt):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                continue
            if isinstance(n, ast.Call) and id(n) not in guarded and \
                    not _is_coordination(n):
                return n
    return None


def _is_coordination(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id in _COORD_FUNCS
    if isinstance(f, ast.Attribute):
        return f.attr in _COORD_METHODS
    return False


# ------------------------------------------------------------------ helpers


def _own_scope(fn_node: ast.AST):
    """Descendants of a function node excluding nested def/class scopes."""
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _parent_map(fn_node: ast.AST) -> dict:
    parents: dict = {}
    for node in ast.walk(fn_node):
        for child in ast.iter_child_nodes(node):
            parents[id(child)] = node
    return parents


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _spelling(func: ast.AST) -> str:
    try:
        return ast.unparse(func)
    except Exception:  # pragma: no cover
        return "<call>"
