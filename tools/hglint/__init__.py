"""hglint — AST-based JAX/TPU hazard analyzer for the hypergraphdb_tpu
codebase.

Four rule families (see ``tools.hglint.model.RULES``):

- HG1xx  host syncs reachable from traced (jit/pjit/shard_map/pallas) code
- HG2xx  retrace/recompile hazards
- HG3xx  Pallas kernel contracts ((8,128) tiling, index maps, dtypes)
- HG4xx  lock-order cycles and unlocked shared-state mutation

Run ``python -m tools.hglint <paths>``; the repo gate is
``tools/lint.sh`` (baseline-filtered, exits nonzero on new findings).
Pure AST analysis: target code is never imported or executed.
"""

from tools.hglint.engine import (
    apply_baseline,
    baseline_counts,
    load_baseline,
    run_lint,
    summarize,
    write_baseline,
)
from tools.hglint.model import RULES, Finding, sort_findings

__all__ = [
    "Finding",
    "RULES",
    "apply_baseline",
    "baseline_counts",
    "load_baseline",
    "run_lint",
    "sort_findings",
    "summarize",
    "write_baseline",
]
