"""hglint — AST-based JAX/TPU hazard analyzer for the hypergraphdb_tpu
codebase.

Rule families (see ``tools.hglint.model.RULES``):

- HG1xx  host syncs reachable from traced (jit/pjit/shard_map/pallas) code,
         donation lifetimes (HG106), host-numpy uploads (HG107)
- HG2xx  retrace/recompile hazards
- HG3xx  Pallas kernel contracts ((8,128) tiling, index maps, dtypes)
- HG4xx  lock-order cycles and unlocked shared-state mutation
- HG5xx  static VMEM budgets per pallas_call (abstract interpretation)
- HG6xx  shard_map collective consistency (mesh axes, divergence)
- HG7xx  blocking work while holding a lock (interprocedural taint)
- HG8xx  thread & resource lifecycle contracts
- HG9xx  analyzer hygiene (stale suppressions)
- HG10xx exception flow & failure discipline (interprocedural raise-set
         inference: swallowed kills, dead fault handlers, permanent-fault
         retries, unguarded worker entry points, evidence-free swallows)
- HG11xx wire-contract analysis (producer/consumer pairing across the
         process boundary: payload arity drift, envelope-key drift,
         unversioned persisted artifacts, typed-error wire-table drift,
         metric-name drift vs the DOTTED_NAMES registry)

Run ``python -m tools.hglint <paths>``; the repo gate is
``tools/lint.sh`` (baseline-filtered, exits nonzero on new findings,
distinct exit code on analyzer crashes). Pure AST analysis: target code
is never imported or executed. ``# hglint: disable=HGnnn`` on a finding's
line suppresses it (for hazards verified by hand / guarded at runtime).
"""

from tools.hglint.engine import (
    apply_baseline,
    baseline_counts,
    build_report,
    finding_dict,
    load_baseline,
    run_lint,
    summarize,
    write_baseline,
)
from tools.hglint.model import RULES, Finding, doc_anchor, sort_findings

__all__ = [
    "Finding",
    "RULES",
    "apply_baseline",
    "baseline_counts",
    "build_report",
    "doc_anchor",
    "finding_dict",
    "load_baseline",
    "run_lint",
    "sort_findings",
    "summarize",
    "write_baseline",
]
