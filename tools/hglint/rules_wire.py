"""hgwire: cross-boundary wire-schema & protocol contract checks (HG11xx).

Every family before this one stops at the process boundary; the bugs that
actually recurred in this tree crossed it — a producer grew its payload
tuple and every consumer crashed at unpack, a JSONL artifact gained a new
schema while old readers kept parsing it, an HTTP error table silently
stopped covering a newly added exception type. hgwire pairs *pack* sites
with *unpack* sites across modules and checks the contract between them:

``HG1101``  payload arity drift — a tuple packed at a send/enqueue site is
            unpacked with a different arity by a consumer of the same
            channel (the PR-9 push-apply crash class, caught at lint time).
``HG1102``  envelope-key drift — a consumer of a discriminator-keyed
            message kind hard-reads a key no producer writes
            (KeyError-in-waiting, error) or a producer writes a key no
            consumer ever reads (dead field, warning). Tolerant
            ``.get(k, default)`` reads satisfy the consumer side without
            counting as a hard dependency.
``HG1103``  persisted-artifact versioning — a ``json.dump``/JSONL writer
            whose record carries no schema-version stamp (error); a module
            that stamps its persisted records but contains a hard-keyed
            JSON reader that never version-checks (error); a reader whose
            accepted-version set rejects a version writers emit (error) or
            admits versions no writer emits (warning).
``HG1104``  typed-error wire-table drift — an in-tree exception deriving a
            wire-mapped family root that no HTTP status-table entry
            covers, or a client-side kind branch that maps a wire error
            name back to a *different* exception type.
``HG1105``  metric-name drift — a literal dotted metric site in a
            namespace governed by a ``DOTTED_NAMES`` registry whose name
            is absent from that registry (the static twin of the runtime
            drift-gate test; fires at edit time instead of test time).

Message kinds are inferred from three sources: envelope discriminator keys
(``"what"``/``"type"``/``"op"``/``"event"``-keyed dict literals at
``Activity.send``/``reply`` and other produce sites, paired with
``content.get("what") == "..."`` dispatch branches), queue/journal
append↔drain pairs (slot channels over ``self.<attr>``/module globals,
with alias and carrier tracking through ``q = self._slots[pid]`` and
``batch.append(q.popleft())`` idioms), and tuple-literal arguments flowing
into named callee parameters (param channels, merged with slot channels
when a carrier is passed across a call).

Like every hglint family this is a pure-AST whole-program pass: the
analyzed tree is never imported. Where a payload or record is not
statically resolvable the analyzer stays silent rather than guessing
(under-approximation: no finding is still not a proof of consistency).
Suppressions use the standard pragma (``# hglint: disable=HG1103``) and
are subject to the HG901 stale-pragma audit.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .callgraph import CallGraph, CallSite, FunctionInfo
from .loader import ModuleInfo
from .model import Finding
from .rules_exceptions import BUILTIN_PARENT

#: envelope keys whose constant-string value names the message kind
DISCRIMINATOR_KEYS = ("what", "type", "op", "event")

#: envelope keys exempt from dead-field reporting (routing metadata that
#: generic middleware reads, not the kind-specific consumer)
ENVELOPE_EXEMPT_KEYS = frozenset(DISCRIMINATOR_KEYS) | {"trace"}

#: record keys accepted as a schema-version stamp
VERSION_KEYS = ("schema_version", "version", "format")

#: single-payload container mutators treated as pack sites
PACK_METHODS = frozenset({"append", "appendleft", "add", "put", "put_nowait"})

#: container accessors peeled while resolving an expression to its slot
POP_METHODS = frozenset({"pop", "popleft", "get_nowait"})
CONTAINER_PEELS = frozenset({"get", "setdefault"})

#: metric facade / registry methods taking a literal dotted name
METRIC_METHODS = frozenset(
    {"incr", "gauge", "observe", "counter", "histogram", "timer"}
)

#: wire key carrying the error *type name* in typed-error round-trips
ERROR_KIND_KEY = "error"

#: open() modes that persist (reading modes never version-drift on write)
PERSIST_MODES = frozenset({"w", "a", "wb", "ab", "w+", "a+", "x", "xb"})

_HTTP_MIN, _HTTP_MAX = 100, 600


# --------------------------------------------------------------- channels


@dataclass
class _Pack:
    arity: Optional[int]   # None: tuple contains *starred / unknown parts
    path: str
    line: int
    scope: str


@dataclass
class _Unpack:
    arity: int             # number of unpack targets (incl. the star slot)
    star: bool             # starred target: arity-1 is the required minimum
    path: str
    line: int
    scope: str


@dataclass
class _Producer:
    keys: Set[str]
    dynamic: bool          # non-literal keys present — suppress key errors
    path: str
    line: int
    scope: str


@dataclass
class _Consumer:
    hard: Set[str]
    soft: Set[str]
    dkey: str
    path: str
    line: int
    scope: str


@dataclass
class _Writer:
    keys: Set[str]
    stamped: bool
    stamp_values: Set[object]
    persisted: bool
    dynamic: bool          # **-unpack / opaque update: cannot prove either way
    path: str
    line: int
    scope: str


@dataclass
class _Reader:
    hard: Set[str]
    version_checked: bool
    accepted: Set[object]
    path: str
    line: int
    scope: str


@dataclass
class _Table:
    mod: str
    path: str
    line: int
    types: Set[str]


@dataclass
class _FnScan:
    fi: FunctionInfo
    nodes: List[ast.AST]
    aliases: Dict[str, str] = field(default_factory=dict)
    key_reads: Dict[str, Tuple[Set[str], Set[str]]] = field(
        default_factory=dict
    )


class _UnionFind:
    def __init__(self):
        self.parent: Dict[str, str] = {}

    def find(self, x: str) -> str:
        p = self.parent.setdefault(x, x)
        while p != x:
            gp = self.parent.setdefault(p, p)
            self.parent[x] = gp
            x, p = p, gp
        return x

    def union(self, a: str, b: str) -> None:
        ra, rb = self.find(a), self.find(b)
        if ra != rb:
            self.parent[ra] = rb


def _own_nodes(root: ast.AST) -> List[ast.AST]:
    """All nodes of *root*'s body in document order, excluding nested
    function/class scopes (they are separate FunctionInfos)."""
    out: List[ast.AST] = []

    def rec(n: ast.AST) -> None:
        for c in ast.iter_child_nodes(n):
            if isinstance(
                c, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                continue
            out.append(c)
            rec(c)

    rec(root)
    return out


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _fmt_keys(keys) -> str:
    return ", ".join(repr(k) for k in sorted(keys))


# -------------------------------------------------------------- registries


def collect_registries(
    modules: Sequence[ModuleInfo],
) -> Tuple[Set[str], Set[str]]:
    """Discover ``DOTTED_NAMES``-style metric registries by AST evaluation
    (the analyzed tree is never imported). Returns ``(vocab, prefixes)``;
    an unresolvable registry contributes nothing (HG1105 then simply does
    not govern its namespace — under-approximation, never a guess)."""
    vocab: Set[str] = set()
    prefixes: Set[str] = set()
    for mod in modules:
        toplevel = {
            t.targets[0].id: t.value
            for t in mod.tree.body
            if isinstance(t, ast.Assign)
            and len(t.targets) == 1
            and isinstance(t.targets[0], ast.Name)
        }
        if "DOTTED_NAMES" not in toplevel:
            continue
        names = _eval_strs(toplevel["DOTTED_NAMES"], mod, toplevel)
        if names is None:
            continue
        vocab.update(names)
        for name, val in toplevel.items():
            if name.endswith("_PREFIX"):
                s = _const_str(val)
                if s and "." in s:
                    prefixes.add(s)
    return vocab, prefixes


def _eval_strs(
    node: ast.AST,
    mod: ModuleInfo,
    toplevel: Dict[str, ast.AST],
    depth: int = 0,
) -> Optional[Tuple[str, ...]]:
    """Evaluate an expression to a tuple of strings, or None. Handles the
    registry idioms: string/tuple literals, ``A + B`` concatenation, names
    bound at module level, and ``tuple(f"..{k}.." for k in KS for p in PS)``
    comprehensions over resolvable iterables."""
    if depth > 8:
        return None
    s = _const_str(node)
    if s is not None:
        return (s,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out: List[str] = []
        for e in node.elts:
            sub = _eval_strs(e, mod, toplevel, depth + 1)
            if sub is None:
                return None
            out.extend(sub)
        return tuple(out)
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        left = _eval_strs(node.left, mod, toplevel, depth + 1)
        right = _eval_strs(node.right, mod, toplevel, depth + 1)
        if left is None or right is None:
            return None
        return left + right
    if isinstance(node, ast.Name):
        v = mod.consts.get(node.id)
        if isinstance(v, str):
            return (v,)
        if isinstance(v, tuple) and all(isinstance(x, str) for x in v):
            return v
        tnode = toplevel.get(node.id)
        if tnode is not None and tnode is not node:
            return _eval_strs(tnode, mod, toplevel, depth + 1)
        return None
    if (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Name)
        and node.func.id in ("tuple", "list", "sorted")
        and len(node.args) == 1
        and not node.keywords
        and isinstance(node.args[0], (ast.GeneratorExp, ast.ListComp))
    ):
        comp = node.args[0]
        envs: List[Dict[str, str]] = [{}]
        for gen in comp.generators:
            if gen.ifs or gen.is_async or not isinstance(
                gen.target, ast.Name
            ):
                return None
            it = _eval_strs(gen.iter, mod, toplevel, depth + 1)
            if it is None:
                return None
            envs = [
                dict(e, **{gen.target.id: v}) for e in envs for v in it
            ]
        out = []
        for env in envs:
            s = _eval_fstring(comp.elt, env)
            if s is None:
                return None
            out.append(s)
        return tuple(out)
    return None


def _eval_fstring(node: ast.AST, env: Dict[str, str]) -> Optional[str]:
    s = _const_str(node)
    if s is not None:
        return s
    if isinstance(node, ast.Name):
        return env.get(node.id)
    if isinstance(node, ast.JoinedStr):
        parts: List[str] = []
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            elif (
                isinstance(v, ast.FormattedValue)
                and v.format_spec is None
                and isinstance(v.value, ast.Name)
                and v.value.id in env
            ):
                parts.append(env[v.value.id])
            else:
                return None
        return "".join(parts)
    return None


# -------------------------------------------------------------- wire model


class _WireModel:
    def __init__(self, cg: CallGraph, modules: Sequence[ModuleInfo]):
        self.cg = cg
        self.modules = list(modules)

        # HG1101
        self.uf = _UnionFind()
        self.packs: Dict[str, List[_Pack]] = {}
        self.unpacks: Dict[str, List[_Unpack]] = {}
        # HG1102
        self.producers: Dict[str, List[_Producer]] = {}
        self.consumers: Dict[str, List[_Consumer]] = {}
        # HG1103 (grouped per module name)
        self.writers: Dict[str, List[_Writer]] = {}
        self.readers: Dict[str, List[_Reader]] = {}
        # HG1104
        self.tables: List[_Table] = []
        self.class_parent: Dict[str, str] = dict(BUILTIN_PARENT)
        self.class_site: Dict[str, Tuple[str, int]] = {}
        self._rt_findings: List[Finding] = []
        # HG1105
        self.vocab, self.prefixes = collect_registries(self.modules)
        self.metric_sites: List[Tuple[str, str, int, str]] = []

        for mod in self.modules:
            self._scan_module_level(mod)

        self.scans: Dict[str, _FnScan] = {}
        for key, fi in self.cg.functions.items():
            sc = _FnScan(fi, _own_nodes(fi.node))
            sc.aliases = self._alias_pass(sc)
            sc.key_reads = self._key_read_pass(sc)
            self.scans[key] = sc
        for sc in self.scans.values():
            self._scan_channels(sc)
            self._scan_envelopes(sc)
            self._scan_artifacts(sc)
            self._scan_roundtrip(sc)
            self._scan_metrics(sc)

    # ------------------------------------------------------ module level

    def _scan_module_level(self, mod: ModuleInfo) -> None:
        for stmt in mod.tree.body:
            if isinstance(stmt, ast.ClassDef):
                self.class_site[stmt.name] = (mod.path, stmt.lineno)
                for b in stmt.bases:
                    base = self._type_name(b)
                    if base:
                        self.class_parent.setdefault(stmt.name, base)
                        break
            if (
                isinstance(stmt, ast.Assign)
                and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and isinstance(stmt.value, (ast.Tuple, ast.List))
            ):
                types = self._status_table_types(stmt.value)
                if types:
                    self.tables.append(
                        _Table(mod.name, mod.path, stmt.lineno, types)
                    )

    def _status_table_types(self, node: ast.AST) -> Optional[Set[str]]:
        """An HTTP status/type table is a tuple/list of 2-tuples mapping
        exception type(s) to an int HTTP status."""
        types: Set[str] = set()
        if not isinstance(node, (ast.Tuple, ast.List)) or not node.elts:
            return None
        for e in node.elts:
            if not (isinstance(e, ast.Tuple) and len(e.elts) == 2):
                return None
            spec, status = e.elts
            if not (
                isinstance(status, ast.Constant)
                and isinstance(status.value, int)
                and _HTTP_MIN <= status.value < _HTTP_MAX
            ):
                return None
            names = []
            specs = spec.elts if isinstance(spec, ast.Tuple) else [spec]
            for s in specs:
                n = self._type_name(s)
                if not n:
                    return None
                names.append(n)
            types.update(names)
        return types or None

    @staticmethod
    def _type_name(node: ast.AST) -> Optional[str]:
        name = None
        if isinstance(node, ast.Name):
            name = node.id
        elif isinstance(node, ast.Attribute):
            name = node.attr
        if name and name[:1].isupper():
            return name
        return None

    def _ancestry(self, t: str) -> List[str]:
        out, seen = [t], {t}
        cur = t
        while cur in self.class_parent:
            cur = self.class_parent[cur]
            if cur in seen:
                break
            seen.add(cur)
            out.append(cur)
        return out

    # --------------------------------------------------------- fn passes

    def _slot_of(
        self, expr: ast.AST, sc: _FnScan
    ) -> Optional[str]:
        """Resolve an expression to the channel it denotes, peeling
        subscripts and container accessors (``q[pid]``, ``q.get(pid)``,
        ``q.popleft()`` — dict-of-queues and element extraction share the
        channel: payload contracts are per-slot, not per-instance)."""
        cur = expr
        while True:
            if isinstance(cur, ast.Subscript):
                cur = cur.value
                continue
            if (
                isinstance(cur, ast.Call)
                and isinstance(cur.func, ast.Attribute)
                and cur.func.attr in (CONTAINER_PEELS | POP_METHODS)
            ):
                cur = cur.func.value
                continue
            break
        fi = sc.fi
        if (
            isinstance(cur, ast.Attribute)
            and isinstance(cur.value, ast.Name)
            and cur.value.id in ("self", "cls")
            and fi.cls_name
        ):
            return f"slot:{fi.mod.name}.{fi.cls_name}.{cur.attr}"
        if isinstance(cur, ast.Name):
            if cur.id in sc.aliases:
                return sc.aliases[cur.id]
            if cur.id in fi.params:
                return f"param:{fi.key}:{cur.id}"
            if cur.id in fi.mod.mutable_globals:
                return f"slot:{fi.mod.name}.{cur.id}"
        return None

    def _alias_pass(self, sc: _FnScan) -> Dict[str, str]:
        sc.aliases = {}
        for _ in range(2):  # aliases of aliases settle in two passes
            for n in sc.nodes:
                if isinstance(n, ast.Assign):
                    names = [
                        t.id for t in n.targets if isinstance(t, ast.Name)
                    ]
                    tchan = next(
                        (
                            c
                            for c in (
                                self._slot_of(t, sc)
                                for t in n.targets
                                if not isinstance(t, ast.Name)
                            )
                            if c
                        ),
                        None,
                    )
                    if tchan and names:
                        # q = self._slots[pid] = deque()
                        for nm in names:
                            sc.aliases[nm] = tchan
                        continue
                    if len(n.targets) == 1 and len(names) == 1:
                        vchan = self._slot_of(n.value, sc)
                        if vchan:
                            sc.aliases[names[0]] = vchan
                elif isinstance(n, (ast.For, ast.AsyncFor)):
                    self._alias_for(n, sc)
                elif (
                    isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr in PACK_METHODS
                    and len(n.args) == 1
                    and isinstance(n.func.value, ast.Name)
                    and n.func.value.id not in sc.aliases
                ):
                    # carrier: batch.append(self._q.popleft())
                    chan = self._slot_of(n.args[0], sc)
                    if chan:
                        sc.aliases[n.func.value.id] = chan
        return sc.aliases

    def _alias_for(self, n, sc: _FnScan) -> None:
        it = n.iter
        if (
            isinstance(it, ast.Call)
            and isinstance(it.func, ast.Attribute)
            and it.func.attr in ("items", "values")
        ):
            chan = self._slot_of(it.func.value, sc)
            if not chan:
                return
            if it.func.attr == "values" and isinstance(n.target, ast.Name):
                sc.aliases[n.target.id] = chan
            if (
                it.func.attr == "items"
                and isinstance(n.target, ast.Tuple)
                and len(n.target.elts) == 2
                and isinstance(n.target.elts[1], ast.Name)
            ):
                sc.aliases[n.target.elts[1].id] = chan
            return
        if isinstance(n.target, ast.Name):
            chan = self._slot_of(it, sc)
            if chan:
                # element alias: `for t in q` then `a, b = t`
                sc.aliases[n.target.id] = chan

    def _key_read_pass(
        self, sc: _FnScan
    ) -> Dict[str, Tuple[Set[str], Set[str]]]:
        reads: Dict[str, Tuple[Set[str], Set[str]]] = {}
        for n in sc.nodes:
            if (
                isinstance(n, ast.Subscript)
                and isinstance(n.value, ast.Name)
                and isinstance(n.ctx, ast.Load)
            ):
                k = _const_str(n.slice)
                if k is not None:
                    reads.setdefault(
                        n.value.id, (set(), set())
                    )[0].add(k)
            elif (
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr == "get"
                and isinstance(n.func.value, ast.Name)
                and n.args
            ):
                k = _const_str(n.args[0])
                if k is not None:
                    reads.setdefault(
                        n.func.value.id, (set(), set())
                    )[1].add(k)
        return reads

    # ------------------------------------------------------------ HG1101

    def _scan_channels(self, sc: _FnScan) -> None:
        fi = sc.fi
        for n in sc.nodes:
            if isinstance(n, ast.Call) and isinstance(
                n.func, ast.Attribute
            ):
                if (
                    n.func.attr in PACK_METHODS
                    and len(n.args) == 1
                    and isinstance(n.args[0], ast.Tuple)
                ):
                    chan = self._slot_of(n.func.value, sc)
                    if chan:
                        self._add_pack(chan, n.args[0], sc, n.lineno)
                elif (
                    n.func.attr == "insert"
                    and len(n.args) == 2
                    and isinstance(n.args[1], ast.Tuple)
                ):
                    chan = self._slot_of(n.func.value, sc)
                    if chan:
                        self._add_pack(chan, n.args[1], sc, n.lineno)
            if isinstance(n, ast.Call):
                self._scan_call_edges(n, sc)
            if isinstance(n, ast.Assign) and len(n.targets) == 1:
                t = n.targets[0]
                if isinstance(t, ast.Subscript) and isinstance(
                    n.value, ast.Tuple
                ):
                    chan = self._slot_of(t.value, sc)
                    if chan:
                        self._add_pack(chan, n.value, sc, n.lineno)
                elif isinstance(t, ast.Tuple):
                    chan = self._slot_of(n.value, sc)
                    if chan:
                        self._add_unpack(chan, t, sc, n.lineno)
            if isinstance(n, (ast.For, ast.AsyncFor)) and isinstance(
                n.target, ast.Tuple
            ):
                it = n.iter
                if (
                    isinstance(it, ast.Call)
                    and isinstance(it.func, ast.Attribute)
                    and it.func.attr in ("items", "keys")
                ):
                    continue  # dict iteration, not a payload unpack
                chan = self._slot_of(it, sc)
                if chan:
                    self._add_unpack(chan, n.target, sc, n.lineno)

    def _scan_call_edges(self, call: ast.Call, sc: _FnScan) -> None:
        """Tuple-literal arguments become packs on the callee's parameter
        channel; carrier arguments link caller and callee channels."""
        fi = sc.fi
        site = CallSite(node=call, fn_key=fi.key, mod=fi.mod)
        callee = self.cg.resolve_callable(call.func, site)
        if callee is None or callee not in self.cg.functions:
            return
        cfi = self.cg.functions[callee]
        params = cfi.params
        offset = (
            1
            if params
            and params[0] in ("self", "cls")
            and isinstance(call.func, ast.Attribute)
            else 0
        )

        def param_chan(name: str) -> str:
            return f"param:{callee}:{name}"

        for i, arg in enumerate(call.args):
            if isinstance(arg, ast.Starred):
                break
            pi = offset + i
            if pi >= len(params):
                break
            self._bind_arg(arg, param_chan(params[pi]), sc)
        for kw in call.keywords:
            if kw.arg and kw.arg in params:
                self._bind_arg(kw.value, param_chan(kw.arg), sc)

    def _bind_arg(self, arg: ast.AST, pchan: str, sc: _FnScan) -> None:
        if isinstance(arg, ast.Tuple):
            self._add_pack(pchan, arg, sc, arg.lineno)
            return
        elts = None
        if isinstance(arg, ast.List):
            elts = arg.elts
        elif isinstance(arg, ast.ListComp):
            elts = [arg.elt]
        if elts is not None:
            for e in elts:
                if isinstance(e, ast.Tuple):
                    self._add_pack(pchan, e, sc, e.lineno)
            return
        chan = self._slot_of(arg, sc)
        if chan:
            self.uf.union(pchan, chan)

    def _add_pack(
        self, chan: str, tup: ast.Tuple, sc: _FnScan, line: int
    ) -> None:
        arity: Optional[int] = len(tup.elts)
        if any(isinstance(e, ast.Starred) for e in tup.elts):
            arity = None
        self.packs.setdefault(self.uf.find(chan), []).append(
            _Pack(arity, sc.fi.mod.path, line, sc.fi.qualpath)
        )

    def _add_unpack(
        self, chan: str, tgt: ast.Tuple, sc: _FnScan, line: int
    ) -> None:
        star = any(isinstance(e, ast.Starred) for e in tgt.elts)
        self.unpacks.setdefault(self.uf.find(chan), []).append(
            _Unpack(
                len(tgt.elts), star, sc.fi.mod.path, line, sc.fi.qualpath
            )
        )

    def arity_findings(self) -> List[Finding]:
        groups: Dict[str, Tuple[List[_Pack], List[_Unpack]]] = {}
        for chan, ps in self.packs.items():
            groups.setdefault(
                self.uf.find(chan), ([], [])
            )[0].extend(ps)
        for chan, us in self.unpacks.items():
            groups.setdefault(
                self.uf.find(chan), ([], [])
            )[1].extend(us)
        out: List[Finding] = []
        for chan, (ps, us) in sorted(groups.items()):
            known = [p for p in ps if p.arity is not None]
            if not known or not us:
                continue
            for u in us:
                need = u.arity - 1 if u.star else u.arity
                bad = [
                    p
                    for p in known
                    if (p.arity < need if u.star else p.arity != need)
                ]
                if not bad:
                    continue
                p = bad[0]
                more = (
                    f" (+{len(bad) - 1} more pack site(s))"
                    if len(bad) > 1
                    else ""
                )
                want = (
                    f"at least {need} values (starred target)"
                    if u.star
                    else f"exactly {u.arity} values"
                )
                out.append(Finding(
                    rule="HG1101", path=u.path, line=u.line,
                    scope=u.scope,
                    message=f"payload arity drift on channel "
                            f"`{chan.split(':', 1)[1]}`: this unpack "
                            f"needs {want} but `{p.scope}` packs "
                            f"{p.arity}-tuples ({p.path}:{p.line})"
                            f"{more} — every consumer of this channel "
                            f"crashes at unpack when the producer "
                            f"payload changes shape",
                ))
        return out

    # ------------------------------------------------------------ HG1102

    def _scan_envelopes(self, sc: _FnScan) -> None:
        fi = sc.fi
        # producers: discriminator-keyed dict literals
        for n in sc.nodes:
            if not isinstance(n, ast.Dict):
                continue
            keys: Set[str] = set()
            dynamic = False
            kind = None
            for k, v in zip(n.keys, n.values):
                ks = _const_str(k) if k is not None else None
                if ks is None:
                    dynamic = True
                    continue
                keys.add(ks)
                if ks in DISCRIMINATOR_KEYS and kind is None:
                    kind = _const_str(v)
            if kind is not None:
                self.producers.setdefault(kind, []).append(_Producer(
                    keys, dynamic, fi.mod.path, n.lineno, fi.qualpath
                ))
        # consumers: kind-dispatch branches
        dvars: Dict[str, Tuple[str, str]] = {}
        for n in sc.nodes:
            if (
                isinstance(n, ast.Assign)
                and len(n.targets) == 1
                and isinstance(n.targets[0], ast.Name)
            ):
                dr = self._disc_read(n.value)
                if dr:
                    dvars[n.targets[0].id] = dr
        for n in sc.nodes:
            if not isinstance(n, ast.If):
                continue
            hit = self._kind_test(n.test, dvars)
            if not hit:
                continue
            container, dkey, kinds = hit
            hard, soft = self._branch_reads(n.body, container, sc)
            for kind in kinds:
                self.consumers.setdefault(kind, []).append(_Consumer(
                    set(hard), set(soft), dkey, fi.mod.path,
                    n.test.lineno, fi.qualpath,
                ))

    @staticmethod
    def _disc_read(expr: ast.AST) -> Optional[Tuple[str, str]]:
        """``content.get("what")`` / ``content["what"]`` →
        ``(container var, discriminator key)``."""
        if (
            isinstance(expr, ast.Call)
            and isinstance(expr.func, ast.Attribute)
            and expr.func.attr == "get"
            and isinstance(expr.func.value, ast.Name)
            and expr.args
        ):
            k = _const_str(expr.args[0])
            if k in DISCRIMINATOR_KEYS:
                return (expr.func.value.id, k)
        if (
            isinstance(expr, ast.Subscript)
            and isinstance(expr.value, ast.Name)
        ):
            k = _const_str(expr.slice)
            if k in DISCRIMINATOR_KEYS:
                return (expr.value.id, k)
        return None

    def _kind_test(
        self, test: ast.AST, dvars: Dict[str, Tuple[str, str]]
    ) -> Optional[Tuple[str, str, List[str]]]:
        if not (
            isinstance(test, ast.Compare)
            and len(test.ops) == 1
            and len(test.comparators) == 1
        ):
            return None
        left, op, right = test.left, test.ops[0], test.comparators[0]
        src = None
        if isinstance(left, ast.Name) and left.id in dvars:
            src = dvars[left.id]
        else:
            src = self._disc_read(left)
        if src is None:
            return None
        container, dkey = src
        if isinstance(op, ast.Eq):
            kind = _const_str(right)
            if kind is not None:
                return (container, dkey, [kind])
        if isinstance(op, ast.In) and isinstance(
            right, (ast.Tuple, ast.List, ast.Set)
        ):
            kinds = [_const_str(e) for e in right.elts]
            if kinds and all(k is not None for k in kinds):
                return (container, dkey, list(kinds))
        return None

    def _branch_reads(
        self, body: List[ast.stmt], container: str, sc: _FnScan
    ) -> Tuple[Set[str], Set[str]]:
        hard: Set[str] = set()
        soft: Set[str] = set()
        for stmt in body:
            for n in ast.walk(stmt):
                if (
                    isinstance(n, ast.Subscript)
                    and isinstance(n.value, ast.Name)
                    and n.value.id == container
                    and isinstance(n.ctx, ast.Load)
                ):
                    k = _const_str(n.slice)
                    if k is not None:
                        hard.add(k)
                elif isinstance(n, ast.Call):
                    if (
                        isinstance(n.func, ast.Attribute)
                        and n.func.attr == "get"
                        and isinstance(n.func.value, ast.Name)
                        and n.func.value.id == container
                        and n.args
                    ):
                        k = _const_str(n.args[0])
                        if k is not None:
                            soft.add(k)
                    else:
                        h2, s2 = self._hop_reads(n, container, sc)
                        hard |= h2
                        soft |= s2
        return hard, soft

    #: forwarded-callee walk budget: a consumer may route the envelope
    #: through up to this many resolvable callees (handler → helper →
    #: decoder) and its reads still count as the consumer's own
    _HOP_DEPTH = 2

    def _hop_reads(
        self, call: ast.Call, container: str, sc: _FnScan,
        depth: Optional[int] = None,
        seen: Optional[Set[Tuple[str, str]]] = None,
    ) -> Tuple[Set[str], Set[str]]:
        """Interprocedural hops: the envelope is forwarded to a
        resolvable callee — that callee's reads on the receiving
        parameter count as this consumer's reads, transitively up to
        ``_HOP_DEPTH`` forwarding hops (a handler that delegates to a
        helper which itself delegates to the real decoder stays
        closed-world). ``seen`` breaks (callee, param) cycles."""
        if depth is None:
            depth = self._HOP_DEPTH
        passed = [
            i
            for i, a in enumerate(call.args)
            if isinstance(a, ast.Name) and a.id == container
        ]
        if not passed:
            return set(), set()
        site = CallSite(node=call, fn_key=sc.fi.key, mod=sc.fi.mod)
        callee = self.cg.resolve_callable(call.func, site)
        if callee is None or callee not in self.cg.functions:
            return set(), set()
        cfi = self.cg.functions[callee]
        csc = self.scans.get(callee)
        if csc is None:
            return set(), set()
        params = cfi.params
        offset = (
            1
            if params
            and params[0] in ("self", "cls")
            and isinstance(call.func, ast.Attribute)
            else 0
        )
        if seen is None:
            seen = set()
        hard: Set[str] = set()
        soft: Set[str] = set()
        for i in passed:
            pi = offset + i
            if pi >= len(params):
                continue
            pname = params[pi]
            if (callee, pname) in seen:
                continue  # mutual forwarding must terminate
            seen.add((callee, pname))
            h, s = csc.key_reads.get(pname, (set(), set()))
            hard |= h
            soft |= s
            if depth > 1:
                # the callee may forward the SAME envelope onward —
                # walk its own calls with one hop less of budget
                for n in csc.nodes:
                    if isinstance(n, ast.Call):
                        h2, s2 = self._hop_reads(
                            n, pname, csc, depth - 1, seen
                        )
                        hard |= h2
                        soft |= s2
        return hard, soft

    def envelope_findings(self) -> List[Finding]:
        out: List[Finding] = []
        for kind in sorted(self.producers):
            prods = self.producers[kind]
            cons = self.consumers.get(kind, [])
            written: Set[str] = set()
            for p in prods:
                written |= p.keys
            any_dynamic = any(p.dynamic for p in prods)
            for c in sorted(cons, key=lambda c: (c.path, c.line)):
                missing = c.hard - written - {c.dkey}
                if missing and not any_dynamic:
                    out.append(Finding(
                        rule="HG1102", path=c.path, line=c.line,
                        scope=c.scope,
                        message=f"envelope-key drift: consumer of kind "
                                f"{kind!r} hard-reads {_fmt_keys(missing)} "
                                f"but no producer of this kind writes "
                                f"{'it' if len(missing) == 1 else 'them'} "
                                f"— a KeyError in waiting; write the key "
                                f"at every produce site or read it with "
                                f"`.get()`",
                    ))
            if not cons:
                continue
            reads: Set[str] = set()
            for c in cons:
                reads |= c.hard | c.soft
            for p in sorted(prods, key=lambda p: (p.path, p.line)):
                dead = p.keys - reads - ENVELOPE_EXEMPT_KEYS
                if dead:
                    out.append(Finding(
                        rule="HG1102", path=p.path, line=p.line,
                        scope=p.scope, severity="warning",
                        message=f"envelope-key drift: producer of kind "
                                f"{kind!r} writes {_fmt_keys(dead)} but "
                                f"no consumer of this kind reads "
                                f"{'it' if len(dead) == 1 else 'them'} — "
                                f"dead field(s); drop or consume",
                    ))
        return out

    # ------------------------------------------------------------ HG1103

    def _scan_artifacts(self, sc: _FnScan) -> None:
        fi = sc.fi
        persists = False
        dicts: Dict[str, _Writer] = {}
        loads: Dict[str, _Reader] = {}
        vver: Set[str] = set()
        writes: List[_Writer] = []

        def record_of(arg: ast.AST) -> Optional[_Writer]:
            if isinstance(arg, ast.Dict):
                return self._dict_record(arg, sc)
            if isinstance(arg, ast.Name):
                return dicts.get(arg.id)
            return None

        for n in sc.nodes:
            if isinstance(n, ast.Call):
                fname = None
                if isinstance(n.func, ast.Name):
                    fname = n.func.id
                elif isinstance(n.func, ast.Attribute):
                    fname = n.func.attr
                if fname == "open":
                    mode = None
                    if len(n.args) >= 2:
                        mode = _const_str(n.args[1])
                    elif isinstance(n.func, ast.Attribute) and n.args:
                        mode = _const_str(n.args[0])  # Path.open("w")
                    for kw in n.keywords:
                        if kw.arg == "mode":
                            mode = _const_str(kw.value)
                    if mode in PERSIST_MODES:
                        persists = True
                elif fname and (
                    "atomic_write" in fname or fname == "replace"
                ):
                    persists = True
                if (
                    isinstance(n.func, ast.Attribute)
                    and n.func.attr in ("dump", "dumps")
                    and isinstance(n.func.value, ast.Name)
                    and n.func.value.id == "json"
                    and n.args
                ):
                    w = record_of(n.args[0])
                    if w is not None:
                        w = _Writer(
                            set(w.keys), w.stamped, set(w.stamp_values),
                            n.func.attr == "dump", w.dynamic,
                            fi.mod.path, n.lineno, fi.qualpath,
                        )
                        writes.append(w)
                if (
                    isinstance(n.func, ast.Attribute)
                    and n.func.attr == "update"
                    and isinstance(n.func.value, ast.Name)
                    and n.func.value.id in dicts
                    and n.args
                    and isinstance(n.args[0], ast.Dict)
                ):
                    extra = self._dict_record(n.args[0], sc)
                    d = dicts[n.func.value.id]
                    d.keys |= extra.keys
                    d.stamped = d.stamped or extra.stamped
                    d.stamp_values |= extra.stamp_values
                    d.dynamic = d.dynamic or extra.dynamic
            if (
                isinstance(n, ast.Assign)
                and len(n.targets) == 1
                and isinstance(n.targets[0], ast.Name)
            ):
                tname = n.targets[0].id
                if isinstance(n.value, ast.Dict):
                    dicts[tname] = self._dict_record(n.value, sc)
                    dicts[tname].line = n.lineno
                elif self._is_json_load(n.value):
                    loads[tname] = _Reader(
                        set(), False, set(),
                        fi.mod.path, n.lineno, fi.qualpath,
                    )
                elif self._version_read(n.value, loads) is not None:
                    vver.add(tname)
            if (
                isinstance(n, ast.Assign)
                and len(n.targets) == 1
                and isinstance(n.targets[0], ast.Subscript)
                and isinstance(n.targets[0].value, ast.Name)
                and n.targets[0].value.id in dicts
            ):
                k = _const_str(n.targets[0].slice)
                if k is not None:
                    d = dicts[n.targets[0].value.id]
                    d.keys.add(k)
                    if k in VERSION_KEYS:
                        d.stamped = True
                        v = self._const_value(n.value, sc)
                        if v is not None:
                            d.stamp_values.add(v)
            # reader key accesses + version comparisons
            if (
                isinstance(n, ast.Subscript)
                and isinstance(n.value, ast.Name)
                and n.value.id in loads
                and isinstance(n.ctx, ast.Load)
            ):
                k = _const_str(n.slice)
                if k is not None:
                    r = loads[n.value.id]
                    if k in VERSION_KEYS:
                        r.version_checked = True
                    else:
                        r.hard.add(k)
            if (
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr == "get"
                and isinstance(n.func.value, ast.Name)
                and n.func.value.id in loads
                and n.args
            ):
                k = _const_str(n.args[0])
                if k in VERSION_KEYS:
                    loads[n.func.value.id].version_checked = True
            if isinstance(n, ast.Compare) and len(n.comparators) == 1:
                self._version_compare(n, loads, vver, sc)

        mkey = fi.mod.name
        for w in writes:
            if not w.persisted:
                w.persisted = persists
            if w.persisted:
                self.writers.setdefault(mkey, []).append(w)
        for r in loads.values():
            self.readers.setdefault(mkey, []).append(r)

    def _dict_record(self, d: ast.Dict, sc: _FnScan) -> _Writer:
        keys: Set[str] = set()
        dynamic = False
        stamped = False
        values: Set[object] = set()
        for k, v in zip(d.keys, d.values):
            ks = _const_str(k) if k is not None else None
            if ks is None:
                dynamic = True
                continue
            keys.add(ks)
            if ks in VERSION_KEYS:
                stamped = True
                cv = self._const_value(v, sc)
                if cv is not None:
                    values.add(cv)
        return _Writer(
            keys, stamped, values, False, dynamic,
            sc.fi.mod.path, d.lineno, sc.fi.qualpath,
        )

    @staticmethod
    def _is_json_load(expr: ast.AST) -> bool:
        return (
            isinstance(expr, ast.Call)
            and isinstance(expr.func, ast.Attribute)
            and expr.func.attr in ("load", "loads")
            and isinstance(expr.func.value, ast.Name)
            and expr.func.value.id == "json"
        )

    @staticmethod
    def _version_read(
        expr: ast.AST, loads: Dict[str, _Reader]
    ) -> Optional[str]:
        """``rec["schema_version"]`` / ``rec.get("schema_version")`` on a
        known json.load() result → the load var name."""
        if (
            isinstance(expr, ast.Subscript)
            and isinstance(expr.value, ast.Name)
            and expr.value.id in loads
        ):
            k = _const_str(expr.slice)
            if k in VERSION_KEYS:
                return expr.value.id
        if (
            isinstance(expr, ast.Call)
            and isinstance(expr.func, ast.Attribute)
            and expr.func.attr == "get"
            and isinstance(expr.func.value, ast.Name)
            and expr.func.value.id in loads
            and expr.args
        ):
            k = _const_str(expr.args[0])
            if k in VERSION_KEYS:
                return expr.func.value.id
        return None

    def _const_value(self, expr: ast.AST, sc: _FnScan):
        if isinstance(expr, ast.Constant) and isinstance(
            expr.value, (int, str)
        ):
            return expr.value
        if isinstance(expr, ast.Name):
            v = sc.fi.mod.consts.get(expr.id)
            if isinstance(v, (int, str)):
                return v
        return None

    def _version_compare(
        self,
        n: ast.Compare,
        loads: Dict[str, _Reader],
        vver: Set[str],
        sc: _FnScan,
    ) -> None:
        side = None
        for expr in (n.left, n.comparators[0]):
            lv = self._version_read(expr, loads)
            if lv is not None:
                side = lv
            elif isinstance(expr, ast.Name) and expr.id in vver:
                side = next(iter(loads), None)
        if side is None or side not in loads:
            return
        r = loads[side]
        r.version_checked = True
        other = (
            n.comparators[0]
            if (
                self._version_read(n.left, loads) is not None
                or (isinstance(n.left, ast.Name) and n.left.id in vver)
            )
            else n.left
        )
        op = n.ops[0]
        vals: Set[object] = set()
        if isinstance(op, (ast.Eq, ast.NotEq)):
            v = self._const_value(other, sc)
            if v is not None:
                vals.add(v)
        elif isinstance(op, (ast.In, ast.NotIn)):
            elts = None
            if isinstance(other, (ast.Tuple, ast.List, ast.Set)):
                elts = other.elts
            elif isinstance(other, ast.Name):
                cv = sc.fi.mod.consts.get(other.id)
                if isinstance(cv, tuple):
                    vals.update(
                        v for v in cv if isinstance(v, (int, str))
                    )
            if elts is not None:
                for e in elts:
                    v = self._const_value(e, sc)
                    if v is not None:
                        vals.add(v)
        r.accepted |= vals

    def artifact_findings(self) -> List[Finding]:
        out: List[Finding] = []
        for mod in sorted(set(self.writers) | set(self.readers)):
            writers = self.writers.get(mod, [])
            readers = self.readers.get(mod, [])
            stamped = [w for w in writers if w.stamped]
            emitted: Set[object] = set()
            for w in stamped:
                emitted |= w.stamp_values
            for w in sorted(writers, key=lambda w: (w.path, w.line)):
                if not w.stamped and not w.dynamic:
                    out.append(Finding(
                        rule="HG1103", path=w.path, line=w.line,
                        scope=w.scope,
                        message=f"persisted JSON record (keys "
                                f"{_fmt_keys(w.keys) or '(none)'}) "
                                f"carries no schema-version stamp "
                                f"({'/'.join(VERSION_KEYS)}) — readers "
                                f"cannot reject a future format change; "
                                f"stamp it and version-check on read",
                    ))
            for r in sorted(readers, key=lambda r: (r.path, r.line)):
                if stamped and r.hard and not r.version_checked:
                    out.append(Finding(
                        rule="HG1103", path=r.path, line=r.line,
                        scope=r.scope,
                        message=f"hard-keyed JSON reader (reads "
                                f"{_fmt_keys(r.hard)}) in a module whose "
                                f"writers stamp a schema version, but it "
                                f"never version-checks — a format bump "
                                f"crashes this reader instead of being "
                                f"rejected cleanly",
                    ))
                if r.accepted and emitted:
                    rejected = emitted - r.accepted
                    if rejected:
                        out.append(Finding(
                            rule="HG1103", path=r.path, line=r.line,
                            scope=r.scope,
                            message=f"schema-version skew: this reader "
                                    f"accepts {_fmt_keys(r.accepted)} "
                                    f"but writers in this module emit "
                                    f"{_fmt_keys(rejected)} — current "
                                    f"artifacts are rejected on read",
                        ))
                    phantom = r.accepted - emitted
                    if phantom:
                        out.append(Finding(
                            rule="HG1103", path=r.path, line=r.line,
                            scope=r.scope, severity="warning",
                            message=f"schema-version skew: this reader "
                                    f"accepts {_fmt_keys(phantom)} "
                                    f"which no writer in this module "
                                    f"emits — a legacy-compat window; "
                                    f"confirm it is intentional or drop "
                                    f"the dead version(s)",
                        ))
        return out

    # ------------------------------------------------------------ HG1104

    def _scan_roundtrip(self, sc: _FnScan) -> None:
        fi = sc.fi
        kvars: Set[str] = set()
        for n in sc.nodes:
            if (
                isinstance(n, ast.Assign)
                and len(n.targets) == 1
                and isinstance(n.targets[0], ast.Name)
                and self._error_kind_read(n.value)
            ):
                kvars.add(n.targets[0].id)
        if not kvars:
            return
        known = set(self.class_site) | set(self.class_parent)
        for n in sc.nodes:
            if not isinstance(n, ast.If):
                continue
            t = n.test
            if not (
                isinstance(t, ast.Compare)
                and len(t.ops) == 1
                and isinstance(t.ops[0], ast.Eq)
                and isinstance(t.left, ast.Name)
                and t.left.id in kvars
            ):
                continue
            lit = _const_str(t.comparators[0])
            if lit is None:
                continue
            raised = [
                self._type_name(
                    r.exc.func if isinstance(r.exc, ast.Call) else r.exc
                )
                for stmt in n.body
                for r in ast.walk(stmt)
                if isinstance(r, ast.Raise) and r.exc is not None
            ]
            raised = [r for r in raised if r]
            if not raised:
                continue
            if lit not in known:
                self._rt_findings.append(Finding(
                    rule="HG1104", path=fi.mod.path, line=t.lineno,
                    scope=fi.qualpath,
                    message=f"typed-error round-trip: wire kind {lit!r} "
                            f"names no known exception class — the "
                            f"server side can never emit it, so this "
                            f"branch is dead (typo or removed type?)",
                ))
                continue
            for r in raised:
                if r != lit:
                    self._rt_findings.append(Finding(
                        rule="HG1104", path=fi.mod.path, line=t.lineno,
                        scope=fi.qualpath,
                        message=f"typed-error round-trip: wire kind "
                                f"{lit!r} is mapped back to `{r}` — the "
                                f"client rehydrates a *different* type "
                                f"than the server raised, so "
                                f"typed-error handling (degraded-not-"
                                f"down semantics) silently breaks",
                    ))

    @staticmethod
    def _error_kind_read(expr: ast.AST) -> bool:
        """``<expr>.get("error")`` / ``<expr>["error"]``."""
        if (
            isinstance(expr, ast.Call)
            and isinstance(expr.func, ast.Attribute)
            and expr.func.attr == "get"
            and expr.args
        ):
            return _const_str(expr.args[0]) == ERROR_KIND_KEY
        if isinstance(expr, ast.Subscript):
            return _const_str(expr.slice) == ERROR_KIND_KEY
        return False

    def errortable_findings(self) -> List[Finding]:
        out = list(self._rt_findings)
        for table in self.tables:
            roots = {
                a
                for t in table.types
                for a in self._ancestry(t)[1:]
                if a in self.class_site
            }
            if not roots:
                continue
            for cls in sorted(self.class_site):
                if cls in roots or cls in table.types:
                    continue
                anc = self._ancestry(cls)[1:]
                if not any(r in anc for r in roots):
                    continue
                if any(t in self._ancestry(cls) for t in table.types):
                    continue
                path, line = self.class_site[cls]
                out.append(Finding(
                    rule="HG1104", path=table.path, line=table.line,
                    scope="<module>",
                    message=f"typed-error wire-table drift: `{cls}` "
                            f"({path}:{line}) derives wire-mapped "
                            f"family root "
                            f"{'/'.join(sorted(roots & set(anc)))} but "
                            f"no status-table entry covers it — it "
                            f"falls through to the generic 500 and the "
                            f"client loses the typed round-trip",
                ))
        return out

    # ------------------------------------------------------------ HG1105

    def _scan_metrics(self, sc: _FnScan) -> None:
        fi = sc.fi
        for n in sc.nodes:
            if (
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr in METRIC_METHODS
                and n.args
            ):
                name = _const_str(n.args[0])
                if name and "." in name:
                    self.metric_sites.append(
                        (name, fi.mod.path, n.lineno, fi.qualpath)
                    )

    def metric_findings(self) -> List[Finding]:
        if not self.vocab:
            return []
        governed = {n.split(".", 1)[0] for n in self.vocab}
        governed |= {p.split(".", 1)[0] for p in self.prefixes}
        out: List[Finding] = []
        for name, path, line, scope in sorted(self.metric_sites):
            ns = name.split(".", 1)[0]
            if ns not in governed:
                continue
            if name in self.vocab:
                continue
            if any(name.startswith(p) for p in self.prefixes):
                continue
            out.append(Finding(
                rule="HG1105", path=path, line=line, scope=scope,
                message=f"metric-name drift: {name!r} is absent from "
                        f"the `DOTTED_NAMES` registry governing the "
                        f"{ns!r} namespace — the runtime drift gate "
                        f"will fail; register the name or fix the "
                        f"typo",
            ))
        return out


def check(
    cg: CallGraph, modules: Sequence[ModuleInfo]
) -> List[Finding]:
    model = _WireModel(cg, modules)
    out: List[Finding] = []
    out.extend(model.arity_findings())
    out.extend(model.envelope_findings())
    out.extend(model.artifact_findings())
    out.extend(model.errortable_findings())
    out.extend(model.metric_findings())
    return out
