"""HG4xx — lock-order and unlocked-shared-state analysis.

Lock identities are the *attribute slots* locks are stored in
(``module.Class.attr`` for ``self.attr = threading.Lock()``, ``module.name``
for module-level locks). The acquire graph has an edge A -> B when code
acquires B (directly, or transitively through a call) while holding A.

HG401  a cycle in the acquire graph (two lock orders that can deadlock),
       including re-entrant acquisition of a non-reentrant ``Lock``.
HG402  a method of a lock-owning class assigns ``self.<attr>`` outside any
       ``with <lock>`` block (methods named ``*_locked`` and constructors
       are exempt — they document the caller-holds-the-lock contract).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Optional

from tools.hglint.callgraph import CallGraph, CallSite
from tools.hglint.loader import ModuleInfo, resolve_fqn
from tools.hglint.model import Finding

LOCK_CTORS = {"threading.Lock", "threading.RLock",
              "multiprocessing.Lock", "multiprocessing.RLock"}
EXEMPT_METHODS = {"__init__", "__new__", "__enter__", "__exit__", "__del__",
                  "__post_init__"}


@dataclass
class LockRegistry:
    kinds: dict = field(default_factory=dict)      # lock id -> "Lock"|"RLock"
    class_attrs: dict = field(default_factory=dict)  # "mod.Cls" -> {attr}
    sites: dict = field(default_factory=dict)      # lock id -> (path, line)


def check(cg: CallGraph, modules: list) -> list:
    reg = _collect_locks(modules)
    if not reg.kinds:
        return []
    acquires, edges = _acquire_analysis(cg, reg)
    findings = _cycles(edges, reg)
    findings += _unlocked_mutations(cg, reg)
    findings += _unlocked_contract_calls(cg, reg)
    return findings


# -------------------------------------------------------------- lock registry


def _collect_locks(modules: list) -> LockRegistry:
    reg = LockRegistry()

    def record(lock_id: str, ctor_fqn: str, mod: ModuleInfo, node):
        reg.kinds[lock_id] = ctor_fqn.rsplit(".", 1)[-1]
        reg.sites[lock_id] = (mod.path, node.lineno)

    for mod in modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Assign) or \
                    not isinstance(node.value, ast.Call):
                continue
            fqn = resolve_fqn(node.value.func, mod)
            if fqn not in LOCK_CTORS:
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    record(f"{mod.name}.{tgt.id}", fqn, mod, node)
                elif isinstance(tgt, ast.Attribute) and \
                        isinstance(tgt.value, ast.Name) and \
                        tgt.value.id == "self":
                    cls = _enclosing_class(mod, node)
                    if cls:
                        lock_id = f"{mod.name}.{cls}.{tgt.attr}"
                        record(lock_id, fqn, mod, node)
                        reg.class_attrs.setdefault(
                            f"{mod.name}.{cls}", set()
                        ).add(tgt.attr)
    return reg


def _enclosing_class(mod: ModuleInfo, target: ast.AST) -> Optional[str]:
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ClassDef):
            if any(n is target for n in ast.walk(node)):
                return node.name
    return None


def _resolve_lock(expr: ast.AST, fi, reg: LockRegistry) -> Optional[str]:
    """Map a ``with``-item / ``.acquire()`` receiver to a lock id."""
    if isinstance(expr, ast.Attribute) and \
            isinstance(expr.value, ast.Name) and expr.value.id == "self" \
            and fi is not None and fi.cls_name:
        cand = f"{fi.mod.name}.{fi.cls_name}.{expr.attr}"
        if cand in reg.kinds:
            return cand
    if isinstance(expr, ast.Name) and fi is not None:
        cand = f"{fi.mod.name}.{expr.id}"
        if cand in reg.kinds:
            return cand
    fqn = resolve_fqn(expr, fi.mod) if fi is not None else None
    if fqn in reg.kinds:
        return fqn
    return None


# ---------------------------------------------------------- acquire analysis


def _acquire_analysis(cg: CallGraph, reg: LockRegistry):
    """Per-function direct acquires + held-call records, then a transitive
    fixpoint over the call graph to produce lock-order edges."""
    direct: dict[str, set] = {}          # fn key -> lock ids acquired
    held_calls: dict[str, list] = {}     # fn key -> [(lock, callee, site)]
    held_acquires: dict[str, list] = {}  # fn key -> [(lock, lock2, site)]

    for key, fi in cg.functions.items():
        d: set = set()
        hc: list = []
        ha: list = []
        _scan_body(cg, fi, fi.node, [], d, hc, ha, reg)
        direct[key] = d
        held_calls[key] = hc
        held_acquires[key] = ha

    # transitive acquires: T(f) = direct(f) U union T(callee)
    trans = {k: set(v) for k, v in direct.items()}
    changed = True
    while changed:
        changed = False
        for key in trans:
            for callee in cg.edges.get(key, ()):
                tc = trans.get(callee)
                if tc and not tc <= trans[key]:
                    trans[key] |= tc
                    changed = True

    edges: dict[tuple, tuple] = {}   # (A, B) -> (path, line, via)
    for key in cg.functions:
        for lock, other, site in held_acquires[key]:
            edges.setdefault((lock, other), site + (None,))
        for lock, callee, site in held_calls[key]:
            for other in trans.get(callee, ()):
                edges.setdefault((lock, other), site + (callee,))
    return trans, edges


def _lock_method_stmt(stmt: ast.AST, fi, reg, method: str):
    """``X.acquire()`` / ``X.release()`` as a bare statement -> lock id."""
    if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call) and \
            isinstance(stmt.value.func, ast.Attribute) and \
            stmt.value.func.attr == method:
        return _resolve_lock(stmt.value.func.value, fi, reg)
    return None


def _scan_stmts(cg, fi, stmts, held, direct, held_calls, held_acquires,
                reg, held_sites=None):
    """Scan a statement list in order, tracking holds from BOTH ``with``
    blocks and bare ``X.acquire()`` statements (held until a matching
    ``X.release()`` in the same list, else to the end of it — the
    acquire/try/finally-release idiom over-approximates safely)."""
    cur = list(held)
    for stmt in stmts:
        lock = _lock_method_stmt(stmt, fi, reg, "acquire")
        if lock is not None:
            direct.add(lock)
            site = (fi.mod.path, stmt.lineno)
            for h in cur:
                held_acquires.append((h, lock, site))
            cur.append(lock)
            continue
        lock = _lock_method_stmt(stmt, fi, reg, "release")
        if lock is not None:
            if lock in cur:
                cur.remove(lock)
            continue
        _scan_body(cg, fi, stmt, cur, direct, held_calls, held_acquires,
                   reg, held_sites)


def _scan_body(cg, fi, node, held, direct, held_calls, held_acquires, reg,
               held_sites=None):
    """Walk a function body tracking the held-lock stack. ``node`` itself is
    examined (so directly nested With/Call statements are seen), then its
    children; nested defs are skipped (they run later, not under the
    current hold). When ``held_sites`` is a list, every call made while at
    least one registered lock is held is appended as
    ``(held lock ids tuple, ast.Call)`` — the shared lock-context feed for
    the HG7xx blocking-under-lock rules."""
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef, ast.Lambda)) and node is not fi.node:
        return
    if isinstance(node, ast.With):
        got = []
        for item in node.items:
            # only Name/Attribute contexts can be lock slots; calls
            # (``with open(...)``) resolve to None naturally
            lock = _resolve_lock(item.context_expr, fi, reg)
            if lock is not None:
                direct.add(lock)
                site = (fi.mod.path, node.lineno)
                for h in held:
                    held_acquires.append((h, lock, site))
                got.append(lock)
        _scan_stmts(cg, fi, node.body, held + got, direct, held_calls,
                    held_acquires, reg, held_sites)
        return
    if isinstance(node, ast.Call):
        # non-statement .acquire() (e.g. ``if lk.acquire(timeout=..)``):
        # still an acquire event, though no hold scope can be inferred
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr == "acquire":
            lock = _resolve_lock(node.func.value, fi, reg)
            if lock is not None:
                direct.add(lock)
                site = (fi.mod.path, node.lineno)
                for h in held:
                    held_acquires.append((h, lock, site))
        elif held:
            if held_sites is not None and not (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "release"
            ):
                held_sites.append((tuple(held), node))
            site_obj = CallSite(node=node, fn_key=fi.key, mod=fi.mod)
            callee = cg.resolve_callable(node.func, site_obj)
            if callee is not None:
                site = (fi.mod.path, node.lineno)
                for h in held:
                    held_calls.append((h, callee, site))
    # statement lists of compound statements scan sequentially so bare
    # acquire/release pairs bound their holds; everything else recurses
    handled = set()
    for attr in ("body", "orelse", "finalbody"):
        stmts = getattr(node, attr, None)
        if isinstance(stmts, list) and stmts and \
                isinstance(stmts[0], ast.stmt):
            _scan_stmts(cg, fi, stmts, held, direct, held_calls,
                        held_acquires, reg, held_sites)
            handled.update(id(s) for s in stmts)
    for h in getattr(node, "handlers", ()) or ():
        _scan_stmts(cg, fi, h.body, held, direct, held_calls,
                    held_acquires, reg, held_sites)
        handled.update(id(s) for s in h.body)
    for child in ast.iter_child_nodes(node):
        if id(child) in handled or isinstance(child, ast.ExceptHandler):
            continue
        _scan_body(cg, fi, child, held, direct, held_calls, held_acquires,
                   reg, held_sites)


def _unlocked_contract_calls(cg: CallGraph, reg: LockRegistry) -> list:
    """HG403 — the INVERSE ``*_locked`` contract: the suffix promises
    "caller already holds the lock", so a call site where the hold
    tracker proves NO registered lock is held breaks the promise (the
    leaf's unsynchronized reads/writes race).  Exempt callers: functions
    themselves named ``*_locked`` (their OWN caller holds it) and the
    single-threaded EXEMPT_METHODS (``__init__`` & co.)."""
    held_sites = function_held_sites(cg, reg)
    findings = []
    for key, fi in sorted(cg.functions.items()):
        caller = fi.qualpath.rsplit(".", 1)[-1]
        if caller.endswith("_locked") or caller in EXEMPT_METHODS:
            continue
        held_ids = {id(node) for _, node in held_sites.get(key, ())}
        for node in _own_calls(fi.node):
            if id(node) in held_ids:
                continue
            site = CallSite(node=node, fn_key=key, mod=fi.mod)
            callee = cg.resolve_callable(node.func, site)
            cfi = cg.functions.get(callee) if callee else None
            if cfi is None or not \
                    cfi.qualpath.rsplit(".", 1)[-1].endswith("_locked"):
                continue
            findings.append(Finding(
                rule="HG403", path=fi.mod.path, line=node.lineno,
                scope=fi.qualpath,
                message=f"`{cfi.qualpath}` promises caller-held locking "
                        f"(`*_locked` contract) but `{fi.qualpath}` "
                        f"calls it holding no registered lock — take the "
                        f"owning lock (or rename the callee if it truly "
                        f"needs none)",
            ))
    return findings


def _own_calls(fn_node: ast.AST):
    """Call nodes in a function's own scope (nested defs/lambdas are
    their own functions with their own hold contexts)."""
    out: list = []

    def walk(node):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)) and \
                node is not fn_node:
            return
        if isinstance(node, ast.Call):
            out.append(node)
        for child in ast.iter_child_nodes(node):
            walk(child)

    walk(fn_node)
    return out


def function_held_sites(cg: CallGraph, reg: LockRegistry) -> dict:
    """Public lock-context feed: fn key -> ``[(held lock ids, ast.Call)]``
    for every call issued while at least one registered lock is held.
    Shared by the HG7xx blocking rules so hold tracking has exactly one
    implementation."""
    out: dict = {}
    for key, fi in cg.functions.items():
        sites: list = []
        _scan_body(cg, fi, fi.node, [], set(), [], [], reg, sites)
        if sites:
            out[key] = sites
    return out


# ------------------------------------------------------------------- HG401


def _cycles(edges: dict, reg: LockRegistry) -> list:
    graph: dict[str, set] = {}
    for (a, b) in edges:
        if a == b:
            continue  # self-edges handled below
        graph.setdefault(a, set()).add(b)

    findings = []
    seen_cycles = set()

    # self-edges: re-acquiring a non-reentrant Lock deadlocks immediately
    for (a, b), (path, line, via) in sorted(edges.items()):
        if a == b and reg.kinds.get(a) == "Lock":
            findings.append(Finding(
                rule="HG401", path=path, line=line, scope=a,
                message=f"non-reentrant Lock `{a}` re-acquired while "
                        f"already held"
                        + (f" (via call to {via})" if via else ""),
            ))

    def dfs(start, node, path_nodes):
        for nxt in sorted(graph.get(node, ())):
            if nxt == start:
                cyc = tuple(sorted(path_nodes))
                if cyc in seen_cycles:
                    continue
                seen_cycles.add(cyc)
                first_edge = (start, path_nodes[1]) if len(path_nodes) > 1 \
                    else (start, start)
                site = edges.get(first_edge) or next(iter(edges.values()))
                order = " -> ".join(path_nodes + [start])
                findings.append(Finding(
                    rule="HG401", path=site[0], line=site[1], scope=start,
                    message=f"lock acquisition cycle: {order}",
                ))
            elif nxt not in path_nodes and len(path_nodes) < 8:
                dfs(start, nxt, path_nodes + [nxt])

    for start in sorted(graph):
        dfs(start, start, [start])
    return findings


# ------------------------------------------------------------------- HG402


def _unlocked_mutations(cg: CallGraph, reg: LockRegistry) -> list:
    findings = []
    for key, fi in cg.functions.items():
        if fi.cls_name is None:
            continue
        cls_key = f"{fi.mod.name}.{fi.cls_name}"
        lock_attrs = reg.class_attrs.get(cls_key)
        if not lock_attrs:
            continue
        method = fi.qualpath.rsplit(".", 1)[-1]
        if method in EXEMPT_METHODS or method.endswith("_locked"):
            continue
        hits: list = []
        _scan_mutations(fi, fi.node, False, lock_attrs, reg, hits)
        for attr, line in hits:
            findings.append(Finding(
                rule="HG402", path=fi.mod.path, line=line,
                scope=fi.qualpath,
                message=f"`self.{attr}` assigned outside `with "
                        f"self.{sorted(lock_attrs)[0]}` in a lock-owning "
                        f"class",
            ))
    return findings


def _scan_mutations(fi, node, locked, lock_attrs, reg, hits):
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef, ast.Lambda)) and node is not fi.node:
        return
    if isinstance(node, ast.With):
        now_locked = locked or any(
            _resolve_lock(item.context_expr, fi, reg) is not None
            for item in node.items
        )
        for stmt in node.body:
            _scan_mutations(fi, stmt, now_locked, lock_attrs, reg, hits)
        return
    if not locked and isinstance(node, (ast.Assign, ast.AugAssign)):
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        for tgt in targets:
            if isinstance(tgt, ast.Attribute) and \
                    isinstance(tgt.value, ast.Name) and \
                    tgt.value.id == "self" and \
                    tgt.attr not in lock_attrs:
                hits.append((tgt.attr, tgt.lineno))
    for child in ast.iter_child_nodes(node):
        _scan_mutations(fi, child, locked, lock_attrs, reg, hits)
