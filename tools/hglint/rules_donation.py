"""HG106 — donated-buffer reuse after ``donate_argnums``/``donate_argnames``.

``jax.jit(f, donate_argnums=(0,))`` lets XLA alias argument 0's buffer
into the output: after the call the caller's array object still *exists*
but its device buffer is deleted. Reading it raises
``RuntimeError: Array has been deleted`` on hardware — and silently works
on CPU test runs where donation is a no-op, which is why this needs a
static rule.

The check is a statement-ordered taint scan per function:

- calls to donating callables (a ``@partial(jax.jit, donate_argnums=...)``
  decorated function, or a name bound to ``jax.jit(f, donate_...)`` at
  module or function scope) mark the plain-``Name`` arguments at donated
  positions as dead;
- any later ``Name`` load of a dead binding is HG106;
- rebinding (``x = step(x)`` — the donation idiom) clears the taint, as
  does any other store to the name;
- ``if``/``else`` branches are scanned independently and their taints
  union (a donation on either path poisons the join);
- a donating call INSIDE a loop whose donated name is never rebound in
  that loop body is flagged too: iteration 2 re-reads the buffer
  iteration 1 donated.
"""

from __future__ import annotations

import ast
from typing import Optional

from tools.hglint.callgraph import JIT_FQNS, PARTIAL_FQNS, CallGraph
from tools.hglint.loader import literal_value, resolve_fqn
from tools.hglint.model import Finding


def check(cg: CallGraph, modules: list) -> list:
    donors = _collect_donors(cg, modules)
    if not donors:
        return []
    findings = []
    for fi in cg.functions.values():
        vis = _visible_donors(donors, fi)
        if vis:
            _Scanner(cg, fi, vis, findings).run()
    return findings


# ----------------------------------------------------------------- donors


def _donate_kw(call: ast.Call, params: list) -> Optional[set]:
    """Donated *positional indices* from donate_argnums/donate_argnames
    keywords (argnames resolved through the callee's parameter list)."""
    out: set = set()
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            v = literal_value(kw.value)
            nums = [v] if isinstance(v, int) else list(v) \
                if isinstance(v, tuple) else []
            out |= {n for n in nums if isinstance(n, int)}
        elif kw.arg == "donate_argnames":
            v = literal_value(kw.value)
            names = [v] if isinstance(v, str) else list(v) \
                if isinstance(v, tuple) else []
            out |= {params.index(n) for n in names
                    if isinstance(n, str) and n in params}
    return out or None


def _collect_donors(cg: CallGraph, modules: list) -> dict:
    """Maps both function keys and caller-visible alias names to donated
    position sets:

    - ``key:<fn key>`` for decorated functions (called by their own name);
    - ``alias:<module>.<name>`` / ``alias:<fn key>.<name>`` for
      ``name = jax.jit(f, donate_...)`` bindings.
    """
    donors: dict[str, set] = {}
    # decorated: @partial(jax.jit, donate_argnums=...)
    for key, fi in cg.functions.items():
        for dec in getattr(fi.node, "decorator_list", ()):
            if not isinstance(dec, ast.Call):
                continue
            base = resolve_fqn(dec.func, fi.mod)
            inner = None
            if base in PARTIAL_FQNS and dec.args:
                inner = resolve_fqn(dec.args[0], fi.mod)
            if base in JIT_FQNS or inner in JIT_FQNS:
                pos = _donate_kw(dec, fi.params)
                if pos:
                    donors[f"key:{key}"] = pos
    # aliased: name = jax.jit(f, donate_...) at module or function scope
    for mod in modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Assign) or \
                    not isinstance(node.value, ast.Call) or \
                    len(node.targets) != 1 or \
                    not isinstance(node.targets[0], ast.Name):
                continue
            call = node.value
            if resolve_fqn(call.func, mod) not in JIT_FQNS or not call.args:
                continue
            target_fqn = resolve_fqn(call.args[0], mod)
            params = []
            if target_fqn in cg.functions:
                params = cg.functions[target_fqn].params
            pos = _donate_kw(call, params)
            if pos:
                donors[f"alias:{mod.name}.{node.targets[0].id}"] = pos
    return donors


def _visible_donors(donors: dict, fi) -> dict:
    """Callable-name -> donated positions, as visible from ``fi``'s body."""
    vis: dict[str, set] = {}
    for tag, pos in donors.items():
        kind, _, rest = tag.partition(":")
        if kind == "key":
            # called by bare name when defined in the same module, or by
            # its imported alias elsewhere
            name = rest.rsplit(".", 1)[-1]
            if rest.startswith(fi.mod.name + "."):
                vis[name] = pos
            else:
                for local, fqn in fi.mod.imports.items():
                    if fqn == rest:
                        vis[local] = pos
        else:
            mod_name, _, name = rest.rpartition(".")
            if mod_name == fi.mod.name:
                vis[name] = pos
            else:
                for local, fqn in fi.mod.imports.items():
                    if fqn == rest:
                        vis[local] = pos
    return vis


# ---------------------------------------------------------------- scanner


class _Scanner:
    def __init__(self, cg, fi, donors: dict, findings: list):
        self.cg = cg
        self.fi = fi
        self.donors = donors
        self.findings = findings

    def run(self) -> None:
        self._stmts(list(getattr(self.fi.node, "body", ())), {})

    # active: name -> (donation line, callee display name)

    def _stmts(self, stmts: list, active: dict) -> dict:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, ast.If):
                self._reads_expr(stmt.test, active)
                a1 = self._stmts(list(stmt.body), dict(active))
                a2 = self._stmts(list(stmt.orelse), dict(active))
                active = {**a1, **a2}
                continue
            if isinstance(stmt, (ast.For, ast.While)):
                # the iterator / condition is itself a read of any already-
                # donated binding
                self._reads_expr(
                    stmt.iter if isinstance(stmt, ast.For) else stmt.test,
                    active,
                )
                self._loop(stmt, active)
                continue
            if isinstance(stmt, ast.Try):
                for blk in (stmt.body, stmt.orelse, stmt.finalbody,
                            *[h.body for h in stmt.handlers]):
                    active = self._stmts(list(blk), active)
                continue
            if isinstance(stmt, ast.With):
                self._reads(stmt, active, items_only=True)
                active = self._stmts(list(stmt.body), active)
                continue
            self._linear(stmt, active)
        return active

    def _loop(self, stmt, active: dict) -> None:
        body = list(stmt.body) + list(stmt.orelse)
        before = set(active)
        inner = self._stmts(body, active)
        # a donation born inside the loop whose name survives to the end of
        # the body is re-read by iteration 2 — at minimum by the donating
        # call itself, or by the loop condition/iterator
        stored = _stored_names(body)
        for name, (line, callee) in list(inner.items()):
            if name in before or name in stored:
                continue
            read_line = line
            it = stmt.iter if isinstance(stmt, ast.For) else stmt.test
            for node in ast.walk(it):
                if isinstance(node, ast.Name) and node.id == name:
                    read_line = node.lineno
            self.findings.append(self._f(
                name, read_line, callee, line,
                extra=" on the next loop iteration",
            ))
            del inner[name]
        active.clear()
        active.update(inner)

    def _reads_expr(self, expr, active: dict) -> None:
        """Report loads of donated bindings inside a bare expression (a
        branch condition or loop iterator)."""
        if expr is None or not active:
            return
        for node in ast.walk(expr):
            if isinstance(node, ast.Name) and \
                    isinstance(node.ctx, ast.Load) and node.id in active:
                line, callee = active.pop(node.id)
                self.findings.append(
                    self._f(node.id, node.lineno, callee, line)
                )

    def _linear(self, stmt, active: dict) -> None:
        self._reads(stmt, active)
        donated = self._donations(stmt)
        stored = _stored_names([stmt])
        for name in stored:
            active.pop(name, None)
        for name, (line, callee) in donated.items():
            if name not in stored:
                active[name] = (line, callee)

    def _reads(self, stmt, active: dict, items_only: bool = False) -> None:
        if not active:
            return
        nodes = stmt.items if items_only else [stmt]
        for root in nodes:
            for node in ast.walk(root if not items_only else root.context_expr):
                if isinstance(node, ast.Name) and \
                        isinstance(node.ctx, ast.Load) and node.id in active:
                    line, callee = active.pop(node.id)
                    self.findings.append(
                        self._f(node.id, node.lineno, callee, line)
                    )

    def _donations(self, stmt) -> dict:
        out = {}
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call) or \
                    not isinstance(node.func, ast.Name):
                continue
            pos = self.donors.get(node.func.id)
            if not pos:
                continue
            for i, a in enumerate(node.args):
                if i in pos and isinstance(a, ast.Name):
                    out[a.id] = (node.lineno, node.func.id)
        return out

    def _f(self, name, read_line, callee, donate_line, extra="") -> Finding:
        return Finding(
            rule="HG106", path=self.fi.mod.path, line=read_line,
            scope=self.fi.qualpath,
            message=(
                f"`{name}` read{extra} after being donated to "
                f"`{callee}` at line {donate_line} — the device buffer is "
                f"freed by donate_argnums; rebind the result "
                f"(`{name} = {callee}(...)`) or drop the donation"
            ),
        )


def _stored_names(stmts: list) -> set:
    out: set = set()
    for stmt in stmts:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Name) and \
                    isinstance(node.ctx, (ast.Store, ast.Del)):
                out.add(node.id)
    return out
