"""HG7xx — blocking work while holding a lock.

A lock held across a blocking call stalls every thread that needs the
lock for as long as the call takes: the dispatch thread behind a sentinel
digest, every submit behind a router health probe, the apply worker
behind a peer send.  The reviews that shaped this family kept finding the
same shapes by hand (digest sorts under the sentinel lock, health-probe
timeouts stacking under the router lock) — this rule family finds them at
lint time.

Mechanics: a *blocking taint set* (``time.sleep``, socket/HTTP sends,
``Thread.join``, ``fsync``/``os.replace``, ``block_until_ready``/device
syncs, bounded-queue get/put, ...) is seeded from direct calls, propagated
backwards through the resolved call graph — direct by-name edges PLUS
arg-passed edges (a callable smuggled through a parameter or a dict
dispatch table runs in its caller's context) — and intersected with the
held-lock contexts the HG4xx lock engine already tracks
(``rules_locks.function_held_sites``).  Thread/timer ``target=``
callables are the carve-out: they run on ANOTHER thread, not under the
constructing caller's hold, so they feed no taint back (the call-graph
arg edges exclude them).

HG701  a direct blocking call while at least one registered lock is held.
HG702  a call while holding a lock whose callee *transitively* reaches a
       blocking primitive (the witness chain is named in the message).
HG703  O(n) work (``sorted(...)`` / ``.sort()``) while holding a lock —
       a whole-ring sort under the hot-path lock is a stall, not a
       deadlock, so this is a warning.

Escape hatches (both kept honest elsewhere):

- functions named ``*_locked`` are audited under-lock leaves (the HG402
  naming contract): findings inside them are suppressed and they do not
  propagate blocking taint to callers — the suffix is an audit marker for
  leaf instrument writes, not a free pass for real sleeps;
- ``# hglint: disable=HG70x`` on the offending line, which the HG901
  stale-suppression audit deletes the moment the rule stops firing.

``Condition.wait`` releases the condition's *own* lock while waiting, so
a wait on a condition constructed over lock L is not a hold of L — but
every OTHER held lock stays held across the wait and is flagged.
"""

from __future__ import annotations

import ast
from typing import Optional

from tools.hglint.callgraph import CallGraph, CallSite, _thread_target_args
from tools.hglint.loader import resolve_fqn
from tools.hglint.model import Finding
from tools.hglint.rules_locks import _collect_locks, function_held_sites

#: fully-qualified callables that block, no matter the receiver
BLOCKING_FQNS = {
    "time.sleep": "time.sleep",
    "os.fsync": "os.fsync (disk barrier)",
    "os.fdatasync": "os.fdatasync (disk barrier)",
    "os.replace": "os.replace (durable rename)",
    "select.select": "select.select",
    "socket.create_connection": "socket.create_connection",
    "urllib.request.urlopen": "urllib.request.urlopen (HTTP round trip)",
    "subprocess.run": "subprocess.run",
    "subprocess.call": "subprocess.call",
    "subprocess.check_call": "subprocess.check_call",
    "subprocess.check_output": "subprocess.check_output",
    "jax.block_until_ready": "jax.block_until_ready (device sync)",
    "jax.device_get": "jax.device_get (device sync)",
}

#: method names that block regardless of receiver type — names specific
#: enough that a false receiver is vanishingly unlikely in this codebase
BLOCKING_METHODS = {
    "sendall": "socket send",
    "recv": "socket receive",
    "recv_into": "socket receive",
    "recvfrom": "socket receive",
    "accept": "socket accept",
    "getresponse": "HTTP response wait",
    "block_until_ready": "device sync",
}

#: ctor fqns used to type receiver slots for the receiver-restricted
#: method rules (`.join` on threads, `.wait` on events/conditions,
#: `.get`/`.put` on bounded queues)
THREAD_CTORS = {"threading.Thread", "threading.Timer"}
EVENT_CTORS = {"threading.Event", "threading.Barrier"}
CONDITION_CTORS = {"threading.Condition"}
QUEUE_CTORS = {"queue.Queue", "queue.LifoQueue", "queue.PriorityQueue",
               "queue.SimpleQueue"}

_SORT_MSG = ("move the sort outside the critical section (snapshot under "
             "the lock, digest outside)")


def check(cg: CallGraph, modules: list) -> list:
    reg = _collect_locks(modules)
    if not reg.kinds:
        return []
    slots = _SlotRegistry(cg, modules)
    edges = _taint_edges(cg)
    blocked = _propagate_blocking(cg, slots, edges)
    findings = []
    for key, sites in sorted(function_held_sites(cg, reg).items()):
        fi = cg.functions[key]
        if _is_locked_leaf(fi):
            continue
        for held, node in sites:
            desc = _classify_blocking(node, fi, slots, held)
            if desc is not None:
                findings.append(_f("HG701", fi, node,
                                   f"blocking {desc} while holding "
                                   f"{_fmt_locks(held)}"))
                continue
            if _is_sort(node, fi):
                findings.append(_f(
                    "HG703", fi, node,
                    f"`{_spelling(node.func)}` while holding "
                    f"{_fmt_locks(held)} — {_SORT_MSG}",
                ))
                continue
            site = CallSite(node=node, fn_key=fi.key, mod=fi.mod)
            callee = cg.resolve_callable(node.func, site)
            if callee is not None and callee in blocked:
                chain = _witness_chain(callee, blocked)
                findings.append(_f(
                    "HG702", fi, node,
                    f"`{_spelling(node.func)}` called while holding "
                    f"{_fmt_locks(held)} reaches blocking "
                    f"{blocked[callee][0]} (via {chain})",
                ))
                continue
            hit = next((k for k in sorted(
                cg.resolve_dispatch(node.func, site)) if k in blocked),
                None)
            if hit is not None:
                chain = _witness_chain(hit, blocked)
                findings.append(_f(
                    "HG702", fi, node,
                    f"dispatch through `{_spelling(node.func)}` while "
                    f"holding {_fmt_locks(held)} can invoke a member "
                    f"that reaches blocking {blocked[hit][0]} "
                    f"(via {chain})",
                ))
                continue
            # a callable SMUGGLED through a parameter runs in the
            # (unresolvable) receiver's context — under this hold; thread
            # targets are exempt (they run on another thread)
            thread_args = _thread_target_args(site)
            for arg in list(node.args) + [k.value for k in node.keywords]:
                if id(arg) in thread_args or \
                        not isinstance(arg, (ast.Name, ast.Attribute)):
                    continue
                k = cg.resolve_callable(arg, site)
                if k is not None and k in blocked:
                    chain = _witness_chain(k, blocked)
                    findings.append(_f(
                        "HG702", fi, node,
                        f"callable `{_spelling(arg)}` passed while "
                        f"holding {_fmt_locks(held)} reaches blocking "
                        f"{blocked[k][0]} (via {chain})",
                    ))
                    break
    return findings


# --------------------------------------------------------------- slot typing


class _SlotRegistry:
    """Types the receiver slots the receiver-restricted rules need:
    thread/timer slots (``.join`` blocks), event/condition slots
    (``.wait`` blocks), bounded-queue slots (``.get``/``.put`` block).
    Slots are ``mod.Cls.attr`` for ``self.attr = ctor()``, ``mod.name``
    for module-level, plus per-function locals."""

    def __init__(self, cg: CallGraph, modules: list):
        self.kinds: dict = {}        # slot id -> "thread"|"event"|...
        self.cond_locks: dict = {}   # condition slot id -> bound lock id
        self._locals: dict = {}      # fn key -> {name: kind}
        self._local_cond_locks: dict = {}  # (fn key, name) -> lock id
        for mod in modules:
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Assign) or \
                        not isinstance(node.value, ast.Call):
                    continue
                kind = _ctor_kind(node.value, mod)
                if kind is None:
                    continue
                for tgt in node.targets:
                    slot = _slot_id(tgt, mod)
                    if slot is None:
                        continue
                    self.kinds[slot] = kind
                    if kind == "condition":
                        lk = _condition_lock(node.value, mod)
                        if lk is not None:
                            self.cond_locks[slot] = lk
        for key, fi in cg.functions.items():
            loc: dict = {}
            for node in ast.walk(fi.node):
                if isinstance(node, ast.Assign) and \
                        isinstance(node.value, ast.Call) and \
                        len(node.targets) == 1 and \
                        isinstance(node.targets[0], ast.Name):
                    kind = _ctor_kind(node.value, fi.mod)
                    if kind is not None:
                        name = node.targets[0].id
                        loc[name] = kind
                        if kind == "condition":
                            lk = _condition_lock(node.value, fi.mod)
                            if lk is not None:
                                self._local_cond_locks[(key, name)] = lk
            if loc:
                self._locals[key] = loc

    def receiver_kind(self, expr: ast.AST, fi) -> Optional[str]:
        slot = self._receiver_slot(expr, fi)
        if slot is None:
            return None
        if isinstance(slot, tuple):          # (fn key, local name)
            return self._locals.get(slot[0], {}).get(slot[1])
        return self.kinds.get(slot)

    def condition_lock(self, expr: ast.AST, fi) -> Optional[str]:
        slot = self._receiver_slot(expr, fi)
        if isinstance(slot, tuple):
            return self._local_cond_locks.get(slot)
        if slot is not None:
            return self.cond_locks.get(slot)
        return None

    def _receiver_slot(self, expr: ast.AST, fi):
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id == "self" and fi.cls_name:
            return f"{fi.mod.name}.{fi.cls_name}.{expr.attr}"
        if isinstance(expr, ast.Name):
            if expr.id in self._locals.get(fi.key, {}):
                return (fi.key, expr.id)
            return f"{fi.mod.name}.{expr.id}"
        return None


def _ctor_kind(call: ast.Call, mod) -> Optional[str]:
    fqn = resolve_fqn(call.func, mod)
    if fqn in THREAD_CTORS:
        return "thread"
    if fqn in EVENT_CTORS:
        return "event"
    if fqn in CONDITION_CTORS:
        return "condition"
    if fqn in QUEUE_CTORS:
        return "queue"
    return None


def _condition_lock(call: ast.Call, mod) -> Optional[str]:
    """``threading.Condition(self._lock)`` -> the wrapped lock's slot id
    (resolved textually; precise enough for the wait carve-out)."""
    args = list(call.args) + [k.value for k in call.keywords
                              if k.arg in (None, "lock")]
    for a in args:
        if isinstance(a, ast.Attribute) and \
                isinstance(a.value, ast.Name) and a.value.id == "self":
            return a.attr          # matched by attr suffix against held ids
        if isinstance(a, ast.Name):
            return a.id
    return None


def _slot_id(tgt: ast.AST, mod) -> Optional[str]:
    if isinstance(tgt, ast.Name):
        return f"{mod.name}.{tgt.id}"
    if isinstance(tgt, ast.Attribute) and \
            isinstance(tgt.value, ast.Name) and tgt.value.id == "self":
        cls = _enclosing_class_of(mod, tgt)
        if cls:
            return f"{mod.name}.{cls}.{tgt.attr}"
    return None


def _enclosing_class_of(mod, target: ast.AST) -> Optional[str]:
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ClassDef):
            if any(n is target for n in ast.walk(node)):
                return node.name
    return None


# ----------------------------------------------------------- classification


def _classify_blocking(node: ast.Call, fi, slots: _SlotRegistry,
                       held: tuple) -> Optional[str]:
    """Human-readable description when this call blocks, else None."""
    func = node.func
    fqn = resolve_fqn(func, fi.mod)
    if fqn in BLOCKING_FQNS:
        return f"`{BLOCKING_FQNS[fqn]}`"
    if not isinstance(func, ast.Attribute):
        return None
    attr = func.attr
    if attr in BLOCKING_METHODS:
        return (f"`{_spelling(func)}` ({BLOCKING_METHODS[attr]})")
    kind = slots.receiver_kind(func.value, fi)
    if attr == "join" and kind == "thread":
        return f"`{_spelling(func)}` (thread join)"
    if attr == "wait" and kind in ("event", "condition"):
        if kind == "condition":
            bound = slots.condition_lock(func.value, fi)
            # waiting on a condition over lock L releases L — only OTHER
            # held locks are held across the wait
            others = [h for h in held
                      if bound is None or not _lock_matches(h, bound)]
            if not others:
                return None
        return f"`{_spelling(func)}` ({kind} wait)"
    if kind == "queue" and attr in ("get", "put"):
        if any(k.arg == "block" and
               isinstance(k.value, ast.Constant) and k.value.value is False
               for k in node.keywords):
            return None
        return f"`{_spelling(func)}` (queue {attr})"
    return None


def _lock_matches(lock_id: str, bound: str) -> bool:
    return lock_id == bound or lock_id.rsplit(".", 1)[-1] == bound


def _is_sort(node: ast.Call, fi) -> bool:
    func = node.func
    if isinstance(func, ast.Name) and func.id == "sorted":
        return True
    if isinstance(func, ast.Attribute) and func.attr == "sort" \
            and not isinstance(func.value, ast.Constant):
        return True
    return False


# -------------------------------------------------------------- propagation


def _taint_edges(cg: CallGraph) -> dict:
    """fn key -> callees whose blocking taints the caller: by-name calls
    and dispatch-table fan-out (``cg.direct_edges``) plus callables
    passed as arguments (``cg.arg_edges`` — a combinator body or a
    callback smuggled through a parameter runs in the caller's context).
    Thread/timer ``target=`` callables are already excluded from
    ``arg_edges`` at graph build: they run on another thread, not under
    the caller's hold, so they must not feed blocking taint back."""
    edges: dict = {k: set(v) for k, v in cg.direct_edges.items()}
    for k, v in cg.arg_edges.items():
        edges.setdefault(k, set()).update(v)
    return edges


def _propagate_blocking(cg: CallGraph, slots: _SlotRegistry,
                        edges: dict) -> dict:
    """fn key -> (primitive description, next hop key or None) for every
    function that directly or transitively blocks. ``*_locked`` leaves are
    excluded as sources (the audited escape hatch)."""
    blocked: dict = {}
    for key, fi in cg.functions.items():
        if _is_locked_leaf(fi):
            continue
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Call):
                # the sentinel held set makes condition waits count as
                # blocking sources: from a caller's hold of any OTHER
                # lock, a helper's cv.wait() is a real stall
                desc = _classify_blocking(node, fi, slots,
                                          held=("<caller-held>",))
                if desc is not None:
                    blocked[key] = (desc, None)
                    break
    rev: dict = {}
    for caller, callees in edges.items():
        for c in callees:
            rev.setdefault(c, set()).add(caller)
    from collections import deque
    q = deque(blocked)
    while q:
        callee = q.popleft()
        for caller in rev.get(callee, ()):
            fi = cg.functions.get(caller)
            if caller not in blocked and fi is not None and \
                    not _is_locked_leaf(fi):
                blocked[caller] = (blocked[callee][0], callee)
                q.append(caller)
    return blocked


def _witness_chain(key: str, blocked: dict, limit: int = 4) -> str:
    names = [_short(key)]
    cur = key
    while blocked.get(cur, (None, None))[1] is not None and \
            len(names) < limit:
        cur = blocked[cur][1]
        names.append(_short(cur))
    return " -> ".join(names)


# ------------------------------------------------------------------ helpers


def _is_locked_leaf(fi) -> bool:
    return fi.qualpath.rsplit(".", 1)[-1].endswith("_locked")


def _fmt_locks(held: tuple) -> str:
    return " + ".join(f"`{h}`" for h in held)


def _spelling(func: ast.AST) -> str:
    try:
        return ast.unparse(func)
    except Exception:  # pragma: no cover
        return "<call>"


def _short(key: str) -> str:
    return key.rsplit(".", 1)[-1] if "." in key else key


def _f(rule: str, fi, node: ast.AST, msg: str) -> Finding:
    return Finding(rule=rule, path=fi.mod.path,
                   line=getattr(node, "lineno", fi.lineno),
                   message=msg, scope=fi.qualpath)
