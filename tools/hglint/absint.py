"""Abstract interpreter for hglint's semantic rules (HG5xx/HG6xx/HG106).

Propagates *compile-time-knowable* facts through the AST call graph:

- integer/float/string/tuple constants, with shape arithmetic folding
  (``n // 2``, ``t + (1,)``, ``t[0]``, ``len(t)``, ``-(-n // m) * m``);
- array values as :class:`ShapeDtype` (shape tuple with per-dim holes,
  dtype name) built from ``jnp.zeros/ones/full/arange/asarray``,
  ``jax.ShapeDtypeStruct``, ``.reshape``/``.astype``/``.T``/``.shape``;
- mesh-axis environments as :class:`MeshEnv` from ``Mesh(devs, axes)``
  constructions (``jax.sharding.Mesh`` or any ``*.Mesh`` spelling);
- **interprocedural constant propagation**: a parameter binds to a value
  when every resolved call site (plus its default) agrees on it — the
  join of disagreeing sites is :data:`UNKNOWN`, never a guess;
- one level of return-value propagation for trivial bodies (a function
  whose body is a single evaluable ``return`` folds at its call sites,
  e.g. ``make_mesh()`` returning ``Mesh(devices, (axis,))``).

Everything stays pure AST work: unresolvable means :data:`UNKNOWN`, and
rules built on this module must stay silent (or emit an explicit
"unresolvable" diagnostic) rather than guess.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Optional

from tools.hglint.callgraph import CallGraph, CallSite
from tools.hglint.loader import ModuleInfo, dtype_name, resolve_fqn


class _Unknown:
    """Singleton bottom value — ``None`` stays available as Python None."""

    _inst = None

    def __new__(cls):
        if cls._inst is None:
            cls._inst = super().__new__(cls)
        return cls._inst

    def __repr__(self):  # pragma: no cover - debugging aid
        return "<unknown>"


UNKNOWN = _Unknown()

#: a parameter no call site supplies (distinct from "supplied but unknown")
_MISSING = object()


@dataclass(frozen=True)
class ShapeDtype:
    """Abstract array: shape is a tuple whose entries are ints or UNKNOWN;
    ``shape is None`` means even the rank is unknown."""

    shape: Optional[tuple]
    dtype: Optional[str]

    def dim(self, i: int):
        if self.shape is None or not (-len(self.shape) <= i < len(self.shape)):
            return UNKNOWN
        return self.shape[i]


@dataclass(frozen=True)
class MeshEnv:
    """Known mesh-axis names of a ``Mesh`` construction."""

    axes: tuple  # tuple[str, ...]


#: dtype name -> element size in bytes (default for unknown dtypes is 4:
#: every index/mask array in this codebase is 32-bit, and assuming wider
#: would flag kernels we cannot prove over budget)
DTYPE_BYTES = {
    "float64": 8, "int64": 8, "uint64": 8, "complex64": 8,
    "float32": 4, "int32": 4, "uint32": 4,
    "bfloat16": 2, "float16": 2, "int16": 2, "uint16": 2,
    "int8": 1, "uint8": 1, "float8_e4m3fn": 1, "float8_e5m2": 1,
    "bool": 1, "bool_": 1,
}
DEFAULT_DTYPE_BYTES = 4

_ZEROS_LIKE = ("zeros", "ones", "empty")
_JNP_HEADS = ("jax.numpy.", "numpy.")


def _is_jnp(fqn: str, name: str) -> bool:
    return any(fqn == h + name for h in _JNP_HEADS)


class Interp:
    """Whole-program abstract interpreter over a built :class:`CallGraph`."""

    MAX_PASSES = 3
    _RET_DEPTH = 3

    def __init__(self, cg: CallGraph, modules: list):
        self.cg = cg
        self.modules = {m.name: m for m in modules}
        self.bindings: dict[str, dict] = {}   # fn key -> {param: value}
        self._env_cache: dict[str, dict] = {}
        self._ret_stack: list = []
        self._infer_bindings()

    # -- public API -----------------------------------------------------------

    def env_for(self, fi) -> dict:
        """Name -> abstract value environment for a function: parameter
        bindings (joined over call sites) + straight-line local assigns.
        A name assigned more than once keeps its LAST evaluable value,
        matching :class:`loader.ConstEnv` — good enough for the literal
        shape plumbing these rules read."""
        cached = self._env_cache.get(fi.key)
        if cached is not None:
            return cached
        env = dict(self.bindings.get(fi.key, {}))
        self._fold_locals(fi.node, env, fi.mod)
        self._env_cache[fi.key] = env
        return env

    def eval_in(self, node: ast.AST, fi) -> object:
        """Evaluate an expression in a function's environment (module env
        when ``fi`` is None)."""
        if fi is None:
            return self.eval(node, {}, None)
        return self.eval(node, self.env_for(fi), fi.mod)

    def dtype_of(self, node: ast.AST, env: dict, mod) -> Optional[str]:
        """Dtype name for a dtype-position expression: literal spellings
        via :func:`loader.dtype_name`, else abstract evaluation (a name
        bound to a dtype string)."""
        if node is None:
            return None
        if mod is not None:
            dt = dtype_name(node, mod)
            if dt is not None:
                return dt
        v = self.eval(node, env, mod)
        return v if isinstance(v, str) else None

    # -- interprocedural parameter bindings -----------------------------------

    def _infer_bindings(self) -> None:
        for _ in range(self.MAX_PASSES):
            nxt = self._one_binding_pass()
            if nxt == self.bindings:
                break
            self.bindings = nxt
            self._env_cache.clear()

    def _one_binding_pass(self) -> dict:
        # seed with evaluable parameter defaults: a default participates in
        # the join alongside every call-site value, so a parameter binds
        # only when the default and all sites agree (or sites always
        # override it with one common value and there is no default)
        cand: dict[str, dict] = {}
        for key, fi in self.cg.functions.items():
            cand[key] = {}
            args = fi.node.args
            pos = args.posonlyargs + args.args
            defaults = args.defaults
            for p, d in zip(pos[len(pos) - len(defaults):], defaults):
                cand[key][p.arg] = {self._freeze(
                    self.eval(d, {}, fi.mod)
                )}
            for p, d in zip(args.kwonlyargs, args.kw_defaults):
                if d is not None:
                    cand[key][p.arg] = {self._freeze(
                        self.eval(d, {}, fi.mod)
                    )}
        for site in self.cg.calls:
            callee = self.cg.resolve_callable(site.node.func, site)
            if callee is None:
                continue
            fi = self.cg.functions[callee]
            caller = self.cg.functions.get(site.fn_key) \
                if site.fn_key else None
            env = self.env_for(caller) if caller is not None else {}
            mod = caller.mod if caller is not None else site.mod
            params = fi.params
            # bound-method call sites skip the explicit self/cls argument
            off = 0
            if params and params[0] in ("self", "cls") and \
                    isinstance(site.node.func, ast.Attribute):
                off = 1
            for i, a in enumerate(site.node.args):
                if isinstance(a, ast.Starred):
                    break
                if i + off < len(params):
                    cand[callee].setdefault(params[i + off], set()).add(
                        self._freeze(self.eval(a, env, mod))
                    )
            for kw in site.node.keywords:
                if kw.arg and kw.arg in params:
                    cand[callee].setdefault(kw.arg, set()).add(
                        self._freeze(self.eval(kw.value, env, mod))
                    )
        out: dict[str, dict] = {}
        for key, pv in cand.items():
            bound = {}
            for name, vals in pv.items():
                if len(vals) == 1:
                    v = next(iter(vals))
                    if v is not UNKNOWN:
                        bound[name] = v
            if bound:
                out[key] = bound
        return out

    @staticmethod
    def _freeze(v):
        """Hashable form for join sets (ShapeDtype/MeshEnv are frozen
        dataclasses already; tuples recurse naturally)."""
        try:
            hash(v)
            return v
        except TypeError:  # pragma: no cover - lists inside tuples etc.
            return UNKNOWN

    # -- local straight-line folding ------------------------------------------

    def _fold_locals(self, fn_node, env: dict, mod) -> None:
        def bind(target, value):
            if isinstance(target, ast.Name):
                env[target.id] = value
            elif isinstance(target, (ast.Tuple, ast.List)):
                # tuple unpacking — `carry, ys = lax.scan(...)` — binds
                # element-wise when the value folds to a matching tuple
                vals = value if isinstance(value, tuple) and \
                    len(value) == len(target.elts) else \
                    (UNKNOWN,) * len(target.elts)
                for t, v in zip(target.elts, vals):
                    bind(t, v)

        def walk(node):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef)):
                    continue
                if isinstance(child, ast.Assign) and \
                        len(child.targets) == 1:
                    bind(child.targets[0],
                         self.eval(child.value, env, mod))
                elif isinstance(child, ast.AnnAssign) and \
                        isinstance(child.target, ast.Name) and \
                        child.value is not None:
                    env[child.target.id] = self.eval(child.value, env, mod)
                walk(child)

        walk(fn_node)

    # -- expression evaluation -------------------------------------------------

    def eval(self, node: ast.AST, env: dict, mod) -> object:  # noqa: C901
        if node is None:
            return UNKNOWN
        if isinstance(node, ast.Constant):
            return node.value
        if isinstance(node, ast.Name):
            if node.id in env:
                return env[node.id]
            if mod is not None and node.id in mod.consts:
                return mod.consts[node.id]
            return UNKNOWN
        if isinstance(node, (ast.Tuple, ast.List)):
            return tuple(self.eval(e, env, mod) for e in node.elts)
        if isinstance(node, ast.UnaryOp):
            v = self.eval(node.operand, env, mod)
            if isinstance(node.op, ast.USub) and isinstance(v, (int, float)):
                return -v
            return UNKNOWN
        if isinstance(node, ast.BinOp):
            return self._binop(node, env, mod)
        if isinstance(node, ast.Attribute):
            return self._attribute(node, env, mod)
        if isinstance(node, ast.Subscript):
            return self._subscript(node, env, mod)
        if isinstance(node, ast.Call):
            return self._call(node, env, mod)
        if isinstance(node, ast.IfExp):
            a = self.eval(node.body, env, mod)
            b = self.eval(node.orelse, env, mod)
            return a if a == b else UNKNOWN
        return UNKNOWN

    def _binop(self, node: ast.BinOp, env, mod):
        lhs = self.eval(node.left, env, mod)
        rhs = self.eval(node.right, env, mod)
        # tuple algebra for shape math
        if isinstance(node.op, ast.Add) and isinstance(lhs, tuple) \
                and isinstance(rhs, tuple):
            return lhs + rhs
        if isinstance(node.op, ast.Mult):
            if isinstance(lhs, tuple) and isinstance(rhs, int):
                return lhs * rhs
            if isinstance(lhs, int) and isinstance(rhs, tuple):
                return rhs * lhs
        if not isinstance(lhs, (int, float)) or \
                not isinstance(rhs, (int, float)):
            return UNKNOWN
        try:
            if isinstance(node.op, ast.Add):
                return lhs + rhs
            if isinstance(node.op, ast.Sub):
                return lhs - rhs
            if isinstance(node.op, ast.Mult):
                return lhs * rhs
            if isinstance(node.op, ast.FloorDiv):
                return lhs // rhs
            if isinstance(node.op, ast.Div):
                return lhs / rhs
            if isinstance(node.op, ast.Mod):
                return lhs % rhs
            if isinstance(node.op, ast.Pow):
                return lhs ** rhs
            if isinstance(node.op, ast.LShift):
                return lhs << rhs
            if isinstance(node.op, ast.RShift):
                return lhs >> rhs
        except Exception:
            return UNKNOWN
        return UNKNOWN

    def _attribute(self, node: ast.Attribute, env, mod):
        base = self.eval(node.value, env, mod)
        if isinstance(base, ShapeDtype):
            if node.attr == "shape":
                return base.shape if base.shape is not None else UNKNOWN
            if node.attr == "dtype":
                return base.dtype if base.dtype is not None else UNKNOWN
            if node.attr == "ndim" and base.shape is not None:
                return len(base.shape)
            if node.attr == "size" and base.shape is not None and \
                    all(isinstance(d, int) for d in base.shape):
                n = 1
                for d in base.shape:
                    n *= d
                return n
            if node.attr == "T" and base.shape is not None:
                return ShapeDtype(tuple(reversed(base.shape)), base.dtype)
            return UNKNOWN
        if isinstance(base, MeshEnv) and node.attr == "axis_names":
            return base.axes
        # cross-module constant: resolve `pkg.mod.CONST` through the import
        # map, then look the name up in that module's literal consts
        if mod is not None:
            fqn = resolve_fqn(node, mod)
            if fqn and "." in fqn:
                mname, _, attr = fqn.rpartition(".")
                other = self.modules.get(mname)
                if other is not None and attr in other.consts:
                    return other.consts[attr]
        return UNKNOWN

    def _subscript(self, node: ast.Subscript, env, mod):
        base = self.eval(node.value, env, mod)
        if not isinstance(base, tuple):
            return UNKNOWN
        if isinstance(node.slice, ast.Slice):
            lo = self.eval(node.slice.lower, env, mod) \
                if node.slice.lower else None
            hi = self.eval(node.slice.upper, env, mod) \
                if node.slice.upper else None
            if (lo is None or isinstance(lo, int)) and \
                    (hi is None or isinstance(hi, int)):
                return base[lo:hi]
            return UNKNOWN
        idx = self.eval(node.slice, env, mod)
        if isinstance(idx, int) and -len(base) <= idx < len(base):
            return base[idx]
        return UNKNOWN

    # -- calls ----------------------------------------------------------------

    def _call(self, node: ast.Call, env, mod):  # noqa: C901
        # method-style calls on abstract arrays
        if isinstance(node.func, ast.Attribute):
            meth = node.func.attr
            base = self.eval(node.func.value, env, mod)
            if isinstance(base, ShapeDtype):
                return self._array_method(base, meth, node, env, mod)
        # wrapper-applied calls: ``jax.vmap(f)(xs)`` parses as
        # Call(Call(vmap, f), xs) — fold through the mapped function
        # (jnp.vectorize is NOT folded: its scalar-core-dims semantics map
        # over every dimension, not just axis 0)
        if isinstance(node.func, ast.Call) and mod is not None:
            if resolve_fqn(node.func.func, mod) == "jax.vmap":
                return self._vmap_result(node, env, mod)
        if mod is None:
            return UNKNOWN
        fqn = resolve_fqn(node.func, mod)
        if fqn is None:
            return UNKNOWN
        if fqn == "jax.lax.scan":
            # scan returns (final_carry, stacked_ys): the carry keeps the
            # init's abstract value; the stacked outputs stay unknown
            kw = {k.arg: k.value for k in node.keywords if k.arg}
            init_node = kw.get(
                "init", node.args[1] if len(node.args) > 1 else None
            )
            if init_node is None:
                return UNKNOWN
            return (self.eval(init_node, env, mod), UNKNOWN)
        if fqn == "len":
            v = self.eval(node.args[0], env, mod) if node.args else UNKNOWN
            if isinstance(v, tuple):
                return len(v)
            if isinstance(v, ShapeDtype) and v.shape is not None:
                return v.dim(0)
            return UNKNOWN
        if fqn in ("int", "float") and len(node.args) == 1:
            v = self.eval(node.args[0], env, mod)
            if isinstance(v, (int, float)):
                return int(v) if fqn == "int" else float(v)
            return UNKNOWN
        if fqn in ("min", "max") and node.args and not node.keywords:
            vals = [self.eval(a, env, mod) for a in node.args]
            if all(isinstance(v, (int, float)) for v in vals):
                return min(vals) if fqn == "min" else max(vals)
            return UNKNOWN
        kw = {k.arg: k.value for k in node.keywords if k.arg}
        if fqn.endswith(".ShapeDtypeStruct"):
            shape_node = kw.get("shape", node.args[0] if node.args else None)
            dt_node = kw.get(
                "dtype", node.args[1] if len(node.args) > 1 else None
            )
            return ShapeDtype(
                self._as_shape(self.eval(shape_node, env, mod)),
                self.dtype_of(dt_node, env, mod),
            )
        if fqn.endswith(".Mesh"):
            ax_node = kw.get(
                "axis_names", node.args[1] if len(node.args) > 1 else None
            )
            axes = self.eval(ax_node, env, mod)
            if isinstance(axes, str):
                return MeshEnv((axes,))
            if isinstance(axes, tuple) and axes and \
                    all(isinstance(a, str) for a in axes):
                return MeshEnv(axes)
            return UNKNOWN
        hit = self._jnp_ctor(fqn, node, kw, env, mod)
        if hit is not UNKNOWN:
            return hit
        # single-return user functions fold at the call site
        return self._fold_return(fqn, node, env, mod)

    def _array_method(self, base: ShapeDtype, meth, node, env, mod):
        if meth == "astype" and node.args:
            return ShapeDtype(
                base.shape, self.dtype_of(node.args[0], env, mod)
            )
        if meth == "reshape":
            dims = [self.eval(a, env, mod) for a in node.args]
            if len(dims) == 1 and isinstance(dims[0], tuple):
                dims = list(dims[0])
            shape = tuple(
                d if isinstance(d, int) and d >= 0 else UNKNOWN for d in dims
            )
            return ShapeDtype(shape if dims else None, base.dtype)
        if meth == "view" and node.args:
            return ShapeDtype(base.shape, self.dtype_of(node.args[0], env, mod))
        return UNKNOWN

    def _jnp_ctor(self, fqn, node, kw, env, mod):
        dt = self.dtype_of(kw.get("dtype"), env, mod)
        for name in _ZEROS_LIKE:
            if _is_jnp(fqn, name):
                shape = self._as_shape(
                    self.eval(node.args[0], env, mod) if node.args
                    else UNKNOWN
                )
                return ShapeDtype(shape, dt or "float32")
        if _is_jnp(fqn, "full"):
            shape = self._as_shape(
                self.eval(node.args[0], env, mod) if node.args else UNKNOWN
            )
            return ShapeDtype(shape, dt)
        if _is_jnp(fqn, "arange"):
            n = self.eval(node.args[0], env, mod) if node.args else UNKNOWN
            shape = (n,) if isinstance(n, int) else (UNKNOWN,)
            return ShapeDtype(shape, dt or (
                "int32" if isinstance(n, int) else None
            ))
        if _is_jnp(fqn, "asarray") or _is_jnp(fqn, "array"):
            v = self.eval(node.args[0], env, mod) if node.args else UNKNOWN
            if isinstance(v, ShapeDtype):
                return ShapeDtype(v.shape, dt or v.dtype)
            if isinstance(v, tuple):
                return ShapeDtype((len(v),), dt)
            return ShapeDtype(None, dt)
        return UNKNOWN

    def _vmap_result(self, node: ast.Call, env, mod):
        """``jax.vmap(f)(xs, ...)`` with default axes: fold ``f``'s
        single-return body over the element shapes (leading dim stripped)
        and prepend the common batch dim to the result. Any explicit
        ``in_axes``/``out_axes`` (or unfoldable pieces) bail to UNKNOWN —
        silence over guessing non-zero axis arithmetic."""
        wrap = node.func
        if wrap.keywords or len(wrap.args) != 1 or not node.args or \
                node.keywords:
            return UNKNOWN
        fn_fqn = resolve_fqn(wrap.args[0], mod)
        if fn_fqn is None:
            return UNKNOWN
        batch = None
        elems = []
        for a in node.args:
            if isinstance(a, ast.Starred):
                return UNKNOWN
            v = self.eval(a, env, mod)
            if not isinstance(v, ShapeDtype) or v.shape is None or \
                    len(v.shape) < 1 or not isinstance(v.shape[0], int):
                return UNKNOWN
            if batch is None:
                batch = v.shape[0]
            elif v.shape[0] != batch:
                return UNKNOWN
            elems.append(ShapeDtype(v.shape[1:], v.dtype))
        out = self._fold_return(fn_fqn, node, env, mod, arg_vals=elems)
        if isinstance(out, ShapeDtype) and out.shape is not None:
            return ShapeDtype((batch,) + out.shape, out.dtype)
        return UNKNOWN

    def _fold_return(self, fqn, node, env, mod, arg_vals=None):
        fi = self.cg.functions.get(fqn)
        if fi is None or len(self._ret_stack) >= self._RET_DEPTH or \
                fqn in self._ret_stack:
            return UNKNOWN
        body = getattr(fi.node, "body", None)
        ret = None
        if body:
            stmts = [s for s in body
                     if not isinstance(s, (ast.Expr,))]  # skip docstrings
            if len(stmts) == 1 and isinstance(stmts[0], ast.Return):
                ret = stmts[0].value
        if ret is None:
            return UNKNOWN
        if arg_vals is not None:
            # explicit abstract arguments (the vmap element shapes)
            callee_env = dict(self.bindings.get(fqn, {}))
            for p, v in zip(fi.params, arg_vals):
                callee_env[p] = v
            self._ret_stack.append(fqn)
            try:
                return self.eval(ret, callee_env, fi.mod)
            finally:
                self._ret_stack.pop()
        # bind THIS call's arguments over the callee's defaults
        callee_env = dict(self.bindings.get(fqn, {}))
        params = fi.params
        off = 1 if params and params[0] in ("self", "cls") and \
            isinstance(node.func, ast.Attribute) else 0
        for i, a in enumerate(node.args):
            if isinstance(a, ast.Starred):
                break
            if i + off < len(params):
                callee_env[params[i + off]] = self.eval(a, env, mod)
        for k in node.keywords:
            if k.arg and k.arg in params:
                callee_env[k.arg] = self.eval(k.value, env, mod)
        self._ret_stack.append(fqn)
        try:
            return self.eval(ret, callee_env, fi.mod)
        finally:
            self._ret_stack.pop()

    @staticmethod
    def _as_shape(v):
        if isinstance(v, int):
            return (v,)
        if isinstance(v, tuple):
            return tuple(d if isinstance(d, int) else UNKNOWN for d in v)
        return None


# ------------------------------------------------------------ shard_map envs


_PSPEC_TAILS = (".PartitionSpec", ".P")


def collect_axis_names(expr: ast.AST, interp: Interp, fi) -> set:
    """Axis-name strings appearing in ``PartitionSpec(...)`` constructions
    inside an ``in_specs``/``out_specs`` expression — the fallback mesh-axis
    environment when the ``mesh=`` object itself doesn't fold."""
    out: set = set()
    if expr is None or fi is None:
        return out
    for node in ast.walk(expr):
        if not isinstance(node, ast.Call):
            continue
        fqn = resolve_fqn(node.func, fi.mod) or ""
        if not (fqn.endswith(_PSPEC_TAILS) or fqn == "P"):
            continue
        for a in list(node.args) + [k.value for k in node.keywords]:
            v = interp.eval_in(a, fi)
            for s in _flat_strs(v):
                out.add(s)
    return out


def _flat_strs(v):
    if isinstance(v, str):
        yield v
    elif isinstance(v, tuple):
        for e in v:
            yield from _flat_strs(e)


def mesh_axes_for_site(site: CallSite, interp: Interp, cg: CallGraph):
    """Mesh-axis environment of a ``shard_map``/``pjit`` call site: the
    folded ``mesh=`` object when resolvable, else the axis names named in
    the site's partition specs. Returns a (possibly empty) frozenset, or
    ``None`` when nothing at the site resolves — callers must then stay
    silent rather than flag against a guessed environment."""
    fi = cg.functions.get(site.fn_key) if site.fn_key else None
    kw = {k.arg: k.value for k in site.node.keywords if k.arg}
    mesh_node = kw.get("mesh")
    if mesh_node is not None and fi is not None:
        v = interp.eval_in(mesh_node, fi)
        if isinstance(v, MeshEnv):
            return frozenset(v.axes)
    axes: set = set()
    for name in ("in_specs", "out_specs"):
        if name in kw:
            axes |= collect_axis_names(kw[name], interp, fi)
    return frozenset(axes) if axes else None


def element_bytes(dtype: Optional[str]) -> int:
    return DTYPE_BYTES.get(dtype or "", DEFAULT_DTYPE_BYTES)
