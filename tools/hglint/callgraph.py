"""Function index, jit-root discovery, call graph, and traced-taint pass.

The taint model: a function is *traced* when JAX may execute its body under
tracing — it is decorated with (or passed to) ``jax.jit`` / ``pjit`` /
``shard_map`` / ``pl.pallas_call``, it is (transitively) called from such a
function, or it is defined inside one (closures handed to ``lax.fori_loop``
/ ``scan`` / ``vmap``). Host-side wrappers that merely *call* jitted
functions are not traced — taint flows root -> callee, never callee ->
caller.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Optional

from tools.hglint.loader import ModuleInfo, literal_value, resolve_fqn

JIT_FQNS = {
    "jax.jit",
    "jax.pjit",
    "jax.experimental.pjit.pjit",
}
SHARD_FQNS = {
    "jax.shard_map",
    "jax.experimental.shard_map.shard_map",
}
PALLAS_FQNS = {
    "jax.experimental.pallas.pallas_call",
}
PARTIAL_FQNS = {"functools.partial"}
WRAPPER_FQNS = JIT_FQNS | SHARD_FQNS | PALLAS_FQNS
#: thread/timer constructors — a callable passed as their ``target=`` runs
#: on ANOTHER thread, so it must not feed caller-context taint (blocking,
#: raise-sets) back through the arg-passed edge
THREAD_CTORS = {"threading.Thread", "threading.Timer"}


@dataclass
class FunctionInfo:
    key: str                 # "<module>.<qualpath>"
    mod: ModuleInfo
    qualpath: str            # "Class.method", "func", "outer.inner"
    node: ast.AST            # FunctionDef | AsyncFunctionDef | Lambda
    cls_name: Optional[str]
    params: list
    lineno: int
    parent: Optional[str] = None          # enclosing function key
    children: dict = field(default_factory=dict)  # local def name -> key
    static_params: set = field(default_factory=set)
    root_kind: Optional[str] = None       # "jit" | "shard_map" | "pallas_call"

    @property
    def is_root(self) -> bool:
        return self.root_kind is not None


@dataclass
class CallSite:
    node: ast.Call
    fn_key: Optional[str]    # enclosing function (None at module level)
    mod: ModuleInfo


class CallGraph:
    def __init__(self):
        self.functions: dict[str, FunctionInfo] = {}
        self.calls: list[CallSite] = []
        self.edges: dict[str, set] = {}
        self.traced: dict[str, str] = {}   # fn key -> root key it's traced via
        #: fn key -> callees invoked by NAME (``f(...)`` / ``self.m(...)``)
        self.direct_edges: dict[str, set] = {}
        #: fn key -> callables PASSED as arguments (combinator bodies,
        #: callbacks smuggled through a parameter) — thread/timer targets
        #: are excluded: they run on another thread, not in the caller's
        #: context, so caller-context taint must not follow them
        self.arg_edges: dict[str, set] = {}
        #: dispatch-table slot id -> function keys stored as dict values
        #: (``HANDLERS = {"x": handle_x}`` / ``self._ops = {...}``) — a
        #: call through ``HANDLERS[kind](...)`` fans out to all of them
        self.dispatch_tables: dict[str, set] = {}

    # -- construction ---------------------------------------------------------

    @classmethod
    def build(cls, modules: list[ModuleInfo]) -> "CallGraph":
        cg = cls()
        for mod in modules:
            _index_functions(cg, mod)
        cg._mark_wrapper_callsite_roots()
        cg._index_dispatch_tables(modules)
        cg._build_edges()
        cg._propagate_taint()
        return cg

    # -- resolution -----------------------------------------------------------

    def resolve_callable(
        self, expr: ast.AST, site: CallSite
    ) -> Optional[str]:
        """Resolve a callable expression at a call site to a function key,
        searching enclosing local defs, same-class methods, module-level
        functions, then imports."""
        fn = self.functions.get(site.fn_key) if site.fn_key else None
        if isinstance(expr, ast.Name):
            cur = fn
            while cur is not None:
                if expr.id in cur.children:
                    return cur.children[expr.id]
                cur = self.functions.get(cur.parent) if cur.parent else None
            local = f"{site.mod.name}.{expr.id}"
            if local in self.functions:
                return local
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id in ("self", "cls")
            and fn is not None
            and fn.cls_name
        ):
            cand = f"{site.mod.name}.{fn.cls_name}.{expr.attr}"
            if cand in self.functions:
                return cand
        fqn = resolve_fqn(expr, site.mod)
        if fqn and fqn in self.functions:
            return fqn
        return None

    # -- roots ----------------------------------------------------------------

    def _mark_wrapper_callsite_roots(self) -> None:
        for site in self.calls:
            fqn = resolve_fqn(site.node.func, site.mod)
            if fqn is None:
                continue
            kind = None
            if fqn in JIT_FQNS:
                kind = "jit"
            elif fqn in SHARD_FQNS:
                kind = "shard_map"
            elif fqn in PALLAS_FQNS:
                kind = "pallas_call"
            if kind is None or not site.node.args:
                continue
            target = _unwrap_partial(site.node.args[0], site.mod)
            key = self.resolve_callable(target, site)
            if key is None:
                continue
            fi = self.functions[key]
            if fi.root_kind is None:
                fi.root_kind = kind
            fi.static_params |= _static_params(site.node, fi)

    # -- edges + taint --------------------------------------------------------

    def _index_dispatch_tables(self, modules: list) -> None:
        """Record dict literals whose values are known functions, keyed by
        the slot they are stored in: ``HANDLERS = {"x": handle_x}`` at
        module level -> ``mod.HANDLERS``; ``self._ops = {"a": self._do_a}``
        inside a method -> ``mod.Cls._ops``."""

        def members(d: ast.Dict, mod, cls_name) -> set:
            out: set = set()
            for v in d.values:
                if isinstance(v, ast.Name):
                    cand = f"{mod.name}.{v.id}"
                    if cand in self.functions:
                        out.add(cand)
                elif isinstance(v, ast.Attribute) and \
                        isinstance(v.value, ast.Name) and \
                        v.value.id in ("self", "cls") and cls_name:
                    cand = f"{mod.name}.{cls_name}.{v.attr}"
                    if cand in self.functions:
                        out.add(cand)
            return out

        for mod in modules:
            for stmt in mod.tree.body:
                if isinstance(stmt, ast.Assign) and \
                        isinstance(stmt.value, ast.Dict):
                    for tgt in stmt.targets:
                        if isinstance(tgt, ast.Name):
                            ms = members(stmt.value, mod, None)
                            if ms:
                                self.dispatch_tables[
                                    f"{mod.name}.{tgt.id}"] = ms
        for key, fi in self.functions.items():
            if fi.cls_name is None:
                continue
            for node in ast.walk(fi.node):
                if isinstance(node, ast.Assign) and \
                        isinstance(node.value, ast.Dict):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Attribute) and \
                                isinstance(tgt.value, ast.Name) and \
                                tgt.value.id == "self":
                            ms = members(node.value, fi.mod, fi.cls_name)
                            if ms:
                                self.dispatch_tables[
                                    f"{fi.mod.name}.{fi.cls_name}."
                                    f"{tgt.attr}"] = ms

    def resolve_dispatch(self, expr: ast.AST, site: CallSite) -> set:
        """``HANDLERS[kind]`` / ``self._ops[op]`` -> the function keys the
        subscripted dispatch table can fan out to (empty when the receiver
        is not a known table)."""
        if not isinstance(expr, ast.Subscript):
            return set()
        recv = expr.value
        fi = self.functions.get(site.fn_key) if site.fn_key else None
        if isinstance(recv, ast.Name):
            return self.dispatch_tables.get(
                f"{site.mod.name}.{recv.id}", set())
        if isinstance(recv, ast.Attribute) and \
                isinstance(recv.value, ast.Name) and \
                recv.value.id == "self" and fi is not None and fi.cls_name:
            return self.dispatch_tables.get(
                f"{site.mod.name}.{fi.cls_name}.{recv.attr}", set())
        return set()

    def _build_edges(self) -> None:
        for site in self.calls:
            if site.fn_key is None:
                continue
            callee = self.resolve_callable(site.node.func, site)
            if callee is not None:
                self.edges.setdefault(site.fn_key, set()).add(callee)
                self.direct_edges.setdefault(site.fn_key, set()).add(callee)
            for k in self.resolve_dispatch(site.node.func, site):
                # a call THROUGH a dispatch table really invokes one of its
                # members in the caller's context — a direct edge to each
                self.edges.setdefault(site.fn_key, set()).add(k)
                self.direct_edges.setdefault(site.fn_key, set()).add(k)
            # a function passed as an argument to another *known* function
            # (e.g. a body handed to lax.fori_loop, a predicate to a local
            # combinator) is conservatively reachable from the caller
            thread_args = _thread_target_args(site)
            for arg in list(site.node.args) + [k.value for k in site.node.keywords]:
                tgt = _unwrap_partial(arg, site.mod)
                if isinstance(tgt, (ast.Name, ast.Attribute)):
                    k = self.resolve_callable(tgt, site)
                    if k is not None:
                        self.edges.setdefault(site.fn_key, set()).add(k)
                        if id(arg) not in thread_args:
                            self.arg_edges.setdefault(
                                site.fn_key, set()).add(k)

    def _propagate_taint(self) -> None:
        from collections import deque

        q = deque()
        for key, fi in self.functions.items():
            if fi.is_root:
                self.traced[key] = key
                q.append(key)
        while q:
            key = q.popleft()
            root = self.traced[key]
            fi = self.functions[key]
            nxt = set(self.edges.get(key, ()))
            nxt |= set(fi.children.values())  # closures trace with the parent
            for n in nxt:
                if n not in self.traced:
                    self.traced[n] = root
                    q.append(n)

    def traced_functions(self) -> list[FunctionInfo]:
        return [self.functions[k] for k in self.traced]


# ------------------------------------------------------------------- indexing


def _index_functions(cg: CallGraph, mod: ModuleInfo) -> None:
    from tools.hglint.loader import def_time_exprs

    def expr_calls(node, fn_stack: list):
        """Record call sites in a def-time expression (decorator, param
        default) — these execute in the ENCLOSING scope when the ``def``
        statement runs, not inside the defined function."""
        if isinstance(node, ast.Call):
            fn_key = fn_stack[-1].key if fn_stack else None
            cg.calls.append(CallSite(node=node, fn_key=fn_key, mod=mod))
        for child in ast.iter_child_nodes(node):
            expr_calls(child, fn_stack)

    def walk(children, qual: list, cls_name: Optional[str],
             fn_stack: list):
        for child in children:
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qp = ".".join(qual + [child.name])
                key = f"{mod.name}.{qp}"
                params = [a.arg for a in (
                    child.args.posonlyargs + child.args.args
                    + child.args.kwonlyargs
                )]
                # same-named modules from DIFFERENT lint roots (e.g.
                # ``hglint dirA/pkg dirB/pkg``) would collide on key and
                # silently drop the second tree's functions/findings —
                # uniquify instead (cross-tree name resolution then binds
                # to the first tree, an accepted imprecision)
                while key in cg.functions:
                    key += "'"
                fi = FunctionInfo(
                    key=key, mod=mod, qualpath=qp, node=child,
                    cls_name=cls_name, params=params, lineno=child.lineno,
                    parent=fn_stack[-1].key if fn_stack else None,
                )
                _decorator_roots(fi, mod)
                cg.functions[key] = fi
                if fn_stack:
                    fn_stack[-1].children[child.name] = key
                for host in def_time_exprs(child):
                    expr_calls(host, fn_stack)
                walk(child.body, qual + [child.name], None,
                     fn_stack + [fi])
            elif isinstance(child, ast.ClassDef):
                hosts = (def_time_exprs(child) + list(child.bases)
                         + [k.value for k in child.keywords])
                for host in hosts:
                    expr_calls(host, fn_stack)
                walk(child.body, qual + [child.name], child.name,
                     fn_stack)
            else:
                if isinstance(child, ast.Call):
                    fn_key = fn_stack[-1].key if fn_stack else None
                    cg.calls.append(
                        CallSite(node=child, fn_key=fn_key, mod=mod)
                    )
                walk(ast.iter_child_nodes(child), qual, cls_name,
                     fn_stack)

    walk(mod.tree.body, [], None, [])


def _decorator_roots(fi: FunctionInfo, mod: ModuleInfo) -> None:
    node = fi.node
    for dec in getattr(node, "decorator_list", ()):
        base = dec.func if isinstance(dec, ast.Call) else dec
        fqn = resolve_fqn(base, mod)
        if fqn in JIT_FQNS:
            fi.root_kind = "jit"
        elif fqn in SHARD_FQNS:
            fi.root_kind = "shard_map"
        elif fqn in PARTIAL_FQNS and isinstance(dec, ast.Call) and dec.args:
            inner = resolve_fqn(dec.args[0], mod)
            if inner in JIT_FQNS:
                fi.root_kind = "jit"
            elif inner in SHARD_FQNS:
                fi.root_kind = "shard_map"
            else:
                continue
        else:
            continue
        if isinstance(dec, ast.Call):
            fi.static_params |= _static_params(dec, fi)


def _static_params(call: ast.Call, fi: FunctionInfo) -> set:
    out: set = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            v = literal_value(kw.value)
            if isinstance(v, str):
                out.add(v)
            elif isinstance(v, tuple):
                out |= {s for s in v if isinstance(s, str)}
        elif kw.arg == "static_argnums":
            v = literal_value(kw.value)
            nums = [v] if isinstance(v, int) else (
                [n for n in v if isinstance(n, int)]
                if isinstance(v, tuple) else []
            )
            for n in nums:
                if 0 <= n < len(fi.params):
                    out.add(fi.params[n])
    return out


def _thread_target_args(site: CallSite) -> set:
    """ids of argument nodes that are thread/timer TARGETS at this call
    site — the guard that keeps caller-context taint (blocking under the
    caller's lock, the caller's raise-set) from following a callable that
    actually runs on another thread."""
    fqn = resolve_fqn(site.node.func, site.mod)
    if fqn not in THREAD_CTORS:
        return set()
    out = {id(k.value) for k in site.node.keywords
           if k.arg in ("target", "function")}
    if fqn == "threading.Timer" and len(site.node.args) >= 2:
        out.add(id(site.node.args[1]))
    return out


def _unwrap_partial(expr: ast.AST, mod: ModuleInfo) -> ast.AST:
    if isinstance(expr, ast.Call):
        fqn = resolve_fqn(expr.func, mod)
        if fqn in PARTIAL_FQNS and expr.args:
            return expr.args[0]
    return expr
