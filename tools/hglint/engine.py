"""Lint orchestration: rule-runner registry, pragma suppression, baseline
filtering, and the machine-readable report."""

from __future__ import annotations

import json
import os
from collections import Counter, defaultdict

from tools.hglint import (
    absint,
    rules_blocking,
    rules_collectives,
    rules_donation,
    rules_exceptions,
    rules_hostsync,
    rules_lifecycle,
    rules_locks,
    rules_pallas,
    rules_retrace,
    rules_vmem,
    rules_wire,
)
from tools.hglint.callgraph import CallGraph
from tools.hglint.loader import discover_modules
from tools.hglint.model import (
    RULES,
    Finding,
    doc_anchor,
    family,
    rule_matches,
    sort_findings,
)

BASELINE_VERSION = 1
REPORT_VERSION = 3


def _runners(cg, modules, interp, vmem_budget):
    """(emittable rule ids, thunk) per rule module — the ``--only`` family
    filter skips whole runners whose rules can't match."""
    return [
        (("HG101", "HG102", "HG103", "HG104", "HG105", "HG107"),
         lambda: rules_hostsync.check(cg)),
        (("HG106",),
         lambda: rules_donation.check(cg, modules)),
        (("HG201", "HG202", "HG203", "HG204"),
         lambda: rules_retrace.check(cg, modules)),
        (("HG301", "HG302", "HG303", "HG304"),
         lambda: rules_pallas.check(cg, modules)),
        (("HG401", "HG402", "HG403"),
         lambda: rules_locks.check(cg, modules)),
        (("HG501", "HG502", "HG503"),
         lambda: rules_vmem.check(cg, modules, interp, vmem_budget)),
        (("HG601", "HG602", "HG603", "HG604"),
         lambda: rules_collectives.check(cg, modules, interp)),
        (("HG701", "HG702", "HG703"),
         lambda: rules_blocking.check(cg, modules)),
        (("HG801", "HG802", "HG803", "HG804", "HG805"),
         lambda: rules_lifecycle.check(cg, modules)),
        (("HG1001", "HG1002", "HG1003", "HG1004", "HG1005"),
         lambda: rules_exceptions.check(cg, modules)),
        (("HG1101", "HG1102", "HG1103", "HG1104", "HG1105"),
         lambda: rules_wire.check(cg, modules)),
    ]


def parse_only(only) -> tuple:
    """``--only`` value -> tuple of rule-id prefixes ("HG5" / "HG5,HG601"
    / "HG10" / already-split sequences all accepted). Matching is
    family-aware (``model.rule_matches``): ``HG10`` selects the HG10xx
    exception family WITHOUT aliasing into HG101-HG107. A prefix matching
    NO known rule raises: a typo'd ``--only`` must not turn the gate into
    a silent green no-op."""
    if not only:
        return ()
    if isinstance(only, str):
        only = only.split(",")
    prefixes = tuple(p.strip() for p in only if p and p.strip())
    for p in prefixes:
        if not any(rule_matches(r, p) for r in RULES):
            raise ValueError(
                f"--only prefix {p!r} matches no known rule; valid ids are "
                f"{sorted(RULES)} (prefixes like 'HG5' select a family)"
            )
    return prefixes


def run_lint(paths: list, only=None, vmem_budget: int = None,
             changed_files=None) -> list:
    """Analyze every ``*.py`` under the given paths (analyzed together so
    cross-module call edges resolve) and return sorted findings.

    ``only`` restricts to rule-id prefixes (e.g. ``"HG5"`` or
    ``["HG5", "HG601"]``); ``vmem_budget`` overrides the default per-core
    VMEM budget for HG501; ``changed_files`` (an iterable of paths, from
    ``--diff-base``) keeps only findings located in those files — the
    whole package is still loaded and analyzed so interprocedural edges
    (HG702 taint, HG401 cycles) stay whole-program."""
    modules = []
    for p in paths:
        modules.extend(discover_modules(p))
    cg = CallGraph.build(modules)
    interp = absint.Interp(cg, modules)
    budget = vmem_budget or rules_vmem.DEFAULT_VMEM_BUDGET
    prefixes = parse_only(only)
    # the HG901 stale-suppression audit needs the findings OTHER rules
    # would have produced — when it's selected, every runner still runs
    # (its findings are filtered back out below)
    audit_on = not prefixes or any(
        rule_matches("HG901", p) for p in prefixes
    )
    findings = []
    ran_rules: set = set()
    for rules, thunk in _runners(cg, modules, interp, budget):
        if prefixes and not audit_on and not any(
            rule_matches(r, p) for p in prefixes for r in rules
        ):
            continue
        ran_rules.update(rules)
        findings += thunk()
    findings, used = _apply_pragmas(findings, modules)
    if audit_on:
        findings += _stale_pragmas(modules, ran_rules, used,
                                   full_run=not prefixes)
    if prefixes:
        findings = [
            f for f in findings
            if any(rule_matches(f.rule, p) for p in prefixes)
        ]
    if changed_files is not None:
        keep = {_slash(p) for p in changed_files}
        findings = [f for f in findings if _slash(f.path) in keep]
    return sort_findings(findings)


def _slash(path: str) -> str:
    return os.path.normpath(path).replace(os.sep, "/")


def _apply_pragmas(findings: list, modules: list) -> tuple:
    """Drop findings whose line carries ``# hglint: disable=<rule>``
    (or ``disable=all``) in the module source. Returns the kept findings
    plus the set of exercised pragmas — ``(path, line, rule-or-"all")``
    triples — which feeds the HG901 stale-suppression audit."""
    by_path = {m.path: m.pragmas for m in modules if m.pragmas}
    used: set = set()
    if not by_path:
        return findings, used
    out = []
    for f in findings:
        rules = by_path.get(f.path, {}).get(f.line, ())
        if f.rule in rules:
            used.add((f.path, f.line, f.rule))
            continue
        if "all" in rules:
            used.add((f.path, f.line, "all"))
            continue
        out.append(f)
    return out, used


def _stale_pragmas(modules: list, ran_rules: set, used: set,
                   full_run: bool) -> list:
    """HG901: a ``# hglint: disable=HGnnn`` whose rule no longer fires on
    that line. Only rules that actually RAN this invocation are audited
    (a scoped ``--only`` run can't prove an un-run rule's pragma dead);
    ``disable=all`` is audited only on full runs for the same reason.
    Unknown ids are ignored (they may name a future rule), and HG901
    does not audit its own suppressions — an HG901 finding is silenced
    only by an explicit ``disable=HG901`` on the pragma's line."""
    out = []
    for m in modules:
        for line, rules in sorted(m.pragmas.items()):
            if "HG901" in rules:
                continue
            for r in sorted(rules):
                if r == "all":
                    if not full_run or (m.path, line, "all") in used:
                        continue
                elif r == "HG901" or r not in RULES or r not in ran_rules \
                        or (m.path, line, r) in used:
                    continue
                out.append(Finding(
                    rule="HG901", path=m.path, line=line,
                    message=f"stale suppression: `disable={r}` but {r} no "
                            f"longer fires on this line — delete the "
                            f"pragma (it would hide a future regression)",
                ))
    return out


# ------------------------------------------------------------------ baseline


def load_baseline(path: str) -> dict:
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path}: version {data.get('version')} != "
            f"{BASELINE_VERSION}"
        )
    return dict(data.get("counts", {}))


def baseline_counts(findings: list) -> dict:
    return dict(sorted(Counter(f.baseline_key for f in findings).items()))


def write_baseline(findings: list, path: str) -> None:
    data = {
        "version": BASELINE_VERSION,
        "comment": "hglint suppression baseline — keys are "
                   "rule:path:function with pre-existing counts. The gate "
                   "fails only when a key's live count EXCEEDS its entry. "
                   "Regenerate with: python -m tools.hglint <paths> "
                   "--write-baseline",
        "counts": baseline_counts(findings),
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(data, fh, indent=2, sort_keys=True)
        fh.write("\n")


def apply_baseline(findings: list, baseline: dict) -> list:
    """Return only findings beyond the baselined count per key. Within a
    key, later (higher-line) findings are treated as the new ones."""
    by_key = defaultdict(list)
    for f in findings:
        by_key[f.baseline_key].append(f)
    out = []
    for key, fs in by_key.items():
        allowed = baseline.get(key, 0)
        if len(fs) > allowed:
            fs = sorted(fs, key=lambda f: f.line)
            out.extend(fs[allowed:])
    return sort_findings(out)


# -------------------------------------------------------------------- report


def finding_dict(f: Finding) -> dict:
    return {
        "rule": f.rule, "severity": f.severity, "path": f.path,
        "line": f.line, "scope": f.scope, "message": f.message,
        "doc": doc_anchor(f.rule),
    }


def build_report(findings: list, paths: list, *, baseline_path=None,
                 suppressed: int = 0, only=None,
                 vmem_budget: int = None, diff_base=None,
                 changed_files=None) -> dict:
    """Machine-readable run report for CI (``--output json``): stable
    envelope, per-rule/severity counts, findings with doc anchors."""
    by_rule = Counter(f.rule for f in findings)
    by_sev = Counter(f.severity for f in findings)
    return {
        "tool": "hglint",
        "report_version": REPORT_VERSION,
        "paths": list(paths),
        "only": list(parse_only(only)),
        "diff_base": diff_base,
        "changed_files": (sorted(_slash(p) for p in changed_files)
                          if changed_files is not None else None),
        "vmem_budget_bytes": vmem_budget or rules_vmem.DEFAULT_VMEM_BUDGET,
        "baseline": {
            "path": baseline_path,
            "applied": baseline_path is not None,
            "suppressed": suppressed,
        },
        "counts": {
            "total": len(findings),
            "by_rule": dict(sorted(by_rule.items())),
            "by_severity": dict(sorted(by_sev.items())),
        },
        "findings": [finding_dict(f) for f in findings],
    }


def summarize(findings: list) -> str:
    fam = Counter(family(f.rule) + "xx" for f in findings)
    rules = Counter(f.rule for f in findings)
    parts = [f"{n} findings" if (n := len(findings)) != 1
             else "1 finding"]
    if findings:
        parts.append(
            "by family: " + ", ".join(
                f"{k}={v}" for k, v in sorted(fam.items())
            )
        )
        parts.append(
            "by rule: " + ", ".join(
                f"{k}={v}" for k, v in sorted(rules.items())
            )
        )
    return "; ".join(parts)
