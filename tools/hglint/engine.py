"""Lint orchestration: rule-runner registry, pragma suppression, baseline
filtering, and the machine-readable report."""

from __future__ import annotations

import json
import os
from collections import Counter, defaultdict

from tools.hglint import (
    absint,
    rules_collectives,
    rules_donation,
    rules_hostsync,
    rules_locks,
    rules_pallas,
    rules_retrace,
    rules_vmem,
)
from tools.hglint.callgraph import CallGraph
from tools.hglint.loader import discover_modules
from tools.hglint.model import RULES, Finding, doc_anchor, sort_findings

BASELINE_VERSION = 1
REPORT_VERSION = 2


def _runners(cg, modules, interp, vmem_budget):
    """(emittable rule ids, thunk) per rule module — the ``--only`` family
    filter skips whole runners whose rules can't match."""
    return [
        (("HG101", "HG102", "HG103", "HG104", "HG105", "HG107"),
         lambda: rules_hostsync.check(cg)),
        (("HG106",),
         lambda: rules_donation.check(cg, modules)),
        (("HG201", "HG202", "HG203", "HG204"),
         lambda: rules_retrace.check(cg, modules)),
        (("HG301", "HG302", "HG303", "HG304"),
         lambda: rules_pallas.check(cg, modules)),
        (("HG401", "HG402"),
         lambda: rules_locks.check(cg, modules)),
        (("HG501", "HG502", "HG503"),
         lambda: rules_vmem.check(cg, modules, interp, vmem_budget)),
        (("HG601", "HG602", "HG603", "HG604"),
         lambda: rules_collectives.check(cg, modules, interp)),
    ]


def parse_only(only) -> tuple:
    """``--only`` value -> tuple of rule-id prefixes ("HG5" / "HG5,HG601"
    / already-split sequences all accepted). A prefix matching NO known
    rule raises: a typo'd ``--only`` must not turn the gate into a silent
    green no-op."""
    if not only:
        return ()
    if isinstance(only, str):
        only = only.split(",")
    prefixes = tuple(p.strip() for p in only if p and p.strip())
    for p in prefixes:
        if not any(r.startswith(p) for r in RULES):
            raise ValueError(
                f"--only prefix {p!r} matches no known rule; valid ids are "
                f"{sorted(RULES)} (prefixes like 'HG5' select a family)"
            )
    return prefixes


def run_lint(paths: list, only=None, vmem_budget: int = None) -> list:
    """Analyze every ``*.py`` under the given paths (analyzed together so
    cross-module call edges resolve) and return sorted findings.

    ``only`` restricts to rule-id prefixes (e.g. ``"HG5"`` or
    ``["HG5", "HG601"]``); ``vmem_budget`` overrides the default per-core
    VMEM budget for HG501."""
    modules = []
    for p in paths:
        modules.extend(discover_modules(p))
    cg = CallGraph.build(modules)
    interp = absint.Interp(cg, modules)
    budget = vmem_budget or rules_vmem.DEFAULT_VMEM_BUDGET
    prefixes = parse_only(only)
    findings = []
    for rules, thunk in _runners(cg, modules, interp, budget):
        if prefixes and not any(
            r.startswith(p) for p in prefixes for r in rules
        ):
            continue
        findings += thunk()
    if prefixes:
        findings = [
            f for f in findings
            if any(f.rule.startswith(p) for p in prefixes)
        ]
    findings = _apply_pragmas(findings, modules)
    return sort_findings(findings)


def _apply_pragmas(findings: list, modules: list) -> list:
    """Drop findings whose line carries ``# hglint: disable=<rule>``
    (or ``disable=all``) in the module source."""
    by_path = {m.path: m.pragmas for m in modules if m.pragmas}
    if not by_path:
        return findings
    out = []
    for f in findings:
        rules = by_path.get(f.path, {}).get(f.line, ())
        if f.rule in rules or "all" in rules:
            continue
        out.append(f)
    return out


# ------------------------------------------------------------------ baseline


def load_baseline(path: str) -> dict:
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path}: version {data.get('version')} != "
            f"{BASELINE_VERSION}"
        )
    return dict(data.get("counts", {}))


def baseline_counts(findings: list) -> dict:
    return dict(sorted(Counter(f.baseline_key for f in findings).items()))


def write_baseline(findings: list, path: str) -> None:
    data = {
        "version": BASELINE_VERSION,
        "comment": "hglint suppression baseline — keys are "
                   "rule:path:function with pre-existing counts. The gate "
                   "fails only when a key's live count EXCEEDS its entry. "
                   "Regenerate with: python -m tools.hglint <paths> "
                   "--write-baseline",
        "counts": baseline_counts(findings),
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(data, fh, indent=2, sort_keys=True)
        fh.write("\n")


def apply_baseline(findings: list, baseline: dict) -> list:
    """Return only findings beyond the baselined count per key. Within a
    key, later (higher-line) findings are treated as the new ones."""
    by_key = defaultdict(list)
    for f in findings:
        by_key[f.baseline_key].append(f)
    out = []
    for key, fs in by_key.items():
        allowed = baseline.get(key, 0)
        if len(fs) > allowed:
            fs = sorted(fs, key=lambda f: f.line)
            out.extend(fs[allowed:])
    return sort_findings(out)


# -------------------------------------------------------------------- report


def finding_dict(f: Finding) -> dict:
    return {
        "rule": f.rule, "severity": f.severity, "path": f.path,
        "line": f.line, "scope": f.scope, "message": f.message,
        "doc": doc_anchor(f.rule),
    }


def build_report(findings: list, paths: list, *, baseline_path=None,
                 suppressed: int = 0, only=None,
                 vmem_budget: int = None) -> dict:
    """Machine-readable run report for CI (``--output json``): stable
    envelope, per-rule/severity counts, findings with doc anchors."""
    by_rule = Counter(f.rule for f in findings)
    by_sev = Counter(f.severity for f in findings)
    return {
        "tool": "hglint",
        "report_version": REPORT_VERSION,
        "paths": list(paths),
        "only": list(parse_only(only)),
        "vmem_budget_bytes": vmem_budget or rules_vmem.DEFAULT_VMEM_BUDGET,
        "baseline": {
            "path": baseline_path,
            "applied": baseline_path is not None,
            "suppressed": suppressed,
        },
        "counts": {
            "total": len(findings),
            "by_rule": dict(sorted(by_rule.items())),
            "by_severity": dict(sorted(by_sev.items())),
        },
        "findings": [finding_dict(f) for f in findings],
    }


def summarize(findings: list) -> str:
    fam = Counter(f.rule[:3] + "xx" for f in findings)
    rules = Counter(f.rule for f in findings)
    parts = [f"{n} findings" if (n := len(findings)) != 1
             else "1 finding"]
    if findings:
        parts.append(
            "by family: " + ", ".join(
                f"{k}={v}" for k, v in sorted(fam.items())
            )
        )
        parts.append(
            "by rule: " + ", ".join(
                f"{k}={v}" for k, v in sorted(rules.items())
            )
        )
    return "; ".join(parts)
