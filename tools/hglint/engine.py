"""Lint orchestration + baseline filtering."""

from __future__ import annotations

import json
import os
from collections import Counter, defaultdict

from tools.hglint import (
    rules_hostsync,
    rules_locks,
    rules_pallas,
    rules_retrace,
)
from tools.hglint.callgraph import CallGraph
from tools.hglint.loader import discover_modules
from tools.hglint.model import Finding, sort_findings

BASELINE_VERSION = 1


def run_lint(paths: list) -> list:
    """Analyze every ``*.py`` under the given paths (analyzed together so
    cross-module call edges resolve) and return sorted findings."""
    modules = []
    for p in paths:
        modules.extend(discover_modules(p))
    cg = CallGraph.build(modules)
    findings = []
    findings += rules_hostsync.check(cg)
    findings += rules_retrace.check(cg, modules)
    findings += rules_pallas.check(cg, modules)
    findings += rules_locks.check(cg, modules)
    return sort_findings(findings)


# ------------------------------------------------------------------ baseline


def load_baseline(path: str) -> dict:
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path}: version {data.get('version')} != "
            f"{BASELINE_VERSION}"
        )
    return dict(data.get("counts", {}))


def baseline_counts(findings: list) -> dict:
    return dict(sorted(Counter(f.baseline_key for f in findings).items()))


def write_baseline(findings: list, path: str) -> None:
    data = {
        "version": BASELINE_VERSION,
        "comment": "hglint suppression baseline — keys are "
                   "rule:path:function with pre-existing counts. The gate "
                   "fails only when a key's live count EXCEEDS its entry. "
                   "Regenerate with: python -m tools.hglint <paths> "
                   "--write-baseline",
        "counts": baseline_counts(findings),
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(data, fh, indent=2, sort_keys=True)
        fh.write("\n")


def apply_baseline(findings: list, baseline: dict) -> list:
    """Return only findings beyond the baselined count per key. Within a
    key, later (higher-line) findings are treated as the new ones."""
    by_key = defaultdict(list)
    for f in findings:
        by_key[f.baseline_key].append(f)
    out = []
    for key, fs in by_key.items():
        allowed = baseline.get(key, 0)
        if len(fs) > allowed:
            fs = sorted(fs, key=lambda f: f.line)
            out.extend(fs[allowed:])
    return sort_findings(out)


def summarize(findings: list) -> str:
    fam = Counter(f.rule[:3] + "xx" for f in findings)
    rules = Counter(f.rule for f in findings)
    parts = [f"{n} findings" if (n := len(findings)) != 1
             else "1 finding"]
    if findings:
        parts.append(
            "by family: " + ", ".join(
                f"{k}={v}" for k, v in sorted(fam.items())
            )
        )
        parts.append(
            "by rule: " + ", ".join(
                f"{k}={v}" for k, v in sorted(rules.items())
            )
        )
    return "; ".join(parts)
