"""HG6xx — collective consistency inside ``shard_map``/``pjit`` regions.

On a real TPU mesh every device must execute the SAME sequence of
collectives over the SAME axis names; anything else hangs the mesh (no
timeout, no traceback — the job just stops). Three statically checkable
ways to get there:

HG601 (error)  a collective names a mesh axis that does not exist in the
               enclosing ``shard_map``'s mesh environment — XLA raises at
               trace time at best, at worst (spelled via a variable that
               aliases another region's axis) it deadlocks.
HG602 (error)  a collective is issued under a Python branch whose
               condition derives from a traced/device value (a parameter
               of the shard-mapped body, or the result of
               ``axis_index``/another collective): devices that take
               different branches issue different collective sequences —
               the classic divergent-program deadlock.
HG603 (error)  caller/callee axis mismatch: a helper reached from a
               shard_map region issues a collective whose axis name
               (constant, or a parameter constant-propagated from its
               call sites) is absent from every region environment that
               reaches the helper.
HG604 (error)  ``jax.lax.cond``/``switch`` inside a shard_map region whose
               branch callables carry MISMATCHED collectives: unlike a
               Python branch (HG602) the cond itself traces fine — both
               branches are staged — but at runtime devices whose
               predicates disagree execute different collective
               sequences and the mesh hangs. Branches are compared as
               multisets of (collective, folded axis names); a branch
               that does not resolve to a known function/lambda voids the
               comparison (silence over guessing).

The mesh environment of a region is resolved by
:func:`tools.hglint.absint.mesh_axes_for_site` — the folded ``mesh=``
object, else the axis names in the site's partition specs. When NOTHING
resolves the region is skipped entirely: silence over guessing.
"""

from __future__ import annotations

import ast
from collections import deque
from typing import Optional

from tools.hglint.absint import Interp, mesh_axes_for_site
from tools.hglint.callgraph import SHARD_FQNS, CallGraph, CallSite
from tools.hglint.loader import own_nodes, resolve_fqn
from tools.hglint.model import Finding
from tools.hglint.rules_retrace import _traced_name_in_test

#: collective fqn -> positional index of its axis-name argument
COLLECTIVES = {
    "jax.lax.psum": 1,
    "jax.lax.pmean": 1,
    "jax.lax.pmax": 1,
    "jax.lax.pmin": 1,
    "jax.lax.all_gather": 1,
    "jax.lax.psum_scatter": 1,
    "jax.lax.all_to_all": 1,
    "jax.lax.ppermute": 1,
    "jax.lax.pshuffle": 1,
    "jax.lax.pcast": 1,
    "jax.lax.axis_index": 0,
}

_AXIS_KWARGS = ("axis_name", "axis_names")

#: device-local queries of the mesh position: they take an axis name (so
#: HG601/HG603 validate them) but perform NO cross-device communication —
#: running one under a divergent branch cannot deadlock (no HG602)
_NON_COMMUNICATING = {"jax.lax.axis_index"}


def check(cg: CallGraph, modules: list, interp: Interp) -> list:
    regions = _regions(cg, interp)
    if not regions:
        return []
    # fn key -> list of (root key, env or None) for every region reaching it
    reach: dict[str, list] = {}
    for root, env in regions.items():
        for key in _reachable(cg, root):
            reach.setdefault(key, []).append((root, env))
    findings = []
    for key, hits in reach.items():
        fi = cg.functions[key]
        envs = [env for _, env in hits]
        if any(env is None for env in envs):
            env_union = None           # an unresolvable region reaches us
        else:
            env_union = frozenset().union(*envs)
        findings += _check_fn(cg, interp, fi, key in regions, env_union)
        findings += _check_cond_branches(cg, interp, fi)
    return findings


# ------------------------------------------------------------------ regions


def _regions(cg: CallGraph, interp: Interp) -> dict:
    """shard_map root key -> mesh-axis env (frozenset | None)."""
    roots = {k for k, fi in cg.functions.items()
             if fi.root_kind == "shard_map"}
    if not roots:
        return {}
    envs: dict[str, list] = {k: [] for k in roots}
    for site in cg.calls:
        fqn = resolve_fqn(site.node.func, site.mod)
        if fqn not in SHARD_FQNS or not site.node.args:
            continue
        key = cg.resolve_callable(site.node.args[0], site)
        if key in envs:
            envs[key].append(mesh_axes_for_site(site, interp, cg))
    out = {}
    for key, site_envs in envs.items():
        if not site_envs or any(e is None for e in site_envs):
            out[key] = None            # decorator-only or unresolvable site
        else:
            out[key] = frozenset().union(*site_envs)
    return out


def _reachable(cg: CallGraph, root: str) -> set:
    seen = {root}
    q = deque([root])
    while q:
        key = q.popleft()
        fi = cg.functions[key]
        nxt = set(cg.edges.get(key, ())) | set(fi.children.values())
        for n in nxt:
            if n not in seen:
                seen.add(n)
                q.append(n)
    return seen


# ------------------------------------------------------------ per function


def _check_fn(cg: CallGraph, interp: Interp, fi, is_root: bool,
              env) -> list:
    """``env`` is the union of resolved region envs reaching ``fi``
    (None when any reaching region is unresolvable — axis checks skip,
    divergence checks still run)."""
    findings = []
    collectives = []   # (call node, fqn)
    derived: set = set()   # names bound to collective results in this fn
    for node in own_nodes(fi.node):
        if isinstance(node, ast.Call):
            fqn = resolve_fqn(node.func, fi.mod)
            if fqn in COLLECTIVES:
                collectives.append((node, fqn))
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                isinstance(node.value, ast.Call):
            fqn = resolve_fqn(node.value.func, fi.mod)
            if fqn in COLLECTIVES:
                derived.add(node.targets[0].id)

    # -- HG601/HG603: axis names vs the mesh environment ---------------------
    if env is not None:
        env_fn = interp.env_for(fi)
        for node, fqn in collectives:
            for axis in _axis_names(node, fqn, interp, env_fn, fi.mod):
                if axis in env:
                    continue
                short = fqn.rsplit(".", 1)[-1]
                if is_root:
                    findings.append(Finding(
                        rule="HG601", path=fi.mod.path, line=node.lineno,
                        scope=fi.qualpath,
                        message=(
                            f"`{short}` over axis '{axis}' but the "
                            f"shard_map mesh only has "
                            f"{sorted(env) or '(no resolvable axes)'}"
                        ),
                    ))
                else:
                    findings.append(Finding(
                        rule="HG603", path=fi.mod.path, line=node.lineno,
                        scope=fi.qualpath,
                        message=(
                            f"`{short}` over axis '{axis}' in a helper "
                            f"reached from shard_map, but every caller "
                            f"region's mesh only has {sorted(env)} — "
                            f"caller/callee axis mismatch"
                        ),
                    ))

    # -- HG602: collectives under traced-value branches -----------------------
    traced = set(derived)
    if is_root:
        traced |= {p for p in fi.params if p not in fi.static_params}
    flagged: set = set()
    for branch in own_nodes(fi.node):
        if not isinstance(branch, (ast.If, ast.While)):
            continue
        hit = _device_test(branch.test, traced, fi.mod)
        if not hit:
            continue
        # only the BODY diverges — a collective in the test itself still
        # executes on every device
        body = list(branch.body) + list(branch.orelse)
        for node, fqn in collectives:
            if fqn in _NON_COMMUNICATING:
                continue
            if id(node) in flagged or \
                    not any(_within(s, node) for s in body):
                continue
            flagged.add(id(node))
            short = fqn.rsplit(".", 1)[-1]
            findings.append(Finding(
                rule="HG602", path=fi.mod.path, line=node.lineno,
                scope=fi.qualpath,
                message=(
                    f"`{short}` under a branch on device value "
                    f"`{hit}` inside shard_map — devices taking "
                    f"different branches issue different collective "
                    f"sequences and the mesh deadlocks; use lax.cond "
                    f"or hoist the collective out of the branch"
                ),
            ))
    return findings


_COND_FQNS = ("jax.lax.cond", "jax.lax.switch")


def _check_cond_branches(cg: CallGraph, interp: Interp, fi) -> list:
    """HG604: compare the collective multisets of every ``lax.cond`` /
    ``lax.switch`` branch inside a shard_map-reachable function."""
    findings = []
    env_fn = interp.env_for(fi)
    for node in own_nodes(fi.node):
        if not isinstance(node, ast.Call):
            continue
        fqn = resolve_fqn(node.func, fi.mod)
        if fqn not in _COND_FQNS or len(node.args) < 2:
            continue
        if fqn.endswith(".cond"):
            branch_nodes = list(node.args[1:3])
        else:  # switch(index, branches, *operands)
            seq = node.args[1]
            if isinstance(seq, (ast.List, ast.Tuple)):
                branch_nodes = list(seq.elts)
            else:
                continue   # branches behind a name: unresolvable, skip
        sets = []
        for bn in branch_nodes:
            s = _callable_collectives(cg, interp, fi, bn, env_fn)
            if s is None:
                sets = None   # one unresolvable branch voids the compare
                break
            sets.append(s)
        if not sets or len(set(sets)) <= 1:
            continue
        short = fqn.rsplit(".", 1)[-1]
        desc = " vs ".join(
            "[" + (", ".join(f"{n}({a})" for n, a in s) or "-") + "]"
            for s in sets
        )
        findings.append(Finding(
            rule="HG604", path=fi.mod.path, line=node.lineno,
            scope=fi.qualpath,
            message=(
                f"`lax.{short}` branches carry mismatched collectives "
                f"({desc}) — devices whose predicates disagree issue "
                f"different collective sequences and the mesh hangs; "
                f"issue the same collectives on every branch (reduce a "
                f"zero contribution instead of skipping the op)"
            ),
        ))
    return findings


def _callable_collectives(cg: CallGraph, interp: Interp, fi, branch,
                          env_fn: dict, _depth: int = 0,
                          _seen: Optional[frozenset] = None):
    """Sorted multiset of (collective short name, axis names) a branch
    callable issues — following calls into RESOLVABLE user functions (so
    a psum routed through a helper still counts on both arms), bounded
    depth, cycle-safe. None when the branch doesn't resolve."""
    seen = _seen or frozenset()
    if isinstance(branch, ast.Lambda):
        body_nodes = ast.walk(branch.body)
        mod = fi.mod
        env = env_fn
        site_fi = fi
    else:
        site = CallSite(node=ast.Call(func=branch, args=[], keywords=[]),
                        fn_key=fi.key, mod=fi.mod)
        key = cg.resolve_callable(branch, site)
        if key is None:
            # at the branch position an unresolvable callable voids the
            # comparison; below it, a dotted name that is not user code
            # is a library call and contributes nothing
            return None if _depth == 0 else ()
        if key in seen:
            return ()
        seen = seen | {key}
        site_fi = cg.functions[key]
        body_nodes = own_nodes(site_fi.node)
        mod = site_fi.mod
        env = interp.env_for(site_fi)
    out = []
    for node in body_nodes:
        if not isinstance(node, ast.Call):
            continue
        fqn = resolve_fqn(node.func, mod)
        if fqn in COLLECTIVES:
            if fqn in _NON_COMMUNICATING:
                continue
            axes = _axis_names(node, fqn, interp, env, mod)
            out.append((fqn.rsplit(".", 1)[-1],
                        ",".join(sorted(axes)) if axes else "?"))
        elif _depth < 3:
            if fqn is None and not isinstance(node.func, ast.Lambda):
                # an OPAQUE callable (dict dispatch, getattr, higher-order
                # result) could hide a collective either way — void the
                # whole comparison: silence over guessing
                return None
            # a dotted name: either known user code (follow it) or a
            # library call (cannot carry a user collective — skip)
            sub = _callable_collectives(
                cg, interp, site_fi, node.func, env, _depth + 1, seen
            )
            if sub is None:
                return None   # opacity anywhere below voids the compare
            out.extend(sub)
    return tuple(sorted(out))


def _axis_names(node: ast.Call, fqn: str, interp: Interp, env_fn: dict,
                mod):
    """Resolved axis-name strings of a collective call ([] when the axis
    expression does not fold — silence over guessing)."""
    pos = COLLECTIVES[fqn]
    axis_node = node.args[pos] if len(node.args) > pos else None
    if axis_node is None:
        for k in node.keywords:
            if k.arg in _AXIS_KWARGS:
                axis_node = k.value
                break
    if axis_node is None:
        return []
    v = interp.eval(axis_node, env_fn, mod)
    out = []
    stack = [v]
    while stack:
        cur = stack.pop()
        if isinstance(cur, str):
            out.append(cur)
        elif isinstance(cur, tuple):
            stack.extend(cur)
        else:
            return []   # any unresolvable component voids the whole check
    return out


def _device_test(test: ast.AST, traced: set, mod) -> str:
    """Name of the device value a branch condition concretizes, or '' —
    a traced name (pruned through static accessors, shared with HG202) or
    a direct collective call in the condition."""
    for node in ast.walk(test):
        if isinstance(node, ast.Call):
            fqn = resolve_fqn(node.func, mod)
            if fqn in COLLECTIVES:
                return fqn.rsplit(".", 1)[-1] + "(...)"
    if traced:
        hit = _traced_name_in_test(test, traced)
        if hit:
            return hit
    return ""


def _within(outer: ast.AST, target: ast.AST) -> bool:
    return any(n is target for n in ast.walk(outer))
