"""HG10xx — exception flow & failure discipline.

The fault vocabulary (``fault/errors.py``) is a contract: ``TransientFault``
is retry-worthy, ``PermanentFault`` is not, and ``InjectedCrash`` is
deliberately a ``BaseException`` so that no recovery layer can swallow a
simulated kill.  The contract was previously enforced only by convention —
and review rounds kept hand-finding exactly the bug classes a static pass
catches mechanically (an evaluation bug raising out of a finalizer and
stranding a pump loop's tickets, a handler quietly eating the error that
every chaos drill depends on observing).  This family checks the
discipline with an **interprocedural raise-set inference**:

Per function, the set of exception types it may RAISE is computed from

- explicit ``raise TypeName(...)`` statements (variable re-raises are
  skipped: the inference is deliberately an under-approximation — it only
  claims types it can prove, so every finding has a witness);
- calls into known-raising runtime APIs (``FaultRegistry.check`` fault
  points — the armed error can be anything up to an ``InjectedCrash`` —
  ``submit_*`` entry points, socket/HTTP transport sends);
- calls to other analyzed functions, propagated to a fixpoint over the
  call graph **including arg-passed call edges** (a callable smuggled
  through a parameter or a dict dispatch raises in its caller's context)
  with the thread-target guard: a ``Thread(target=f)`` callable runs on
  another thread, so ``f``'s raise-set must NOT flow into the
  constructing caller.

Types escaping a function subtract everything absorbed by enclosing
``try`` handlers — a handler whose body re-raises (contains any ``raise``)
is transparent.  A small name-based hierarchy (the tree's fault taxonomy +
the Python builtins) decides what a handler catches and which types are
transient.

Rules on top of the inference:

HG1001  a handler that can receive an ``InjectedCrash`` (bare ``except``,
        ``except BaseException``, or ``except InjectedCrash``) and does
        not re-raise — a swallowed simulated kill silently invalidates
        every recovery drill.  The witness chain names the path the crash
        travels.
HG1002  dead typed fault handler: ``except TransientFault`` (or any
        FaultError subtype) around calls whose inferred raise-set is
        CLOSED and cannot contain the caught type — the handler documents
        recovery that can never run.
HG1003  retry loop whose caught set includes provably non-transient types
        (an explicit ``PermanentFault`` catch that retries, or a broad
        catch over a body that raises one, with no ``is_transient`` /
        ``.transient`` guard and no re-raise).
HG1004  thread/timer target entry point whose body lets an
        Exception-level raise escape (no top-level guard) — one raise
        kills the thread and strands the loop's tickets/queue.
        ``InjectedCrash``-level types are exempt: kills MUST escape.
HG1005  swallow-without-evidence: a broad handler that neither re-raises,
        logs, increments a counter, completes a future/ticket
        (``resolve``/``fail``/``shed``/``set_exception`` sinks), uses the
        bound exception, nor returns a typed fallback.

Escape hatch: ``# hglint: disable=HG100x`` on the handler's line — audited
by HG901 the moment the rule stops firing.
"""

from __future__ import annotations

import ast
from typing import Optional

from tools.hglint.callgraph import (
    CallGraph,
    CallSite,
    _thread_target_args,
)
from tools.hglint.loader import resolve_fqn
from tools.hglint.model import Finding

# --------------------------------------------------------------- type model

#: name-based exception hierarchy: child -> parent.  Short names keep
#: cross-module matching simple (``errors.TransientFault`` and a bare
#: ``TransientFault`` import are the same type to the lint).
BUILTIN_PARENT = {
    "Exception": "BaseException",
    "InjectedCrash": "BaseException",
    "KeyboardInterrupt": "BaseException",
    "SystemExit": "BaseException",
    "GeneratorExit": "BaseException",
    "FaultError": "Exception",
    "TransientFault": "FaultError",
    "PermanentFault": "FaultError",
    "OSError": "Exception",
    "IOError": "OSError",
    "TimeoutError": "OSError",
    "ConnectionError": "OSError",
    "ConnectionResetError": "ConnectionError",
    "ConnectionRefusedError": "ConnectionError",
    "ConnectionAbortedError": "ConnectionError",
    "BrokenPipeError": "ConnectionError",
    "ArithmeticError": "Exception",
    "ZeroDivisionError": "ArithmeticError",
    "OverflowError": "ArithmeticError",
    "LookupError": "Exception",
    "KeyError": "LookupError",
    "IndexError": "LookupError",
    "RuntimeError": "Exception",
    "NotImplementedError": "RuntimeError",
    "RecursionError": "RuntimeError",
    "ValueError": "Exception",
    "UnicodeDecodeError": "ValueError",
    "TypeError": "Exception",
    "AttributeError": "Exception",
    "AssertionError": "Exception",
    "MemoryError": "Exception",
    "StopIteration": "Exception",
    "FileNotFoundError": "OSError",
    "PermissionError": "OSError",
    "InterruptedError": "OSError",
}

#: transience roots beyond an explicit ``transient =`` class attribute
#: (mirrors ``fault.errors.DEFAULT_TRANSIENT``)
TRANSIENT_ROOTS = {"TransientFault", "TimeoutError", "ConnectionError"}
NON_TRANSIENT_ROOTS = {"PermanentFault"}

#: socket/HTTP primitives whose failure mode is a dropped/timed-out wire
TRANSPORT_METHODS = {
    "sendall", "recv", "recv_into", "recvfrom", "accept",
    "create_connection", "getresponse", "urlopen",
}
TRANSPORT_RAISES = frozenset({"ConnectionError", "TimeoutError"})

#: fault-point sites raise whatever error the drill armed — up to a kill
FAULT_POINT_RAISES = frozenset(
    {"TransientFault", "PermanentFault", "InjectedCrash"}
)

#: serve/peer submit entry points shed or fault-type their admission
#: failures; modeled for receiver-typed calls the graph cannot resolve
SUBMIT_RAISES = frozenset({"TransientFault", "PermanentFault"})

#: calls that are closed-world no-raise for HG1002's purposes: builtins
#: and container/str/coordination methods that cannot produce the fault
#: types a typed handler catches
CLOSED_FUNCS = {
    "len", "int", "str", "float", "bool", "repr", "min", "max", "abs",
    "sum", "sorted", "list", "dict", "set", "tuple", "frozenset", "range",
    "enumerate", "zip", "isinstance", "issubclass", "getattr", "hasattr",
    "setattr", "id", "hash", "print", "format", "iter", "next", "any",
    "all", "callable", "vars", "type", "round", "divmod", "map", "filter",
}
CLOSED_METHODS = {
    "append", "appendleft", "extend", "extendleft", "add", "discard",
    "remove", "clear", "pop", "popleft", "popitem", "setdefault",
    "update", "items", "keys", "values", "get", "copy", "sort",
    "reverse", "index", "count", "split", "rsplit", "strip", "lstrip",
    "rstrip", "startswith", "endswith", "lower", "upper", "replace",
    "format", "encode", "decode", "is_set", "set", "clear", "acquire",
    "release", "notify", "notify_all", "debug", "info", "warning",
    "error", "exception", "critical", "getLogger", "monotonic", "time",
    "perf_counter", "is_alive", "incr", "observe", "record",
}

#: handler-body calls that count as EVIDENCE the failure was handled:
#: logging, counters, future/ticket resolution, rollback/abort paths
EVIDENCE_METHODS = {
    # logging
    "debug", "info", "warning", "error", "exception", "critical", "log",
    # counters / registries
    "incr", "inc", "increment", "observe", "record", "record_failure",
    "record_retry", "note", "mark", "bump", "add",
    # future / ticket sinks
    "resolve", "fail", "shed", "set_result", "set_exception", "cancel",
    "fail_batch", "abort", "rollback", "rollback_mem", "finish_error",
    "force_sample", "put", "append", "appendleft", "extendleft", "extend",
    "send", "respond", "reject", "retry", "close", "stop", "shutdown",
}

_F = Finding


def check(cg: CallGraph, modules: list) -> list:
    model = RaiseModel(cg, modules)
    findings = []
    findings += _swallowed_kills(cg, model)          # HG1001
    findings += _dead_typed_handlers(cg, model)      # HG1002
    findings += _retry_discipline(cg, model)         # HG1003
    findings += _entry_point_guards(cg, model)       # HG1004
    findings += _swallow_evidence(cg, model)         # HG1005
    return findings


# ---------------------------------------------------------------- the model


class _Ev:
    """One exception-producing event inside a function body."""

    __slots__ = ("node", "guards", "kind", "types", "callee", "desc",
                 "unknown")

    def __init__(self, node, guards, kind, types=frozenset(), callee=None,
                 desc="", unknown=False):
        self.node = node
        self.guards = guards      # tuple of _Guard, outermost first
        self.kind = kind          # "raise" | "api" | "call"
        self.types = types        # for raise/api
        self.callee = callee      # for call
        self.desc = desc          # human label for api events
        self.unknown = unknown    # unresolvable non-closed call


class _Guard:
    """One enclosing ``try`` whose handlers may absorb an event."""

    __slots__ = ("try_id", "handlers")

    def __init__(self, try_id, handlers):
        self.try_id = try_id
        #: [(catch name set, reraises, handler node)]
        self.handlers = handlers


class RaiseModel:
    """Interprocedural raise-set inference over the hglint call graph."""

    def __init__(self, cg: CallGraph, modules: list):
        self.cg = cg
        self.parent = dict(BUILTIN_PARENT)
        self.transient_attr: dict = {}
        #: mod name -> alias -> type names, for module-level exception
        #: tuples (``_PERMANENT = (Unservable, PermanentFault, ...)``)
        #: spliced into catch clauses (``except (Deadline, *_PERMANENT)``)
        self.catch_aliases: dict = {}
        #: in-tree top-level classes, module-qualified ("pkg.mod.Cls")
        self.class_fqns: set = set()
        #: (mod name, class name, attr) -> receiver class fqn; None when
        #: the attr is rebound to anything other than one in-tree class
        #: (``self.x = None`` placeholders don't poison — they are
        #: "unset", and calling through an unset receiver crashes anyway)
        self.receiver_class: dict = {}
        self._index_classes(modules)
        self.events: dict = {}    # fn key -> [_Ev]
        self.tries: dict = {}     # fn key -> [(Try node, [_Guard.handlers])]
        self.open_direct: dict = {}   # fn key -> bool (has unknown call)
        for key, fi in cg.functions.items():
            self._walk_function(fi)
        #: fn key -> {type: (lineno, via callee key or None, desc)}
        self.escapes: dict = {key: {} for key in cg.functions}
        #: fn key -> transitively open (unknown call anywhere reachable)
        self.open: dict = dict(self.open_direct)
        self._fixpoint()

    # -- class / type hierarchy ----------------------------------------------

    def _index_classes(self, modules: list) -> None:
        for mod in modules:
            for stmt in mod.tree.body:
                if isinstance(stmt, ast.ClassDef):
                    self.class_fqns.add(f"{mod.name}.{stmt.name}")
        for mod in modules:
            aliases = self.catch_aliases.setdefault(mod.name, {})
            for stmt in mod.tree.body:
                if isinstance(stmt, ast.Assign) and \
                        len(stmt.targets) == 1 and \
                        isinstance(stmt.targets[0], ast.Name) and \
                        isinstance(stmt.value, ast.Tuple):
                    names = [_type_name(e) for e in stmt.value.elts]
                    if names and all(n is not None for n in names):
                        aliases[stmt.targets[0].id] = frozenset(names)
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                base = None
                for b in node.bases:
                    name = _type_name(b)
                    if name is not None:
                        base = name
                        break
                if base is not None and node.name not in BUILTIN_PARENT:
                    self.parent[node.name] = base
                for stmt in node.body:
                    if isinstance(stmt, ast.Assign) and \
                            len(stmt.targets) == 1 and \
                            isinstance(stmt.targets[0], ast.Name) and \
                            stmt.targets[0].id == "transient" and \
                            isinstance(stmt.value, ast.Constant):
                        self.transient_attr[node.name] = bool(
                            stmt.value.value
                        )
        for mod in modules:
            for cls in mod.tree.body:
                if isinstance(cls, ast.ClassDef):
                    self._index_receivers(mod, cls)

    def _index_receivers(self, mod, cls: ast.ClassDef) -> None:
        """Class-of-receiver inference: ``self.x = Ctor()`` assignments
        (across ALL of the class's methods) type the receiver attr, so
        ``self.x.m()`` resolves to ``Ctor.m``'s raise-set instead of
        opening the world."""
        for meth in cls.body:
            if not isinstance(meth, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            for n in ast.walk(meth):
                if not (isinstance(n, ast.Assign) and
                        len(n.targets) == 1):
                    continue
                t = n.targets[0]
                if not (isinstance(t, ast.Attribute) and
                        isinstance(t.value, ast.Name) and
                        t.value.id == "self"):
                    continue
                if isinstance(n.value, ast.Constant) and \
                        n.value.value is None:
                    continue  # unset placeholder, not a retype
                fqn = None
                if isinstance(n.value, ast.Call):
                    f = n.value.func
                    if isinstance(f, ast.Name) and \
                            f"{mod.name}.{f.id}" in self.class_fqns:
                        fqn = f"{mod.name}.{f.id}"
                    else:
                        r = resolve_fqn(f, mod)
                        if r in self.class_fqns:
                            fqn = r
                key = (mod.name, cls.name, t.attr)
                if key not in self.receiver_class:
                    self.receiver_class[key] = fqn
                elif self.receiver_class[key] != fqn:
                    self.receiver_class[key] = None

    def receiver_method(self, func, fi) -> Optional[str]:
        """``self.<attr>.<m>()`` on a receiver typed by
        :meth:`_index_receivers` -> the method's function key, when the
        analyzed class defines it."""
        if not (isinstance(func, ast.Attribute) and
                isinstance(func.value, ast.Attribute) and
                isinstance(func.value.value, ast.Name) and
                func.value.value.id == "self" and
                fi.cls_name):
            return None
        fqn = self.receiver_class.get(
            (fi.mod.name, fi.cls_name, func.value.attr)
        )
        if not fqn:
            return None
        cand = f"{fqn}.{func.attr}"
        return cand if cand in self.cg.functions else None

    def ancestry(self, t: str):
        seen = []
        cur = t
        while cur is not None and cur not in seen:
            seen.append(cur)
            if cur == "BaseException":
                break
            if cur == "Exception":
                cur = "BaseException"
            else:
                cur = self.parent.get(cur, "Exception")
        return seen

    def catches(self, catch_set, t: str) -> bool:
        return any(a in catch_set for a in self.ancestry(t))

    def base_only(self, t: str) -> bool:
        """True when ``t`` derives from BaseException WITHOUT passing
        through Exception (kills: InjectedCrash, KeyboardInterrupt...)."""
        anc = self.ancestry(t)
        return "Exception" not in anc and "BaseException" in anc

    def transience(self, t: str,
                   extra: frozenset = frozenset()) -> Optional[bool]:
        """True transient / False provably non-transient / None unknown.
        An explicit ``transient =`` class attribute anywhere in the MRO
        wins — the runtime's ``is_transient`` checks ``getattr`` BEFORE
        ``isinstance(DEFAULT_TRANSIENT + extra)``, so a ``PermanentFault``
        subclass stays non-transient even when listed in ``extra`` — then
        the ancestry roots and the call site's ``extra`` tuple."""
        anc = self.ancestry(t)
        for a in anc:
            if a in self.transient_attr:
                return self.transient_attr[a]
        for a in anc:
            if a in TRANSIENT_ROOTS or a in extra:
                return True
            if a in NON_TRANSIENT_ROOTS:
                return False
        return None

    # -- per-function event collection ---------------------------------------

    def _walk_function(self, fi) -> None:
        events: list = []
        tries: list = []
        self.open_direct.setdefault(fi.key, False)

        aliases = self.catch_aliases.get(fi.mod.name, {})

        def resolve_catch(e):
            if isinstance(e, ast.Starred):      # except (A, *_PERMANENT)
                e = e.value
            if isinstance(e, ast.Name) and e.id in aliases:
                return set(aliases[e.id])
            n = _type_name(e)
            return {n} if n is not None else set()

        def handler_info(try_node):
            handlers = []
            for h in try_node.handlers:
                if h.type is None:
                    names = frozenset({"BaseException"})
                else:
                    elts = h.type.elts if isinstance(h.type, ast.Tuple) \
                        else [h.type]
                    resolved: set = set()
                    for e in elts:
                        resolved |= resolve_catch(e)
                    names = frozenset(resolved) or \
                        frozenset({"BaseException"})
                reraises = any(
                    isinstance(n, ast.Raise) for s in h.body
                    for n in ast.walk(s)
                )
                handlers.append((names, reraises, h))
            return handlers

        def classify_call(node: ast.Call, guards) -> None:
            site = CallSite(node=node, fn_key=fi.key, mod=fi.mod)
            callee = self.cg.resolve_callable(node.func, site)
            fanout = self.cg.resolve_dispatch(node.func, site)
            if callee is not None:
                events.append(_Ev(node, guards, "call", callee=callee))
            elif fanout:
                for k in sorted(fanout):
                    events.append(_Ev(node, guards, "call", callee=k))
            else:
                api = _known_api(node, fi)
                if api is not None:
                    types, desc = api
                    events.append(_Ev(node, guards, "api", types=types,
                                      desc=desc))
                elif not _closed_call(node) and \
                        _type_name(node.func) not in self.parent:
                    rk = self.receiver_method(node.func, fi)
                    if rk is not None:
                        # receiver-typed: `self.x.m()` where `self.x` is
                        # provably one in-tree class — a closed edge, not
                        # an open world
                        events.append(_Ev(node, guards, "call", callee=rk))
                    else:
                        # an exception CONSTRUCTOR (`raise ValueError(...)`)
                        # is not a raising call — the enclosing Raise event
                        # already carries its type
                        events.append(_Ev(node, guards, "call",
                                          unknown=True))
                        self.open_direct[fi.key] = True
            # a callable passed as an argument may raise in the caller's
            # context — except thread/timer targets, which run elsewhere
            thread_args = _thread_target_args(site)
            for arg in list(node.args) + [k.value for k in node.keywords]:
                if id(arg) in thread_args:
                    continue
                if isinstance(arg, (ast.Name, ast.Attribute)):
                    k = self.cg.resolve_callable(arg, site)
                    if k is not None and k != callee:
                        events.append(_Ev(node, guards, "call", callee=k))

        def walk(node, guards) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef, ast.Lambda)) and \
                    node is not fi.node:
                return
            if isinstance(node, ast.Try):
                handlers = handler_info(node)
                tries.append((node, handlers))
                inner = guards + (_Guard(id(node), handlers),)
                for s in node.body:
                    walk(s, inner)
                # handler bodies, else, and finally are covered only by
                # OUTER tries (standard propagation semantics)
                for _, _, h in handlers:
                    for s in h.body:
                        walk(s, guards)
                for s in node.orelse + node.finalbody:
                    walk(s, guards)
                return
            if isinstance(node, ast.Raise):
                t = _raised_type(node)
                if t is not None:
                    events.append(_Ev(node, guards, "raise",
                                      types=frozenset({t})))
            if isinstance(node, ast.Call):
                classify_call(node, guards)
            for child in ast.iter_child_nodes(node):
                walk(child, guards)

        body = fi.node.body if isinstance(
            fi.node, (ast.FunctionDef, ast.AsyncFunctionDef)
        ) else [fi.node]
        for stmt in body:
            walk(stmt, ())
        self.events[fi.key] = events
        self.tries[fi.key] = tries

    # -- fixpoint ------------------------------------------------------------

    def event_types(self, ev: _Ev) -> dict:
        """Types an event may produce -> (via callee or None, desc)."""
        if ev.kind in ("raise", "api"):
            return {t: (None, ev.desc) for t in ev.types}
        if ev.callee is not None:
            esc = self.escapes.get(ev.callee, {})
            return {t: (ev.callee, "") for t in esc}
        return {}

    def absorbed(self, t: str, guards) -> bool:
        """True when some enclosing non-reraising handler catches ``t``
        (a first-matching handler that re-raises stays transparent)."""
        for g in guards:
            for names, reraises, _ in g.handlers:
                if self.catches(names, t):
                    if not reraises:
                        return True
                    break   # first match re-raises: continue outward
        return False

    def _fixpoint(self) -> None:
        changed = True
        while changed:
            changed = False
            for key, events in self.events.items():
                esc = self.escapes[key]
                opened = self.open_direct.get(key, False)
                for ev in events:
                    if ev.kind == "call" and ev.callee is not None and \
                            self.open.get(ev.callee, False):
                        opened = True
                    for t, (via, desc) in self.event_types(ev).items():
                        if t in esc or self.absorbed(t, ev.guards):
                            continue
                        esc[t] = (ev.node.lineno, via, desc)
                        changed = True
                if opened and not self.open.get(key, False):
                    self.open[key] = True
                    changed = True

    # -- queries -------------------------------------------------------------

    def arrivals(self, fn_key: str, try_node, handlers) -> dict:
        """Types arriving AT a given try's handler clause from its body:
        event types surviving guards INSIDE the try, keyed to the handler
        index that first matches (or absorbed earlier -> dropped)."""
        out: dict = {}   # type -> (handler index, ev)
        tid = id(try_node)
        for ev in self.events.get(fn_key, ()):
            pos = next((i for i, g in enumerate(ev.guards)
                        if g.try_id == tid), None)
            if pos is None:
                continue
            inner = ev.guards[pos + 1:]
            for t, (via, desc) in self.event_types(ev).items():
                if self.absorbed(t, inner):
                    continue
                for hi, (names, _, _) in enumerate(handlers):
                    if self.catches(names, t):
                        if t not in out:
                            out[t] = (hi, ev, via, desc)
                        break
        return out

    def try_is_closed(self, fn_key: str, try_node) -> bool:
        """Closed-world test for HG1002: every call event under this try
        resolves to a known raiser or a transitively-closed function."""
        tid = id(try_node)
        for ev in self.events.get(fn_key, ()):
            if not any(g.try_id == tid for g in ev.guards):
                continue
            if ev.kind != "call":
                continue
            if ev.unknown:
                return False
            if ev.callee is not None and self.open.get(ev.callee, False):
                return False
        return True

    def witness(self, fn_key: str, t: str, via, desc, limit: int = 5) -> str:
        """``caller -> callee -> ... -> origin`` chain for type ``t``."""
        names = [_short(fn_key)]
        cur = via
        tail = desc
        while cur is not None and len(names) < limit:
            names.append(_short(cur))
            ln, nxt, d = self.escapes.get(cur, {}).get(t, (0, None, ""))
            tail = d or tail
            cur = nxt
        chain = " -> ".join(names)
        if tail:
            chain += f" ({tail})"
        return chain


# ------------------------------------------------------------------- HG1001


def _swallowed_kills(cg: CallGraph, model: RaiseModel) -> list:
    findings = []
    for key, fi in sorted(cg.functions.items()):
        for try_node, handlers in model.tries.get(key, ()):
            arrivals = None
            for hi, (names, reraises, h) in enumerate(handlers):
                if reraises:
                    continue
                if not model.catches(names, "InjectedCrash"):
                    continue
                if arrivals is None:
                    arrivals = model.arrivals(key, try_node, handlers)
                hit = arrivals.get("InjectedCrash")
                if hit is None or hit[0] != hi:
                    continue
                _, ev, via, desc = hit
                chain = model.witness(key, "InjectedCrash", via, desc)
                spelled = "bare except" if h.type is None else \
                    f"except {_spell(h.type)}"
                findings.append(Finding(
                    rule="HG1001", path=fi.mod.path, line=h.lineno,
                    scope=fi.qualpath,
                    message=f"`{spelled}` swallows `InjectedCrash` "
                            f"(raised at line {ev.node.lineno} via "
                            f"{chain}) without re-raising — a swallowed "
                            f"simulated kill silently invalidates every "
                            f"recovery drill; re-raise non-Exception "
                            f"errors (`if not isinstance(e, Exception): "
                            f"raise`)",
                ))
    return findings


# ------------------------------------------------------------------- HG1002


def _dead_typed_handlers(cg: CallGraph, model: RaiseModel) -> list:
    findings = []
    for key, fi in sorted(cg.functions.items()):
        for try_node, handlers in model.tries.get(key, ()):
            arrivals = None
            for hi, (names, _, h) in enumerate(handlers):
                # typed FAULT handlers only: every caught name sits in the
                # FaultError taxonomy (broad/builtin catches are HG1005's
                # territory, not dead-code candidates)
                if not names or not all(
                    "FaultError" in model.ancestry(n) for n in names
                ):
                    continue
                if not model.try_is_closed(key, try_node):
                    continue
                if arrivals is None:
                    arrivals = model.arrivals(key, try_node, handlers)
                if any(idx == hi for idx, _, _, _ in arrivals.values()):
                    continue
                findings.append(Finding(
                    rule="HG1002", path=fi.mod.path, line=h.lineno,
                    scope=fi.qualpath,
                    message=f"dead typed handler `except {_spell(h.type)}`"
                            f" — the guarded calls' inferred raise-set "
                            f"{_fmt_types(arrivals) or '(empty)'} cannot "
                            f"contain it; the recovery it documents can "
                            f"never run",
                ))
    return findings


def _fmt_types(arrivals: dict) -> str:
    if not arrivals:
        return ""
    return "{" + ", ".join(sorted(arrivals)) + "}"


# ------------------------------------------------------------------- HG1003


def _retry_discipline(cg: CallGraph, model: RaiseModel) -> list:
    findings = []
    for key, fi in sorted(cg.functions.items()):
        loops = [n for n in ast.walk(fi.node)
                 if isinstance(n, (ast.While, ast.For))]
        if not loops:
            continue
        for try_node, handlers in model.tries.get(key, ()):
            loop = next(
                (lp for lp in loops
                 if any(n is try_node for n in ast.walk(lp))), None,
            )
            if loop is None:
                continue
            arrivals = None
            for hi, (names, reraises, h) in enumerate(handlers):
                if reraises or not _handler_retries(h):
                    continue
                extra = _handler_extra(
                    h, model.catch_aliases.get(fi.mod.name, {})
                )
                if extra is None:
                    # an is_transient(..., extra=<unresolvable>) call:
                    # the handler's transience contract can't be proved
                    # either way — stay silent (under-approximation)
                    continue
                explicit = sorted(
                    n for n in names
                    if model.transience(n, extra) is False
                )
                if explicit:
                    findings.append(Finding(
                        rule="HG1003", path=fi.mod.path, line=h.lineno,
                        scope=fi.qualpath,
                        message=f"retry loop catches non-transient "
                                f"{_fmt_set(explicit)} and re-attempts — "
                                f"retrying a permanent failure burns the "
                                f"caller's deadline for nothing; re-raise "
                                f"or fail the ticket instead",
                    ))
                    continue
                if not _is_broad(names, model) or \
                        _has_transience_guard(h):
                    continue
                if arrivals is None:
                    arrivals = model.arrivals(key, try_node, handlers)
                perm = sorted(
                    t for t, (idx, _, _, _) in arrivals.items()
                    if idx == hi and model.transience(t) is False
                )
                if perm:
                    findings.append(Finding(
                        rule="HG1003", path=fi.mod.path, line=h.lineno,
                        scope=fi.qualpath,
                        message=f"broad retry handler re-attempts "
                                f"provably non-transient {_fmt_set(perm)} "
                                f"raised in the loop body — gate the "
                                f"retry on `is_transient(e)` (or catch "
                                f"the transient types only)",
                    ))
    return findings


def _handler_retries(h: ast.ExceptHandler) -> bool:
    """True when the handler leads to another loop iteration: an explicit
    ``continue``, or a fall-through body with no raise/return/break."""
    for s in h.body:
        for n in ast.walk(s):
            if isinstance(n, ast.Continue):
                return True
    for s in h.body:
        for n in ast.walk(s):
            if isinstance(n, (ast.Raise, ast.Return, ast.Break)):
                return False
    return True


def _is_broad(names, model: RaiseModel) -> bool:
    return any(
        n in ("Exception", "BaseException") or
        model.catches(frozenset({n}), "PermanentFault")
        for n in names
    )


def _handler_extra(h: ast.ExceptHandler,
                   aliases: dict) -> Optional[frozenset]:
    """The union of ``extra=`` type tuples passed to ``is_transient``
    calls in the handler body (the runtime widens its transient set per
    call site: ``is_transient(e, extra=(CacheMiss,))``). Returns a
    frozenset of type names — empty when no call passes ``extra`` — or
    None when any ``extra`` argument is unresolvable."""
    extra: set = set()
    for s in h.body:
        for n in ast.walk(s):
            if not (isinstance(n, ast.Call) and (
                (isinstance(n.func, ast.Name) and
                 n.func.id == "is_transient") or
                (isinstance(n.func, ast.Attribute) and
                 n.func.attr == "is_transient")
            )):
                continue
            arg = n.args[1] if len(n.args) >= 2 else None
            for kw in n.keywords:
                if kw.arg == "extra":
                    arg = kw.value
            if arg is None:
                continue
            if isinstance(arg, ast.Name) and arg.id in aliases:
                extra |= set(aliases[arg.id])
                continue
            if not isinstance(arg, (ast.Tuple, ast.List)):
                return None
            names = [_type_name(e) for e in arg.elts]
            if any(nm is None for nm in names):
                return None
            extra |= set(names)
    return frozenset(extra)


def _has_transience_guard(h: ast.ExceptHandler) -> bool:
    for s in h.body:
        for n in ast.walk(s):
            if isinstance(n, ast.Call) and (
                (isinstance(n.func, ast.Name) and
                 n.func.id == "is_transient") or
                (isinstance(n.func, ast.Attribute) and
                 n.func.attr == "is_transient")
            ):
                return True
            if isinstance(n, ast.Attribute) and n.attr == "transient":
                return True
    return False


# ------------------------------------------------------------------- HG1004


def _entry_point_guards(cg: CallGraph, model: RaiseModel) -> list:
    from tools.hglint.rules_lifecycle import _thread_targets

    findings = []
    for key in sorted(_thread_targets(cg)):
        fi = cg.functions.get(key)
        if fi is None:
            continue
        esc = {
            t: v for t, v in model.escapes.get(key, {}).items()
            if not model.base_only(t)
        }
        if not esc:
            continue
        t = sorted(esc)[0]
        line, via, desc = esc[t]
        chain = model.witness(key, t, via, desc)
        findings.append(Finding(
            rule="HG1004", path=fi.mod.path, line=fi.lineno,
            scope=fi.qualpath,
            message=f"thread target `{fi.qualpath}` lets "
                    f"{_fmt_set(sorted(esc))} escape (e.g. line {line} "
                    f"via {chain}) — one raise kills the thread and "
                    f"strands its tickets/queue; guard the body with a "
                    f"broad except that resolves them (kills excepted)",
        ))
    return findings


# ------------------------------------------------------------------- HG1005


def _swallow_evidence(cg: CallGraph, model: RaiseModel) -> list:
    findings = []
    for key, fi in sorted(cg.functions.items()):
        for try_node, handlers in model.tries.get(key, ()):
            for names, reraises, h in handlers:
                if reraises:
                    continue
                if not ("Exception" in names or "BaseException" in names):
                    continue
                if _handler_has_evidence(h):
                    continue
                spelled = "bare except" if h.type is None else \
                    f"except {_spell(h.type)}"
                findings.append(Finding(
                    rule="HG1005", path=fi.mod.path, line=h.lineno,
                    scope=fi.qualpath,
                    message=f"`{spelled}` swallows the error with no "
                            f"evidence — no re-raise, log, counter, "
                            f"ticket resolution, or typed fallback; a "
                            f"silent swallow here turns a failure into "
                            f"a stuck request",
                ))
    return findings


def _handler_has_evidence(h: ast.ExceptHandler) -> bool:
    bound = h.name
    for s in h.body:
        for n in ast.walk(s):
            if isinstance(n, (ast.Continue, ast.Break, ast.Return,
                              ast.Yield, ast.YieldFrom, ast.Delete)):
                return True   # loop control / an explicit fallback result
                # is a DECISION — the caller's contract includes it
            if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                return True   # fallback binding the fall-through code uses
            if bound and isinstance(n, ast.Name) and n.id == bound and \
                    isinstance(n.ctx, ast.Load):
                return True   # the exception object is captured/used
            if isinstance(n, ast.Call):
                f = n.func
                attr = f.attr if isinstance(f, ast.Attribute) else (
                    f.id if isinstance(f, ast.Name) else None
                )
                if attr is None:
                    continue
                if attr in EVIDENCE_METHODS:
                    return True
                low = attr.lower()
                if low.startswith(("log", "fail", "record", "emit")):
                    return True
    return False


# ------------------------------------------------------------------ helpers


def _type_name(node: ast.AST) -> Optional[str]:
    """``TransientFault`` / ``errors.TransientFault`` -> short type name;
    None for anything that doesn't look like an exception class."""
    if isinstance(node, ast.Attribute):
        name = node.attr
    elif isinstance(node, ast.Name):
        name = node.id
    else:
        return None
    return name if name[:1].isupper() else None


def _raised_type(node: ast.Raise) -> Optional[str]:
    exc = node.exc
    if exc is None:
        return None           # bare re-raise: handled via guard reraises
    if isinstance(exc, ast.Call):
        exc = exc.func
    return _type_name(exc)


def _known_api(node: ast.Call, fi) -> Optional[tuple]:
    """(raise types, description) for known-raising runtime APIs the call
    graph cannot resolve (receiver-typed method calls)."""
    func = node.func
    fqn = resolve_fqn(func, fi.mod)
    if fqn in ("urllib.request.urlopen", "socket.create_connection"):
        return TRANSPORT_RAISES, f"{fqn} (transport)"
    if not isinstance(func, ast.Attribute):
        return None
    attr = func.attr
    if attr == "check" and node.args and \
            isinstance(node.args[0], ast.Constant) and \
            isinstance(node.args[0].value, str) and \
            "." in node.args[0].value:
        return FAULT_POINT_RAISES, (
            f"fault point {node.args[0].value!r}"
        )
    if attr in TRANSPORT_METHODS:
        return TRANSPORT_RAISES, f".{attr} (transport)"
    if attr.startswith("submit"):
        return SUBMIT_RAISES, f".{attr} (submit entry)"
    return None


def _closed_call(node: ast.Call) -> bool:
    f = node.func
    if isinstance(f, ast.Name):
        return f.id in CLOSED_FUNCS
    if isinstance(f, ast.Attribute):
        return f.attr in CLOSED_METHODS
    return False


def _spell(node) -> str:
    if node is None:
        return ""
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover
        return "<type>"


def _fmt_set(names) -> str:
    return "{" + ", ".join(f"`{n}`" for n in names) + "}"


def _short(key: str) -> str:
    return key.rsplit(".", 1)[-1] if "." in key else key
