"""Module loading and name/constant resolution for hglint.

Everything here is pure AST work — target code is never imported, so
fixture files may contain deliberately broken or device-only code.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Optional

#: ``# hglint: disable=HG502`` / ``# hglint: disable=HG501,HG502`` — line
#: pragma suppressing the named rules for findings reported on that line
_PRAGMA_RE = re.compile(r"#\s*hglint:\s*disable=([A-Za-z0-9_,\s]+)")


@dataclass
class ModuleInfo:
    name: str                     # dotted module name, e.g. "pkg.ops.frontier"
    path: str                     # path as reported in findings
    tree: ast.Module
    imports: dict = field(default_factory=dict)   # local alias -> dotted fqn
    toplevel: set = field(default_factory=set)    # names def'd at module level
    consts: dict = field(default_factory=dict)    # module-level literal consts
    mutable_globals: dict = field(default_factory=dict)  # name -> lineno
    np_globals: dict = field(default_factory=dict)  # numpy-valued module
    #                                                 globals: name -> lineno
    pragmas: dict = field(default_factory=dict)   # lineno -> {rule ids}


def discover_modules(root: str) -> list[ModuleInfo]:
    """Load every ``*.py`` under ``root`` (a package dir or plain dir, or a
    single file). Module names are derived from the path below the root's
    parent; when two lint roots contain same-named packages, the call graph
    uniquifies colliding function keys (see ``callgraph._index_functions``)
    so no tree's findings are dropped."""
    mods: list[ModuleInfo] = []
    if os.path.isfile(root):
        files = [root]
        base = os.path.dirname(root) or "."
    else:
        base = os.path.dirname(os.path.abspath(root))
        files = []
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = sorted(
                d for d in dirnames
                if d not in ("__pycache__", ".git")
            )
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    files.append(os.path.join(dirpath, fn))
    for path in files:
        with open(path, "r", encoding="utf-8") as fh:
            src = fh.read()
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError:
            continue  # not our job; flake8/py_compile own syntax errors
        name = _module_name(path, base)
        rel = os.path.relpath(path)
        shown = rel if not rel.startswith("..") else path
        mod = ModuleInfo(name=name, path=shown, tree=tree)
        for lineno, line in enumerate(src.splitlines(), start=1):
            m = _PRAGMA_RE.search(line)
            if m:
                mod.pragmas[lineno] = {
                    r.strip() for r in m.group(1).split(",") if r.strip()
                }
        _index_module(mod)
        mods.append(mod)
    return mods


def _module_name(path: str, base: str) -> str:
    rel = os.path.relpath(os.path.abspath(path), base)
    parts = rel[:-3].split(os.sep)  # strip .py
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


# --------------------------------------------------------------- module index


def _module_stmts(tree: ast.Module):
    """Module-level statements, descending into try/except/if bodies so
    guarded imports (``try: import fast except ImportError: import shim``)
    register like plain ones."""
    stack = list(reversed(tree.body))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, ast.Try):
            stack.extend(reversed(
                node.body + node.orelse + node.finalbody
                + [s for h in node.handlers for s in h.body]
            ))
        elif isinstance(node, ast.If):
            stack.extend(reversed(node.body + node.orelse))


def _index_module(mod: ModuleInfo) -> None:
    pkg_parts = mod.name.split(".")[:-1]  # package containing this module
    for node in _module_stmts(mod.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                local = alias.asname or alias.name.split(".")[0]
                target = alias.name if alias.asname else alias.name.split(".")[0]
                mod.imports[local] = target
        elif isinstance(node, ast.ImportFrom):
            src = _resolve_from(node, pkg_parts)
            if src is None:
                continue
            for alias in node.names:
                if alias.name == "*":
                    continue
                local = alias.asname or alias.name
                mod.imports[local] = f"{src}.{alias.name}" if src else alias.name
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            mod.toplevel.add(node.name)
        elif isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            value = node.value
            for t in targets:
                if not isinstance(t, ast.Name):
                    continue
                mod.toplevel.add(t.id)
                if value is None:
                    continue
                cv = literal_value(value)
                if cv is not NOT_CONST:
                    mod.consts[t.id] = cv
                if _is_mutable_literal(value):
                    mod.mutable_globals[t.id] = t.lineno
                if isinstance(value, ast.Call):
                    fqn = resolve_fqn(value.func, mod)
                    if fqn and fqn.startswith("numpy."):
                        mod.np_globals[t.id] = t.lineno


def _resolve_from(node: ast.ImportFrom, pkg_parts: list[str]) -> Optional[str]:
    if node.level == 0:
        return node.module or ""
    # relative import: climb level-1 packages up from the containing package
    up = node.level - 1
    if up > len(pkg_parts):
        return None
    head = pkg_parts[: len(pkg_parts) - up]
    if node.module:
        head = head + node.module.split(".")
    return ".".join(head)


def _is_mutable_literal(node: ast.AST) -> bool:
    if isinstance(node, (ast.Dict, ast.List, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp)):
        return True
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        return node.func.id in ("dict", "list", "set", "defaultdict",
                                "OrderedDict", "deque")
    return False


# ----------------------------------------------------------- name resolution


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` attribute chain -> "a.b.c"; None for anything else."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


def resolve_fqn(node: ast.AST, mod: ModuleInfo) -> Optional[str]:
    """Resolve an expression to a fully-qualified dotted name using the
    module's import map. ``jnp.asarray`` -> "jax.numpy.asarray";
    a module-level symbol ``f`` -> "<modname>.f"; an unknown bare name is
    returned as-is (so builtins read as "float", "int", ...)."""
    dn = dotted_name(node)
    if dn is None:
        return None
    head, _, rest = dn.partition(".")
    if head in mod.imports:
        base = mod.imports[head]
        return f"{base}.{rest}" if rest else base
    if head in mod.toplevel:
        return f"{mod.name}.{dn}"
    return dn


# ------------------------------------------------------ constant evaluation

NOT_CONST = object()

_DTYPE_HEADS = ("jax.numpy.", "numpy.", "jnp.", "np.")


def literal_value(node: ast.AST):
    """Evaluate compile-time literals: ints, floats, strings, bools, None,
    and tuples/lists of them. Unresolvable leaves inside a tuple become
    ``None`` elements (rank survives, value doesn't); anything else returns
    ``NOT_CONST``."""
    if isinstance(node, ast.Constant):
        return node.value
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            v = literal_value(e)
            out.append(None if v is NOT_CONST else v)
        return tuple(out)
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        v = literal_value(node.operand)
        if isinstance(v, (int, float)):
            return -v
        return NOT_CONST
    return NOT_CONST


class ConstEnv:
    """Best-effort integer/tuple constant environment: module-level literal
    assignments plus (optionally) straight-line function-local assignments.
    ``eval_node`` returns an int/float/str/tuple or None when unknown."""

    def __init__(self, mod: ModuleInfo, local: Optional[dict] = None):
        self.mod = mod
        self.env: dict = dict(mod.consts)
        if local:
            self.env.update(local)

    @classmethod
    def for_function(cls, mod: ModuleInfo, fn: ast.AST) -> "ConstEnv":
        ce = cls(mod)
        for stmt in ast.walk(fn):
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                    isinstance(stmt.targets[0], ast.Name):
                v = ce.eval_node(stmt.value)
                name = stmt.targets[0].id
                # later unknown assignment shadows an earlier known one
                ce.env[name] = v
        return ce

    def eval_node(self, node: ast.AST):
        if node is None:
            return None
        if isinstance(node, ast.Constant):
            return node.value
        if isinstance(node, ast.Name):
            return self.env.get(node.id)
        if isinstance(node, (ast.Tuple, ast.List)):
            return tuple(self.eval_node(e) for e in node.elts)
        if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
            v = self.eval_node(node.operand)
            return -v if isinstance(v, (int, float)) else None
        if isinstance(node, ast.BinOp):
            lhs = self.eval_node(node.left)
            rhs = self.eval_node(node.right)
            if not isinstance(lhs, (int, float)) or \
                    not isinstance(rhs, (int, float)):
                return None
            try:
                if isinstance(node.op, ast.Add):
                    return lhs + rhs
                if isinstance(node.op, ast.Sub):
                    return lhs - rhs
                if isinstance(node.op, ast.Mult):
                    return lhs * rhs
                if isinstance(node.op, ast.FloorDiv):
                    return lhs // rhs
                if isinstance(node.op, ast.Mod):
                    return lhs % rhs
                if isinstance(node.op, ast.LShift):
                    return lhs << rhs
                if isinstance(node.op, ast.RShift):
                    return lhs >> rhs
                if isinstance(node.op, ast.Pow):
                    return lhs ** rhs
            except Exception:
                return None
        return None


def dtype_name(node: ast.AST, mod: ModuleInfo) -> Optional[str]:
    """"jnp.int32" / "np.float32" / '"uint32"' -> canonical dtype string."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    fqn = resolve_fqn(node, mod)
    if fqn is None:
        return None
    for head in _DTYPE_HEADS:
        if fqn.startswith(head):
            return fqn[len(head):]
    return None


def def_time_exprs(fn_node: ast.AST) -> list:
    """Expressions a ``def``/``class`` statement evaluates in its
    ENCLOSING scope when it executes: decorators, parameter defaults,
    and annotations (evaluated eagerly absent ``from __future__ import
    annotations`` — including them is the conservative attribution
    either way). Decorators of a module-level function run at import on
    host; the same decorators on a def nested inside a jitted function
    run under tracing — scope attribution matters."""
    out = list(getattr(fn_node, "decorator_list", ()))
    args = getattr(fn_node, "args", None)
    if args is not None:
        out.extend(args.defaults)
        out.extend(d for d in args.kw_defaults if d is not None)
        params = (args.posonlyargs + args.args + args.kwonlyargs
                  + [a for a in (args.vararg, args.kwarg) if a])
        out.extend(a.annotation for a in params if a.annotation)
    ret = getattr(fn_node, "returns", None)
    if ret is not None:
        out.append(ret)
    return out


def own_nodes(fn_node: ast.AST):
    """Yield every descendant of a function node that belongs to the
    function's own scope — nested function/class definitions are not
    entered (they are analyzed as their own scopes), but their decorators
    and parameter defaults ARE yielded (they execute when the nested
    ``def`` runs, i.e. in this scope). The function's OWN decorators and
    defaults are excluded: they run in the enclosing (usually module =
    host) scope, not under trace. Lambdas ARE entered: they trace with
    their parent."""
    if isinstance(fn_node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        stack = list(fn_node.body)
    else:
        stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            stack.extend(def_time_exprs(node))
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


#: dtype -> required sublane multiple on TPU (second-to-last block dim)
DTYPE_SUBLANE = {
    "float32": 8, "int32": 8, "uint32": 8,
    "bfloat16": 16, "float16": 16, "int16": 16, "uint16": 16,
    "int8": 32, "uint8": 32, "float8_e4m3fn": 32, "float8_e5m2": 32,
    "bool": 8, "bool_": 8,
}
