"""HG2xx — retrace / recompile hazards.

HG201  jax.jit(...) constructed inside a Python loop (fresh callable each
       iteration -> full retrace per iteration).
HG202  Python `if`/`while` on a traced (non-static) parameter of a jit
       root — under trace this raises or bakes in one branch.
HG203  traced function reads a mutable module-level global (dict/list/set)
       — silently captured at trace time, later mutations are invisible.
HG204  static_argnums/static_argnames given a non-hashable value.
"""

from __future__ import annotations

import ast

from tools.hglint.callgraph import (
    JIT_FQNS,
    PARTIAL_FQNS,
    CallGraph,
)
from tools.hglint.loader import ModuleInfo, own_nodes, resolve_fqn
from tools.hglint.model import Finding

SHAPE_ATTRS = {"shape", "ndim", "size", "dtype"}


def check(cg: CallGraph, modules: list) -> list:
    findings = []
    for mod in modules:
        findings += _jit_in_loop(mod)
        findings += _unhashable_static(mod)
    for fi in cg.functions.values():
        if fi.root_kind == "jit":
            findings += _branch_on_traced(fi)
    for fi in cg.traced_functions():
        findings += _mutable_global_capture(fi)
    return findings


# ------------------------------------------------------------------- HG201


def _jit_in_loop(mod: ModuleInfo) -> list:
    findings = []
    for loop in ast.walk(mod.tree):
        if not isinstance(loop, (ast.For, ast.While)):
            continue
        for node in _loop_own_nodes(loop):
            if not isinstance(node, ast.Call):
                continue
            if _is_jit_ctor(node, mod):
                scope = _enclosing_scope(mod, loop)
                findings.append(Finding(
                    rule="HG201", path=mod.path, line=node.lineno,
                    scope=scope,
                    message="jax.jit(...) constructed inside a loop — hoist "
                            "the jitted callable out of the loop",
                ))
    return findings


def _loop_own_nodes(loop: ast.AST):
    """Descendants of a loop body, not descending into nested defs (a def
    inside the loop only traces when called)."""
    stack = loop.body + getattr(loop, "orelse", [])
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _is_jit_ctor(call: ast.Call, mod: ModuleInfo) -> bool:
    fqn = resolve_fqn(call.func, mod)
    if fqn in JIT_FQNS:
        return True
    if fqn in PARTIAL_FQNS and call.args:
        return resolve_fqn(call.args[0], mod) in JIT_FQNS
    return False


def _enclosing_scope(mod: ModuleInfo, target: ast.AST) -> str:
    """qualname of the innermost def/class containing ``target``."""
    best = "<module>"

    def walk(node, qual):
        nonlocal best
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                q = qual + [child.name]
                if _contains(child, target):
                    best = ".".join(q)
                walk(child, q)
            else:
                walk(child, qual)

    walk(mod.tree, [])
    return best


def _contains(root: ast.AST, target: ast.AST) -> bool:
    return any(n is target for n in ast.walk(root))


# ------------------------------------------------------------------- HG202


def _branch_on_traced(fi) -> list:
    traced_params = [p for p in fi.params if p not in fi.static_params]
    if traced_params:
        traced_params = set(traced_params)
    else:
        return []
    findings = []
    for node in own_nodes(fi.node):
        if not isinstance(node, (ast.If, ast.While)):
            continue
        hit = _traced_name_in_test(node.test, traced_params)
        if hit:
            findings.append(Finding(
                rule="HG202", path=fi.mod.path, line=node.lineno,
                scope=fi.qualpath,
                message=f"Python branch on traced parameter `{hit}` of jit "
                        f"root `{fi.qualpath}` — use lax.cond/jnp.where or "
                        f"mark it static",
            ))
    return findings


def _traced_name_in_test(test: ast.AST, traced_params: set):
    """First traced param name the branch condition concretizes, pruning
    constructs that are static under tracing (shape/dtype access, len,
    isinstance, `is [not] None`)."""
    if isinstance(test, ast.Compare) and all(
        isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops
    ):
        return None
    if isinstance(test, ast.Attribute):
        if test.attr in SHAPE_ATTRS:
            return None
        return _traced_name_in_test(test.value, traced_params)
    if isinstance(test, ast.Call):
        fn = test.func
        if isinstance(fn, ast.Name) and fn.id in ("len", "isinstance",
                                                  "hasattr", "getattr"):
            return None
        for sub in [fn] + list(test.args):
            hit = _traced_name_in_test(sub, traced_params)
            if hit:
                return hit
        return None
    if isinstance(test, ast.Subscript):
        return _traced_name_in_test(test.value, traced_params)
    if isinstance(test, ast.Name):
        return test.id if test.id in traced_params else None
    for child in ast.iter_child_nodes(test):
        hit = _traced_name_in_test(child, traced_params)
        if hit:
            return hit
    return None


# ------------------------------------------------------------------- HG203


def _mutable_global_capture(fi) -> list:
    mg = fi.mod.mutable_globals
    if not mg:
        return []
    local_stores = set(fi.params)
    loads: dict[str, int] = {}
    for node in own_nodes(fi.node):
        if isinstance(node, ast.Name):
            if isinstance(node.ctx, ast.Store):
                local_stores.add(node.id)
            elif node.id in mg:
                loads.setdefault(node.id, node.lineno)
        elif isinstance(node, ast.Global):
            local_stores.update(node.names)  # explicit opt-out of capture
    findings = []
    for name, lineno in sorted(loads.items()):
        if name in local_stores:
            continue
        findings.append(Finding(
            rule="HG203", path=fi.mod.path, line=lineno, scope=fi.qualpath,
            message=f"traced function reads mutable module global `{name}` "
                    f"(defined at line {mg[name]}) — captured at trace "
                    f"time, later mutations are invisible",
        ))
    return findings


# ------------------------------------------------------------------- HG204


def _unhashable_static(mod: ModuleInfo) -> list:
    findings = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        if not _is_jit_ctor(node, mod):
            continue
        for kw in node.keywords:
            if kw.arg not in ("static_argnums", "static_argnames"):
                continue
            bad = _unhashable(kw.value)
            if bad is not None:
                findings.append(Finding(
                    rule="HG204", path=mod.path, line=kw.value.lineno,
                    scope=_enclosing_scope(mod, node),
                    message=f"`{kw.arg}` given a non-hashable {bad} — jit "
                            f"raises (or silently retraces) at call time",
                ))
    return findings


def _unhashable(expr: ast.AST):
    if isinstance(expr, (ast.Dict, ast.DictComp)):
        return "dict"
    if isinstance(expr, (ast.Set, ast.SetComp)):
        return "set"
    if isinstance(expr, (ast.Tuple, ast.List)):
        for e in expr.elts:
            bad = _unhashable(e)
            if bad:
                return f"{bad} element"
    return None
