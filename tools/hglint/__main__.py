"""CLI: ``python -m tools.hglint [paths...] [--baseline FILE]``.

Exit status: 0 no (post-baseline) findings · 1 findings · 2 usage error
(argparse) · 3 analyzer crash. ``tools/lint.sh`` distinguishes crashes
from findings by the >= 2 codes.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import traceback

from tools.hglint import engine


def _changed_files(rev: str) -> set:
    """Files changed vs ``rev`` plus untracked files, as cwd-relative
    paths (module paths in findings are cwd-relative too)."""
    def git(*argv, cwd=None):
        out = subprocess.run(
            ["git", *argv], cwd=cwd, capture_output=True, text=True,
        )
        if out.returncode != 0:
            raise RuntimeError(out.stderr.strip() or f"git {argv[0]} failed")
        return out.stdout
    top = git("rev-parse", "--show-toplevel").strip()
    names = git("diff", "--name-only", rev, "--", cwd=top).splitlines()
    names += git("ls-files", "--others", "--exclude-standard",
                 cwd=top).splitlines()
    return {
        os.path.relpath(os.path.join(top, n))
        for n in names if n.strip()
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="hglint",
        description="AST-based JAX/TPU hazard analyzer "
                    "(host-sync, retrace, Pallas tiling, lock-order, VMEM "
                    "budgets, shard_map collectives, donation lifetimes, "
                    "blocking-under-lock, thread/resource lifecycle, "
                    "exception-flow discipline, wire contracts)",
    )
    p.add_argument("paths", nargs="*", default=["hypergraphdb_tpu"],
                   help="package dirs / files to analyze "
                        "(default: hypergraphdb_tpu)")
    p.add_argument("--baseline", metavar="FILE", default=None,
                   help="suppress findings recorded in this baseline json")
    p.add_argument("--write-baseline", metavar="FILE", default=None,
                   help="write current findings as the new baseline and "
                        "exit 0")
    p.add_argument("--only", metavar="PREFIXES", default=None,
                   help="comma-separated rule-id prefixes to run "
                        "(e.g. 'HG5' or 'HG5,HG601') — skips other rule "
                        "families entirely for fast local runs")
    p.add_argument("--diff-base", metavar="REV", default=None,
                   help="report only findings in files changed vs this "
                        "git rev (plus untracked files); the WHOLE package "
                        "is still analyzed so call-graph edges stay "
                        "whole-program — this scopes the report, not the "
                        "analysis")
    p.add_argument("--vmem-budget", metavar="BYTES", type=int, default=None,
                   help="per-core VMEM budget for HG501 "
                        "(default 16 MiB = 16777216)")
    p.add_argument("--output", choices=("text", "json"), default="text",
                   help="'json' emits the full machine-readable report "
                        "(counts, findings, doc anchors) for CI")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit findings as a bare json list "
                        "(legacy; prefer --output json)")
    p.add_argument("--severity", choices=("error", "warning", "info"),
                   default=None,
                   help="only report findings at this severity")
    args = p.parse_args(argv)

    try:
        engine.parse_only(args.only)   # validate prefixes up front
    except ValueError as e:
        p.error(str(e))                # usage error: exit 2

    if args.diff_base and args.write_baseline:
        p.error("--diff-base cannot be combined with --write-baseline: a "
                "scoped run must never become the whole-tree baseline")

    changed = None
    if args.diff_base:
        try:
            changed = _changed_files(args.diff_base)
        except Exception as e:
            p.error(f"--diff-base {args.diff_base!r}: {e}")

    try:
        findings = engine.run_lint(
            args.paths, only=args.only, vmem_budget=args.vmem_budget,
            changed_files=changed,
        )

        if args.write_baseline:
            engine.write_baseline(findings, args.write_baseline)
            print(f"wrote {len(findings)} findings to "
                  f"{args.write_baseline}")
            return 0

        suppressed = 0
        if args.baseline:
            baseline = engine.load_baseline(args.baseline)
            fresh = engine.apply_baseline(findings, baseline)
            suppressed = len(findings) - len(fresh)
            findings = fresh
            label = "new finding(s) beyond baseline"
        else:
            label = "finding(s)"

        if args.severity:
            findings = [f for f in findings if f.severity == args.severity]
    except Exception:
        traceback.print_exc(file=sys.stderr)
        print("hglint: internal analyzer crash (exit 3) — this is a lint "
              "bug, not a finding", file=sys.stderr)
        return 3

    if args.output == "json":
        print(json.dumps(engine.build_report(
            findings, args.paths, baseline_path=args.baseline,
            suppressed=suppressed, only=args.only,
            vmem_budget=args.vmem_budget, diff_base=args.diff_base,
            changed_files=changed,
        ), indent=2))
    elif args.as_json:
        print(json.dumps(
            [engine.finding_dict(f) for f in findings], indent=2,
        ))
    else:
        for f in findings:
            print(f.render())
        print(f"hglint: {len(findings)} {label}; "
              f"{engine.summarize(findings)}")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
