"""CLI: ``python -m tools.hglint [paths...] [--baseline FILE]``.

Exit status: 0 when no (post-baseline) findings, 1 otherwise.
"""

from __future__ import annotations

import argparse
import json
import sys

from tools.hglint import engine


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="hglint",
        description="AST-based JAX/TPU hazard analyzer "
                    "(host-sync, retrace, Pallas tiling, lock-order)",
    )
    p.add_argument("paths", nargs="*", default=["hypergraphdb_tpu"],
                   help="package dirs / files to analyze "
                        "(default: hypergraphdb_tpu)")
    p.add_argument("--baseline", metavar="FILE", default=None,
                   help="suppress findings recorded in this baseline json")
    p.add_argument("--write-baseline", metavar="FILE", default=None,
                   help="write current findings as the new baseline and "
                        "exit 0")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit findings as json")
    p.add_argument("--severity", choices=("error", "warning", "info"),
                   default=None,
                   help="only report findings at this severity")
    args = p.parse_args(argv)

    findings = engine.run_lint(args.paths)

    if args.write_baseline:
        engine.write_baseline(findings, args.write_baseline)
        print(f"wrote {len(findings)} findings to {args.write_baseline}")
        return 0

    if args.baseline:
        baseline = engine.load_baseline(args.baseline)
        findings = engine.apply_baseline(findings, baseline)
        label = "new finding(s) beyond baseline"
    else:
        label = "finding(s)"

    if args.severity:
        findings = [f for f in findings if f.severity == args.severity]

    if args.as_json:
        print(json.dumps(
            [
                {
                    "rule": f.rule, "severity": f.severity, "path": f.path,
                    "line": f.line, "scope": f.scope, "message": f.message,
                }
                for f in findings
            ],
            indent=2,
        ))
    else:
        for f in findings:
            print(f.render())
        print(f"hglint: {len(findings)} {label}; {engine.summarize(findings)}")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
