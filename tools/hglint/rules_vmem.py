"""HG5xx — static VMEM budgeting per ``pl.pallas_call``.

A TPU core has ~16 MiB of VMEM (see pallas guide: HBM → VMEM → compute).
Mosaic allocates every blocked input/output a **double-buffered** VMEM
window (compute on block k while block k+1 streams in) plus every
``scratch_shapes`` VMEM buffer once; a call whose working set exceeds the
budget fails at compile time on hardware — on CPU interpret-mode tests it
silently passes, which is exactly the hazard this rule pins.

The model, per ``pallas_call`` site (via :mod:`tools.hglint.absint`):

- each in/out ``BlockSpec`` with a VMEM (or default) memory space
  contributes ``tile_padded(block_shape) * dtype_bytes * (2 if gridded
  else 1)`` — block dims are padded up to the dtype's (sublane, 128)
  tile, matching Mosaic's physical allocation;
- a BlockSpec dim of ``None`` (and a missing block_shape) means "the full
  array dim", taken from the folded operand / ``out_shape``;
- ``memory_space=ANY``/``SMEM``/semaphore specs contribute nothing
  (they never live in VMEM);
- ``scratch_shapes`` ``pltpu.VMEM((dims), dtype)`` entries contribute
  once; DMA semaphores contribute nothing;
- input dtypes come from abstract evaluation of the operands actually
  passed to the returned callable; an unresolvable dtype falls back to 4
  bytes (every index/mask array here is 32-bit — assuming wider would
  manufacture overflows we cannot prove).

HG501 (error)  the folded working set exceeds the budget (default 16 MiB,
               ``--vmem-budget`` to override).
HG502 (warn)   the working set is NOT statically resolvable — some block
               dim, operand shape, or scratch shape doesn't fold. Fix by
               making the shape static, or verify the bound by hand, guard
               it at runtime, and add ``# hglint: disable=HG502`` on the
               flagged line.
HG503 (error)  the SCALAR-PREFETCH operands (the first
               ``num_scalar_prefetch`` arguments of a
               ``PrefetchScalarGridSpec`` call) exceed the 1 MB SMEM
               budget. Scalar prefetch lands whole in SMEM before the
               grid runs — an oversized index segment fails Mosaic
               allocation on hardware while CPU interpret tests pass
               (the hazard ``ops/pallas_gather.py`` bounds with its
               ``SEG`` segmentation; its import-time guard asserts the
               same contract this rule checks statically). Operands that
               do not fold stay silent here — silence over guessing; the
               VMEM model reports its own unresolvables via HG502.
"""

from __future__ import annotations

import ast
from typing import Optional

from tools.hglint.absint import (
    UNKNOWN,
    Interp,
    ShapeDtype,
    element_bytes,
)
from tools.hglint.callgraph import PALLAS_FQNS, CallGraph, CallSite
from tools.hglint.loader import DTYPE_SUBLANE, resolve_fqn
from tools.hglint.model import Finding

#: default per-core VMEM budget in bytes (v4/v5 generations: ~16 MiB)
DEFAULT_VMEM_BUDGET = 16 << 20

#: per-core SMEM budget for scalar-prefetch operands (1 MB on v4/v5)
SMEM_BUDGET = 1 << 20

LANE = 128

_VMEM_TAILS = (".VMEM",)
_OFF_VMEM_TAILS = (".ANY", ".SMEM", ".HBM", ".SEMAPHORE")


def check(cg: CallGraph, modules: list, interp: Interp,
          budget: int = DEFAULT_VMEM_BUDGET) -> list:
    # map pallas_call(...) node -> the outer call that supplies operands:
    # ``pl.pallas_call(kernel, ...)(x, y)`` parses as Call(Call(...), x, y)
    outer_by_inner = {}
    for site in cg.calls:
        if isinstance(site.node.func, ast.Call):
            outer_by_inner[id(site.node.func)] = site.node
    findings = []
    for site in cg.calls:
        fqn = resolve_fqn(site.node.func, site.mod)
        if fqn not in PALLAS_FQNS:
            continue
        findings += _check_call(
            cg, site, interp, budget, outer_by_inner.get(id(site.node))
        )
    return findings


# ---------------------------------------------------------------- per call


def _check_call(cg: CallGraph, site: CallSite, interp: Interp, budget: int,
                outer: Optional[ast.Call]) -> list:
    call, mod = site.node, site.mod
    fi = cg.functions.get(site.fn_key) if site.fn_key else None
    env = interp.env_for(fi) if fi is not None else {}
    scope = fi.qualpath if fi else "<module>"

    kw = {k.arg: k.value for k in call.keywords if k.arg}
    grid_node = kw.get("grid")
    in_specs = kw.get("in_specs")
    out_specs = kw.get("out_specs")
    scratch = kw.get("scratch_shapes")
    n_scalar = 0
    gs = kw.get("grid_spec")
    if isinstance(gs, ast.Call):
        gkw = {k.arg: k.value for k in gs.keywords if k.arg}
        grid_node = gkw.get("grid", grid_node)
        in_specs = gkw.get("in_specs", in_specs)
        out_specs = gkw.get("out_specs", out_specs)
        scratch = gkw.get("scratch_shapes", scratch)
        v = interp.eval(gkw.get("num_scalar_prefetch"), env, mod)
        if isinstance(v, int):
            n_scalar = v

    gridded = grid_node is not None
    buf_factor = 2 if gridded else 1

    # abstract operand values (for dtypes and full-dim substitution)
    operands: list = []
    if outer is not None:
        operands = [interp.eval(a, env, mod) for a in outer.args]
    scalar_ops = operands[:n_scalar]  # scalar-prefetch args live in SMEM
    operands = operands[n_scalar:]

    smem_findings = _check_smem(scalar_ops, call, mod, scope)

    out_vals = _out_shape_vals(kw.get("out_shape"), interp, env, mod)

    total = 0
    unresolved: list[str] = []

    in_elts = _spec_nodes(in_specs)
    if in_elts is None and in_specs is not None:
        unresolved.append("in_specs is not a literal list/tuple/BlockSpec")
        in_elts = []
    for i, spec in enumerate(in_elts or []):
        op = operands[i] if i < len(operands) else UNKNOWN
        total += _spec_bytes(
            spec, op, interp, env, mod, buf_factor, unresolved,
            f"in_specs[{i}]",
        )
    if in_specs is None and operands:
        # no blocking: each operand lands in VMEM whole
        for i, op in enumerate(operands):
            total += _whole_array_bytes(op, buf_factor, unresolved,
                                        f"operand {i}")

    out_elts = _spec_nodes(out_specs)
    if out_elts is None and out_specs is not None:
        unresolved.append("out_specs is not a literal list/tuple/BlockSpec")
        out_elts = []
    for i, spec in enumerate(out_elts or []):
        ov = out_vals[i] if i < len(out_vals) else UNKNOWN
        total += _spec_bytes(
            spec, ov, interp, env, mod, buf_factor, unresolved,
            f"out_specs[{i}]",
        )
    if out_specs is None:
        if out_vals:
            for i, ov in enumerate(out_vals):
                total += _whole_array_bytes(ov, buf_factor, unresolved,
                                            f"out_shape[{i}]")
        else:
            unresolved.append("out_shape does not fold")

    for j, sc in enumerate(_scratch_nodes(scratch)):
        total += _scratch_bytes(sc, interp, env, mod, unresolved, j)

    if unresolved:
        return smem_findings + [Finding(
            rule="HG502", path=mod.path, line=call.lineno, scope=scope,
            message=(
                "VMEM working set of pallas_call is not statically "
                "resolvable (" + "; ".join(unresolved[:3])
                + ("; ..." if len(unresolved) > 3 else "")
                + f"); resolved portion is {_fmt(total)} — make the "
                "shapes static or verify the budget by hand and add "
                "`# hglint: disable=HG502` with a runtime guard"
            ),
        )]
    if total > budget:
        return smem_findings + [Finding(
            rule="HG501", path=mod.path, line=call.lineno, scope=scope,
            message=(
                f"pallas_call VMEM working set {_fmt(total)} exceeds the "
                f"{_fmt(budget)} per-core budget (double-buffered blocks + "
                f"scratch); shrink block shapes or re-tile the grid"
            ),
        )]
    return smem_findings


def _check_smem(scalar_ops: list, call: ast.Call, mod, scope: str) -> list:
    """HG503: folded scalar-prefetch operand bytes vs the SMEM budget.
    SMEM is scalar memory — raw element bytes, no (sublane, lane) tile
    padding. Unfoldable operands contribute nothing (silence over
    guessing)."""
    total = 0
    for op in scalar_ops:
        if isinstance(op, ShapeDtype) and op.shape is not None and \
                all(isinstance(d, int) for d in op.shape):
            n = 1
            for d in op.shape:
                n *= max(d, 1)
            total += n * element_bytes(op.dtype)
    if total <= SMEM_BUDGET:
        return []
    return [Finding(
        rule="HG503", path=mod.path, line=call.lineno, scope=scope,
        message=(
            f"scalar-prefetch operands total {_fmt(total)} but SMEM is "
            f"{_fmt(SMEM_BUDGET)} per core — prefetch lands whole before "
            f"the grid runs; segment the index array (see "
            f"ops/pallas_gather.py SEG) or move it to a blocked VMEM "
            f"input"
        ),
    )]


# ---------------------------------------------------------------- pieces


def _spec_nodes(specs) -> Optional[list]:
    if specs is None:
        return []
    if isinstance(specs, (ast.List, ast.Tuple)):
        return list(specs.elts)
    if isinstance(specs, ast.Call):
        return [specs]
    return None


def _scratch_nodes(scratch) -> list:
    if isinstance(scratch, (ast.List, ast.Tuple)):
        return list(scratch.elts)
    if isinstance(scratch, ast.Call):
        return [scratch]
    return []


def _out_shape_vals(node, interp: Interp, env, mod) -> list:
    if node is None:
        return []
    if isinstance(node, (ast.List, ast.Tuple)):
        return [interp.eval(e, env, mod) for e in node.elts]
    return [interp.eval(node, env, mod)]


def _memory_space(spec: ast.Call, mod) -> str:
    for k in spec.keywords:
        if k.arg == "memory_space":
            fqn = resolve_fqn(k.value, mod) or ""
            if fqn.endswith(_OFF_VMEM_TAILS):
                return "off"
            return "vmem"
    return "vmem"


def _spec_bytes(spec, op, interp: Interp, env, mod, buf_factor: int,
                unresolved: list, label: str) -> int:
    """VMEM bytes of one BlockSpec window (0 for non-VMEM spaces).
    Appends to ``unresolved`` when the window doesn't fold."""
    if not isinstance(spec, ast.Call):
        unresolved.append(f"{label} is not a BlockSpec call")
        return 0
    fqn = resolve_fqn(spec.func, mod) or ""
    if not fqn.endswith("BlockSpec"):
        unresolved.append(f"{label} is not a BlockSpec")
        return 0
    if _memory_space(spec, mod) == "off":
        return 0
    block_node = None
    if spec.args:
        block_node = spec.args[0]
    for k in spec.keywords:
        if k.arg == "block_shape":
            block_node = k.value
    op_shape = op.shape if isinstance(op, ShapeDtype) else None
    dtype = op.dtype if isinstance(op, ShapeDtype) else None
    if block_node is None:
        # whole-array window
        if op_shape is None:
            unresolved.append(f"{label} has no block_shape and the operand "
                              f"shape does not fold")
            return 0
        dims = op_shape
    else:
        block = interp.eval(block_node, env, mod)
        if not isinstance(block, tuple):
            unresolved.append(f"{label} block_shape does not fold")
            return 0
        dims = []
        for d, b in enumerate(block):
            if b is None:  # None dim = full array dim
                b = op_shape[d] if op_shape is not None and \
                    d < len(op_shape) else UNKNOWN
            dims.append(b)
        dims = tuple(dims)
    if not all(isinstance(d, int) for d in dims):
        unresolved.append(f"{label} block dim does not fold to an int")
        return 0
    return _tile_padded_bytes(dims, dtype) * buf_factor


def _whole_array_bytes(op, buf_factor: int, unresolved: list,
                       label: str) -> int:
    if not isinstance(op, ShapeDtype) or op.shape is None or \
            not all(isinstance(d, int) for d in op.shape):
        unresolved.append(f"{label} shape does not fold (unblocked arrays "
                          f"land in VMEM whole)")
        return 0
    return _tile_padded_bytes(op.shape, op.dtype) * buf_factor


def _scratch_bytes(sc, interp: Interp, env, mod, unresolved: list,
                   j: int) -> int:
    if not isinstance(sc, ast.Call):
        unresolved.append(f"scratch_shapes[{j}] is not a call")
        return 0
    fqn = resolve_fqn(sc.func, mod) or ""
    if "SemaphoreType" in fqn or fqn.endswith(".SMEM"):
        return 0
    if not fqn.endswith(_VMEM_TAILS):
        unresolved.append(f"scratch_shapes[{j}] `{fqn}` is not recognized")
        return 0
    dims = interp.eval(sc.args[0], env, mod) if sc.args else UNKNOWN
    dtype = interp.dtype_of(
        sc.args[1] if len(sc.args) > 1 else None, env, mod
    )
    if not isinstance(dims, tuple) or \
            not all(isinstance(d, int) for d in dims):
        unresolved.append(f"scratch_shapes[{j}] shape does not fold")
        return 0
    return _tile_padded_bytes(dims, dtype)


def _tile_padded_bytes(dims: tuple, dtype: Optional[str]) -> int:
    """Physical VMEM footprint: the last dim pads to the 128-lane tile and
    the second-to-last to the dtype's sublane multiple, matching Mosaic's
    tiled layout (a (1, 1, 128) int32 block really occupies (1, 8, 128))."""
    eb = element_bytes(dtype)
    sublane = DTYPE_SUBLANE.get(dtype or "", 8)
    dims = list(dims)
    if len(dims) >= 1:
        dims[-1] = -(-dims[-1] // LANE) * LANE
    if len(dims) >= 2:
        dims[-2] = -(-dims[-2] // sublane) * sublane
    n = 1
    for d in dims:
        n *= max(d, 1)
    return n * eb


def _fmt(n: int) -> str:
    if n >= 1 << 20:
        return f"{n / (1 << 20):.1f} MiB"
    if n >= 1 << 10:
        return f"{n / (1 << 10):.1f} KiB"
    return f"{n} B"
