"""Core data model for hglint findings."""

from __future__ import annotations

import os
from dataclasses import dataclass, field

#: severity ordering for sorting/filtering
SEVERITIES = ("error", "warning", "info")

#: one-line summaries, keyed by rule id (also serves as the rule registry)
RULES = {
    # -- family 1: host sync inside traced code ------------------------------
    "HG101": "`.item()` forces a device->host sync inside traced code",
    "HG102": "float()/int()/bool() on a traced value concretizes it on host",
    "HG103": "numpy call inside traced code materializes a host value",
    "HG104": "jax.device_get inside traced code is a blocking transfer",
    "HG105": "block_until_ready inside traced code defeats async dispatch",
    # -- family 2: retrace hazards -------------------------------------------
    "HG201": "jax.jit(...) constructed inside a loop retraces every iteration",
    "HG202": "Python branch on a traced parameter (shape-independent control "
             "flow must use lax.cond/select)",
    "HG203": "traced function captures a mutable module-level global",
    "HG204": "static_argnums/static_argnames given a non-hashable value",
    # -- family 3: Pallas kernel contracts -----------------------------------
    "HG301": "Pallas block shape is not a multiple of the (8,128) TPU tile",
    "HG302": "Pallas index_map arity/rank/bounds disagree with grid/block",
    "HG303": "Pallas block sublane count violates the dtype tiling rule",
    "HG304": "Pallas kernel writes a dtype that disagrees with out_shape",
    # -- family 4: lock order -------------------------------------------------
    "HG401": "lock acquisition cycle (potential deadlock)",
    "HG402": "shared attribute mutated outside the instance lock",
}

#: rule id -> default severity
RULE_SEVERITY = {
    "HG101": "error",
    "HG102": "warning",
    "HG103": "error",
    "HG104": "error",
    "HG105": "error",
    "HG201": "warning",
    "HG202": "warning",
    "HG203": "warning",
    "HG204": "warning",
    "HG301": "error",
    "HG302": "error",
    "HG303": "error",
    "HG304": "error",
    "HG401": "error",
    "HG402": "warning",
}


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str          # repo-relative (or as-given) file path
    line: int
    message: str
    scope: str = "<module>"   # enclosing function qualname — baseline key part
    severity: str = field(default="")

    def __post_init__(self):
        if not self.severity:
            object.__setattr__(
                self, "severity", RULE_SEVERITY.get(self.rule, "warning")
            )

    @property
    def baseline_key(self) -> str:
        """Line-number-free key so baselines survive unrelated edits."""
        return f"{self.rule}:{_norm(self.path)}:{self.scope}"

    def render(self) -> str:
        return (
            f"{self.path}:{self.line} {self.rule} {self.severity}: "
            f"{self.message}"
        )


def _norm(path: str) -> str:
    return path.replace(os.sep, "/")


def sort_findings(findings):
    sev_rank = {s: i for i, s in enumerate(SEVERITIES)}
    return sorted(
        findings,
        key=lambda f: (_norm(f.path), f.line, sev_rank.get(f.severity, 9),
                       f.rule),
    )
