"""Core data model for hglint findings."""

from __future__ import annotations

import os
from dataclasses import dataclass, field

#: severity ordering for sorting/filtering
SEVERITIES = ("error", "warning", "info")

#: one-line summaries, keyed by rule id (also serves as the rule registry)
RULES = {
    # -- family 1: host sync inside traced code ------------------------------
    "HG101": "`.item()` forces a device->host sync inside traced code",
    "HG102": "float()/int()/bool() on a traced value concretizes it on host",
    "HG103": "numpy call inside traced code materializes a host value",
    "HG104": "jax.device_get inside traced code is a blocking transfer",
    "HG105": "block_until_ready inside traced code defeats async dispatch",
    "HG106": "binding read after its buffer was donated (donate_argnums)",
    "HG107": "jnp.asarray/jnp.array on a host numpy value inside traced "
             "code (silent host->device transfer per trace)",
    # -- family 2: retrace hazards -------------------------------------------
    "HG201": "jax.jit(...) constructed inside a loop retraces every iteration",
    "HG202": "Python branch on a traced parameter (shape-independent control "
             "flow must use lax.cond/select)",
    "HG203": "traced function captures a mutable module-level global",
    "HG204": "static_argnums/static_argnames given a non-hashable value",
    # -- family 3: Pallas kernel contracts -----------------------------------
    "HG301": "Pallas block shape is not a multiple of the (8,128) TPU tile",
    "HG302": "Pallas index_map arity/rank/bounds disagree with grid/block",
    "HG303": "Pallas block sublane count violates the dtype tiling rule",
    "HG304": "Pallas kernel writes a dtype that disagrees with out_shape",
    # -- family 4: lock order -------------------------------------------------
    "HG401": "lock acquisition cycle (potential deadlock)",
    "HG402": "shared attribute mutated outside the instance lock",
    "HG403": "`*_locked` contract function called from a context that "
             "holds no lock",
    # -- family 5: VMEM budgets ----------------------------------------------
    "HG501": "pallas_call VMEM working set exceeds the per-core budget",
    "HG502": "pallas_call VMEM working set is not statically resolvable",
    "HG503": "pallas_call scalar-prefetch operands exceed the 1 MB SMEM "
             "budget",
    # -- family 6: shard_map collective consistency ---------------------------
    "HG601": "collective over an axis name absent from the shard_map mesh",
    "HG602": "collective under a branch on a traced value "
             "(divergent-program deadlock)",
    "HG603": "collective axis mismatch between shard_map caller and callee",
    "HG604": "lax.cond/switch branches carry mismatched collectives",
    # -- family 7: blocking work under a held lock ----------------------------
    "HG701": "blocking call while holding a lock (stalls every waiter)",
    "HG702": "call while holding a lock transitively reaches a blocking "
             "primitive",
    "HG703": "O(n) work (sort) while holding a lock",
    # -- family 8: thread / resource lifecycle --------------------------------
    "HG801": "thread/timer started but neither daemon nor join/cancel-"
             "reachable",
    "HG802": "closeable resource not closed on the exception edge",
    "HG803": "check-then-act lifecycle transition without a lifecycle lock",
    "HG804": "Condition.wait outside a predicate re-check loop "
             "(spurious wakeup unsafe)",
    "HG805": "worker loop can exit on an unguarded exception, stranding "
             "in-flight work",
    # -- family 9: analyzer hygiene -------------------------------------------
    "HG901": "stale `# hglint: disable` suppression — the named rule no "
             "longer fires on that line",
    # -- family 10: exception flow & failure discipline ------------------------
    "HG1001": "broad handler on an InjectedCrash-carrying path swallows a "
              "simulated kill (no BaseException re-raise)",
    "HG1002": "dead typed fault handler — the guarded calls cannot raise "
              "the caught type",
    "HG1003": "retry loop re-attempts non-transient failures (retrying a "
              "PermanentFault burns the deadline for nothing)",
    "HG1004": "thread/worker entry point without a top-level guard — one "
              "raise strands the loop's tickets/queue",
    "HG1005": "exception swallowed without evidence (no re-raise, log, "
              "counter, or ticket resolution)",
    # -- family 11: cross-boundary wire-schema & protocol contracts -------------
    "HG1101": "payload arity drift — a tuple packed at a send/enqueue site "
              "is unpacked with a different arity by a consumer of the "
              "same channel",
    "HG1102": "envelope-key drift — a consumer reads a key no producer "
              "writes (KeyError in waiting) or a producer writes a key no "
              "consumer reads (dead field)",
    "HG1103": "persisted artifact without a schema-version stamp, a "
              "stamped writer whose reader never version-checks, or "
              "writer/reader version skew",
    "HG1104": "typed-error wire-table drift — an exception family member "
              "missing from the HTTP status/type table, or a wire kind "
              "rehydrated as a different type",
    "HG1105": "metric-name drift — a literal dotted metric site absent "
              "from the governing DOTTED_NAMES registry",
}

#: rule id -> default severity
RULE_SEVERITY = {
    "HG101": "error",
    "HG102": "warning",
    "HG103": "error",
    "HG104": "error",
    "HG105": "error",
    "HG201": "warning",
    "HG202": "warning",
    "HG203": "warning",
    "HG204": "warning",
    "HG301": "error",
    "HG302": "error",
    "HG303": "error",
    "HG304": "error",
    "HG401": "error",
    "HG402": "warning",
    "HG403": "warning",
    "HG106": "error",
    "HG107": "warning",
    "HG501": "error",
    "HG502": "warning",
    "HG503": "error",
    "HG601": "error",
    "HG602": "error",
    "HG603": "error",
    "HG604": "error",
    "HG701": "error",
    "HG702": "error",
    "HG703": "warning",
    "HG801": "error",
    "HG802": "error",
    "HG803": "warning",
    "HG804": "error",
    "HG805": "warning",
    "HG901": "warning",
    "HG1001": "error",
    "HG1002": "warning",
    "HG1003": "error",
    "HG1004": "warning",
    "HG1005": "warning",
    "HG1101": "error",
    "HG1102": "error",
    "HG1103": "error",
    "HG1104": "error",
    "HG1105": "error",
}


def family(rule: str) -> str:
    """Rule id -> family prefix: the id minus its two trailing digits
    (``HG101`` -> ``HG1``, ``HG1001`` -> ``HG10``). Keeps four-digit
    families from aliasing into three-digit ones under ``startswith``."""
    return rule[:-2]


#: family prefix -> README.md section anchor (rule docs live there); HG106
#: and HG107 extend family 1, so the family mapping covers them
DOC_ANCHORS = {
    "HG1": "hg1xx-host-sync-in-traced-code",
    "HG2": "hg2xx-retrace-hazards",
    "HG3": "hg3xx-pallas-kernel-contracts",
    "HG4": "hg4xx-lock-order",
    "HG5": "hg5xx-vmem-budgets",
    "HG6": "hg6xx-shard_map-collective-consistency",
    "HG7": "hg7xx-blocking-under-lock",
    "HG8": "hg8xx-thread--resource-lifecycle",
    "HG9": "hg9xx-analyzer-hygiene",
    "HG10": "hg10xx-exception-flow--failure-discipline",
    "HG11": "hg11xx-wire-contract-analysis",
}


def rule_matches(rule: str, prefix: str) -> bool:
    """``--only`` selection: a prefix selects an exact rule id, an exact
    family (``HG10`` selects HG1001-HG1005 but NOT HG101), or — for
    prefixes shorter than a family id — any rule it is a string prefix of
    (``HG`` selects everything)."""
    if rule == prefix or family(rule) == prefix:
        return True
    return len(prefix) < 3 and rule.startswith(prefix)


def doc_anchor(rule: str) -> str:
    """URL-style pointer to the rule family's README section, printed in
    every rendered diagnostic (``HG5xx`` -> ``README.md#hg5xx-...``)."""
    slug = DOC_ANCHORS.get(family(rule), "static-analysis-hglint")
    return f"README.md#{slug}"


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str          # repo-relative (or as-given) file path
    line: int
    message: str
    scope: str = "<module>"   # enclosing function qualname — baseline key part
    severity: str = field(default="")

    def __post_init__(self):
        if not self.severity:
            object.__setattr__(
                self, "severity", RULE_SEVERITY.get(self.rule, "warning")
            )

    @property
    def baseline_key(self) -> str:
        """Line-number-free key so baselines survive unrelated edits."""
        return f"{self.rule}:{_norm(self.path)}:{self.scope}"

    def render(self) -> str:
        return (
            f"{self.path}:{self.line} {self.rule} {self.severity}: "
            f"{self.message} [{doc_anchor(self.rule)}]"
        )


def _norm(path: str) -> str:
    return path.replace(os.sep, "/")


def sort_findings(findings):
    sev_rank = {s: i for i, s in enumerate(SEVERITIES)}
    return sorted(
        findings,
        key=lambda f: (_norm(f.path), f.line, sev_rank.get(f.severity, 9),
                       f.rule),
    )
